"""Pallas row-scatter (VERDICT r2 next-#9 falsification kernel): parity vs
XLA's .at[].add under the embed path's contract (unique, in-range ids)."""

import jax.numpy as jnp
import numpy as np
import pytest

from distributeddeeplearningspark_tpu.ops.scatter_rows import scatter_add_rows


def _case(v, d, k, seed=0):
    rng = np.random.default_rng(seed)
    table = jnp.asarray(rng.normal(0, 1, (v, d)).astype(np.float32))
    idx = jnp.asarray(np.sort(rng.choice(v, k, replace=False)).astype(np.int32))
    upd = jnp.asarray(rng.normal(0, 1, (k, d)).astype(np.float32))
    return table, idx, upd


@pytest.mark.parametrize("v,d,k", [(64, 16, 9), (128, 64, 32), (32, 8, 32)])
def test_matches_xla_scatter_add(v, d, k):
    table, idx, upd = _case(v, d, k, seed=v)
    got = scatter_add_rows(table, idx, upd)
    want = table.at[idx].add(upd, unique_indices=True, indices_are_sorted=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-6)
    # untouched rows bit-identical
    mask = np.ones(v, bool)
    mask[np.asarray(idx)] = False
    np.testing.assert_array_equal(np.asarray(got)[mask],
                                  np.asarray(table)[mask])


def test_bad_update_shape_rejected():
    table, idx, upd = _case(16, 8, 4)
    with pytest.raises(ValueError, match="updates"):
        scatter_add_rows(table, idx, upd[:, :4])
