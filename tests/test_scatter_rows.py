"""Pallas row-scatter (VERDICT r2 next-#9 falsification kernel): parity vs
XLA's .at[].add under the embed path's contract (unique, in-range ids)."""

import jax.numpy as jnp
import numpy as np
import pytest

from distributeddeeplearningspark_tpu.ops.scatter_rows import scatter_add_rows


def _case(v, d, k, seed=0):
    rng = np.random.default_rng(seed)
    table = jnp.asarray(rng.normal(0, 1, (v, d)).astype(np.float32))
    idx = jnp.asarray(np.sort(rng.choice(v, k, replace=False)).astype(np.int32))
    upd = jnp.asarray(rng.normal(0, 1, (k, d)).astype(np.float32))
    return table, idx, upd


@pytest.mark.parametrize("v,d,k", [(64, 16, 9), (128, 64, 32), (32, 8, 32)])
def test_matches_xla_scatter_add(v, d, k):
    table, idx, upd = _case(v, d, k, seed=v)
    got = scatter_add_rows(table, idx, upd)
    want = table.at[idx].add(upd, unique_indices=True, indices_are_sorted=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-6)
    # untouched rows bit-identical
    mask = np.ones(v, bool)
    mask[np.asarray(idx)] = False
    np.testing.assert_array_equal(np.asarray(got)[mask],
                                  np.asarray(table)[mask])


def test_bad_update_shape_rejected():
    table, idx, upd = _case(16, 8, 4)
    with pytest.raises(ValueError, match="updates"):
        scatter_add_rows(table, idx, upd[:, :4])


def test_dropping_wrapper_discards_sentinels():
    """VERDICT r3 weak-#7 / next-#6: the guarded boundary must accept the
    embed caller's OOB-sentinel padding (ids >= V, unique, trailing) and
    drop those rows exactly, like XLA mode='drop'."""
    from distributeddeeplearningspark_tpu.ops.scatter_rows import (
        scatter_add_rows_dropping)

    v, d, k = 32, 8, 12
    table, _, upd = _case(v, d, k, seed=3)
    rng = np.random.default_rng(4)
    real = np.sort(rng.choice(v, 7, replace=False))
    # embed-style padding: sentinels v+0, v+1, ... (unique, sorted, OOB)
    idx = jnp.asarray(np.concatenate(
        [real, v + np.arange(k - 7)]).astype(np.int32))
    got = scatter_add_rows_dropping(table, idx, upd)
    want = table.at[idx].add(upd, mode="drop", unique_indices=True,
                             indices_are_sorted=True)
    assert got.shape == table.shape
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-6)


def test_rowwise_adagrad_pallas_impl_matches_xla():
    """The embed call-site switch: scatter_impl='pallas' (through the
    guarded wrapper) must equal the XLA path bit-for-bit-ish, including the
    duplicate-id case whose unique() padding produces the sentinels."""
    from distributeddeeplearningspark_tpu.train.embed import (
        rowwise_adagrad_update)

    rng = np.random.default_rng(5)
    v, d = 24, 8
    table = jnp.asarray(rng.normal(0, 1, (v, d)).astype(np.float32))
    accum = jnp.asarray(rng.uniform(0, 0.5, (v,)).astype(np.float32))
    ids = jnp.asarray(rng.integers(0, v, (6, 2)).astype(np.int32))  # dups
    d_vecs = jnp.asarray(rng.normal(0, 1, (6, 2, d)).astype(np.float32))
    xla_t, xla_a = rowwise_adagrad_update(
        table, accum, ids, d_vecs, lr=0.1, eps=1e-8)
    pls_t, pls_a = rowwise_adagrad_update(
        table, accum, ids, d_vecs, lr=0.1, eps=1e-8, scatter_impl="pallas")
    np.testing.assert_allclose(np.asarray(pls_t), np.asarray(xla_t),
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_array_equal(np.asarray(pls_a), np.asarray(xla_a))


def test_rowwise_adagrad_rejects_unknown_impl():
    from distributeddeeplearningspark_tpu.train.embed import (
        rowwise_adagrad_update)

    table = jnp.zeros((4, 8), jnp.float32)
    with pytest.raises(ValueError, match="scatter_impl"):
        rowwise_adagrad_update(table, jnp.zeros((4,), jnp.float32),
                               jnp.zeros((2,), jnp.int32),
                               jnp.zeros((2, 8), jnp.float32),
                               lr=0.1, scatter_impl="cuda")
