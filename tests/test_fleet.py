"""Pod-level observability: per-host tagging, fleet aggregation, skew,
straggler/hang verdicts, dlstatus --hosts, and the supervisor's culprit
naming (ISSUE 3).

All synthetic streams run on fake clocks (the fleet fold is a pure function
of event dicts); the one real-process test is the supervisor hang drill,
whose worker is plain python (no jax) so it stays in the fast tier.
"""

import json
import os
import subprocess
import sys

import pytest

from distributeddeeplearningspark_tpu import status, telemetry
from distributeddeeplearningspark_tpu.telemetry import fleet

FIXTURE_3HOST = os.path.join(os.path.dirname(__file__), "fixtures",
                             "fleet_3host")


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


def _writer(tmp_path, host, *, hosts=3, t0=0.0):
    clock = FakeClock(t0)
    w = telemetry.EventWriter(tmp_path, process=f"p{host}", clock=clock,
                              host=host, hosts=hosts)
    return w, clock


def _ev(ts, kind, host, **f):
    return {"ts": ts, "kind": kind, "process": f"p{host}", "host": host, **f}


# -- writer-side host tagging & heartbeat enrichment -------------------------


def test_writer_tags_events_with_host_from_env(tmp_path, monkeypatch):
    monkeypatch.setenv("DLS_PROCESS_ID", "2")
    monkeypatch.setenv("DLS_NUM_PROCESSES", "4")
    w = telemetry.EventWriter(tmp_path, clock=FakeClock())
    w.heartbeat(step=5)
    w.close()
    (e,) = telemetry.read_events(tmp_path)
    assert e["host"] == 2 and e["hosts"] == 4
    assert e["process"] == "p2"


def test_writer_host_none_opts_out(tmp_path):
    """Non-host processes (supervisor, tpu_watch) carry no host field and
    stay out of the fleet table."""
    w = telemetry.EventWriter(tmp_path, process="supervisor",
                              clock=FakeClock(), host=None)
    w.attempt("begin", 0)
    w.close()
    (e,) = telemetry.read_events(tmp_path)
    assert "host" not in e
    assert fleet.split_hosts([e]) == {}


def test_heartbeat_enriched_with_innermost_open_phase(tmp_path):
    w, clock = _writer(tmp_path, 0)
    w.emit("phase", name="run", edge="begin")
    w.heartbeat(step=1)
    with w.phase("restore"):
        clock.t = 5.0
        w.heartbeat(step=1)
    clock.t = 9.0
    w.heartbeat(step=2)
    w.close()
    hbs = [e for e in telemetry.read_events(tmp_path)
           if e["kind"] == "heartbeat"]
    assert [h["phase"] for h in hbs] == ["run", "restore", "run"]


def test_legacy_streams_fall_back_to_process_name(tmp_path):
    """Streams written before the host field exist still aggregate via the
    p<k> process-name convention."""
    events = [{"ts": 1.0, "kind": "heartbeat", "process": "p3", "step": 7}]
    assert list(fleet.split_hosts(events)) == [3]


# -- host table ---------------------------------------------------------------


def _three_host_stream(*, stall_host=None, crash_host=None, jitter=0.0):
    """Synthetic gang: steps 10..40 at ~1s/step per lap boundary, per-host
    clock offset ``jitter * host``. ``stall_host`` enters restore after
    step 20 and goes silent; ``crash_host`` dies right after step 10
    (stream just ends). Hosts keep heartbeating until t=50."""
    events = []
    for h in range(3):
        off = jitter * h
        events.append(_ev(0.0 + off, "phase", h, name="run", edge="begin"))
        events.append(_ev(0.1 + off, "heartbeat", h, step=0, phase="run"))
        for step in (10, 20, 30, 40):
            t = step + off
            if crash_host == h and step > 10:
                break
            if stall_host == h and step > 20:
                break
            events.append(_ev(t, "step_metrics", h, step=step, steps=10,
                              lap_s=10.0, metrics={}))
            events.append(_ev(t + 0.01, "heartbeat", h, step=step,
                              phase="run"))
        if stall_host == h:
            events.append(_ev(21.0 + off, "phase", h, name="restore",
                              edge="begin"))
        elif crash_host != h:
            events.append(_ev(50.0 + off, "heartbeat", h, step=40,
                              phase="run"))
    return sorted(events, key=lambda e: e["ts"])


def test_host_table_uneven_lengths_and_ages():
    events = _three_host_stream(stall_host=2, jitter=0.05)
    rows = fleet.host_table(events)
    assert [r["host"] for r in rows] == [0, 1, 2]
    assert [r["last_step"] for r in rows] == [40, 40, 20]
    # ages anchor on the merged stream's end by default
    assert rows[0]["heartbeat_age_s"] == pytest.approx(0.05, abs=0.02)
    assert rows[2]["heartbeat_age_s"] == pytest.approx(30.0, abs=0.5)
    assert rows[2]["phase"] == "restore"
    assert rows[2]["silence_s"] > 25.0
    # healthy hosts report the outer run phase, not the stalled one's
    assert rows[0]["phase"] == "run"


def test_host_table_comms_wait_column():
    events = [
        _ev(0.0, "heartbeat", 0, step=0),
        _ev(1.0, "collective", 0, op="barrier", axis="data", wait_s=0.5),
        _ev(2.0, "collective", 0, op="all_gather", axis="data", wait_s=0.25),
        _ev(2.0, "heartbeat", 1, step=0),
    ]
    rows = fleet.host_table(events)
    assert rows[0]["comms_wait_s"] == pytest.approx(0.75)
    assert rows[0]["collectives"] == 2
    assert rows[1]["comms_wait_s"] == 0.0


def test_host_table_per_host_goodput():
    events = [
        _ev(0.0, "heartbeat", 0),
        _ev(0.0, "phase", 0, name="compile", edge="begin"),
        _ev(4.0, "phase", 0, name="compile", edge="end", dur_s=4.0),
        _ev(10.0, "heartbeat", 0),
        _ev(0.0, "heartbeat", 1),
        _ev(10.0, "heartbeat", 1),
    ]
    rows = fleet.host_table(events)
    assert rows[0]["goodput"]["compile_s"] == 4.0
    assert rows[0]["goodput"]["goodput_frac"] == pytest.approx(0.6)
    assert rows[1]["goodput"]["goodput_frac"] == pytest.approx(1.0)


def test_stale_phase_from_crashed_attempt_does_not_leak():
    """A worker killed mid-restore never writes the restore end; its
    relaunch appends a fresh run begin to the SAME file. The stale open
    restore must not be reported as the new attempt's current phase."""
    events = [
        _ev(0.0, "phase", 0, name="run", edge="begin"),
        _ev(5.0, "phase", 0, name="restore", edge="begin"),
        # SIGKILL; relaunch appends:
        _ev(20.0, "phase", 0, name="run", edge="begin"),
        _ev(21.0, "heartbeat", 0, step=10, phase="run"),
    ]
    (row,) = fleet.host_table(events)
    assert row["phase"] == "run"
    assert row["phase_since_ts"] is None  # run umbrella is not a dwell


def test_hb_phase_fallback_cleared_when_phase_ends():
    """A heartbeat's self-reported phase must stop being 'current' once
    that phase's end edge arrives — a cleanly finished run is not 'in
    restore' just because its last heartbeat happened during one."""
    events = [
        _ev(0.0, "phase", 0, name="run", edge="begin"),
        _ev(1.0, "phase", 0, name="restore", edge="begin"),
        _ev(2.0, "heartbeat", 0, step=5, phase="restore"),
        _ev(3.0, "phase", 0, name="restore", edge="end", dur_s=2.0),
        _ev(4.0, "phase", 0, name="run", edge="end"),
    ]
    (row,) = fleet.host_table(events)
    assert row["phase"] is None  # everything closed: no current phase


def test_supervisor_writer_stays_out_of_fleet_table(tmp_path):
    """The supervisor's own events (reap-time attempt ends, restarts) must
    not refresh host 0's liveness — it describes the gang, it isn't in it."""
    from distributeddeeplearningspark_tpu.supervisor import Supervisor

    sup = Supervisor(["true"], telemetry_dir=str(tmp_path))
    sup._telemetry().attempt("begin", 0)
    sup._tele.close()
    (e,) = telemetry.read_events(str(tmp_path))
    assert e["process"] == "supervisor" and "host" not in e
    assert fleet.host_table([e]) == []


# -- step skew & straggler ----------------------------------------------------


def test_step_skew_numbers_with_clock_jitter():
    events = _three_host_stream(jitter=0.2)
    sk = fleet.step_skew(events)
    assert sk["num_hosts"] == 3
    steps = [w["step"] for w in sk["per_step"]]
    assert steps == [0, 10, 20, 30, 40]
    # constant 0.2s/host offset → 0.4s spread, host 2 always "slowest"
    assert sk["max_skew_s"] == pytest.approx(0.4, abs=0.01)
    assert sk["median_skew_s"] == pytest.approx(0.4, abs=0.01)
    assert sk["last_common_step"] == 40
    assert sk["step_lag"] == 0


def test_step_skew_step_lag_when_one_host_stops():
    sk = fleet.step_skew(_three_host_stream(stall_host=1))
    assert sk["last_common_step"] == 20
    assert sk["step_lag"] == 20  # host 1 stopped at 20, others reached 40


def test_straggler_verdict_persistent_slow_host():
    events = []
    for h in range(3):
        for step in (10, 20, 30, 40):
            lag = 2.5 if h == 1 else 0.05 * h
            events.append(_ev(step + lag, "step_metrics", h, step=step,
                              steps=10, lap_s=10.0, metrics={}))
    sk = fleet.step_skew(events)
    verdict = fleet.straggler_verdict(sk)
    assert verdict is not None
    assert verdict["host"] == 1
    assert verdict["slow_windows"] == 4 and verdict["windows"] == 4
    assert verdict["median_skew_s"] == pytest.approx(2.5, abs=0.01)
    assert "host 1 slowest in 4/4" in verdict["verdict"]


def test_straggler_none_on_rotating_or_small_skew():
    # skew below min_skew_s: clock jitter, not a sick machine
    sk = fleet.step_skew(_three_host_stream(jitter=0.1))
    assert fleet.straggler_verdict(sk) is None
    # rotating slowest host: no single culprit
    events = []
    for i, step in enumerate((10, 20, 30, 40)):
        for h in range(3):
            lag = 3.0 if h == i % 3 else 0.0
            events.append(_ev(step + lag, "step_metrics", h, step=step,
                              steps=10, lap_s=10.0, metrics={}))
    assert fleet.straggler_verdict(fleet.step_skew(events)) is None


# -- hang localization --------------------------------------------------------


def test_localize_hang_names_stalled_host_and_phase():
    events = _three_host_stream(stall_host=2, jitter=0.05)
    loc = fleet.localize_hang(events)
    assert loc["host"] == 2
    assert loc["phase"] == "restore"
    assert loc["others_at_step"] == 40
    # stalled-for measures from the open phase begin to the stream end
    assert loc["stalled_for_s"] == pytest.approx(50.05 - 21.1, abs=0.2)
    assert "host 2 stuck in phase=restore" in loc["verdict"]
    assert "waiting at step 40" in loc["verdict"]


def test_localize_hang_crashed_host_attributed():
    """A host whose stream just ends (crash, no phase open) is still the
    culprit — silence attribution doesn't need a phase record."""
    loc = fleet.localize_hang(_three_host_stream(crash_host=1))
    assert loc["host"] == 1
    assert loc["others_at_step"] == 40


def test_localize_hang_simultaneous_silence_is_unattributed():
    """The whole gang dying within the jitter margin (network partition)
    must NOT name an arbitrary host."""
    events = _three_host_stream(jitter=0.1)  # all end ~50.0..50.2
    assert fleet.localize_hang(events) is None


def test_localize_hang_single_host_gang():
    events = [
        _ev(0.0, "phase", 0, name="run", edge="begin"),
        _ev(5.0, "phase", 0, name="checkpoint", edge="begin"),
    ]
    loc = fleet.localize_hang(events, now=60.0)
    assert loc["host"] == 0 and loc["phase"] == "checkpoint"
    assert loc["stalled_for_s"] == pytest.approx(55.0)
    # the same stream inspected stream-anchored (silence 0 — a live or
    # finished run) must NOT be flagged: one host has no one to lag behind
    assert fleet.localize_hang(events) is None


def test_finished_run_with_trailing_supervisor_events_not_flagged():
    """The supervisor's reap records land seconds after the worker's last
    event on every CLEAN run; that lag is teardown, not silence — the
    stream-anchored hang gate must ignore non-host events."""
    events = [
        _ev(0.0, "phase", 0, name="run", edge="begin"),
        _ev(10.0, "heartbeat", 0, step=12),
        _ev(10.1, "phase", 0, name="run", edge="end", step=12),
        {"ts": 12.5, "kind": "attempt", "process": "supervisor",
         "edge": "end", "ordinal": 0, "returncodes": [0]},
    ]
    assert fleet.localize_hang(events) is None
    (row,) = fleet.host_table(events)
    assert row["silence_s"] == pytest.approx(0.0)  # host-stream anchored


def test_localize_hang_margin_scales_with_observed_skew():
    """A gang whose normal per-step skew is large must not have its
    slowest-but-healthy host named on a gap the skew baseline explains."""
    events = _three_host_stream(jitter=2.0)  # median step skew = 4s
    # hosts end at 50, 52, 54 — 2s lead < 3×4s margin → no culprit
    assert fleet.localize_hang(events) is None
    # but an explicit margin below the lead names the earliest-silent host
    assert fleet.localize_hang(events, margin_s=1.0)["host"] == 0


# -- fleet report & dlstatus --hosts -----------------------------------------


def test_fleet_report_missing_hosts_from_writer_stamp():
    """A host that never wrote an event still shows as missing: the other
    writers' own `hosts` stamp says how many there should be."""
    events = [
        {"ts": 1.0, "kind": "heartbeat", "process": "p0", "host": 0,
         "hosts": 3, "step": 4},
        {"ts": 1.1, "kind": "heartbeat", "process": "p1", "host": 1,
         "hosts": 3, "step": 4},
    ]
    rep = fleet.fleet_report(events)
    assert rep["num_hosts"] == 2
    assert rep["expected_hosts"] == 3
    assert rep["missing_hosts"] == [2]


def test_dlstatus_hosts_json_schema(tmp_path, capsys):
    """The acceptance shape: on a 3-host fixture with one host stalled
    mid-phase, --hosts --json reports per-host last-step/heartbeat-age/
    phase, a step-skew figure, and names the stalled host + phase."""
    assert status.main([FIXTURE_3HOST, "--hosts", "--json"]) == 0
    rep = json.loads(capsys.readouterr().out)
    fl = rep["fleet"]
    assert fl["num_hosts"] == 3 and fl["expected_hosts"] == 3
    by_host = {r["host"]: r for r in fl["hosts"]}
    assert set(by_host) == {0, 1, 2}
    for r in fl["hosts"]:
        assert {"last_step", "heartbeat_age_s", "phase", "comms_wait_s",
                "silence_s", "goodput"} <= set(r)
    assert by_host[2]["phase"] == "restore"
    assert by_host[2]["heartbeat_age_s"] > 0
    assert by_host[0]["last_step"] == 40
    assert fl["skew"]["max_skew_s"] > 0
    assert fl["skew"]["per_step"]
    hang = fl["hang"]
    assert hang["host"] == 2 and hang["phase"] == "restore"
    assert hang["others_at_step"] == 40


def test_dlstatus_hosts_renders_table_and_verdict(capsys):
    assert status.main([FIXTURE_3HOST, "--hosts"]) == 0
    out = capsys.readouterr().out
    assert "fleet: 3/3 host(s) reporting" in out
    assert "step skew" in out
    assert "host 2 stuck in phase=restore" in out


def test_dlstatus_without_hosts_flag_has_no_fleet(tmp_path, capsys):
    w, _ = _writer(tmp_path, 0)
    w.heartbeat(step=1)
    w.close()
    assert status.main([str(tmp_path), "--json"]) == 0
    assert "fleet" not in json.loads(capsys.readouterr().out)


# -- supervisor hang path names the culprit ----------------------------------


_STALL_WORKER = """\
import os, time
from distributeddeeplearningspark_tpu import telemetry
if os.environ.get("DLS_RESTART", "0") != "0":
    raise SystemExit(0)  # the relaunch after the hang succeeds
w = telemetry.EventWriter(os.environ["DLS_TELEMETRY_DIR"])
w.emit("phase", name="run", edge="begin", step=0)
w.heartbeat(step=3)
w.emit("phase", name="restore", edge="begin")
open(os.environ["DLS_HEARTBEAT_FILE"], "w").write("x")  # progress, then stall
time.sleep(120)
"""


def test_supervisor_hang_recovery_names_culprit(tmp_path):
    """The acceptance contract's supervisor half: a hang's recovery event
    carries the fleet-localized culprit host + phase, not a bare 'hang'."""
    from distributeddeeplearningspark_tpu.supervisor import Supervisor

    script = tmp_path / "stall_worker.py"
    script.write_text(_STALL_WORKER)
    wd = tmp_path / "run"
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sup = Supervisor(
        [sys.executable, str(script)],
        num_processes=1, max_restarts=1, poll_interval=0.05,
        restart_backoff_s=0.01, backoff_jitter=0.0,
        # dwell must clear fleet.MIN_STALL_MARGIN_S (1s) so the single-host
        # localization has real silence evidence at reap time
        hang_timeout_s=1.5, startup_grace_s=30.0,
        progress_path=str(wd), telemetry_dir=str(wd),
        env={"PYTHONPATH": repo_root + os.pathsep
             + os.environ.get("PYTHONPATH", "")},
    )
    result = sup.run()
    assert result.ok, [(a.returncodes, a.classification)
                       for a in result.attempts]
    hung = result.attempts[0]
    assert hung.classification == "hang"
    assert hung.culprit is not None
    assert hung.culprit["host"] == 0
    assert hung.culprit["phase"] == "restore"

    events = telemetry.read_events(str(wd))
    restarts = [e for e in events if e.get("kind") == "recovery"
                and e.get("event") == "restart"]
    assert len(restarts) == 1
    assert restarts[0]["classification"] == "hang"
    assert restarts[0]["culprit_host"] == 0
    assert restarts[0]["culprit_phase"] == "restore"
    assert restarts[0]["stalled_for_s"] > 0
    ends = [e for e in events if e.get("kind") == "attempt"
            and e.get("edge") == "end" and e.get("ordinal") == 0]
    assert ends[0]["culprit_host"] == 0


def test_supervisor_hang_without_telemetry_stays_bare():
    """No telemetry dir → the hang path degrades to the bare
    classification (no crash, no culprit fields)."""
    from distributeddeeplearningspark_tpu.supervisor import Supervisor

    sup = Supervisor(["true"], num_processes=1)
    assert sup._localize_hang() is None


# -- satellite: bench + tpu_watch availability audit trail -------------------


def test_bench_probe_timeout_emits_recovery_event(tmp_path, monkeypatch):
    import bench

    monkeypatch.setenv("DLS_TELEMETRY_DIR", str(tmp_path))

    def fake_run(*a, **kw):
        raise subprocess.TimeoutExpired(cmd="probe", timeout=kw["timeout"])

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    ok, errors = bench.probe_backend(attempts=2, timeout_s=0.1, backoff_s=0.0)
    # ONE probe-timeout, not two: a full-deadline hang caches the
    # unavailable verdict for the remaining attempts (ISSUE 4 satellite —
    # BENCH_r05 burned 3×150 s re-learning the same hang)
    assert not ok and "hung" in errors[0] and "cached" in errors[1]
    events = telemetry.read_events(str(tmp_path))
    kinds = [(e["kind"], e.get("event")) for e in events]
    assert kinds == [("recovery", "probe-timeout"),
                     ("recovery", "backend-unavailable")]
    assert all(e["process"] == "bench" and "host" not in e for e in events)
    assert events[-1]["errors"]


def test_bench_single_attempt_poll_emits_no_terminal_verdict(tmp_path,
                                                             monkeypatch):
    """tpu_watch polls with attempts=1 every interval; the per-attempt
    event is the record — a duplicate backend-unavailable per poll would
    flood a long outage's recovery timeline."""
    import bench

    monkeypatch.setenv("DLS_TELEMETRY_DIR", str(tmp_path))

    def fake_run(*a, **kw):
        raise subprocess.TimeoutExpired(cmd="probe", timeout=kw["timeout"])

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    ok, _ = bench.probe_backend(attempts=1, timeout_s=0.1, backoff_s=0.0)
    assert not ok
    events = telemetry.read_events(str(tmp_path))
    assert [e.get("event") for e in events] == ["probe-timeout"]


def test_bench_probe_no_workdir_no_telemetry(tmp_path, monkeypatch):
    import bench

    monkeypatch.delenv("DLS_TELEMETRY_DIR", raising=False)
    bench.telemetry_recovery("probe-timeout", attempt=1)
    assert telemetry.read_events(str(tmp_path)) == []


def test_tpu_watch_mirrors_probe_observations(tmp_path):
    import importlib.util

    path = os.path.join(os.path.dirname(__file__), "..", "tools",
                        "tpu_watch.py")
    spec = importlib.util.spec_from_file_location("tpu_watch_fleet", path)
    watch = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(watch)

    tele = watch.WatchTelemetry(str(tmp_path))
    tele.observe(1, False, pending=9, errors=["probe 1/1: hung past 120s"])
    tele.observe(2, False, pending=9, errors=["probe 1/1: hung past 120s"])
    tele.observe(3, True, pending=9)
    tele.observe(4, True, pending=4)
    tele.close()
    events = telemetry.read_events(str(tmp_path))
    hbs = [e for e in events if e["kind"] == "heartbeat"]
    recs = [e for e in events if e["kind"] == "recovery"]
    assert len(hbs) == 4  # one per probe
    assert [e["event"] for e in recs] == ["tpu-down", "tpu-up"]  # transitions
    assert recs[0]["errors"]
    assert all(e["process"] == "tpu-watch" for e in events)
    # and dlstatus can read the watch workdir like any run
    assert status.main([str(tmp_path)]) == 0


# -- satellite: collective probes --------------------------------------------


def test_barrier_probe_emits_collective_event(tmp_path):
    from distributeddeeplearningspark_tpu.parallel import collectives
    from distributeddeeplearningspark_tpu.parallel.mesh import MeshSpec

    mesh = MeshSpec().build()
    telemetry.configure(tmp_path)
    wait = collectives.barrier_probe(mesh)
    assert wait >= 0.0
    collectives.barrier_probe(mesh)
    events = [e for e in telemetry.read_events(tmp_path)
              if e["kind"] == "collective"]
    assert len(events) == 2
    assert events[0]["op"] == "barrier" and events[0]["wait_s"] >= 0.0
    # the fleet table folds them into the comms-wait column
    rows = fleet.host_table(telemetry.read_events(tmp_path))
    assert rows[0]["collectives"] == 2


def test_probed_collectives_transparent_under_tracing(tmp_path):
    """The opt-in wrappers must not change traced semantics or emit from
    inside a trace — XLA owns scheduling there."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from distributeddeeplearningspark_tpu.parallel import collectives
    from distributeddeeplearningspark_tpu.parallel.mesh import MeshSpec

    mesh = MeshSpec().build()
    telemetry.configure(tmp_path)
    collectives.enable_collective_probes(True)
    try:
        f = jax.jit(collectives.shard_map(
            lambda x: collectives.all_reduce_sum(x, ("data",)),
            mesh=mesh, in_specs=P("data"), out_specs=P()))
        out = f(jnp.ones((8,), jnp.float32))
        assert float(out[0]) == 8.0
        assert [e for e in telemetry.read_events(tmp_path)
                if e["kind"] == "collective"] == []
    finally:
        collectives.enable_collective_probes(False)
