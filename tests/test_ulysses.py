"""Ulysses (all-to-all) context parallelism vs dense attention, on a real
seq mesh — the second CP strategy next to the ring (ops/ulysses.py).

Runs on 8 fake CPU devices with nontrivial (data × seq × tensor) meshes so
the all_to_all head-scatter/seq-gather pair and the batch/head shardings
are genuinely exercised. Coverage mirrors tests/test_ring_attention.py:
causal/non-causal parity, gradients, GQA, key-padding masks, packed
segment ids, the head-divisibility guard, and a full Llama CP train step
whose loss equals the pure-DP loss.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributeddeeplearningspark_tpu.models import LlamaConfig, LlamaForCausalLM
from distributeddeeplearningspark_tpu.ops.attention import _xla_attention
from distributeddeeplearningspark_tpu.ops import ring_attention as ring_mod
from distributeddeeplearningspark_tpu.ops.ulysses import ulysses_attention
from distributeddeeplearningspark_tpu.parallel.mesh import MeshSpec
from distributeddeeplearningspark_tpu.parallel.sharding import ShardingRules
from distributeddeeplearningspark_tpu.train import losses, step as step_lib


def _qkv(b=4, s=32, h=8, d=16, seed=0, hkv=None):
    rng = np.random.default_rng(seed)
    mk = lambda hh: jnp.asarray(
        rng.normal(0, 1, (b, s, hh, d)).astype(np.float32))
    return mk(h), mk(hkv or h), mk(hkv or h)


@pytest.mark.parametrize("spec", [
    MeshSpec(data=2, seq=4),
    MeshSpec(data=1, seq=8),
    MeshSpec(data=2, seq=2, tensor=2),
])
@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_matches_dense(spec, causal, eight_devices):
    mesh = spec.build()
    q, k, v = _qkv()
    want = _xla_attention(q, k, v, bias=None, mask=None, causal=causal,
                          scale=None)
    got = jax.jit(lambda a, b_, c: ulysses_attention(
        a, b_, c, mesh=mesh, causal=causal))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


def test_ulysses_gradients_match_dense(eight_devices):
    mesh = MeshSpec(data=2, seq=4).build()
    q, k, v = _qkv(b=2, s=16, h=4, d=8, seed=7)

    def loss_u(q, k, v):
        return jnp.sum(ulysses_attention(q, k, v, mesh=mesh, causal=True) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(_xla_attention(q, k, v, bias=None, mask=None,
                                      causal=True, scale=None) ** 2)

    g_u = jax.jit(jax.grad(loss_u, argnums=(0, 1, 2)))(q, k, v)
    g_d = jax.jit(jax.grad(loss_dense, argnums=(0, 1, 2)))(q, k, v)
    for a, b_ in zip(g_u, g_d):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   atol=3e-5, rtol=3e-5)


def test_ulysses_gqa_matches_xla_repeat(eight_devices):
    """Grouped KV (hkv < h) scatters at its own width; parity vs the dense
    path's broadcast."""
    mesh = MeshSpec(data=4, seq=2).build()
    q, k, v = _qkv(h=8, hkv=4, seed=11)
    want = _xla_attention(q, jnp.repeat(k, 2, 2), jnp.repeat(v, 2, 2),
                          bias=None, mask=None, causal=True, scale=None)
    got = jax.jit(lambda a, b_, c: ulysses_attention(
        a, b_, c, mesh=mesh, causal=True))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


def test_ulysses_key_padding_mask_and_segments(eight_devices):
    """Key-only padding masks and packed segment ids gather to full length
    and match the dense path (incl. zeroed fully-masked rows)."""
    mesh = MeshSpec(data=2, seq=4).build()
    b, s = 4, 32
    q, k, v = _qkv(b=b, s=s, seed=13)
    rng = np.random.default_rng(5)
    kv_mask = jnp.asarray(np.arange(s)[None, :] < rng.integers(8, s, (b, 1)))
    segs = jnp.asarray(np.sort(rng.integers(0, 3, (b, s))).astype(np.int32))

    seg_mask = segs[:, None, :, None] == segs[:, None, None, :]
    dense_mask = jnp.logical_and(kv_mask[:, None, None, :], seg_mask)
    want = _xla_attention(q, k, v, bias=None, mask=dense_mask, causal=True,
                          scale=None)
    # dense path leaves fully-masked rows as uniform-softmax junk; CP paths
    # zero them — compare only rows with at least one allowed key
    got = jax.jit(lambda a, b_, c, m, sg: ulysses_attention(
        a, b_, c, mesh=mesh, causal=True, mask=m, segment_ids=sg))(
            q, k, v, kv_mask, segs)
    rows_ok = np.asarray(jnp.any(
        dense_mask & (jnp.arange(s)[None, None, :, None]
                      >= jnp.arange(s)[None, None, None, :]), axis=-1))[:, 0]
    np.testing.assert_allclose(np.asarray(got)[rows_ok],
                               np.asarray(want)[rows_ok],
                               atol=2e-5, rtol=2e-5)


def test_ulysses_rejects_undividable_heads_and_bias(eight_devices):
    mesh = MeshSpec(data=1, seq=8).build()
    q, k, v = _qkv(h=4)  # 4 heads over seq=8 → no
    with pytest.raises(ValueError, match="impl='ring'"):
        ulysses_attention(q, k, v, mesh=mesh)
    mesh2 = MeshSpec(data=2, seq=4).build()
    q2, k2, v2 = _qkv()
    with pytest.raises(NotImplementedError, match="bias"):
        ulysses_attention(q2, k2, v2, mesh=mesh2,
                          bias=jnp.zeros((1, 1, 32, 32)))


def test_llama_ulysses_context_parallel_train_step(eight_devices):
    """Full CP train step via impl='ulysses' over data=2 × seq=4; loss ≡
    the pure-DP loss on the same batch/params (mirrors the ring's test)."""
    mesh = MeshSpec(data=2, seq=4).build()
    # tiny() has 4q/2kv heads — too few for seq=4 head scatter; widen to
    # 8q/4kv (the guard under test elsewhere rejects the default)
    cfg = LlamaConfig.tiny(num_heads=8, num_kv_heads=4,
                           attention_impl="ulysses",
                           scan_layers=False, remat=False)
    ring_mod.set_default_mesh(mesh)
    try:
        model = LlamaForCausalLM(cfg)
        batch = {
            "input_ids": np.tile(np.arange(32, dtype=np.int32)[None],
                                 (8, 1)) % cfg.vocab_size,
            "loss_mask": np.ones((8, 32), np.float32),
        }
        tx = optax.adamw(1e-3)
        state, shardings = step_lib.init_state(model, tx, batch, mesh,
                                               ShardingRules())
        train = step_lib.make_train_step(model.apply, tx, losses.causal_lm)
        jitted = step_lib.jit_train_step(train, mesh, shardings,
                                         seq_sharded=True)
        from distributeddeeplearningspark_tpu.data.feed import put_global

        gbatch = put_global(batch, mesh, seq_sharded=True)
        _, metrics = jitted(state, gbatch)

        mesh_dp = MeshSpec(data=8).build()
        cfg_dp = dataclasses.replace(cfg, attention_impl="xla")
        model_dp = LlamaForCausalLM(cfg_dp)
        state_dp, sh_dp = step_lib.init_state(model_dp, tx, batch, mesh_dp,
                                              ShardingRules())
        train_dp = step_lib.make_train_step(model_dp.apply, tx,
                                            losses.causal_lm)
        jitted_dp = step_lib.jit_train_step(train_dp, mesh_dp, sh_dp)
        _, metrics_dp = jitted_dp(state_dp, put_global(batch, mesh_dp))
        np.testing.assert_allclose(
            float(jax.device_get(metrics["loss"])),
            float(jax.device_get(metrics_dp["loss"])),
            rtol=1e-4,
        )
    finally:
        ring_mod.set_default_mesh(None)
