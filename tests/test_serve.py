"""Serving subsystem (ISSUE 4): dynamic batcher coalescing + bucket reuse,
load-shed under a full queue, hot-reload mid-traffic with zero dropped
requests, corrupt-checkpoint reload rejected via manifest verification,
continuous batched decode, and the dlstatus serving rollup."""

import json
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributeddeeplearningspark_tpu import Checkpointer, faults
from distributeddeeplearningspark_tpu.serve import (
    ContinuousGenerator,
    EngineStoppedError,
    HotReloader,
    InferenceEngine,
    OverloadedError,
)
from distributeddeeplearningspark_tpu.serve.engine import default_buckets


def _mul_forward(params, batch):
    return {"y": batch["x"] * params["w"]}


def _mk_engine(**kw):
    kw.setdefault("max_batch", 8)
    kw.setdefault("max_wait_ms", 5.0)
    kw.setdefault("max_queue", 64)
    return InferenceEngine(_mul_forward, {"w": jnp.float32(1.0)}, **kw)


# -- bucket ladder ------------------------------------------------------------


def test_default_buckets():
    assert default_buckets(32) == (1, 2, 4, 8, 16, 32)
    assert default_buckets(24) == (1, 2, 4, 8, 16, 24)
    # mesh-shard multiple: every bucket divides evenly over the data shards
    assert default_buckets(16, multiple_of=4) == (4, 8, 16)
    assert default_buckets(1) == (1,)


# -- coalescing + bucket reuse ------------------------------------------------


def test_coalesces_waiting_requests_into_one_batch():
    """Requests queued before the worker starts dispatch as ONE batch,
    padded to the covering bucket (not one forward per request)."""
    eng = _mk_engine(max_batch=16)
    futs = [eng.submit({"x": np.float32(i)}) for i in range(10)]
    with eng:
        res = [f.result(30) for f in futs]
    for i, r in enumerate(res):
        assert float(r["y"]) == float(i)
    st = eng.stats()
    assert st["batches"] == 1, st
    assert st["bucket_counts"] == {16: 1}, st  # 10 requests → bucket 16


def test_bucket_reuse_no_recompile_per_request():
    """Steady traffic reuses the compiled bucket set: the jit cache stops
    growing after each bucket's first hit (the no-recompile contract)."""
    eng = _mk_engine(max_batch=8, max_wait_ms=1.0)
    with eng:
        eng.warmup({"x": np.float32(0)})
        compiled_after_warmup = eng.stats()["compiled_batch_shapes"]
        assert compiled_after_warmup == len(eng.batch_sizes)
        for wave in range(4):  # varying arrival counts — same buckets
            futs = [eng.submit({"x": np.float32(i)})
                    for i in range(1 + 2 * wave)]
            for f in futs:
                f.result(30)
        st = eng.stats()
    assert st["compiled_batch_shapes"] == compiled_after_warmup, st
    assert st["requests"] == 1 + 3 + 5 + 7
    assert set(st["bucket_counts"]) <= set(eng.batch_sizes)


def test_results_map_back_to_their_requests_across_buckets():
    rng = np.random.default_rng(0)
    eng = _mk_engine(max_batch=4, max_wait_ms=2.0, max_queue=512)
    xs = rng.normal(0, 1, (100,)).astype(np.float32)
    with eng:
        futs = [eng.submit({"x": x}) for x in xs]
        res = [float(f.result(30)["y"]) for f in futs]
    np.testing.assert_allclose(res, xs, rtol=1e-6)


def test_engine_serves_on_a_mesh(eight_devices):
    """The mesh path: batches are placed with the training feed's batch
    sharding (put_global) and the bucket ladder rounds to the data-shard
    count so every bucket divides evenly."""
    from distributeddeeplearningspark_tpu.parallel.mesh import MeshSpec

    mesh = MeshSpec(data=8).build()
    eng = InferenceEngine(_mul_forward, {"w": jnp.float32(3.0)}, mesh=mesh,
                          max_batch=16, max_wait_ms=2.0)
    assert all(b % 8 == 0 for b in eng.batch_sizes), eng.batch_sizes
    futs = [eng.submit({"x": np.float32(i)}) for i in range(5)]
    with eng:
        res = [float(f.result(30)["y"]) for f in futs]
    np.testing.assert_allclose(res, [3.0 * i for i in range(5)])
    with pytest.raises(ValueError, match="data shards"):
        InferenceEngine(_mul_forward, {"w": jnp.float32(1.0)}, mesh=mesh,
                        max_batch=12)


# -- admission control / load shed --------------------------------------------


def test_load_shed_under_full_queue():
    """The queue bound sheds with the typed rejection, carrying evidence."""
    eng = _mk_engine(max_queue=4)  # not started: nothing drains
    for i in range(4):
        eng.submit({"x": np.float32(i)})
    with pytest.raises(OverloadedError) as ei:
        eng.submit({"x": np.float32(99)})
    assert ei.value.queue_depth == 4 and ei.value.max_queue == 4
    st = eng.stats()
    assert st["shed"] == 1 and st["queue_depth"] == 4
    # the queued 4 still complete once the worker runs
    with eng:
        pass  # stop() drains
    assert st["requests"] == 4


def test_stop_without_drain_fails_queued_requests():
    eng = _mk_engine()
    fut = eng.submit({"x": np.float32(1)})
    eng.stop(drain=False)
    with pytest.raises(EngineStoppedError):
        fut.result(5)
    with pytest.raises(EngineStoppedError):
        eng.submit({"x": np.float32(2)})


# -- hot reload ---------------------------------------------------------------


def test_swap_params_mid_traffic_zero_dropped():
    """Params swap between batches: every request completes, and every
    result is consistent with exactly one of the param versions (no torn
    batch, no dropped future)."""
    eng = _mk_engine(max_batch=4, max_wait_ms=1.0, max_queue=4096)
    n = 200
    futs = []
    with eng:
        for i in range(n):
            futs.append(eng.submit({"x": np.float32(1.0)}))
            if i % 20 == 10:
                eng.swap_params({"w": jnp.float32(float(i))})
            if i % 7 == 0:
                time.sleep(0.001)  # let batches interleave with swaps
        res = [float(f.result(30)["y"]) for f in futs]
    assert len(res) == n
    valid = {1.0} | {float(i) for i in range(n) if i % 20 == 10}
    assert set(res) <= valid, sorted(set(res) - valid)
    assert eng.stats()["reloads"] == len(valid) - 1


def test_hot_reload_racing_full_admission_queue():
    """The edge the fleet's rolling reload leans on (ISSUE 6 satellite):
    swap_params hammered while the admission queue sits AT max_queue.
    Invariants: every admitted request resolves exactly once (no drop, no
    double-serve — resolution counted via done-callbacks), every result
    belongs to exactly one param version (no torn batch), sheds seen by
    callers equal the engine's shed counter, and the swaps themselves
    never error against a full queue."""
    eng = _mk_engine(max_batch=4, max_wait_ms=0.5, max_queue=8)
    # deterministic full-queue phase: worker not started, queue pins at 8
    admitted = [eng.submit({"x": np.float32(1.0)}) for _ in range(8)]
    sheds_seen = 0
    for i in range(5):
        eng.swap_params({"w": jnp.float32(2000.0 + i)})  # reload AT full
        with pytest.raises(OverloadedError):
            eng.submit({"x": np.float32(9.0)})
        sheds_seen += 1
    assert eng.stats()["queue_depth"] == 8

    resolved, lock = [0], threading.Lock()

    def on_done(_f):
        with lock:
            resolved[0] += 1

    for f in admitted:
        f.add_done_callback(on_done)

    # racing phase: drain + new traffic while a reloader thread swaps
    stop = threading.Event()
    swapped: list[float] = []

    def reloader():
        i = 0
        while not stop.is_set():
            i += 1
            eng.swap_params({"w": jnp.float32(3000.0 + i)})
            swapped.append(3000.0 + i)
            time.sleep(0.0005)

    t = threading.Thread(target=reloader)
    with eng:
        t.start()
        try:
            for i in range(200):
                try:
                    f = eng.submit({"x": np.float32(1.0)})
                except OverloadedError:
                    sheds_seen += 1
                    continue
                f.add_done_callback(on_done)
                admitted.append(f)
        finally:
            stop.set()
            t.join()
    res = [float(f.result(30)["y"]) for f in admitted]
    assert len(res) == len(admitted)                    # zero dropped
    assert resolved[0] == len(admitted)                 # exactly once each
    valid = {1.0} | {2000.0 + i for i in range(5)} | set(swapped)
    assert set(res) <= valid, sorted(set(res) - valid)  # never torn
    st = eng.stats()
    assert st["requests"] == len(admitted)
    assert st["shed"] == sheds_seen                     # caller view == engine


def test_shed_accounting_matches_telemetry_request_events(tmp_path):
    """OverloadedError accounting must tie out EXACTLY across all three
    ledgers the fleet reconciles: exceptions callers caught, the engine's
    stats counters, and the telemetry ``request`` events dlstatus reads
    (ISSUE 6 satellite — a mismatch makes the --fleet-serve shed rate a
    lie)."""
    from distributeddeeplearningspark_tpu import telemetry

    eng = _mk_engine(max_queue=3, max_batch=2, max_wait_ms=0.5,
                     workdir=str(tmp_path))
    admitted = [eng.submit({"x": np.float32(i)}) for i in range(3)]
    caught = []
    for i in range(4):
        with pytest.raises(OverloadedError) as ei:
            eng.submit({"x": np.float32(50.0 + i)})
        caught.append(ei.value)
    assert all(e.queue_depth == 3 and e.max_queue == 3 for e in caught)
    with eng:
        pass                                    # context exit drains the 3
    for f in admitted:
        f.result(30)

    st = eng.stats()
    assert st["requests"] == 3 and st["shed"] == len(caught) == 4
    evs = [e for e in telemetry.read_events(tmp_path)
           if e.get("kind") == "request"]
    ok = [e for e in evs if e["outcome"] == "ok"]
    shed = [e for e in evs if e["outcome"] == "shed"]
    assert len(evs) == len(ok) + len(shed)      # no third outcome leaked
    assert len(ok) == st["requests"] == 3
    assert len(shed) == st["shed"] == 4
    # every shed event carries the full-queue evidence and its own id —
    # ids disjoint from the served ones (an id in both = double-counted)
    assert all(e["queue_depth"] == 3 for e in shed)
    assert {e["id"] for e in ok}.isdisjoint({e["id"] for e in shed})
    telemetry.reset()


class _EngineDouble:
    def __init__(self):
        self.swaps = []

    def swap_params(self, params, *, version=None):
        self.swaps.append((params, version))


def _tiny_state(w: float):
    from distributeddeeplearningspark_tpu.train.state import TrainState

    params = {"w": jnp.float32(w)}
    return TrainState.create(
        params=params, opt_state=optax.sgd(0.1).init(params), mutable={},
        rng=jax.random.PRNGKey(0))


def test_hot_reload_corrupt_candidate_rejected_then_recovers(tmp_path):
    """A torn newest step is rejected via its integrity manifest — the old
    params keep serving (rollback), the rejection is remembered (no retry
    loop), and a later intact step reloads normally."""
    from distributeddeeplearningspark_tpu import telemetry

    wd = tmp_path / "ckpt"
    telemetry.configure(wd)
    with Checkpointer(wd, async_save=False) as ck:
        ck.save(1, _tiny_state(1.0))
        ck.save(2, _tiny_state(2.0))
        ck.wait()
    assert faults.truncate_latest_checkpoint(str(wd))

    eng = _EngineDouble()
    rel = HotReloader(eng, wd, current_step=1)
    try:
        act = rel.poll()
        assert act == {"step": 2, "action": "rejected",
                       "reason": act["reason"]}
        assert "checksum" in act["reason"] or "size" in act["reason"]
        assert eng.swaps == []           # old params keep serving
        assert rel.current_step == 1
        assert rel.poll() is None        # rejection remembered, not retried

        with Checkpointer(wd, async_save=False) as ck:
            ck.save(3, _tiny_state(3.0))
            ck.wait()
        act = rel.poll()
        assert act["action"] == "reloaded" and act["step"] == 3
        assert len(eng.swaps) == 1
        params, version = eng.swaps[0]
        assert version == 3
        assert float(np.asarray(params["w"])) == 3.0
    finally:
        rel.stop()
    events = telemetry.read_events(wd)
    kinds = [(e.get("event"), e.get("step")) for e in events
             if e.get("kind") == "recovery"]
    assert ("reload-rejected", 2) in kinds and ("reload", 3) in kinds


def test_hot_reload_corrupt_latest_falls_back_to_older_verified(tmp_path):
    """When the newest unseen step is torn but an OLDER unseen step
    verifies, the reloader serves the older one instead of nothing."""
    wd = tmp_path / "ckpt"
    with Checkpointer(wd, async_save=False) as ck:
        ck.save(5, _tiny_state(5.0))
        ck.save(6, _tiny_state(6.0))
        ck.wait()
    assert faults.truncate_latest_checkpoint(str(wd))
    eng = _EngineDouble()
    rel = HotReloader(eng, wd)  # fresh server: no current step
    try:
        act = rel.poll()
        assert act["action"] == "reloaded" and act["step"] == 5
        assert act["fell_back_past"] == 6
        assert [v for _, v in eng.swaps] == [5]
    finally:
        rel.stop()


def test_hot_reload_watcher_swaps_live_engine(tmp_path):
    """The background watcher + a real engine: a new verified checkpoint
    changes served results mid-traffic with zero dropped requests."""
    wd = tmp_path / "ckpt"
    with Checkpointer(wd, async_save=False) as ck:
        ck.save(1, _tiny_state(1.0))
        ck.wait()

    eng = InferenceEngine(_mul_forward, {"w": jnp.float32(0.0)},
                          max_batch=4, max_wait_ms=1.0, max_queue=4096)
    rel = HotReloader(eng, wd, interval_s=0.02)
    futs = []
    with eng, rel:
        deadline = time.monotonic() + 10
        while eng.params_version != 1 and time.monotonic() < deadline:
            futs.append(eng.submit({"x": np.float32(1.0)}))
            time.sleep(0.002)
        assert eng.params_version == 1, "watcher never reloaded step 1"
        futs.append(eng.submit({"x": np.float32(1.0)}))
        res = [float(f.result(30)["y"]) for f in futs]
    assert set(res) <= {0.0, 1.0}
    assert res[-1] == 1.0                 # post-reload batch on new params
    assert len(res) == len(futs)          # zero dropped across the swap


def test_restore_params_roundtrip_and_verification(tmp_path):
    state = _tiny_state(7.0)
    with Checkpointer(tmp_path / "ck", async_save=False) as ck:
        ck.save(4, state, data_state={"examples_seen": 8})
        ck.wait()
        params, step = ck.restore_params()
        assert step == 4
        np.testing.assert_array_equal(np.asarray(params["w"]),
                                      np.asarray(state.params["w"]))
        faults.truncate_latest_checkpoint(str(tmp_path / "ck"))
        from distributeddeeplearningspark_tpu.checkpoint import RestoreError

        with pytest.raises(RestoreError):
            ck.restore_params(step=4)


# -- continuous batched decode ------------------------------------------------


@pytest.fixture(scope="module")
def nano_llama():
    from distributeddeeplearningspark_tpu.models import (
        LlamaConfig,
        LlamaForCausalLM,
    )

    cfg = LlamaConfig(vocab_size=128, hidden_size=64, num_layers=2,
                      num_heads=4, num_kv_heads=2, intermediate_size=128,
                      max_position=64, dtype=jnp.float32)
    model = LlamaForCausalLM(cfg)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, 128, (n,)).astype(np.int32)
               for n in (5, 7, 6, 4)]
    params = model.init(jax.random.PRNGKey(0),
                        {"input_ids": prompts[0][None]},
                        train=False)["params"]

    def ref_rollout(prompt, n):
        """Greedy full-recompute reference (no KV cache at all)."""
        ids = prompt[None, :]
        out = []
        for _ in range(n):
            lg = model.apply({"params": params}, {"input_ids": ids},
                             train=False)
            nxt = np.argmax(np.asarray(lg[0, -1])).astype(np.int32)
            out.append(int(nxt))
            ids = np.concatenate([ids, [[nxt]]], axis=1)
        return np.asarray(out, np.int32)

    return cfg, params, prompts, ref_rollout


def test_continuous_decode_matches_reference_and_joins_midflight(nano_llama):
    """4 requests over 2 KV slots: every output matches the full-recompute
    rollout (so slot admission at differing positions is numerically
    clean), admissions exceed the pool (join-mid-flight), and tokens
    stream in order as they are sampled."""
    cfg, params, prompts, ref = nano_llama
    streamed: list[int] = []
    gen = ContinuousGenerator(cfg, params, slots=2, max_cache_len=32,
                              prompt_buckets=(8, 16))
    with gen:
        futs = [gen.submit(p, 6,
                           stream=(streamed.append if i == 0 else None))
                for i, p in enumerate(prompts)]
        res = [f.result(300) for f in futs]
    for p, r in zip(prompts, res):
        np.testing.assert_array_equal(r, ref(p, 6))
    assert streamed == list(res[0])
    st = gen.stats()
    assert st["completed"] == 4 and st["admitted"] == 4
    assert st["max_active"] == 2          # the pool really ran full
    assert st["queue_depth"] == 0 and st["active"] == 0


def test_continuous_decode_prompt_buckets_bound_prefill_compiles(nano_llama):
    """Prompts of different lengths share prefill programs per bucket."""
    cfg, params, prompts, ref = nano_llama
    gen = ContinuousGenerator(cfg, params, slots=2, max_cache_len=32,
                              prompt_buckets=(8,))
    with gen:
        for p in prompts:                 # lengths 4..7 → all bucket 8
            np.testing.assert_array_equal(gen.generate(p, 3), ref(p, 3))
    assert gen._prefill._cache_size() == 1


def test_continuous_decode_swap_params_midflight(nano_llama):
    """A params swap mid-sequence completes every request (tokens after
    the swap come from the new tree — nothing drops or restarts)."""
    cfg, params, prompts, _ = nano_llama
    gen = ContinuousGenerator(cfg, params, slots=2, max_cache_len=64)
    seen = threading.Event()

    def on_tok(_):
        seen.set()

    with gen:
        fut = gen.submit(prompts[0], 24, stream=on_tok)
        assert seen.wait(120), "no token streamed"
        gen.swap_params(jax.tree.map(lambda x: x * 1.01, params))
        out = fut.result(300)
    assert out.shape == (24,)
    assert gen.stats()["reloads"] == 1 and gen.params_version == 1


def test_generator_rejects_oversized_and_sheds(nano_llama):
    cfg, params, prompts, _ = nano_llama
    gen = ContinuousGenerator(cfg, params, slots=2, max_cache_len=16,
                              prompt_buckets=(8,), max_queue=1)
    with pytest.raises(ValueError, match="max_cache_len"):
        gen.submit(prompts[0], 16)
    with pytest.raises(ValueError, match="prompt bucket"):
        gen.submit(np.arange(9, dtype=np.int32), 2)
    gen.submit(prompts[0], 2)            # queued (not started)
    with pytest.raises(OverloadedError):
        gen.submit(prompts[1], 2)
    gen.stop(drain=False)


def test_generator_eos_frees_slot_early(nano_llama):
    """eos mid-sequence completes the request (eos token included) before
    max_new_tokens, freeing the slot for the queue."""
    cfg, params, prompts, ref = nano_llama
    full = ref(prompts[0], 8)
    # first token value whose FIRST occurrence is past position 0 — using
    # it as eos must stop the rollout exactly there (eos token included)
    cut = next(i for i in range(1, len(full)) if full[i] not in full[:i])
    eos = int(full[cut])
    gen = ContinuousGenerator(cfg, params, slots=1, max_cache_len=32,
                              eos_id=eos)
    with gen:
        out = gen.generate(prompts[0], 8)
    np.testing.assert_array_equal(out, full[:cut + 1])


# -- telemetry + dlstatus rollup ----------------------------------------------


def test_emit_many_single_flush_stream(tmp_path):
    from distributeddeeplearningspark_tpu import telemetry

    w = telemetry.EventWriter(tmp_path, process="p0", clock=lambda: 100.0,
                              host=None)
    w.emit_many("request", [dict(id=i, outcome="ok", latency_s=0.01 * i)
                            for i in range(5)])
    w.emit_many("request", [])           # no-op, no crash
    w.close()
    events = telemetry.read_events(tmp_path)
    assert len(events) == 5
    assert all(e["kind"] == "request" and e["ts"] == 100.0 for e in events)
    assert [e["id"] for e in events] == list(range(5))


def test_dlstatus_serving_rollup(tmp_path, capsys):
    from distributeddeeplearningspark_tpu import status, telemetry

    w = telemetry.EventWriter(tmp_path, process="p0", clock=lambda: 0.0,
                              host=None)
    t = [0.0]

    def clock():
        t[0] += 0.5
        return t[0]

    w._clock = clock
    lat = [0.010, 0.020, 0.030, 0.040, 0.100]
    w.emit_many("request", [
        dict(engine="lenet", id=i, outcome="ok", latency_s=v,
             queue_wait_s=v / 2, infer_s=v / 2, batch_size=4)
        for i, v in enumerate(lat)])
    w.emit("request", engine="lenet", id=99, outcome="shed", queue_depth=64)
    w.emit("request", engine="lenet", id=98, outcome="error", batch_size=2)
    w.close()

    rep = status.report(str(tmp_path))
    sv = rep["serving"]
    assert sv["requests"] == 7 and sv["ok"] == 5
    assert sv["shed"] == 1 and sv["errors"] == 1
    assert sv["engines"] == ["lenet"]
    assert sv["latency_p50_s"] == 0.030
    assert sv["latency_p99_s"] == 0.100 and sv["latency_max_s"] == 0.100
    assert sv["mean_batch_size"] == 4.0
    assert sv["requests_per_s"] > 0

    assert status.main([str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "serving (lenet)" in out and "p99=100.0ms" in out
    assert status.main([str(tmp_path), "--json"]) == 0
    rec = json.loads(capsys.readouterr().out)
    assert rec["serving"]["shed"] == 1


def test_dlstatus_no_requests_serving_is_none(tmp_path):
    from distributeddeeplearningspark_tpu import status, telemetry

    w = telemetry.EventWriter(tmp_path, process="p0", clock=lambda: 1.0,
                              host=None)
    w.heartbeat(step=0)
    w.close()
    assert status.report(str(tmp_path))["serving"] is None


# -- dlserve CLI --------------------------------------------------------------


def test_dlserve_cli_flag_validation():
    from distributeddeeplearningspark_tpu.serve import cli

    with pytest.raises(SystemExit) as e:
        cli.main(["--watch"])            # --watch needs --checkpoint-dir
    assert e.value.code == 2
    with pytest.raises(SystemExit) as e:
        cli.build_parser().parse_args(["--model", "nope"])
    assert e.value.code == 2


def test_engine_rejects_bad_config():
    with pytest.raises(ValueError, match="max_batch"):
        _mk_engine(max_batch=0)
    with pytest.raises(ValueError, match="smaller than"):
        _mk_engine(max_batch=8, batch_sizes=(2, 4))
