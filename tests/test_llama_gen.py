"""KV-cached Llama generation (models/llama_gen.py): the decode path must be
numerically identical to the training forward, and the jitted sampler must
match a naive full-recompute rollout."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributeddeeplearningspark_tpu.models import LlamaConfig, LlamaForCausalLM
from distributeddeeplearningspark_tpu.models.llama_gen import decode_model, generate


@pytest.fixture(scope="module")
def tiny():
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, cfg.vocab_size, (2, 8)).astype(np.int32)
    variables = model.init(jax.random.PRNGKey(0), {"input_ids": prompt},
                           train=False)
    return cfg, model, variables["params"], prompt


def _assert_decode_matches_teacher_forcing(cfg, model, params, seed):
    """Prefill + per-token decode must reproduce the full-forward logits
    (the KV cache holds the same K/V the training path recomputes)."""
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, cfg.vocab_size, (2, 12)).astype(np.int32)
    ref = model.apply({"params": params}, {"input_ids": ids}, train=False)
    dmodel = decode_model(cfg, 12)
    lo, mut = dmodel.apply({"params": params}, {"input_ids": ids[:, :8]},
                           train=False, mutable=["cache"])
    np.testing.assert_allclose(np.asarray(lo), np.asarray(ref[:, :8]),
                               rtol=2e-4, atol=2e-4)
    cache = mut["cache"]
    for i in range(8, 12):
        lo, mut = dmodel.apply({"params": params, "cache": cache},
                               {"input_ids": ids[:, i:i + 1]},
                               train=False, mutable=["cache"])
        cache = mut["cache"]
        np.testing.assert_allclose(np.asarray(lo[:, 0]), np.asarray(ref[:, i]),
                                   rtol=2e-4, atol=2e-4)


def test_decode_logits_match_teacher_forcing(tiny):
    cfg, model, params, _ = tiny
    _assert_decode_matches_teacher_forcing(cfg, model, params, seed=1)


def test_int8_base_decode_matches_its_own_teacher_forcing():
    """Serving is where int8 base storage pays (per-token weight reads
    halve): the int8 model's decode must equal the SAME model's training
    forward — quantization error cancels in the self-comparison, so any
    mismatch is a decode-path bug."""
    cfg = LlamaConfig.tiny(lora_rank=4, base_quant="int8")
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        {"input_ids": np.zeros((2, 12), np.int32)},
                        train=False)["params"]
    _assert_decode_matches_teacher_forcing(cfg, model, params, seed=3)


def test_greedy_generate_matches_full_recompute_rollout(tiny):
    cfg, model, params, prompt = tiny
    out = generate(params, jnp.asarray(prompt), cfg=cfg, max_new_tokens=6)
    assert out.shape == (2, 6)
    ids = prompt
    for _ in range(6):
        lg = model.apply({"params": params}, {"input_ids": jnp.asarray(ids)},
                         train=False)
        nxt = np.argmax(np.asarray(lg[:, -1]), -1).astype(np.int32)
        ids = np.concatenate([ids, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(out), ids[:, 8:])


def test_generate_unscanned_layers_matches_scanned(tiny):
    cfg, _, params, prompt = tiny
    out_scan = generate(params, jnp.asarray(prompt), cfg=cfg, max_new_tokens=4)
    # same params flattened into the unscanned layout would differ in tree
    # structure; instead just check the unscanned decode path runs and is
    # self-consistent with its own training forward
    cfg2 = dataclasses.replace(cfg, scan_layers=False)
    model2 = LlamaForCausalLM(cfg2)
    v2 = model2.init(jax.random.PRNGKey(1), {"input_ids": prompt}, train=False)
    out2 = generate(v2["params"], jnp.asarray(prompt), cfg=cfg2,
                    max_new_tokens=4)
    ids = prompt
    for _ in range(4):
        lg = model2.apply({"params": v2["params"]},
                          {"input_ids": jnp.asarray(ids)}, train=False)
        nxt = np.argmax(np.asarray(lg[:, -1]), -1).astype(np.int32)
        ids = np.concatenate([ids, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(out2), ids[:, 8:])
    assert out_scan.shape == out2.shape


def test_eos_freezes_finished_rows(tiny):
    """After a row emits eos, it pads; other rows keep generating."""
    cfg, model, params, prompt = tiny
    ref = generate(params, jnp.asarray(prompt), cfg=cfg, max_new_tokens=5)
    eos = int(np.asarray(ref)[0, 1])  # force row 0 to 'finish' at step 1
    out = np.asarray(generate(params, jnp.asarray(prompt), cfg=cfg,
                              max_new_tokens=5, eos_id=eos, pad_id=0))
    row = out[0]
    hit = np.flatnonzero(row == eos)
    assert hit.size, "eos token never appears in the row that produced it"
    assert (row[hit[0] + 1:] == 0).all(), f"row not frozen after eos: {row}"


def test_sampling_modes_are_valid(tiny):
    cfg, _, params, prompt = tiny
    out = generate(params, jnp.asarray(prompt), cfg=cfg, max_new_tokens=4,
                   temperature=1.0, top_k=8, seed=3)
    arr = np.asarray(out)
    assert arr.shape == (2, 4)
    assert (0 <= arr).all() and (arr < cfg.vocab_size).all()
    # reproducible for a fixed seed
    out2 = generate(params, jnp.asarray(prompt), cfg=cfg, max_new_tokens=4,
                    temperature=1.0, top_k=8, seed=3)
    np.testing.assert_array_equal(arr, np.asarray(out2))


def test_cache_overflow_rejected(tiny):
    cfg, _, params, prompt = tiny
    with pytest.raises(ValueError, match="max_position"):
        generate(params, jnp.asarray(prompt), cfg=cfg,
                 max_new_tokens=cfg.max_position + 1)


def test_explicit_cache_len_too_small_rejected(tiny):
    cfg, _, params, prompt = tiny
    with pytest.raises(ValueError, match="max_cache_len"):
        generate(params, jnp.asarray(prompt), cfg=cfg, max_new_tokens=8,
                 max_cache_len=10)  # 8 prompt + 8 new > 10


def test_decode_rejects_padding_mask(tiny):
    cfg, _, params, prompt = tiny
    dmodel = decode_model(cfg, 16)
    with pytest.raises(ValueError, match="equal-length prompts"):
        dmodel.apply({"params": params},
                     {"input_ids": prompt,
                      "attention_mask": np.ones_like(prompt)},
                     train=False, mutable=["cache"])


def test_generate_with_tp_sharded_params(tiny, eight_devices):
    """Multi-chip serving: generation runs with TP-sharded params on a
    data x tensor mesh and matches the unsharded greedy output."""
    from distributeddeeplearningspark_tpu.models import llama_rules
    from distributeddeeplearningspark_tpu.parallel.mesh import MeshSpec
    from distributeddeeplearningspark_tpu.parallel.sharding import state_shardings

    cfg, model, params, prompt = tiny
    ref = np.asarray(generate(params, jnp.asarray(prompt), cfg=cfg,
                              max_new_tokens=5))
    mesh = MeshSpec(data=4, tensor=2).build()
    rules = llama_rules(cfg, fsdp=False)
    sh = state_shardings(jax.eval_shape(lambda: params), mesh, rules)
    sharded = jax.tree.map(jax.device_put, params, sh)
    out = np.asarray(generate(sharded, jnp.asarray(prompt), cfg=cfg,
                              max_new_tokens=5))
    np.testing.assert_array_equal(out, ref)
