"""bench.py resilience (VERDICT r1 weak-#1): the harness must survive a dead
backend and emit structured JSON, never a traceback."""

import json
import subprocess
import sys

import jax
import numpy as np

import bench


def test_probe_timeout_and_failure_are_contained(monkeypatch):
    """A hanging probe subprocess is killed at the timeout, logged, and the
    unavailable verdict is CACHED for the remaining attempts — BENCH_r05
    burned 3×150 s learning the same hang three times."""
    calls = {"n": 0}

    def fake_run(*a, **kw):
        calls["n"] += 1
        raise subprocess.TimeoutExpired(cmd=a[0], timeout=kw["timeout"])

    monkeypatch.setattr(subprocess, "run", fake_run)
    ok, errors = bench.probe_backend(attempts=3, timeout_s=0.01, backoff_s=0.0)
    assert not ok
    assert calls["n"] == 1, "a hang must not be retried"
    assert "hung" in errors[0]
    assert any("cached" in e and "skipping" in e for e in errors)


def test_probe_hang_on_last_attempt_adds_no_cache_note(monkeypatch):
    """rc-failures retry (they may be flaky inits); a hang on the FINAL
    attempt has nothing left to skip and says nothing about caching."""
    calls = {"n": 0}

    def fake_run(*a, **kw):
        calls["n"] += 1
        if calls["n"] < 3:
            return subprocess.CompletedProcess(a[0], 1, stdout="",
                                               stderr="setup error\n")
        raise subprocess.TimeoutExpired(cmd=a[0], timeout=kw["timeout"])

    monkeypatch.setattr(subprocess, "run", fake_run)
    ok, errors = bench.probe_backend(attempts=3, timeout_s=0.01, backoff_s=0.0)
    assert not ok and calls["n"] == 3
    assert not any("cached" in e for e in errors)


def test_probe_rc_failure_recorded(monkeypatch):
    def fake_run(*a, **kw):
        return subprocess.CompletedProcess(
            a[0], 1, stdout="", stderr="UNAVAILABLE: TPU backend setup error\n")

    monkeypatch.setattr(subprocess, "run", fake_run)
    ok, errors = bench.probe_backend(attempts=2, timeout_s=1, backoff_s=0.0)
    assert not ok and len(errors) == 2
    assert "UNAVAILABLE" in errors[0]


def test_probe_success_short_circuits(monkeypatch):
    def fake_run(*a, **kw):
        return subprocess.CompletedProcess(a[0], 0, stdout="tpu v5 1\n", stderr="")

    monkeypatch.setattr(subprocess, "run", fake_run)
    ok, errors = bench.probe_backend(attempts=3, timeout_s=1, backoff_s=0.0)
    assert ok and errors == []


def test_backend_unavailable_emits_structured_json(monkeypatch, capsys):
    """Main with a dead backend: rc 0 and one parseable JSON line (this is
    exactly the r1 failure mode that produced BENCH_r01.json rc=1)."""
    monkeypatch.setattr(bench, "probe_backend",
                        lambda **kw: (False, ["probe 1/3: hung past 150s (killed)"]))
    # single device workload: all-mode now degrades to the host input bench
    # instead (see test_all_mode_degrades_to_host_input_when_tpu_down)
    rc = bench.main(["--model", "resnet"])
    assert rc == 0
    line = capsys.readouterr().out.strip().splitlines()[-1]
    rec = json.loads(line)
    assert rec["metric"] == "backend_unavailable"
    assert rec["value"] == 0.0 and rec["vs_baseline"] == 0.0
    assert rec["extra"]["errors"]


def test_bench_failure_in_one_model_does_not_kill_the_other(monkeypatch, capsys):
    monkeypatch.setattr(bench, "probe_backend", lambda **kw: (True, []))
    monkeypatch.setattr(bench, "bench_resnet",
                        lambda iters, **kw: {"images_per_sec_per_chip": 123.0,
                                             "mfu": 0.5, "step_time_ms": 1.0,
                                             "batch_size": 8, "chips": 1})

    def boom(iters, **kw):
        raise RuntimeError("RESOURCE_EXHAUSTED: OOM")

    monkeypatch.setattr(bench, "bench_bert", boom)
    monkeypatch.setattr(bench, "bench_llama", lambda iters, **kw: {
        "tokens_per_sec_per_chip": 1.0, "mfu_hlo_scan_opaque": 0.1,
        "step_time_ms": 1.0, "params": 1, "batch_size": 4, "seq_len": 2048,
        "chips": 1})
    monkeypatch.setattr(bench, "bench_dlrm", lambda iters, **kw: {
        "examples_per_sec_per_chip": 1.0, "mfu": 0.0, "step_time_ms": 1.0,
        "batch_size": 8192, "embedding_rows": 1, "chips": 1})
    monkeypatch.setattr(bench, "pallas_smoke", lambda: {"causal_d128": "ok"})
    rc = bench.main([])
    assert rc == 0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["metric"] == "resnet50_images_per_sec_per_chip"
    assert rec["value"] == 123.0
    assert any("OOM" in e for e in rec["extra"]["errors"])
    assert rec["extra"]["pallas_smoke"] == {"causal_d128": "ok"}


def test_bench_cli_parses_before_heavy_import():
    """Argparse runs before any jax import: a bad flag exits 2 instantly
    (no backend init, no hang) and --help exits 0."""
    import pytest

    with pytest.raises(SystemExit) as e:
        bench.main(["--model", "nope"])
    assert e.value.code == 2
    with pytest.raises(SystemExit) as e:
        bench.main(["--help"])
    assert e.value.code == 0


def test_bench_help_never_touches_a_backend():
    """--help in a FRESH interpreter with a bogus JAX platform must succeed:
    if bench.py ever initializes jax before argparse, this fails/hangs (the r1
    'one flaky PJRT init burned the whole round' mode)."""
    out = subprocess.run(
        [sys.executable, "bench.py", "--help"],
        capture_output=True, text=True, timeout=60, cwd=".",
        env={**__import__("os").environ, "JAX_PLATFORMS": "bogus_platform"},
        check=False)
    assert out.returncode == 0
    assert "usage:" in out.stdout


def test_timing_suspect_zeroes_vs_baseline(monkeypatch, capsys):
    """An MFU>100% artifact must not be reported as a real headline ratio."""
    monkeypatch.setattr(bench, "probe_backend", lambda **kw: (True, []))
    monkeypatch.setattr(bench, "bench_resnet",
                        lambda iters, **kw: {"images_per_sec_per_chip": 9e4,
                                             "mfu": 10.47, "step_time_ms": 3.0,
                                             "batch_size": 256, "chips": 1,
                                             "timing_suspect": "mfu 10.47 > 1.0"})
    monkeypatch.setattr(bench, "bench_bert", lambda iters, **kw: {
        "tokens_per_sec_per_chip": 1.0, "mfu": 0.3, "step_time_ms": 1.0,
        "batch_size": 32, "seq_len": 512, "chips": 1})
    monkeypatch.setattr(bench, "bench_llama", lambda iters, **kw: {
        "tokens_per_sec_per_chip": 1.0, "mfu_hlo_scan_opaque": 0.1,
        "step_time_ms": 1.0, "params": 1, "batch_size": 4, "seq_len": 2048,
        "chips": 1})
    monkeypatch.setattr(bench, "bench_dlrm", lambda iters, **kw: {
        "examples_per_sec_per_chip": 1.0, "mfu": 0.0, "step_time_ms": 1.0,
        "batch_size": 8192, "embedding_rows": 1, "chips": 1})
    monkeypatch.setattr(bench, "pallas_smoke", lambda: {})
    assert bench.main([]) == 0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["vs_baseline"] == 0.0
    assert any("timing" in e or "mfu" in e for e in rec["extra"]["errors"])


def test_sanity_check_mfu_flags_impossible():
    rec = {"mfu": 10.47}
    bench._sanity_check_mfu(rec)
    assert "timing_suspect" in rec
    rec2 = {"mfu": 0.35}
    bench._sanity_check_mfu(rec2)
    assert "timing_suspect" not in rec2


def test_attention_matmul_flops_convention():
    """Model-flops convention: fwd = 2 matmuls, bwd = 4, causal halves,
    GQA/masking don't enter (both matmuls run at the q-head count)."""
    from distributeddeeplearningspark_tpu.metrics import attention_matmul_flops

    b, h, s, d = 2, 3, 64, 16
    one = 2.0 * b * h * s * s * d
    assert attention_matmul_flops(b, h, s, d, train=False) == 2 * one
    assert attention_matmul_flops(b, h, s, d, train=True) == 6 * one
    assert attention_matmul_flops(b, h, s, d, causal=True, train=True) == 3 * one


def test_llama_model_flops_formula():
    """The analytic MFU formula (metrics.llama_model_flops_per_token):
    closed-form identities that would catch any ×2/×L bookkeeping slip —
    the bug class it exists to route around (XLA cost analysis counts the
    layer-scan body once, not ×L — r5 finding, see
    test_cost_analysis_is_scan_opaque — deflating llama MFU to 12% on the
    r4 device record while the same step's analytic count puts it ~50%)."""
    from distributeddeeplearningspark_tpu.metrics import (
        attention_matmul_flops, llama_model_flops_per_token)
    from distributeddeeplearningspark_tpu.models import LlamaConfig

    cfg = LlamaConfig(vocab_size=2048, hidden_size=256, num_layers=4,
                      num_heads=8, num_kv_heads=4, intermediate_size=512,
                      max_position=256, lora_rank=8, dtype="float32")
    s = 256
    h, i, v = 256, 512, 2048
    kvh = cfg.num_kv_heads * cfg.head_dim
    p = cfg.num_layers * (2 * h * h + 2 * h * kvh + 3 * h * i) + v * h
    lora = sum(cfg.num_layers * 8 * (h + {"wq": h, "wv": kvh}[t])
               for t in ("wq", "wv"))
    attn = cfg.num_layers * attention_matmul_flops(
        1, 8, s, 32, causal=True, train=True) / s
    frozen = llama_model_flops_per_token(cfg, s, frozen_base=True)
    full = llama_model_flops_per_token(cfg, s, frozen_base=False)
    assert frozen == 4 * p + 6 * lora + attn
    assert full == 6 * p + 6 * lora + attn
    # full-autodiff : frozen ratio must be exactly the dW share
    assert (full - frozen) == 2 * p
    # no-LoRA config drops the adapter term and the frozen distinction
    dense_cfg = LlamaConfig(vocab_size=2048, hidden_size=256, num_layers=4,
                            num_heads=8, num_kv_heads=4,
                            intermediate_size=512, max_position=256,
                            dtype="float32")
    assert llama_model_flops_per_token(
        dense_cfg, s, frozen_base=False) == 6 * p + attn
    # MoE: top_k expert FFNs + router replace the dense FFN term (the r4
    # review caught mfu_model silently undercounting --moe-experts runs)
    moe_cfg = LlamaConfig(vocab_size=2048, hidden_size=256, num_layers=4,
                          num_heads=8, num_kv_heads=4, intermediate_size=512,
                          max_position=256, dtype="float32",
                          moe_experts=4, moe_top_k=2)
    p_moe = p + cfg.num_layers * ((2 - 1) * 3 * h * i + h * 4)
    assert llama_model_flops_per_token(
        moe_cfg, s, frozen_base=False) == 6 * p_moe + attn


def _compiled_llama_flops(num_layers: int, *, scan: bool):
    """Compile a tiny frozen-base llama step and return (measured HLO
    flops, analytic model flops) — shared by the cross-check tests."""
    import optax

    from distributeddeeplearningspark_tpu.metrics import (
        compiled_flops_per_step, llama_model_flops_per_token)
    from distributeddeeplearningspark_tpu.models import (
        LlamaConfig, LlamaForCausalLM, llama_rules, lora_trainable)
    from distributeddeeplearningspark_tpu.parallel.mesh import MeshSpec
    from distributeddeeplearningspark_tpu.train import losses, step as step_lib

    b, s = 2, 256
    cfg = LlamaConfig(vocab_size=2048, hidden_size=256,
                      num_layers=num_layers, num_heads=8, num_kv_heads=4,
                      intermediate_size=512, max_position=s, lora_rank=8,
                      dtype="float32", remat=False, scan_layers=scan)
    model = LlamaForCausalLM(cfg)
    batch = {"input_ids": np.ones((b, s), np.int32),
             "loss_mask": np.ones((b, s), np.float32)}
    mesh = MeshSpec(data=1).build(jax.devices()[:1])
    state, sh = step_lib.init_state(
        model, optax.sgd(1e-3), batch, mesh,
        llama_rules(cfg, fsdp_min_size=1 << 30))
    step = step_lib.jit_train_step(
        step_lib.make_train_step(model.apply, optax.sgd(1e-3),
                                 losses.causal_lm, trainable=lora_trainable),
        mesh, sh)
    measured = compiled_flops_per_step(step.lower(state, batch).compile())
    assert measured is not None
    analytic = llama_model_flops_per_token(cfg, s, frozen_base=True) * b * s
    return measured, analytic


def test_llama_model_flops_vs_cpu_cost_analysis():
    """Cross-check the analytic formula against the UNROLLED compiled
    step, whose HLO cost analysis sees every layer (XLA convention:
    2 flops/MAC, same as the formula). Bounds are tight enough to catch a
    dropped backward at ANY depth (VERDICT r4 weak-#4: the old ±40%
    window on the scanned step passed only because a 2× convention error
    and the scan-body undercount canceled at L=4): measured r5 ratios are
    1.065 (L=2) and 1.105 (L=4) — the excess over 1.0 is elementwise/
    optimizer work the formula excludes — while a dropped backward
    divides the true count by ~2.1 (the measured fwd:frozen-step ratio),
    putting the ratio at ~0.5, far outside [0.95, 1.30] at every depth."""
    for num_layers in (2, 4):
        measured, analytic = _compiled_llama_flops(num_layers, scan=False)
        ratio = measured / analytic
        assert 0.95 < ratio < 1.30, (num_layers, measured, analytic, ratio)


def test_cost_analysis_is_scan_opaque():
    """Pin the mechanism `mfu_hlo_scan_opaque` is named for: XLA cost
    analysis reports the layer-scan body ONCE, not × trip count, so the
    scanned L=4 count comes in BELOW even the unrolled L=2 count (one
    body + head < two layers + head). If a jax upgrade starts counting
    scan trips, this fails and the suspect-number plumbing (bench_llama,
    metrics docstrings, BASELINE r5 log) should be retired."""
    scanned4, _ = _compiled_llama_flops(4, scan=True)
    unrolled2, _ = _compiled_llama_flops(2, scan=False)
    assert scanned4 < unrolled2, (scanned4, unrolled2)


def test_routes_to_flash_matches_router(monkeypatch):
    """The bench's FLOPs adjustment must follow the real attention router:
    off-TPU it reports False (XLA path), so no adjustment is applied."""
    assert bench._routes_to_flash(b=2, s=512, h=12, d=64, masked=True) is False

    import jax

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    assert bench._routes_to_flash(b=2, s=512, h=12, d=64, masked=True) is True
    # sub-block sequence falls back to XLA even on TPU
    assert bench._routes_to_flash(b=2, s=256, h=12, d=64, masked=True) is False


def test_all_mode_degrades_to_host_input_when_tpu_down(monkeypatch, capsys):
    """A downed TPU must not empty the round artifact: --model all falls back
    to the host-only input-pipeline workload with the outage recorded."""
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")  # contain the env mutation
    monkeypatch.setattr(bench, "probe_backend",
                        lambda **kw: (False, ["probe 1/1: hung (killed)"]))
    monkeypatch.setattr(bench, "bench_input",
                        lambda iters, **kw: {"host_images_per_sec": 42.0})
    rc = bench.main(["--model", "all"])
    assert rc == 0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["metric"] == "input_pipeline_host_images_per_sec"
    assert rec["value"] == 42.0
    assert any("device workloads skipped" in e for e in rec["extra"]["errors"])


def test_llama_7b_oom_returns_structured_evidence(monkeypatch):
    """VERDICT r2 next-#3: a resource-exhaustion failure of the 7B attempt
    must come back as the budget-bearing evidence record; any other error
    must still raise (a code bug cannot masquerade as memory evidence)."""
    import pytest

    def oom(*a, **k):
        raise RuntimeError("XLA:TPU RESOURCE_EXHAUSTED: Ran out of memory "
                           "in hbm. Used 17.1G of 15.48G")

    monkeypatch.setattr(bench, "_train_setup", oom)
    rec = bench.bench_llama(2, variant="7b")
    assert rec["error"].startswith("RuntimeError")
    assert "memory_report" in rec and "memory_v4_32" in rec
    # the v4-32 record must carry the CONTRACT shape, not the clamped
    # single-chip attempt shape
    assert rec["memory_v4_32"]["mesh"] == {"data": 2, "fsdp": 8}
    assert "fits 32 GiB/chip: True" in " ".join(rec["memory_v4_32"]["notes"])
    # b clamps to 1 always; seq caps at 2048 (r4: relaxed from 1024 once
    # the executed-7B evidence existed at s=1024)
    assert rec["batch_size"] == 1 and rec["seq_len"] == 2048

    def bug(*a, **k):
        raise TypeError("not a memory problem")

    monkeypatch.setattr(bench, "_train_setup", bug)
    with pytest.raises(TypeError):
        bench.bench_llama(2, variant="7b")


def test_chip_queue_items_are_unique_and_parse():
    """VERDICT r3 next-#1: the one-command chip queue. A typo'd argv or a
    duplicate item name would burn a real chip window — validate every
    entry against bench's own CLI parser, off-chip."""
    import bench

    names = [n for n, _, _ in bench.CHIP_QUEUE]
    assert len(names) == len(set(names))
    ap = bench.build_parser()
    for name, argv, timeout_s in bench.CHIP_QUEUE:
        args = ap.parse_args(argv)  # SystemExit on an invalid flag
        assert timeout_s >= 300, f"{name}: timeout too tight for axon compiles"
    # r5 priority order (VERDICT r4 next-#1): the unrecorded headline
    # claims — 7B executed steps, then long-context — must run first so a
    # short window yields the highest-value artifacts before anything else
    assert names[0] == "llama_7b" and names[1] == "llama_7b_s2048"
    assert names[2] == "llama_longctx_16k"


def test_chip_queue_aborts_when_backend_never_up(monkeypatch, tmp_path):
    """A dead tunnel must not burn the per-item timeouts: the queue probes
    first, records the failure, and exits 0 with a parseable line."""
    import bench

    monkeypatch.setattr(bench, "probe_backend",
                        lambda **kw: (False, ["probe 1/1: hung (killed)"]))
    out = tmp_path / "q.jsonl"
    rc = bench.run_chip_queue(str(out))
    assert rc == 0
    recs = [json.loads(l) for l in out.read_text().splitlines()]
    assert recs[0]["item"] == "probe" and recs[0]["ok"] is False


def test_chip_queue_appends_as_items_complete(monkeypatch, tmp_path):
    """Each item's record must land in the file AS IT COMPLETES (a killed
    window keeps everything already measured), and an item failure triggers
    a re-probe that can stop the queue."""
    import subprocess as sp

    import bench

    probes = iter([(True, []), (False, ["gone"])])
    monkeypatch.setattr(bench, "probe_backend",
                        lambda **kw: next(probes))

    calls = []

    def fake_run(cmd, **kw):
        calls.append(cmd)
        class R:
            returncode = 0
            stderr = ""
            stdout = ('{"metric": "m", "value": 1.0}\n' if len(calls) == 1
                      else "boom not json\n")
        return R()

    monkeypatch.setattr(sp, "run", fake_run)
    out = tmp_path / "q.jsonl"
    # subset runs in CHIP_QUEUE's own priority order: memval, then
    # kernels_mosaic, then all_model (r5 order — headline items first)
    bench.run_chip_queue(str(out), items=["all_model", "kernels_mosaic",
                                          "memval"])
    recs = [json.loads(l) for l in out.read_text().splitlines()]
    items = [r["item"] for r in recs]
    # probe ok, first item ok, second item non-JSON -> re-probe fails ->
    # queue stops; the last item never runs
    assert items[0] == "probe" and "memval" in items
    assert "kernels_mosaic" in items and "all_model" not in items
    assert recs[-1]["item"] == "probe_recheck" and recs[-1]["skipped_rest"]


def test_bench_kernels_interpret_smoke():
    """--model kernels off-chip: both Pallas kernels parity-check against
    their XLA reference chains in interpret mode (timing skipped — only the
    compiled path's numbers mean anything)."""
    rec = bench.bench_kernels()
    assert rec["mode"] == "interpret"
    assert rec["conv_bn"]["compile"] == "ok", rec["conv_bn"]
    assert rec["conv_bn"]["grad_max_rel_err"] < 0.02
    assert rec["conv_bn"]["fused_ms"] is None
    assert rec["scatter_rows"]["compile"] == "ok", rec["scatter_rows"]
    assert rec["scatter_rows"]["max_abs_err"] == 0.0
    # ulysses CP smoke (VERDICT r4 weak-#7): off-chip the local attention
    # is the einsum fallback vs interpret-mode flash — parity bounds the
    # whole all-to-all + local-attention chain
    assert rec["ulysses_smoke"]["compile"] == "ok", rec["ulysses_smoke"]
    assert rec["ulysses_smoke"]["finite"]
    assert rec["ulysses_smoke"]["max_abs_err_vs_direct_flash"] < 0.05


def test_is_good_record_excludes_failure_shapes():
    """The shared queue/watcher success rule (r5 review: bench.py exits 0
    with a bench_failed line on runner exceptions, which the watcher was
    counting as done — evidence silently never collected)."""
    good = {"metric": "llama_lora_tokens_per_sec_per_chip", "value": 0.0}
    assert bench.is_good_record(0, good)           # 7B OOM evidence counts
    assert not bench.is_good_record(1, good)       # nonzero rc
    assert not bench.is_good_record(0, {"raw_tail": "boom"})   # no metric
    assert not bench.is_good_record(0, "not a dict")
    assert not bench.is_good_record(
        0, {"metric": "bench_failed", "value": 0.0})
    assert not bench.is_good_record(
        0, {"metric": "backend_unavailable", "value": 0.0})
    assert not bench.is_good_record(
        0, {"metric": "pallas_kernels_compiled", "value": 0.0})
    assert bench.is_good_record(
        0, {"metric": "pallas_kernels_compiled", "value": 3.0})


def test_chip_queue_rejects_unknown_item_names(tmp_path):
    import pytest

    with pytest.raises(SystemExit, match="unknown --queue-items"):
        bench.run_chip_queue(str(tmp_path / "q.jsonl"), items=["memvall"])


def test_llama_09b_cfg_long_context_flip():
    """s>=16384 must flip the 0.9b bench config to full remat + fused CE —
    the pair that made s=16384 fit a single 16 GiB chip on the r4 window
    (9677 tok/s/chip); below that the measured-fastest 'dots' policy stays."""
    import bench

    short = bench._llama_09b_cfg(seq=2048)
    assert short.remat_policy == "dots" and not short.fused_head_loss
    long = bench._llama_09b_cfg(seq=16384)
    assert long.remat_policy is None and long.fused_head_loss
    # explicit --fused-head-loss still wins at short seq
    assert bench._llama_09b_cfg(seq=2048, fused_head=True).fused_head_loss


def test_bench_llama_decode_record(monkeypatch):
    """--decode mode: the KV-cache generation bench produces its record
    shape off-chip at a tiny geometry (the 0.9b default is monkeypatched —
    128 sequential 0.9b decode steps on CPU would take minutes)."""
    from distributeddeeplearningspark_tpu.models import LlamaConfig

    def tiny_cfg(*, seq=2048, fused_head=False, moe_experts=0, moe_group=0,
                 base_quant=None):
        return LlamaConfig(
            vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
            num_kv_heads=2, intermediate_size=128, max_position=seq,
            lora_rank=4, dtype="float32", remat=False,
            base_quant=base_quant)

    monkeypatch.setattr(bench, "_llama_09b_cfg", tiny_cfg)
    rec = bench.bench_llama_decode(5, batch_size=2, prompt_len=8,
                                   new_tokens=8)
    assert rec["decode_tokens_per_sec_per_chip"] > 0
    assert rec["ms_per_decode_step"] > 0
    # prefill subtracted: a decode step must be cheaper than the whole
    # prefill+decode call
    assert rec["ms_per_decode_step"] * 7 < rec["prefill_plus_first_token_ms"] * 8
    assert rec["batch_size"] == 2 and rec["new_tokens"] == 8
    assert rec["base_quant"] is None
    # first-record discipline (VERDICT r5 weak-#5): the compile-bearing
    # first device call of each shape is timed apart, discarded from the
    # averages, and recorded; a clean run passes the wall-clock
    # cross-check (decode steps are the cheapest tokens, so the
    # subtraction-derived step must not exceed full_wall/new_tokens +10%)
    fc = rec["first_call_discarded_ms"]
    assert fc["full"] > 0 and fc["prefill"] > 0
    if "timing_suspect" not in rec:
        wall_divide_ms = (rec["end_to_end_tokens_per_sec"] and
                          rec["batch_size"] * 1e3
                          / rec["end_to_end_tokens_per_sec"])
        assert rec["ms_per_decode_step"] <= wall_divide_ms * 1.10
    # int8 composition: same record shape, quantized base leaves
    rec8 = bench.bench_llama_decode(5, batch_size=2, prompt_len=8,
                                    new_tokens=8, base_quant="int8")
    assert rec8["base_quant"] == "int8"
    # no silently-ignored flags with --decode (the house guard pattern)
    import pytest

    with pytest.raises(SystemExit):
        bench.main(["--model", "llama", "--decode", "--seq", "8192"])
    with pytest.raises(SystemExit):
        bench.main(["--model", "llama", "--decode", "--variant", "7b"])
