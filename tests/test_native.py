"""Native (C++) host data-plane kernels: build, parity with numpy, wiring.

The native library is the rebuild's host-side native layer (SURVEY.md §1 L2:
the reference's native layer is CUDA/NCCL; ours is XLA on-device + these
kernels on-host). Parity tests pin native == numpy so either path is safe.
"""

import numpy as np
import pytest

from distributeddeeplearningspark_tpu.data import vision
from distributeddeeplearningspark_tpu.utils import native


def test_native_builds_and_loads():
    # g++ is baked into the image; the kernels must actually build here.
    assert native.available(), "native kernels failed to build/load"


def _rand_u8(shape, seed=0):
    return np.random.default_rng(seed).integers(0, 256, shape).astype(np.uint8)


def test_crop_flip_normalize_parity():
    imgs = _rand_u8((4, 12, 16, 3))
    ys = np.array([0, 1, 2, 3], np.int32)
    xs = np.array([3, 2, 1, 0], np.int32)
    flips = np.array([0, 1, 0, 1], np.uint8)
    mean, std = vision.IMAGENET_MEAN, vision.IMAGENET_STD
    got = native.crop_flip_normalize_batch(imgs, ys, xs, flips, (8, 10), mean, std)
    assert got.shape == (4, 8, 10, 3) and got.dtype == np.float32
    for i in range(4):
        ref = imgs[i, ys[i]:ys[i] + 8, xs[i]:xs[i] + 10]
        if flips[i]:
            ref = ref[:, ::-1]
        ref = (ref.astype(np.float32) / 255.0 - mean) / std
        np.testing.assert_allclose(got[i], ref, atol=1e-6)


def test_normalize_u8_batch_parity():
    imgs = _rand_u8((3, 6, 7, 3), seed=1)
    got = native.normalize_u8_batch(imgs, vision.IMAGENET_MEAN, vision.IMAGENET_STD)
    ref = (imgs.astype(np.float32) / 255.0 - vision.IMAGENET_MEAN) / vision.IMAGENET_STD
    np.testing.assert_allclose(got, ref, atol=1e-6)


def test_resize_bilinear_parity():
    img = np.random.default_rng(2).normal(0, 1, (17, 23, 3)).astype(np.float32)
    got = native.resize_bilinear(img, (8, 9))
    ref = vision.resize_bilinear(img, (8, 9))
    np.testing.assert_allclose(got, ref, atol=1e-5, rtol=1e-5)
    # upsampling too
    got_up = native.resize_bilinear(img, (30, 40))
    ref_up = vision.resize_bilinear(img, (30, 40))
    np.testing.assert_allclose(got_up, ref_up, atol=1e-5, rtol=1e-5)


def test_sum_into_parity():
    a = np.random.default_rng(3).normal(0, 1, (1 << 17,)).astype(np.float32)
    b = np.random.default_rng(4).normal(0, 1, (1 << 17,)).astype(np.float32)
    want = a + b
    got = native.sum_into(a.copy(), b)
    np.testing.assert_allclose(got, want, atol=1e-6)


def test_numpy_fallback_matches_native(monkeypatch):
    imgs = _rand_u8((2, 8, 8, 3), seed=5)
    ys = xs = np.zeros(2, np.int32)
    flips = np.array([1, 0], np.uint8)
    args = (imgs, ys, xs, flips, (8, 8), vision.IMAGENET_MEAN, vision.IMAGENET_STD)
    with_native = native.crop_flip_normalize_batch(*args)
    monkeypatch.setattr(native, "_LIB", None)
    monkeypatch.setattr(native, "_TRIED", True)
    assert not native.available()
    without = native.crop_flip_normalize_batch(*args)
    np.testing.assert_allclose(with_native, without, atol=1e-6)


def test_train_transform_uint8_standardizes():
    """uint8 inputs must come out unit-scaled AND standardized — including
    through the crop path (regression: crop path skipped /255)."""
    tf = vision.train_transform(size=8, seed=0)
    same = tf({"image": _rand_u8((8, 8, 3), seed=6), "label": np.int32(0)})["image"]
    cropped = tf({"image": _rand_u8((14, 14, 3), seed=7), "label": np.int32(0)})["image"]
    for out in (same, cropped):
        assert out.shape == (8, 8, 3) and out.dtype == np.float32
        # standardized pixels live in roughly [-3, 3]; unnormalized would be ~255
        assert np.abs(out).max() < 5.0


def test_eval_transform_uint8_standardizes():
    tf = vision.eval_transform(size=8)
    out = tf({"image": _rand_u8((12, 16, 3), seed=8)})["image"]
    assert out.shape == (8, 8, 3) and np.abs(out).max() < 5.0
    out_same = tf({"image": _rand_u8((8, 8, 3), seed=9)})["image"]
    assert np.abs(out_same).max() < 5.0


def test_crop_origin_bounds_checked():
    """ADVICE r1: invalid crop origins must raise, not heap-overread in C++."""
    imgs = _rand_u8((2, 12, 16, 3))
    flips = np.zeros(2, np.uint8)
    mean, std = vision.IMAGENET_MEAN, vision.IMAGENET_STD
    # y origin too large: 5 + 8 > 12
    with pytest.raises(ValueError, match="out of bounds"):
        native.crop_flip_normalize_batch(
            imgs, np.array([0, 5], np.int32), np.zeros(2, np.int32), flips,
            (8, 10), mean, std)
    # negative x origin
    with pytest.raises(ValueError, match="out of bounds"):
        native.crop_flip_normalize_batch(
            imgs, np.zeros(2, np.int32), np.array([-1, 0], np.int32), flips,
            (8, 10), mean, std)
    # crop larger than image
    with pytest.raises(ValueError, match="exceeds"):
        native.crop_flip_normalize_batch(
            imgs, np.zeros(2, np.int32), np.zeros(2, np.int32), flips,
            (13, 10), mean, std)


def test_rrc_flip_normalize_parity():
    """Fused crop→resize→flip→normalize == the numpy chain (crop the /255
    float frame, resize_bilinear, flip, standardize) to fp tolerance —
    up- and down-scaling crops, both flip states."""
    img = _rand_u8((37, 53, 3), seed=11)
    mean, std = vision.IMAGENET_MEAN, vision.IMAGENET_STD
    for region, flip, size in [
        ((3, 5, 20, 30), False, (16, 16)),   # downscale
        ((0, 0, 9, 7), True, (24, 24)),      # upscale
        ((10, 10, 16, 16), True, (16, 16)),  # identity resize
    ]:
        got = native.rrc_flip_normalize(img, region, flip, size, mean, std)
        assert got is not None and got.dtype == np.float32
        y, x, ch, cw = region
        ref = vision.resize_bilinear(
            img[y:y + ch, x:x + cw].astype(np.float32) / 255.0, size)
        if flip:
            ref = ref[:, ::-1]
        ref = (ref - mean) / std
        np.testing.assert_allclose(got, ref, atol=1e-5, rtol=1e-5)


def test_rrc_region_bounds_checked():
    img = _rand_u8((16, 16, 3), seed=12)
    mean, std = vision.IMAGENET_MEAN, vision.IMAGENET_STD
    for bad in [(-1, 0, 8, 8), (0, 0, 17, 8), (10, 10, 8, 8), (0, 0, 0, 8)]:
        with pytest.raises(ValueError, match="out of bounds"):
            native.rrc_flip_normalize(img, bad, False, (8, 8), mean, std)


def test_train_transform_native_matches_numpy(monkeypatch):
    """The fused-native and numpy train paths must pick the SAME crop (same
    rng stream) and agree to fp tolerance — scheduling/native availability
    cannot change the augmented output."""
    ex = {"image": _rand_u8((40, 48, 3), seed=13), "label": np.int32(1)}
    tf = vision.train_transform(size=16, seed=3)
    with_native = tf(dict(ex))["image"]
    monkeypatch.setattr(native, "_LIB", None)
    monkeypatch.setattr(native, "_TRIED", True)
    without = tf(dict(ex))["image"]
    np.testing.assert_allclose(with_native, without, atol=1e-4, rtol=1e-4)
