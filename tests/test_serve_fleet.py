"""Serving fleet subsystem (ISSUE 6): paged KV arena first-fit/refcount
discipline, prefix-cache sharing semantics, paged-vs-dense token identity,
router placement/tenant budgets/failover, rolling hot-reload with zero
dropped requests, the dlstatus --fleet-serve rollup, and (slow tier) the
real multi-process replica fleet."""

import threading
import time

import numpy as np
import pytest

from distributeddeeplearningspark_tpu.serve import (
    ContinuousGenerator,
    InferenceEngine,
    LocalReplica,
    NoReplicaError,
    OverloadedError,
    PagedKVArena,
    PrefixCache,
    Router,
)

# -- paged KV arena -----------------------------------------------------------


class TestPagedKVArena:
    def test_first_fit_reuses_lowest_freed_page(self):
        """Out-of-order release + first-fit: the hole opened by freeing a
        LOW page is refilled by the very next allocation (the workers.py
        shm discipline, page-granular)."""
        a = PagedKVArena(num_pages=8, page_size=4)
        p1 = a.alloc(3)
        p2 = a.alloc(2)
        assert p1 == [1, 2, 3] and p2 == [4, 5]       # page 0 reserved
        a.release([2])                                 # hole mid-pool
        assert a.alloc(2) == [2, 6]                    # hole refilled first
        assert a.pages_used == 6

    def test_refcounts_share_and_free(self):
        a = PagedKVArena(num_pages=6, page_size=4)
        pages = a.alloc(2)
        a.retain(pages)                                # a second reader
        assert a.release(pages) == 0                   # still referenced
        assert a.pages_used == 2
        assert a.release(pages) == 2                   # last ref frees
        assert a.pages_used == 0

    def test_exhaustion_returns_none_and_counts(self):
        a = PagedKVArena(num_pages=4, page_size=4)
        assert a.alloc(3) is not None
        assert a.alloc(1) is None
        assert a.alloc_failures == 1
        assert a.stats()["kv_page_occupancy"] == 1.0

    def test_misuse_guards(self):
        a = PagedKVArena(num_pages=4, page_size=4)
        with pytest.raises(ValueError):
            a.release([1])
        with pytest.raises(ValueError):
            a.retain([1])
        with pytest.raises(ValueError):
            PagedKVArena(num_pages=1, page_size=4)


class TestPrefixCache:
    def _prompt(self, n, seed=0):
        return np.random.default_rng(seed).integers(
            0, 100, (n,)).astype(np.int32)

    def test_register_all_depths_then_hit_at_divergence(self):
        """Two prompts share 8 of 12 tokens (page 4): the second must hit
        at the SHARED depth (2 pages), not the registrant's full depth."""
        a = PagedKVArena(num_pages=16, page_size=4)
        c = PrefixCache(a)
        p1 = self._prompt(12, seed=1)
        pages = a.alloc(3)
        assert c.register(p1, pages, version=0) == 3   # depths 1..3
        p2 = np.concatenate([p1[:8], self._prompt(6, seed=2)])
        n, shared = c.lookup(p2, version=0)
        assert n == 2 and shared == pages[:2]
        c.record(n * 4)
        assert c.hits == 1 and c.tokens_saved == 8
        # full-prompt lookup caps at len-1: an identical prompt reuses at
        # most 2 pages (one real token must remain to prefill)
        n3, _ = c.lookup(p1, version=0)
        assert n3 == 2

    def test_version_mismatch_misses(self):
        a = PagedKVArena(num_pages=16, page_size=4)
        c = PrefixCache(a)
        p = self._prompt(12)
        c.register(p, a.alloc(2), version=0)
        n, _ = c.lookup(np.concatenate([p, p]), version=1)
        assert n == 0

    def test_flush_and_lru_eviction_free_pages(self):
        a = PagedKVArena(num_pages=16, page_size=4)
        c = PrefixCache(a)
        p1, p2 = self._prompt(8, seed=1), self._prompt(8, seed=2)
        g1, g2 = a.alloc(2), a.alloc(2)
        c.register(p1, g1, version=0)
        c.register(p2, g2, version=0)
        a.release(g1)
        a.release(g2)                                  # cache holds the refs
        assert a.pages_used == 4
        n, got = c.lookup(np.concatenate([p2, p2]), version=0)  # p2 now MRU
        assert n == 2
        c.evict_until(a.pages_free + 2)                # evicts LRU = p1's
        # only p2's 2 distinct pages survive: its cache entries and the
        # lookup's retain share the SAME pages (refcounts, not copies)
        assert a.pages_used == 2
        a.release(got)
        c.flush()
        assert a.pages_used == 0


# -- paged decode: token identity + prefix reuse ------------------------------


@pytest.fixture(scope="module")
def nano_llama_fleet():
    import jax
    import jax.numpy as jnp

    from distributeddeeplearningspark_tpu.models import (
        LlamaConfig,
        LlamaForCausalLM,
    )

    cfg = LlamaConfig(vocab_size=128, hidden_size=64, num_layers=2,
                      num_heads=4, num_kv_heads=2, intermediate_size=128,
                      max_position=64, dtype=jnp.float32)
    model = LlamaForCausalLM(cfg)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, 128, (n,)).astype(np.int32)
               for n in (5, 7, 6, 4)]
    params = model.init(jax.random.PRNGKey(0),
                        {"input_ids": prompts[0][None]},
                        train=False)["params"]
    return cfg, params, prompts, rng


def test_paged_arena_token_identical_to_fixed_slot_pool(nano_llama_fleet):
    """The acceptance pin: the SAME requests through the dense PR 4 pool
    and the paged arena produce identical tokens — paging is a memory
    discipline, not a numerics change (gathers are exact; garbage beyond a
    row's length is masked to exactly-zero weight either way)."""
    cfg, params, prompts, _ = nano_llama_fleet
    dense = ContinuousGenerator(cfg, params, slots=2, max_cache_len=32,
                                prompt_buckets=(8, 16))
    with dense:
        ref = [dense.generate(p, 6) for p in prompts]
    paged = ContinuousGenerator(cfg, params, slots=2, max_cache_len=32,
                                prompt_buckets=(8, 16), page_size=8)
    with paged:
        futs = [paged.submit(p, 6) for p in prompts]
        out = [f.result(300) for f in futs]
    for r, o in zip(ref, out):
        np.testing.assert_array_equal(r, o)
    st = paged.stats()
    assert st["completed"] == 4
    # every slot's pages reclaimed (prompts < page_size register nothing)
    assert st["kv_pages_used"] == 0 and st["kv_page_allocs"] > 0


def test_prefix_cache_reuses_pages_and_matches_dense(nano_llama_fleet):
    """Prefix-heavy workload (shared 16-token system prompt): later
    requests hit the cache, skip re-prefilling the shared pages, and still
    produce exactly the dense pool's tokens. The ≥2× prefill-savings
    acceptance: ≥half the prompt tokens are served from cached pages."""
    cfg, params, _, rng = nano_llama_fleet
    system = rng.integers(0, 128, (16,)).astype(np.int32)
    prompts = [np.concatenate([system,
                               rng.integers(0, 128, (4,)).astype(np.int32)])
               for _ in range(4)]
    paged = ContinuousGenerator(cfg, params, slots=2, max_cache_len=64,
                                prompt_buckets=(8, 16, 24, 32), page_size=8)
    with paged:
        out = [paged.generate(p, 5) for p in prompts]
    dense = ContinuousGenerator(cfg, params, slots=2, max_cache_len=64,
                                prompt_buckets=(8, 16, 24, 32))
    with dense:
        ref = [dense.generate(p, 5) for p in prompts]
    for a, b in zip(out, ref):
        np.testing.assert_array_equal(a, b)
    st = paged.stats()
    assert st["prefix_hits"] == 3 and st["prefix_misses"] == 1
    # request 1 prefills all 20 prompt tokens; 2..4 reuse 16 each
    assert st["prefix_tokens_saved"] == 48
    total_prompt = sum(p.size for p in prompts)
    assert st["prefix_tokens_saved"] >= total_prompt / 2   # ≥2× savings
    assert st["prefix_entries"] == 2                       # depths 1..2


def test_paged_admission_defers_under_page_pressure(nano_llama_fleet):
    """An arena sized for ~one long sequence: concurrent requests admit
    one at a time (deferred, not failed), every future still completes,
    and the pool is fully reclaimed afterwards."""
    cfg, params, _, rng = nano_llama_fleet
    prompts = [rng.integers(0, 128, (9,)).astype(np.int32)
               for _ in range(3)]
    gen = ContinuousGenerator(cfg, params, slots=3, max_cache_len=32,
                              prompt_buckets=(16,), page_size=8,
                              kv_pages=6, prefix_cache=False)
    with gen:
        futs = [gen.submit(p, 12) for p in prompts]
        res = [f.result(300) for f in futs]
    assert all(r.shape == (12,) for r in res)
    st = gen.stats()
    assert st["completed"] == 3
    assert st["deferred"] >= 1            # pressure actually happened
    assert st["kv_pages_used"] == 0       # all reclaimed


def test_swap_params_flushes_prefix_cache(nano_llama_fleet):
    """A hot-reload makes cached prefix K/V stale: the flush must happen
    before the next admission can hit it."""
    import jax

    cfg, params, _, rng = nano_llama_fleet
    system = rng.integers(0, 128, (16,)).astype(np.int32)
    mk = lambda: np.concatenate(  # noqa: E731
        [system, rng.integers(0, 128, (4,)).astype(np.int32)])
    gen = ContinuousGenerator(cfg, params, slots=2, max_cache_len=64,
                              prompt_buckets=(8, 16, 24, 32), page_size=8)
    with gen:
        gen.generate(mk(), 3)
        gen.generate(mk(), 3)
        assert gen.stats()["prefix_hits"] == 1
        gen.swap_params(jax.tree.map(lambda x: x * 1.01, params))
        gen.generate(mk(), 3)             # post-swap: stale entries flushed
        st = gen.stats()
    assert st["prefix_hits"] == 1 and st["prefix_misses"] == 2
    assert st["reloads"] == 1


# -- router -------------------------------------------------------------------


class _FakeReplica:
    """Handle double with a controllable future queue."""

    def __init__(self, name):
        self.name = name
        self.alive = True
        self.submitted = []

    def submit(self, payload, op="infer"):
        from concurrent.futures import Future

        fut = Future()
        self.submitted.append((payload, op, fut))
        return fut


def test_router_places_by_queue_depth_and_p99():
    """Dispatch minimizes (outstanding+1)×p99: a slow replica attracts
    less load the moment its completions come back slow."""
    fast, slow = _FakeReplica("fast"), _FakeReplica("slow")
    r = Router([fast, slow], p99_window=8)
    # seed latency history: resolve one request from each at skewed speed
    for rep, lat in ((fast, 0.001), (slow, 0.1)):
        f = r.submit({"x": 1})
        payload, op, inner = rep.submitted[-1] if rep.submitted else (None,) * 3
        # resolve whichever replica got it; force history by direct append
    # deterministic: install latency history directly
    r._lat["fast"].extend([0.001] * 8)
    r._lat["slow"].extend([0.100] * 8)
    for _ in range(10):
        r.submit({"x": 1})
    # all outstanding; fast should have absorbed ~10× slow's share
    assert len(fast.submitted) > len(slow.submitted)
    st = r.stats()
    assert st["dispatched"] == 12
    assert st["replicas"]["fast"]["recent_p99_ms"] == 1.0


def test_router_tenant_budget_sheds_typed_with_telemetry(tmp_path):
    """Per-tenant budgets: the over-budget tenant sheds with the typed
    error AND a telemetry request event naming it; other tenants admit."""
    from distributeddeeplearningspark_tpu import telemetry

    rep = _FakeReplica("r0")
    r = Router([rep], default_tenant_budget=2, workdir=str(tmp_path))
    r.submit({"x": 1}, tenant="greedy")
    r.submit({"x": 2}, tenant="greedy")
    with pytest.raises(OverloadedError):
        r.submit({"x": 3}, tenant="greedy")
    r.submit({"x": 4}, tenant="polite")    # different tenant: admitted
    assert r.stats()["shed_tenant"] == 1
    r._tele.close()
    evs = [e for e in telemetry.read_events(tmp_path)
           if e.get("kind") == "request"]
    assert len(evs) == 1
    assert evs[0]["outcome"] == "shed" and evs[0]["tenant"] == "greedy"
    assert evs[0]["process"] == "router"

    # budget releases when requests complete
    for payload, op, fut in rep.submitted:
        fut.set_result({"ok": True})
    deadline = time.monotonic() + 5
    while r.stats()["tenants"].get("greedy") and time.monotonic() < deadline:
        time.sleep(0.005)
    r.submit({"x": 5}, tenant="greedy")    # admitted again


def test_router_fails_over_on_replica_death():
    """A replica dying mid-request re-dispatches to a survivor; the dead
    one stops being a candidate."""
    from distributeddeeplearningspark_tpu.serve.router import ReplicaDiedError

    a, b = _FakeReplica("a"), _FakeReplica("b")
    r = Router([a, b])
    futs = [r.submit({"x": i}) for i in range(4)]
    victim, survivor = (a, b) if a.submitted else (b, a)
    victim.alive = False
    for payload, op, fut in list(victim.submitted):
        fut.set_exception(ReplicaDiedError("gone"))
    # every re-dispatched request landed on the survivor
    for payload, op, fut in list(survivor.submitted):
        if not fut.done():
            fut.set_result({"y": 0})
    for f in futs:
        assert f.result(10) == {"y": 0} or f.result(10)["ok"]
    assert r.stats()["failovers"] >= 1
    assert len(survivor.submitted) == 4


def test_router_drain_guard_and_no_replica_error():
    a, b = _FakeReplica("a"), _FakeReplica("b")
    r = Router([a, b])
    r.drain("a")
    with pytest.raises(RuntimeError, match="zero serving"):
        r.drain("b")
    r.undrain("a")
    a.alive = b.alive = False
    with pytest.raises(NoReplicaError):
        r.submit({"x": 1})


# -- rolling reload (in-process fleet) ----------------------------------------


def test_rolling_reload_zero_dropped_in_process():
    """Two engine replicas under concurrent load; a rolling drain→swap→
    undrain across both completes with every request answered and both
    replicas on new params — the zero-global-downtime contract, minus the
    process boundary (the slow tier + CI smoke cover that)."""
    import jax.numpy as jnp

    def fwd(params, batch):
        return {"y": batch["x"] * params["w"]}

    engines = [InferenceEngine(fwd, {"w": jnp.float32(1.0)}, max_batch=4,
                               max_wait_ms=1.0, max_queue=4096,
                               name=f"e{i}").start()
               for i in range(2)]
    reps = [LocalReplica(f"r{i}", e,
                         reload_fn=lambda n: {"w": jnp.float32(100.0 + n)})
            for i, e in enumerate(engines)]
    router = Router(reps)
    stop = threading.Event()
    futs, lock = [], threading.Lock()

    def client():
        while not stop.is_set():
            try:
                f = router.submit({"example": {"x": np.float32(1.0)}})
            except OverloadedError:
                continue
            with lock:
                futs.append(f)
            time.sleep(0.001)

    threads = [threading.Thread(target=client) for _ in range(4)]
    for t in threads:
        t.start()
    try:
        deadline = time.monotonic() + 10
        while router.stats()["dispatched"] < 8 \
                and time.monotonic() < deadline:
            time.sleep(0.002)
        # the rolling reload: one replica at a time
        for rep in reps:
            router.drain(rep.name)
            while router.inflight(rep.name) > 0:
                time.sleep(0.001)
            rep.call("reload")
            router.undrain(rep.name)
    finally:
        stop.set()
        for t in threads:
            t.join()
    res = [float(f.result(30)["y"]) for f in futs]
    assert len(res) == len(futs)                      # zero dropped
    assert set(res) <= {1.0, 101.0}                   # old or new, never torn
    assert 101.0 in res                               # reload actually landed
    for e in engines:
        assert e.params_version == 1
        e.stop()


# -- peer warm-up (in-process fleet) ------------------------------------------


def test_local_replica_peer_warmup_export_import():
    """A relaunched replica warms its weights from a serving peer instead
    of disk: export (numpy tree + blake2b digest) → import (re-hash,
    verify, swap) — the newcomer then serves the donor's exact params at
    the donor's version."""
    import jax.numpy as jnp

    def fwd(params, batch):
        return {"y": batch["x"] * params["w"]}

    donor_e = InferenceEngine(fwd, {"w": jnp.float32(7.0)}, max_batch=4,
                              max_wait_ms=1.0, max_queue=64,
                              name="donor").start()
    target_e = InferenceEngine(fwd, {"w": jnp.float32(1.0)}, max_batch=4,
                               max_wait_ms=1.0, max_queue=64,
                               name="target").start()
    try:
        donor_e.swap_params({"w": jnp.float32(7.0)}, version=3)
        donor = LocalReplica("donor", donor_e)
        target = LocalReplica("target", target_e)
        exported = donor.call("export_params")
        assert exported["version"] == 3 and exported["digest"]
        rec = target.call("import_params", params=exported["params"],
                          version=exported["version"],
                          digest=exported["digest"])
        assert rec["digest"] == exported["digest"]
        assert rec["params_version"] == 3
        out = target.call("infer",
                          example={"x": np.float32(2.0)}, timeout=60.0)
        assert float(out["y"]) == 14.0  # the donor's weights, not seed 1.0

        # a torn transfer is refused, the replica keeps serving its params
        with pytest.raises(ValueError, match="digest mismatch"):
            target.call("import_params", params={"w": np.float32(9.0)},
                        digest="0" * 32)
        assert target.engine.params_version == 3
    finally:
        donor_e.stop()
        target_e.stop()


# -- dlstatus --fleet-serve ----------------------------------------------------


def test_dlstatus_fleet_serve_rollup(tmp_path, capsys):
    """Synthetic two-replica stream (+ router sheds) through the
    --fleet-serve report: per-replica p99/shed rate/KV occupancy/prefix
    hit rate, and fleet totals."""
    import json

    from distributeddeeplearningspark_tpu import status, telemetry

    for proc, base in (("p0", 0.010), ("p1", 0.020)):
        w = telemetry.EventWriter(tmp_path, process=proc, host=None,
                                  clock=lambda: 1.0)
        w.emit_many("request", [
            dict(engine="tinyllama", id=i, outcome="ok",
                 latency_s=base * (1 + i), queue_wait_s=0.001, batch_size=2)
            for i in range(5)])
        w.emit("request", engine="tinyllama", id=99, outcome="shed",
               queue_depth=3)
        w.emit("serve", engine="tinyllama", kv_pages_total=12,
               kv_pages_used=6, kv_page_occupancy=0.5, prefix_hits=3,
               prefix_misses=1, prefix_hit_rate=0.75,
               prefix_tokens_saved=48, active=2)
        w.close()
    wr = telemetry.EventWriter(tmp_path, process="router", host=None,
                               clock=lambda: 1.0)
    wr.emit("request", engine="router", outcome="shed", tenant="greedy")
    wr.close()

    rep = status.report(str(tmp_path), fleet_serve=True)
    fs = rep["fleet_serve"]
    assert [r["process"] for r in fs["replicas"]] == ["p0", "p1", "router"]
    p0 = fs["replicas"][0]
    assert p0["ok"] == 5 and p0["shed"] == 1
    assert p0["shed_rate"] == pytest.approx(1 / 6)
    assert p0["latency_p99_s"] == pytest.approx(0.050)
    assert p0["kv_page_occupancy"] == 0.5
    assert p0["prefix_hit_rate"] == 0.75
    t = fs["totals"]
    assert t["requests"] == 13 and t["ok"] == 10 and t["shed"] == 3
    assert t["prefix_hits"] == 6 and t["prefix_hit_rate"] == 0.75
    assert t["prefix_tokens_saved"] == 96
    assert t["kv_page_occupancy_max"] == 0.5

    assert status.main([str(tmp_path), "--fleet-serve"]) == 0
    out = capsys.readouterr().out
    assert "serving fleet: 3 process(es)" in out
    assert "prefix hit rate 75%" in out
    # no serve traffic at all → key is None, render skips the section
    empty = tmp_path / "empty"
    w = telemetry.EventWriter(empty, process="p0", clock=lambda: 1.0)
    w.heartbeat(step=0)
    w.close()
    assert status.report(str(empty), fleet_serve=True)["fleet_serve"] is None
    import json as _json  # noqa: F401 — keep the --json path covered too
    assert status.main([str(tmp_path), "--fleet-serve", "--json"]) == 0
    rec = json.loads(capsys.readouterr().out)
    assert rec["fleet_serve"]["totals"]["prefix_tokens_saved"] == 96


# -- the real thing: replica processes (slow tier) ----------------------------


@pytest.mark.slow
def test_fleet_processes_end_to_end(tmp_path):
    """2 lenet replica PROCESSES (gang env contract): infer through the
    router, per-replica telemetry in ONE workdir, a rolling reload with
    zero dropped requests, and a kill → route-around → restart drill."""
    from distributeddeeplearningspark_tpu import status
    from distributeddeeplearningspark_tpu.serve.fleet import ServingFleet

    rng = np.random.default_rng(0)

    def payload(i):
        return {"example": {
            "image": rng.normal(0, 1, (28, 28, 1)).astype(np.float32)}}

    spec = {"model": "lenet", "seed": 0, "max_batch": 8, "max_queue": 4096,
            "warmup": False}
    with ServingFleet(spec, replicas=2, workdir=str(tmp_path)) as fleet:
        router = fleet.router()
        futs = [router.submit(payload(i)) for i in range(16)]
        res = [f.result(120) for f in futs]
        assert len(res) == 16
        assert all("logits" in r or r is not None for r in res)

        # rolling reload mid-traffic
        futs = [router.submit(payload(i)) for i in range(16)]
        recs = fleet.rolling_reload(router)
        assert [r["replica"] for r in recs] == ["r0", "r1"]
        assert all(r["params_version"] == 1 for r in recs)
        for f in futs:
            f.result(120)                  # zero dropped across the reload

        # replica death: kill r0, requests route around it, restart brings
        # it back under the same name
        fleet.handles[0].proc.kill()
        fleet.handles[0].proc.wait()
        deadline = time.monotonic() + 10
        while fleet.handles[0].alive and time.monotonic() < deadline:
            time.sleep(0.05)
        futs = [router.submit(payload(i)) for i in range(8)]
        for f in futs:
            f.result(120)                  # survivors absorbed the load
        assert fleet.restart_dead(router) == ["r0"]
        assert fleet.handles[0].alive
        # the relaunch warmed from the surviving peer, not disk: it comes
        # back already on the fleet's CURRENT (post-reload) weights
        donor_v = fleet.handles[1].call("stats")["params_version"]
        assert donor_v == 1
        assert fleet.handles[0].call("stats")["params_version"] == donor_v
        fut = router.submit(payload(0))
        fut.result(120)

    rep = status.report(str(tmp_path), fleet_serve=True)
    fs = rep["fleet_serve"]
    procs = {r["process"] for r in fs["replicas"]}
    assert {"p0", "p1"} <= procs           # both replicas left events
    assert fs["totals"]["ok"] >= 41
    recov = [e for e in rep["recovery_events"]
             if e.get("event") in ("rolling-reload", "replica-restart",
                                   "replica-warmup")]
    assert {e["event"] for e in recov} == {"rolling-reload",
                                           "replica-restart",
                                           "replica-warmup"}
    restart = next(e for e in recov if e["event"] == "replica-restart")
    assert restart.get("warmed_from") == "r1"
