"""PartitionedDataset (RDD-shaped) semantics tests."""

import numpy as np

from distributeddeeplearningspark_tpu.rdd import PartitionedDataset


def test_parallelize_slicing():
    ds = PartitionedDataset.parallelize(list(range(10)), 3)
    assert ds.num_partitions == 3
    parts = [list(ds.iter_partition(i)) for i in range(3)]
    assert [len(p) for p in parts] == [3, 3, 4]
    assert ds.collect() == list(range(10))


def test_lazy_map_filter():
    evals = []

    def f(x):
        evals.append(x)
        return x * 2

    ds = PartitionedDataset.parallelize(range(4), 2).map(f)
    assert evals == []  # lazy
    assert ds.collect() == [0, 2, 4, 6]
    assert ds.filter(lambda x: x > 2).collect() == [4, 6]


def test_map_partitions_with_index():
    ds = PartitionedDataset.parallelize(range(6), 3)
    tagged = ds.map_partitions_with_index(lambda i, it: ((i, x) for x in it))
    assert tagged.collect() == [(0, 0), (0, 1), (1, 2), (1, 3), (2, 4), (2, 5)]


def test_batch_and_repeat():
    ds = PartitionedDataset.parallelize(range(10), 2).batch(2)
    assert ds.collect() == [[0, 1], [2, 3], [5, 6], [7, 8]]  # drop remainder per partition
    r = PartitionedDataset.parallelize(range(2), 1).repeat(3)
    assert r.collect() == [0, 1, 0, 1, 0, 1]


def test_shuffle_deterministic_and_partition_local():
    ds = PartitionedDataset.parallelize(range(8), 2)
    s1 = ds.shuffle(seed=1).collect()
    s2 = ds.shuffle(seed=1).collect()
    assert s1 == s2
    assert sorted(s1[:4]) == [0, 1, 2, 3]  # partition contents preserved
    assert sorted(s1[4:]) == [4, 5, 6, 7]


def test_tree_aggregate_matches_sum():
    ds = PartitionedDataset.parallelize(range(100), 4)
    total = ds.tree_aggregate(0, lambda acc, x: acc + x, lambda a, b: a + b)
    assert total == sum(range(100))


def test_actions():
    ds = PartitionedDataset.parallelize(range(7), 3)
    assert ds.count() == 7
    assert ds.take(3) == [0, 1, 2]
    assert ds.first() == 0
    assert ds.reduce(lambda a, b: a + b) == 21
    assert ds.coalesce(2).num_partitions == 2
    assert ds.coalesce(2).collect() == list(range(7))


def test_zip_with_index():
    ds = PartitionedDataset.parallelize(list("abcd"), 2)
    assert ds.zip_with_index().collect() == [("a", 0), ("b", 1), ("c", 2), ("d", 3)]


def test_numpy_parallelize():
    arr = np.arange(12).reshape(6, 2)
    ds = PartitionedDataset.parallelize(arr, 3)
    got = np.concatenate([np.asarray(list(ds.iter_partition(i))) for i in range(3)])
    np.testing.assert_array_equal(got.reshape(6, 2), arr)


def test_union_concatenates_partitions():
    a = PartitionedDataset.parallelize([1, 2], 2)
    b = PartitionedDataset.parallelize([3, 4, 5], 1)
    u = a.union(b)
    assert u.num_partitions == 3
    assert u.collect() == [1, 2, 3, 4, 5]
    import pytest

    with pytest.raises(ValueError, match="union"):
        a.union(b.repeat())


def test_sample_deterministic_and_bounded():
    ds = PartitionedDataset.parallelize(range(1000), 4)
    s1 = ds.sample(0.3, seed=7).collect()
    s2 = ds.sample(0.3, seed=7).collect()
    assert s1 == s2  # deterministic per seed
    assert 200 < len(s1) < 400  # ~300 expected
    assert set(s1) <= set(range(1000))
    assert ds.sample(0.0).count() == 0
    assert ds.sample(1.0).count() == 1000
    import pytest

    with pytest.raises(ValueError, match="fraction"):
        ds.sample(1.5)


def test_distinct_keeps_first_occurrence_order():
    ds = PartitionedDataset.parallelize([3, 1, 3, 2, 1, 2, 5], 3)
    assert ds.distinct().collect() == [3, 1, 2, 5]


def test_reduce_by_key_combines_across_partitions():
    ds = PartitionedDataset.parallelize(
        [("a", 1), ("b", 2), ("a", 3), ("c", 4), ("b", 5)], 3)
    out = ds.reduce_by_key(lambda x, y: x + y)
    assert out.num_partitions == 3
    assert dict(out.collect()) == {"a": 4, "b": 7, "c": 4}
    # every pair lands in the partition its CANONICAL key hash owns (PR 8:
    # exchange.key_bytes, stable across runs — hash() moves with
    # PYTHONHASHSEED), in key_bytes order within the partition
    from distributeddeeplearningspark_tpu.data import exchange

    for i in range(out.num_partitions):
        part = list(out.iter_partition(i))
        for k, _ in part:
            assert exchange.bucket_of(exchange.key_bytes(k), 3) == i
        kbs = [exchange.key_bytes(k) for k, _ in part]
        assert kbs == sorted(kbs)
    # num_partitions override + the infinite guard
    assert dict(ds.reduce_by_key(lambda x, y: x + y,
                                 num_partitions=1).collect()) == {
        "a": 4, "b": 7, "c": 4}
    import pytest

    with pytest.raises(ValueError, match="reduce_by_key"):
        ds.repeat().reduce_by_key(lambda x, y: x + y)


def test_group_by_key_orders_values_partition_major():
    ds = PartitionedDataset.parallelize(
        [("a", 1), ("b", 2), ("a", 3), ("a", 5)], 2)
    got = dict(ds.group_by_key().collect())
    assert got == {"a": [1, 3, 5], "b": [2]}


def test_by_key_camel_aliases_and_guards():
    ds = PartitionedDataset.parallelize([("a", 1), ("a", 2)], 2)
    assert dict(ds.reduceByKey(lambda x, y: x + y).collect()) == {"a": 3}
    assert dict(ds.groupByKey().collect()) == {"a": [1, 2]}
    assert ds.sortBy(lambda kv: kv[1]).collect() == [("a", 1), ("a", 2)]
    import pytest

    with pytest.raises(ValueError, match="num_partitions"):
        ds.reduce_by_key(lambda x, y: x, num_partitions=-2)
    with pytest.raises(ValueError, match="num_partitions"):
        ds.group_by_key(num_partitions=-2)
    with pytest.raises(ValueError, match="num_partitions"):
        ds.sort_by(lambda x: x, num_partitions=0)


def test_sort_by_is_range_partitioned_total_order():
    ds = PartitionedDataset.parallelize([5, 1, 4, 2, 3, 9, 0], 3)
    out = ds.sort_by(lambda x: x)
    assert out.collect() == [0, 1, 2, 3, 4, 5, 9]
    # range partitioning: max of partition i <= min of partition i+1
    parts = [list(out.iter_partition(i)) for i in range(out.num_partitions)]
    flat_bounds = [(min(p), max(p)) for p in parts if p]
    for (_, hi), (lo, _) in zip(flat_bounds, flat_bounds[1:]):
        assert hi <= lo
    assert ds.sort_by(lambda x: x, ascending=False).collect() == [
        9, 5, 4, 3, 2, 1, 0]


def test_cache_materializes_once_and_survives_partial_reads():
    calls = [0]

    def gen():
        calls[0] += 1
        yield from range(5)

    ds = PartitionedDataset.from_generators([gen]).cache()
    assert ds.take(2) == [0, 1]   # partial read: cache must NOT freeze this
    assert ds.collect() == [0, 1, 2, 3, 4]
    assert ds.collect() == [0, 1, 2, 3, 4]
    # one partial + one full pass over the source; the last collect was served
    # from memory
    assert calls[0] == 2
    # interleaved live iterators must not corrupt the committed store
    # (r4 review repro: a shared fill buffer yielded [0..4, 1..4] forever)
    it = ds.iter_partition(0)
    next(it)
    assert ds.collect() == [0, 1, 2, 3, 4]
    list(it)  # drain the stale iterator
    assert ds.collect() == [0, 1, 2, 3, 4]


def test_pyspark_aliases():
    ds = PartitionedDataset.parallelize(range(4), 2)
    assert ds.mapPartitions(lambda it: (x + 1 for x in it)).collect() == [1, 2, 3, 4]
    assert ds.flatMap(lambda x: [x, x]).count() == 8


class TestMapParallel:
    """map_parallel: thread-pool map (the Spark task-slot analog) must be a
    pure drop-in for map — same order, same values, bounded on infinite
    streams. (This sandbox has 1 CPU, so speedup is asserted architecturally
    on real hosts, not here.)"""

    def test_order_preserved_under_jittered_durations(self):
        import time

        def slow_square(x):
            time.sleep(0.001 * (7 - x % 7))  # later items finish earlier
            return x * x

        ds = PartitionedDataset.parallelize(list(range(40)), num_slices=2)
        got = ds.map_parallel(slow_square, num_threads=8).collect()
        assert got == [x * x for x in ds.collect()]

    def test_infinite_stream_stays_bounded(self):
        """The sliding window must not consume the infinite iterator up
        front (ThreadPoolExecutor.map would)."""
        ds = PartitionedDataset.parallelize(list(range(8)), num_slices=2)
        inf = ds.repeat().map_parallel(lambda x: x + 1, num_threads=4)
        it = inf.iter_partition(0)
        got = [next(it) for _ in range(50)]
        assert len(got) == 50 and got[:4] == [1, 2, 3, 4]  # partition 0 = first contiguous slice

    def test_imagenet_train_parallel_equals_serial(self, tmp_path):
        """Content-seeded augmentation: thread scheduling cannot change the
        pipeline output, so parallel ≡ serial example-for-example."""
        import numpy as np
        from PIL import Image

        from distributeddeeplearningspark_tpu.data.sources import imagenet_folder
        from distributeddeeplearningspark_tpu.data.vision import imagenet_train

        rng = np.random.default_rng(0)
        for cls in range(2):
            d = tmp_path / f"c{cls}"
            d.mkdir()
            for i in range(6):
                arr = rng.integers(0, 255, (64, 64, 3), np.uint8)
                Image.fromarray(arr).save(str(d / f"i{i}.jpg"), quality=92)
        serial = imagenet_train(
            imagenet_folder(str(tmp_path), num_partitions=2),
            size=32, num_threads=1).collect()
        parallel = imagenet_train(
            imagenet_folder(str(tmp_path), num_partitions=2),
            size=32, num_threads=6).collect()
        assert len(serial) == len(parallel) == 12
        for a, b in zip(serial, parallel):
            np.testing.assert_array_equal(a["image"], b["image"])
            assert a["label"] == b["label"]
