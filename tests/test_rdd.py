"""PartitionedDataset (RDD-shaped) semantics tests."""

import numpy as np

from distributeddeeplearningspark_tpu.rdd import PartitionedDataset


def test_parallelize_slicing():
    ds = PartitionedDataset.parallelize(list(range(10)), 3)
    assert ds.num_partitions == 3
    parts = [list(ds.iter_partition(i)) for i in range(3)]
    assert [len(p) for p in parts] == [3, 3, 4]
    assert ds.collect() == list(range(10))


def test_lazy_map_filter():
    evals = []

    def f(x):
        evals.append(x)
        return x * 2

    ds = PartitionedDataset.parallelize(range(4), 2).map(f)
    assert evals == []  # lazy
    assert ds.collect() == [0, 2, 4, 6]
    assert ds.filter(lambda x: x > 2).collect() == [4, 6]


def test_map_partitions_with_index():
    ds = PartitionedDataset.parallelize(range(6), 3)
    tagged = ds.map_partitions_with_index(lambda i, it: ((i, x) for x in it))
    assert tagged.collect() == [(0, 0), (0, 1), (1, 2), (1, 3), (2, 4), (2, 5)]


def test_batch_and_repeat():
    ds = PartitionedDataset.parallelize(range(10), 2).batch(2)
    assert ds.collect() == [[0, 1], [2, 3], [5, 6], [7, 8]]  # drop remainder per partition
    r = PartitionedDataset.parallelize(range(2), 1).repeat(3)
    assert r.collect() == [0, 1, 0, 1, 0, 1]


def test_shuffle_deterministic_and_partition_local():
    ds = PartitionedDataset.parallelize(range(8), 2)
    s1 = ds.shuffle(seed=1).collect()
    s2 = ds.shuffle(seed=1).collect()
    assert s1 == s2
    assert sorted(s1[:4]) == [0, 1, 2, 3]  # partition contents preserved
    assert sorted(s1[4:]) == [4, 5, 6, 7]


def test_tree_aggregate_matches_sum():
    ds = PartitionedDataset.parallelize(range(100), 4)
    total = ds.tree_aggregate(0, lambda acc, x: acc + x, lambda a, b: a + b)
    assert total == sum(range(100))


def test_actions():
    ds = PartitionedDataset.parallelize(range(7), 3)
    assert ds.count() == 7
    assert ds.take(3) == [0, 1, 2]
    assert ds.first() == 0
    assert ds.reduce(lambda a, b: a + b) == 21
    assert ds.coalesce(2).num_partitions == 2
    assert ds.coalesce(2).collect() == list(range(7))


def test_zip_with_index():
    ds = PartitionedDataset.parallelize(list("abcd"), 2)
    assert ds.zip_with_index().collect() == [("a", 0), ("b", 1), ("c", 2), ("d", 3)]


def test_numpy_parallelize():
    arr = np.arange(12).reshape(6, 2)
    ds = PartitionedDataset.parallelize(arr, 3)
    got = np.concatenate([np.asarray(list(ds.iter_partition(i))) for i in range(3)])
    np.testing.assert_array_equal(got.reshape(6, 2), arr)


def test_pyspark_aliases():
    ds = PartitionedDataset.parallelize(range(4), 2)
    assert ds.mapPartitions(lambda it: (x + 1 for x in it)).collect() == [1, 2, 3, 4]
    assert ds.flatMap(lambda x: [x, x]).count() == 8
