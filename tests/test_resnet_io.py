"""ResNet weight import (torchvision key convention) — numerical parity
against the transformers torch ResNet (same v1.5 graph, renamed keys)."""

import jax
import numpy as np
import pytest

from distributeddeeplearningspark_tpu.models.resnet import ResNet
from distributeddeeplearningspark_tpu.models.resnet_io import (
    hf_resnet_to_torchvision_keys,
    import_torchvision_resnet,
)

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


def _hf_tiny(depths, widths, stem, classes=7):
    cfg = transformers.ResNetConfig(
        embedding_size=stem,
        hidden_sizes=[4 * w for w in widths],
        depths=list(depths),
        layer_type="bottleneck",
        num_labels=classes,
    )
    torch.manual_seed(0)
    return transformers.ResNetForImageClassification(cfg).eval()


def test_hf_to_torchvision_key_translation_covers_everything():
    m = _hf_tiny((2, 2), (8, 16), stem=8)
    sd = hf_resnet_to_torchvision_keys(m.state_dict())
    assert "conv1.weight" in sd and "fc.weight" in sd
    assert "layer1.0.conv1.weight" in sd
    assert "layer2.0.downsample.0.weight" in sd
    assert "layer2.0.downsample.1.running_mean" in sd
    # every non-counter source key maps somewhere
    n_src = sum(1 for k in m.state_dict() if not k.endswith("num_batches_tracked"))
    assert len(sd) == n_src


def test_imported_resnet_matches_torch_logits():
    """import_torchvision_resnet: our NHWC flax model reproduces the torch
    model's logits from the same weights (eval mode, running BN stats)."""
    depths, widths, stem, classes = (2, 2), (8, 16), 8, 7
    m = _hf_tiny(depths, widths, stem, classes)
    sd = hf_resnet_to_torchvision_keys(m.state_dict())
    params, stats = import_torchvision_resnet(
        sd, stage_sizes=depths, bottleneck=True)

    model = ResNet(stage_sizes=depths, num_classes=classes, width=widths[0],
                   dtype=np.float32)
    rng = np.random.default_rng(0)
    img = rng.normal(0, 1, (2, 64, 64, 3)).astype(np.float32)
    # structure check: imported trees match a fresh init exactly
    init = model.init(jax.random.PRNGKey(0), {"image": img}, train=False)
    ref_paths = {jax.tree_util.keystr(p) for p, _ in
                 jax.tree_util.tree_flatten_with_path(init["params"])[0]}
    got_paths = {jax.tree_util.keystr(p) for p, _ in
                 jax.tree_util.tree_flatten_with_path(params)[0]}
    assert got_paths == ref_paths
    ours = model.apply({"params": params, "batch_stats": stats},
                       {"image": img}, train=False)
    with torch.no_grad():
        theirs = m(pixel_values=torch.tensor(
            img.transpose(0, 3, 1, 2))).logits.numpy()
    np.testing.assert_allclose(np.asarray(ours), theirs, rtol=2e-4, atol=2e-4)


def test_import_rejects_missing_keys():
    with pytest.raises(KeyError):
        import_torchvision_resnet({"conv1.weight": np.zeros((8, 3, 7, 7))},
                                  stage_sizes=(2,), bottleneck=True)


def test_translator_rejects_unrecognized_layout():
    with pytest.raises(ValueError, match="does not look like"):
        hf_resnet_to_torchvision_keys(
            {"embedder.convolution.weight": np.zeros((8, 3, 7, 7))})


def test_trainer_load_pretrained_places_batch_stats(eight_devices):
    """load_pretrained(batch_stats=...) lands running BN stats in
    state.mutable; a fresh-head fine-tune keeps the init head."""
    import optax

    from distributeddeeplearningspark_tpu import Session, Trainer
    from distributeddeeplearningspark_tpu.train import losses

    depths, widths = (2, 2), (8, 16)
    m = _hf_tiny(depths, widths, stem=8, classes=1000)
    sd = hf_resnet_to_torchvision_keys(m.state_dict())
    params, stats = import_torchvision_resnet(sd, stage_sizes=depths)
    params.pop("head")  # new label space

    spark = Session.builder.master("local[8]").appName("ft").getOrCreate()
    model = ResNet(stage_sizes=depths, num_classes=5, width=widths[0],
                   dtype=np.float32)
    trainer = Trainer(spark, model, losses.softmax_xent, optax.sgd(0.1))
    batch = {"image": np.zeros((8, 32, 32, 3), np.float32),
             "label": np.zeros((8,), np.int32)}
    trainer.init(batch)
    trainer.load_pretrained(params, batch_stats=stats,
                            allow_uncovered=("head",))
    got = np.asarray(
        trainer.state.mutable["batch_stats"]["stem_bn"]["mean"])
    np.testing.assert_allclose(got, sd["bn1.running_mean"], rtol=1e-6)
    got_w = np.asarray(trainer.state.params["stem_conv"]["kernel"])
    np.testing.assert_allclose(
        got_w, np.asarray(sd["conv1.weight"]).transpose(2, 3, 1, 0), rtol=1e-6)
    assert trainer.state.params["head"]["bias"].shape == (5,)
    spark.stop()
