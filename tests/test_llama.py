"""Llama-2 + LoRA tests (config 5, SURVEY.md §4).

Covers: forward/causality, scan↔loop layer-stack equivalence, LoRA freeze
semantics, FSDP×TP sharded training on the 8-fake-device mesh, safetensors
round-trip, and numerical parity against torch/transformers' LlamaForCausalLM
(the §4 "numerical parity" strategy — torch CPU is the stand-in oracle for the
unreachable reference).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributeddeeplearningspark_tpu.data.feed import put_global, stack_examples
from distributeddeeplearningspark_tpu.models import (
    LlamaConfig,
    LlamaForCausalLM,
    llama_rules,
    llama_tiny,
    lora_trainable,
)
from distributeddeeplearningspark_tpu.models import llama_io
from distributeddeeplearningspark_tpu.parallel.mesh import MeshSpec
from distributeddeeplearningspark_tpu.parallel.sharding import path_str
from distributeddeeplearningspark_tpu.train import losses, optim, step as step_lib


def make_batch(b=2, s=16, vocab=512, seed=0):
    rng = np.random.default_rng(seed)
    return {"input_ids": rng.integers(0, vocab, (b, s)).astype(np.int32)}


def test_forward_shape_and_dtype():
    model = llama_tiny()
    batch = make_batch()
    variables = model.init(jax.random.PRNGKey(0), batch, train=False)
    logits = model.apply(variables, batch, train=False)
    assert logits.shape == (2, 16, model.cfg.vocab_size)
    assert logits.dtype == jnp.float32
    assert np.isfinite(np.asarray(logits)).all()


def test_causality():
    """Changing token t+k must not change the logits at position t."""
    model = llama_tiny()
    batch = make_batch(b=1, s=16)
    variables = model.init(jax.random.PRNGKey(0), batch, train=False)
    base = np.asarray(model.apply(variables, batch, train=False))
    mutated = {"input_ids": batch["input_ids"].copy()}
    mutated["input_ids"][0, 10:] = (mutated["input_ids"][0, 10:] + 7) % 512
    out = np.asarray(model.apply(variables, mutated, train=False))
    np.testing.assert_allclose(base[0, :10], out[0, :10], atol=1e-5)
    assert np.abs(base[0, 10:] - out[0, 10:]).max() > 1e-4


def test_scan_matches_loop():
    """nn.scan layer stacking must be numerically identical to the python loop."""
    cfg_scan = LlamaConfig.tiny(scan_layers=True, remat=False)
    cfg_loop = LlamaConfig.tiny(scan_layers=False, remat=False)
    batch = make_batch()
    scan_model = LlamaForCausalLM(cfg_scan)
    params = scan_model.init(jax.random.PRNGKey(0), batch, train=False)["params"]

    # unstack layers/[L,...] into layers_i/... for the loop model
    loop_params = {k: v for k, v in params.items() if k != "layers"}
    for i in range(cfg_loop.num_layers):
        loop_params[f"layers_{i}"] = jax.tree.map(lambda x: x[i], params["layers"])

    out_scan = scan_model.apply({"params": params}, batch, train=False)
    out_loop = LlamaForCausalLM(cfg_loop).apply({"params": loop_params}, batch, train=False)
    np.testing.assert_allclose(np.asarray(out_scan), np.asarray(out_loop), atol=2e-5)


def test_scan_param_barrier_is_numerics_neutral():
    """scan_param_barrier (default on; the 7B single-chip fit lever, r4)
    wraps each layer's sliced params in optimization_barrier — identity
    math, so init, logits and grads must be BIT-identical with it off.
    Ordering is load-bearing: the barrier sits inside the remat region
    (outside, its outputs become saved residuals — +12.5 GiB of stacked
    weight copies at 7B, measured on the r4 chip window)."""
    import dataclasses

    batch = make_batch()
    outs = {}
    for flag in (True, False):
        cfg = LlamaConfig.tiny(remat=True, lora_rank=4,
                               scan_param_barrier=flag)
        model = LlamaForCausalLM(cfg)
        variables = model.init(jax.random.PRNGKey(0), batch, train=False)

        def loss_fn(v):
            return jnp.mean(
                model.apply(v, batch, train=False).astype(jnp.float32) ** 2)

        outs[flag] = (variables, model.apply(variables, batch, train=False),
                      jax.grad(loss_fn)(variables))
    for on_leaf, off_leaf in zip(jax.tree.leaves(outs[True]),
                                 jax.tree.leaves(outs[False])):
        np.testing.assert_array_equal(np.asarray(on_leaf),
                                      np.asarray(off_leaf))


def test_trainable_filter_grads_match_and_frozen_are_zero():
    """make_train_step(trainable=...) must not change the math: LoRA-leaf
    grads equal the unfiltered step's, frozen base grads are exactly zero
    (they were stop_gradient'ed out of the backward), and the two steps land
    on identical adapters after an update."""
    import optax

    from distributeddeeplearningspark_tpu.train import losses, optim, step as step_lib

    cfg = LlamaConfig.tiny(lora_rank=2)
    model = LlamaForCausalLM(cfg)
    batch = make_batch()
    mesh = MeshSpec(data=1).build(jax.devices()[:1])
    tx = optim.masked(optax.sgd(0.1), lora_trainable)

    def run(trainable):
        state, sh = step_lib.init_state(model, tx, batch, mesh, llama_rules(cfg))
        step = step_lib.jit_train_step(
            step_lib.make_train_step(model.apply, tx, losses.causal_lm,
                                     trainable=trainable),
            mesh, sh)
        return step(state, put_global(batch, mesh))

    state_full, m_full = run(None)
    state_filt, m_filt = run(lora_trainable)
    # same loss; grad_norm must DROP by exactly the discarded base grads
    np.testing.assert_allclose(float(m_full["loss"]), float(m_filt["loss"]),
                               rtol=1e-6)
    assert float(m_filt["grad_norm"]) < float(m_full["grad_norm"]), (
        m_filt["grad_norm"], m_full["grad_norm"])
    params_full = jax.device_get(state_full.params)
    params_filt = jax.device_get(state_filt.params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=1e-6),
        params_full, params_filt)

    # gradient-level proof (not masked by the optimizer): frozen leaves get
    # exactly-zero grads under the filter, LoRA leaves identical grads
    from distributeddeeplearningspark_tpu.parallel.sharding import path_str

    params = model.init(jax.random.PRNGKey(0), batch, train=False)["params"]

    def loss_fn_of(filtered):
        def f(p):
            if filtered:
                p = jax.tree_util.tree_map_with_path(
                    lambda path, x: x if lora_trainable(path_str(path))
                    else jax.lax.stop_gradient(x), p)
            logits = model.apply({"params": p}, batch, train=False)
            return losses.causal_lm(logits, batch)[0]
        return f

    g_full = jax.grad(loss_fn_of(False))(params)
    g_filt = jax.grad(loss_fn_of(True))(params)

    def check(path, a, b):
        if lora_trainable(path_str(path)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-6, err_msg=path_str(path))
        else:
            np.testing.assert_array_equal(np.asarray(b), 0.0,
                                          err_msg=path_str(path))
            assert np.abs(np.asarray(a)).max() > 0, (
                f"{path_str(path)}: full grad unexpectedly zero — "
                "the 'frozen grads are zero' check would be vacuous")

    jax.tree_util.tree_map_with_path(check, g_full, g_filt)


def test_remat_policy_dots_matches_full_remat_gradients():
    """remat_policy changes what the backward keeps, never the math: grads
    under 'dots' (keep matmul outputs) must equal full remat to fp tolerance.
    A bad policy name raises at trace time."""
    batch = make_batch()
    cfg_full = LlamaConfig.tiny(remat=True)
    cfg_dots = LlamaConfig.tiny(remat=True, remat_policy="dots")
    model_full = LlamaForCausalLM(cfg_full)
    params = model_full.init(jax.random.PRNGKey(0), batch, train=False)["params"]

    def loss(model):
        def f(p):
            logits = model.apply({"params": p}, batch, train=False)
            return jnp.mean(logits.astype(jnp.float32) ** 2)
        return f

    g_full = jax.grad(loss(model_full))(params)
    g_dots = jax.grad(loss(LlamaForCausalLM(cfg_dots)))(params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5),
        g_full, g_dots)

    with pytest.raises(ValueError, match="remat_policy"):
        LlamaForCausalLM(LlamaConfig.tiny(remat_policy="bogus")).init(
            jax.random.PRNGKey(0), batch, train=False)


class TestLoRA:
    def test_zero_init_matches_base(self):
        """With B=0 at init, the adapted model must equal the base model."""
        base_cfg = LlamaConfig.tiny(remat=False)
        lora_cfg = LlamaConfig.tiny(remat=False, lora_rank=4)
        batch = make_batch()
        lora_params = LlamaForCausalLM(lora_cfg).init(
            jax.random.PRNGKey(0), batch, train=False)["params"]
        # strip lora leaves to form the base tree
        def strip(node):
            if isinstance(node, dict):
                return {k: strip(v) for k, v in node.items()
                        if k not in ("lora_a", "lora_b")}
            return node
        base_params = strip(lora_params)
        out_lora = LlamaForCausalLM(lora_cfg).apply({"params": lora_params}, batch, train=False)
        out_base = LlamaForCausalLM(base_cfg).apply({"params": base_params}, batch, train=False)
        np.testing.assert_allclose(np.asarray(out_lora), np.asarray(out_base), atol=1e-6)

    def test_masked_optimizer_freezes_base(self):
        """One train step: base kernels unchanged, lora_b updated, loss finite."""
        cfg = LlamaConfig.tiny(lora_rank=4)
        model = LlamaForCausalLM(cfg)
        mesh = MeshSpec(data=-1).build()
        tx = optim.masked(optax.adamw(1e-2), lora_trainable)
        batch = stack_examples([{"input_ids": r} for r in make_batch(8, 16)["input_ids"]])
        state, shardings = step_lib.init_state(model, tx, batch, mesh, llama_rules(cfg))
        before = {path_str(p): np.asarray(x) for p, x in
                  jax.tree_util.tree_flatten_with_path(state.params)[0]}
        train = step_lib.jit_train_step(
            step_lib.make_train_step(model.apply, tx, losses.causal_lm), mesh, shardings)
        state, metrics = train(state, put_global(batch, mesh))
        assert np.isfinite(float(jax.device_get(metrics["loss"])))
        after = {path_str(p): np.asarray(x) for p, x in
                 jax.tree_util.tree_flatten_with_path(state.params)[0]}
        for pstr, old in before.items():
            new = after[pstr]
            if "lora_b" in pstr:
                assert np.abs(new - old).max() > 0, f"{pstr} should have trained"
            elif "lora" not in pstr:
                np.testing.assert_array_equal(new, old, err_msg=f"{pstr} must stay frozen")

    def test_merge_lora(self):
        """merge_lora(base+adapters) must reproduce the adapted forward."""
        cfg = LlamaConfig.tiny(remat=False, lora_rank=4)
        model = LlamaForCausalLM(cfg)
        batch = make_batch()
        params = model.init(jax.random.PRNGKey(0), batch, train=False)["params"]
        # make adapters non-trivial (B=0 at init would make the merge vacuous)
        params = jax.tree_util.tree_map_with_path(
            lambda p, x: x + 0.01 if "lora_b" in path_str(p) else x, params)
        out_adapted = model.apply({"params": params}, batch, train=False)
        merged = llama_io.merge_lora(jax.tree.map(np.asarray, params), cfg)
        base_model = LlamaForCausalLM(LlamaConfig.tiny(remat=False))
        out_merged = base_model.apply({"params": merged}, batch, train=False)
        np.testing.assert_allclose(
            np.asarray(out_adapted), np.asarray(out_merged), atol=2e-5)


class TestInt8Base:
    """QLoRA-style int8 frozen-base storage (LlamaConfig.base_quant)."""

    def _cfgs(self):
        dense = LlamaConfig.tiny(remat=False, lora_rank=4)
        q = LlamaConfig.tiny(remat=False, lora_rank=4, base_quant="int8")
        return dense, q

    def test_quantize_transform_parity(self):
        """quantize_base_int8(dense tree) must (a) produce exactly the int8
        model's param shapes/dtypes and (b) preserve the forward within
        per-channel absmax quantization error."""
        dense_cfg, q_cfg = self._cfgs()
        batch = make_batch()
        dense_params = LlamaForCausalLM(dense_cfg).init(
            jax.random.PRNGKey(0), batch, train=False)["params"]
        q_params = llama_io.quantize_base_int8(
            jax.tree.map(np.asarray, dense_params))
        # shapes/dtypes must match the int8 model's own init exactly
        want = LlamaForCausalLM(q_cfg).init(
            jax.random.PRNGKey(0), batch, train=False)["params"]
        flat_q = {path_str(p): x for p, x in
                  jax.tree_util.tree_flatten_with_path(q_params)[0]}
        flat_w = {path_str(p): x for p, x in
                  jax.tree_util.tree_flatten_with_path(want)[0]}
        assert flat_q.keys() == flat_w.keys()
        for k in flat_w:
            assert np.shape(flat_q[k]) == np.shape(flat_w[k]), k
            if "base_q8" in k:
                assert np.asarray(flat_q[k]).dtype == np.int8, k
        out_dense = LlamaForCausalLM(dense_cfg).apply(
            {"params": dense_params}, batch, train=False)
        out_q = LlamaForCausalLM(q_cfg).apply(
            {"params": q_params}, batch, train=False)
        # int8 absmax error is ≤ scale/2 per weight; at tiny width the
        # logits stay close — this bounds gross layout/scale mistakes
        # (a wrong fold axis or scale broadcast blows this to O(1))
        err = np.abs(np.asarray(out_q, np.float32)
                     - np.asarray(out_dense, np.float32))
        ref = np.abs(np.asarray(out_dense, np.float32)).max()
        assert err.max() < 0.05 * ref, (err.max(), ref)

    def test_frozen_training_step_and_memory(self):
        """A masked-LoRA train step on the int8 model: loss finite, adapters
        move, int8 kernels and scales bit-frozen; the memory model prices
        the base at ~1 byte/weight."""
        _, q_cfg = self._cfgs()
        model = LlamaForCausalLM(q_cfg)
        mesh = MeshSpec(data=-1).build()
        tx = optim.masked(optax.adamw(1e-2), lora_trainable)
        batch = stack_examples(
            [{"input_ids": r} for r in make_batch(8, 16)["input_ids"]])
        state, shardings = step_lib.init_state(
            model, tx, batch, mesh, llama_rules(q_cfg))
        before = {path_str(p): np.asarray(x) for p, x in
                  jax.tree_util.tree_flatten_with_path(state.params)[0]}
        train = step_lib.jit_train_step(
            step_lib.make_train_step(model.apply, tx, losses.causal_lm,
                                     trainable=lora_trainable),
            mesh, shardings)
        state, metrics = train(state, put_global(batch, mesh))
        assert np.isfinite(float(jax.device_get(metrics["loss"])))
        after = {path_str(p): np.asarray(x) for p, x in
                 jax.tree_util.tree_flatten_with_path(state.params)[0]}
        for pstr, old in before.items():
            if "lora_b" in pstr:
                assert np.abs(after[pstr] - old).max() > 0, pstr
            elif "lora" not in pstr:
                np.testing.assert_array_equal(after[pstr], old, err_msg=pstr)

        from distributeddeeplearningspark_tpu.utils.memory import (
            llama_memory_report, llama_param_count)

        # exact param count (incl. scale leaves) vs the real tree
        n_leaves = sum(int(np.prod(np.shape(x))) for x in before.values())
        counts = llama_param_count(q_cfg)
        assert counts["base"] + counts["lora"] == n_leaves
        rep = llama_memory_report(q_cfg, batch=2, seq=16).to_dict()
        assert "base_params_int8" in rep["per_chip_gib"]

    def test_7b_int8_budget_headroom(self):
        """The point of the knob: the 7B base drops ~12.6 → ~6.3 GiB, so the
        single-chip (16 GiB) budget gains ~6 GiB of batch/context headroom."""
        from distributeddeeplearningspark_tpu.utils.memory import (
            llama_memory_report)

        bf16 = LlamaConfig.llama2_7b(lora_rank=16, fused_head_loss=True,
                                     remat_policy=None)
        q = LlamaConfig.llama2_7b(lora_rank=16, fused_head_loss=True,
                                  remat_policy=None, base_quant="int8")
        r16 = llama_memory_report(bf16, batch=1, seq=2048).to_dict()
        rq = llama_memory_report(q, batch=1, seq=2048).to_dict()
        saved = r16["total_gib_per_chip"] - rq["total_gib_per_chip"]
        assert 5.0 < saved < 7.0, (r16["total_gib_per_chip"],
                                   rq["total_gib_per_chip"])

    def test_quality_bound_at_bench_geometry(self):
        """End-to-end quality bound at the REAL 0.9b bench geometry
        (VERDICT r4 next-#4: the 5%-on-tiny-logits absmax argument was too
        loose to say anything about config-5 quality). Quantizes a full
        0.9b tree (hidden 2048 × 16 layers × vocab 32k, the exact
        `bench._llama_09b_cfg` shape so it can't drift from the measured
        series) and asserts the next-token cross-entropy delta on a
        held-out synthetic corpus slice through the real `lm_dataset`
        path. Measured when written: ΔCE = +0.0024 nats (ppl ratio
        1.0024); the 0.01-nat bound is 4× that — tight enough to catch a
        wrong scale axis or a per-tensor (vs per-channel) regression,
        which measure O(0.1–1) nats. Caveat, stated honestly: the base
        tree is init-random (no pretrained 0.9b weights exist offline);
        absmax per-channel error is distribution-robust, but the bound is
        a storage-faithfulness property, not a fine-tune-accuracy claim.
        ~2.5 min on one CPU core (two 0.9b forwards + quantize)."""
        import dataclasses

        import bench
        from distributeddeeplearningspark_tpu.data import text as text_lib

        s = 128
        cfg_d = dataclasses.replace(bench._llama_09b_cfg(seq=s), remat=False)
        assert (cfg_d.hidden_size, cfg_d.num_layers) == (2048, 16)
        model_d = LlamaForCausalLM(cfg_d)
        docs = text_lib.synthetic_wikipedia(12, num_partitions=1, seed=7)
        tok = text_lib.WordPieceTokenizer.train(docs.collect(),
                                                vocab_size=512)
        examples = list(text_lib.lm_dataset(
            docs, tok, seq_len=s).take(2))
        batch = stack_examples(examples)
        params = model_d.init(jax.random.PRNGKey(0),
                              {"input_ids": batch["input_ids"]},
                              train=False)["params"]
        out_d = model_d.apply({"params": params},
                              {"input_ids": batch["input_ids"]}, train=False)
        qp = llama_io.quantize_base_int8(jax.tree.map(np.asarray, params))
        cfg_q = dataclasses.replace(cfg_d, base_quant="int8")
        out_q = LlamaForCausalLM(cfg_q).apply(
            {"params": qp}, {"input_ids": batch["input_ids"]}, train=False)

        def next_token_ce(logits):
            lg = jnp.asarray(np.asarray(logits, np.float32)[:, :-1])
            tgt = jnp.asarray(batch["input_ids"][:, 1:])
            w = jnp.asarray(batch["loss_mask"][:, 1:])
            lse = jax.nn.logsumexp(lg, axis=-1)
            picked = jnp.take_along_axis(lg, tgt[..., None], axis=-1)[..., 0]
            return float(jnp.sum((lse - picked) * w) / jnp.sum(w))

        ce_d, ce_q = next_token_ce(out_d), next_token_ce(out_q)
        delta = abs(ce_q - ce_d)
        assert delta < 0.01, (ce_d, ce_q, delta)
        assert float(np.exp(delta)) < 1.0101  # perplexity ratio ≤ ~1%

    def _forward_rel_err(self, dense_cfg, q_cfg, outlier_tree, batch):
        """(max, mean) forward logits error of int8-vs-dense on the SAME
        tree, relative to the dense logits scale."""
        q_params = llama_io.quantize_base_int8(outlier_tree)
        out_dense = LlamaForCausalLM(dense_cfg).apply(
            {"params": outlier_tree}, batch, train=False)
        out_q = LlamaForCausalLM(q_cfg).apply(
            {"params": q_params}, batch, train=False)
        err = np.abs(np.asarray(out_q, np.float32)
                     - np.asarray(out_dense, np.float32))
        ref = np.abs(np.asarray(out_dense, np.float32)).max()
        return err.max() / ref, err.mean() / ref, q_params

    def test_quality_bound_at_outlier_weights(self):
        """The quality bound with TEETH at absmax-per-channel's known
        failure mode (VERDICT r5 missing-#4): outlier weights. One
        outlier in a channel inflates that channel's absmax scale, which
        multiplies the quantization error of every OTHER weight sharing
        the channel. Two regimes, both measured on this geometry when
        written:

        - **Outlier channels** (the realistic LLM shape: a few channels
          per kernel carry x32 spikes, the rest are clean): measured max
          logits error 2.3% of the logits scale — the 5% bound of the
          clean-init parity test above STILL HOLDS, because the damage is
          confined to the spiked channels.
        - **Heavy-tailed everywhere** (0.5% of ALL entries x32 — at tiny
          width that lands an outlier in nearly every channel): measured
          max logits error 49%, mean 4.3%. Per-channel absmax genuinely
          fails here, and this test pins the measured band rather than
          pretending otherwise: the documented degradation is the
          motivation line for any future outlier-aware scheme (clip /
          SmoothQuant-style migration), whose success criterion is
          dropping the lower edge of this band."""
        dense_cfg, q_cfg = self._cfgs()
        batch = make_batch()
        params = LlamaForCausalLM(dense_cfg).init(
            jax.random.PRNGKey(0), batch, train=False)["params"]

        def inject(fn):
            rng = np.random.default_rng(42)

            def f(path, x):
                x = np.asarray(x, np.float32)
                if "base/kernel" not in path_str(path):
                    return x
                return fn(rng, x.copy())
            return jax.tree_util.tree_map_with_path(f, params)

        # regime 1: outliers confined to 2 output channels per kernel
        def confined(rng, x):
            flat = x.reshape(-1, x.shape[-1])
            for c in rng.choice(x.shape[-1], size=2, replace=False):
                flat[rng.integers(0, flat.shape[0]), c] *= 32.0
            return flat.reshape(x.shape)

        mx, _, _ = self._forward_rel_err(
            dense_cfg, q_cfg, inject(confined), batch)
        assert mx < 0.05, f"confined-outlier bound broke: {mx:.4f}"

        # regime 2: heavy-tailed everywhere
        heavy = inject(lambda rng, x: np.where(
            rng.random(x.shape) < 0.005, x * 32.0, x))
        mx, mean, q_params = self._forward_rel_err(
            dense_cfg, q_cfg, heavy, batch)
        # the measured-degradation band: bad enough to prove the failure
        # mode is real (>5%: the clean bound does NOT hold), bounded
        # enough to catch a broken scale axis (O(100%) error)
        assert 0.05 < mx < 1.0, f"heavy-tail band moved: {mx:.4f}"
        assert mean < 0.15, f"heavy-tail mean error: {mean:.4f}"

        # the construction guarantee survives even here, hand-folded on
        # the scanned wq stack: |dequant - w| <= scale/2 everywhere,
        # outlier channels included
        w = np.asarray(heavy["layers"]["attention"]["wq"]["base"]
                       ["kernel"], np.float32)     # [L, h, nh, hd]
        q8 = np.asarray(q_params["layers"]["attention"]["wq"]
                        ["base_q8"], np.float32)   # [L, h, nh, hd]
        scale = np.asarray(q_params["layers"]["attention"]["wq"]
                           ["base_scale"])         # [L, nh, hd]
        err_w = np.abs(q8 * scale[:, None] - w)
        assert (err_w <= scale[:, None] / 2 + 1e-7).all()
        # and the outliers really did inflate scales: spread >= the x32
        assert scale.max() / scale.min() > 8.0

    def test_io_guards_on_quantized_trees(self):
        """merge_lora / export on an int8 tree must refuse loudly — a
        silent unmerged return or a KeyError would break the deploy path
        (r4 review finding)."""
        dense_cfg, q_cfg = self._cfgs()
        batch = make_batch()
        dense_params = LlamaForCausalLM(dense_cfg).init(
            jax.random.PRNGKey(0), batch, train=False)["params"]
        q_params = llama_io.quantize_base_int8(
            jax.tree.map(np.asarray, dense_params))
        with pytest.raises(NotImplementedError, match="dense tree"):
            llama_io.merge_lora(q_params, q_cfg)
        with pytest.raises(NotImplementedError, match="DENSE tree"):
            llama_io.export_llama_safetensors(q_params, q_cfg, "/tmp/x.st")

    def test_guards(self):
        batch = make_batch()
        with pytest.raises(ValueError, match="lora_rank"):
            LlamaForCausalLM(LlamaConfig.tiny(base_quant="int8")).init(
                jax.random.PRNGKey(0), batch, train=False)
        with pytest.raises(NotImplementedError, match="expert"):
            LlamaForCausalLM(LlamaConfig.tiny(
                base_quant="int8", lora_rank=4, moe_experts=2,
                intermediate_size=64)).init(
                    jax.random.PRNGKey(0), batch, train=False)
        with pytest.raises(ValueError, match="base_quant"):
            LlamaForCausalLM(LlamaConfig.tiny(
                base_quant="int4", lora_rank=4)).init(
                    jax.random.PRNGKey(0), batch, train=False)


def test_fsdp_tp_sharded_train_step(eight_devices):
    """FSDP×TP mesh: params actually sharded, step runs, grads sync (config 5)."""
    cfg = LlamaConfig.tiny(lora_rank=4)
    model = LlamaForCausalLM(cfg)
    mesh = MeshSpec(data=2, fsdp=2, tensor=2).build(eight_devices)
    rules = llama_rules(cfg, fsdp_min_size=1)
    tx = optim.masked(optax.adamw(1e-2), lora_trainable)
    batch = stack_examples([{"input_ids": r} for r in make_batch(8, 16)["input_ids"]])
    state, shardings = step_lib.init_state(model, tx, batch, mesh, rules)

    specs = rules.tree_specs(state.params, mesh)
    flat = {path_str(p): s for p, s in jax.tree_util.tree_flatten_with_path(
        specs, is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec))[0]}
    wq = flat["layers/attention/wq/base/kernel"]
    assert "tensor" in jax.tree.leaves(tuple(wq)), f"wq spec {wq} should use tensor axis"
    assert any("fsdp" in str(s) for s in flat.values()), "no param picked up fsdp axis"

    train = step_lib.jit_train_step(
        step_lib.make_train_step(model.apply, tx, losses.causal_lm), mesh, shardings)
    state, metrics = train(state, put_global(batch, mesh))
    assert np.isfinite(float(jax.device_get(metrics["loss"])))


def test_int8_base_fsdp_tp_sharded_train_step(eight_devices):
    """The int8 leaves (base_q8/base_scale) must shard like their dense
    siblings on a data×fsdp×tensor mesh — the rules added for them were
    otherwise never exercised on more than one device — and the masked
    step must run with frozen int8 params under real shardings."""
    cfg = LlamaConfig.tiny(lora_rank=4, base_quant="int8")
    model = LlamaForCausalLM(cfg)
    mesh = MeshSpec(data=2, fsdp=2, tensor=2).build(eight_devices)
    rules = llama_rules(cfg, fsdp_min_size=1)
    tx = optim.masked(optax.adamw(1e-2), lora_trainable)
    batch = stack_examples([{"input_ids": r}
                            for r in make_batch(8, 16)["input_ids"]])
    state, shardings = step_lib.init_state(model, tx, batch, mesh, rules)

    specs = rules.tree_specs(state.params, mesh)
    flat = {path_str(p): s for p, s in jax.tree_util.tree_flatten_with_path(
        specs, is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec))[0]}
    assert "tensor" in jax.tree.leaves(tuple(flat["layers/attention/wq/base_q8"])), flat[
        "layers/attention/wq/base_q8"]
    assert "tensor" in jax.tree.leaves(tuple(flat["layers/mlp/gate/base_q8"])), flat[
        "layers/mlp/gate/base_q8"]

    train = step_lib.jit_train_step(
        step_lib.make_train_step(model.apply, tx, losses.causal_lm,
                                 trainable=lora_trainable), mesh, shardings)
    before = jax.device_get(state.params["layers"]["attention"]["wq"]["base_q8"])
    state, metrics = train(state, put_global(batch, mesh))
    assert np.isfinite(float(jax.device_get(metrics["loss"])))
    after = jax.device_get(state.params["layers"]["attention"]["wq"]["base_q8"])
    np.testing.assert_array_equal(before, after)  # int8 base bit-frozen


class TestSafetensorsIO:
    def test_roundtrip_loop_layout(self, tmp_path):
        cfg = LlamaConfig.tiny(scan_layers=False, remat=False)
        model = LlamaForCausalLM(cfg)
        batch = make_batch()
        params = jax.tree.map(
            np.asarray, model.init(jax.random.PRNGKey(1), batch, train=False)["params"])
        path = str(tmp_path / "model.safetensors")
        llama_io.export_llama_safetensors(params, cfg, path)
        loaded = llama_io.load_llama_safetensors(path, cfg)
        jax.tree.map(np.testing.assert_allclose, params, loaded)

    def test_hf_file_loads_into_scan_layout(self, tmp_path):
        """Same HF file must load into scanned and loop layouts with equal logits."""
        loop_cfg = LlamaConfig.tiny(scan_layers=False, remat=False)
        scan_cfg = LlamaConfig.tiny(scan_layers=True, remat=False)
        model = LlamaForCausalLM(loop_cfg)
        batch = make_batch()
        params = jax.tree.map(
            np.asarray, model.init(jax.random.PRNGKey(2), batch, train=False)["params"])
        path = str(tmp_path / "model.safetensors")
        llama_io.export_llama_safetensors(params, loop_cfg, path)
        scan_params = llama_io.load_llama_safetensors(path, scan_cfg)
        out_loop = model.apply({"params": params}, batch, train=False)
        out_scan = LlamaForCausalLM(scan_cfg).apply({"params": scan_params}, batch, train=False)
        np.testing.assert_allclose(np.asarray(out_loop), np.asarray(out_scan), atol=2e-5)


def test_lm_dataset_packing():
    """Packed causal-LM blocks: fixed shapes, full loss mask except final pad."""
    from distributeddeeplearningspark_tpu.data import text as text_lib

    docs = text_lib.synthetic_wikipedia(32, num_partitions=2, seed=3)
    tok = text_lib.WordPieceTokenizer.train(docs.collect(), vocab_size=512)
    examples = text_lib.lm_dataset(docs, tok, seq_len=64).collect()
    assert len(examples) > 2
    for ex in examples:
        assert set(ex) == {"input_ids", "loss_mask"}
        assert ex["input_ids"].shape == (64,) and ex["loss_mask"].shape == (64,)
    full = [ex for ex in examples if ex["loss_mask"].all()]
    assert len(full) >= len(examples) - 2  # only trailing blocks may be padded


def test_parity_with_transformers(tmp_path):
    """Golden parity vs torch LlamaForCausalLM (SURVEY.md §4 'Numerical parity')."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    hf_cfg = transformers.LlamaConfig(
        vocab_size=512, hidden_size=128, num_hidden_layers=4,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=256,
        max_position_embeddings=128, rms_norm_eps=1e-5, rope_theta=10000.0,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    hf_model = transformers.LlamaForCausalLM(hf_cfg).eval()
    hf_dir = str(tmp_path / "hf")
    hf_model.save_pretrained(hf_dir, safe_serialization=True)

    cfg = LlamaConfig.tiny(remat=False)
    params = llama_io.load_llama_safetensors(hf_dir, cfg)
    batch = make_batch(b=2, s=16)
    ours = np.asarray(LlamaForCausalLM(cfg).apply({"params": params}, batch, train=False))

    with torch.no_grad():
        theirs = hf_model(torch.from_numpy(batch["input_ids"].astype(np.int64))).logits.numpy()
    np.testing.assert_allclose(ours, theirs, atol=2e-4, rtol=2e-3)


def test_fused_head_loss_matches_plain_path():
    """fused_head_loss=True + causal_lm_fused ≡ plain logits + causal_lm:
    identical param tree (lm_head/kernel preserved for TP/IO), identical
    loss, identical grads — only the [B,S,V] materialization differs."""
    from distributeddeeplearningspark_tpu.train import losses

    cfg_plain = LlamaConfig.tiny()
    cfg_fused = LlamaConfig.tiny(fused_head_loss=True)
    batch = make_batch()
    batch["loss_mask"] = np.ones_like(batch["input_ids"], np.float32)
    m_plain = LlamaForCausalLM(cfg_plain)
    m_fused = LlamaForCausalLM(cfg_fused)
    params = m_plain.init(jax.random.PRNGKey(0), batch, train=False)["params"]
    params_f = m_fused.init(jax.random.PRNGKey(0), batch, train=False)["params"]
    assert jax.tree.structure(params) == jax.tree.structure(params_f)
    assert params["lm_head"]["kernel"].shape == params_f["lm_head"]["kernel"].shape

    def loss_plain(p):
        return losses.causal_lm(
            m_plain.apply({"params": p}, batch, train=True), batch)[0]

    def loss_fused(p):
        return losses.causal_lm_fused(
            m_fused.apply({"params": p}, batch, train=True), batch)[0]

    lp, gp = jax.value_and_grad(loss_plain)(params)
    lf, gf = jax.value_and_grad(loss_fused)(params)
    np.testing.assert_allclose(float(lp), float(lf), rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-5),
        gp, gf)


def test_fused_head_loss_ignored_in_decode_mode():
    """Generation needs real logits: decode=True overrides the fused flag."""
    import dataclasses

    cfg = LlamaConfig.tiny(fused_head_loss=True)
    batch = make_batch()
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0), batch, train=False)["params"]
    dcfg = dataclasses.replace(cfg, decode=True, max_cache_len=32)
    dmodel = LlamaForCausalLM(dcfg)
    variables = dmodel.init(jax.random.PRNGKey(0), batch, train=False)
    out, _ = dmodel.apply(
        {"params": params, "cache": variables["cache"]}, batch, train=False,
        mutable=["cache"])
    assert isinstance(out, jax.Array)  # logits, not the fused dict
    assert out.shape[-1] == cfg.vocab_size


def test_predict_on_fused_model_returns_logits():
    """Trainer.predict is the one consumer that wants real logits — a
    fused-head model must still produce them there (train/step.py
    make_predict_step materializes hidden @ kernel)."""
    from distributeddeeplearningspark_tpu.train.step import make_predict_step

    cfg = LlamaConfig.tiny(fused_head_loss=True)
    model = LlamaForCausalLM(cfg)
    batch = make_batch()
    params = model.init(jax.random.PRNGKey(0), batch, train=False)["params"]

    class S:  # minimal TrainState stand-in
        pass

    state = S()
    state.params, state.mutable = params, {}
    logits = make_predict_step(model.apply)(state, batch)
    assert logits.shape == (2, 16, cfg.vocab_size)
    # matches the plain model's logits
    plain = LlamaForCausalLM(LlamaConfig.tiny()).apply(
        {"params": params}, batch, train=False)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(plain),
                               atol=2e-5, rtol=2e-5)


class TestLlamaPackedSegments:
    """Packed causal training with cross-document isolation (lm_dataset
    segment ids → LlamaAttention → flash/ring/xla)."""

    def test_lm_dataset_emits_segment_ids(self):
        from distributeddeeplearningspark_tpu.data import text as text_lib

        docs = text_lib.synthetic_wikipedia(16, num_partitions=2)
        tok = text_lib.WordPieceTokenizer.train(docs.collect(), vocab_size=512)
        ds = text_lib.lm_dataset(docs, tok, seq_len=64, segment_ids=True)
        exs = ds.take(3)
        for ex in exs:
            assert ex["segment_ids"].shape == (64,)
            # ids nondecreasing within a window except pads (-1 tail)
            sids = ex["segment_ids"]
            body = sids[sids >= 0]
            assert (np.diff(body) >= 0).all()
        # pads (if any) carry -1 exactly where loss_mask is 0
        for ex in exs:
            np.testing.assert_array_equal(ex["segment_ids"] == -1,
                                          ex["loss_mask"] == 0)

    def test_packed_forward_isolates_documents(self):
        """Causal attention with segment ids: doc 0's logits equal running
        doc 0 alone (absolute RoPE positions match at offsets 0..n)."""
        cfg = LlamaConfig.tiny()
        model = LlamaForCausalLM(cfg)
        rng = np.random.default_rng(11)
        ids = rng.integers(1, 500, (2, 32)).astype(np.int32)
        segs = np.zeros((2, 32), np.int32)
        segs[:, 20:] = 1
        batch = {"input_ids": ids}
        v = model.init(jax.random.PRNGKey(0), batch, train=False)
        packed = model.apply(v, {**batch, "segment_ids": segs}, train=False)
        alone = model.apply(v, {"input_ids": ids[:, :20]}, train=False)
        np.testing.assert_allclose(np.asarray(packed)[:, :20],
                                   np.asarray(alone), atol=2e-5, rtol=2e-5)
        # and doc 1 differs from the unisolated run
        plain = model.apply(v, batch, train=False)
        assert not np.allclose(np.asarray(packed)[:, 20:],
                               np.asarray(plain)[:, 20:])

    def test_packed_train_step_under_cp(self, eight_devices):
        """Segment ids ride the ring: packed batch trains on data=2 x seq=4
        with finite loss."""
        import dataclasses

        import optax

        from distributeddeeplearningspark_tpu.data.feed import (
            put_global, stack_examples)
        from distributeddeeplearningspark_tpu.ops import ring_attention as ring_mod
        from distributeddeeplearningspark_tpu.parallel.mesh import MeshSpec
        from distributeddeeplearningspark_tpu.parallel.sharding import ShardingRules
        from distributeddeeplearningspark_tpu.train import losses, step as step_lib

        mesh = MeshSpec(data=2, seq=4).build(eight_devices)
        ring_mod.set_default_mesh(mesh)
        cfg = dataclasses.replace(LlamaConfig.tiny(), attention_impl="ring",
                                  scan_layers=False, remat=False)
        model = LlamaForCausalLM(cfg)
        rng = np.random.default_rng(13)
        segs = np.zeros((4, 32), np.int32)
        segs[:, 16:] = 1
        batch = stack_examples([
            {"input_ids": rng.integers(1, 500, (32,)).astype(np.int32),
             "loss_mask": np.ones((32,), np.float32),
             "segment_ids": segs[i]}
            for i in range(4)])
        tx = optax.adamw(1e-3)
        state, shardings = step_lib.init_state(model, tx, batch, mesh,
                                               ShardingRules())
        step = step_lib.jit_train_step(
            step_lib.make_train_step(model.apply, tx, losses.causal_lm),
            mesh, shardings, seq_sharded=True)
        gbatch = put_global(batch, mesh, seq_sharded=True)
        state, metrics = step(state, gbatch)
        assert np.isfinite(float(jax.device_get(metrics["loss"])))


def test_pp_rejects_segment_ids(eight_devices):
    """PP stage forwards don't thread segment ids — must refuse loudly."""
    from distributeddeeplearningspark_tpu.models.llama_pp import make_pp_apply
    from distributeddeeplearningspark_tpu.parallel.mesh import MeshSpec

    mesh = MeshSpec(data=4, pipe=2).build()
    cfg = LlamaConfig.tiny()
    apply_fn = make_pp_apply(cfg, mesh, 2)
    model = LlamaForCausalLM(cfg)
    batch = {"input_ids": np.ones((4, 32), np.int32)}
    v = model.init(jax.random.PRNGKey(0), batch, train=False)
    with pytest.raises(NotImplementedError, match="segment_ids"):
        apply_fn(v, {**batch, "segment_ids": np.zeros((4, 32), np.int32)})
