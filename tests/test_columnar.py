"""Columnar shuffle transport + device-side segment-reduce (ISSUE 12).

The contract under test is byte-identity: the columnar planes, the
pickled-tuple path, and the device segment-reduce kernels are three data
planes for the SAME operation — every test here pins two or three of
them against each other, including the ugly corners (mixed-eligibility
buckets, forced spills, dtype edges, fabricated hash collisions) where a
format boundary could quietly reorder or retype a row.
"""

import multiprocessing as mp
import os
import pickle
import time

import numpy as np
import pytest

from distributeddeeplearningspark_tpu import status, telemetry
from distributeddeeplearningspark_tpu.data import exchange
from distributeddeeplearningspark_tpu.data.dataframe import DataFrame
from distributeddeeplearningspark_tpu.rdd import PartitionedDataset


@pytest.fixture
def _spill_here(tmp_path, monkeypatch):
    spill_root = tmp_path / "spill"
    spill_root.mkdir()
    monkeypatch.setenv(exchange.SPILL_DIR_ENV, str(spill_root))
    monkeypatch.delenv("DLS_DATA_WORKERS", raising=False)
    monkeypatch.delenv(exchange.MEM_MB_ENV, raising=False)
    monkeypatch.delenv(exchange.TRANSPORT_ENV, raising=False)
    yield spill_root


def _collect_parts(ds):
    return [list(ds.iter_partition(i)) for i in range(ds.num_partitions)]


def _pairs_ds(n=3000, kmod=97, nparts=4):
    data = [((i * 2654435761) % kmod, i % 13) for i in range(n)]
    chunks = [data[i::nparts] for i in range(nparts)]
    return PartitionedDataset.from_generators(
        [(lambda c=c: iter(c)) for c in chunks])


def _agg_df(n=6000, kmod=151, nparts=3, key_dtype=np.int64):
    k = ((np.arange(n) * 2654435761) % kmod).astype(key_dtype)
    v = (np.arange(n) % 29 - 14).astype(np.float64)
    chunks = []
    for i in range(nparts):
        sl = slice(i * n // nparts, (i + 1) * n // nparts)
        chunks.append({"k": k[sl].copy(), "v": v[sl].copy()})
    ds = PartitionedDataset.from_generators(
        [(lambda c=c: iter([c])) for c in chunks])
    return DataFrame(ds, ["k", "v"])


def _agg_bytes(df) -> bytes:
    chunks = [ch for p in range(df._chunks.num_partitions)
              for ch in df._chunks.iter_partition(p)]
    assert chunks, "empty result"
    return b"".join(
        np.ascontiguousarray(
            np.concatenate([np.atleast_1d(ch[c]) for ch in chunks])).tobytes()
        for c in sorted(chunks[0]))


def _spy_stats(monkeypatch) -> dict:
    """Capture the last run_exchange's stats without changing behavior."""
    seen: dict = {}
    orig = exchange.run_exchange

    def spy(*a, **kw):
        r = orig(*a, **kw)
        seen.update(r.stats)
        return r

    monkeypatch.setattr(exchange, "run_exchange", spy)
    return seen


# ---------------------------------------------------------------------------
# columnar ↔ tuple identity, all five wide ops, 0/1/4 workers
# ---------------------------------------------------------------------------

def test_all_five_wide_ops_columnar_tuple_identity(_spill_here):
    """The per-op identity sweep: serial (0), 1 and 4 workers, columnar
    (auto) vs forced tuple — every format lands identical output. The
    ops without a columnar plan (group_by_key, sort_by) are swept too:
    their 'columnar' run must be a byte-identical no-op fallback."""
    cases = {
        "reduce_by_key": lambda ds, nw, tr: _collect_parts(
            ds.reduce_by_key(lambda a, b: a + b, num_workers=nw,
                             combine="sum", transport=tr)),
        "group_by_key": lambda ds, nw, tr: _collect_parts(
            ds.group_by_key(num_workers=nw)),
        "sort_by": lambda ds, nw, tr: list(
            ds.sort_by(lambda kv: kv[0], num_workers=nw).collect()),
    }
    for name, run in cases.items():
        ref = run(_pairs_ds(), 0, "tuple")
        for nw in (1, 4):
            for tr in ("tuple", "columnar"):
                got = run(_pairs_ds(), nw, tr)
                assert got == ref, (name, nw, tr)
    # distinct: the serial path keeps first-occurrence order in ONE
    # partition by contract, so the exchange layouts compare against the
    # tuple-transport exchange run (plus set-identity with serial)
    def distinct_run(nw, tr):
        return _collect_parts(_pairs_ds().map(lambda kv: kv[0])
                              .distinct(num_workers=nw, transport=tr))

    serial = set(_pairs_ds().map(lambda kv: kv[0])
                 .distinct(num_workers=0).collect())
    ref = distinct_run(1, "tuple")
    assert {x for p in ref for x in p} == serial
    for nw in (1, 4):
        for tr in ("tuple", "columnar"):
            assert distinct_run(nw, tr) == ref, ("distinct", nw, tr)
    spec = {"v": "sum", "k": "count"}
    ref = _agg_bytes(_agg_df().groupBy("k").agg(spec, num_workers=0))
    for nw in (1, 4):
        for tr in ("tuple", "columnar"):
            got = _agg_bytes(_agg_df().groupBy("k").agg(
                spec, num_workers=nw, transport=tr))
            assert got == ref, ("groupBy.agg", nw, tr)


def test_columnar_is_the_auto_default_and_stats_say_so(
        _spill_here, monkeypatch):
    seen = _spy_stats(monkeypatch)
    _agg_bytes(_agg_df().groupBy("k").agg({"v": "sum"}, num_workers=2))
    assert seen["transport"] == "columnar"
    assert seen["columnar_pairs"] == seen["pairs_in"] > 0
    assert seen["tuple_pairs"] == 0
    assert seen["columnar_bytes"] > 0
    assert seen["columnar_buckets"] > 0 and seen["tuple_buckets"] == 0


def test_reduce_by_key_declared_combine_goes_columnar(
        _spill_here, monkeypatch):
    seen = _spy_stats(monkeypatch)
    out = _collect_parts(_pairs_ds().reduce_by_key(
        lambda a, b: a + b, num_workers=2, combine="sum"))
    assert seen["transport"] == "columnar"
    ref = _collect_parts(_pairs_ds().reduce_by_key(
        lambda a, b: a + b, num_workers=0))
    assert out == ref
    with pytest.raises(ValueError, match="combine"):
        _pairs_ds().reduce_by_key(lambda a, b: a + b, combine="prod")


# ---------------------------------------------------------------------------
# mixed eligibility: some buckets columnar, some degrade to tuple
# ---------------------------------------------------------------------------

def _string_keys_for_bucket(bucket: int, n_out: int, count: int) -> list:
    out = []
    i = 0
    while len(out) < count:
        s = f"tok{i}"
        if exchange.bucket_of(exchange.key_bytes((s,)), n_out) == bucket:
            out.append(s)
        i += 1
    return out


def test_mixed_eligibility_buckets_split_formats(_spill_here, monkeypatch):
    """A dataset whose string-key chunk hashes entirely into bucket 0:
    bucket 0 must degrade to tuple merging while the numeric buckets stay
    columnar — and the output must equal the all-tuple run exactly."""
    n_out = 3
    strs = _string_keys_for_bucket(0, n_out, 40)

    def mk():
        int_chunks = [{"k": np.arange(i * 400, (i + 1) * 400,
                                      dtype=np.int64),
                       "v": np.full(400, float(i + 1))} for i in range(2)]
        str_chunk = {"k": np.asarray(strs * 5),
                     "v": np.arange(len(strs) * 5, dtype=np.float64)}
        chunks = int_chunks + [str_chunk]
        ds = PartitionedDataset.from_generators(
            [(lambda c=c: iter([c])) for c in chunks])
        return DataFrame(ds, ["k", "v"])

    seen = _spy_stats(monkeypatch)
    spec = {"v": "sum", "k": "count"}
    got = _agg_bytes(mk().groupBy("k").agg(spec, num_workers=2))
    assert seen["transport"] == "mixed"
    assert seen["columnar_pairs"] > 0 and seen["tuple_pairs"] > 0
    assert seen["columnar_buckets"] >= 1, seen
    assert seen["tuple_buckets"] >= 1, seen
    ref = _agg_bytes(mk().groupBy("k").agg(spec, num_workers=2,
                                           transport="tuple"))
    assert got == ref


# ---------------------------------------------------------------------------
# spill ≡ memory
# ---------------------------------------------------------------------------

def test_columnar_spill_path_equals_in_memory(_spill_here, monkeypatch):
    spec = {"v": "sum", "k": "count"}
    big = _agg_bytes(_agg_df(n=120_000, kmod=119_993).groupBy("k").agg(
        spec, num_workers=2))
    monkeypatch.setenv(exchange.MEM_MB_ENV, "4")  # floor budget → spills
    seen = _spy_stats(monkeypatch)
    spilled = _agg_bytes(_agg_df(n=120_000, kmod=119_993).groupBy("k").agg(
        spec, num_workers=2))
    assert seen["transport"] == "columnar"
    assert seen["spills"] > 0, "4MB budget at 120k keys did not spill"
    assert spilled == big


# ---------------------------------------------------------------------------
# dtype-edge keys + hash collisions
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("dtype", [np.int32, np.int64, np.float32,
                                   np.float64])
def test_dtype_edge_keys_identity(_spill_here, dtype):
    spec = {"v": "sum", "k": "count"}
    ref = _agg_bytes(_agg_df(key_dtype=dtype).groupBy("k").agg(
        spec, num_workers=2, transport="tuple"))
    got = _agg_bytes(_agg_df(key_dtype=dtype).groupBy("k").agg(
        spec, num_workers=2, transport="columnar"))
    assert got == ref, dtype


def test_int_sums_past_int32_fall_back_to_arbitrary_precision(_spill_here):
    """Huge int values must NOT ride the int64 sum planes: the tuple
    path's python ints are arbitrary-precision, and a wrapped int64
    accumulator would be a silently wrong answer. Values past int32 fall
    back per batch; in-range values stay columnar and exact."""
    big = 2 ** 62
    ds = PartitionedDataset.parallelize([(1, big), (1, big)], 2)
    got = [kv for p in _collect_parts(ds.reduce_by_key(
        lambda a, b: a + b, num_workers=2, combine="sum")) for kv in p]
    assert got == [(1, 2 ** 63)]  # exact, not wrapped to -2**63
    # and the declared-combine columnar path still matches for sane ints
    ds2 = PartitionedDataset.parallelize(
        [(i % 7, 2 ** 31 - 1) for i in range(100)], 4)
    ref = _collect_parts(ds2.reduce_by_key(
        lambda a, b: a + b, num_workers=2, transport="tuple"))
    assert _collect_parts(PartitionedDataset.parallelize(
        [(i % 7, 2 ** 31 - 1) for i in range(100)], 4).reduce_by_key(
            lambda a, b: a + b, num_workers=2, combine="sum")) == ref


def test_signed_zero_float_keys_fall_back(_spill_here, monkeypatch):
    """-0.0 == 0.0 in a merge dict but pickles to different key bytes:
    batches containing a signed zero must stay on the tuple path so both
    transports sit on the same side of that documented caveat."""
    seen = _spy_stats(monkeypatch)

    def mk():
        chunks = [{"k": np.asarray([-0.0, 1.0, 2.0]),
                   "v": np.asarray([1.0, 2.0, 3.0])},
                  {"k": np.asarray([0.0, 1.0, 2.0]),
                   "v": np.asarray([4.0, 5.0, 6.0])}]
        ds = PartitionedDataset.from_generators(
            [(lambda c=c: iter([c])) for c in chunks])
        return DataFrame(ds, ["k", "v"])

    got = _agg_bytes(mk().groupBy("k").agg({"v": "sum"}, num_workers=2))
    assert seen["transport"] == "tuple"  # every zero-bearing batch fell back
    ref = _agg_bytes(mk().groupBy("k").agg({"v": "sum"}, num_workers=2,
                                           transport="tuple"))
    assert got == ref
    # nw=1 is the sharp case: one mapper's dict merges -0.0 with 0.0 —
    # a columnar 0.0 batch would miss that merge and emit two rows
    got1 = _agg_bytes(mk().groupBy("k").agg({"v": "sum"}, num_workers=1))
    ref1 = _agg_bytes(mk().groupBy("k").agg({"v": "sum"}, num_workers=1,
                                            transport="tuple"))
    assert got1 == ref1
    # rdd scalar path: same guard
    ds = PartitionedDataset.parallelize([(-0.0, 1), (0.0, 2)], 2)
    got_r = [kv for p in _collect_parts(ds.reduce_by_key(
        lambda a, b: a + b, num_workers=2, combine="sum")) for kv in p]
    ref_r = [kv for p in _collect_parts(PartitionedDataset.parallelize(
        [(-0.0, 1), (0.0, 2)], 2).reduce_by_key(
            lambda a, b: a + b, num_workers=2,
            transport="tuple")) for kv in p]
    assert got_r == ref_r


def test_bool_values_keep_their_type(_spill_here):
    """min/max over bool values must come back as bools (the tuple
    path's), never int64-plane 0/1 — bool values are tuple-path only."""
    ds = PartitionedDataset.parallelize([(1, True), (1, False)], 2)
    got = [kv for p in _collect_parts(ds.reduce_by_key(
        min, num_workers=2, combine="min")) for kv in p]
    assert got == [(1, False)]
    assert type(got[0][1]) is bool


def test_float_and_int_scalar_keys_reduce_by_key(_spill_here):
    def mk(cast):
        data = [(cast(i % 37), i) for i in range(2000)]
        return PartitionedDataset.parallelize(data, 4)

    for cast in (int, float):
        ref = _collect_parts(mk(cast).reduce_by_key(
            lambda a, b: a + b, num_workers=2, transport="tuple"))
        got = _collect_parts(mk(cast).reduce_by_key(
            lambda a, b: a + b, num_workers=2, combine="sum"))
        assert got == ref, cast


def test_hash_collisions_resolved_by_full_key_compare(
        _spill_here, monkeypatch):
    """Shrink the canonical hash to 8 BITS so distinct keys collide
    constantly on the key_hash column: the columnar path must fall back
    to full pickled-key comparison inside colliding runs and still match
    the tuple path byte for byte (which shares the same patched hash)."""
    def tiny_hash_key_bytes(key):
        import hashlib as _h

        kb = pickle.dumps(key, protocol=4)
        return (b"\x00" * 7 + _h.blake2b(kb, digest_size=1).digest() + kb)

    monkeypatch.setattr(exchange, "key_bytes", tiny_hash_key_bytes)
    spec = {"v": "sum", "k": "count"}
    # 151 distinct keys over 256 hash values → many guaranteed collisions
    ref = _agg_bytes(_agg_df().groupBy("k").agg(
        spec, num_workers=2, transport="tuple"))
    got = _agg_bytes(_agg_df().groupBy("k").agg(
        spec, num_workers=2, transport="columnar"))
    assert got == ref
    serial = _agg_bytes(_agg_df().groupBy("k").agg(spec, num_workers=0))
    assert got == serial


def test_combine_colliding_orders_by_full_key_bytes(monkeypatch):
    """Unit check of the collision fold itself: same fabricated hash for
    every key → output order must be the pickled-bytes order, combines
    still exact."""
    plan = exchange.reduce_pair_plan("sum")
    keys = np.array([300, 5, 300, 1000000, 5], dtype=np.int64)
    vals = np.array([1, 2, 3, 4, 5], dtype=np.int64)
    h = np.zeros(5, dtype=np.uint64)
    out = exchange.combine_planes(exchange._Planes(h, (keys,), (vals,)),
                                  plan)
    # the collision fold re-derives each key's FULL key_bytes and orders
    # by it (digest prefix + pickled tail — the tuple path's total order)
    expect = sorted({300: 4, 5: 7, 1000000: 4}.items(),
                    key=lambda t: exchange.key_bytes(t[0]))
    assert out.keys[0].tolist() == [k for k, _ in expect]
    assert out.vals[0].tolist() == [v for _, v in expect]


# ---------------------------------------------------------------------------
# exact plane metering (the _ByteMeter satellite)
# ---------------------------------------------------------------------------

def test_byte_meter_add_exact_charges_planes_verbatim():
    m = exchange._ByteMeter()
    m.add_exact(16 << 20)
    assert m.value == float(16 << 20)
    m.add_exact(100)
    assert m.value == float((16 << 20) + 100)
    # the sampled path is unchanged: 64 tiny adds charge ~estimate each,
    # nowhere near what one exact 16MB plane charges
    sampled = exchange._ByteMeter()
    for _ in range(64):
        sampled.add(b"x" * 32)
    assert sampled.value < 1 << 16
    m.reset()
    assert m.value == 0.0


def test_planes_nbytes_is_exact():
    pl = exchange._Planes(
        np.zeros(100, np.uint64), (np.zeros(100, np.int64),),
        (np.zeros(100, np.float64), np.zeros(100, np.int64)))
    assert pl.nbytes == 100 * 8 * 4


# ---------------------------------------------------------------------------
# device-side segment-reduce (data/device_agg.py)
# ---------------------------------------------------------------------------

def _fresh_device_agg(monkeypatch):
    from distributeddeeplearningspark_tpu.data import device_agg

    monkeypatch.setattr(device_agg, "_kernels", {})
    monkeypatch.setattr(device_agg, "_state", {"available": None})
    return device_agg


@pytest.mark.parametrize("spec", [
    {"v": "count"}, {"v": "sum"}, {"v": "min"}, {"v": "max"},
    {"v": "mean"}, {"v": "sum", "k": "count"},
])
def test_device_agg_matches_exchange_bit_exact(_spill_here, monkeypatch,
                                               spec):
    device_agg = _fresh_device_agg(monkeypatch)
    if not device_agg.available():
        pytest.skip("no jax device available")
    ref = _agg_bytes(_agg_df().groupBy("k").agg(spec, num_workers=2))
    got = _agg_bytes(_agg_df().groupBy("k").agg(spec, transport="device"))
    assert got == ref, spec
    serial = _agg_bytes(_agg_df().groupBy("k").agg(spec, num_workers=0))
    assert got == serial, spec


def test_device_agg_ledger_and_warm_repeat(_spill_here, monkeypatch,
                                           tmp_path):
    """Acceptance: device compiles appear in the PR 9 ledger and a warm
    repeat at the same shapes compiles nothing (no recompile flags)."""
    device_agg = _fresh_device_agg(monkeypatch)
    if not device_agg.available():
        pytest.skip("no jax device available")
    wd = tmp_path / "wd"
    telemetry.configure(str(wd))
    try:
        first = _agg_bytes(_agg_df().groupBy("k").agg(
            {"v": "sum", "k": "count"}, transport="device"))
        events = telemetry.read_events(str(wd))
        compiles = [e for e in events if e.get("kind") == "compile"
                    and str(e.get("fn", "")).startswith("device_agg.")]
        assert compiles, "no ledgered device_agg compiles"
        assert not any(e.get("recompile") for e in compiles), compiles
        n = len(compiles)
        again = _agg_bytes(_agg_df().groupBy("k").agg(
            {"v": "sum", "k": "count"}, transport="device"))
        assert again == first
        events = telemetry.read_events(str(wd))
        compiles2 = [e for e in events if e.get("kind") == "compile"
                     and str(e.get("fn", "")).startswith("device_agg.")]
        assert len(compiles2) == n, "warm repeat recompiled"
        # the device run lands the standard shuffle done event too
        done = [e for e in events if e.get("kind") == "shuffle"
                and e.get("edge") == "done"]
        assert done and done[-1]["transport"] == "device"
        rep = status.report(str(wd), anatomy=True)
        by_fn = rep["anatomy"]["compile_ledger"]["by_fn"]
        assert any(fn.startswith("device_agg.") for fn in by_fn), by_fn
    finally:
        telemetry.reset()


def test_device_agg_rejects_non_numeric_keys(_spill_here, monkeypatch):
    device_agg = _fresh_device_agg(monkeypatch)
    if not device_agg.available():
        pytest.skip("no jax device available")
    ds = PartitionedDataset.from_generators([lambda: iter(
        [{"k": np.asarray(["a", "b", "a"]),
          "v": np.asarray([1.0, 2.0, 3.0])}])])
    df = DataFrame(ds, ["k", "v"])
    g = df.groupBy("k").agg({"v": "sum"}, transport="device")
    with pytest.raises(ValueError, match="numeric"):
        list(g._chunks.iter_partition(0))


def test_top_v_matches_heap_semantics(monkeypatch):
    device_agg = _fresh_device_agg(monkeypatch)
    if not device_agg.available():
        pytest.skip("no jax device available")
    import heapq

    rng = np.random.default_rng(3)
    toks = np.asarray([f"t{i}" for i in range(5000)])
    cnts = rng.integers(1, 40, size=5000)  # heavy ties → tie-break matters
    tv = device_agg.TopV(50, block=512)
    for lo in range(0, 5000, 700):  # uneven update blocks
        tv.update(cnts[lo:lo + 700], toks[lo:lo + 700])
    got = tv.ranked()
    heap: list = []
    for c, t in zip(cnts.tolist(), toks.tolist()):
        item = (c, t)
        if len(heap) < 50:
            heapq.heappush(heap, item)
        elif item > heap[0]:
            heapq.heapreplace(heap, item)
    expect = sorted(heap, reverse=True)
    assert got == expect


def test_segment_reduce_kernels_exact(monkeypatch):
    device_agg = _fresh_device_agg(monkeypatch)
    if not device_agg.available():
        pytest.skip("no jax device available")
    v = np.asarray([1.5, 2.5, -3.0, 7.0, 0.25], np.float64)
    ids = np.asarray([0, 0, 1, 1, 2], np.int32)
    assert device_agg.segment_reduce("sum", v, ids, 3).tolist() == \
        [4.0, 4.0, 0.25]
    assert device_agg.segment_reduce("min", v, ids, 3).tolist() == \
        [1.5, -3.0, 0.25]
    assert device_agg.segment_reduce("max", v, ids, 3).tolist() == \
        [2.5, 7.0, 0.25]
    c = np.asarray([1, 1, 1, 1, 1], np.int64)
    assert device_agg.segment_reduce("sum", c, ids, 3).tolist() == [2, 2, 1]


# ---------------------------------------------------------------------------
# telemetry / dlstatus per-format rows
# ---------------------------------------------------------------------------

def test_shuffle_per_format_rows_in_dlstatus(_spill_here, tmp_path):
    wd = tmp_path / "wd"
    telemetry.configure(str(wd))
    try:
        spec = {"v": "sum"}
        _agg_bytes(_agg_df().groupBy("k").agg(spec, num_workers=2))
        _agg_bytes(_agg_df().groupBy("k").agg(spec, num_workers=2,
                                              transport="tuple"))
    finally:
        telemetry.reset()
    sh = status.shuffle_from(telemetry.read_events(str(wd)))
    assert sh is not None
    fmts = sh["formats"]
    assert fmts["columnar"]["pairs"] > 0
    assert fmts["columnar"]["bytes"] > 0
    assert fmts["columnar"]["buckets"] > 0
    assert fmts["tuple"]["pairs"] > 0  # the forced-tuple second run
    assert (fmts["columnar"]["pairs"] + fmts["tuple"]["pairs"]
            == sh["pairs_in"])
    assert (fmts["columnar"]["bytes"] + fmts["tuple"]["bytes"]
            == sh["bytes_moved"])
    assert sh["last"]["transport"] == "tuple"  # newest run was forced
    # rendered text carries the per-format line
    rep = status.report(str(wd))
    text = status.render(rep)
    assert "by format" in text and "columnar:" in text


def test_resolve_transport_validation(monkeypatch):
    monkeypatch.delenv(exchange.TRANSPORT_ENV, raising=False)
    assert exchange.resolve_transport(None) == "auto"
    assert exchange.resolve_transport("tuple") == "tuple"
    monkeypatch.setenv(exchange.TRANSPORT_ENV, "columnar")
    assert exchange.resolve_transport(None) == "columnar"
    monkeypatch.setenv(exchange.TRANSPORT_ENV, "device")
    assert exchange.resolve_transport(None) == "auto"  # env device, rdd op
    assert exchange.resolve_transport(
        None, allow_device=True) == "device"
    with pytest.raises(ValueError, match="device"):
        exchange.resolve_transport("device")  # explicit on an rdd op
    with pytest.raises(ValueError, match="unknown"):
        exchange.resolve_transport("arrow")


def test_no_child_or_shm_leaks_after_columnar_runs(_spill_here):
    _agg_bytes(_agg_df().groupBy("k").agg({"v": "sum"}, num_workers=2))
    deadline = time.time() + 5.0
    while time.time() < deadline:
        if not [p for p in mp.active_children()
                if p.name.startswith("dlsx-")]:
            break
        time.sleep(0.05)
    assert not [p for p in mp.active_children()
                if p.name.startswith("dlsx-")]
    if os.path.isdir("/dev/shm"):
        mine = [f for f in os.listdir("/dev/shm")
                if f.startswith(f"dlsx-{os.getpid()}-")]
        assert not mine, mine
    import gc

    gc.collect()
