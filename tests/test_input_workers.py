"""Multi-process input pipeline (data/workers.py — ISSUE 5).

The three contracts under test:

- determinism: the batch stream is byte-identical for num_workers 0/1/4,
  across the vision (JPEG), record, batched-fused, and text paths, and
  across a checkpoint fast-forward resume;
- crash propagation: a worker that raises or dies surfaces a typed
  WorkerCrashed in the consumer within a bounded wait, with no orphaned
  processes or leaked shared-memory segments — including on plain
  interpreter exit without close();
- backpressure: the per-worker in-flight window (metadata queue + byte
  ring) stays bounded under a slow consumer, with zero overflow when the
  consumer releases views promptly.
"""

import multiprocessing as mp
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from distributeddeeplearningspark_tpu.data import workers as W
from distributeddeeplearningspark_tpu.data.feed import host_batches
from distributeddeeplearningspark_tpu.data.workers import (
    WorkerCrashed, WorkerMappedDataset, WorkerPool, _Arena, _split_budget,
    pool_gauges, resolve_num_workers)
from distributeddeeplearningspark_tpu.rdd import PartitionedDataset

pytestmark = pytest.mark.skipif(
    not W.fork_available(), reason="worker pool needs the fork start method")


def _assert_no_leaks():
    """No dls worker processes or dlsw shm segments survive."""
    deadline = time.time() + 5.0
    while time.time() < deadline:
        kids = [p for p in mp.active_children()
                if p.name.startswith("dls-worker")]
        if not kids:
            break
        time.sleep(0.05)
    assert not [p for p in mp.active_children()
                if p.name.startswith("dls-worker")]
    if os.path.isdir("/dev/shm"):
        mine = [f for f in os.listdir("/dev/shm")
                if f.startswith(f"dlsw-{os.getpid()}-")]
        assert not mine, mine


# ---------------------------------------------------------------------------
# unit: budget split, env resolution, byte ring
# ---------------------------------------------------------------------------

def test_resolve_num_workers_env(monkeypatch):
    assert resolve_num_workers(3) == 3
    assert resolve_num_workers(0) == 0
    monkeypatch.delenv(W.WORKERS_ENV, raising=False)
    assert resolve_num_workers(None) == 0
    monkeypatch.setenv(W.WORKERS_ENV, "4")
    assert resolve_num_workers(None) == 4
    # explicit beats env; garbage env is ignored with a warning
    assert resolve_num_workers(1) == 1
    monkeypatch.setenv(W.WORKERS_ENV, "lots")
    with pytest.warns(UserWarning):
        assert resolve_num_workers(None) == 0


def test_split_budget_totals_and_floor():
    # budget >= P: exact total, spread round-robin
    assert [_split_budget(8, 4, i) for i in range(4)] == [2, 2, 2, 2]
    assert [_split_budget(5, 4, i) for i in range(4)] == [2, 1, 1, 1]
    # 0 < budget < P rounds UP to one per partition (a serial partition
    # would gate the whole round-robin interleave)
    assert [_split_budget(2, 4, i) for i in range(4)] == [1, 1, 1, 1]
    assert [_split_budget(0, 4, i) for i in range(4)] == [0, 0, 0, 0]


class TestArena:
    def test_alloc_free_coalesce(self):
        a = _Arena(100)
        assert a.try_alloc(0, 40) == 0
        assert a.try_alloc(1, 40) == 40
        assert a.try_alloc(2, 30) is None  # only 20 left
        a.free(0)
        assert a.try_alloc(2, 30) == 0  # first-fit reuses the hole
        assert a.used == 100 - 10 - 20  # 30 + 40 live, [30,40)+[80,100) free

    def test_out_of_order_free_is_reusable(self):
        """The consumer's hold pattern: the OLDEST allocations (a batch's
        first views) stay live while everything after them churns — frees
        behind a live tail must still be reusable (the FIFO-ring design
        this replaced wedged full here and fell back to pickling)."""
        a = _Arena(100)
        assert a.try_alloc(0, 20) == 0  # held view (batch head)
        ids = 1
        for _ in range(50):  # churn far past capacity while id 0 is held
            got = a.try_alloc(ids, 40)
            assert got is not None and got >= 20
            a.free(ids)
            ids += 1
        a.free(0)
        assert a.used == 0

    def test_free_intervals_coalesce_both_sides(self):
        a = _Arena(90)
        assert a.try_alloc(0, 30) == 0
        assert a.try_alloc(1, 30) == 30
        assert a.try_alloc(2, 30) == 60
        a.free(0)
        a.free(2)
        a.free(1)  # merges with both neighbors
        assert a._free == [[0, 90]]
        assert a.try_alloc(3, 90) == 0

    def test_oversized_is_refused(self):
        a = _Arena(64)
        assert a.try_alloc(0, 65) is None
        assert a.try_alloc(1, 0) is None


# ---------------------------------------------------------------------------
# pool core: ordering, transport, gauges
# ---------------------------------------------------------------------------

def test_ordered_delivery_and_shm_transport():
    n = 41
    src = lambda: ({"plane": np.full((32, 32, 3), i % 251, np.uint8),
                    "label": np.int32(i)} for i in range(n))
    fn = lambda ex: {**ex, "plane": ex["plane"].astype(np.float32) / 255.0}
    pool = WorkerPool(src, fn, 3)
    got = list(pool.stream())
    want = [fn(e) for e in src()]
    assert len(got) == n
    for a, b in zip(got, want):
        assert int(a["label"]) == int(b["label"])  # inline (queue) path
        assert np.asarray(a["plane"]).tobytes() == b["plane"].tobytes()
    _assert_no_leaks()


def test_non_dict_results_unwrap():
    pool = WorkerPool(lambda: iter(range(10)),
                      lambda x: np.full(200, x, np.int32), 2)
    got = list(pool.stream())
    assert [int(g[0]) for g in got] == list(range(10))
    # both transports: 200×i32=800B rides shm, tiny arrays ride the queue
    pool2 = WorkerPool(lambda: iter(range(7)),
                       lambda x: np.int32(x * 2), 2)
    assert [int(v) for v in pool2.stream()] == [0, 2, 4, 6, 8, 10, 12]
    _assert_no_leaks()


def test_gauges_shape():
    pool = WorkerPool(lambda: iter(range(30)),
                      lambda x: {"v": np.full(400, x, np.float32)}, 2)
    s = pool.stream()
    for _ in range(10):
        next(s)
    g = pool.gauges()
    assert g["workers"] == 2 and len(g["per_worker"]) == 2
    agg = pool_gauges()
    assert agg["input_workers"] == 2
    assert set(agg) >= {"worker_util_mean", "worker_util_min",
                        "worker_items", "worker_overflow",
                        "worker_ahead_mean", "worker_ring_used_mb"}
    s.close()
    assert pool_gauges() == {}  # closed pools drop out of the rollup
    _assert_no_leaks()


# ---------------------------------------------------------------------------
# crash propagation
# ---------------------------------------------------------------------------

def test_worker_exception_propagates_typed():
    def boom(x):
        if x == 11:
            raise ValueError("poisoned example")
        return {"v": np.full(300, x, np.float32)}

    pool = WorkerPool(lambda: iter(range(40)), boom, 2)
    t0 = time.monotonic()
    with pytest.raises(WorkerCrashed) as ei:
        list(pool.stream())
    assert time.monotonic() - t0 < 30.0  # bounded wait
    assert "poisoned example" in str(ei.value)  # original traceback forwarded
    assert ei.value.worker in (0, 1)
    _assert_no_leaks()


def test_worker_sigkill_respawns_byte_identical():
    """A SIGKILL'd worker respawns (ISSUE 14): the replacement takes over
    the residue class fast-forwarded past what was already delivered, so
    the stream completes with EXACTLY the bytes of an unfaulted run, and
    the respawn leaves a `recovery` telemetry event."""
    from distributeddeeplearningspark_tpu import telemetry

    def work(x):
        time.sleep(0.002)
        return {"v": np.full(300, x, np.float32)}

    n = 400
    ref = [work(x)["v"].tobytes() for x in range(n)]
    events = []
    orig_emit = telemetry.emit
    telemetry.emit = lambda kind, **f: events.append({"kind": kind, **f})
    try:
        pool = WorkerPool(lambda: iter(range(n)), work, 2)
        s = pool.stream()
        got = [next(s)["v"].tobytes()]
        os.kill(pool._procs[0].pid, signal.SIGKILL)
        t0 = time.monotonic()
        for ex in s:
            got.append(ex["v"].tobytes())
        assert time.monotonic() - t0 < 30.0
    finally:
        telemetry.emit = orig_emit
    assert got == ref  # ordered, byte-identical despite the kill
    rec = [e for e in events if e["kind"] == "recovery"
           and e.get("event") == "input-worker-respawn"]
    assert len(rec) == 1 and rec[0]["worker"] == 0
    assert rec[0]["exitcode"] == -signal.SIGKILL
    _assert_no_leaks()


def test_worker_sigkill_escalates_when_budget_exhausted(monkeypatch):
    """With the respawn budget at 0, a dead worker is the old typed
    CRASH — bounded wait, exitcode preserved, full teardown."""
    monkeypatch.setenv("DLS_DATA_WORKER_MAX_RETRIES", "0")

    def work(x):
        time.sleep(0.01)
        return {"v": np.full(300, x, np.float32)}

    pool = WorkerPool(lambda: iter(range(10_000)), work, 2)
    s = pool.stream()
    next(s)
    victim = pool._procs[0]
    os.kill(victim.pid, signal.SIGKILL)
    t0 = time.monotonic()
    with pytest.raises(WorkerCrashed) as ei:
        for _ in s:
            pass
    assert time.monotonic() - t0 < 30.0
    assert ei.value.exitcode == -signal.SIGKILL
    assert "died" in str(ei.value)
    _assert_no_leaks()


def test_worker_repeated_kills_exhaust_budget():
    """Each respawn burns budget; kills past DLS_DATA_WORKER_MAX_RETRIES
    escalate. (Kill the same slot every time a replacement appears.)"""
    def work(x):
        time.sleep(0.005)
        return {"v": np.full(300, x, np.float32)}

    pool = WorkerPool(lambda: iter(range(10_000)), work, 2, max_retries=1)
    s = pool.stream()
    next(s)
    with pytest.raises(WorkerCrashed):
        killed = pool._procs[0]
        os.kill(killed.pid, signal.SIGKILL)
        for _ in s:
            if pool._procs[0] is not killed:  # replacement is up: kill it
                killed = pool._procs[0]
                os.kill(killed.pid, signal.SIGKILL)
    _assert_no_leaks()


def test_interpreter_exit_leaks_nothing(tmp_path):
    """A script that abandons a live pool mid-stream must still exit
    cleanly, reap its workers (daemon), and leave no shm segment behind
    (finalize/atexit + resource tracker)."""
    script = r"""
import numpy as np, sys
from distributeddeeplearningspark_tpu.data.workers import WorkerPool
pool = WorkerPool(lambda: iter(range(10_000)),
                  lambda x: {"v": np.full(500, x, np.float32)}, 2)
s = pool.stream()
for _ in range(5):
    next(s)
print("pid", __import__("os").getpid())
"""
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    out = subprocess.run([sys.executable, "-c", script], env=env,
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr[-2000:]
    pid = int(out.stdout.split()[-1])
    if os.path.isdir("/dev/shm"):
        left = [f for f in os.listdir("/dev/shm")
                if f.startswith(f"dlsw-{pid}-")]
        assert not left, left


# ---------------------------------------------------------------------------
# backpressure
# ---------------------------------------------------------------------------

def test_bounded_inflight_under_slow_consumer():
    pool = WorkerPool(lambda: iter(range(500)),
                      lambda x: {"v": np.full(300, x, np.float32)},
                      1, max_ahead=4)
    s = pool.stream()
    consumed = 0
    for _ in range(6):
        next(s)
        consumed += 1
        time.sleep(0.05)  # slow consumer; views dropped promptly
        g = pool.gauges()["per_worker"][0]
        # produced never runs past consumed + queue bound (+1 handoff)
        assert g["items"] <= consumed + 4 + 1, g
    # give the worker a beat: it must be parked at the bound, not running on
    time.sleep(0.3)
    g = pool.gauges()["per_worker"][0]
    assert g["items"] <= consumed + 4 + 1, g
    assert g["overflow"] == 0
    s.close()
    _assert_no_leaks()


def test_ring_backpressure_overflows_not_deadlocks():
    """A consumer that HOLDS every view (worst case) exceeds a tiny ring;
    the pool must degrade to queue transport (overflow gauge), never
    deadlock, and the stream must stay correct and ordered."""
    pool = WorkerPool(
        lambda: iter(range(40)),
        lambda x: {"v": np.full((64, 64), x, np.float32)},  # 16 KB each
        1, ring_bytes=1 << 20, max_ahead=8)
    held = list(pool.stream())  # holds all 40 views: 640 KB < ring, ok…
    assert [int(h["v"][0, 0]) for h in held] == list(range(40))
    # …now an actually-too-small ring: 3 examples fill it
    pool2 = WorkerPool(
        lambda: iter(range(12)),
        lambda x: {"v": np.full((128, 128, 3), x, np.float32)},  # 196 KB
        1, ring_bytes=1 << 19, max_ahead=4)
    t0 = time.monotonic()
    held2 = list(pool2.stream())
    assert time.monotonic() - t0 < 60.0
    assert [int(h["v"][0, 0, 0]) for h in held2] == list(range(12))
    _assert_no_leaks()


# ---------------------------------------------------------------------------
# WorkerMappedDataset + feed integration
# ---------------------------------------------------------------------------

def _toy_base(n=60, parts=3):
    return PartitionedDataset.parallelize(
        [{"x": np.full((16, 16), i, np.float32), "label": np.int32(i)}
         for i in range(n)], parts)


def _tf(ex):
    return {"x": ex["x"] * 2.0 + 1.0, "label": ex["label"]}


def test_worker_mapped_dataset_parity_and_fallback():
    base = _toy_base()
    serial = [[_tf(e) for e in base.iter_partition(i)] for i in range(3)]
    for nw in (0, 1, 4):
        ds = WorkerMappedDataset(base, _tf, nw)
        assert ds.num_partitions == 3
        assert ds.is_infinite is False
        for i in range(3):
            got = list(ds.iter_partition(i))
            assert len(got) == len(serial[i])
            for a, b in zip(got, serial[i]):
                assert np.asarray(a["x"]).tobytes() == b["x"].tobytes()
                assert int(a["label"]) == int(b["label"])
    _assert_no_leaks()


def test_host_batches_num_workers_knob():
    base = _toy_base(48, 2)
    ds = WorkerMappedDataset(base, _tf, 0)  # dataset says serial
    ref = list(host_batches(ds, 8))
    # the feed knob overrides the dataset's setting; bytes must not change
    got = list(host_batches(ds, 8, num_workers=3))
    assert len(ref) == len(got) == 6
    for a, b in zip(ref, got):
        for k in a:
            assert a[k].tobytes() == b[k].tobytes()
    # plain datasets ignore the knob (nothing to fan out)
    plain = base.map(_tf)
    got2 = list(host_batches(plain, 8, num_workers=3))
    for a, b in zip(ref, got2):
        for k in a:
            assert a[k].tobytes() == b[k].tobytes()
    _assert_no_leaks()


def test_fast_forward_resume_parity():
    """Trainer resume burns host batches with islice: batch k..k+2 of a
    fast-forwarded pooled feed must equal the uninterrupted stream's."""
    import itertools

    base = _toy_base(96, 2)
    ds = WorkerMappedDataset(base, _tf, 2)
    straight = list(itertools.islice(host_batches(ds, 8), 8))
    resumed = list(itertools.islice(host_batches(ds, 8), 5, 8))
    for a, b in zip(straight[5:], resumed):
        for k in a:
            assert a[k].tobytes() == b[k].tobytes()
    _assert_no_leaks()


def test_probe_snapshot_carries_worker_gauges():
    from distributeddeeplearningspark_tpu.data.prefetch import StarvationProbe

    base = _toy_base(40, 2)
    ds = WorkerMappedDataset(base, _tf, 2)
    probe = StarvationProbe()
    feed = host_batches(ds, 8)
    next(feed)
    snap = probe.snapshot()
    assert snap["input_workers"] == 2
    assert 0.0 <= snap["worker_util_mean"] <= 1.0
    assert snap["worker_items"] >= 8
    feed.close()
    # with no live pool the keys disappear (non-worker runs emit nothing new)
    assert "input_workers" not in probe.snapshot()
    _assert_no_leaks()


def test_dlstatus_reports_input_workers(tmp_path):
    from distributeddeeplearningspark_tpu import status, telemetry

    w = telemetry.EventWriter(str(tmp_path), process=0, host=0)
    w.step_metrics(10, steps=10, lap_s=1.0, metrics={"loss": 1.0},
                   input_wait_s=0.0, input_workers=4, worker_util_mean=0.97,
                   worker_util_min=0.91, worker_items=640,
                   worker_overflow=0, worker_ahead_mean=3.5,
                   worker_ring_used_mb=12.0)
    w.close()
    rep = status.report(str(tmp_path))
    assert rep["input_workers"]["input_workers"] == 4
    text = status.render(rep)
    assert "input workers: 4 process(es)" in text
    assert "util mean=0.97" in text
    assert "verdict:" in text


# ---------------------------------------------------------------------------
# real-path determinism: vision JPEG, records, batched-fused, text
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def jpeg_root(tmp_path_factory):
    from PIL import Image

    root = tmp_path_factory.mktemp("jpegs")
    rng = np.random.default_rng(0)
    for cls in range(2):
        d = root / f"class_{cls}"
        d.mkdir()
        for i in range(8):
            arr = rng.integers(0, 255, (72, 88, 3), np.uint8)
            Image.fromarray(arr).save(str(d / f"img_{i}.jpg"), quality=90)
    return str(root)


# Quarantine of the environmental byte-identity flake (SMOKE_LOG/ROADMAP:
# fails identically on clean HEAD and polluted every tier-1 read). Probed
# root cause on the shared CI box: the THREAD-POOL in-process arm
# (``map_parallel`` with its default thread count) is nondeterministic
# RUN-TO-RUN — concurrent native-kernel invocations race — while decode and
# transform are bit-stable called sequentially, and BOTH the truly serial
# map (``num_threads=0``) and the worker-pool arm (any width) reproduce
# exactly and agree byte-for-byte. The determinism tests therefore use the
# serial map as the in-process reference: the contract under test is
# pipeline alignment across worker counts, not the thread pool's scheduling.
_SERIAL_MAP = {"num_threads": 0}


def _take_batches(feed, n):
    return [next(feed) for _ in range(n)]


def test_vision_jpeg_path_byte_identical_across_workers(jpeg_root):
    from distributeddeeplearningspark_tpu.data.sources import imagenet_folder
    from distributeddeeplearningspark_tpu.data.vision import imagenet_train

    def batches(nw):
        ds = imagenet_train(
            imagenet_folder(jpeg_root, num_partitions=2, decode=False),
            seed=0, size=48, repeat=True, num_workers=nw,
            **(_SERIAL_MAP if nw == 0 else {}))
        feed = host_batches(ds, 8)
        out = _take_batches(feed, 3)
        feed.close()
        return out

    b0, b1, b4 = batches(0), batches(1), batches(4)
    for x, y, z in zip(b0, b1, b4):
        assert x.keys() == y.keys() == z.keys()
        for k in x:
            assert (x[k].tobytes() == np.asarray(y[k]).tobytes()
                    == np.asarray(z[k]).tobytes()), k
    _assert_no_leaks()


def test_records_and_batched_fused_byte_identical(jpeg_root, tmp_path):
    from distributeddeeplearningspark_tpu.data.records import (
        array_records, write_imagenet_records)
    from distributeddeeplearningspark_tpu.data.vision import (
        imagenet_train, imagenet_train_batched)

    rec = str(tmp_path / "recs")
    write_imagenet_records(jpeg_root, rec, size=56, num_shards=2)

    def per_example(nw):
        feed = host_batches(
            imagenet_train(array_records(rec), seed=0, size=48, repeat=True,
                           num_workers=nw,
                           **(_SERIAL_MAP if nw == 0 else {})), 8)
        out = _take_batches(feed, 3)
        feed.close()
        return out

    def fused(nw):
        feed = imagenet_train_batched(
            array_records(rec).shuffle(0).repeat(), 8, size=48, seed=0,
            num_workers=nw)
        out = _take_batches(feed, 3)
        feed.close()
        return out

    for a, b in zip(per_example(0), per_example(4)):
        for k in a:
            assert a[k].tobytes() == np.asarray(b[k]).tobytes(), k
    for a, b in zip(fused(0), fused(2)):
        for k in a:
            assert a[k].tobytes() == np.asarray(b[k]).tobytes(), k
    _assert_no_leaks()


def test_text_tokenize_paths_byte_identical():
    from distributeddeeplearningspark_tpu.data.text import (
        WordPieceTokenizer, lm_dataset, mlm_dataset, synthetic_wikipedia)

    docs = synthetic_wikipedia(20, num_partitions=2)
    tok = WordPieceTokenizer.train(docs.collect(), vocab_size=256)
    builders = [
        lambda nw: mlm_dataset(docs, tok, seq_len=32, segment_ids=True,
                               num_workers=nw),
        lambda nw: mlm_dataset(docs, tok, seq_len=32, pack=False,
                               num_workers=nw),
        lambda nw: lm_dataset(docs, tok, seq_len=32, segment_ids=True,
                              num_workers=nw),
    ]
    for build in builders:
        ref = [e for i in range(2) for e in build(0).iter_partition(i)]
        pooled = [e for i in range(2) for e in build(3).iter_partition(i)]
        assert len(ref) == len(pooled) > 0
        for a, b in zip(ref, pooled):
            assert a.keys() == b.keys()
            for k in a:
                assert (np.asarray(a[k]).tobytes()
                        == np.asarray(b[k]).tobytes()), k
    _assert_no_leaks()
