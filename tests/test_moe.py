"""Mixture-of-Experts FFN + expert parallelism (beyond-contract EP).

The dense one-hot dispatch must be a faithful router: every kept token's
output is a convex combination of its chosen experts' FFN outputs, capacity
drops fall through to the residual, E=1 reduces to a plain SwiGLU, and the
whole thing trains under a data × expert mesh with the stacked expert
kernels genuinely sharded."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributeddeeplearningspark_tpu.models import LlamaConfig, LlamaForCausalLM
from distributeddeeplearningspark_tpu.models.moe import MoEMLP
from distributeddeeplearningspark_tpu.parallel.mesh import MeshSpec
from distributeddeeplearningspark_tpu.train import losses, step as step_lib


def _x(b=2, s=8, h=16, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.normal(0, 1, (b, s, h)).astype(np.float32))


class TestMoEMLP:
    def test_shapes_and_finite(self):
        x = _x()
        m = MoEMLP(16, 32, num_experts=4, top_k=2, dtype=jnp.float32)
        v = m.init(jax.random.PRNGKey(0), x)
        y, (aux, dropped) = m.apply(v, x)
        assert y.shape == x.shape and y.dtype == x.dtype
        assert np.isfinite(np.asarray(y)).all()
        assert np.isfinite(float(aux)) and float(aux) > 0
        assert 0.0 <= float(dropped) <= 1.0

    def test_single_expert_matches_dense_swiglu(self):
        """E=1, top_k=1, ample capacity: routing is the identity, so the
        MoE output must equal the plain SwiGLU with the same kernels."""
        x = _x(seed=1)
        m = MoEMLP(16, 32, num_experts=1, top_k=1, capacity_factor=2.0,
                   dtype=jnp.float32)
        v = m.init(jax.random.PRNGKey(1), x)
        y, (aux, dropped) = m.apply(v, x)
        # ample capacity, one expert: nothing can drop
        assert float(dropped) == 0.0
        p = v["params"]
        g = np.asarray(x) @ np.asarray(p["w_gate"][0])
        u = np.asarray(x) @ np.asarray(p["w_up"][0])
        silu = g * (1 / (1 + np.exp(-g)))
        want = (silu * u) @ np.asarray(p["w_down"][0])
        np.testing.assert_allclose(np.asarray(y), want, atol=1e-4, rtol=1e-4)
        # single expert: perfectly "balanced" → aux = E · 1 · 1 = 1
        assert abs(float(aux) - 1.0) < 1e-5

    def test_capacity_drop_falls_through(self):
        """capacity_factor → tiny: most tokens are dropped; dropped tokens
        must output ZERO (the residual carries them), never garbage."""
        x = _x(b=1, s=16, seed=2)
        m = MoEMLP(16, 32, num_experts=2, top_k=1, capacity_factor=0.07,
                   dtype=jnp.float32)  # cap = max(1, int(.07*16/2)) = 1
        v = m.init(jax.random.PRNGKey(2), x)
        y, (_, dropped) = m.apply(v, x)
        y = np.asarray(y)[0]
        zero_rows = (np.abs(y).max(axis=-1) < 1e-7).sum()
        assert zero_rows >= 16 - 2 * 1  # at most cap tokens per expert kept
        # the honesty metric must agree with what actually fell through:
        # ≥ 14 of 16 top-1 assignments dropped (r3 weak-#4)
        assert float(dropped) >= (16 - 2) / 16

    def test_top_k_bounds_checked(self):
        with pytest.raises(ValueError, match="top_k"):
            MoEMLP(16, 32, num_experts=2, top_k=3).init(
                jax.random.PRNGKey(0), _x())

    def test_group_size_equal_to_seq_is_identity(self):
        """group_size = S regroups [B, S] into B groups of S — exactly the
        default per-sequence grouping, so outputs must match bit-for-bit
        (same einsums, same capacity, same drops)."""
        x = _x(b=2, s=8, seed=3)
        base = MoEMLP(16, 32, num_experts=4, top_k=2, dtype=jnp.float32)
        grouped = MoEMLP(16, 32, num_experts=4, top_k=2, group_size=8,
                         dtype=jnp.float32)
        v = base.init(jax.random.PRNGKey(3), x)
        y0, (a0, d0) = base.apply(v, x)
        y1, (a1, d1) = grouped.apply(v, x)
        np.testing.assert_array_equal(np.asarray(y0), np.asarray(y1))
        assert float(a0) == float(a1) and float(d0) == float(d1)

    def test_group_size_invariant_when_capacity_ample(self):
        """E=1 top-1 with ample capacity: every token goes to the only
        expert with gate 1 and nothing drops, so the output equals the
        dense SwiGLU no matter how tokens are grouped — the correctness
        contract that lets group_size be a pure cost knob."""
        x = _x(b=2, s=8, seed=4)
        v = None
        outs = []
        for g in (0, 2, 4, 16):  # 16 = B·S: one global group
            m = MoEMLP(16, 32, num_experts=1, top_k=1, capacity_factor=2.0,
                       group_size=g, dtype=jnp.float32)
            v = v or m.init(jax.random.PRNGKey(4), x)
            y, (_, dropped) = m.apply(v, x)
            assert float(dropped) == 0.0
            outs.append(np.asarray(y))
        for y in outs[1:]:
            np.testing.assert_allclose(y, outs[0], atol=1e-5, rtol=1e-5)

    def test_group_size_must_divide_tokens(self):
        with pytest.raises(ValueError, match="group_size"):
            MoEMLP(16, 32, num_experts=2, group_size=5).init(
                jax.random.PRNGKey(0), _x(b=2, s=8))

    def test_small_groups_can_only_drop_more(self):
        """Capacity enforced per group is a tighter constraint than per
        sequence: at tight capacity the grouped router's drop fraction
        must be ≥ the per-sequence one. QUALIFIED claim (ADVICE r4): this
        holds when cf·g·k/E ≥ 1; below that the ≥1 capacity floor gives
        tiny groups a full slot per expert and the inequality can flip.
        The shape here is checked to sit in the valid regime so the test
        can't silently rely on the floor."""
        x = _x(b=1, s=16, seed=5)
        kw = dict(num_experts=2, top_k=1, capacity_factor=0.5,
                  dtype=jnp.float32)
        g = 4
        assert kw["capacity_factor"] * g * kw["top_k"] / kw["num_experts"] >= 1
        base = MoEMLP(16, 32, **kw)
        v = base.init(jax.random.PRNGKey(5), x)
        _, (_, d_seq) = base.apply(v, x)
        _, (_, d_grp) = MoEMLP(16, 32, group_size=g, **kw).apply(v, x)
        assert float(d_grp) >= float(d_seq) - 1e-9

    def test_capacity_floor_below_regime_boundary(self):
        """The other side of the qualified claim: with cf·g·k/E < 1 the
        ≥1 floor is active — per-group capacity is 1 per expert and the
        aggregate across groups EXCEEDS the per-sequence cap, so tiny
        groups may drop fewer tokens. Pins the documented boundary so a
        future capacity rework that changes the semantics fails loudly."""
        # cf·g·k/E = 0.5·2·1/4 = 0.25 < 1 → floor active, cap=1/group
        # aggregate grouped capacity: (16/2 groups)·4 experts·1 = 32 slots
        # vs per-sequence cap max(1, int(0.5·16·1/4)) = 2 slots·... = 8
        x = _x(b=1, s=16, seed=6)
        kw = dict(num_experts=4, top_k=1, capacity_factor=0.5,
                  dtype=jnp.float32)
        base = MoEMLP(16, 32, **kw)
        v = base.init(jax.random.PRNGKey(6), x)
        _, (_, d_seq) = base.apply(v, x)
        _, (_, d_grp) = MoEMLP(16, 32, group_size=2, **kw).apply(v, x)
        # the floor regime permits d_grp < d_seq — both must stay valid
        # fractions, and the per-sequence run at tight capacity must
        # actually be dropping (else this test exercises nothing)
        assert 0.0 <= float(d_grp) <= 1.0
        assert float(d_seq) > 0.0


class TestMoELlama:
    def _cfg(self, **kw):
        return LlamaConfig.tiny(moe_experts=4, moe_top_k=2,
                                intermediate_size=64, **kw)

    def test_forward_reports_aux(self):
        cfg = self._cfg()
        model = LlamaForCausalLM(cfg)
        batch = {"input_ids": np.ones((2, 16), np.int32)}
        v = model.init(jax.random.PRNGKey(0), batch, train=False)
        out = model.apply(v, batch, train=True)
        assert isinstance(out, dict) and "moe_aux" in out
        assert "moe_dropped_frac" in out
        assert 0.0 <= float(out["moe_dropped_frac"]) <= 1.0
        assert out["logits"].shape == (2, 16, cfg.vocab_size)
        loss, metrics = losses.causal_lm(
            out, {"input_ids": batch["input_ids"],
                  "loss_mask": np.ones((2, 16), np.float32)})
        assert "moe_aux" in metrics and np.isfinite(float(loss))
        assert "moe_dropped_frac" in metrics

    def test_trains_on_data_expert_mesh(self, eight_devices):
        """Full train step over data=2 × expert=4: expert kernels sharded,
        loss (incl. aux) finite, params move."""
        from distributeddeeplearningspark_tpu.data.feed import (
            put_global, stack_examples)
        from distributeddeeplearningspark_tpu.models import llama_rules

        mesh = MeshSpec(data=2, expert=4).build(eight_devices)
        cfg = self._cfg()
        model = LlamaForCausalLM(cfg)
        rules = llama_rules(cfg, fsdp_min_size=1)
        batch = stack_examples([
            {"input_ids": np.full((16,), i % cfg.vocab_size, np.int32),
             "loss_mask": np.ones((16,), np.float32)}
            for i in range(4)])
        tx = optax.adamw(1e-3)
        state, shardings = step_lib.init_state(model, tx, batch, mesh, rules)
        wg = shardings.params["layers"]["moe"]["w_gate"]
        assert "expert" in str(wg.spec), wg
        step = step_lib.jit_train_step(
            step_lib.make_train_step(model.apply, tx, losses.causal_lm),
            mesh, shardings)
        before = jax.device_get(
            jax.tree_util.tree_leaves(state.params)[0])
        state, metrics = step(state, put_global(batch, mesh))
        assert np.isfinite(float(jax.device_get(metrics["loss"])))
        assert np.isfinite(float(jax.device_get(metrics["moe_aux"])))
        after = jax.device_get(jax.tree_util.tree_leaves(state.params)[0])
        assert not np.allclose(before, after)

    def test_moe_composes_with_fused_head(self):
        cfg = self._cfg(fused_head_loss=True)
        model = LlamaForCausalLM(cfg)
        batch = {"input_ids": np.ones((2, 16), np.int32),
                 "loss_mask": np.ones((2, 16), np.float32)}
        v = model.init(jax.random.PRNGKey(0), batch, train=False)
        out = model.apply(v, batch, train=True)
        assert {"hidden", "lm_head", "moe_aux"} <= set(out)
        loss, metrics = losses.causal_lm_fused(out, batch)
        assert "moe_aux" in metrics and np.isfinite(float(loss))

    def test_moe_loss_decreases(self, eight_devices):
        """Training signal end-to-end: repeated-token corpus, loss drops."""
        mesh = MeshSpec(data=2, expert=4).build(eight_devices)
        from distributeddeeplearningspark_tpu.data.feed import (
            put_global, stack_examples)
        from distributeddeeplearningspark_tpu.models import llama_rules

        cfg = self._cfg()
        model = LlamaForCausalLM(cfg)
        batch = stack_examples([
            {"input_ids": (np.arange(16, dtype=np.int32) * (i + 1))
             % cfg.vocab_size,
             "loss_mask": np.ones((16,), np.float32)}
            for i in range(4)])
        tx = optax.adamw(3e-3)
        state, shardings = step_lib.init_state(
            model, tx, batch, mesh, llama_rules(cfg, fsdp_min_size=1))
        step = step_lib.jit_train_step(
            step_lib.make_train_step(model.apply, tx, losses.causal_lm),
            mesh, shardings)
        gbatch = put_global(batch, mesh)
        first = last = None
        for _ in range(30):
            state, metrics = step(state, gbatch)
            loss = float(jax.device_get(metrics["loss"]))
            first = loss if first is None else first
            last = loss
        assert last < first * 0.7, (first, last)


def test_predict_and_eval_get_plain_logits():
    """train=False must return a bare logits array — Trainer.predict row
    indexing and argmax output_fns cannot take the aux dict."""
    cfg = LlamaConfig.tiny(moe_experts=2, intermediate_size=64)
    model = LlamaForCausalLM(cfg)
    batch = {"input_ids": np.ones((2, 16), np.int32)}
    v = model.init(jax.random.PRNGKey(0), batch, train=False)
    out = model.apply(v, batch, train=False)
    assert not isinstance(out, dict)
    assert out.shape == (2, 16, cfg.vocab_size)


def test_moe_with_pipeline_rejected(eight_devices):
    """PP's stage forward discards the aux loss — must refuse, not silently
    train a collapsing router."""
    from distributeddeeplearningspark_tpu.models.llama_pp import make_pp_apply

    mesh = MeshSpec(data=4, pipe=2).build(eight_devices)
    cfg = LlamaConfig.tiny(moe_experts=2, intermediate_size=64)
    with pytest.raises(NotImplementedError, match="MoE"):
        make_pp_apply(cfg, mesh, 2)
