"""Array records (VERDICT r2 missing-#4 / next-#5): the materialized-RDD
input path — write-once preprocessed shards, stream back at memory rate —
plus the map_parallel thread-scaling proof this sandbox can produce."""

import os
import time

import numpy as np
import pytest

from distributeddeeplearningspark_tpu.data.records import (
    RecordShardWriter,
    array_records,
    write_array_records,
    write_imagenet_records,
)
from distributeddeeplearningspark_tpu.rdd import PartitionedDataset


def _examples(n, seed=0):
    rng = np.random.default_rng(seed)
    return [
        {
            "image": rng.integers(0, 255, (20 + i % 3, 24, 3), np.uint8),
            "label": np.int32(i % 7),
            "weight": np.float32(rng.random()),
        }
        for i in range(n)
    ]


class TestRoundTrip:
    def test_exact_roundtrip(self, tmp_path):
        exs = _examples(17)
        ds = PartitionedDataset.parallelize(exs, 3)
        paths = write_array_records(ds, str(tmp_path / "rec"))
        assert len(paths) == 3
        back = array_records(str(tmp_path / "rec")).collect()
        assert len(back) == 17
        # partition-major order: same multiset, exact bytes/dtypes/shapes
        by_label = sorted(back, key=lambda e: e["image"].tobytes())
        want = sorted(exs, key=lambda e: e["image"].tobytes())
        for g, w in zip(by_label, want):
            assert g["image"].dtype == np.uint8 and g["label"].dtype == np.int32
            np.testing.assert_array_equal(g["image"], w["image"])
            assert g["label"] == w["label"]
            np.testing.assert_allclose(g["weight"], w["weight"])

    def test_resharding_via_footer_index(self, tmp_path):
        exs = _examples(40, seed=1)
        write_array_records(PartitionedDataset.parallelize(exs, 2),
                            str(tmp_path / "rec"))
        for nparts in (1, 2, 5, 8):
            ds = array_records(str(tmp_path / "rec"), num_partitions=nparts)
            assert ds.num_partitions == nparts
            got = ds.collect()
            assert len(got) == 40
            assert (sorted(e["image"].tobytes() for e in got)
                    == sorted(e["image"].tobytes() for e in exs))

    def test_empty_and_scalar_records(self, tmp_path):
        p = str(tmp_path / "part-00000.dlsrec")
        with RecordShardWriter(p) as w:
            w.write({"x": np.float64(3.5), "l": np.int32(7),
                     "name_Ωé": np.arange(3)})
        (rec,) = array_records(p).collect()
        assert rec["x"] == 3.5 and rec["x"].dtype == np.float64
        # scalars must round-trip 0-d — ascontiguousarray's ndmin=1 quirk
        # once turned labels into [1] arrays that batched to [B, 1]
        assert np.ndim(rec["x"]) == 0 and np.ndim(rec["l"]) == 0
        assert rec["l"] == 7 and rec["l"].dtype == np.int32
        np.testing.assert_array_equal(rec["name_Ωé"], np.arange(3))

    def test_noncontiguous_input_roundtrips(self, tmp_path):
        p = str(tmp_path / "part-00000.dlsrec")
        base = np.arange(24, dtype=np.float32).reshape(4, 6)
        with RecordShardWriter(p) as w:
            w.write({"t": base.T, "s": base[:, ::2]})  # both non-contiguous
        (rec,) = array_records(p).collect()
        np.testing.assert_array_equal(rec["t"], base.T)
        np.testing.assert_array_equal(rec["s"], base[:, ::2])

    def test_rejects_non_record_file(self, tmp_path):
        p = tmp_path / "junk.dlsrec"
        p.write_bytes(b"not a record file")
        with pytest.raises(ValueError, match="DLSREC01"):
            array_records(str(p)).collect()

    def test_explicit_num_shards(self, tmp_path):
        exs = _examples(12, seed=2)
        paths = write_array_records(PartitionedDataset.parallelize(exs, 3),
                                    str(tmp_path / "rec"), num_shards=5)
        assert len(paths) == 5
        assert len(array_records(str(tmp_path / "rec")).collect()) == 12


class TestImagenetRecords:
    def _folder(self, tmp_path, n=8, size=64):
        from PIL import Image

        rng = np.random.default_rng(3)
        tmp_path.mkdir(parents=True, exist_ok=True)
        for cls in range(2):
            d = tmp_path / f"class_{cls}"
            d.mkdir()
            for i in range(n // 2):
                arr = rng.integers(0, 255, (size, size + 10, 3), np.uint8)
                Image.fromarray(arr).save(str(d / f"im{i}.jpg"), quality=92)
        return str(tmp_path)

    def test_materialize_then_train_path(self, tmp_path):
        from distributeddeeplearningspark_tpu.data.vision import imagenet_train

        root = self._folder(tmp_path / "jpeg", n=8, size=64)
        out = str(tmp_path / "rec")
        paths = write_imagenet_records(root, out, size=32, num_shards=2)
        assert len(paths) == 2
        ds = array_records(out)
        recs = ds.collect()
        assert len(recs) == 8
        for r in recs:
            # shorter side resized to 32, aspect preserved, uint8
            assert min(r["image"].shape[:2]) == 32
            assert r["image"].dtype == np.uint8
        # records feed the standard train pipeline unchanged
        batch = next(iter(imagenet_train(ds, size=16).batch(4).iter_partition(0)))
        assert len(batch) == 4
        assert batch[0]["image"].shape == (16, 16, 3)
        assert batch[0]["image"].dtype == np.float32

    def test_never_upscales(self, tmp_path):
        root = self._folder(tmp_path / "jpeg", n=4, size=24)
        write_imagenet_records(root, str(tmp_path / "rec"), size=48, num_shards=1)
        for r in array_records(str(tmp_path / "rec")).collect():
            assert min(r["image"].shape[:2]) == 24  # kept original


class TestThreadScaling:
    """VERDICT r2 weak-#6: turn map_parallel's scaling claim into evidence
    this 1-core sandbox CAN produce — a GIL-releasing (sleeping) transform
    must scale ~N× with N threads, because the pool's sliding window keeps
    N sleeps in flight."""

    @staticmethod
    def _run(num_threads, n=24, delay=0.02):
        ds = PartitionedDataset.parallelize(list(range(n)), 1)

        def slow_id(x):
            time.sleep(delay)  # stands in for GIL-releasing native decode
            return x

        t0 = time.perf_counter()
        out = ds.map_parallel(slow_id, num_threads=num_threads).collect()
        dt = time.perf_counter() - t0
        assert out == list(range(n))  # order preserved at any parallelism
        return dt

    def test_threads_scale_throughput(self):
        serial = self._run(1)
        par4 = self._run(4)
        par8 = self._run(8)
        # ideal: 24·20ms = 480ms serial, 120ms at 4 threads, 60ms at 8.
        # Generous bounds absorb CI jitter while still proving scaling.
        assert par4 < serial / 2.2, (serial, par4)
        assert par8 < serial / 3.5, (serial, par8)


class TestWriterFailure:
    def test_failed_shard_not_left_looking_complete(self, tmp_path):
        p = str(tmp_path / "part-00000.dlsrec")
        with pytest.raises(RuntimeError):
            with RecordShardWriter(p) as w:
                w.write({"x": np.arange(3)})
                raise RuntimeError("decode failed")
        assert not os.path.exists(p)  # aborted, not sealed

    def test_streaming_reshard_failure_aborts_all(self, tmp_path):
        def gen():
            yield {"x": np.arange(2)}
            raise RuntimeError("boom")

        with pytest.raises(RuntimeError):
            write_array_records(gen(), str(tmp_path / "rec"), num_shards=3)
        assert not any(f.endswith(".dlsrec")
                       for f in os.listdir(tmp_path / "rec"))


class TestBatchedFusedFeed:
    """imagenet_train_batched: whole-batch native augment == the per-example
    chain (same content-seeded rng stream), exactly batched."""

    def _records(self, tmp_path, n=12, hw=(40, 52)):
        rng = np.random.default_rng(8)
        exs = [{"image": rng.integers(0, 255, (*hw, 3), np.uint8),
                "label": np.int32(i % 5)} for i in range(n)]
        write_array_records(PartitionedDataset.parallelize(exs, 2),
                            str(tmp_path / "rec"))
        from distributeddeeplearningspark_tpu.data.records import array_records
        return array_records(str(tmp_path / "rec"))

    def test_matches_per_example_chain(self, tmp_path):
        from distributeddeeplearningspark_tpu.data.feed import host_batches
        from distributeddeeplearningspark_tpu.data.vision import (
            imagenet_train_batched, train_transform)

        ds = self._records(tmp_path)
        want = list(host_batches(ds.map(train_transform(16, seed=3)), 4))
        got = list(imagenet_train_batched(ds, 4, size=16, seed=3))
        assert len(got) == len(want) == 3
        for gb, wb in zip(got, want):
            assert gb["image"].shape == (4, 16, 16, 3)
            assert gb["image"].dtype == np.float32
            np.testing.assert_allclose(gb["image"], wb["image"],
                                       atol=1e-4, rtol=1e-4)
            np.testing.assert_array_equal(gb["label"], wb["label"])

    def test_remainder_and_fallback(self, tmp_path, monkeypatch):
        from distributeddeeplearningspark_tpu.data.vision import (
            imagenet_train_batched)
        from distributeddeeplearningspark_tpu.utils import native

        ds = self._records(tmp_path, n=10)
        got = list(imagenet_train_batched(ds, 4, size=16,
                                          drop_remainder=False))
        assert [len(b["label"]) for b in got] == [4, 4, 2]
        # no native → numpy fallback produces the same stream
        with_native = got
        monkeypatch.setattr(native, "_LIB", None)
        monkeypatch.setattr(native, "_TRIED", True)
        without = list(imagenet_train_batched(ds, 4, size=16,
                                              drop_remainder=False))
        for a, b in zip(with_native, without):
            np.testing.assert_allclose(a["image"], b["image"],
                                       atol=1e-4, rtol=1e-4)
