"""Session (SparkSession-shaped) lifecycle tests."""

import pytest

from distributeddeeplearningspark_tpu import Session


def test_builder_local2(eight_devices):
    spark = Session.builder.master("local[2]").appName("t").getOrCreate()
    assert spark.app_name == "t"
    assert spark.num_devices == 2
    assert spark.default_parallelism == 2
    spark.stop()


def test_get_or_create_is_singleton(eight_devices):
    a = Session.builder.master("local[2]").getOrCreate()
    b = Session.builder.getOrCreate()
    assert a is b
    a.stop()
    c = Session.builder.master("local[4]").getOrCreate()
    assert c is not a
    assert c.num_devices == 4


def test_executor_instances_conf(eight_devices):
    spark = (
        Session.builder.config("spark.executor.instances", 4).getOrCreate()
    )
    assert spark.default_parallelism == 4
    assert spark.num_devices == 4


def test_mesh_conf_axes(eight_devices):
    spark = (
        Session.builder.master("local[2]")
        .config("mesh.fsdp", 2)
        .config("mesh.tensor", 2)
        .getOrCreate()
    )
    assert spark.mesh.shape["data"] == 2
    assert spark.mesh.shape["fsdp"] == 2
    assert spark.mesh.shape["tensor"] == 2
    assert spark.num_devices == 8


def test_master_too_large_raises(eight_devices):
    with pytest.raises(ValueError):
        Session.builder.master("local[16]").getOrCreate()


def test_parallelize_roundtrip(eight_devices):
    spark = Session.builder.master("local[2]").getOrCreate()
    rdd = spark.parallelize(range(10))
    assert rdd.num_partitions == 2
    assert rdd.collect() == list(range(10))
    assert spark.sparkContext is spark  # context == session


def test_context_manager(eight_devices):
    with Session.builder.master("local[2]").getOrCreate() as spark:
        assert spark.num_devices == 2
    with pytest.raises(RuntimeError):
        Session.active()


def test_compilation_cache_conf_key(tmp_path):
    """spark.jax.compilationCache.dir enables the persistent XLA cache for
    the session's lifetime and restores the prior value on stop()."""
    import jax

    from distributeddeeplearningspark_tpu.session import Session

    before = jax.config.jax_compilation_cache_dir
    cache = str(tmp_path / "xla_cache")
    sess = (Session.builder.master("local[1]").appName("cache")
            .config("spark.jax.compilationCache.dir", cache).getOrCreate())
    try:
        assert jax.config.jax_compilation_cache_dir == cache
    finally:
        sess.stop()
    assert jax.config.jax_compilation_cache_dir == before


def test_compilation_cache_applies_to_live_session(tmp_path):
    """Merging the cache key into an already-active session must still reach
    jax.config (not just sit in session.conf)."""
    import jax

    from distributeddeeplearningspark_tpu.session import Session

    before = jax.config.jax_compilation_cache_dir
    sess = Session.builder.master("local[1]").appName("live").getOrCreate()
    try:
        cache = str(tmp_path / "late_cache")
        again = (Session.builder
                 .config("spark.jax.compilationCache.dir", cache).getOrCreate())
        assert again is sess
        assert jax.config.jax_compilation_cache_dir == cache
    finally:
        sess.stop()
    assert jax.config.jax_compilation_cache_dir == before
