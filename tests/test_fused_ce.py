"""Chunked-vocab fused CE (train/fused_ce.py) ≡ materialized-logits CE.

The whole point of the module is being a pure memory optimization — loss
values and gradients (hidden AND kernel, duplicates included) must match the
naive path to fp tolerance.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributeddeeplearningspark_tpu.train.fused_ce import (
    _chunk_geometry,
    chunked_softmax_xent,
)


def naive(hidden, kernel, labels):
    logits = (hidden.astype(jnp.float32) @ kernel.astype(jnp.float32))
    return optax.softmax_cross_entropy_with_integer_labels(logits, labels)


def make(n=24, h=16, v=40, seed=0, dtype=np.float32):
    rng = np.random.default_rng(seed)
    hidden = jnp.asarray(rng.normal(0, 1, (n, h)).astype(dtype))
    kernel = jnp.asarray(rng.normal(0, 0.5, (h, v)).astype(np.float32))
    # force duplicate labels so the scatter-add correction is exercised
    labels = jnp.asarray(rng.integers(0, v // 2, (n,)).astype(np.int32))
    return hidden, kernel, labels


@pytest.mark.parametrize("num_chunks", [1, 4, 16, 40])
def test_loss_matches_naive(num_chunks):
    hidden, kernel, labels = make()
    got = chunked_softmax_xent(hidden, kernel, labels, num_chunks=num_chunks)
    want = naive(hidden, kernel, labels)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_gradients_match_naive_including_duplicate_labels():
    hidden, kernel, labels = make(seed=1)
    w = jnp.asarray(np.random.default_rng(2).uniform(0.5, 1.5, (24,))
                    .astype(np.float32))

    def loss_fused(hd, kn):
        return jnp.sum(chunked_softmax_xent(hd, kn, labels, num_chunks=4) * w)

    def loss_naive(hd, kn):
        return jnp.sum(naive(hd, kn, labels) * w)

    gf = jax.jit(jax.grad(loss_fused, argnums=(0, 1)))(hidden, kernel)
    gn = jax.jit(jax.grad(loss_naive, argnums=(0, 1)))(hidden, kernel)
    np.testing.assert_allclose(np.asarray(gf[0]), np.asarray(gn[0]),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(gf[1]), np.asarray(gn[1]),
                               rtol=2e-5, atol=2e-5)


def test_bf16_hidden_matches_bf16_naive():
    hidden, kernel, labels = make(seed=3)
    hidden16 = hidden.astype(jnp.bfloat16)
    got = chunked_softmax_xent(hidden16, kernel, labels, num_chunks=4)
    logits = jnp.dot(hidden16, kernel.astype(jnp.bfloat16),
                     preferred_element_type=jnp.float32)
    want = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
    # both paths run the matmul in bf16 inputs/f32 accum; the label-logit
    # gather path differs slightly (f32 einsum) — tolerance reflects bf16
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-2, atol=2e-2)


def test_leading_dims_and_shape_checks():
    hidden, kernel, labels = make()
    got = chunked_softmax_xent(hidden.reshape(4, 6, 16), kernel,
                               labels.reshape(4, 6), num_chunks=4)
    assert got.shape == (4, 6)
    with pytest.raises(ValueError, match="kernel"):
        chunked_softmax_xent(hidden, kernel.T, labels)
    with pytest.raises(ValueError, match="labels"):
        chunked_softmax_xent(hidden, kernel, labels[:5])


def test_chunk_geometry_pads_all_vocab_sizes():
    assert _chunk_geometry(32000, 16) == (16, 32000)
    assert _chunk_geometry(50257, 16) == (16, 50272)  # GPT-2's prime-ish vocab
    assert _chunk_geometry(31, 16) == (16, 32)
    assert _chunk_geometry(40, 100) == (40, 40)


@pytest.mark.parametrize("v", [31, 37, 50])
def test_prime_and_odd_vocab_sizes_match_naive(v):
    """Padded-column masking: chunking must stay exact (loss AND grads) for
    vocab sizes with no small divisors — never fall back to one full chunk."""
    hidden, kernel, labels = make(v=v, seed=v)

    def loss_fused(hd, kn):
        return jnp.sum(chunked_softmax_xent(hd, kn, labels, num_chunks=8))

    def loss_naive(hd, kn):
        return jnp.sum(naive(hd, kn, labels))

    np.testing.assert_allclose(float(loss_fused(hidden, kernel)),
                               float(loss_naive(hidden, kernel)), rtol=1e-5)
    gf = jax.jit(jax.grad(loss_fused, argnums=(0, 1)))(hidden, kernel)
    gn = jax.jit(jax.grad(loss_naive, argnums=(0, 1)))(hidden, kernel)
    for a, b in zip(gf, gn):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-5)
