"""GPipe pipeline parallelism over the `pipe` mesh axis: parity + grads."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributeddeeplearningspark_tpu.parallel.mesh import MeshSpec
from distributeddeeplearningspark_tpu.parallel.pipeline import (
    pipeline,
    stack_stages,
)


def _stage_fn(w, x):
    return jnp.tanh(x @ w)


def _sequential(stage_params, x):
    for i in range(stage_params.shape[0]):
        x = _stage_fn(stage_params[i], x)
    return x


@pytest.mark.parametrize("microbatches", [1, 2, 4])
def test_pipeline_matches_sequential(microbatches, eight_devices):
    mesh = MeshSpec(data=2, pipe=4).build()
    rng = np.random.default_rng(0)
    stage_params = jnp.asarray(rng.normal(0, 0.5, (4, 16, 16)).astype(np.float32))
    x = jnp.asarray(rng.normal(0, 1, (8, 16)).astype(np.float32))
    want = _sequential(stage_params, x)
    got = jax.jit(lambda p, a: pipeline(
        _stage_fn, p, a, mesh=mesh, num_microbatches=microbatches))(stage_params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5, rtol=1e-5)


def test_pipeline_gradients_match_sequential(eight_devices):
    mesh = MeshSpec(data=1, pipe=8).build()
    rng = np.random.default_rng(1)
    stage_params = jnp.asarray(rng.normal(0, 0.5, (8, 8, 8)).astype(np.float32))
    x = jnp.asarray(rng.normal(0, 1, (4, 8)).astype(np.float32))

    loss_pipe = jax.jit(jax.grad(lambda p: jnp.sum(
        pipeline(_stage_fn, p, x, mesh=mesh, num_microbatches=2) ** 2)))
    loss_seq = jax.jit(jax.grad(lambda p: jnp.sum(_sequential(p, x) ** 2)))
    np.testing.assert_allclose(np.asarray(loss_pipe(stage_params)),
                               np.asarray(loss_seq(stage_params)),
                               atol=1e-4, rtol=1e-4)


def test_stack_stages_regroups_scanned_layers():
    layers = {"w": jnp.arange(24.0).reshape(6, 2, 2)}
    staged = stack_stages(layers, 3)
    assert staged["w"].shape == (3, 2, 2, 2)
    np.testing.assert_array_equal(np.asarray(staged["w"][1, 0]),
                                  np.asarray(layers["w"][2]))
    with pytest.raises(ValueError, match="divisible"):
        stack_stages(layers, 4)


def test_pipeline_validates_inputs(eight_devices):
    mesh = MeshSpec(data=2, pipe=4).build()
    params = jnp.zeros((3, 4, 4))  # wrong stage count
    with pytest.raises(ValueError, match="pipe degree"):
        pipeline(_stage_fn, params, jnp.zeros((8, 4)), mesh=mesh, num_microbatches=2)
    with pytest.raises(ValueError, match="divide"):
        pipeline(_stage_fn, jnp.zeros((4, 4, 4)), jnp.zeros((7, 4)),
                 mesh=mesh, num_microbatches=2)


# -- end-to-end PP integration (VERDICT r1 next-#5) --------------------------

def _llama_batch(n, s, vocab, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "input_ids": rng.integers(0, vocab, (n, s)).astype(np.int32),
        "loss_mask": np.ones((n, s), np.float32),
    }


def test_pp_llama_loss_equals_non_pp(eight_devices):
    """One train step of pipelined Llama == non-PP Llama: identical init,
    identical data, same loss and same updated params (fp tol)."""
    import optax

    from distributeddeeplearningspark_tpu.data.feed import put_global
    from distributeddeeplearningspark_tpu.models import (
        LlamaConfig, LlamaForCausalLM, llama_rules,
    )
    from distributeddeeplearningspark_tpu.train import losses, step as step_lib

    cfg = LlamaConfig.tiny()  # scan_layers=True, remat=True, 4 layers
    model = LlamaForCausalLM(cfg)
    batch = _llama_batch(8, 32, cfg.vocab_size, seed=3)
    tx = optax.adamw(1e-3)

    from distributeddeeplearningspark_tpu.models.llama_pp import make_pp_apply

    results = {}
    for mode in ("pp", "dp"):
        if mode == "pp":
            mesh = MeshSpec(data=2, pipe=2).build(jax.devices()[:4])
            rules = llama_rules(cfg, fsdp=False, pipeline=True)
            apply_fn = make_pp_apply(cfg, mesh, 2)
        else:
            mesh = MeshSpec(data=4).build(jax.devices()[:4])
            rules = llama_rules(cfg, fsdp=False)
            apply_fn = model.apply
        state, shardings = step_lib.init_state(model, tx, batch, mesh, rules, seed=7)
        step = step_lib.jit_train_step(
            step_lib.make_train_step(apply_fn, tx, losses.causal_lm),
            mesh, shardings,
        )
        new_state, metrics = step(state, put_global(batch, mesh))
        results[mode] = (
            jax.device_get(metrics), jax.device_get(new_state.params),
        )

    m_pp, p_pp = results["pp"]
    m_dp, p_dp = results["dp"]
    np.testing.assert_allclose(m_pp["loss"], m_dp["loss"], rtol=1e-5, atol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5),
        p_pp, p_dp,
    )


def test_pp_layer_params_sharded_over_pipe(eight_devices):
    """pipeline=True rules put every stacked layer param on its stage's
    devices (PP as depth-wise param partitioning)."""
    import optax

    from distributeddeeplearningspark_tpu.models import (
        LlamaConfig, LlamaForCausalLM, llama_rules,
    )
    from distributeddeeplearningspark_tpu.train import step as step_lib

    cfg = LlamaConfig.tiny(lora_rank=2)
    mesh = MeshSpec(data=2, pipe=2).build(jax.devices()[:4])
    model = LlamaForCausalLM(cfg)
    batch = _llama_batch(4, 16, cfg.vocab_size)
    state, shardings = step_lib.init_state(
        model, optax.sgd(0.1), batch, mesh, llama_rules(cfg, fsdp=False, pipeline=True))
    flat = jax.tree_util.tree_flatten_with_path(shardings.params)[0]
    layer_leaves = [(p, s) for p, s in flat if "layers" in str(p[0])]
    assert layer_leaves
    for path, sh in layer_leaves:
        assert "pipe" in str(sh.spec), f"{path} not pipe-sharded: {sh.spec}"


def test_trainer_pp_fit(eight_devices):
    """Trainer on a data x pipe mesh trains Llama end-to-end via the PP path."""
    import optax

    from distributeddeeplearningspark_tpu import Session, Trainer
    from distributeddeeplearningspark_tpu.models import (
        LlamaConfig, LlamaForCausalLM, llama_rules,
    )
    from distributeddeeplearningspark_tpu.rdd import PartitionedDataset
    from distributeddeeplearningspark_tpu.train import losses

    spark = (Session.builder.master("local[2]")
             .config("mesh.pipe", "2").getOrCreate())
    assert spark.mesh.shape["pipe"] == 2
    cfg = LlamaConfig.tiny()
    examples = [
        {"input_ids": np.random.default_rng(i).integers(
            0, cfg.vocab_size, (32,)).astype(np.int32),
         "loss_mask": np.ones((32,), np.float32)}
        for i in range(64)
    ]
    ds = PartitionedDataset.parallelize(examples, 2)
    trainer = Trainer(spark, LlamaForCausalLM(cfg), losses.causal_lm,
                      optax.adamw(1e-3),
                      rules=llama_rules(cfg, fsdp=False, pipeline=True),
                      pipeline_microbatches=2)
    state, summary = trainer.fit(ds.repeat(), batch_size=8, steps=3, log_every=10)
    assert int(jax.device_get(state.step)) == 3
    assert np.isfinite(summary["loss"])


def test_pp_composes_with_tp_and_dp(eight_devices):
    """data=2 x pipe=2 x tensor=2: the GPipe ring, Megatron TP sharding, and
    batch sharding in one step — loss equals the pure-DP loss."""
    import optax

    from distributeddeeplearningspark_tpu.data.feed import put_global, stack_examples
    from distributeddeeplearningspark_tpu.models import (
        LlamaConfig, LlamaForCausalLM, llama_rules)
    from distributeddeeplearningspark_tpu.models.llama_pp import make_pp_apply
    from distributeddeeplearningspark_tpu.parallel.mesh import MeshSpec
    from distributeddeeplearningspark_tpu.parallel.sharding import ShardingRules
    from distributeddeeplearningspark_tpu.train import losses, step as step_lib

    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    examples = [{"input_ids": np.arange(32, dtype=np.int32) + i,
                 "loss_mask": np.ones((32,), np.float32)} for i in range(16)]
    for e in examples:
        e["input_ids"] %= cfg.vocab_size
    batch = stack_examples(examples)
    tx = optax.adamw(1e-3)

    mesh = MeshSpec(data=2, pipe=2, tensor=2).build()
    state, sh = step_lib.init_state(
        model, tx, batch, mesh, llama_rules(cfg, fsdp=False, pipeline=True))
    ts = step_lib.jit_train_step(
        step_lib.make_train_step(make_pp_apply(cfg, mesh, 4), tx,
                                 losses.causal_lm), mesh, sh)
    _, met = ts(state, put_global(batch, mesh))

    mesh_dp = MeshSpec(data=8).build()
    state_dp, sh_dp = step_lib.init_state(model, tx, batch, mesh_dp,
                                          ShardingRules())
    ts_dp = step_lib.jit_train_step(
        step_lib.make_train_step(model.apply, tx, losses.causal_lm),
        mesh_dp, sh_dp)
    _, met_dp = ts_dp(state_dp, put_global(batch, mesh_dp))
    np.testing.assert_allclose(float(jax.device_get(met["loss"])),
                               float(jax.device_get(met_dp["loss"])),
                               rtol=1e-4)
