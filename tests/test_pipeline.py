"""GPipe pipeline parallelism over the `pipe` mesh axis: parity + grads."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributeddeeplearningspark_tpu.parallel.mesh import MeshSpec
from distributeddeeplearningspark_tpu.parallel.pipeline import (
    pipeline,
    stack_stages,
)


def _stage_fn(w, x):
    return jnp.tanh(x @ w)


def _sequential(stage_params, x):
    for i in range(stage_params.shape[0]):
        x = _stage_fn(stage_params[i], x)
    return x


@pytest.mark.parametrize("microbatches", [1, 2, 4])
def test_pipeline_matches_sequential(microbatches, eight_devices):
    mesh = MeshSpec(data=2, pipe=4).build()
    rng = np.random.default_rng(0)
    stage_params = jnp.asarray(rng.normal(0, 0.5, (4, 16, 16)).astype(np.float32))
    x = jnp.asarray(rng.normal(0, 1, (8, 16)).astype(np.float32))
    want = _sequential(stage_params, x)
    got = jax.jit(lambda p, a: pipeline(
        _stage_fn, p, a, mesh=mesh, num_microbatches=microbatches))(stage_params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5, rtol=1e-5)


def test_pipeline_gradients_match_sequential(eight_devices):
    mesh = MeshSpec(data=1, pipe=8).build()
    rng = np.random.default_rng(1)
    stage_params = jnp.asarray(rng.normal(0, 0.5, (8, 8, 8)).astype(np.float32))
    x = jnp.asarray(rng.normal(0, 1, (4, 8)).astype(np.float32))

    loss_pipe = jax.jit(jax.grad(lambda p: jnp.sum(
        pipeline(_stage_fn, p, x, mesh=mesh, num_microbatches=2) ** 2)))
    loss_seq = jax.jit(jax.grad(lambda p: jnp.sum(_sequential(p, x) ** 2)))
    np.testing.assert_allclose(np.asarray(loss_pipe(stage_params)),
                               np.asarray(loss_seq(stage_params)),
                               atol=1e-4, rtol=1e-4)


def test_stack_stages_regroups_scanned_layers():
    layers = {"w": jnp.arange(24.0).reshape(6, 2, 2)}
    staged = stack_stages(layers, 3)
    assert staged["w"].shape == (3, 2, 2, 2)
    np.testing.assert_array_equal(np.asarray(staged["w"][1, 0]),
                                  np.asarray(layers["w"][2]))
    with pytest.raises(ValueError, match="divisible"):
        stack_stages(layers, 4)


def test_pipeline_validates_inputs(eight_devices):
    mesh = MeshSpec(data=2, pipe=4).build()
    params = jnp.zeros((3, 4, 4))  # wrong stage count
    with pytest.raises(ValueError, match="pipe degree"):
        pipeline(_stage_fn, params, jnp.zeros((8, 4)), mesh=mesh, num_microbatches=2)
    with pytest.raises(ValueError, match="divide"):
        pipeline(_stage_fn, jnp.zeros((4, 4, 4)), jnp.zeros((7, 4)),
                 mesh=mesh, num_microbatches=2)
