"""Mesh construction and sharding-rule unit tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distributeddeeplearningspark_tpu.parallel import (
    FSDP,
    MESH_AXES,
    MeshSpec,
    ShardingRules,
    batch_sharding,
    num_data_shards,
)


def test_meshspec_wildcard_data(eight_devices):
    mesh = MeshSpec().build()
    assert mesh.shape["data"] == 8
    assert all(mesh.shape[a] == 1 for a in MESH_AXES if a != "data")


def test_meshspec_mixed_axes(eight_devices):
    mesh = MeshSpec(data=2, fsdp=2, tensor=2).build()
    assert mesh.shape["data"] == 2
    assert mesh.shape["fsdp"] == 2
    assert mesh.shape["tensor"] == 2
    assert num_data_shards(mesh) == 4


def test_meshspec_subset_of_devices(eight_devices):
    mesh = MeshSpec(data=2).build(eight_devices[:2])
    assert mesh.devices.size == 2


def test_meshspec_errors(eight_devices):
    with pytest.raises(ValueError):
        MeshSpec(data=3).build()  # 8 % 3 != 0
    with pytest.raises(ValueError):
        MeshSpec(data=-1, fsdp=-1).build()


def test_batch_sharding_splits_leading_axis(eight_devices):
    mesh = MeshSpec(data=4, fsdp=2).build()
    x = jnp.arange(16 * 3, dtype=jnp.float32).reshape(16, 3)
    gx = jax.device_put(x, batch_sharding(mesh, x.ndim))
    # 8 shards of 2 rows each
    assert len(gx.addressable_shards) == 8
    assert all(s.data.shape == (2, 3) for s in gx.addressable_shards)
    np.testing.assert_array_equal(np.asarray(gx), np.asarray(x))


def test_fsdp_rules_shard_largest_dim(eight_devices):
    mesh = MeshSpec(data=2, fsdp=4).build()
    params = {"layer": {"kernel": jnp.zeros((128, 512)), "bias": jnp.zeros((512,))}}
    specs = FSDP.tree_specs(params, mesh)
    assert specs["layer"]["kernel"] == P(None, "fsdp")  # 512 is largest dim
    # bias: 512 >= min_size? 512 < 2**14 → replicated
    assert specs["layer"]["bias"] == P(None)


def test_explicit_rules_take_precedence(eight_devices):
    mesh = MeshSpec(data=2, fsdp=2, tensor=2).build()
    rules = ShardingRules(rules=(("attn/qkv/kernel", P(None, "tensor")),), fsdp=True, fsdp_min_size=1)
    params = {"attn": {"qkv": {"kernel": jnp.zeros((64, 64))}}}
    spec = rules.tree_specs(params, mesh)["attn"]["qkv"]["kernel"]
    # tensor axis from explicit rule, fsdp added on the remaining dim
    assert spec == P("fsdp", "tensor")


def test_scalar_leaves_replicated(eight_devices):
    mesh = MeshSpec().build()
    specs = FSDP.tree_specs({"count": jnp.zeros(())}, mesh)
    assert specs["count"] == P()
