"""Row-sparse embedding training (train/embed.py) — correctness proofs.

The sparse step must be a pure traffic optimization: identical math to a
dense implementation of the same row-wise AdaGrad, touched rows only, exact
under duplicate ids, and composable with the expert-sharded table layout
(8 fake devices via conftest).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributeddeeplearningspark_tpu.data.feed import put_global, stack_examples
from distributeddeeplearningspark_tpu.models import DLRM
from distributeddeeplearningspark_tpu.models.dlrm import (
    WideAndDeep,
    dlrm_rules,
    fused_flat_ids,
    sparse_embed_specs,
)
from distributeddeeplearningspark_tpu.parallel.mesh import MeshSpec
from distributeddeeplearningspark_tpu.train import losses, optim, step as step_lib
from distributeddeeplearningspark_tpu.train.embed import (
    dense_trainable,
    make_sparse_embed_train_step,
    rowwise_adagrad_update,
)

VOCABS = (11, 7, 5)


def make_batch(n=8, seed=0):
    rng = np.random.default_rng(seed)
    return stack_examples([
        {"dense": rng.normal(0, 1, (13,)).astype(np.float32),
         "sparse": np.array([rng.integers(0, v) for v in VOCABS], np.int32),
         "label": np.int32(rng.integers(0, 2))}
        for _ in range(n)])


def dense_rowwise_adagrad(table, accum, ids, d_vecs, *, lr, eps):
    """Naive dense reference: scatter-add the vector grads into a full [V, D]
    gradient, then apply row-wise AdaGrad to every touched row."""
    v, d = table.shape
    flat = np.asarray(ids).reshape(-1)
    g = np.asarray(d_vecs, np.float32).reshape(-1, d)
    full = np.zeros((v, d), np.float32)
    np.add.at(full, flat, g)
    touched = np.zeros((v,), bool)
    touched[flat] = True
    acc = np.asarray(accum, np.float32).copy()
    out = np.asarray(table, np.float32).copy()
    acc_new = acc + np.mean(full * full, axis=1)
    upd = -lr * full / np.sqrt(acc_new + eps)[:, None]
    out[touched] += upd[touched]
    acc[touched] = acc_new[touched]
    return out, acc


def test_rowwise_adagrad_matches_dense_reference_with_duplicates():
    rng = np.random.default_rng(1)
    v, d = 13, 4
    table = jnp.asarray(rng.normal(0, 1, (v, d)).astype(np.float32))
    accum = jnp.asarray(rng.uniform(0, 0.5, (v,)).astype(np.float32))
    # heavy duplication: 10 lookups over 13 rows
    ids = jnp.asarray(rng.integers(0, v, (5, 2)).astype(np.int32))
    d_vecs = jnp.asarray(rng.normal(0, 1, (5, 2, d)).astype(np.float32))
    new_t, new_a = rowwise_adagrad_update(table, accum, ids, d_vecs, lr=0.1, eps=1e-8)
    ref_t, ref_a = dense_rowwise_adagrad(table, accum, ids, d_vecs, lr=0.1, eps=1e-8)
    np.testing.assert_allclose(np.asarray(new_t), ref_t, rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(new_a), ref_a, rtol=1e-6, atol=1e-6)


def test_rowwise_adagrad_leaves_untouched_rows_bitwise_identical():
    rng = np.random.default_rng(2)
    v, d = 20, 8
    table = jnp.asarray(rng.normal(0, 1, (v, d)).astype(np.float32))
    accum = jnp.zeros((v,), jnp.float32)
    ids = jnp.asarray([[1, 3], [1, 17]], jnp.int32)
    d_vecs = jnp.asarray(rng.normal(0, 1, (2, 2, d)).astype(np.float32))
    new_t, new_a = jax.jit(
        lambda *a: rowwise_adagrad_update(*a, lr=0.5, eps=1e-8)
    )(table, accum, ids, d_vecs)
    untouched = np.setdiff1d(np.arange(v), [1, 3, 17])
    np.testing.assert_array_equal(
        np.asarray(new_t)[untouched], np.asarray(table)[untouched])
    np.testing.assert_array_equal(np.asarray(new_a)[untouched], 0.0)
    for r in (1, 3, 17):
        assert not np.array_equal(np.asarray(new_t)[r], np.asarray(table)[r])


class TestSparseTrainStep:
    def _states(self, model, specs, batch, mesh, rules):
        tx = optim.masked(optax.adamw(1e-3), dense_trainable(specs))
        state, shardings = step_lib.init_state(
            model, tx, batch, mesh, rules, sparse_embed=specs)
        step = step_lib.jit_train_step(
            make_sparse_embed_train_step(model.apply, tx, losses.binary_xent, specs),
            mesh, shardings)
        return state, step

    def test_loss_decreases_and_only_touched_rows_move(self):
        mesh = MeshSpec(data=1).build(jax.devices()[:1])
        model = DLRM(vocab_sizes=VOCABS, embed_dim=8, bottom_mlp=(16, 8),
                     top_mlp=(16, 1))
        batch = make_batch()
        specs = sparse_embed_specs(model, lr=0.05)
        state, step = self._states(model, specs, batch, mesh, dlrm_rules())
        table0 = np.asarray(state.params["embedding"]["embedding_table"])
        gbatch = put_global(batch, mesh)
        losses_seen = []
        for _ in range(12):
            state, metrics = step(state, gbatch)
            losses_seen.append(float(metrics["loss"]))
        assert losses_seen[-1] < losses_seen[0], losses_seen
        table1 = np.asarray(state.params["embedding"]["embedding_table"])
        flat = np.asarray(fused_flat_ids(VOCABS, batch["sparse"])).reshape(-1)
        untouched = np.setdiff1d(np.arange(sum(VOCABS)), flat)
        np.testing.assert_array_equal(table1[untouched], table0[untouched])
        touched_moved = np.abs(table1[np.unique(flat)] - table0[np.unique(flat)]).max()
        assert touched_moved > 0
        # accumulator grew exactly on touched rows
        acc = np.asarray(state.embed_state["embedding"]["row_accum"])
        assert (acc[np.unique(flat)] > 0).all()
        np.testing.assert_array_equal(acc[untouched], 0.0)

    def test_matches_manual_dense_math_one_step(self):
        """One sparse step ≡ dense-autodiff grads + dense row-wise AdaGrad.

        f32 MLPs: the sparse and dense paths build differently-shaped
        backward graphs (override-injected vs in-model lookup), and bf16
        rounding differences between the two graphs would swamp the 1e-5
        equivalence this test asserts."""
        mesh = MeshSpec(data=1).build(jax.devices()[:1])
        model = DLRM(vocab_sizes=VOCABS, embed_dim=8, bottom_mlp=(16, 8),
                     top_mlp=(16, 1), dtype=jnp.float32)
        batch = make_batch(n=4, seed=3)
        specs = sparse_embed_specs(model, lr=0.07)
        state, step = self._states(model, specs, batch, mesh, dlrm_rules())

        # dense reference: full autodiff grad of the same loss w.r.t. table
        def loss_of(params):
            logits = model.apply({"params": params}, batch, train=True)
            return losses.binary_xent(logits, batch)[0]

        g = jax.grad(loss_of)(state.params)
        ref_table, ref_acc = dense_rowwise_adagrad(
            state.params["embedding"]["embedding_table"],
            state.embed_state["embedding"]["row_accum"],
            fused_flat_ids(VOCABS, batch["sparse"]),
            # dense grad rows for the touched ids reproduce the vector grads
            np.asarray(g["embedding"]["embedding_table"])[
                np.asarray(fused_flat_ids(VOCABS, batch["sparse"])).reshape(-1)
            ].reshape(4, len(VOCABS), 8),
            lr=0.07, eps=1e-8)
        new_state, _ = step(state, put_global(batch, mesh))
        got = np.asarray(new_state.params["embedding"]["embedding_table"])
        # duplicate ids make the dense-grad-row reconstruction double-count;
        # restrict the comparison to rows that appear exactly once
        flat = np.asarray(fused_flat_ids(VOCABS, batch["sparse"])).reshape(-1)
        ids_once = [i for i in np.unique(flat) if (flat == i).sum() == 1]
        assert ids_once, "test batch must contain non-duplicated ids"
        np.testing.assert_allclose(got[ids_once], ref_table[ids_once],
                                   rtol=1e-5, atol=1e-5)

    def test_expert_sharded_mesh_runs_and_keeps_rows_sparse(self, eight_devices):
        mesh = MeshSpec(data=4, expert=2).build(jax.devices()[:8])
        model = DLRM(vocab_sizes=(16, 8), embed_dim=8, bottom_mlp=(16, 8),
                     top_mlp=(8, 1))
        rng = np.random.default_rng(5)
        batch = stack_examples([
            {"dense": rng.normal(0, 1, (13,)).astype(np.float32),
             "sparse": np.array([rng.integers(0, v) for v in (16, 8)], np.int32),
             "label": np.int32(rng.integers(0, 2))}
            for _ in range(16)])
        specs = sparse_embed_specs(model)
        tx = optim.masked(optax.adagrad(1e-2), dense_trainable(specs))
        state, shardings = step_lib.init_state(
            model, tx, batch, mesh, dlrm_rules(), sparse_embed=specs)
        # the accumulator must shard over `expert` like the table rows
        acc_sh = shardings.embed_state["embedding"]["row_accum"]
        assert "expert" in str(acc_sh.spec), acc_sh
        step = step_lib.jit_train_step(
            make_sparse_embed_train_step(model.apply, tx, losses.binary_xent, specs),
            mesh, shardings)
        state, metrics = step(state, put_global(batch, mesh))
        assert np.isfinite(float(jax.device_get(metrics["loss"])))

    def test_wide_and_deep_trains_both_tables(self):
        mesh = MeshSpec(data=1).build(jax.devices()[:1])
        model = WideAndDeep(vocab_sizes=VOCABS, embed_dim=8, deep_mlp=(16, 1))
        batch = make_batch(n=4, seed=7)
        specs = sparse_embed_specs(model)
        assert {s.name for s in specs} == {"embedding", "wide_table"}
        state, step = self._states(model, specs, batch, mesh, dlrm_rules())
        wide0 = np.asarray(state.params["wide_table"]["embedding_table"])
        state, metrics = step(state, put_global(batch, mesh))
        assert np.isfinite(float(metrics["loss"]))
        wide1 = np.asarray(state.params["wide_table"]["embedding_table"])
        flat = np.unique(np.asarray(fused_flat_ids(VOCABS, batch["sparse"])))
        assert np.abs(wide1[flat] - wide0[flat]).max() > 0


def test_unconsumed_override_fails_loudly_with_nan():
    """A spec whose name the model never consumes must NaN the loss on step
    one (the poison mechanism, train/embed.py) — never silently train the
    MLPs while the table neither trains nor stays out of the dense path."""
    import dataclasses

    mesh = MeshSpec(data=1).build(jax.devices()[:1])
    model = DLRM(vocab_sizes=VOCABS, embed_dim=8, bottom_mlp=(16, 8),
                 top_mlp=(16, 1))
    batch = make_batch(n=4)
    good = sparse_embed_specs(model)[0]
    bad = dataclasses.replace(good, name="not_a_module_name")
    tx = optim.masked(optax.adamw(1e-3), dense_trainable((bad,)))
    state, shardings = step_lib.init_state(
        model, tx, batch, mesh, dlrm_rules(), sparse_embed=(bad,))
    step = step_lib.jit_train_step(
        make_sparse_embed_train_step(model.apply, tx, losses.binary_xent, (bad,)),
        mesh, shardings)
    _, metrics = step(state, put_global(batch, mesh))
    assert not np.isfinite(float(jax.device_get(metrics["loss"])))


def test_trainer_wires_sparse_embed():
    """Trainer(sparse_embed=...) masks the optimizer off the tables and runs."""
    from distributeddeeplearningspark_tpu import Session, Trainer
    from distributeddeeplearningspark_tpu.rdd import PartitionedDataset

    session = Session.builder.master("local[1]").appName("se").getOrCreate()
    model = DLRM(vocab_sizes=VOCABS, embed_dim=8, bottom_mlp=(16, 8),
                 top_mlp=(16, 1))
    specs = sparse_embed_specs(model, lr=0.05)
    trainer = Trainer(session, model, losses.binary_xent, optax.adamw(1e-3),
                      rules=dlrm_rules(), sparse_embed=specs)
    examples = [dict(zip(("dense", "sparse", "label"), t)) for t in zip(
        np.random.default_rng(0).normal(0, 1, (32, 13)).astype(np.float32),
        np.stack([np.random.default_rng(1).integers(0, v, 32) for v in VOCABS],
                 1).astype(np.int32),
        np.zeros((32,), np.int32))]
    ds = PartitionedDataset.parallelize(examples, num_slices=2)
    state, summary = trainer.fit(ds.repeat(), batch_size=8, steps=6)
    assert np.isfinite(summary["loss"])
    assert state.embed_state["embedding"]["row_accum"].shape == (sum(VOCABS),)
    with pytest.raises(ValueError, match="accum_steps"):
        Trainer(session, model, losses.binary_xent, optax.adamw(1e-3),
                rules=dlrm_rules(), sparse_embed=specs, accum_steps=2)
