"""BERT MLM + text pipeline tests (config 3, SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from distributeddeeplearningspark_tpu.data import text as text_lib
from distributeddeeplearningspark_tpu.data.feed import host_batches, put_global
from distributeddeeplearningspark_tpu.models import bert_large, bert_tiny
from distributeddeeplearningspark_tpu.parallel.mesh import MeshSpec
from distributeddeeplearningspark_tpu.parallel.sharding import REPLICATED
from distributeddeeplearningspark_tpu.train import losses, optim, step as step_lib


def build_tokenizer():
    docs = text_lib.synthetic_wikipedia(64, num_partitions=2, seed=1)
    return text_lib.WordPieceTokenizer.train(docs.collect(), vocab_size=512)


class TestTokenizer:
    def test_roundtrip_known_words(self):
        tok = build_tokenizer()
        ids = tok.encode("the history of the city")
        assert ids and all(i not in (tok.unk_id,) for i in ids)
        assert tok.decode(ids) == "the history of the city"

    def test_char_fallback_no_unk(self):
        tok = build_tokenizer()
        # unseen word decomposes into char pieces, not UNK
        ids = tok.tokenize_word("zzzq")
        assert tok.unk_id not in ids or len(ids) == 1

    def test_save_load(self, tmp_path):
        tok = build_tokenizer()
        path = str(tmp_path / "vocab.txt")
        tok.save(path)
        tok2 = text_lib.WordPieceTokenizer.load(path)
        assert tok2.vocab == tok.vocab


class TestMasking:
    def test_shapes_and_mask_rate(self):
        tok = build_tokenizer()
        rng = np.random.default_rng(0)
        ids = np.array([tok.cls_id] + [10] * 126 + [tok.sep_id], np.int32)
        ex = text_lib.mask_tokens(ids, tok, rng)
        assert ex["input_ids"].shape == (128,)
        assert ex["mlm_labels"].shape == (128,)
        rate = ex["mlm_weights"].mean()
        assert 0.05 < rate < 0.30  # ~15%
        # specials never masked
        assert ex["mlm_weights"][0] == 0 and ex["mlm_weights"][-1] == 0
        # labels hold the ORIGINAL ids everywhere
        assert (ex["mlm_labels"] == ids).all()

    def test_pipeline_example_schema(self):
        tok = build_tokenizer()
        docs = text_lib.synthetic_wikipedia(16, num_partitions=2)
        ds = text_lib.mlm_dataset(docs, tok, seq_len=64)
        ex = ds.first()
        assert set(ex) == {"input_ids", "attention_mask", "mlm_labels", "mlm_weights"}
        assert all(v.shape == (64,) for v in ex.values())


def test_bert_forward_shapes():
    model = bert_tiny()
    batch = {
        "input_ids": np.ones((2, 32), np.int32),
        "attention_mask": np.ones((2, 32), np.int32),
    }
    variables = model.init(jax.random.PRNGKey(0), batch, train=False)
    logits = model.apply(variables, batch, train=False)
    assert logits.shape == (2, 32, model.cfg.vocab_size)
    assert logits.dtype == jnp.float32


def test_bert_large_geometry_param_count():
    """BertConfig.large must be the published BERT-large: ~340M params
    (Devlin et al. Table 1), counted abstractly via eval_shape — no 340M
    f32 init on the test host."""
    model = bert_large()
    batch = {"input_ids": jax.ShapeDtypeStruct((1, 16), np.int32)}
    abstract = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0), batch, train=False))
    n = sum(int(np.prod(leaf.shape))
            for leaf in jax.tree_util.tree_leaves(abstract))
    assert 3.2e8 < n < 3.6e8, n


def test_tied_decoder_shares_embedding():
    """The MLM decoder must reuse the token-embedding table (no second one)."""
    model = bert_tiny()
    batch = {"input_ids": np.ones((1, 16), np.int32)}
    variables = model.init(jax.random.PRNGKey(0), batch, train=False)
    flat = jax.tree_util.tree_flatten_with_path(variables["params"])[0]
    emb_tables = [p for p, v in flat if any("embedding" in str(k) for k in p)
                  and v.shape[-1] == model.cfg.hidden_size
                  and v.shape[0] == model.cfg.vocab_size]
    assert len(emb_tables) == 1  # token table exists once, not duplicated


def test_bert_mlm_learns(eight_devices):
    """DP MLM training on 8 fake chips: loss drops, masked acc beats chance."""
    mesh = MeshSpec(data=8).build(eight_devices)
    tok = build_tokenizer()
    model = bert_tiny(vocab_size=tok.vocab_size, num_layers=2, hidden_size=64,
                      num_heads=2, intermediate_size=128, dropout_rate=0.0)
    docs = text_lib.synthetic_wikipedia(256, num_partitions=8)
    ds = text_lib.mlm_dataset(docs, tok, seq_len=64).repeat()
    feed = host_batches(ds, 32, num_shards=8)

    tx = optim.adamw(optim.warmup_linear(3e-3, 10, 80))
    batch = next(feed)
    state, shardings = step_lib.init_state(model, tx, batch, mesh, REPLICATED)
    train_step = step_lib.jit_train_step(
        step_lib.make_train_step(model.apply, tx, losses.masked_lm),
        mesh, shardings,
    )
    first = last = None
    for i, hb in enumerate(feed):
        if i >= 60:
            break
        state, m = train_step(state, put_global(hb, mesh))
        if first is None:
            first = float(m["loss"])
        last = m
    assert float(last["loss"]) < first * 0.8
    assert float(last["mlm_accuracy"]) > 2.0 / tok.vocab_size


def test_gathered_mlm_head_matches_full_length():
    """mlm_positions gather: same loss/grads as the full-length head on the
    same targets (the original TPU BERT masked_lm_positions design)."""
    import optax

    from distributeddeeplearningspark_tpu.data.text import pack_mlm_predictions
    from distributeddeeplearningspark_tpu.models import bert_tiny
    from distributeddeeplearningspark_tpu.train import losses

    model = bert_tiny()
    V = model.cfg.vocab_size
    rng = np.random.default_rng(0)
    b, s, p = 2, 32, 8
    full = {
        "input_ids": rng.integers(0, V, (b, s)).astype(np.int32),
        "attention_mask": np.ones((b, s), np.int32),
        "mlm_labels": rng.integers(0, V, (b, s)).astype(np.int32),
        "mlm_weights": np.zeros((b, s), np.float32),
    }
    for i in range(b):  # 5 masked positions per row (< p)
        full["mlm_weights"][i, rng.choice(s, 5, replace=False)] = 1.0
    packed_rows = [pack_mlm_predictions(
        {k: v[i] for k, v in full.items()}, p) for i in range(b)]
    packed = {k: np.stack([r[k] for r in packed_rows]) for k in packed_rows[0]}

    variables = model.init(jax.random.PRNGKey(0), full, train=False)

    def loss_for(batch):
        def f(params):
            logits = model.apply({"params": params}, batch, train=False)
            return losses.masked_lm(logits, batch)[0]
        return f

    lf = jax.value_and_grad(loss_for(full))(variables["params"])
    lp = jax.value_and_grad(loss_for(packed))(variables["params"])
    np.testing.assert_allclose(float(lf[0]), float(lp[0]), rtol=2e-5)
    for a, b2 in zip(jax.tree.leaves(lf[1]), jax.tree.leaves(lp[1])):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b2, np.float32),
                                   rtol=5e-3, atol=2e-5)


def test_mlm_dataset_packed_form():
    from distributeddeeplearningspark_tpu.data.text import (
        WordPieceTokenizer, mlm_dataset)
    from distributeddeeplearningspark_tpu.rdd import PartitionedDataset

    tok = WordPieceTokenizer.train(
        ["the quick brown fox jumps over the lazy dog"] * 20, vocab_size=64)
    docs = PartitionedDataset.parallelize(
        ["the quick brown fox jumps over the lazy dog"] * 8, 2)
    ds = mlm_dataset(docs, tok, seq_len=16, max_predictions=4, seed=1)
    ex = ds.take(3)[1]
    assert set(ex) == {"input_ids", "attention_mask", "mlm_positions",
                      "mlm_labels", "mlm_weights"}
    assert ex["mlm_positions"].shape == (4,)
    assert ex["mlm_weights"].sum() >= 1
    # packed labels must equal the full-length example's ORIGINAL tokens at
    # the packed positions — verify against an identically-seeded unpacked run
    ds_full = mlm_dataset(docs, tok, seq_len=16, seed=1)
    full = ds_full.take(3)[1]
    for j in range(4):
        if ex["mlm_weights"][j] > 0:
            assert ex["mlm_labels"][j] == full["mlm_labels"][ex["mlm_positions"][j]]
            assert full["mlm_weights"][ex["mlm_positions"][j]] > 0
    # and the packed input_ids are the same corrupted stream
    np.testing.assert_array_equal(ex["input_ids"], full["input_ids"])


def _tiny_hf_bert():
    transformers = __import__("pytest").importorskip("transformers")
    HFBertConfig = transformers.BertConfig
    FlaxBertForMaskedLM = transformers.FlaxBertForMaskedLM

    hf_cfg = HFBertConfig(
        vocab_size=256, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=128,
        max_position_embeddings=64, type_vocab_size=2,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)
    return FlaxBertForMaskedLM(hf_cfg, seed=0), hf_cfg


def test_hf_bert_import_logits_parity():
    """import_hf_bert: our BertForMLM reproduces FlaxBertForMaskedLM logits
    on the same (randomly initialized) weights — full numerical parity of
    embeddings, encoder stack, and tied MLM head."""
    from distributeddeeplearningspark_tpu.models.bert import BertConfig, BertForMLM
    from distributeddeeplearningspark_tpu.models.bert_io import import_hf_bert

    hf_model, hf_cfg = _tiny_hf_bert()
    cfg = BertConfig(vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
                     intermediate_size=128, max_position=64,
                     dropout_rate=0.0, dtype=jnp.float32, attention_impl="xla")
    params = import_hf_bert(hf_model.params, cfg)

    rng = np.random.default_rng(0)
    ids = rng.integers(0, 256, (2, 16)).astype(np.int32)
    attn = np.ones((2, 16), np.int32)
    attn[1, 12:] = 0
    ours = BertForMLM(cfg).apply(
        {"params": params},
        {"input_ids": jnp.asarray(ids), "attention_mask": jnp.asarray(attn)},
        train=False)
    theirs = hf_model(input_ids=ids, attention_mask=attn).logits
    np.testing.assert_allclose(np.asarray(ours), np.asarray(theirs),
                               rtol=2e-4, atol=2e-4)


def test_hf_bert_export_round_trip():
    from distributeddeeplearningspark_tpu.models.bert import BertConfig
    from distributeddeeplearningspark_tpu.models.bert_io import (
        export_hf_bert, import_hf_bert)

    hf_model, _ = _tiny_hf_bert()
    cfg = BertConfig(vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
                     intermediate_size=128, max_position=64)
    ours = import_hf_bert(hf_model.params, cfg)
    back = export_hf_bert(ours, cfg)
    again = import_hf_bert(back, cfg)
    flat_a = jax.tree_util.tree_flatten_with_path(ours)[0]
    flat_b = jax.tree_util.tree_flatten_with_path(again)[0]
    assert len(flat_a) == len(flat_b)
    for (pa, a), (pb, b) in zip(flat_a, flat_b):
        assert pa == pb
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_hf_bert_torch_import_matches_flax_import():
    import pytest

    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    HFBertConfig, BertForMaskedLM = transformers.BertConfig, transformers.BertForMaskedLM

    from distributeddeeplearningspark_tpu.models.bert import BertConfig, BertForMLM
    from distributeddeeplearningspark_tpu.models.bert_io import import_hf_bert_torch

    hf_cfg = HFBertConfig(
        vocab_size=256, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=128,
        max_position_embeddings=64, type_vocab_size=2,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)
    torch.manual_seed(0)
    tmodel = BertForMaskedLM(hf_cfg).eval()
    cfg = BertConfig(vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
                     intermediate_size=128, max_position=64,
                     dropout_rate=0.0, dtype=jnp.float32, attention_impl="xla")
    params = import_hf_bert_torch(tmodel.state_dict(), cfg)

    rng = np.random.default_rng(1)
    ids = rng.integers(0, 256, (2, 16)).astype(np.int32)
    attn = np.ones((2, 16), np.int32)
    ours = BertForMLM(cfg).apply(
        {"params": params},
        {"input_ids": jnp.asarray(ids), "attention_mask": jnp.asarray(attn)},
        train=False)
    with torch.no_grad():
        theirs = tmodel(input_ids=torch.tensor(ids.astype(np.int64)),
                        attention_mask=torch.tensor(attn.astype(np.int64))).logits
    np.testing.assert_allclose(np.asarray(ours), theirs.numpy(),
                               rtol=2e-4, atol=2e-4)


class TestSequencePacking:
    """VERDICT r2 #4: packing honesty — packed windows are ~pad-free, the
    naive per-document mode is mostly padding, segment ids isolate documents."""

    def test_packed_windows_full_and_segmented(self):
        tok = build_tokenizer()
        docs = text_lib.synthetic_wikipedia(24, num_partitions=1).collect()
        pairs = list(text_lib.packed_segments_from_docs(docs, tok, 64))
        assert len(pairs) >= 2
        for ids, sids in pairs[:-1]:  # all but corpus tail: zero padding
            assert ids.shape == (64,) and sids.shape == (64,)
            assert not (ids == tok.pad_id).any()
            # segment ids are a nondecreasing doc counter within the window
            assert (np.diff(sids[1:-1]) >= 0).all()
        ids, sids = pairs[-1]
        assert ((ids == tok.pad_id) == (sids == -1)).all()

    def test_padded_mode_mostly_padding(self):
        tok = build_tokenizer()
        docs = text_lib.synthetic_wikipedia(32, num_partitions=2)
        packed = text_lib.mlm_dataset(docs, tok, seq_len=512)
        naive = text_lib.mlm_dataset(docs, tok, seq_len=512, pack=False)
        s_packed = text_lib.token_stats(packed)
        s_naive = text_lib.token_stats(naive)
        # synthetic docs are 60–120 words → well under 512 tokens each
        assert s_naive["pad_frac"] > 0.5
        assert s_packed["pad_frac"] < 0.1
        assert s_packed["effective_frac"] > s_naive["effective_frac"] + 0.4

    def test_mlm_dataset_emits_segment_ids(self):
        tok = build_tokenizer()
        docs = text_lib.synthetic_wikipedia(16, num_partitions=2)
        ex = text_lib.mlm_dataset(docs, tok, seq_len=64,
                                  segment_ids=True).first()
        assert "segment_ids" in ex and ex["segment_ids"].shape == (64,)
        # gathered form passes them through
        ex2 = text_lib.mlm_dataset(docs, tok, seq_len=64, segment_ids=True,
                                   max_predictions=12).first()
        assert "segment_ids" in ex2 and ex2["segment_ids"].shape == (64,)
        assert ex2["mlm_positions"].shape == (12,)

    def test_bert_consumes_segment_ids(self):
        """Packed batch with segment ids runs through the model, and doc
        isolation changes the output vs ignoring the ids."""
        model = bert_tiny(num_layers=1, hidden_size=32, num_heads=2,
                          intermediate_size=64, dropout_rate=0.0)
        rng = np.random.default_rng(5)
        ids = rng.integers(10, 500, (2, 32)).astype(np.int32)
        segs = np.zeros((2, 32), np.int32)
        segs[:, 16:] = 1
        batch = {"input_ids": ids, "attention_mask": np.ones_like(ids)}
        variables = model.init(jax.random.PRNGKey(0), batch, train=False)
        plain = model.apply(variables, batch, train=False)
        packed = model.apply(variables, {**batch, "segment_ids": segs},
                             train=False)
        assert np.isfinite(np.asarray(packed)).all()
        assert not np.allclose(np.asarray(plain), np.asarray(packed))
        # isolation: with segment ids, doc 0's logits equal running doc 0
        # alone (positions are absolute either way)
        alone = model.apply(
            variables,
            {"input_ids": ids[:, :16],
             "attention_mask": np.ones((2, 16), np.int32)},
            train=False)
        np.testing.assert_allclose(np.asarray(packed)[:, :16],
                                   np.asarray(alone), atol=1e-5, rtol=1e-5)
