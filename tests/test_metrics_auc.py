"""StreamingAUC (config 4's real metric) and nucleus sampling."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributeddeeplearningspark_tpu.metrics import StreamingAUC, auc_from_predictions


class TestStreamingAUC:
    def test_matches_exact_auc(self):
        """Binned estimator vs the exact rank statistic on random scores."""
        rng = np.random.default_rng(0)
        n = 20_000
        labels = (rng.random(n) < 0.25).astype(np.int32)
        # informative but noisy scores
        scores = np.clip(0.35 * labels + rng.normal(0.3, 0.2, n), 0, 1)

        def exact_auc(s, y):
            order = np.argsort(s, kind="stable")
            ranks = np.empty(n, np.float64)
            # average ranks for ties
            s_sorted = s[order]
            r = np.arange(1, n + 1, dtype=np.float64)
            i = 0
            while i < n:
                j = i
                while j + 1 < n and s_sorted[j + 1] == s_sorted[i]:
                    j += 1
                r[i:j + 1] = (i + 1 + j + 1) / 2
                i = j + 1
            ranks[order] = r
            npos = y.sum()
            return (ranks[y > 0].sum() - npos * (npos + 1) / 2) / (
                npos * (n - npos))

        auc = StreamingAUC()
        # stream in chunks — order must not matter
        for lo in range(0, n, 1111):
            auc.update(scores[lo:lo + 1111], labels[lo:lo + 1111])
        got, want = auc.compute(), exact_auc(scores, labels)
        assert abs(got - want) < 2e-3, (got, want)

    def test_perfect_and_random_and_inverted(self):
        y = np.array([0, 0, 1, 1])
        perfect = StreamingAUC(); perfect.update([0.1, 0.2, 0.8, 0.9], y)
        assert perfect.compute() == 1.0
        inverted = StreamingAUC(); inverted.update([0.9, 0.8, 0.2, 0.1], y)
        assert inverted.compute() == 0.0
        ties = StreamingAUC(); ties.update([0.5, 0.5, 0.5, 0.5], y)
        assert ties.compute() == 0.5

    def test_single_class_nan(self):
        auc = StreamingAUC()
        auc.update([0.5, 0.6], [1, 1])
        assert np.isnan(auc.compute())

    def test_from_predictions_stream(self):
        preds = [([0.9], [1]), ([0.1], [0]), ([0.8], [1]), ([0.3], [0])]
        assert auc_from_predictions(iter(preds)) == 1.0

    def test_shape_mismatch_rejected(self):
        auc = StreamingAUC()
        with pytest.raises(ValueError, match="scores"):
            auc.update([0.5, 0.6], [1])


class TestTopPSampling:
    def test_nucleus_truncates_tail(self):
        from distributeddeeplearningspark_tpu.models.llama_gen import _sample

        # one dominant token (p≈0.73), a mid token, and a long tail
        logits = jnp.asarray(np.array(
            [[5.0, 3.0, 0.0, -1.0, -1.0, -1.0]], np.float32))
        keys = jax.random.split(jax.random.PRNGKey(0), 256)
        toks = np.array([
            int(_sample(logits, k, temperature=1.0, top_k=0, top_p=0.5)[0])
            for k in keys])
        # top_p=0.5: only the argmax survives (its mass alone ≥ 0.5 … the
        # first sorted token is always kept and the second's prefix mass
        # 0.73 ≥ 0.5 cuts it)
        assert set(toks) == {0}

    def test_top_p_one_is_plain_sampling(self):
        from distributeddeeplearningspark_tpu.models.llama_gen import _sample

        logits = jnp.asarray(np.zeros((1, 4), np.float32))
        keys = jax.random.split(jax.random.PRNGKey(1), 128)
        toks = {int(_sample(logits, k, temperature=1.0, top_k=0, top_p=1.0)[0])
                for k in keys}
        assert toks == {0, 1, 2, 3}  # uniform logits: everything reachable

    def test_composes_with_top_k(self):
        from distributeddeeplearningspark_tpu.models.llama_gen import _sample

        logits = jnp.asarray(np.array([[4.0, 3.0, 2.0, 1.0]], np.float32))
        keys = jax.random.split(jax.random.PRNGKey(2), 128)
        toks = {int(_sample(logits, k, temperature=1.0, top_k=3, top_p=0.95)[0])
                for k in keys}
        assert 3 not in toks  # k-truncated
        assert 0 in toks


def test_from_predictions_with_inputs_shape():
    """The Trainer.predict(with_inputs=True) pair shape: (example, score)."""
    stream = iter([
        ({"label": np.int32(1), "dense": np.zeros(3)}, np.float32(0.9)),
        ({"label": np.int32(0), "dense": np.zeros(3)}, np.float32(0.2)),
        ({"label": np.int32(1), "dense": np.zeros(3)}, np.float32(0.7)),
        ({"label": np.int32(0), "dense": np.zeros(3)}, np.float32(0.4)),
    ])
    assert auc_from_predictions(stream) == 1.0


def test_from_predictions_max_examples_stops_stream():
    pulled = []

    def gen():
        for i in range(1000):
            pulled.append(i)
            yield (np.float64(i % 2), np.int64(i % 2))

    auc = auc_from_predictions(gen(), max_examples=10)
    assert len(pulled) == 10
    assert auc == 1.0
