"""Scheduler surfaces: dlsubmit --cluster/--priority, the preemption-
notice channel, sched edges in the incident timeline / chrome trace /
``dlstatus --cluster``, and a real end-to-end launch of a trivial job.
"""

import json
import os
import sys

from distributeddeeplearningspark_tpu import cli, faults, status, telemetry
from distributeddeeplearningspark_tpu.scheduler import core, ledger
from distributeddeeplearningspark_tpu.scheduler import __main__ as sched_cli
from distributeddeeplearningspark_tpu.telemetry import health
from distributeddeeplearningspark_tpu.telemetry import trace as trace_lib


class FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        self.t += 1.0
        return self.t


# -- the preemption-notice channel (faults.py) --------------------------------


def test_preempt_notice_roundtrip(tmp_path):
    path = str(tmp_path / "PREEMPT")
    assert faults.read_preempt_notice(path) is None
    faults.deliver_preempt_notice(path, host=2, step=17)
    n = faults.read_preempt_notice(path)
    assert n == faults.PreemptNotice(host=2, step=17)
    # consumption retires it (rename, crash-safe) so a relaunch after the
    # drain does not re-drain on a stale notice
    faults.consume_preempt_notice(path, ordinal=3)
    assert faults.read_preempt_notice(path) is None
    assert os.path.exists(path + ".consumed-3")
    # consuming a missing/None path is a no-op, never a raise
    faults.consume_preempt_notice(path, ordinal=4)
    faults.consume_preempt_notice(None, ordinal=4)


def test_preempt_notice_env_lookup(tmp_path, monkeypatch):
    monkeypatch.delenv(faults.PREEMPT_NOTICE_ENV, raising=False)
    assert faults.preempt_notice_path() is None
    path = str(tmp_path / "PREEMPT")
    monkeypatch.setenv(faults.PREEMPT_NOTICE_ENV, path)
    assert faults.preempt_notice_path() == path
    faults.deliver_preempt_notice(path, host=0, step=5)
    assert faults.read_preempt_notice() == faults.PreemptNotice(0, 5)


def test_read_preempt_notice_never_raises_on_garbage(tmp_path):
    path = str(tmp_path / "PREEMPT")
    with open(path, "w") as f:
        f.write('{"host": "nope')
    assert faults.read_preempt_notice(path) is None


# -- dlsubmit: --priority stamping + --cluster submission ---------------------


def test_dlsubmit_priority_exported_and_stamped(tmp_path, monkeypatch):
    # setenv (not delenv) so monkeypatch records an undo and the exports
    # cli.main makes below cannot leak past this test; the placeholder
    # values prove cli.main overwrites rather than inherits them
    monkeypatch.setenv(telemetry.TENANT_ENV, "placeholder")
    monkeypatch.setenv(telemetry.PRIORITY_ENV, "0")
    script = tmp_path / "probe.py"
    script.write_text(
        "import os\n"
        "assert os.environ['DLS_PRIORITY'] == '7'\n"
        "assert os.environ['DLS_TENANT'] == 'research'\n")
    rc = cli.main(["--tenant", "research", "--priority", "7", str(script)])
    assert rc == 0
    # ...and the env var is what EventWriter stamps on every record
    w = telemetry.EventWriter(tmp_path, process="p0", clock=FakeClock())
    w.heartbeat(step=1)
    w.close()
    [e] = [e for e in telemetry.read_events(tmp_path)
           if e["kind"] == "heartbeat"]
    assert e["priority"] == 7 and e["tenant"] == "research"


def test_event_writer_priority_param_overrides_env(tmp_path, monkeypatch):
    monkeypatch.setenv(telemetry.PRIORITY_ENV, "3")
    w = telemetry.EventWriter(tmp_path, process="p0", clock=FakeClock(),
                              priority=9)
    w.heartbeat(step=1)
    w.close()
    [e] = [e for e in telemetry.read_events(tmp_path)
           if e["kind"] == "heartbeat"]
    assert e["priority"] == 9


def test_dlsubmit_cluster_enqueues_instead_of_running(
        tmp_path, capsys, monkeypatch):
    monkeypatch.delenv(telemetry.TENANT_ENV, raising=False)
    monkeypatch.delenv(telemetry.PRIORITY_ENV, raising=False)
    root = str(tmp_path / "pool")
    ledger.init_cluster(root, hosts=2, quotas={"research": 2})
    script = tmp_path / "train.py"
    script.write_text("raise SystemExit('must not run at submit time')\n")
    rc = cli.main([
        "--cluster", root, "--tenant", "research", "--priority", "10",
        "--hosts", "2", "--min-hosts", "1", "--name", "mnist",
        "--conf", "spark.executor.instances=2",
        str(script), "--ckpt-dir", "{ckpt}"])
    assert rc == 0
    job_id = capsys.readouterr().out.strip()
    st = ledger.load_state(root)
    j = st.jobs[job_id]
    assert j.status == "PENDING"
    assert j.tenant == "research" and j.priority == 10
    assert j.gangs == (2,) and j.min_hosts == 1
    assert j.name == "mnist"
    # the command re-enters the script through the interpreter, args kept
    assert j.cmd[0] == sys.executable
    assert j.cmd[1] == str(script)
    assert j.cmd[2:] == ("--ckpt-dir", "{ckpt}")
    # conf rides along as the same DLS_CONF_* contract direct mode uses
    assert j.env[cli.CONF_ENV_PREFIX + "spark__executor__instances"] == "2"
    # ...in the JOB's env only: a cluster submit must not leak conf or
    # tenant/priority exports into the submitting process (a later
    # Session.builder in this process would silently pick them up)
    assert cli.CONF_ENV_PREFIX + "spark__executor__instances" not in os.environ
    assert telemetry.TENANT_ENV not in os.environ
    assert telemetry.PRIORITY_ENV not in os.environ


def test_dlsubmit_cluster_gangs_flag(tmp_path, capsys):
    root = str(tmp_path / "pool")
    ledger.init_cluster(root, hosts=4)
    script = tmp_path / "mpmd.py"
    script.write_text("pass\n")
    assert cli.main(["--cluster", root, "--tenant", "t", "--gangs", "2,2",
                     "--kind", "mpmd", str(script)]) == 0
    job_id = capsys.readouterr().out.strip()
    j = ledger.load_state(root).jobs[job_id]
    assert j.gangs == (2, 2) and j.min_hosts == 4 and j.kind == "mpmd"


# -- operator CLI (python -m ...scheduler) ------------------------------------


def test_scheduler_cli_init_tick_status(tmp_path, capsys):
    root = str(tmp_path / "pool")
    assert sched_cli.main(["init", root, "--hosts", "2",
                           "--quota", "a=1"]) == 0
    cfg = json.loads(capsys.readouterr().out)
    assert cfg["hosts"] == ["h0", "h1"] and cfg["quotas"] == {"a": 1}
    s = core.Scheduler(root, clock=FakeClock())
    s.submit(["true"], tenant="a", priority=0, gangs=1, name="x")
    s.close()
    assert sched_cli.main(["tick", root, "--no-launch"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["placed"] == ["j000"]
    assert sched_cli.main(["status", root]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["tenants"]["a"] == {"used": 1, "quota": 1}


# -- sched edges in the observability surfaces --------------------------------


def _preempted_cluster(tmp_path):
    """A state dir where j000 was shrink-preempted for j001."""
    root = str(tmp_path / "pool")
    ledger.init_cluster(root, hosts=2)
    s = core.Scheduler(root, clock=FakeClock())
    lo = s.submit(["true"], tenant="research", priority=0, gangs=2,
                  min_hosts=1, name="train-lo")
    s.tick(launch=False)
    ledger.append(root, "launch", lo, pid=os.getpid())
    hi = s.submit(["true"], tenant="prod", priority=5, gangs=1,
                  name="serve-hi")
    s.tick(launch=False)  # delivers the shrink preemption
    s.close()
    return root, lo, hi


def test_incident_timeline_folds_sched_edges(tmp_path):
    root, lo, hi = _preempted_cluster(tmp_path)
    # the scheduler's own stream carries every edge
    rows = health.incident_timeline(
        telemetry.read_events(ledger.sched_dir(root)))
    types = [r["type"] for r in rows]
    assert "sched-submit" in types and "sched-place" in types
    [pre] = [r for r in rows if r["type"] == "sched-preempt"]
    assert pre["key"] == lo
    assert pre["severity"] == "WARN"
    assert pre["who"] == "tenant research"
    assert "shrink" in pre["summary"] and f"for {hi}" in pre["summary"]
    # the victim's own workdir got the mirror: its timeline shows its
    # preemption without reading the scheduler's stream
    wd = ledger.load_state(root).jobs[lo].workdir
    mine = health.incident_timeline(telemetry.read_events(wd))
    assert [r["type"] for r in mine if r["type"].startswith("sched")] \
        == ["sched-place", "sched-preempt"]


def test_chrome_trace_renders_sched_instants(tmp_path):
    root, lo, hi = _preempted_cluster(tmp_path)
    doc = trace_lib.chrome_trace(
        telemetry.read_events(ledger.sched_dir(root)))
    instants = [e for e in doc["traceEvents"] if e.get("cat") == "sched"]
    assert instants, "sched edges must land on the trace"
    assert all(e["ph"] == "i" and e["s"] == "g" for e in instants)
    names = {e["name"] for e in instants}
    assert f"sched-preempt {lo}" in names
    [pre] = [e for e in instants if e["name"] == f"sched-preempt {lo}"]
    assert pre["args"]["mode"] == "shrink"
    assert pre["args"]["victim_of"] == hi
    # they share the alerts row: markers line up against the spans
    rows = [e for e in doc["traceEvents"]
            if e.get("ph") == "M" and e["name"] == "thread_name"]
    assert any(e["args"]["name"] == "alerts" for e in rows)


def test_dlstatus_cluster_renders_scheduler_section(tmp_path, capsys):
    root, lo, hi = _preempted_cluster(tmp_path)
    assert status.main(["--cluster", root]) == 0
    out = capsys.readouterr().out
    assert "scheduler: hosts 0/2 free" in out
    assert "train-lo" in out and "serve-hi" in out
    assert "draining g1" in out        # the victim's in-flight drain
    assert "PENDING" in out            # the beneficiary still queued
    # --json carries the sched block verbatim for machine consumers
    assert status.main(["--cluster", root, "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["sched"] == ledger.load_state(root).to_report()
    by_id = {j["job"]: j for j in doc["sched"]["jobs"]}
    assert by_id[lo]["draining"] == 1
    assert by_id[hi]["status"] == "PENDING"


def test_workdir_kind_sched(tmp_path):
    root, lo, hi = _preempted_cluster(tmp_path)
    events = telemetry.read_events(ledger.sched_dir(root))
    assert health._workdir_kind(events) == "sched"


# -- end to end: a real launch through the runner -----------------------------


def test_scheduler_launches_trivial_job_to_completion(tmp_path):
    root = str(tmp_path / "pool")
    ledger.init_cluster(root, hosts=1)
    script = tmp_path / "hello.py"
    script.write_text(
        "import os, sys\n"
        "assert os.environ['DLS_TENANT'] == 't1'\n"
        "assert os.environ['DLS_PRIORITY'] == '2'\n"
        "assert os.environ['DLS_PREEMPT_NOTICE']\n"
        "ckpt = sys.argv[sys.argv.index('--ckpt-dir') + 1]\n"
        "assert os.path.isdir(ckpt), ckpt\n"
        "print('hello from', os.environ.get('DLS_PROCESS_ID'))\n")
    s = core.Scheduler(root)
    try:
        jid = s.submit(
            [sys.executable, str(script), "--ckpt-dir", "{ckpt}"],
            tenant="t1", priority=2, gangs=1, name="hello")
        s.run(interval=0.2, max_ticks=100, until_idle=True)
    finally:
        s.close()
    st = ledger.load_state(root)
    j = st.jobs[jid]
    assert j.status == "COMPLETED" and j.rc == 0, \
        open(os.path.join(j.workdir, "runner.log")).read()
    assert "hello from 0" in open(
        os.path.join(j.workdir, "runner.log")).read()
    # the runner's verdict landed in the job's own stream too
    kinds = [(e["kind"], e.get("edge")) for e in
             telemetry.read_events(j.workdir)]
    assert ("sched", "complete") in kinds
