"""Fused 1×1-conv + BN-stats epilogue kernel (VERDICT r2 next-#2).

Interpret-mode parity on CPU: the Pallas matmul must equal jnp.dot, its
epilogue stats must equal whole-tensor reductions, gradients must match the
unfused chain (the stats cotangents fold into dY), and the Conv1x1BN module
must be numerically interchangeable with the reference XLA chain inside a
real bottleneck training step.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributeddeeplearningspark_tpu.ops.conv_bn import Conv1x1BN, matmul_stats


def _xw(m=64, k=32, n=128, seed=0, dtype=np.float32):
    rng = np.random.default_rng(seed)
    return (jnp.asarray(rng.normal(0, 1, (m, k)).astype(dtype)),
            jnp.asarray(rng.normal(0, 0.1, (k, n)).astype(dtype)))


class TestMatmulStats:
    def test_matches_dot_and_reductions(self):
        x, w = _xw()
        y, s1, s2 = matmul_stats(x, w, 32, 64, 32)
        want = jnp.dot(x, w)
        np.testing.assert_allclose(np.asarray(y), np.asarray(want),
                                   atol=1e-5, rtol=1e-5)
        np.testing.assert_allclose(np.asarray(s1), np.asarray(want.sum(0)),
                                   atol=1e-3, rtol=1e-5)
        np.testing.assert_allclose(np.asarray(s2),
                                   np.asarray((want * want).sum(0)),
                                   atol=1e-3, rtol=1e-5)

    def test_single_block(self):
        x, w = _xw(m=8, k=16, n=16, seed=1)
        y, s1, s2 = matmul_stats(x, w)
        np.testing.assert_allclose(np.asarray(y), np.asarray(jnp.dot(x, w)),
                                   atol=1e-5, rtol=1e-5)

    def test_gradients_match_unfused(self):
        """A loss using y, mean AND var: stats cotangents exercise the
        dY + ds1 + 2·Y·ds2 fold."""
        x, w = _xw(m=32, k=16, n=32, seed=2)
        m = x.shape[0]

        def loss_fused(x, w):
            y, s1, s2 = matmul_stats(x, w, 16, 16, 16)
            mean = s1 / m
            var = s2 / m - mean * mean
            return (jnp.sum(y ** 2) * 0.01 + jnp.sum(mean ** 2)
                    + jnp.sum(jnp.sqrt(var + 1e-5)))

        def loss_ref(x, w):
            y = jnp.dot(x, w)
            mean = y.mean(0)
            var = (y * y).mean(0) - mean * mean
            return (jnp.sum(y ** 2) * 0.01 + jnp.sum(mean ** 2)
                    + jnp.sum(jnp.sqrt(var + 1e-5)))

        gf = jax.grad(loss_fused, argnums=(0, 1))(x, w)
        gr = jax.grad(loss_ref, argnums=(0, 1))(x, w)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4, rtol=1e-4)

    def test_bad_shapes_rejected(self):
        x, w = _xw(m=30, k=16, n=32)
        with pytest.raises(ValueError, match="divisible"):
            matmul_stats(x, w, 16, 16, 16)
        with pytest.raises(ValueError, match="mismatch"):
            matmul_stats(x, jnp.zeros((8, 32)))


def _apply(module, x, *, train, seed=0):
    variables = module.init(jax.random.PRNGKey(seed), x, train=False)
    out, updates = module.apply(variables, x, train=train,
                                mutable=["batch_stats"])
    return variables, out, updates


class TestConv1x1BN:
    def test_fused_matches_unfused_forward(self):
        rng = np.random.default_rng(3)
        x = jnp.asarray(rng.normal(0, 1, (2, 8, 8, 16)).astype(np.float32))
        fused = Conv1x1BN(32, dtype=jnp.float32, fused=True)
        plain = Conv1x1BN(32, dtype=jnp.float32, fused=False)
        v1, out_f, up_f = _apply(fused, x, train=True)
        v2, out_p, up_p = _apply(plain, x, train=True)
        # same init (same structure/seed) → same params
        chex_equal = jax.tree_util.tree_all(jax.tree.map(
            lambda a, b: bool(jnp.allclose(a, b)), v1["params"], v2["params"]))
        assert chex_equal
        np.testing.assert_allclose(np.asarray(out_f), np.asarray(out_p),
                                   atol=2e-5, rtol=2e-5)
        for k in ("mean", "var"):
            np.testing.assert_allclose(
                np.asarray(up_f["batch_stats"][k]),
                np.asarray(up_p["batch_stats"][k]), atol=2e-5, rtol=2e-5)

    def test_matches_flax_conv_bn_chain(self):
        """The unfused reference itself must equal nn.Conv → nn.BatchNorm."""
        from flax import linen as nn

        rng = np.random.default_rng(4)
        x = jnp.asarray(rng.normal(0, 1, (2, 4, 4, 8)).astype(np.float32))

        class Chain(nn.Module):
            @nn.compact
            def __call__(self, x, *, train):
                y = nn.Conv(16, (1, 1), use_bias=False, dtype=jnp.float32,
                            name="conv")(x)
                return nn.BatchNorm(use_running_average=not train,
                                    momentum=0.9, epsilon=1e-5,
                                    dtype=jnp.float32, name="bn")(y)

        chain = Chain()
        vc = chain.init(jax.random.PRNGKey(0), x, train=False)
        ours = Conv1x1BN(16, dtype=jnp.float32, fused=True)
        vo = ours.init(jax.random.PRNGKey(0), x, train=False)
        # transplant the chain's params into our layout
        vo = {
            "params": {
                "kernel": vc["params"]["conv"]["kernel"],
                "scale": vc["params"]["bn"]["scale"],
                "bias": vc["params"]["bn"]["bias"],
            },
            "batch_stats": {
                "mean": vc["batch_stats"]["bn"]["mean"],
                "var": vc["batch_stats"]["bn"]["var"],
            },
        }
        for train in (True, False):
            want, up_c = chain.apply(vc, x, train=train, mutable=["batch_stats"])
            got, up_o = ours.apply(vo, x, train=train, mutable=["batch_stats"])
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       atol=2e-5, rtol=2e-5)
            if train:
                # the running-stat UPDATES must match flax too (biased batch
                # variance, no Bessel term — the eval path depends on it)
                for k in ("mean", "var"):
                    np.testing.assert_allclose(
                        np.asarray(up_o["batch_stats"][k]),
                        np.asarray(up_c["batch_stats"]["bn"][k]),
                        atol=2e-5, rtol=2e-5, err_msg=k)

    def test_gradients_match_unfused(self):
        rng = np.random.default_rng(5)
        x = jnp.asarray(rng.normal(0, 1, (2, 4, 4, 16)).astype(np.float32))
        fused = Conv1x1BN(32, dtype=jnp.float32, fused=True)
        plain = Conv1x1BN(32, dtype=jnp.float32, fused=False)
        v = fused.init(jax.random.PRNGKey(1), x, train=False)

        def loss(params, module):
            out, _ = module.apply(
                {"params": params, "batch_stats": v["batch_stats"]}, x,
                train=True, mutable=["batch_stats"])
            return jnp.sum(out ** 2)

        gf = jax.grad(loss)(v["params"], fused)
        gp = jax.grad(loss)(v["params"], plain)
        for (pf, a), (pp, b) in zip(
                jax.tree_util.tree_leaves_with_path(gf),
                jax.tree_util.tree_leaves_with_path(gp)):
            assert pf == pp
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4, rtol=1e-4, err_msg=str(pf))

    def test_running_stats_update_and_eval_path(self):
        rng = np.random.default_rng(6)
        x = jnp.asarray(rng.normal(2.0, 3.0, (4, 4, 4, 16)).astype(np.float32))
        mod = Conv1x1BN(16, dtype=jnp.float32, fused=True)
        v, _, up = _apply(mod, x, train=True)
        # running stats moved toward the batch stats
        assert not np.allclose(np.asarray(up["batch_stats"]["mean"]), 0.0)
        # eval uses running stats (no batch stats → output differs from train)
        out_eval, _ = mod.apply(v, x, train=False, mutable=["batch_stats"])
        assert np.isfinite(np.asarray(out_eval)).all()


def test_resnet_fused_flag_end_to_end():
    """ResNet-50-shaped tiny net with fused_conv_bn trains a step and
    matches the unfused model's forward on identical params."""
    from distributeddeeplearningspark_tpu.models.resnet import ResNet, BottleneckBlock

    kw = dict(stage_sizes=(1, 1), block_cls=BottleneckBlock, num_classes=10,
              width=16, dtype=jnp.float32)
    fused = ResNet(fused_conv_bn=True, **kw)
    plain = ResNet(fused_conv_bn=False, **kw)
    rng = np.random.default_rng(7)
    batch = {"image": rng.normal(0, 1, (2, 32, 32, 3)).astype(np.float32)}
    vf = fused.init(jax.random.PRNGKey(0), batch, train=False)
    # param trees differ in nesting (conv_bn_* vs Conv_*/BatchNorm_*) —
    # compare leaf counts and total size instead of transplanting
    vp = plain.init(jax.random.PRNGKey(0), batch, train=False)
    nf = sum(np.size(l) for l in jax.tree_util.tree_leaves(vf["params"]))
    npl = sum(np.size(l) for l in jax.tree_util.tree_leaves(vp["params"]))
    assert nf == npl  # same parameterization, different grouping
    out, ups = fused.apply(vf, batch, train=True, mutable=["batch_stats"])
    assert out.shape == (2, 10) and np.isfinite(np.asarray(out)).all()
    # gradient flows through the fused kernel
    g = jax.grad(lambda p: fused.apply(
        {**vf, "params": p}, batch, train=True,
        mutable=["batch_stats"])[0].sum())(vf["params"])
    assert all(np.isfinite(np.asarray(l)).all()
               for l in jax.tree_util.tree_leaves(g))
