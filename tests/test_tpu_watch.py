"""tools/tpu_watch.py resume logic — the r5 chip-window collector.

The watcher decides which queue items still need a run by parsing the
append-only JSONL; a wrong 'done' classification either re-burns a real
chip window on completed items or (the r5 review's finding) silently
ends the watch with evidence missing. scan_records must share bench's
is_good_record rule exactly.
"""

import importlib.util
import json
import os

import pytest


@pytest.fixture()
def watch():
    path = os.path.join(os.path.dirname(__file__), "..", "tools",
                        "tpu_watch.py")
    spec = importlib.util.spec_from_file_location("tpu_watch", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _write(path, recs):
    with open(path, "w") as f:
        for r in recs:
            # raw strings land as-is (corrupt/truncated-line fixtures);
            # dicts as JSON records
            f.write((r if isinstance(r, str) else json.dumps(r)) + "\n")


def test_scan_records_good_vs_failed(watch, tmp_path):
    out = tmp_path / "q.jsonl"
    _write(out, [
        {"item": "probe", "ok": True},                        # ignored
        {"item": "llama_7b", "rc": 0,
         "record": {"metric": "llama_lora_tokens_per_sec_per_chip",
                    "value": 0.0}},                           # OOM evidence: good
        {"item": "bert", "rc": 0,
         "record": {"metric": "bench_failed", "value": 0.0}},  # failure
        {"item": "bert", "rc": 0,
         "record": {"metric": "bench_failed", "value": 0.0}},  # failure #2
        {"item": "memval", "rc": -1,
         "record": {"error": "timed out after 1200s"}},        # timeout
        {"item": "kernels_mosaic", "rc": 0,
         "record": {"metric": "pallas_kernels_compiled",
                    "value": 0.0}},                            # all-FAIL kernels
        {"item": "dlrm_scatter_ab", "rc": 0,
         "record": {"metric": "dlrm_examples_per_sec_per_chip",
                    "value": 250000.0}},                       # good
        '{"item": "truncated-mid-write", "rc": 0, "reco',  # corrupt line
        '"a bare json string"',                            # non-dict JSON
    ])
    ok, failed = watch.scan_records(str(out))
    assert ok == {"llama_7b", "dlrm_scatter_ab"}
    assert failed == {"bert": 2, "memval": 1, "kernels_mosaic": 1}


def test_scan_records_retry_then_success_counts_done(watch, tmp_path):
    out = tmp_path / "q.jsonl"
    _write(out, [
        {"item": "bert", "rc": 0, "record": {"metric": "bench_failed"}},
        {"item": "bert", "rc": 0,
         "record": {"metric": "bert_base_mlm_tokens_per_sec_per_chip",
                    "value": 117000.0}},
    ])
    ok, failed = watch.scan_records(str(out))
    # a later success wins; earlier failures still counted (attempt cap
    # input) but the item is done
    assert ok == {"bert"}
    assert failed == {"bert": 1}


def test_scan_records_missing_file(watch, tmp_path):
    ok, failed = watch.scan_records(str(tmp_path / "nope.jsonl"))
    assert ok == set() and failed == {}


def test_queue_report_renders_r4_artifact(capsys):
    """tools/queue_report.py must render the checked-in r4 artifact: every
    record line becomes a citable bullet (the BASELINE.md same-day-update
    step is mechanical, per VERDICT r4 next-#1's done-condition)."""
    path = os.path.join(os.path.dirname(__file__), "..", "tools",
                        "queue_report.py")
    spec = importlib.util.spec_from_file_location("queue_report", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    art = os.path.join(os.path.dirname(__file__), "..",
                       "CHIP_QUEUE_r04.jsonl")
    if not os.path.exists(art):
        pytest.skip("r4 artifact not present")
    assert mod.main([art]) == 0
    out = capsys.readouterr().out
    assert "all_model" in out and "9 good records" in out
    assert "citable" in out
