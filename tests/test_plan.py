"""Unified GSPMD Plan compile layer (ISSUE 15): Plan validation (incl. the
tensor-axis skew guard), serialization, layout fingerprint parity through
the new layer, ZeRO weight-update sharding (bitwise vs the replicated
optimizer + memory_analysis evidence), per-plan donation, the shard_map
compile style, plan-tagged compile ledger rows, and the plan_sweep
ranking."""

import dataclasses
import importlib.util
import json
import os
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from distributeddeeplearningspark_tpu import telemetry
from distributeddeeplearningspark_tpu.data.feed import put_global, stack_examples
from distributeddeeplearningspark_tpu.models import (
    LlamaConfig,
    LlamaForCausalLM,
    llama_rules,
)
from distributeddeeplearningspark_tpu.parallel import plan as plan_lib
from distributeddeeplearningspark_tpu.parallel.mesh import MeshSpec
from distributeddeeplearningspark_tpu.parallel.plan import (
    DP,
    Plan,
    PlanError,
    PlanTensorAxisWarning,
    PlanValidationError,
    compile_step_with_plan,
    plan_for_rules,
    stage_plan,
    zero_plan,
)
from distributeddeeplearningspark_tpu.parallel.sharding import (
    REPLICATED,
    ShardingRules,
    add_axis_spec,
    path_str,
)
from distributeddeeplearningspark_tpu.telemetry import anatomy
from distributeddeeplearningspark_tpu.train import losses, step as step_lib


def _load_plan_sweep():
    path = os.path.join(os.path.dirname(__file__), "..", "tools",
                        "plan_sweep.py")
    spec = importlib.util.spec_from_file_location("plan_sweep", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _llama_batch(cfg, rows=8, seq=16):
    return stack_examples([
        {"input_ids": np.full((seq,), i % cfg.vocab_size, np.int32),
         "loss_mask": np.ones((seq,), np.float32)}
        for i in range(rows)])


# -- validation ---------------------------------------------------------------


def test_validate_rejects_unknown_axes():
    mesh = MeshSpec(data=-1).build()
    with pytest.raises(PlanValidationError, match="do not exist"):
        Plan(name="bad", batch_axes=("data", "nonsense")).validate(mesh)
    with pytest.raises(PlanValidationError, match="do not exist"):
        Plan(name="bad2",
             rules=ShardingRules(rules=((r"w", P("warp")),))).validate(mesh)
    with pytest.raises(PlanValidationError, match="style"):
        Plan(name="bad3", style="pmap").validate(mesh)
    with pytest.raises(PlanValidationError, match="replica"):
        # zero axes must be replica (batch) axes — 'seq' replicates nothing
        Plan(name="bad4", zero_axes=("seq",)).validate(mesh)
    DP.validate(mesh)  # sane plan passes


def test_tensor_axis_guard_warns_and_strict_refuses(monkeypatch):
    mesh = MeshSpec(data=-1, tensor=2).build()
    monkeypatch.delenv(plan_lib.TENSOR_ESCAPE_ENV, raising=False)
    with pytest.warns(PlanTensorAxisWarning, match="1.2%"):
        DP.validate(mesh)
    with pytest.raises(PlanValidationError, match="DLS_PLAN_ALLOW_TENSOR"):
        DP.validate(mesh, strict=True)
    # the escape hatch silences both (re-probed-on-a-newer-jax override)
    monkeypatch.setenv(plan_lib.TENSOR_ESCAPE_ENV, "1")
    with warnings.catch_warnings():
        warnings.simplefilter("error", PlanTensorAxisWarning)
        DP.validate(mesh)
        DP.validate(mesh, strict=True)


def test_tensor_mesh_refuses_whole_sweep(monkeypatch):
    monkeypatch.delenv(plan_lib.TENSOR_ESCAPE_ENV, raising=False)
    sweep = _load_plan_sweep()
    mesh = MeshSpec(data=-1, tensor=2).build()
    cfg = LlamaConfig.tiny()
    with pytest.raises(PlanValidationError, match="Refusing to sweep"):
        sweep.run_sweep(mesh, cfg, _llama_batch(cfg), steps=1)


# -- serialization / identity -------------------------------------------------


def test_plan_roundtrip_and_signature(tmp_path):
    cfg = LlamaConfig.tiny()
    p = Plan(name="ulysses+fsdp",
             rules=llama_rules(cfg, fsdp=True, fsdp_min_size=1),
             seq_axis="seq", zero_axes=("data",),
             model_hints=(("attention_impl", "ulysses"),),
             description="composed layout")
    path = str(tmp_path / "p.plan.json")
    p.save(path)
    q = Plan.load(path)
    assert q == p
    assert q.signature() == p.signature()
    assert q.hints() == {"attention_impl": "ulysses"}
    # description is NOT identity: same compile-relevant content, same sig
    r = dataclasses.replace(p, description="different words")
    assert r.signature() == p.signature()
    assert dataclasses.replace(p, zero_axes=()).signature() != p.signature()
    la = q.logical_axes()
    assert la["batch"] == ("data", "fsdp")
    assert la["sequence"] == ("seq",)
    assert la["weight_update"] == ("data",)
    assert "tensor" in la["params"] and "fsdp" in la["params"]
    # a record claiming a future format refuses instead of misparsing
    rec = p.to_record()
    rec["plan_format"] = 99
    with pytest.raises(PlanError, match="newer"):
        Plan.from_record(rec)


def test_plan_for_rules_naming():
    assert plan_for_rules(REPLICATED).name == "dp"
    assert plan_for_rules(ShardingRules(fsdp=True)).name == "fsdp"
    p = plan_for_rules(REPLICATED, context_parallel=True)
    assert p.name == "dp+seq" and p.seq_axis == "seq"


def test_stage_plan_names():
    cfg = LlamaConfig.tiny()
    assert stage_plan("replicated").rules == ShardingRules()
    assert stage_plan("fsdp", fsdp_min_size=64).rules.fsdp
    assert stage_plan("zero").zero_axes == ("data", "fsdp")
    assert stage_plan("tensor", cfg).rules.rules  # llama TP rules present
    with pytest.raises(PlanError, match="tensor.*cfg"):
        stage_plan("tensor")
    with pytest.raises(PlanError, match="unknown stage plan"):
        stage_plan("magic")


# -- add_axis_spec (the generalized auto-shard pass) --------------------------


def test_add_axis_spec_placement():
    mesh = MeshSpec(data=2, fsdp=2, seq=2).build()
    # single axis on the largest divisible dim
    assert add_axis_spec(P(), (8, 4), mesh, ("data",), 1) == P("data", None)
    # multi-axis tuple lands on ONE dim divisible by the product
    assert add_axis_spec(P(), (8, 3), mesh, ("data", "fsdp"), 1) == \
        P(("data", "fsdp"), None)
    # no dim takes the product: axes placed separately (tie on dim size
    # resolves to the later dim, the rule engine's max() tiebreak)
    assert add_axis_spec(P(), (2, 2), mesh, ("data", "fsdp"), 1) == \
        P("fsdp", "data")
    # below min size / already mentioned / indivisible: untouched
    assert add_axis_spec(P(), (2, 2), mesh, ("data",), 1000) == P()
    assert add_axis_spec(P("data"), (8, 4), mesh, ("data",), 1) == P("data")
    assert add_axis_spec(P(), (3, 5), mesh, ("data",), 1) == P()


# -- the ZeRO plan + fingerprint parity (one shared compiled setup) -----------


@pytest.fixture(scope="module")
def zero_vs_replicated():
    """Replicated-DP vs ZeRO-plan train setups on the same tiny llama —
    shared by the parity/memory/donation/ledger tests below (compiles are
    the expensive part; pay them once)."""
    mesh = MeshSpec(data=4).build(jax.devices()[:4])
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    batch = _llama_batch(cfg)
    gbatch = put_global(batch, mesh)
    out = {}
    for name, plan in (("dp", DP), ("zero", zero_plan(DP, axes=("data",)))):
        tx = plan.wrap_optimizer(optax.adam(1e-3), mesh)
        state, shardings = step_lib.init_state(
            model, tx, batch, mesh, plan.rules, plan=plan)
        step = compile_step_with_plan(
            step_lib.make_train_step(model.apply, tx, losses.causal_lm),
            plan, mesh, state_shardings=shardings, name=f"t-{name}",
            instrument=True)
        ledger = step.prepare(state, gbatch)
        donated = state
        traj = []
        for _ in range(3):
            state, metrics = step(state, gbatch)
            traj.append(float(jax.device_get(metrics["loss"])))
        out[name] = {
            "plan": plan, "shardings": shardings, "ledger": ledger,
            "step": step, "donated": donated, "losses": traj,
            "params": jax.device_get(state.params),
            "opt": jax.device_get(state.opt_state),
        }
    return out


def test_zero_plan_shards_optimizer_state(zero_vs_replicated):
    sh = zero_vs_replicated["zero"]["shardings"]
    flat = [(path_str(p), s) for p, s in
            jax.tree_util.tree_flatten_with_path(sh)[0]]
    opt = [(p, s) for p, s in flat if p.startswith("opt_state")
           and hasattr(s, "spec")]
    sharded = [p for p, s in opt if "data" in str(s.spec)]
    assert sharded, "no optimizer-state leaf sharded over the replica axis"
    # params stay replicated (this is weight-UPDATE sharding, not FSDP)
    for p, s in flat:
        if p.startswith("params"):
            assert "data" not in str(s.spec), (p, s)


def test_zero_plan_memory_analysis_evidence(zero_vs_replicated):
    """The anatomy ledger's memory_analysis is the acceptance evidence:
    the ZeRO executable's per-device argument bytes must drop vs the
    replicated layout (Adam moments stop being replicated 4x)."""
    rep = zero_vs_replicated["dp"]["ledger"]
    zero = zero_vs_replicated["zero"]["ledger"]
    assert rep and rep.get("argument_bytes"), rep
    assert zero and zero.get("argument_bytes"), zero
    assert zero["argument_bytes"] < 0.75 * rep["argument_bytes"], (
        rep["argument_bytes"], zero["argument_bytes"])


def test_zero_plan_matches_replicated_bitwise(zero_vs_replicated):
    """ZeRO weight-update sharding is a LAYOUT, not different math: the
    3-step loss trajectory, final params, and final optimizer state all
    match the replicated optimizer bit for bit (Plan.wrap_optimizer pins
    the gradient all-reduce; without it GSPMD's reduce-scatter order
    drifts the trajectory at step 2 — measured on this jax)."""
    rep, zero = zero_vs_replicated["dp"], zero_vs_replicated["zero"]
    assert rep["losses"] == zero["losses"]
    for a, b in zip(jax.tree.leaves(rep["params"]),
                    jax.tree.leaves(zero["params"])):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree.leaves(rep["opt"]),
                    jax.tree.leaves(zero["opt"])):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_donation_frees_input_state_per_plan(zero_vs_replicated):
    """donate_state=True plans actually free the donated buffers — the
    input state of the first step call is deleted for BOTH layouts."""
    for name in ("dp", "zero"):
        donated = zero_vs_replicated[name]["donated"]
        leaves = jax.tree.leaves(donated.params)
        assert leaves and all(x.is_deleted() for x in leaves), name


def test_compile_ledger_rows_carry_plan_identity(zero_vs_replicated):
    for name in ("dp", "zero"):
        step = zero_vs_replicated[name]["step"]
        plan = zero_vs_replicated[name]["plan"]
        rec = step.records[-1]
        assert rec["plan"] == plan.name
        assert rec["plan_sig"] == plan.signature()
        s = step.compile_summary()
        assert s["plan"] == plan.name and s["plan_sig"] == plan.signature()


def test_plan_path_matches_direct_jit_bitwise():
    """Fingerprint parity: the SAME step jitted directly (the pre-plan
    wiring) and compiled through the plan layer produce bit-identical
    losses and post-step params — the layer changes where compiles are
    declared, never what they compute."""
    mesh = MeshSpec(data=2, fsdp=2).build(jax.devices()[:4])
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    rules = llama_rules(cfg, fsdp_min_size=1)
    batch = _llama_batch(cfg)
    tx = optax.sgd(1e-2)
    train = step_lib.make_train_step(model.apply, tx, losses.causal_lm)

    st1, sh = step_lib.init_state(model, tx, batch, mesh, rules)
    direct = jax.jit(train, in_shardings=(sh, None),
                     out_shardings=(sh, NamedSharding(mesh, P())),
                     donate_argnums=(0,))
    st1, m1 = direct(st1, put_global(batch, mesh))

    st2, sh2 = step_lib.init_state(model, tx, batch, mesh, rules)
    plan = Plan(name="llama-fsdp", rules=rules)
    planned = compile_step_with_plan(train, plan, mesh,
                                     state_shardings=sh2, instrument=False)
    st2, m2 = planned(st2, put_global(batch, mesh))

    assert float(jax.device_get(m1["loss"])) == \
        float(jax.device_get(m2["loss"]))
    for a, b in zip(jax.tree.leaves(jax.device_get(st1.params)),
                    jax.tree.leaves(jax.device_get(st2.params))):
        assert np.array_equal(np.asarray(a), np.asarray(b))


# -- shard_map style ----------------------------------------------------------


def test_shard_map_style_matches_jit_style():
    """A map-style step (explicit all_reduce_mean over the batch axes)
    compiled via style='shard_map' equals the jit-style GSPMD step on the
    same data — the one compile path serves both idioms."""
    from distributeddeeplearningspark_tpu.parallel import collectives

    mesh = MeshSpec(data=4).build(jax.devices()[:4])
    w0 = np.linspace(-1, 1, 8).astype(np.float32).reshape(2, 4)
    x = np.arange(32, dtype=np.float32).reshape(8, 4) / 32.0
    y = np.ones((8, 2), np.float32)

    def grads_of(state, batch):
        def loss(w):
            pred = batch["x"] @ w.T
            return jnp.mean((pred - batch["y"]) ** 2)

        return jax.grad(loss)(state["w"])

    def map_step(state, batch):
        g = grads_of(state, batch)
        g = collectives.all_reduce_mean({"w": g}, ("data", "fsdp"))["w"]
        new = {"w": state["w"] - 0.1 * g}
        return new, {"gnorm": jnp.sqrt(jnp.sum(
            collectives.all_reduce_mean({"g": g},
                                        ("data", "fsdp"))["g"] ** 2))}

    def jit_step(state, batch):
        g = grads_of(state, batch)
        new = {"w": state["w"] - 0.1 * g}
        return new, {"gnorm": jnp.sqrt(jnp.sum(g ** 2))}

    rep = NamedSharding(mesh, P())
    row = NamedSharding(mesh, P(("data", "fsdp")))
    batch = {"x": jax.device_put(x, row), "y": jax.device_put(y, row)}

    sm_plan = Plan(name="map-style", style="shard_map", donate_state=False)
    sm = compile_step_with_plan(
        map_step, sm_plan, mesh,
        state_shardings={"w": rep}, instrument=False)
    s1, m1 = sm({"w": jax.device_put(w0, rep)}, batch)

    jp = compile_step_with_plan(
        jit_step, Plan(name="gspmd", donate_state=False), mesh,
        state_shardings={"w": rep}, instrument=False)
    s2, m2 = jp({"w": jax.device_put(w0, rep)}, batch)

    np.testing.assert_allclose(np.asarray(s1["w"]), np.asarray(s2["w"]),
                               rtol=1e-6)
    np.testing.assert_allclose(float(m1["gnorm"]), float(m2["gnorm"]),
                               rtol=1e-6)


# -- trainer integration ------------------------------------------------------


def test_trainer_accepts_plan_and_tags_ledger(tmp_path):
    """Trainer(plan=...) trains end to end with the plan's layout, the
    instrumented train step carries the plan identity, and telemetry
    compile events + chrome_trace compile spans are plan-tagged."""
    from distributeddeeplearningspark_tpu.session import Session
    from distributeddeeplearningspark_tpu.telemetry.trace import chrome_trace
    from distributeddeeplearningspark_tpu.train.trainer import Trainer
    from distributeddeeplearningspark_tpu.rdd import PartitionedDataset

    import flax.linen as nn

    class TinyMLP(nn.Module):
        @nn.compact
        def __call__(self, batch, train=False):
            h = nn.Dense(16)(batch["x"])
            return nn.Dense(2)(nn.relu(h))

    def loss_fn(outputs, batch):
        onehot = jax.nn.one_hot(batch["label"], 2)
        loss = jnp.mean(optax.softmax_cross_entropy(outputs, onehot))
        return loss, {"loss": loss}

    rng = np.random.default_rng(0)
    examples = [{"x": rng.normal(0, 1, (8,)).astype(np.float32),
                 "label": np.int32(i % 2)} for i in range(64)]
    ds = PartitionedDataset.parallelize(examples, 2)
    telemetry.configure(tmp_path)
    try:
        spec = MeshSpec(data=4)
        session = Session("plan-test", {}, spec.build(jax.devices()[:4]),
                          spec)
        plan = dataclasses.replace(
            zero_plan(DP, axes=("data",), name="mlp-zero"),
            zero_min_size=64)  # the tiny MLP's leaves still shard
        tr = Trainer(session, TinyMLP(), loss_fn, optax.adam(1e-2),
                     plan=plan)
        os.environ["DLS_TELEMETRY_DIR"] = str(tmp_path)
        try:
            _, summary = tr.fit(ds, batch_size=16, steps=4, log_every=2)
        finally:
            os.environ.pop("DLS_TELEMETRY_DIR", None)
        assert np.isfinite(summary["loss"])
        assert tr._train_step.plan_name == "mlp-zero"
        events = telemetry.read_events(tmp_path)
        comp = [e for e in events if e.get("kind") == "compile"
                and e.get("fn") == "train_step"]
        assert comp and comp[0]["plan"] == "mlp-zero"
        assert comp[0]["plan_sig"] == plan.signature()
        # opt state actually sharded over the replica axis
        flat = [(path_str(p), s) for p, s in
                jax.tree_util.tree_flatten_with_path(tr.state_shardings)[0]]
        assert any(p.startswith("opt_state") and "data" in str(s.spec)
                   for p, s in flat if hasattr(s, "spec"))
        # chrome_trace: the compile span's args carry the plan tag
        trace = chrome_trace(events)
        spans = [e for e in trace["traceEvents"]
                 if e.get("name") == "compile" and e.get("ph") in ("X", "B")]
        assert spans and any(
            e["args"].get("plan") == "mlp-zero" for e in spans), spans
    finally:
        telemetry.reset()


def test_trainer_rejects_shard_map_plans():
    """Trainer's step bodies rely on GSPMD's implicit grad reduction —
    a shard_map plan would silently skip it, so construction refuses."""
    from distributeddeeplearningspark_tpu.session import Session
    from distributeddeeplearningspark_tpu.train.trainer import Trainer

    spec = MeshSpec(data=4)
    session = Session("plan-style-test", {}, spec.build(jax.devices()[:4]),
                      spec)
    with pytest.raises(PlanValidationError, match="style='jit'"):
        Trainer(session, object(), lambda o, b: (o, {}), optax.sgd(1e-2),
                plan=Plan(name="mapstyle", style="shard_map"))


# -- anatomy report / dlstatus ------------------------------------------------


def test_anatomy_report_by_fn_carries_plan():
    events = [
        {"kind": "compile", "ts": 1.0, "fn": "plan:dp", "sig": "f32[2]",
         "sig_hash": "aa", "compile_s": 0.5, "flops": 10.0,
         "bytes_accessed": 100.0, "plan": "dp", "plan_sig": "0123456789ab",
         "recompile": False, "aot": True},
        {"kind": "compile", "ts": 2.0, "fn": "plan:zero", "sig": "f32[2]",
         "sig_hash": "bb", "compile_s": 0.6, "plan": "dp+zero",
         "plan_sig": "ba9876543210", "recompile": False, "aot": True},
    ]
    rep = anatomy.anatomy_report(events)
    by_fn = rep["compile_ledger"]["by_fn"]
    assert by_fn["plan:dp"]["plan"] == "dp"
    assert by_fn["plan:dp"]["plan_sig"] == "0123456789ab"
    assert by_fn["plan:zero"]["plan"] == "dp+zero"
    assert all(e.get("plan") for e in rep["compile_ledger"]["events"])


# -- plan sweep ---------------------------------------------------------------


def test_plan_sweep_ranks_and_pins(tmp_path):
    sweep = _load_plan_sweep()
    mesh = MeshSpec(data=4).build(jax.devices()[:4])
    cfg = LlamaConfig.tiny()
    batch, digest = sweep._build_batch(cfg, 8, 16)
    assert digest == sweep._build_batch(cfg, 8, 16)[1]
    report = sweep.run_sweep(mesh, cfg, batch, steps=2, warmup=1,
                             rerun_steps=1, only={"dp", "dp+zero", "fsdp"})
    ranked = report["ranked"]
    assert {r["plan"] for r in ranked} == {"dp", "dp+zero"}
    times = [r["step_time_s"] for r in ranked]
    assert times == sorted(times)
    # fsdp needs an fsdp axis > 1: skipped WITH a reason, not missing
    sk = [r for r in report["skipped"] if r["plan"] == "fsdp"]
    assert sk and "mesh axes too small" in sk[0]["reason"]
    assert report["winner"] == ranked[0]["plan"]
    assert report["winner_rerun_new_compiles"] == 0
    for r in ranked:
        assert r["compiles"] == 1 and r["recompiles"] == 0
        assert r["steps_per_sec"] and r["compile_s"] is not None
        assert "_runtime" not in r
    # the winner serializes and re-loads identically (the pin contract)
    plans, _ = sweep.build_candidates(mesh, cfg,
                                      only={report["winner"]})
    path = str(tmp_path / "w.plan.json")
    plans[0].save(path)
    assert Plan.load(path).signature() == report["winner_sig"]


def test_pipeline_stage_plan_spec_parsing():
    from distributeddeeplearningspark_tpu.train.pipeline_trainer import (
        _stage_plan,
    )

    cfg = LlamaConfig.tiny()
    spec = {"stage_plans": {"0": "fsdp", "1": "tensor"}}
    assert _stage_plan(spec, 0, cfg).rules.fsdp
    assert _stage_plan(spec, 1, cfg).rules.rules
    # legacy key still honored
    assert _stage_plan({"stage_rules": {"0": "zero"}}, 0, cfg).zero_axes
    # inline serialized plan record (a pinned sweep winner)
    rec = zero_plan(DP, name="pinned").to_record()
    assert _stage_plan({"stage_plans": {"0": rec}}, 0, cfg).name == "pinned"
    with pytest.raises(ValueError, match="DLS_PIPE_SPEC"):
        _stage_plan({"stage_rules": {"0": "magic"}}, 0, cfg)
