"""Pallas flash attention vs dense XLA attention (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributeddeeplearningspark_tpu.ops.attention import (
    _pick_impl,
    _xla_attention,
    padding_mask,
)
from distributeddeeplearningspark_tpu.ops.flash_attention import flash_attention


def _qkv(b=2, s=128, h=2, d=32, seed=0, dtype=np.float32, hkv=None):
    rng = np.random.default_rng(seed)
    mk = lambda hh: jnp.asarray(rng.normal(0, 1, (b, s, hh, d)).astype(dtype))
    hkv = hkv or h
    return mk(h), mk(hkv), mk(hkv)


def _pad_mask(b, s, valid, seed=0):
    """[B, S] 1/0 attention mask with `valid` real tokens per row."""
    am = np.zeros((b, s), np.int32)
    am[:, :valid] = 1
    return jnp.asarray(am)


def _dense(q, k, v, *, mask=None, causal=False):
    """XLA reference; expands GQA KV heads the reference way (repeat)."""
    h, hkv = q.shape[2], k.shape[2]
    if h != hkv:
        k = jnp.repeat(k, h // hkv, axis=2)
        v = jnp.repeat(v, h // hkv, axis=2)
    return _xla_attention(q, k, v, bias=None, mask=mask, causal=causal, scale=None)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_dense(causal):
    q, k, v = _qkv()
    want = _dense(q, k, v, causal=causal)
    got = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_gradients_match_dense(causal):
    q, k, v = _qkv(b=1, s=64, h=2, d=16, seed=3)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal,
                                       block_q=32, block_k=32) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(_dense(q, k, v, causal=causal) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   atol=2e-4, rtol=2e-4)


# -- key-padding masks (the BERT case: VERDICT r1 item 2) --------------------

@pytest.mark.parametrize("mask_shape", ["bs", "b11s"])
def test_flash_padding_mask_matches_dense(mask_shape):
    b, s = 2, 128
    q, k, v = _qkv(b=b, s=s)
    am = _pad_mask(b, s, valid=80)
    mask = am if mask_shape == "bs" else padding_mask(am)
    want = _dense(q, k, v, mask=padding_mask(am))
    got = flash_attention(q, k, v, mask=mask, block_q=64, block_k=64)
    # padded *query* rows still attend (masked in the loss downstream); all
    # rows must agree since the mask is key-only
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


def test_flash_padding_mask_gradients_match_dense():
    b, s = 1, 64
    q, k, v = _qkv(b=b, s=s, h=2, d=16, seed=5)
    am = _pad_mask(b, s, valid=40)
    # weight like a real loss: only valid query rows contribute
    w = jnp.asarray(np.asarray(am), jnp.float32)[:, :, None, None]

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, mask=am, block_q=32, block_k=32)
        return jnp.sum((o * w) ** 2)

    def loss_dense(q, k, v):
        o = _dense(q, k, v, mask=padding_mask(am))
        return jnp.sum((o * w) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   atol=2e-4, rtol=2e-4)


def test_flash_fully_masked_key_block_no_nan():
    # valid tokens confined to the first of two key blocks: the second block
    # is fully masked for every row and must contribute exactly nothing
    b, s = 1, 64
    q, k, v = _qkv(b=b, s=s, h=1, d=16, seed=9)
    am = _pad_mask(b, s, valid=32)
    got = flash_attention(q, k, v, mask=am, block_q=32, block_k=32)
    assert np.isfinite(np.asarray(got)).all()
    want = _dense(q, k, v, mask=padding_mask(am))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


def test_flash_rejects_query_varying_mask():
    q, k, v = _qkv(s=64)
    with pytest.raises(NotImplementedError, match="key-only"):
        flash_attention(q, k, v, mask=jnp.ones((2, 1, 64, 64), bool))


# -- GQA (grouped KV without jnp.repeat: VERDICT r1 item 2) ------------------

@pytest.mark.parametrize("causal", [False, True])
def test_flash_gqa_matches_dense(causal):
    q, k, v = _qkv(b=2, s=128, h=4, hkv=2, d=32, seed=11)
    want = _dense(q, k, v, causal=causal)
    got = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


def test_flash_gqa_gradients_match_dense():
    q, k, v = _qkv(b=1, s=64, h=4, hkv=2, d=16, seed=13)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True,
                                       block_q=32, block_k=32) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(_dense(q, k, v, causal=True) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   atol=2e-4, rtol=2e-4)


def test_flash_gqa_masked_causal_combined():
    b, s = 2, 64
    q, k, v = _qkv(b=b, s=s, h=4, hkv=2, d=16, seed=17)
    am = _pad_mask(b, s, valid=48)
    want = _dense(q, k, v, mask=padding_mask(am), causal=True)
    got = flash_attention(q, k, v, mask=am, causal=True, block_q=32, block_k=32)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


def test_flash_gqa_bad_head_ratio_rejected():
    q, k, v = _qkv(b=1, s=64, h=4, hkv=3, d=16)
    with pytest.raises(ValueError, match="multiple"):
        flash_attention(q, k, v)


# -- auto impl selection -----------------------------------------------------

def test_pick_impl_routes_bert_and_gqa_on_tpu(monkeypatch):
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    q = jnp.zeros((2, 512, 12, 64))        # BERT-base: S=512, d=64
    kv = jnp.zeros((2, 512, 12, 64))
    bert_mask = padding_mask(jnp.ones((2, 512), jnp.int32))
    # BERT with its padding mask rides the kernel (in-model measured faster)
    assert _pick_impl(q, kv, None, bert_mask) == "flash"
    # GQA llama: 8 q heads / 2 kv heads, long seq
    q2 = jnp.zeros((1, 8192, 8, 128))
    kv2 = jnp.zeros((1, 8192, 2, 128))
    assert _pick_impl(q2, kv2, None, None) == "flash"
    # q-varying mask → xla
    assert _pick_impl(q, kv, None, jnp.ones((2, 1, 512, 512), bool)) == "xla"
    # bias → xla
    assert _pick_impl(q, kv, jnp.zeros((2, 12, 512, 512)), None) == "xla"
    # threshold override forces the XLA path (A/B timing escape hatch)
    monkeypatch.setenv("DLS_FLASH_MIN_SEQ", "100000")
    assert _pick_impl(q, kv, None, bert_mask) == "xla"
    assert _pick_impl(q2, kv2, None, None) == "xla"


def test_flash_uneven_blocks_rejected():
    q, k, v = _qkv(s=96)
    with pytest.raises(ValueError, match="divide"):
        flash_attention(q, k, v, block_q=64, block_k=64)


def test_flash_bf16_close_to_f32_reference():
    q, k, v = _qkv(s=64, d=32, seed=7)
    want = _dense(q, k, v, causal=True)
    got = flash_attention(*(x.astype(jnp.bfloat16) for x in (q, k, v)),
                          causal=True, block_q=32, block_k=32)
    np.testing.assert_allclose(np.asarray(got, np.float32), np.asarray(want),
                               atol=5e-2, rtol=5e-2)


# -- packed-sequence segment ids (VERDICT r2 #4: BERT packing) ---------------

def _seg_ids(b, s, boundaries, seed=0):
    """[B, S] int32 segment ids: `boundaries[i]` = doc-start offsets of row i."""
    out = np.zeros((b, s), np.int32)
    for i, starts in enumerate(boundaries):
        for d, st in enumerate(starts):
            out[i, st:] = d
    return jnp.asarray(out)


def _seg_mask(segs):
    """Dense [B, 1, S, S] attend-mask equivalent of segment-id blocking."""
    return (segs[:, None, :, None] == segs[:, None, None, :])


class TestSegmentIds:
    def test_forward_matches_dense(self):
        q, k, v = _qkv(b=2, s=128, h=2, d=32, seed=7)
        segs = _seg_ids(2, 128, [[0, 40, 90], [0, 64]])
        want = _dense(q, k, v, mask=_seg_mask(segs))
        got = flash_attention(q, k, v, segment_ids=segs, block_q=64, block_k=64)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-5, rtol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_gradients_match_dense(self, causal):
        q, k, v = _qkv(b=1, s=64, h=2, d=16, seed=8)
        segs = _seg_ids(1, 64, [[0, 17, 40]])

        def loss_flash(a, b_, c):
            return jnp.sum(flash_attention(
                a, b_, c, causal=causal, segment_ids=segs,
                block_q=32, block_k=32) ** 2)

        def loss_dense(a, b_, c):
            return jnp.sum(_dense(a, b_, c, mask=_seg_mask(segs),
                                  causal=causal) ** 2)

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
        for a, b_ in zip(gf, gd):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       atol=2e-4, rtol=2e-4)

    def test_composes_with_padding_mask(self):
        """Packed tail window: padding mask AND segment ids together (pads
        additionally carry segment -1, the pipeline's convention)."""
        b, s = 2, 128
        q, k, v = _qkv(b=b, s=s, seed=9)
        am = _pad_mask(b, s, 100)
        segs = np.array(_seg_ids(b, s, [[0, 30], [0, 77]]))
        segs[:, 100:] = -1
        segs = jnp.asarray(segs)
        want = _dense(q, k, v, mask=_seg_mask(segs) & padding_mask(am))
        got = flash_attention(q, k, v, mask=padding_mask(am), segment_ids=segs,
                              block_q=64, block_k=64)
        w, g = np.asarray(want), np.asarray(got)
        # valid rows agree; pad q rows: flash emits zeros (fully-masked-row
        # convention) — assert finite
        np.testing.assert_allclose(g[:, :100], w[:, :100], atol=2e-5, rtol=2e-5)
        assert np.isfinite(g).all()

    def test_gqa_with_segments(self):
        q, k, v = _qkv(b=2, s=128, h=4, d=32, seed=10, hkv=2)
        segs = _seg_ids(2, 128, [[0, 50], [0]])
        want = _dense(q, k, v, mask=_seg_mask(segs))
        got = flash_attention(q, k, v, segment_ids=segs, block_q=64, block_k=64)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-5, rtol=2e-5)

    def test_bad_shape_rejected(self):
        q, k, v = _qkv()
        with pytest.raises(ValueError, match="segment_ids"):
            flash_attention(q, k, v, segment_ids=jnp.zeros((2, 64), jnp.int32))
