"""Pallas flash attention vs dense XLA attention (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributeddeeplearningspark_tpu.ops.attention import _xla_attention
from distributeddeeplearningspark_tpu.ops.flash_attention import flash_attention


def _qkv(b=2, s=128, h=2, d=32, seed=0, dtype=np.float32):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(0, 1, (b, s, h, d)).astype(dtype))
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_dense(causal):
    q, k, v = _qkv()
    want = _xla_attention(q, k, v, bias=None, mask=None, causal=causal, scale=None)
    got = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_gradients_match_dense(causal):
    q, k, v = _qkv(b=1, s=64, h=2, d=16, seed=3)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal,
                                       block_q=32, block_k=32) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(_xla_attention(q, k, v, bias=None, mask=None,
                                      causal=causal, scale=None) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   atol=2e-4, rtol=2e-4)


def test_flash_uneven_blocks_rejected():
    q, k, v = _qkv(s=96)
    with pytest.raises(ValueError, match="divide"):
        flash_attention(q, k, v, block_q=64, block_k=64)


def test_flash_rejects_mask():
    q, k, v = _qkv(s=64)
    with pytest.raises(NotImplementedError):
        flash_attention(q, k, v, mask=jnp.ones((2, 1, 1, 64), bool))


def test_flash_bf16_close_to_f32_reference():
    q, k, v = _qkv(s=64, d=32, seed=7)
    want = _xla_attention(q, k, v, bias=None, mask=None, causal=True, scale=None)
    got = flash_attention(*(x.astype(jnp.bfloat16) for x in (q, k, v)),
                          causal=True, block_q=32, block_k=32)
    np.testing.assert_allclose(np.asarray(got, np.float32), np.asarray(want),
                               atol=5e-2, rtol=5e-2)
