"""Checkpoint/resume: round-trip, reshard-on-restore, retention, trainer resume.

Covers SURVEY.md §5 'Checkpoint/resume' — the TPU-first replacement for the
reference's driver-side torch.save/load + re-broadcast (§3.4).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributeddeeplearningspark_tpu import Checkpointer, PartitionedDataset, Trainer
from distributeddeeplearningspark_tpu.models import LeNet5
from distributeddeeplearningspark_tpu.parallel.mesh import MeshSpec
from distributeddeeplearningspark_tpu.parallel.sharding import FSDP, REPLICATED
from distributeddeeplearningspark_tpu.session import Session
from distributeddeeplearningspark_tpu.train import losses, step as step_lib


def _sample_batch(n=8):
    rng = np.random.default_rng(0)
    return {
        "image": rng.normal(0, 1, (n, 28, 28, 1)).astype(np.float32),
        "label": rng.integers(0, 10, (n,)).astype(np.int32),
    }


def _host_tree(tree):
    return jax.tree.map(lambda x: np.asarray(jax.device_get(x)), tree)


def _assert_trees_equal(a, b):
    fa, fb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(fa) == len(fb)
    for x, y in zip(fa, fb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_roundtrip_sharded_state(tmp_path, eight_devices):
    mesh = MeshSpec(data=2, fsdp=4).build()
    model = LeNet5()
    tx = optax.adamw(1e-3)
    batch = _sample_batch()
    state, shardings = step_lib.init_state(model, tx, batch, mesh, FSDP)

    with Checkpointer(tmp_path / "ckpt", async_save=True) as ckpt:
        assert ckpt.latest_step() is None
        ckpt.save(5, state, data_state={"examples_seen": 40})
        ckpt.wait()
        assert ckpt.latest_step() == 5
        restored, data_state = ckpt.restore(state, shardings=shardings)
    _assert_trees_equal(_host_tree(state), _host_tree(restored))
    assert data_state == {"examples_seen": 40}
    # restore honored the requested shardings
    flat_r = jax.tree.leaves(restored)
    flat_s = jax.tree.leaves(shardings, is_leaf=lambda x: hasattr(x, "spec"))
    for arr, sh in zip(flat_r, flat_s):
        assert arr.sharding.is_equivalent_to(sh, arr.ndim)


def test_reshard_on_restore(tmp_path, eight_devices):
    """Write replicated on an 8-way DP mesh; restore FSDP-sharded on 2x4."""
    model = LeNet5()
    tx = optax.sgd(0.1)
    batch = _sample_batch()

    mesh_a = MeshSpec(data=8).build()
    state_a, _ = step_lib.init_state(model, tx, batch, mesh_a, REPLICATED, seed=3)
    with Checkpointer(tmp_path / "ckpt", async_save=False) as ckpt:
        ckpt.save(1, state_a)
        ckpt.wait()

        mesh_b = MeshSpec(data=2, fsdp=4).build()
        abstract = jax.eval_shape(lambda s: s, state_a)
        from distributeddeeplearningspark_tpu.parallel.sharding import state_shardings

        sh_b = state_shardings(abstract, mesh_b, FSDP)
        restored, _ = ckpt.restore(abstract, shardings=sh_b)
    _assert_trees_equal(_host_tree(state_a), _host_tree(restored))
    # at least one large param actually came back sharded over fsdp
    specs = {str(l.sharding.spec) for l in jax.tree.leaves(restored.params)}
    assert any("fsdp" in s for s in specs)


def test_retention(tmp_path, eight_devices):
    mesh = MeshSpec(data=8).build()
    state, _ = step_lib.init_state(
        LeNet5(), optax.sgd(0.1), _sample_batch(), mesh, REPLICATED
    )
    with Checkpointer(tmp_path / "ckpt", max_to_keep=2, async_save=False) as ckpt:
        for s in (1, 2, 3, 4):
            ckpt.save(s, state)
        ckpt.wait()
        assert ckpt.all_steps() == [3, 4]


def test_trainer_resume_matches_uninterrupted_run(tmp_path):
    """3 steps + crash + resume for 3 == 6 straight steps, bit-exact."""
    rng = np.random.default_rng(7)
    examples = [
        {"image": rng.normal(0, 1, (28, 28, 1)).astype(np.float32),
         "label": np.int32(i % 10)}
        for i in range(96)
    ]
    batch_size = 16

    def make_trainer(ckpt):
        sess = Session.builder.master("local[2]").getOrCreate()
        ds = PartitionedDataset.parallelize(examples, 2)
        t = Trainer(sess, LeNet5(), losses.softmax_xent, optax.sgd(0.1, momentum=0.9),
                    checkpointer=ckpt, seed=11)
        return t, ds

    # uninterrupted 6 steps
    t0, ds = make_trainer(None)
    state6, _ = t0.fit(ds, batch_size=batch_size, steps=6, log_every=100)
    Session._active and Session._active.stop()

    # 3 steps, checkpoint, "crash"
    with Checkpointer(tmp_path / "ck", async_save=False) as ck:
        t1, ds = make_trainer(ck)
        t1.fit(ds, batch_size=batch_size, steps=3, checkpoint_every=3, log_every=100)
        Session._active and Session._active.stop()

        # fresh process analogue: new trainer, restore, continue with skip
        t2, ds = make_trainer(ck)
        t2.init(t2._sample_batch(ds, batch_size))
        _, data_state = t2.restore()
        assert int(jax.device_get(t2.state.step)) == 3
        state_r, _ = t2.fit(ds, batch_size=batch_size, steps=6, log_every=100,
                            data_state=data_state)

    assert int(jax.device_get(state_r.step)) == 6
    _assert_trees_equal(_host_tree(state6.params), _host_tree(state_r.params))


def test_resume_batch_size_mismatch_rejected(tmp_path):
    """ADVICE r1: resuming with a different batch_size would fast-forward to
    the wrong stream position — must raise, not silently misalign."""
    rng = np.random.default_rng(3)
    examples = [
        {"image": rng.normal(0, 1, (28, 28, 1)).astype(np.float32),
         "label": np.int32(i % 10)}
        for i in range(64)
    ]
    sess = Session.builder.master("local[2]").getOrCreate()
    ds = PartitionedDataset.parallelize(examples, 2)
    t = Trainer(sess, LeNet5(), losses.softmax_xent, optax.sgd(0.1))
    with pytest.raises(ValueError, match="batch_size mismatch"):
        t.fit(ds.repeat(), batch_size=32, steps=4, log_every=100,
              data_state={"examples_seen": 64, "batch_size": 16})


def test_resume_exhausted_feed_raises(tmp_path):
    """ADVICE r1: if the fast-forward skip consumes the whole (finite)
    dataset, fit() must raise instead of returning zero-step success."""
    rng = np.random.default_rng(4)
    examples = [
        {"image": rng.normal(0, 1, (28, 28, 1)).astype(np.float32),
         "label": np.int32(i % 10)}
        for i in range(32)
    ]
    sess = Session.builder.master("local[2]").getOrCreate()
    ds = PartitionedDataset.parallelize(examples, 2)
    t = Trainer(sess, LeNet5(), losses.softmax_xent, optax.sgd(0.1))
    with pytest.raises(RuntimeError, match="fast-forward"):
        t.fit(ds, batch_size=16, steps=100, log_every=100,
              data_state={"examples_seen": 64, "batch_size": 16})


def test_data_state_roundtrip_preserves_fields(tmp_path):
    """data_state is a JSON rider on the state step: every field written
    (examples_seen, batch_size, arbitrary extras) must come back exactly —
    the fast-forward math below consumes these verbatim."""
    import optax as _optax

    from distributeddeeplearningspark_tpu.train.state import TrainState

    params = {"w": jnp.float32(1.0)}
    state = TrainState.create(
        params=params, opt_state=_optax.sgd(0.1).init(params), mutable={},
        rng=jax.random.PRNGKey(0))
    ds = {"examples_seen": 48, "batch_size": 16, "epoch": 2,
          "source": "synthetic"}
    with Checkpointer(tmp_path / "ck", async_save=False) as ck:
        ck.save(3, state, data_state=ds)
        ck.wait()
        _, restored = ck.restore(state)
    assert restored == ds


def test_fast_forward_resume_consumes_same_batch_sequence(tmp_path):
    """Determinism contract of the examples_seen fast-forward: a resumed
    run's feed must yield exactly the batches the uninterrupted run would
    have consumed at the same step — Trainer._feed(skip_batches=k) equals
    the uninterrupted feed with its first k batches dropped, element for
    element, through the REAL checkpointed data_state round trip."""
    rng = np.random.default_rng(7)
    examples = [
        {"image": rng.normal(0, 1, (28, 28, 1)).astype(np.float32),
         "label": np.int32(i % 10)}
        for i in range(96)
    ]
    batch_size = 16
    sess = Session.builder.master("local[2]").getOrCreate()
    ds = PartitionedDataset.parallelize(examples, 2).repeat()
    t = Trainer(sess, LeNet5(), losses.softmax_xent, optax.sgd(0.1))

    import itertools

    def take(feed, n):
        return [
            {k: np.asarray(jax.device_get(v)) for k, v in b.items()}
            for b in itertools.islice(feed, n)
        ]

    uninterrupted = take(t._feed(ds, batch_size), 6)

    # the resume path's own arithmetic: data_state rides a checkpoint,
    # comes back verbatim, and skip = examples_seen // batch_size (fit())
    import optax as _optax

    from distributeddeeplearningspark_tpu.train.state import TrainState

    params = {"w": jnp.float32(1.0)}
    state = TrainState.create(
        params=params, opt_state=_optax.sgd(0.1).init(params), mutable={},
        rng=jax.random.PRNGKey(0))
    with Checkpointer(tmp_path / "ck", async_save=False) as ck:
        ck.save(3, state, data_state={"examples_seen": 3 * batch_size,
                                      "batch_size": batch_size})
        ck.wait()
        _, data_state = ck.restore(state)
    skip = int(data_state["examples_seen"]) // int(data_state["batch_size"])
    assert skip == 3

    resumed = take(t._feed(ds, batch_size, skip_batches=skip), 3)
    assert len(resumed) == 3
    for got, want in zip(resumed, uninterrupted[skip:]):
        assert got.keys() == want.keys()
        for k in got:
            np.testing.assert_array_equal(got[k], want[k])


def test_manifest_written_and_verified(tmp_path, eight_devices):
    """Every committed step gets an integrity manifest at the next finalize
    point; verify() passes on intact bytes and latest_verified_step tracks."""
    from distributeddeeplearningspark_tpu.checkpoint import MANIFEST_NAME

    mesh = MeshSpec(data=8).build()
    state, _ = step_lib.init_state(
        LeNet5(), optax.sgd(0.1), _sample_batch(), mesh, REPLICATED
    )
    with Checkpointer(tmp_path / "ckpt", async_save=True) as ckpt:
        ckpt.save(1, state)
        ckpt.save(2, state)  # finalizes step 1 → manifest 1 flush queued
        ckpt._join_manifest_thread()  # (flush runs on the helper thread)
        assert (tmp_path / "ckpt" / "1" / MANIFEST_NAME).exists()
        assert not (tmp_path / "ckpt" / "2" / MANIFEST_NAME).exists()
        ckpt.wait()  # finalizes step 2 → manifest 2 committed
        assert (tmp_path / "ckpt" / "2" / MANIFEST_NAME).exists()
        assert ckpt.verify(1) and ckpt.verify(2)
        assert ckpt.latest_verified_step() == 2


def test_restore_walks_back_past_corrupt_step(tmp_path, eight_devices):
    """A torn latest step (bytes disagree with its manifest) is quarantined
    to <step>.corrupt-N and restore lands on the newest verified step; the
    quarantined dir no longer counts as a checkpoint."""
    import os

    from distributeddeeplearningspark_tpu import faults

    mesh = MeshSpec(data=8).build()
    state, shardings = step_lib.init_state(
        LeNet5(), optax.sgd(0.1), _sample_batch(), mesh, REPLICATED, seed=3
    )
    with Checkpointer(tmp_path / "ckpt", async_save=False) as ckpt:
        ckpt.save(1, state, data_state={"examples_seen": 8})
        ckpt.save(2, state, data_state={"examples_seen": 16})
        ckpt.wait()
        assert faults.truncate_latest_checkpoint(str(tmp_path / "ckpt"))
        assert not ckpt.verify(2)
        restored, data_state = ckpt.restore(state, shardings=shardings)
        assert data_state == {"examples_seen": 8}
        _assert_trees_equal(_host_tree(state), _host_tree(restored))
        assert ckpt.latest_step() == 1
    entries = os.listdir(tmp_path / "ckpt")
    assert any(e.startswith("2.corrupt-") for e in entries), entries


def test_restore_raises_when_all_steps_corrupt(tmp_path, eight_devices):
    from distributeddeeplearningspark_tpu import faults
    from distributeddeeplearningspark_tpu.checkpoint import RestoreError

    mesh = MeshSpec(data=8).build()
    state, _ = step_lib.init_state(
        LeNet5(), optax.sgd(0.1), _sample_batch(), mesh, REPLICATED
    )
    with Checkpointer(tmp_path / "ckpt", async_save=False) as ckpt:
        ckpt.save(1, state)
        ckpt.wait()
        faults.truncate_latest_checkpoint(str(tmp_path / "ckpt"))
        with pytest.raises(RestoreError, match="no intact checkpoint"):
            ckpt.restore(state)


def test_manifestless_step_restores_structurally(tmp_path, eight_devices):
    """A step whose writer died between orbax finalize and the manifest
    flush (commit marker present, no manifest) is still restorable — atomic
    rename means it is whole; only manifest-contradicting bytes walk back."""
    import os

    from distributeddeeplearningspark_tpu.checkpoint import MANIFEST_NAME

    mesh = MeshSpec(data=8).build()
    state, shardings = step_lib.init_state(
        LeNet5(), optax.sgd(0.1), _sample_batch(), mesh, REPLICATED
    )
    with Checkpointer(tmp_path / "ckpt", async_save=False) as ckpt:
        ckpt.save(3, state)
        ckpt.wait()
        os.remove(tmp_path / "ckpt" / "3" / MANIFEST_NAME)
        assert ckpt.verify(3)  # structural fallback
        restored, _ = ckpt.restore(state, shardings=shardings)
    _assert_trees_equal(_host_tree(state), _host_tree(restored))


def test_restore_metadata_fallback_path(tmp_path, eight_devices, monkeypatch):
    """The non-default step-name branch: when the step dir isn't at
    <root>/<step>, item presence comes from orbax item_metadata — and when
    even that raises, restore still proceeds assuming the default items."""
    mesh = MeshSpec(data=8).build()
    state, shardings = step_lib.init_state(
        LeNet5(), optax.sgd(0.1), _sample_batch(), mesh, REPLICATED
    )
    with Checkpointer(tmp_path / "ckpt", async_save=False,
                      verify_on_restore=False) as ckpt:
        ckpt.save(1, state, data_state={"examples_seen": 8})
        ckpt.wait()
        # simulate a step-name format whose dir we can't list directly:
        # the path probe misses, forcing the orbax item_metadata branch
        monkeypatch.setattr(
            ckpt, "_step_dir",
            lambda step: str(tmp_path / "ckpt" / f"nope-{step}"))
        restored, data_state = ckpt.restore(state, shardings=shardings)
        assert data_state == {"examples_seen": 8}
        _assert_trees_equal(_host_tree(state), _host_tree(restored))

        # the `except Exception` arm: item_metadata itself blows up → the
        # default {state, data} item set is assumed and restore still works
        monkeypatch.setattr(
            ckpt._mgr, "item_metadata",
            lambda step: (_ for _ in ()).throw(RuntimeError("boom")))
        restored2, data_state2 = ckpt.restore(state, shardings=shardings)
        assert data_state2 == {"examples_seen": 8}
        _assert_trees_equal(_host_tree(state), _host_tree(restored2))


def test_reshard_on_restore_fsdp_to_tensor_bitwise(tmp_path, eight_devices):
    """ISSUE 11 acceptance: fsdp-saved → tensor-restored (and → replicated)
    round-trips are bitwise on params, with optimizer momentum following
    the same template — through restore_params' metadata-templated path
    (no caller-side state), driven only by (mesh, rules)."""
    from jax.sharding import PartitionSpec as P

    from distributeddeeplearningspark_tpu.parallel.sharding import ShardingRules

    mesh_a = MeshSpec(data=2, fsdp=4).build()
    state, _ = step_lib.init_state(
        LeNet5(), optax.sgd(0.1, momentum=0.9), _sample_batch(), mesh_a,
        FSDP, seed=3)
    with Checkpointer(tmp_path / "ckpt", async_save=False) as ckpt:
        ckpt.save(1, state)
        ckpt.wait()
        # the saved geometry names the fsdp layout
        geo = ckpt.saved_geometry(1)
        assert geo["num_devices"] == 8
        assert any("fsdp" in str(v) for v in geo["specs"].values())

        tensor_rules = ShardingRules(rules=(
            (r"Dense_0/kernel", P(None, "tensor")),
            (r"Dense_1/kernel", P("tensor", None))))
        mesh_t = MeshSpec(data=1, tensor=8).build()
        params_t, step = ckpt.restore_params(mesh=mesh_t, rules=tensor_rules)
        assert step == 1
        flat_a = {tuple(map(str, p)): v for p, v in
                  jax.tree_util.tree_flatten_with_path(state.params)[0]}
        flat_t = {tuple(map(str, p)): v for p, v in
                  jax.tree_util.tree_flatten_with_path(params_t)[0]}
        assert flat_a.keys() == flat_t.keys()
        for k, v in flat_a.items():
            assert (_host_tree(v).tobytes()
                    == _host_tree(flat_t[k]).tobytes()), k
        specs = {str(l.sharding.spec) for l in jax.tree.leaves(params_t)}
        assert any("tensor" in s for s in specs), specs

        # → replicated (the serving shape), still bitwise
        params_r, _ = ckpt.restore_params(mesh=MeshSpec(data=8).build())
        flat_r = {tuple(map(str, p)): v for p, v in
                  jax.tree_util.tree_flatten_with_path(params_r)[0]}
        for k, v in flat_a.items():
            assert (_host_tree(v).tobytes()
                    == _host_tree(flat_r[k]).tobytes()), k

        # full-state restore onto a SMALLER topology (8 → 4 devices) via the
        # recorded-layout projection: optimizer momentum survives the move
        mesh_half = MeshSpec(data=1, fsdp=4).build(jax.devices()[:4])
        restored, _ = ckpt.restore(state, mesh=mesh_half)
    _assert_trees_equal(_host_tree(state), _host_tree(restored))
    half_devs = set(mesh_half.devices.flat)
    for leaf in jax.tree.leaves(restored):
        assert set(leaf.sharding.device_set) <= half_devs


def test_restore_params_walks_back_past_quarantined_boundary(
        tmp_path, eight_devices):
    """Satellite: restore_params at a quarantined ``step.corrupt-N``
    walk-back boundary — the newest step is torn and already quarantined by
    the owner; the reader must land on the previous verified step without
    touching the quarantined dir (and a torn-but-not-yet-quarantined latest
    must be skipped without quarantining it: readers don't rename the
    owner's steps)."""
    import os

    from distributeddeeplearningspark_tpu import faults

    mesh = MeshSpec(data=8).build()
    state, _ = step_lib.init_state(
        LeNet5(), optax.sgd(0.1), _sample_batch(), mesh, REPLICATED, seed=3)
    with Checkpointer(tmp_path / "ckpt", async_save=False) as ckpt:
        ckpt.save(1, state, data_state={"examples_seen": 8})
        ckpt.save(2, state, data_state={"examples_seen": 16})
        ckpt.wait()
        faults.truncate_latest_checkpoint(str(tmp_path / "ckpt"))
        # owner-side quarantine: step 2 becomes 2.corrupt-0
        ckpt.quarantine(2)
        params, step = ckpt.restore_params()
        assert step == 1
        entries = sorted(os.listdir(tmp_path / "ckpt"))
        assert any(e.startswith("2.corrupt-") for e in entries), entries

        # now tear step 1 too but do NOT quarantine: the reader walks past
        # it only in selection (latest_verified_step), never renames
        ckpt2 = Checkpointer(tmp_path / "ckpt", async_save=False)
        ckpt2.save(3, state, data_state={"examples_seen": 24})
        ckpt2.wait()
        faults.truncate_latest_checkpoint(str(tmp_path / "ckpt"))
        _, step = ckpt2.restore_params()
        assert step == 1
        entries = sorted(os.listdir(tmp_path / "ckpt"))
        assert os.path.isdir(tmp_path / "ckpt" / "3"), entries
        assert not any(e.startswith("3.corrupt-") for e in entries), entries


def test_restore_needing_more_devices_raises_typed_error(
        tmp_path, eight_devices, monkeypatch):
    """Satellite: asking for the RECORDED layout back when the checkpoint
    was saved on more devices than are visible must raise ReshardError
    (naming the reshard escape hatch), not a shape/device mismatch deep in
    orbax — and passing a target mesh must still restore fine."""
    from distributeddeeplearningspark_tpu import checkpoint as ckpt_mod

    mesh = MeshSpec(data=2, fsdp=4).build()
    state, _ = step_lib.init_state(
        LeNet5(), optax.sgd(0.1), _sample_batch(), mesh, FSDP)
    with Checkpointer(tmp_path / "ckpt", async_save=False) as ckpt:
        ckpt.save(1, state)
        ckpt.wait()
        # simulate a host that sees fewer devices than the checkpoint used
        monkeypatch.setattr(ckpt_mod.jax, "device_count", lambda: 4)
        with pytest.raises(ckpt_mod.ReshardError, match="8 device"):
            ckpt.restore(state)
        with pytest.raises(ckpt_mod.ReshardError, match="reshard"):
            ckpt.restore_params()
        monkeypatch.undo()
        # the escape hatch the error names: restore onto the mesh we have
        mesh_half = MeshSpec(data=1, fsdp=4).build(jax.devices()[:4])
        restored, _ = ckpt.restore(state, mesh=mesh_half)
    _assert_trees_equal(_host_tree(state), _host_tree(restored))


def test_trainer_restore_before_init_raises(tmp_path):
    """Satellite: the restore guards are real exceptions (visible under
    python -O), with a call-init()-first message."""
    sess = Session.builder.master("local[2]").getOrCreate()
    t = Trainer(sess, LeNet5(), losses.softmax_xent, optax.sgd(0.1))
    with pytest.raises(RuntimeError, match="no checkpointer"):
        t.restore()
    with Checkpointer(tmp_path / "ck") as ck:
        with pytest.raises(RuntimeError, match=r"call init\(\)"):
            t.restore(ck)


def test_roundtrip_preserves_sparse_embed_state(tmp_path, eight_devices):
    """embed_state (row accumulators of the sparse embedding optimizer) must
    survive save→restore with its expert-axis sharding, and a restored state
    must continue training sparsely from the same accumulators."""
    from distributeddeeplearningspark_tpu.data.feed import put_global, stack_examples
    from distributeddeeplearningspark_tpu.models import DLRM
    from distributeddeeplearningspark_tpu.models.dlrm import dlrm_rules, sparse_embed_specs
    from distributeddeeplearningspark_tpu.train import embed, optim

    mesh = MeshSpec(data=4, expert=2).build()
    model = DLRM(vocab_sizes=(16, 8), embed_dim=8, bottom_mlp=(16, 8),
                 top_mlp=(8, 1))
    rng = np.random.default_rng(0)
    batch = stack_examples([
        {"dense": rng.normal(0, 1, (13,)).astype(np.float32),
         "sparse": np.array([rng.integers(0, v) for v in (16, 8)], np.int32),
         "label": np.int32(rng.integers(0, 2))}
        for _ in range(16)])
    specs = sparse_embed_specs(model)
    tx = optim.masked(optax.adagrad(1e-2), embed.dense_trainable(specs))
    state, shardings = step_lib.init_state(
        model, tx, batch, mesh, dlrm_rules(), sparse_embed=specs)
    step = step_lib.jit_train_step(
        embed.make_sparse_embed_train_step(model.apply, tx, losses.binary_xent, specs),
        mesh, shardings)
    state, _ = step(state, put_global(batch, mesh))
    acc_before = np.asarray(jax.device_get(
        state.embed_state["embedding"]["row_accum"]))
    assert acc_before.max() > 0  # training actually touched rows

    with Checkpointer(tmp_path / "ckpt", async_save=True) as ckpt:
        ckpt.save(1, state, data_state={"examples_seen": 16})
        ckpt.wait()
        restored, _ = ckpt.restore(state, shardings=shardings)
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(restored.embed_state["embedding"]["row_accum"])),
        acc_before)
    acc_sh = restored.embed_state["embedding"]["row_accum"].sharding
    assert "expert" in str(acc_sh.spec), acc_sh
    # restored state keeps training through the sparse path
    restored, metrics = step(restored, put_global(batch, mesh))
    assert np.isfinite(float(jax.device_get(metrics["loss"])))
