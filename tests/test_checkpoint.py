"""Checkpoint/resume: round-trip, reshard-on-restore, retention, trainer resume.

Covers SURVEY.md §5 'Checkpoint/resume' — the TPU-first replacement for the
reference's driver-side torch.save/load + re-broadcast (§3.4).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributeddeeplearningspark_tpu import Checkpointer, PartitionedDataset, Trainer
from distributeddeeplearningspark_tpu.models import LeNet5
from distributeddeeplearningspark_tpu.parallel.mesh import MeshSpec
from distributeddeeplearningspark_tpu.parallel.sharding import FSDP, REPLICATED
from distributeddeeplearningspark_tpu.session import Session
from distributeddeeplearningspark_tpu.train import losses, step as step_lib


def _sample_batch(n=8):
    rng = np.random.default_rng(0)
    return {
        "image": rng.normal(0, 1, (n, 28, 28, 1)).astype(np.float32),
        "label": rng.integers(0, 10, (n,)).astype(np.int32),
    }


def _host_tree(tree):
    return jax.tree.map(lambda x: np.asarray(jax.device_get(x)), tree)


def _assert_trees_equal(a, b):
    fa, fb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(fa) == len(fb)
    for x, y in zip(fa, fb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_roundtrip_sharded_state(tmp_path, eight_devices):
    mesh = MeshSpec(data=2, fsdp=4).build()
    model = LeNet5()
    tx = optax.adamw(1e-3)
    batch = _sample_batch()
    state, shardings = step_lib.init_state(model, tx, batch, mesh, FSDP)

    with Checkpointer(tmp_path / "ckpt", async_save=True) as ckpt:
        assert ckpt.latest_step() is None
        ckpt.save(5, state, data_state={"examples_seen": 40})
        ckpt.wait()
        assert ckpt.latest_step() == 5
        restored, data_state = ckpt.restore(state, shardings=shardings)
    _assert_trees_equal(_host_tree(state), _host_tree(restored))
    assert data_state == {"examples_seen": 40}
    # restore honored the requested shardings
    flat_r = jax.tree.leaves(restored)
    flat_s = jax.tree.leaves(shardings, is_leaf=lambda x: hasattr(x, "spec"))
    for arr, sh in zip(flat_r, flat_s):
        assert arr.sharding.is_equivalent_to(sh, arr.ndim)


def test_reshard_on_restore(tmp_path, eight_devices):
    """Write replicated on an 8-way DP mesh; restore FSDP-sharded on 2x4."""
    model = LeNet5()
    tx = optax.sgd(0.1)
    batch = _sample_batch()

    mesh_a = MeshSpec(data=8).build()
    state_a, _ = step_lib.init_state(model, tx, batch, mesh_a, REPLICATED, seed=3)
    with Checkpointer(tmp_path / "ckpt", async_save=False) as ckpt:
        ckpt.save(1, state_a)
        ckpt.wait()

        mesh_b = MeshSpec(data=2, fsdp=4).build()
        abstract = jax.eval_shape(lambda s: s, state_a)
        from distributeddeeplearningspark_tpu.parallel.sharding import state_shardings

        sh_b = state_shardings(abstract, mesh_b, FSDP)
        restored, _ = ckpt.restore(abstract, shardings=sh_b)
    _assert_trees_equal(_host_tree(state_a), _host_tree(restored))
    # at least one large param actually came back sharded over fsdp
    specs = {str(l.sharding.spec) for l in jax.tree.leaves(restored.params)}
    assert any("fsdp" in s for s in specs)


def test_retention(tmp_path, eight_devices):
    mesh = MeshSpec(data=8).build()
    state, _ = step_lib.init_state(
        LeNet5(), optax.sgd(0.1), _sample_batch(), mesh, REPLICATED
    )
    with Checkpointer(tmp_path / "ckpt", max_to_keep=2, async_save=False) as ckpt:
        for s in (1, 2, 3, 4):
            ckpt.save(s, state)
        ckpt.wait()
        assert ckpt.all_steps() == [3, 4]


def test_trainer_resume_matches_uninterrupted_run(tmp_path):
    """3 steps + crash + resume for 3 == 6 straight steps, bit-exact."""
    rng = np.random.default_rng(7)
    examples = [
        {"image": rng.normal(0, 1, (28, 28, 1)).astype(np.float32),
         "label": np.int32(i % 10)}
        for i in range(96)
    ]
    batch_size = 16

    def make_trainer(ckpt):
        sess = Session.builder.master("local[2]").getOrCreate()
        ds = PartitionedDataset.parallelize(examples, 2)
        t = Trainer(sess, LeNet5(), losses.softmax_xent, optax.sgd(0.1, momentum=0.9),
                    checkpointer=ckpt, seed=11)
        return t, ds

    # uninterrupted 6 steps
    t0, ds = make_trainer(None)
    state6, _ = t0.fit(ds, batch_size=batch_size, steps=6, log_every=100)
    Session._active and Session._active.stop()

    # 3 steps, checkpoint, "crash"
    with Checkpointer(tmp_path / "ck", async_save=False) as ck:
        t1, ds = make_trainer(ck)
        t1.fit(ds, batch_size=batch_size, steps=3, checkpoint_every=3, log_every=100)
        Session._active and Session._active.stop()

        # fresh process analogue: new trainer, restore, continue with skip
        t2, ds = make_trainer(ck)
        t2.init(t2._sample_batch(ds, batch_size))
        _, data_state = t2.restore()
        assert int(jax.device_get(t2.state.step)) == 3
        state_r, _ = t2.fit(ds, batch_size=batch_size, steps=6, log_every=100,
                            data_state=data_state)

    assert int(jax.device_get(state_r.step)) == 6
    _assert_trees_equal(_host_tree(state6.params), _host_tree(state_r.params))


def test_resume_batch_size_mismatch_rejected(tmp_path):
    """ADVICE r1: resuming with a different batch_size would fast-forward to
    the wrong stream position — must raise, not silently misalign."""
    rng = np.random.default_rng(3)
    examples = [
        {"image": rng.normal(0, 1, (28, 28, 1)).astype(np.float32),
         "label": np.int32(i % 10)}
        for i in range(64)
    ]
    sess = Session.builder.master("local[2]").getOrCreate()
    ds = PartitionedDataset.parallelize(examples, 2)
    t = Trainer(sess, LeNet5(), losses.softmax_xent, optax.sgd(0.1))
    with pytest.raises(ValueError, match="batch_size mismatch"):
        t.fit(ds.repeat(), batch_size=32, steps=4, log_every=100,
              data_state={"examples_seen": 64, "batch_size": 16})


def test_resume_exhausted_feed_raises(tmp_path):
    """ADVICE r1: if the fast-forward skip consumes the whole (finite)
    dataset, fit() must raise instead of returning zero-step success."""
    rng = np.random.default_rng(4)
    examples = [
        {"image": rng.normal(0, 1, (28, 28, 1)).astype(np.float32),
         "label": np.int32(i % 10)}
        for i in range(32)
    ]
    sess = Session.builder.master("local[2]").getOrCreate()
    ds = PartitionedDataset.parallelize(examples, 2)
    t = Trainer(sess, LeNet5(), losses.softmax_xent, optax.sgd(0.1))
    with pytest.raises(RuntimeError, match="fast-forward"):
        t.fit(ds, batch_size=16, steps=100, log_every=100,
              data_state={"examples_seen": 64, "batch_size": 16})


def test_roundtrip_preserves_sparse_embed_state(tmp_path, eight_devices):
    """embed_state (row accumulators of the sparse embedding optimizer) must
    survive save→restore with its expert-axis sharding, and a restored state
    must continue training sparsely from the same accumulators."""
    from distributeddeeplearningspark_tpu.data.feed import put_global, stack_examples
    from distributeddeeplearningspark_tpu.models import DLRM
    from distributeddeeplearningspark_tpu.models.dlrm import dlrm_rules, sparse_embed_specs
    from distributeddeeplearningspark_tpu.train import embed, optim

    mesh = MeshSpec(data=4, expert=2).build()
    model = DLRM(vocab_sizes=(16, 8), embed_dim=8, bottom_mlp=(16, 8),
                 top_mlp=(8, 1))
    rng = np.random.default_rng(0)
    batch = stack_examples([
        {"dense": rng.normal(0, 1, (13,)).astype(np.float32),
         "sparse": np.array([rng.integers(0, v) for v in (16, 8)], np.int32),
         "label": np.int32(rng.integers(0, 2))}
        for _ in range(16)])
    specs = sparse_embed_specs(model)
    tx = optim.masked(optax.adagrad(1e-2), embed.dense_trainable(specs))
    state, shardings = step_lib.init_state(
        model, tx, batch, mesh, dlrm_rules(), sparse_embed=specs)
    step = step_lib.jit_train_step(
        embed.make_sparse_embed_train_step(model.apply, tx, losses.binary_xent, specs),
        mesh, shardings)
    state, _ = step(state, put_global(batch, mesh))
    acc_before = np.asarray(jax.device_get(
        state.embed_state["embedding"]["row_accum"]))
    assert acc_before.max() > 0  # training actually touched rows

    with Checkpointer(tmp_path / "ckpt", async_save=True) as ckpt:
        ckpt.save(1, state, data_state={"examples_seen": 16})
        ckpt.wait()
        restored, _ = ckpt.restore(state, shardings=shardings)
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(restored.embed_state["embedding"]["row_accum"])),
        acc_before)
    acc_sh = restored.embed_state["embedding"]["row_accum"].sharding
    assert "expert" in str(acc_sh.spec), acc_sh
    # restored state keeps training through the sparse path
    restored, metrics = step(restored, put_global(batch, mesh))
    assert np.isfinite(float(jax.device_get(metrics["loss"])))
