"""tools/queue_report.py — the record→prose step must apply the SAME success
rule as the queue runner (bench.is_good_record), so a failed measurement can
never be pasted into BASELINE.md as a citable number (ADVICE r5)."""

import json
import subprocess
import sys
import os

TOOL = os.path.join(os.path.dirname(__file__), "..", "tools", "queue_report.py")


def _run(path):
    out = subprocess.run([sys.executable, TOOL, str(path)],
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    return out.stdout


def test_drifted_success_records_report_as_failed(tmp_path):
    records = [
        # a genuinely good record
        {"item": "resnet50", "rc": 0, "ts": "t", "elapsed_s": 1,
         "record": {"metric": "resnet50_images_per_sec_per_chip",
                    "value": 100.0, "unit": "images/sec/chip"}},
        # rc=0 but the runner caught an exception: NOT citable
        {"item": "bert_mlm", "rc": 0, "ts": "t", "elapsed_s": 1,
         "record": {"metric": "bench_failed", "value": 1, "unit": "",
                    "error": "XlaRuntimeError: ..."}},
        # rc=0 but the backend was gone: NOT citable
        {"item": "llama_lora", "rc": 0, "ts": "t", "elapsed_s": 1,
         "record": {"metric": "backend_unavailable", "value": 1, "unit": ""}},
        # rc=0 but zero kernels compiled: NOT citable
        {"item": "kernels", "rc": 0, "ts": "t", "elapsed_s": 1,
         "record": {"metric": "pallas_kernels_compiled", "value": 0,
                    "unit": "kernels"}},
        # nonzero rc stays failed
        {"item": "dlrm", "rc": 2, "ts": "t", "elapsed_s": 1,
         "record": {"metric": "dlrm_examples_per_sec_per_chip", "value": 5,
                    "unit": "ex/s"}},
    ]
    p = tmp_path / "q.jsonl"
    p.write_text("".join(json.dumps(r) + "\n" for r in records))
    out = _run(p)
    assert "1 good records, 4 failed" in out, out
    assert "**resnet50**: resnet50_images_per_sec_per_chip = **100.0**" in out
    for item in ("bert_mlm", "llama_lora", "kernels", "dlrm"):
        line = next(ln for ln in out.splitlines() if f"**{item}**" in ln)
        assert "FAILED" in line, line
    # the reason names the actual cause, not a phantom zero value
    assert "FAILED (rc=2)" in out
    assert "pallas_kernels_compiled=0" in out
    assert "XlaRuntimeError" in out


def test_usage_line_advertises_no_unparsed_flags():
    """The docstring usage must only name flags argparse accepts (the old
    [--md] exited 2 when someone followed the docs)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location("qr", TOOL)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert "--md" not in (mod.__doc__ or "")
    out = subprocess.run([sys.executable, TOOL, "/nonexistent", "--md"],
                         capture_output=True, text=True)
    assert out.returncode == 2  # argparse rejects it, and we don't advertise it
