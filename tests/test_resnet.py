"""ResNet family + image pipeline tests (config 2, SURVEY.md §4).

Small variants / tiny images keep CPU compile time bounded; the full
ResNet-50 shape is exercised by bench.py on the real chip.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributeddeeplearningspark_tpu.data import vision
from distributeddeeplearningspark_tpu.data.feed import put_global, stack_examples
from distributeddeeplearningspark_tpu.data.sources import synthetic_images
from distributeddeeplearningspark_tpu.models import ResNet18, ResNet50
from distributeddeeplearningspark_tpu.parallel.mesh import MeshSpec
from distributeddeeplearningspark_tpu.parallel.sharding import REPLICATED
from distributeddeeplearningspark_tpu.train import losses, step as step_lib


def tiny_batch(n=8, size=32, classes=10):
    rng = np.random.default_rng(0)
    return {
        "image": rng.normal(0, 1, (n, size, size, 3)).astype(np.float32),
        "label": rng.integers(0, classes, (n,)).astype(np.int32),
    }


def test_resnet18_forward_shapes_and_dtypes():
    model = ResNet18(num_classes=10)
    batch = tiny_batch()
    variables = model.init(jax.random.PRNGKey(0), batch, train=False)
    logits = model.apply(variables, batch, train=False)
    assert logits.shape == (8, 10)
    assert logits.dtype == jnp.float32  # head stays f32 even with bf16 compute
    assert "batch_stats" in variables  # BN state present


def test_norm_dtype_follows_compute_dtype_with_f32_override():
    """BN compute follows model dtype by default (the measured 32% step-time
    win, models/resnet.py docstring); norm_dtype=f32 restores torch-default
    numerics and must stay available for the weight-import parity path."""
    batch = tiny_batch()
    fast = ResNet18(num_classes=10)  # default: bf16 compute, bf16 BN
    exact = ResNet18(num_classes=10, norm_dtype=jnp.float32)
    v_fast = fast.init(jax.random.PRNGKey(0), batch, train=False)
    v_exact = exact.init(jax.random.PRNGKey(0), batch, train=False)
    # same params/state trees — norm_dtype changes compute only, not state
    assert jax.tree.structure(v_fast) == jax.tree.structure(v_exact)
    out_fast = fast.apply(v_fast, batch, train=False)
    out_exact = exact.apply(v_exact, batch, train=False)
    # bf16 BN is a numerics change but a small one at init scale
    assert jnp.allclose(out_fast, out_exact, atol=0.05), (
        jnp.max(jnp.abs(out_fast - out_exact)))
    # BN running statistics stay f32 regardless of compute dtype — check the
    # UPDATED stats from a train-mode apply, not the init-time zeros (flax
    # upcasts inside _compute_stats; this pins that behavior)
    _, mutated = fast.apply(v_fast, batch, train=True, mutable=["batch_stats"])
    for leaf in jax.tree.leaves(mutated["batch_stats"]):
        assert leaf.dtype == jnp.float32


def test_resnet50_param_count():
    # ResNet-50/ImageNet-1k is famously 25.56M params — structural check.
    model = ResNet50(num_classes=1000)
    batch = {"image": np.zeros((1, 64, 64, 3), np.float32), "label": np.zeros((1,), np.int32)}
    abstract = jax.eval_shape(lambda: model.init(jax.random.PRNGKey(0), batch, train=False))
    n = sum(int(np.prod(v.shape)) for v in jax.tree.leaves(abstract["params"]))
    assert abs(n - 25_557_032) / 25_557_032 < 0.01, n


def test_batch_stats_update_in_train_step(eight_devices):
    mesh = MeshSpec(data=8).build(eight_devices)
    model = ResNet18(num_classes=10, dtype=jnp.float32)
    batch = tiny_batch(n=16)
    tx = optax.sgd(0.1, momentum=0.9)
    state, shardings = step_lib.init_state(model, tx, batch, mesh, REPLICATED)
    assert "batch_stats" in state.mutable
    before = jax.device_get(jax.tree.leaves(state.mutable["batch_stats"])[0])

    train_step = step_lib.jit_train_step(
        step_lib.make_train_step(
            model.apply, tx, losses.softmax_xent, mutable_keys=("batch_stats",)
        ),
        mesh, shardings,
    )
    state, metrics = train_step(state, put_global(batch, mesh))
    after = jax.device_get(jax.tree.leaves(state.mutable["batch_stats"])[0])
    assert not np.allclose(before, after)  # running stats moved
    assert np.isfinite(float(metrics["loss"]))


def test_resnet_learns_on_fake_data(eight_devices):
    """DP training on 8 fake chips reduces loss on the synthetic image task."""
    mesh = MeshSpec(data=8).build(eight_devices)
    model = ResNet18(num_classes=8, width=16, dtype=jnp.float32)
    ds = synthetic_images(512, image_size=32, num_classes=8, num_partitions=8, seed=0)
    tx = optax.sgd(0.05, momentum=0.9)

    examples = ds.take(32)
    batch = stack_examples(examples)
    state, shardings = step_lib.init_state(model, tx, batch, mesh, REPLICATED)
    train_step = step_lib.jit_train_step(
        step_lib.make_train_step(
            model.apply, tx, losses.softmax_xent, mutable_keys=("batch_stats",)
        ),
        mesh, shardings,
    )
    gbatch = put_global(batch, mesh)
    state, first = train_step(state, gbatch)
    for _ in range(20):
        state, last = train_step(state, gbatch)
    assert float(last["loss"]) < float(first["loss"])


class TestVisionTransforms:
    def test_resize_bilinear_identity_and_shape(self):
        img = np.random.default_rng(0).random((17, 23, 3)).astype(np.float32)
        assert vision.resize_bilinear(img, (17, 23)) is img
        out = vision.resize_bilinear(img, (8, 8))
        assert out.shape == (8, 8, 3)
        # constant image stays constant under bilinear interpolation
        const = np.full((10, 10, 3), 0.5, np.float32)
        assert np.allclose(vision.resize_bilinear(const, (7, 13)), 0.5, atol=1e-6)

    def test_center_crop(self):
        img = np.random.default_rng(0).random((300, 400, 3)).astype(np.float32)
        out = vision.center_crop(img, 224)
        assert out.shape == (224, 224, 3)

    def test_random_resized_crop_shape(self):
        img = np.random.default_rng(0).random((100, 80, 3)).astype(np.float32)
        out = vision.random_resized_crop(img, np.random.default_rng(1), 64)
        assert out.shape == (64, 64, 3)

    def test_normalize_uint8(self):
        img = np.full((4, 4, 3), 255, np.uint8)
        out = vision.normalize(img)
        assert out.dtype == np.float32
        assert np.allclose(out, (1.0 - vision.IMAGENET_MEAN) / vision.IMAGENET_STD)

    def test_pipeline_preserves_count_and_shape(self):
        ds = synthetic_images(64, image_size=32, num_classes=4, num_partitions=4)
        out = vision.imagenet_train(ds, size=32)
        assert out.count() == 64
        ex = out.first()
        assert ex["image"].shape == (32, 32, 3)
