"""M1 acceptance: MNIST LeNet-5, 2-executor data parallelism (PR1 parity).

Covers SURVEY.md §4's key assertions:
- DP grad sync: training on a 2-device mesh computes the SAME numbers as the
  driver-side broadcast/treeAggregate round loop (reference §3.1 semantics);
- end-to-end learning: accuracy target on synthetic MNIST;
- the full Session → parallelize → Trainer.fit user path.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from distributeddeeplearningspark_tpu import Session, Trainer
from distributeddeeplearningspark_tpu.data import host_batches, put_global, stack_examples
from distributeddeeplearningspark_tpu.data.sources import synthetic_mnist
from distributeddeeplearningspark_tpu.models import LeNet5
from distributeddeeplearningspark_tpu.parallel.collectives import (
    assert_replicas_in_sync,
    grad_average,
)
from distributeddeeplearningspark_tpu.parallel.mesh import MeshSpec
from distributeddeeplearningspark_tpu.parallel.sharding import REPLICATED
from distributeddeeplearningspark_tpu.train import losses, step as step_lib


def _loss_of(model, params, batch):
    logits = model.apply({"params": params}, batch, train=True)
    return losses.softmax_xent(logits, batch)[0]


def test_spmd_step_equals_driver_round_loop(eight_devices):
    """The psum-under-GSPMD gradient must equal driver-averaged per-partition
    grads — the reference's treeAggregate path — bit-for-bit (fp32 tol)."""
    model = LeNet5()
    ds = synthetic_mnist(num_examples=64, num_partitions=2, seed=3)
    batch = stack_examples(ds.take(16))

    mesh = MeshSpec(data=2).build(eight_devices[:2])
    state, shardings = step_lib.init_state(
        model, optax.sgd(0.1), batch, mesh, REPLICATED, seed=0
    )
    params = jax.device_get(state.params)

    # SPMD: grad of mean loss over the global batch, batch sharded 2 ways.
    gbatch = put_global(batch, mesh)
    spmd_grads = jax.jit(
        jax.grad(lambda p, b: _loss_of(model, p, b))
    )(state.params, gbatch)
    spmd_grads = jax.device_get(spmd_grads)

    # Driver round loop: per-partition grads on half-batches, then average
    # (Spark treeAggregate of gradient sums / N, SURVEY.md §3.1).
    half = {k: v[:8] for k, v in batch.items()}, {k: v[8:] for k, v in batch.items()}
    part_grads = [
        jax.device_get(jax.grad(lambda p: _loss_of(model, p, h))(params)) for h in half
    ]
    driver_grads = grad_average(part_grads)

    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-6),
        spmd_grads,
        driver_grads,
    )


def test_mnist_end_to_end_accuracy(eight_devices):
    """Full user path: local[2] session, parallelized partitions, fit → learn."""
    spark = Session.builder.master("local[2]").appName("mnist-pr1").getOrCreate()
    train_ds = synthetic_mnist(num_examples=2048, num_partitions=2, seed=0)
    test_ds = synthetic_mnist(num_examples=256, num_partitions=2, seed=99)

    trainer = Trainer(
        spark,
        LeNet5(),
        losses.softmax_xent,
        optax.sgd(0.01, momentum=0.9),
    )
    state, summary = trainer.fit(
        train_ds.repeat(), batch_size=64, steps=120, log_every=40
    )
    assert int(jax.device_get(state.step)) == 120
    metrics = trainer.evaluate(test_ds, batch_size=64)
    assert metrics["accuracy"] > 0.9, f"LeNet failed to learn: {metrics}"
    # replicated params must be in sync across the 2 devices
    assert_replicas_in_sync(state.params, spark.mesh)
    assert summary["examples_per_sec"] > 0


def test_same_result_1_vs_8_devices(eight_devices):
    """Device count must not change the math: 120 steps on a 1-device mesh and
    an 8-device mesh from the same init produce the same loss trajectory."""
    model = LeNet5()
    ds = synthetic_mnist(num_examples=512, num_partitions=8, seed=1)
    import itertools

    batches = list(itertools.islice(host_batches(ds.repeat(), 32, num_shards=8), 20))

    results = {}
    for ndev in (1, 8):
        mesh = MeshSpec(data=ndev).build(eight_devices[:ndev])
        tx = optax.sgd(0.1)
        state, shardings = step_lib.init_state(
            model, tx, batches[0], mesh, REPLICATED, seed=7
        )
        step = step_lib.jit_train_step(
            step_lib.make_train_step(model.apply, tx, losses.softmax_xent),
            mesh,
            shardings,
        )
        loss_hist = []
        for hb in batches:
            state, m = step(state, put_global(hb, mesh))
            loss_hist.append(float(jax.device_get(m["loss"])))
        results[ndev] = loss_hist

    np.testing.assert_allclose(results[1], results[8], rtol=1e-4, atol=1e-5)


def test_eval_step_runs_without_dropout(eight_devices):
    spark = Session.builder.master("local[1]").getOrCreate()
    ds = synthetic_mnist(num_examples=64, num_partitions=1)
    trainer = Trainer(spark, LeNet5(), losses.softmax_xent, optax.sgd(0.1))
    trainer.init(stack_examples(ds.take(4)))
    m = trainer.evaluate(ds, batch_size=32)
    assert 0.0 <= m["accuracy"] <= 1.0
    assert np.isfinite(m["loss"])


def test_evaluate_counts_tail_batch_exactly(eight_devices):
    """VERDICT r1: eval on a non-divisible set must equal one full-batch pass
    (the tail used to be silently dropped)."""
    spark = Session.builder.master("local[2]").getOrCreate()
    # 80 examples, batch 32 → 32 + 32 + 16-tail
    ds = synthetic_mnist(num_examples=80, num_partitions=2, seed=21)
    trainer = Trainer(spark, LeNet5(), losses.softmax_xent, optax.sgd(0.1))
    trainer.init(stack_examples(ds.take(4)))

    got = trainer.evaluate(ds, batch_size=32)
    want = trainer.evaluate(ds, batch_size=80)  # one full batch, trivially exact
    np.testing.assert_allclose(got["loss"], want["loss"], rtol=1e-5)
    np.testing.assert_allclose(got["accuracy"], want["accuracy"], rtol=1e-5)


def test_evaluate_weight_metric_aggregation(eight_devices):
    """Token-weighted losses aggregate by their reported weight, so unequal
    mask counts across batches still reduce to the exact global mean."""
    import jax.numpy as jnp

    from distributeddeeplearningspark_tpu.data.feed import put_global as _pg
    from distributeddeeplearningspark_tpu.models import bert_tiny
    from distributeddeeplearningspark_tpu.rdd import PartitionedDataset

    spark = Session.builder.master("local[2]").getOrCreate()
    rng = np.random.default_rng(5)
    seq, vocab = 16, 1024
    examples = []
    for i in range(24):  # batch 16 → one full batch + 8-tail
        ids = rng.integers(0, vocab, (seq,)).astype(np.int32)
        w = np.zeros((seq,), np.float32)
        w[: rng.integers(1, 6)] = 1.0  # unequal mask counts per example
        examples.append({
            "input_ids": ids,
            "attention_mask": np.ones((seq,), np.int32),
            "mlm_labels": ids,
            "mlm_weights": w,
        })
    ds = PartitionedDataset.parallelize(examples, 2)
    trainer = Trainer(spark, bert_tiny(), losses.masked_lm, optax.sgd(0.1))
    trainer.init(stack_examples(ds.take(4)))
    got = trainer.evaluate(ds, batch_size=16)
    want = trainer.evaluate(ds, batch_size=24)
    np.testing.assert_allclose(got["loss"], want["loss"], rtol=1e-5)
    np.testing.assert_allclose(got["mlm_accuracy"], want["mlm_accuracy"], rtol=1e-5)


def test_predict_streams_outputs_in_order(eight_devices):
    """SURVEY §3.3 inference stack: broadcast -> per-partition predict ->
    collect; order-preserving, tail included, post-processing on device."""
    import jax.numpy as jnp
    import optax

    from distributeddeeplearningspark_tpu import Session, Trainer
    from distributeddeeplearningspark_tpu.data.sources import synthetic_mnist
    from distributeddeeplearningspark_tpu.models import LeNet5
    from distributeddeeplearningspark_tpu.train import losses

    spark = Session.builder.master("local[8]").appName("predict").getOrCreate()
    # 100 examples over 8 partitions; batch 16 -> 6 full batches + tail of 4
    ds = synthetic_mnist(100, num_partitions=8, seed=3)
    trainer = Trainer(spark, LeNet5(num_classes=10), losses.softmax_xent,
                      optax.sgd(0.1))
    trainer.fit(ds.repeat(), batch_size=16, steps=30, log_every=100)

    pairs = list(trainer.predict(ds, batch_size=16, with_inputs=True,
                                 output_fn=lambda o: jnp.argmax(o, -1)))
    # exact tail semantics for this config: 100 rows over 8 shards (partition
    # sizes 13x4 + 12x4), per-shard draw 2 -> 6 full batches of 16 = 96 rows;
    # the 4 leftover rows can't fill all 8 shards equally -> dropped
    assert len(pairs) == 96
    assert all(p.shape == () for _, p in pairs)
    # with_inputs pairs each prediction with ITS example (no order footgun)
    acc = np.mean([int(p) == int(ex["label"]) for ex, p in pairs])
    assert acc > 0.9, f"predict accuracy {acc}"


def test_evaluate_exact_with_subshard_tail(eight_devices):
    """VERDICT r3 missing-#5 / next-#3: dataset sizes whose tail cannot fill
    every data shard (size mod (nshards×batch) ∈ {1, nshards−1}) must yield
    metrics IDENTICAL to a single-device pass — the tail is padded with
    eval_mask=0 rows through the weighted-mean machinery, never dropped."""
    for size in (65, 71):  # batch 32 on 8 shards → sub-shard tails of 1 / 7
        # synthetic_mnist rounds to even partitions — build the uneven set
        # explicitly so the sub-shard tail actually exists
        rows = synthetic_mnist(num_examples=128, num_partitions=1,
                               seed=31).collect()[:size]
        assert len(rows) == size
        from distributeddeeplearningspark_tpu.rdd import PartitionedDataset
        ds = PartitionedDataset.parallelize(rows, 8)
        spark8 = Session.builder.master("local[8]").getOrCreate()
        t8 = Trainer(spark8, LeNet5(), losses.softmax_xent, optax.sgd(0.1))
        t8.init(stack_examples(ds.take(4)))
        got = t8.evaluate(ds, batch_size=32)
        spark8.stop()

        spark1 = Session.builder.master("local[1]").getOrCreate()
        t1 = Trainer(spark1, LeNet5(), losses.softmax_xent, optax.sgd(0.1))
        t1.init(stack_examples(ds.take(4)))
        want = t1.evaluate(ds, batch_size=size)  # one full batch, exact
        spark1.stop()
        assert set(got) == set(want)
        for k in want:
            np.testing.assert_allclose(
                got[k], want[k], rtol=2e-5, atol=1e-6,
                err_msg=f"metric {k} at size {size}")


def test_evaluate_raises_when_loss_ignores_eval_mask(eight_devices):
    """A loss that reports no 'weight' for a padded batch would let padding
    rows contaminate the mean — evaluate must refuse loudly, not skew."""
    import pytest

    def careless_loss(logits, batch):  # ignores eval_mask entirely
        labels = batch["label"]
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, labels).mean()
        return loss, {"loss": loss}

    spark = Session.builder.master("local[8]").getOrCreate()
    from distributeddeeplearningspark_tpu.rdd import PartitionedDataset
    rows = synthetic_mnist(num_examples=64, num_partitions=1,
                           seed=7).collect()[:33]
    ds = PartitionedDataset.parallelize(rows, 8)
    trainer = Trainer(spark, LeNet5(), careless_loss, optax.sgd(0.1))
    trainer.init(stack_examples(ds.take(4)))
    with pytest.raises(RuntimeError, match="eval_mask"):
        trainer.evaluate(ds, batch_size=32)


def test_lenet_matches_torch_reference():
    """Numerical parity vs an independent torch LeNet-5 (SURVEY §4: torch
    parity stands in for the unreachable reference; config 1's model was
    the last family without one). Weights copied flax→torch; the flatten
    order is the one real translation hazard (NHWC [B,4,4,16] vs torch's
    NCHW) and is exercised explicitly."""
    import torch

    from distributeddeeplearningspark_tpu.models import LeNet5

    model = LeNet5()
    rng = np.random.default_rng(5)
    batch = {"image": rng.normal(0, 1, (3, 28, 28, 1)).astype(np.float32)}
    params = model.init(jax.random.PRNGKey(2), batch, train=False)["params"]
    ours = np.asarray(model.apply({"params": params}, batch, train=False))

    def conv(p, padding):
        w = np.asarray(p["kernel"]).transpose(3, 2, 0, 1)  # HWIO→OIHW
        m = torch.nn.Conv2d(w.shape[1], w.shape[0], w.shape[2],
                            padding=padding)
        with torch.no_grad():
            m.weight.copy_(torch.tensor(w))
            m.bias.copy_(torch.tensor(np.asarray(p["bias"])))
        return m

    def lin(p):
        m = torch.nn.Linear(p["kernel"].shape[0], p["kernel"].shape[1])
        with torch.no_grad():
            m.weight.copy_(torch.tensor(np.asarray(p["kernel"]).T))
            m.bias.copy_(torch.tensor(np.asarray(p["bias"])))
        return m

    c0, c1 = conv(params["Conv_0"], 2), conv(params["Conv_1"], 0)
    d0, d1, d2 = (lin(params[f"Dense_{i}"]) for i in range(3))
    with torch.no_grad():
        x = torch.tensor(batch["image"].transpose(0, 3, 1, 2))  # NHWC→NCHW
        x = torch.max_pool2d(torch.relu(c0(x)), 2, 2)
        x = torch.max_pool2d(torch.relu(c1(x)), 2, 2)
        # flatten in the flax (NHWC) order, not torch's NCHW order
        x = x.permute(0, 2, 3, 1).reshape(x.shape[0], -1)
        x = torch.relu(d0(x))
        x = torch.relu(d1(x))
        theirs = d2(x).numpy()
    np.testing.assert_allclose(ours, theirs, rtol=1e-5, atol=1e-5)
