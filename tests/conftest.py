"""Test harness: 8 fake CPU devices (SURVEY.md §4 "Multi-device sim").

Must run before any jax import: forces the CPU backend (the sandbox default is
the experimental `axon` TPU platform) and splits the host into 8 virtual
devices so real Mesh/pjit/GSPMD code paths — including collectives — execute
in unit tests exactly as they would on an 8-chip slice.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"

import jax  # noqa: E402

# The sandbox's sitecustomize pre-imports jax and registers the `axon` TPU
# PJRT plugin before any conftest can run, so the env vars above may be read
# too late; config.update wins regardless of import order.
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # jax < 0.5 has no jax_num_cpu_devices option; on those versions nothing
    # pre-imports jax either, so the XLA_FLAGS env set above already took
    pass

import pytest  # noqa: E402

from distributeddeeplearningspark_tpu.session import Session  # noqa: E402

# ---------------------------------------------------------------------------
# Two-tier suite (VERDICT r2 next-#7): the default run (`pytest tests/ -q`,
# what the driver executes) deselects tests marked `slow` via pytest.ini's
# addopts and finishes in minutes on one core; the full suite is
# `pytest tests/ -q -m "slow or not slow"`, slow-only is `-m slow`.
# Slow = multi-second jit-compile integration tests, multi-process gangs,
# SIGKILL drills, subprocess benches — marked centrally here (measured list,
# --durations=50 2026-07-30) so test files stay clean and the tier boundary
# lives in one place.
# ---------------------------------------------------------------------------

_SLOW_PATTERNS = (
    "test_supervisor.py",          # multi-process gangs + SIGKILL drills
    # multi-second subprocess drill (abandons a recovering exchange and
    # asserts interpreter-exit reaps respawned children + epoch arenas)
    "test_exchange_recovery.py::test_interpreter_exit_mid_recovery",
    # chaos drills that compile whole-model steps; the pure-python drills
    # (restore-fallback, fault parsing) stay in the fast tier
    "test_chaos.py::test_rollback_without_checkpointer",
    "test_chaos.py::test_on_nonfinite_validation",
    "test_profiling.py::test_fit", # Trainer runs writing real trace files
    "test_profiling.py::test_profile_cli",
    "test_profiling.py::test_op_breakdown",
    "test_llama_gen.py",           # KV-cache decode rollouts (big compiles)
    "test_bench.py::test_bench_failure",
    "test_bench.py::test_bench_kernels_interpret_smoke",  # interpret Pallas
    "test_bench.py::test_timing_suspect",
    "test_bench.py::test_llama_model_flops_vs_cpu_cost_analysis",  # 0.9b-shape-free but compiles full tiny train steps (unrolled, 2 depths)
    "test_bench.py::test_cost_analysis_is_scan_opaque",  # 2 more tiny compiles
    "test_checkpoint.py::test_trainer_resume",
    "test_checkpoint.py::test_roundtrip",
    "test_pipeline.py::test_pp_composes_with_tp_and_dp",
    "test_pipeline.py::test_pp_llama_loss_equals_non_pp",
    "test_pipeline.py::test_trainer_pp_fit",
    "test_ring_attention.py::test_llama_context_parallel_train_step",
    "test_ring_attention.py::TestFlashHops",
    "test_ring_attention.py::TestKeyPaddingMask::test_masked_and_causal",
    "test_ring_attention.py::test_ring_gqa_matches_xla_repeat",
    "test_llama.py::test_trainable_filter_grads",
    "test_llama.py::test_fused_head_loss",
    "test_llama.py::test_remat_policy_dots",
    "test_llama.py::test_fsdp_tp_sharded_train_step",
    "test_llama.py::test_int8_base_fsdp_tp_sharded_train_step",
    "test_llama.py::TestInt8Base::test_quality_bound_at_bench_geometry",  # two 0.9b fwds, ~2.5 min
    "test_llama.py::TestLoRA::test_masked_optimizer_freezes_base",
    "test_resnet.py::test_resnet_learns_on_fake_data",
    "test_resnet.py::test_batch_stats_update_in_train_step",
    "test_resnet_io.py::test_imported_resnet_matches_torch_logits",
    "test_resnet_io.py::test_trainer_load_pretrained",
    "test_sparse_embed.py::TestSparseTrainStep",
    "test_sparse_embed.py::test_unconsumed_override",
    "test_sparse_embed.py::test_trainer_wires_sparse_embed",
    "test_train_mnist.py::test_spmd_step_equals_driver_round_loop",
    "test_train_mnist.py::test_same_result_1_vs_8_devices",
    "test_train_mnist.py::test_mnist_end_to_end_accuracy",
    "test_train_mnist.py::test_predict_streams",
    "test_bert.py::test_bert_mlm_learns",
    "test_bert.py::test_hf_bert_import_logits_parity",
    "test_bert.py::test_gathered_mlm_head_matches_full_length",
    "test_flash_attention.py::test_flash_gqa_gradients",
    "test_flash_attention.py::test_flash_gradients_match_dense",
    "test_real_data.py",           # on-disk dump/tsv/idx fixtures
    # second pass (fast-tier --durations, 2026-07-30): everything ≥6s —
    # mostly whole-model jit compiles; cheaper siblings keep the coverage
    "test_resnet.py::test_resnet18_forward_shapes_and_dtypes",
    "test_resnet.py::test_norm_dtype_follows_compute_dtype",
    "test_conv_bn.py::test_resnet_fused_flag_end_to_end",
    "test_grad_accum.py::test_accum_multiple_steps_trains",
    "test_grad_accum.py::test_trainer_fit_accum_wiring",
    "test_grad_accum.py::test_accum_equals_full_batch_step",
    "test_bert.py::test_hf_bert_export_round_trip",
    "test_bert.py::test_hf_bert_torch_import_matches_flax_import",
    "test_bert.py::TestSequencePacking::test_bert_consumes_segment_ids",
    "test_dataframe.py::test_criteo_shaped_pipeline_end_to_end",
    "test_llama.py::test_scan_matches_loop",
    "test_llama.py::TestLoRA::test_zero_init_matches_base",
    "test_llama.py::TestLoRA::test_merge_lora",
    "test_train_mnist.py::test_evaluate_weight_metric_aggregation",
    "test_train_mnist.py::test_evaluate_counts_tail_batch_exactly",
    "test_dlrm.py::test_dlrm_forward_shape",
    "test_dlrm.py::test_sharded_embedding_matches_replicated",
    "test_checkpoint.py::test_reshard_on_restore",
    "test_memory.py::test_7b_fsdp_layout_lowers_abstractly",
    # third pass: r3 additions that compile whole-model train steps
    "test_moe.py::TestMoELlama",
    "test_moe.py::test_predict_and_eval_get_plain_logits",
    "test_llama.py::TestLlamaPackedSegments",
    "test_llama.py::test_pp_rejects_segment_ids",
    "test_conv_bn.py::TestConv1x1BN::test_gradients_match_unfused",
    "test_bench.py::test_llama_7b_oom_returns_structured_evidence",
    "test_memory.py::test_param_count_matches_model_exactly",
    "test_llama.py::test_parity_with_transformers",
    "test_checkpoint.py::test_retention",
    # MPMD pipelines: whole-model jits on threads, plus a real
    # process-level stage-kill drill
    "test_mpmd.py::test_mpmd_bitwise_parity_vs_single_program_llama_pp",
    "test_mpmd.py::test_mpmd_heterogeneous_stage_meshes",
    "test_mpmd.py::test_mpmd_stage_geometry_change_on_restore",
    "test_mpmd.py::test_pipeline_supervisor_stage_kill_drill",
)


def pytest_collection_modifyitems(config, items):
    for item in items:
        if any(pat in item.nodeid for pat in _SLOW_PATTERNS):
            item.add_marker(pytest.mark.slow)


@pytest.fixture(autouse=True)
def _reset_session():
    """Each test gets a clean Session slate (module-level singleton) and a
    clean telemetry binding — a writer configured against one test's tmp
    dir must not leak events into the next test's run."""
    yield
    if Session._active is not None:
        Session._active.stop()
    from distributeddeeplearningspark_tpu import telemetry

    telemetry.reset()


@pytest.fixture
def eight_devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 fake CPU devices, got {len(devs)}"
    return devs
