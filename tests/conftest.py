"""Test harness: 8 fake CPU devices (SURVEY.md §4 "Multi-device sim").

Must run before any jax import: forces the CPU backend (the sandbox default is
the experimental `axon` TPU platform) and splits the host into 8 virtual
devices so real Mesh/pjit/GSPMD code paths — including collectives — execute
in unit tests exactly as they would on an 8-chip slice.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"

import jax  # noqa: E402

# The sandbox's sitecustomize pre-imports jax and registers the `axon` TPU
# PJRT plugin before any conftest can run, so the env vars above may be read
# too late; config.update wins regardless of import order.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)

import pytest  # noqa: E402
import pytest  # noqa: E402

from distributeddeeplearningspark_tpu.session import Session  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_session():
    """Each test gets a clean Session slate (module-level singleton)."""
    yield
    if Session._active is not None:
        Session._active.stop()


@pytest.fixture
def eight_devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 fake CPU devices, got {len(devs)}"
    return devs
