"""Metrics time-series plane: store durability, downsample arithmetic,
trend rules, --history, OpenMetrics exposition, perf_guard --series.

Everything on fake clocks — no sleeps. The live end-to-end drill (a real
train run + a faulted serving fleet populating multi-resolution series,
the predictive WARN beating the level CRIT, a real scrape tying out
against health.json) is ``tools/ci.sh history``; this file pins the
contracts it relies on.
"""

import json
import math
import os
import re
import subprocess
import sys
import urllib.request

import pytest

from distributeddeeplearningspark_tpu import status, telemetry
from distributeddeeplearningspark_tpu.telemetry import health
from distributeddeeplearningspark_tpu.telemetry import series
from distributeddeeplearningspark_tpu.telemetry import trace as trace_lib
from tools import perf_guard


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def tick(self, dt):
        self.t += dt


def _alert_events(workdir):
    return [e for e in telemetry.read_events(workdir)
            if e.get("kind") == "alert"]


# -- keys ---------------------------------------------------------------------


def test_series_key_roundtrip():
    assert series.series_key("goodput_frac") == "goodput_frac"
    k = series.series_key("queue_depth", replica="p0")
    assert k == "queue_depth{replica=p0}"
    assert series.parse_key(k) == ("queue_depth", {"replica": "p0"})
    # labels encode sorted -> one identity per (name, labels)
    a = series.series_key("x", b="2", a="1")
    assert a == "x{a=1,b=2}" and series.parse_key(a)[1] == {"a": "1",
                                                            "b": "2"}


# -- downsample arithmetic ----------------------------------------------------


def test_bucket_downsample_arithmetic_hand_computed(tmp_path):
    store = series.SeriesStore(tmp_path, resolutions=((10.0, 8), (40.0, 4)))
    # fake-clock sequence: ts 0,5 land in bucket 0; 12,15,18 in bucket 10;
    # 41 in bucket 40
    for ts, v in ((0.0, 4.0), (5.0, 2.0), (12.0, 10.0), (15.0, 7.0),
                  (18.0, 1.0), (41.0, 5.0)):
        assert store.record(ts, {"m": v}) is True
    fine = series.read_buckets(tmp_path, 10.0)["m"]
    assert [b["t"] for b in fine] == [0.0, 10.0, 40.0]
    b0, b1, b2 = fine
    assert (b0["count"], b0["min"], b0["max"], b0["mean"], b0["last"]) == (
        2, 2.0, 4.0, 3.0, 2.0)
    assert (b1["count"], b1["min"], b1["max"], b1["last"]) == (3, 1.0,
                                                               10.0, 1.0)
    assert b1["mean"] == pytest.approx(6.0)  # (10+7+1)/3
    assert (b2["count"], b2["last"]) == (1, 5.0)
    coarse = series.read_buckets(tmp_path, 40.0)["m"]
    assert [b["t"] for b in coarse] == [0.0, 40.0]
    assert coarse[0]["count"] == 5 and coarse[0]["mean"] == pytest.approx(
        24.0 / 5)
    assert coarse[0]["min"] == 1.0 and coarse[0]["max"] == 10.0


def test_record_replay_is_idempotent_and_nonfinite_dropped(tmp_path):
    store = series.SeriesStore(tmp_path, resolutions=((10.0, 8),))
    assert store.record(5.0, {"m": 1.0}) is True
    assert store.record(5.0, {"m": 99.0}) is False   # same ts: replay
    assert store.record(4.0, {"m": 99.0}) is False   # past ts: replay
    assert store.record(6.0, {"m": float("nan"),
                              "x": float("inf")}) is False
    assert series.read_buckets(tmp_path, 10.0)["m"][0]["last"] == 1.0


def test_reopened_store_continues_and_seeds_tails(tmp_path):
    a = series.SeriesStore(tmp_path, resolutions=((10.0, 8),))
    for i in range(4):
        a.record(float(i), {"m": float(i)})
    b = series.SeriesStore(tmp_path)
    assert b.resolutions == ((10.0, 8),)   # ladder read back from header
    assert b.last_ts == 3.0
    assert b.tails["m"]                     # history survives the restart
    b.record(25.0, {"m": 9.0})
    got = series.read_buckets(tmp_path, 10.0)["m"]
    assert [bk["t"] for bk in got] == [0.0, 20.0]


# -- crash tolerance ----------------------------------------------------------


def test_torn_segment_line_skipped_and_writes_continue(tmp_path):
    store = series.SeriesStore(tmp_path, resolutions=((1.0, 16),))
    for i in range(4):
        store.record(float(i), {"m": float(i)})   # finalizes buckets 0..2
    path = os.path.join(series.series_dir(tmp_path),
                        series.bucket_filename(1.0))
    with open(path, "a") as f:
        f.write('{"t": 99.0, "k": "m", "n": 1, "mi')  # torn mid-append
    got = series.read_buckets(tmp_path, 1.0)["m"]
    assert [b["t"] for b in got] == [0.0, 1.0, 2.0, 3.0]
    # a new writer instance keeps going on the same segment
    b = series.SeriesStore(tmp_path)
    b.record(5.0, {"m": 5.0})
    assert [x["t"] for x in series.read_buckets(tmp_path, 1.0)["m"]][-1] == 5.0


def test_truncated_segment_recovers(tmp_path):
    store = series.SeriesStore(tmp_path, resolutions=((1.0, 16),))
    for i in range(6):
        store.record(float(i), {"m": float(i)})
    path = os.path.join(series.series_dir(tmp_path),
                        series.bucket_filename(1.0))
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(size // 2)   # half a segment, ends mid-line
    got = series.read_buckets(tmp_path, 1.0).get("m", [])
    assert all(math.isfinite(b["mean"]) for b in got)
    # open bucket (ts=5) still served from the atomic header
    assert any(b["t"] == 5.0 for b in got)


def test_duplicate_bucket_lines_dedupe_last_wins(tmp_path):
    """A crash between the bucket append and the header rewrite replays
    the same (key, t) on restart — readers keep the newest line."""
    store = series.SeriesStore(tmp_path, resolutions=((1.0, 16),))
    store.record(0.0, {"m": 1.0})
    store.record(1.5, {"m": 2.0})   # finalizes bucket t=0
    path = os.path.join(series.series_dir(tmp_path),
                        series.bucket_filename(1.0))
    with open(path, "a") as f:      # the replayed duplicate, updated
        f.write(json.dumps({"t": 0.0, "k": "m", "n": 2, "min": 1.0,
                            "max": 3.0, "sum": 4.0, "last": 3.0}) + "\n")
    b0 = series.read_buckets(tmp_path, 1.0)["m"][0]
    assert (b0["count"], b0["last"], b0["max"]) == (2, 3.0, 3.0)


def test_compaction_bounds_ring_mid_append(tmp_path):
    """Rotation mid-append: the ring bound is enforced by temp+rename
    compaction, stale temps are ignored, newest buckets survive."""
    cap = 4
    store = series.SeriesStore(tmp_path, resolutions=((1.0, cap),))
    sdir = series.series_dir(tmp_path)
    os.makedirs(sdir, exist_ok=True)
    stale = os.path.join(sdir, series.bucket_filename(1.0) + ".tmp.999")
    with open(stale, "w") as f:
        f.write("leftover from a crashed compaction\n")
    for i in range(40):
        store.record(float(i), {"m": float(i)})
    path = os.path.join(sdir, series.bucket_filename(1.0))
    with open(path) as f:
        lines = sum(1 for _ in f)
    assert lines <= 2 * cap + 1   # bounded, not 39 finalized lines
    got = series.read_buckets(tmp_path, 1.0)["m"]
    assert got[-1]["t"] == 39.0   # newest survive
    assert len(got) >= cap
    assert os.path.exists(stale)  # ignored, never parsed


# -- trend fitting / sparklines ----------------------------------------------


def test_linear_trend_exact_and_degenerate():
    fit = series.linear_trend([(0.0, 1.0), (10.0, 2.0), (20.0, 3.0)])
    assert fit["slope_per_s"] == pytest.approx(0.1)
    assert fit["level"] == pytest.approx(2.0)
    assert series.linear_trend([(0.0, 1.0)]) is None
    assert series.linear_trend([(5.0, 1.0), (5.0, 2.0)]) is None
    assert series.trend_verdict(fit) == "rising"
    flat = series.linear_trend([(0.0, 2.0), (10.0, 2.0)])
    assert series.trend_verdict(flat) == "flat"
    assert series.trend_verdict(None) == "flat"
    down = series.linear_trend([(0.0, 3.0), (10.0, 1.0)])
    assert series.trend_verdict(down) == "falling"


def test_sparkline_finite_and_gaps():
    s = series.sparkline([0.0, 1.0, 2.0, 3.0])
    assert s[0] == "▁" and s[-1] == "█" and len(s) == 4
    assert series.sparkline([5.0, 5.0, 5.0]) == "▄▄▄"
    gap = series.sparkline([1.0, float("nan"), 2.0, None])
    assert gap[1] == "·" and gap[3] == "·"
    assert series.sparkline([]) == ""


# -- history report (pinned schema) -------------------------------------------


def _engine_workdir(tmp_path, evals=8):
    clock = FakeClock(0.0)
    w = telemetry.EventWriter(tmp_path, process="p0", clock=clock)
    eng = health.HealthEngine(tmp_path, damping=2, clock=clock,
                              window_s=100.0)
    for i in range(evals):
        w.emit("serve", queue_depth=float(i))
        w.emit("request", outcome="ok", latency_s=0.01)
        clock.tick(5.0)
        eng.evaluate()
    eng.close()
    w.close()
    return clock


def test_history_report_pinned_keys(tmp_path):
    _engine_workdir(tmp_path)
    hist = series.history_report(tmp_path, since_s=3600.0)
    assert tuple(hist) == series.HISTORY_KEYS
    assert hist["schema"] == series.HISTORY_SCHEMA
    assert hist["series"]
    for row in hist["series"]:
        assert tuple(row) == series.HISTORY_ROW_KEYS
        assert "nan" not in row["spark"].lower()
    keys = [r["key"] for r in hist["series"]]
    assert "queue_depth{replica=p0}" in keys
    assert series.ENGINE_TICK_SERIES in keys  # engine self-telemetry
    # KEY filter: exact key or bare series name
    one = series.history_report(tmp_path, key="queue_depth",
                                since_s=3600.0)
    assert [r["key"] for r in one["series"]] == ["queue_depth{replica=p0}"]


def test_dlstatus_history_json_and_filters(tmp_path, capsys):
    _engine_workdir(tmp_path)
    assert status.main([str(tmp_path), "--history", "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert tuple(doc) == series.HISTORY_KEYS
    assert all(tuple(r) == series.HISTORY_ROW_KEYS for r in doc["series"])
    rc = status.main([str(tmp_path), "--history", "queue_depth",
                      "--since", "10m"])
    out = capsys.readouterr().out
    assert rc == 0 and "queue_depth{replica=p0}" in out
    assert any(g in out for g in "▁▂▃▄▅▆▇█")
    # an explicit resolution overrides the --since auto-pick
    assert status.main([str(tmp_path), "--history", "--json",
                        "--resolution", "120"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["resolution_s"] == 120.0


def test_dlstatus_history_without_store_is_rc1(tmp_path, capsys):
    w = telemetry.EventWriter(tmp_path, process="p0", clock=FakeClock())
    w.heartbeat(step=1)
    w.close()
    assert status.main([str(tmp_path), "--history"]) == 1
    assert "no series store" in capsys.readouterr().err


# -- predictive trend rules ---------------------------------------------------


def test_predictive_warn_fires_before_level_crit(tmp_path):
    """The tentpole ordering contract: the trend rule's projection WARN
    raises strictly before the damped level CRIT on a growing queue."""
    clock = FakeClock(0.0)
    w = telemetry.EventWriter(tmp_path, process="p0", clock=clock)
    eng = health.HealthEngine(tmp_path, damping=2, clock=clock,
                              window_s=100.0)
    for i in range(12):
        w.emit("serve", queue_depth=2 + 4 * i)
        clock.tick(5.0)
        eng.evaluate()
    eng.close()
    w.close()
    edges = {(e["edge"], e["key"]): float(e["ts"])
             for e in _alert_events(tmp_path)}
    assert ("raise", "trend:queue:p0") in edges
    assert ("raise", "queue:p0") in edges
    crit = [e for e in _alert_events(tmp_path)
            if e["key"] == "queue:p0" and e["severity"] == "CRIT"]
    assert crit and edges[("raise", "trend:queue:p0")] < float(
        crit[0]["ts"])
    trend_raise = [e for e in _alert_events(tmp_path)
                   if e["key"] == "trend:queue:p0"
                   and e["edge"] == "raise"][0]
    assert trend_raise["severity"] == "WARN"
    assert trend_raise["evidence"]["projected_crit_in_s"] > 0
    # once the level CRIT owns the incident the trend alert clears
    assert ("clear", "trend:queue:p0") in edges


def test_trend_slo_projects_exhausted(tmp_path):
    w = telemetry.EventWriter(tmp_path, process="p0", clock=FakeClock(30.0))
    for i in range(100):
        w.emit("request", outcome="ok", tenant="t0",
               latency_s=(1.0 if i < 3 else 0.01))
    w.close()
    events = telemetry.read_events(tmp_path)
    key = series.series_key(series.BURN_SERIES, tenant="t0")
    tails = {key: [(0.0, 1.1), (10.0, 1.5), (20.0, 2.0)]}
    rep = health.evaluate_health(events, slo_target_s=0.5, now=30.0,
                                 window_s=300.0, trend_tails=tails)
    slo_rows = rep["slo"]["tenants"]["t0"]
    assert slo_rows["verdict"] == "BURNING"   # not yet EXHAUSTED
    trend = [v for v in rep["_verdicts"] if v["rule"] == "trend_slo"]
    assert len(trend) == 1 and trend[0]["severity"] == "WARN"
    ev = trend[0]["evidence"]
    assert ev["projected_exhausted_in_s"] <= 300.0
    assert "EXHAUSTED" in trend[0]["summary"]
    # without memory the same stream raises no prediction
    bare = health.evaluate_health(events, slo_target_s=0.5, now=30.0,
                                  window_s=300.0)
    assert [v for v in bare["_verdicts"] if v["rule"].startswith(
        "trend")] == []


def test_trend_engine_rule_warns_on_growing_lag(tmp_path):
    w = telemetry.EventWriter(tmp_path, process="p0", clock=FakeClock(30.0))
    w.heartbeat(step=1)
    w.close()
    tails = {series.ENGINE_LAG_SERIES: [(0.0, 100.0), (10.0, 2000.0),
                                        (20.0, 5000.0), (30.0, 9000.0)]}
    rep = health.evaluate_health(telemetry.read_events(tmp_path),
                                 now=31.0, trend_tails=tails)
    v = [v for v in rep["_verdicts"] if v["rule"] == "trend_engine"]
    assert len(v) == 1 and v[0]["key"] == "trend:engine"
    assert v[0]["evidence"]["lag_bytes"] == 9000.0


def test_engine_self_telemetry_gauge_and_series(tmp_path):
    _engine_workdir(tmp_path)
    with open(os.path.join(str(tmp_path), health.HEALTH_FILENAME)) as f:
        doc = json.load(f)
    assert set(doc["engine"]) == {"tick_s", "lag_bytes", "rules_evaluated",
                                  "bytes_read"}
    assert doc["engine"]["rules_evaluated"] == len(health.RULES)
    got = series.read_buckets(tmp_path, 10.0)
    for key in (series.ENGINE_TICK_SERIES, series.ENGINE_LAG_SERIES,
                series.ENGINE_RULES_SERIES):
        assert key in got


# -- cursor byte accounting ---------------------------------------------------


def test_cursor_bytes_read_and_lag(tmp_path):
    w = telemetry.EventWriter(tmp_path, process="p0", clock=FakeClock())
    for i in range(10):
        w.heartbeat(step=i)
    cur = telemetry.EventCursor(tmp_path)
    assert cur.lag_bytes() > 0          # appended, unread
    cur.poll()
    assert cur.lag_bytes() == 0
    first = cur.bytes_read
    assert first > 0
    cur.poll()                          # nothing new: no re-read
    assert cur.bytes_read == first
    w.heartbeat(step=10)
    assert cur.lag_bytes() > 0
    cur.poll()
    w.close()
    total = sum(os.path.getsize(p) for p in telemetry.event_files(tmp_path))
    assert cur.bytes_read == total      # read-once, bounded by appends


# -- cluster: trend column + cursor watch -------------------------------------


def _train_workdir(root, name):
    wd = os.path.join(root, name)
    clock = FakeClock(0.0)
    w = telemetry.EventWriter(wd, process="p0", clock=clock)
    eng = health.HealthEngine(wd, damping=1, clock=clock,
                              write_alerts=False)
    for step in range(1, 5):
        w.step_metrics(step, steps=1, lap_s=1.0, metrics={})
        clock.tick(1.0)
        eng.evaluate()
    eng.close()
    w.heartbeat(step=4)
    w.close()
    return wd


def test_cluster_trend_column_and_cursor_reads(tmp_path):
    root = str(tmp_path)
    wd = _train_workdir(root, "jobs/a")
    cursors = {}
    rep = health.cluster_report(root, cursors=cursors)
    row = rep["workdirs"][0]
    assert row["trend"] is not None
    assert row["trend"]["key"] == series.GOODPUT_SERIES
    assert row["trend"]["trend"] in ("rising", "falling", "flat")
    first = sum(c.bytes_read for c in cursors.values())
    total = sum(os.path.getsize(p) for p in telemetry.event_files(wd))
    assert first <= total
    # a second tick with nothing appended re-reads nothing
    health.cluster_report(root, cursors=cursors)
    assert sum(c.bytes_read for c in cursors.values()) == first
    # the human render gains the trend column
    out = status.render_cluster(rep)
    assert "trend" in out.splitlines()[1]


# -- OpenMetrics exposition ---------------------------------------------------

_OM_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? -?[0-9+.eEnaIf-]+$")


def test_openmetrics_schema_and_bitwise_tie(tmp_path):
    _engine_workdir(tmp_path)
    body = series.openmetrics_exposition(tmp_path)
    lines = body.splitlines()
    assert lines[-1] == "# EOF"
    seen_types = set()
    values = {}
    for ln in lines[:-1]:
        if ln.startswith("# TYPE "):
            fam = ln.split()[2]
            assert fam not in seen_types    # one TYPE line per family
            seen_types.add(fam)
            assert ln.endswith(" gauge")
            continue
        assert _OM_LINE.match(ln), ln
        name_labels, _, raw = ln.rpartition(" ")
        assert name_labels.split("{", 1)[0] in seen_types
        values[name_labels] = float(raw)
    with open(os.path.join(str(tmp_path), health.HEALTH_FILENAME)) as f:
        doc = json.load(f)
    wd = os.fspath(tmp_path)
    # gauge values bitwise-tie to the health.json they mirror
    assert values[f'dls_goodput_frac{{workdir="{wd}"}}'] == (
        doc["goodput"]["goodput_frac"])
    assert values[
        f'dls_queue_depth{{replica="p0",workdir="{wd}"}}'] == (
        doc["queue_depth"]["p0"])
    assert values[f'dls_health_alerts_active{{workdir="{wd}"}}'] == len(
        doc["alerts_active"])
    sev = {s: i for i, s in enumerate(health.SEVERITIES)}
    assert values[f'dls_health_worst_severity{{workdir="{wd}"}}'] == (
        sev[doc["worst_severity"]])
    # series gauges expose the newest finest bucket per stat
    assert any(k.startswith("dls_series_queue_depth{") for k in values)


def test_openmetrics_label_escaping(tmp_path):
    store = series.SeriesStore(tmp_path, resolutions=((10.0, 8),))
    store.record(1.0, {series.series_key("m", host='a"b\\c'): 1.0})
    body = series.openmetrics_exposition(tmp_path)
    assert 'host="a\\"b\\\\c"' in body
    assert body.endswith("# EOF\n")


def test_serve_metrics_endpoint_scrape(tmp_path):
    """--serve-metrics: a real scrape over HTTP returns the exposition
    byte-for-byte with the OpenMetrics content type."""
    _engine_workdir(tmp_path)
    proc = subprocess.Popen(
        [sys.executable, "-m", "distributeddeeplearningspark_tpu.status",
         str(tmp_path), "--serve-metrics", "0", "--watch-count", "1"],
        stderr=subprocess.PIPE, text=True)
    try:
        banner = proc.stderr.readline()
        m = re.search(r"http://([\d.]+):(\d+)/metrics", banner)
        assert m, banner
        with urllib.request.urlopen(
                f"http://{m.group(1)}:{m.group(2)}/metrics",
                timeout=10) as resp:
            ctype = resp.headers["Content-Type"]
            got = resp.read().decode("utf-8")
        assert ctype == series.OPENMETRICS_CONTENT_TYPE
        assert got == series.openmetrics_exposition(tmp_path)
        assert proc.wait(timeout=10) == 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()


# -- chrome trace counter tracks ----------------------------------------------


def test_chrome_trace_series_counter_tracks(tmp_path):
    _engine_workdir(tmp_path)
    events = telemetry.read_events(tmp_path)
    buckets = series.read_buckets(tmp_path, 10.0)
    data = trace_lib.chrome_trace(events, series_buckets=buckets)
    counters = [e for e in data["traceEvents"]
                if e.get("ph") == "C" and e.get("cat") == "series"]
    assert counters
    assert {"queue_depth{replica=p0}"} <= {c["name"] for c in counters}
    assert all(math.isfinite(c["args"]["mean"]) for c in counters)
    assert all(c["ts"] >= 0 for c in counters)


# -- perf_guard --series ------------------------------------------------------


def _mk_buckets(vals, t0=0.0, width=10.0):
    return [{"t": t0 + i * width, "count": 1, "min": v, "max": v,
             "mean": v, "last": v} for i, v in enumerate(vals)]


def test_guard_series_flags_within_run_decline():
    declining = _mk_buckets([10.0] * 6 + [6.0] * 6)   # last quartile -40%
    steady = _mk_buckets([0.9] * 12)
    rep = perf_guard.guard_series({
        "steps_per_sec": declining, "goodput_frac": steady}, band=0.15)
    assert rep["verdict"] == "REGRESSED"
    assert rep["regressed"] == ["steps_per_sec"]
    by = {c["check"]: c for c in rep["checks"]}
    assert by["goodput_frac"]["status"] == "ok"
    # lower-better series regress on GROWTH
    rep2 = perf_guard.guard_series({
        "queue_depth{replica=p0}": _mk_buckets([1.0] * 6 + [9.0] * 6)})
    assert rep2["regressed"] == ["queue_depth{replica=p0}"]
    # a decline inside the band is noise
    ok = perf_guard.guard_series({
        "steps_per_sec": _mk_buckets([10.0] * 6 + [9.0] * 6)})
    assert ok["verdict"] == "OK"
    # too few buckets -> refuses to guess; unknown series never judged
    few = perf_guard.guard_series({"steps_per_sec": _mk_buckets([1.0] * 4),
                                   "unguarded_series": _mk_buckets(
                                       [1.0] * 12)})
    assert few["verdict"] == "INSUFFICIENT_HISTORY"


def test_perf_guard_series_cli(tmp_path, capsys):
    missing = tmp_path / "nope"
    assert perf_guard.main(["--series", str(missing)]) == 2
    capsys.readouterr()
    store = series.SeriesStore(tmp_path, resolutions=((10.0, 64),))
    for i in range(16):
        v = 10.0 if i < 8 else 5.0       # in-run 50% steps/sec collapse
        store.record(i * 10.0 + 5.0, {"steps_per_sec": v})
    store.flush()
    assert perf_guard.main(["--series", str(tmp_path), "--json"]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["verdict"] == "REGRESSED"
    assert doc["regressed"] == ["steps_per_sec"]
