"""Request-level distributed tracing (ISSUE 7): span model + crash-tolerant
tree fold, train-phase lowering, Chrome trace_event export, latency anatomy,
SLO sentinel, telemetry segment rotation, heartbeat/span hang localization,
and the serve-layer span emission (engine, generator, router).

Reader-side tests run on synthetic timestamped records (fake clocks, no
sleeps) — the folds are pure functions over streams, torn or whole. The
jit-bearing tests at the bottom drive a real engine/generator and pin the
acceptance shape: every request yields a complete causal tree whose stage
sum covers ≥95% of its end-to-end latency.
"""

import json

import numpy as np
import pytest

from distributeddeeplearningspark_tpu import status, telemetry
from distributeddeeplearningspark_tpu.telemetry import fleet as fleet_lib
from distributeddeeplearningspark_tpu.telemetry import trace as trace_lib


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def tick(self, dt):
        self.t += dt


def _span_ev(trace_id, span_id, name, t0, t1, *, parent_id=None,
             process="p0", ts=None, **attrs):
    """A span record as it appears ON THE BUS (what EventWriter appends)."""
    rec = {"ts": ts if ts is not None else (t1 if t1 is not None else t0),
           "kind": "span", "process": process, "trace_id": trace_id,
           "span_id": span_id, "name": name, "t0": t0, "t1": t1}
    if parent_id is not None:
        rec["parent_id"] = parent_id
    if attrs:
        rec["attrs"] = attrs
    return rec


def _request_tree(tid, *, t0=100.0, dur=1.0, process="p0", tenant=None,
                  outcome="ok", stages=("queue", "prefill", "decode")):
    """A complete request trace: root + evenly-split stage children."""
    attrs = {"outcome": outcome, "hops": 0}
    if tenant is not None:
        attrs["tenant"] = tenant
    evs = [_span_ev(tid, f"{tid}-root", "request", t0, t0 + dur,
                    process=process, **attrs)]
    step = dur / len(stages)
    for i, name in enumerate(stages):
        evs.append(_span_ev(tid, f"{tid}-s{i}", name, t0 + i * step,
                            t0 + (i + 1) * step, parent_id=f"{tid}-root",
                            process=process))
    return evs


# -- SpanBuffer / context -----------------------------------------------------


def test_span_buffer_roots_fresh_trace_without_context():
    buf = trace_lib.SpanBuffer.from_context(None)
    assert not buf.joined
    root = buf.add("request", 1.0, 2.0, outcome="ok")
    buf.add("queue", 1.0, 1.5, parent_id=root)
    assert len(buf.records) == 2
    assert buf.records[0]["span_id"] == root
    assert buf.records[1]["parent_id"] == root
    assert buf.records[0]["attrs"] == {"outcome": "ok"}


def test_span_buffer_joins_upstream_context():
    """The two-field trace context the router puts on the replica socket:
    a joined buffer parents its spans under the upstream span, and does
    NOT emit its own root."""
    buf = trace_lib.SpanBuffer.from_context(
        {"trace_id": "abc", "parent_id": "root1"})
    assert buf.joined and buf.trace_id == "abc"
    buf.add("queue", 1.0, 1.5)
    assert buf.records[0]["parent_id"] == "root1"
    # a malformed / empty context roots a fresh trace instead of crashing
    assert not trace_lib.SpanBuffer.from_context({}).joined
    assert not trace_lib.SpanBuffer.from_context("garbage").joined


def test_span_buffer_flush_writes_once_and_clears(tmp_path):
    w = telemetry.EventWriter(tmp_path, process="p0", clock=lambda: 5.0,
                              host=None)
    buf = trace_lib.SpanBuffer()
    root = buf.add("request", 1.0, 2.0)
    buf.add("queue", 1.0, 1.5, parent_id=root)
    buf.flush(w)
    assert buf.records == []
    buf.flush(w)  # empty flush: no-op
    buf.flush(None)  # writer-less serving: no-op, no crash
    w.close()
    evs = telemetry.read_events(tmp_path)
    assert len(evs) == 2 and all(e["kind"] == "span" for e in evs)


# -- trace_trees: the crash-tolerant fold ------------------------------------


def test_trace_trees_builds_causal_tree():
    evs = _request_tree("t1")
    trees = trace_lib.trace_trees(evs)
    tree = trees["t1"]
    assert not tree["incomplete"]
    assert tree["root"]["span"]["name"] == "request"
    names = [c["span"]["name"] for c in tree["root"]["children"]]
    assert names == ["queue", "prefill", "decode"]  # sorted by t0


def test_trace_trees_parentless_span_is_orphan_flagged_incomplete():
    """Crash mid-request: the child spans' emit landed but the root's
    died with the process — the evidence still renders as orphans."""
    evs = _request_tree("t1")[1:]  # drop the root
    tree = trace_lib.trace_trees(evs)["t1"]
    assert tree["incomplete"]
    assert tree["root"] is None
    assert len(tree["orphans"]) == 3


def test_trace_trees_unclosed_span_flagged_incomplete():
    evs = _request_tree("t1")
    evs[2]["t1"] = None  # prefill never closed
    tree = trace_lib.trace_trees(evs)["t1"]
    assert tree["incomplete"] and tree["root"] is not None


def test_trace_trees_duplicate_and_garbage_records_never_throw():
    evs = _request_tree("t1")
    evs.append(dict(evs[0]))                        # duplicate span id
    evs.append({"ts": 1.0, "kind": "span"})         # no ids at all
    evs.append({"ts": 1.0, "kind": "span", "trace_id": "t1",
                "span_id": "x", "name": "bad", "t0": "not-a-float"})
    evs.append({"ts": 1.0, "kind": "step_metrics", "step": 3})
    tree = trace_lib.trace_trees(evs)["t1"]
    assert tree["num_spans"] == 4 and not tree["incomplete"]


def test_trace_trees_two_roots_keeps_earliest():
    evs = _request_tree("t1")
    evs.append(_span_ev("t1", "r2", "request", 200.0, 201.0))
    tree = trace_lib.trace_trees(evs)["t1"]
    assert tree["root"]["span"]["span_id"] == "t1-root"
    assert tree["incomplete"]  # the extra root is flagged, not silently kept
    assert any(o["span"]["span_id"] == "r2" for o in tree["orphans"])


def test_trace_trees_self_parented_span_is_orphan_not_cycle():
    evs = [_span_ev("t1", "s1", "request", 1.0, 2.0, parent_id="s1")]
    tree = trace_lib.trace_trees(evs)["t1"]
    assert tree["root"] is None and len(tree["orphans"]) == 1


# -- train-phase lowering -----------------------------------------------------


def _phase_ev(ts, name, edge, process="p0"):
    return {"ts": ts, "kind": "phase", "process": process, "name": name,
            "edge": edge}


def test_spans_from_phases_nesting_and_open_spans():
    """begin/end pairs lower to nested spans; a begin with no end becomes
    an open span (t1=None) — the honest shape of a crash mid-phase."""
    evs = [
        _phase_ev(0.0, "run", "begin"),
        _phase_ev(1.0, "checkpoint", "begin"),
        _phase_ev(1.2, "checkpoint-wait", "begin"),
        _phase_ev(1.8, "checkpoint-wait", "end"),
        _phase_ev(2.0, "checkpoint", "end"),
        _phase_ev(3.0, "restore", "begin"),  # crash: no end, run never ends
    ]
    spans = trace_lib.spans_from_phases(evs)
    by_name = {s["name"]: s for s in spans}
    assert by_name["checkpoint-wait"]["parent_id"] == \
        by_name["checkpoint"]["span_id"]
    assert by_name["checkpoint"]["parent_id"] == by_name["run"]["span_id"]
    assert by_name["restore"]["t1"] is None and by_name["run"]["t1"] is None
    assert all(s["trace_id"] == "train:p0" for s in spans)


def test_spans_from_phases_run_begin_resets_stack():
    """A relaunched attempt appends to the same file: its phases must not
    parent into the crashed session's open spans."""
    evs = [
        _phase_ev(0.0, "run", "begin"),
        _phase_ev(1.0, "restore", "begin"),      # crashed mid-restore
        _phase_ev(10.0, "run", "begin"),         # relaunch
        _phase_ev(11.0, "compile", "begin"),
        _phase_ev(12.0, "compile", "end"),
    ]
    spans = trace_lib.spans_from_phases(evs)
    compile_s = next(s for s in spans if s["name"] == "compile")
    runs = [s for s in spans if s["name"] == "run"]
    assert compile_s["parent_id"] == runs[-1]["span_id"]
    restore = next(s for s in spans if s["name"] == "restore")
    assert restore["t1"] is None
    # an end with no begin (rotated-away head) is dropped, not raised on
    assert trace_lib.spans_from_phases(
        [_phase_ev(1.0, "eval", "end")]) == []


# -- Chrome trace_event export ------------------------------------------------


def test_chrome_trace_valid_and_covers_serve_and_train():
    evs = _request_tree("t1") + [
        _phase_ev(50.0, "run", "begin", process="p1"),
        _phase_ev(51.0, "compile", "begin", process="p1"),
        _phase_ev(55.0, "compile", "end", process="p1"),
    ]
    data = json.loads(json.dumps(trace_lib.chrome_trace(evs)))
    assert data["displayTimeUnit"] == "ms"
    tevs = data["traceEvents"]
    cats = {e.get("cat") for e in tevs if e.get("ph") in ("X", "B")}
    assert cats == {"serve", "train"}
    complete = [e for e in tevs if e["ph"] == "X"]
    for e in complete:
        assert {"name", "pid", "tid", "ts", "dur"} <= set(e)
        assert e["ts"] >= 0 and e["dur"] >= 0  # µs, relative to the epoch
    # the open `run` phase exports as a lone B (begin) event
    opens = [e for e in tevs if e["ph"] == "B"]
    assert {e["name"] for e in opens} == {"run"}
    # metadata rows name every process
    meta = [e for e in tevs if e["ph"] == "M" and e["name"] == "process_name"]
    assert {m["args"]["name"] for m in meta} == {"p0", "p1"}
    assert trace_lib.chrome_trace([]) == {"traceEvents": [],
                                          "displayTimeUnit": "ms"}


# -- request anatomy / latency fold ------------------------------------------


def test_request_anatomy_coverage_and_incomplete():
    evs = _request_tree("t1", t0=100.0, dur=1.0)
    evs += _request_tree("t2", t0=200.0, dur=2.0)[:2]  # torn: root + queue
    evs[-1]["t1"] = None                               # queue never closed
    recs = {r["trace_id"]: r for r in trace_lib.request_anatomy(evs)}
    full = recs["t1"]
    assert not full["incomplete"] and full["e2e_s"] == pytest.approx(1.0)
    assert full["coverage"] == pytest.approx(1.0)
    assert set(full["stages"]) == {"queue", "prefill", "decode"}
    assert recs["t2"]["incomplete"]


def test_latency_anatomy_percentiles_and_slowest():
    evs = []
    for i, dur in enumerate([0.1, 0.2, 0.3, 5.0]):
        evs += _request_tree(f"t{i}", t0=100.0 + 10 * i, dur=dur,
                             process=f"p{i % 2}")
    la = fleet_lib.latency_anatomy(evs, slow_n=2)
    assert la["requests"] == 4 and la["complete"] == 4
    assert la["coverage_median"] == pytest.approx(1.0)
    assert set(la["stages"]) == {"queue", "prefill", "decode"}
    assert la["stages"]["decode"]["count"] == 4
    assert [r["trace_id"] for r in la["slowest"]] == ["t3", "t2"]
    assert set(la["per_process"]) == {"p0", "p1"}
    assert fleet_lib.latency_anatomy([]) is None


def test_latency_anatomy_sheds_do_not_skew_latency_pools():
    """A shed's root-only trace (closed root, zero stage spans, few-ms
    e2e) must not drag coverage toward 0 and p50 toward 0 during the
    shed-heavy incident the operator is debugging."""
    evs = []
    for i in range(3):
        evs += _request_tree(f"ok{i}", t0=10.0 * i, dur=1.0)
    for i in range(5):  # root-only sheds, 1ms each
        evs.append(_span_ev(f"sh{i}", f"sh{i}-root", "request",
                            100.0 + i, 100.001 + i,
                            outcome="shed", hops=0))
    la = fleet_lib.latency_anatomy(evs)
    assert la["requests"] == 8 and la["complete"] == 8
    assert la["e2e_p50_s"] == pytest.approx(1.0)   # served requests only
    assert la["coverage_median"] == pytest.approx(1.0)
    assert all(r["outcome"] == "ok" for r in la["slowest"])


# -- SLO sentinel -------------------------------------------------------------


def test_slo_report_verdict_ladder():
    """GOOD at burn ≤1×, BURNING above, EXHAUSTED at ≥10× — and the slow
    tail is judged per request against the target, not via averages."""
    evs = []
    for i in range(99):
        evs += _request_tree(f"g{i}", t0=float(i), dur=0.01, tenant="good")
    evs += _request_tree("g99", t0=99.0, dur=5.0, tenant="good")  # 1% slow
    for i in range(10):
        dur = 5.0 if i < 5 else 0.01                              # 50% slow
        evs += _request_tree(f"b{i}", t0=200.0 + i, dur=dur, tenant="bad")
    rep = fleet_lib.slo_report(evs, target_p99_s=1.0, budget=0.01)
    assert rep["tenants"]["good"]["verdict"] == "GOOD"
    assert rep["tenants"]["good"]["burn_rate"] == pytest.approx(1.0)
    assert rep["tenants"]["bad"]["verdict"] == "EXHAUSTED"
    assert rep["totals"]["verdict"] == "BURNING"  # 6/110 ≈ 5.5% > 1% budget
    assert rep["totals"]["requests"] == 110


def test_slo_report_counts_sheds_errors_and_traceless_fallback():
    # traced run: errors + router tenant sheds count as violations
    evs = _request_tree("t1", dur=0.01, tenant="t")
    evs += _request_tree("t2", dur=0.01, tenant="t", outcome="error")
    evs.append({"ts": 300.0, "kind": "request", "process": "router",
                "outcome": "shed", "tenant": "t"})
    rep = fleet_lib.slo_report(evs, target_p99_s=1.0, budget=0.5)
    row = rep["tenants"]["t"]
    assert row["requests"] == 3 and row["shed"] == 1 and row["errors"] == 1
    assert row["violations"] == 2

    # traced run, BARE-ENGINE sheds: queue-full rejections carry neither
    # tenant nor trace (no router minted one) — still violations, under
    # "default"; a replica-side shed inside a traced fleet request
    # carries `trace` and is skipped (its root span already counted it)
    evs2 = _request_tree("t9", dur=0.01, tenant="t")
    evs2.append({"ts": 300.0, "kind": "request", "process": "p0",
                 "outcome": "shed", "queue_depth": 4})
    evs2.append({"ts": 301.0, "kind": "request", "process": "p0",
                 "outcome": "shed", "queue_depth": 4, "trace": "t9"})
    rep2 = fleet_lib.slo_report(evs2, target_p99_s=1.0, budget=0.5)
    assert rep2["tenants"]["default"]["shed"] == 1
    assert rep2["totals"]["requests"] == 2

    # untraced run (no spans): plain request events under one tenant
    reqs = [{"ts": float(i), "kind": "request", "process": "p0",
             "outcome": "ok", "latency_s": 0.01} for i in range(9)]
    reqs.append({"ts": 9.0, "kind": "request", "process": "p0",
                 "outcome": "ok", "latency_s": 9.0})
    rep = fleet_lib.slo_report(reqs, target_p99_s=1.0, budget=0.01)
    assert rep["tenants"].keys() == {"default"}
    assert rep["totals"]["slow"] == 1
    assert rep["totals"]["verdict"] == "EXHAUSTED"  # 10% at 1% budget
    assert fleet_lib.slo_report([], target_p99_s=1.0) is None


# -- fleet rollup: failovers + per-tenant shed rate ---------------------------


def test_serving_fleet_surfaces_failovers_and_tenant_sheds():
    evs = [{"ts": 1.0, "kind": "request", "process": "p0", "engine": "m",
            "outcome": "ok", "latency_s": 0.01},
           {"ts": 2.0, "kind": "request", "process": "router",
            "outcome": "shed", "tenant": "greedy"}]
    evs += _request_tree("t1", tenant="greedy")
    evs.append(_span_ev("t1", "fo1", "failover", 100.1, 100.1,
                        parent_id="t1-root", process="router",
                        from_replica="r0"))
    fs = fleet_lib.serving_fleet(evs)
    t = fs["totals"]
    assert t["failovers"] == 1
    greedy = t["tenants"]["greedy"]
    assert greedy["requests"] == 2 and greedy["shed"] == 1
    assert greedy["shed_rate"] == 0.5


# -- dlstatus surfaces --------------------------------------------------------


def _write_traced_run(tmp_path):
    w = telemetry.EventWriter(tmp_path, process="p0", clock=lambda: 0.0,
                              host=None)
    evs = []
    for i, dur in enumerate([0.01, 0.02, 2.0]):
        evs += _request_tree(f"t{i}", t0=10.0 * i, dur=dur, tenant="t0")
    w.emit_many("span", [{k: v for k, v in e.items()
                          if k not in ("ts", "kind", "process")}
                         for e in evs])
    w.emit("phase", name="run", edge="begin")
    w.emit("phase", name="compile", edge="begin")
    w.emit("phase", name="compile", edge="end", dur_s=0.0)
    w.close()


def test_dlstatus_traces_slo_and_export(tmp_path, capsys):
    _write_traced_run(tmp_path)
    rep = status.report(str(tmp_path), traces=True, slo_target=1.0)
    assert rep["traces"]["requests"] == 3
    assert rep["slo"]["tenants"]["t0"]["slow"] == 1

    assert status.main([str(tmp_path), "--traces", "--slo", "1.0"]) == 0
    out = capsys.readouterr().out
    assert "request traces: 3" in out and "slowest requests:" in out
    assert "SLO: p99 target 1000.0ms" in out
    assert "EXHAUSTED" in out  # 1/3 slow at the default 1% budget

    export = tmp_path / "trace.json"
    assert status.main([str(tmp_path), "--export-trace", str(export)]) == 0
    capsys.readouterr()
    data = json.loads(export.read_text())  # loadable trace_event JSON
    cats = {e.get("cat") for e in data["traceEvents"]
            if e.get("ph") in ("X", "B")}
    assert cats == {"serve", "train"}  # both halves of the run present

    assert status.main([str(tmp_path), "--json", "--traces",
                        "--slo", "1.0"]) == 0
    rec = json.loads(capsys.readouterr().out)
    assert rec["traces"]["e2e_p99_s"] == pytest.approx(2.0)
    assert rec["slo"]["totals"]["verdict"] == "EXHAUSTED"


# -- telemetry segment rotation (satellite) -----------------------------------


def test_writer_rotates_at_size_cap_and_reader_merges(tmp_path):
    clock = FakeClock(0.0)
    w = telemetry.EventWriter(tmp_path, process="p0", clock=clock,
                              host=None, max_mb=2e-4)  # ~200 bytes
    for i in range(20):
        clock.tick(1.0)
        w.emit("heartbeat", seq=i)
    w.close()
    segs = telemetry.event_files(tmp_path)
    assert len(segs) > 1, segs  # rotation happened
    assert any(p.endswith("events-p0.jsonl") for p in segs)
    assert any(p.endswith("events-p0.1.jsonl") for p in segs)
    evs = telemetry.read_events(tmp_path)
    assert [e["seq"] for e in evs] == list(range(20))  # merged in order


def test_writer_resumes_newest_segment_after_restart(tmp_path):
    clock = FakeClock(0.0)
    w = telemetry.EventWriter(tmp_path, process="p0", clock=clock,
                              host=None, max_mb=2e-4)
    for i in range(20):
        clock.tick(1.0)
        w.emit("heartbeat", seq=i)
    w.close()
    n_segs = len(telemetry.event_files(tmp_path))
    # a restarted process extends its predecessor's rotation sequence
    w2 = telemetry.EventWriter(tmp_path, process="p0", clock=FakeClock(100.0),
                               host=None, max_mb=2e-4)
    w2.emit("heartbeat", seq=20)
    w2.close()
    assert len(telemetry.event_files(tmp_path)) == n_segs
    evs = telemetry.read_events(tmp_path)
    assert [e["seq"] for e in evs] == list(range(21))


def test_writer_survives_failed_rotation_reopen(tmp_path, monkeypatch):
    """A rotation whose reopen fails (disk full, EMFILE) must degrade to
    the telemetry warning contract — never leave a closed handle behind
    for the next emit to die on — and recover once opens succeed again."""
    import builtins

    clock = FakeClock(0.0)
    w = telemetry.EventWriter(tmp_path, process="p0", clock=clock,
                              host=None, max_mb=2e-4)
    w.emit("heartbeat", seq=0)  # opens segment 0

    real_open = builtins.open

    def failing_open(*a, **k):
        raise OSError("disk full")

    monkeypatch.setattr(builtins, "open", failing_open)
    for i in range(1, 12):  # enough to cross the cap → rotation attempt
        clock.tick(1.0)
        w.emit("heartbeat", seq=i)  # must warn, never raise
    monkeypatch.setattr(builtins, "open", real_open)
    clock.tick(1.0)
    w.emit("heartbeat", seq=99)  # recovered: lands in a real segment
    w.close()
    evs = telemetry.read_events(tmp_path)
    assert evs[-1]["seq"] == 99


def test_writer_unbounded_without_cap(tmp_path, monkeypatch):
    monkeypatch.delenv(telemetry.MAX_MB_ENV, raising=False)
    w = telemetry.EventWriter(tmp_path, process="p0", clock=FakeClock(),
                              host=None)
    for i in range(50):
        w.emit("heartbeat", seq=i)
    w.close()
    assert len(telemetry.event_files(tmp_path)) == 1
    # malformed env cap: warn-and-ignore, never break the writer
    monkeypatch.setenv(telemetry.MAX_MB_ENV, "banana")
    w = telemetry.EventWriter(tmp_path, process="p1", clock=FakeClock(),
                              host=None)
    w.emit("heartbeat", seq=0)
    w.close()
    assert w._max_bytes is None


# -- heartbeat/span hang localization (satellite) -----------------------------


def test_heartbeat_carries_oldest_open_request_span(tmp_path):
    clock = FakeClock(10.0)
    w = telemetry.EventWriter(tmp_path, process="p0", clock=clock, host=0)
    w.note_span(("req", 1), "request")
    clock.tick(2.0)
    w.note_span(("req", 2), "request")
    clock.tick(1.0)
    w.heartbeat()
    w.clear_span(("req", 1))
    clock.tick(1.0)
    w.heartbeat()
    w.clear_span(("req", 2))
    clock.tick(1.0)
    w.heartbeat()
    w.close()
    hbs = [e for e in telemetry.read_events(tmp_path)
           if e["kind"] == "heartbeat"]
    # oldest open request wins; its t0 is when THAT request was noted
    assert hbs[0]["phase"] == "request" and hbs[0]["phase_t0"] == 10.0
    assert hbs[1]["phase"] == "request" and hbs[1]["phase_t0"] == 12.0
    assert "phase" not in hbs[2]  # nothing open: plain liveness


def test_open_training_phase_wins_over_request_span(tmp_path):
    clock = FakeClock(0.0)
    w = telemetry.EventWriter(tmp_path, process="p0", clock=clock, host=0)
    w.note_span(("req", 1), "request")
    with w.phase("restore"):
        clock.tick(1.0)
        w.heartbeat()
    w.close()
    hb = next(e for e in telemetry.read_events(tmp_path)
              if e["kind"] == "heartbeat")
    assert hb["phase"] == "restore"


def test_fold_host_reads_request_span_dwell():
    """A wedged serving replica localizes like a wedged restore: the host
    row's phase comes from the heartbeat's span enrichment, and the dwell
    anchors on the REQUEST's start (phase_t0), not the heartbeat's ts."""
    evs = [
        {"ts": 100.0, "kind": "heartbeat", "process": "p0", "host": 0},
        {"ts": 110.0, "kind": "heartbeat", "process": "p0", "host": 0,
         "phase": "request", "phase_t0": 104.5},
    ]
    row = fleet_lib.host_table(evs)[0]
    assert row["phase"] == "request"
    assert row["phase_since_ts"] == 104.5

    # a later phase-LESS heartbeat clears the position: the request
    # completed (clear_span), and an idle replica must not read as
    # "stuck in request" with an hour-old dwell
    evs.append({"ts": 106.0, "kind": "heartbeat", "process": "p0",
                "host": 0})
    row = fleet_lib.host_table(evs)[0]
    assert row["phase"] is None


# -- serve-layer span emission (jit-bearing) ----------------------------------


def _mul_forward(params, batch):
    return {"y": batch["x"] * params["w"]}


def test_engine_emits_joined_span_tree(tmp_path):
    """Engine requests produce queue+infer spans; with an upstream trace
    context they JOIN it (no second root); without one the engine roots
    the trace itself."""
    import jax.numpy as jnp

    from distributeddeeplearningspark_tpu.serve import InferenceEngine

    eng = InferenceEngine(_mul_forward, {"w": jnp.float32(2.0)},
                          max_batch=4, max_wait_ms=2.0, max_queue=64,
                          workdir=str(tmp_path), name="mul")
    with eng:
        f1 = eng.submit({"x": np.float32(3.0)},
                        trace={"trace_id": "up1", "parent_id": "root1"})
        f2 = eng.submit({"x": np.float32(4.0)})
        assert float(f1.result(30)["y"]) == 6.0
        assert float(f2.result(30)["y"]) == 8.0
    telemetry.reset()
    evs = telemetry.read_events(tmp_path)
    trees = trace_lib.trace_trees(evs)
    joined = trees["up1"]
    # joined: stage spans only, parented under the upstream span — the
    # root lives in the router's stream (incomplete HERE by design)
    names = {n["span"]["name"] for n in joined["orphans"]}
    assert names == {"queue", "infer"}
    assert all(n["span"]["parent_id"] == "root1" for n in joined["orphans"])
    rooted = next(t for tid, t in trees.items() if tid != "up1")
    assert not rooted["incomplete"]
    assert rooted["root"]["span"]["name"] == "request"
    assert {c["span"]["name"] for c in rooted["root"]["children"]} == \
        {"queue", "infer"}
    # stage sum covers the request (the acceptance shape, engine path)
    anat = next(r for r in trace_lib.request_anatomy(evs)
                if not r["incomplete"])
    assert anat["coverage"] is not None and anat["coverage"] >= 0.95


def test_engine_error_batch_emits_error_spans(tmp_path):
    import jax.numpy as jnp

    from distributeddeeplearningspark_tpu.serve import InferenceEngine

    eng = InferenceEngine(_mul_forward, {"w": jnp.float32(1.0)},
                          max_batch=4, max_wait_ms=2.0, max_queue=64,
                          workdir=str(tmp_path), name="mul")
    with eng:
        bad = eng.submit({"y": np.float32(1.0)})   # wrong key: forward dies
        with pytest.raises(Exception):
            bad.result(30)
    telemetry.reset()
    evs = telemetry.read_events(tmp_path)
    roots = [e for e in trace_lib.spans_of(evs)
             if e["name"] == "request" and not e.get("parent_id")]
    assert len(roots) == 1
    assert roots[0]["attrs"]["outcome"] == "error"
    assert "error" in roots[0]["attrs"]


@pytest.fixture(scope="module")
def micro_llama():
    import jax
    import jax.numpy as jnp

    from distributeddeeplearningspark_tpu.models import (
        LlamaConfig,
        LlamaForCausalLM,
    )

    cfg = LlamaConfig(vocab_size=64, hidden_size=32, num_layers=2,
                      num_heads=4, num_kv_heads=2, intermediate_size=64,
                      max_position=64, dtype=jnp.float32)
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        {"input_ids": np.zeros((1, 8), np.int32)},
                        train=False)["params"]
    return cfg, params


def test_router_generator_full_causal_tree(tmp_path, micro_llama):
    """The tentpole end to end, in process: router root → place → replica
    queue/admission/prefill/decode/stream, one tree per request, stage sum
    ≥95% of the measured end-to-end latency (the acceptance bar)."""
    from distributeddeeplearningspark_tpu.serve import (
        ContinuousGenerator,
        LocalReplica,
        Router,
    )

    cfg, params = micro_llama
    telemetry.reset()
    gen = ContinuousGenerator(cfg, params, slots=2, max_cache_len=64,
                              page_size=8, workdir=str(tmp_path),
                              name="tinyllama", gauge_interval_s=0.2)
    gen.start()
    router = Router([LocalReplica("r0", gen)], workdir=str(tmp_path))
    futs = [router.submit(
        {"prompt": np.arange(1, 9, dtype=np.int32) + i, "max_new_tokens": 4},
        op="generate", tenant=f"t{i % 2}") for i in range(4)]
    for f in futs:
        f.result(timeout=120)
    gen.stop()
    router._tele.close()
    telemetry.reset()

    evs = telemetry.read_events(tmp_path)
    anat = [r for r in trace_lib.request_anatomy(evs)]
    assert len(anat) == 4
    for r in anat:
        assert not r["incomplete"], r
        assert set(r["stages"]) >= {"queue", "admission", "prefill",
                                    "decode", "stream"}, r
        assert r["coverage"] >= 0.95, r
        assert r["outcome"] == "ok" and r["tenant"] in ("t0", "t1")
    # trees root in the ROUTER's stream; stage spans carry the replica's
    trees = trace_lib.trace_trees(evs)
    tree = trees[anat[0]["trace_id"]]
    assert tree["root"]["span"]["process"] == "router"
    child_names = {c["span"]["name"] for c in tree["root"]["children"]}
    assert "place" in child_names and "decode" in child_names
    # the decode span carries the per-token timeline + first-token latency
    decode = next(c["span"] for c in tree["root"]["children"]
                  if c["span"]["name"] == "decode")
    assert decode["attrs"]["tokens"] == 4
    assert len(decode["attrs"]["token_ms"]) == 4
    assert decode["attrs"]["first_token_s"] > 0
    # prefix/admission evidence rides the admission span
    admission = next(c["span"] for c in tree["root"]["children"]
                     if c["span"]["name"] == "admission")
    assert "prefix_hit" in admission["attrs"]
    # the SLO sentinel reads the same stream
    rep = fleet_lib.slo_report(evs, target_p99_s=60.0)
    assert rep["totals"]["verdict"] == "GOOD"
    assert set(rep["tenants"]) == {"t0", "t1"}


def test_router_failover_span_and_hops(tmp_path):
    """A replica dying mid-request leaves a failover hop in the trace and
    hops=1 on the root; the rollup surfaces the count."""
    from concurrent.futures import Future

    from distributeddeeplearningspark_tpu.serve import Router
    from distributeddeeplearningspark_tpu.serve.router import (
        ReplicaDiedError,
    )

    class _Replica:
        def __init__(self, name, die=False):
            self.name = name
            self.alive = True
            self.die = die
            self.submitted = []

        def submit(self, payload, op="infer"):
            fut = Future()
            self.submitted.append((payload, fut))
            if self.die:
                fut.set_exception(ReplicaDiedError(self.name))
            return fut

    dying, healthy = _Replica("r0", die=True), _Replica("r1")
    r = Router([dying, healthy], workdir=str(tmp_path))
    fut = r.submit({"x": 1}, tenant="t0")
    # the dying replica's future failed synchronously → the router already
    # failed over; whichever replica was picked first, the request must
    # have landed on the healthy one with the SAME trace context
    assert len(healthy.submitted) == 1
    tid = healthy.submitted[0][0]["trace"]["trace_id"]
    if dying.submitted:
        assert dying.submitted[0][0]["trace"]["trace_id"] == tid
    healthy.submitted[0][1].set_result({"y": 1})
    assert fut.result(5) == {"y": 1}
    r._tele.close()

    evs = telemetry.read_events(tmp_path)
    spans = trace_lib.spans_of(evs)
    assert any(s["name"] == "failover" for s in spans)
    root = next(s for s in spans if s["name"] == "request")
    assert root["attrs"]["hops"] == 1 and root["attrs"]["outcome"] == "ok"
    # place spans: one per dispatch attempt, naming the replica
    places = [s for s in spans if s["name"] == "place"]
    assert [s["attrs"]["replica"] for s in places][-1] == "r1"
    assert r.stats()["failovers"] == 1
    # the rollup surfaces the hop count
    fs = fleet_lib.serving_fleet(evs + [
        {"ts": 1.0, "kind": "request", "process": "p0", "outcome": "ok"}])
    assert fs["totals"]["failovers"] == 1


def test_router_replica_shed_roots_outcome_shed(tmp_path):
    """A replica-side OverloadedError is the typed shed contract, not a
    failure: the root span must say outcome=shed so the tenant folds
    (serving_fleet, slo_report) account overload as capacity, not bugs."""
    from concurrent.futures import Future

    from distributeddeeplearningspark_tpu.serve import Router
    from distributeddeeplearningspark_tpu.serve.engine import OverloadedError

    class _Replica:
        name, alive = "r0", True

        def submit(self, payload, op="infer"):
            fut = Future()
            fut.set_exception(OverloadedError(4, 4))
            return fut

    r = Router([_Replica()], workdir=str(tmp_path))
    with pytest.raises(OverloadedError):
        r.submit({"x": 1}, tenant="t0").result(5)
    r._tele.close()
    evs = telemetry.read_events(tmp_path)
    root = next(s for s in trace_lib.spans_of(evs)
                if s["name"] == "request")
    assert root["attrs"]["outcome"] == "shed"
    rep = fleet_lib.slo_report(evs, target_p99_s=1.0, budget=0.5)
    assert rep["tenants"]["t0"]["shed"] == 1
    assert rep["tenants"]["t0"]["errors"] == 0


def test_generator_prefill_error_emits_error_span(tmp_path, micro_llama):
    """A poisoned prompt that dies in prefill still yields a trace: root
    outcome=error with queue + admission evidence, never an unclosed
    stream the reader chokes on."""
    from distributeddeeplearningspark_tpu.serve import ContinuousGenerator

    cfg, params = micro_llama
    telemetry.reset()
    gen = ContinuousGenerator(cfg, params, slots=2, max_cache_len=64,
                              page_size=8, workdir=str(tmp_path),
                              name="tinyllama")
    # poison AFTER submit-side validation: out-of-vocab ids crash the
    # gather inside the jitted prefill on some paths; more robustly, break
    # the prefill function itself
    gen._paged_prefill = _boom
    gen.start()
    fut = gen.submit(np.arange(1, 9, dtype=np.int32), 4)
    with pytest.raises(RuntimeError, match="boom"):
        fut.result(timeout=30)
    gen.stop()
    telemetry.reset()
    evs = telemetry.read_events(tmp_path)
    anat = trace_lib.request_anatomy(evs)
    assert len(anat) == 1
    assert anat[0]["outcome"] == "error"
    assert not anat[0]["incomplete"]  # error traces still close cleanly
    assert "queue" in anat[0]["stages"]
    # the failing prefill's elapsed time is booked as PREFILL — landing
    # it under stream/decode would send the anatomy chasing a ghost stage
    assert "prefill" in anat[0]["stages"]
    assert "stream" not in anat[0]["stages"]
    assert "decode" not in anat[0]["stages"]


def _boom(*a, **k):
    raise RuntimeError("boom")
