"""Multi-process test worker, launched by the Supervisor under jax.distributed.

Modes:
- ``train``:  MNIST-shaped training with checkpoint/resume; with
  ``--fault-step K``, process 1 SIGKILLs itself at step K on attempt 0 only
  (DLS_RESTART=0) — the fault-injection path of SURVEY.md §4.
- ``desync``: constructs an intentionally desynced replicated array and
  asserts the sanitizer catches it (and passes on a synced one).
- ``fingerprint``: runs K deterministic DP train steps over the gang's
  global mesh and (process 0) saves the post-step params to ``--out`` —
  the test compares them numerically against a single-process reference
  (VERDICT r4 next-#8: the supervisor drills prove lifecycle across the
  DCN/process boundary; this proves the NUMBERS cross it unchanged).
- ``elastic``: the kill-a-host chaos drill's gang shape for builds whose
  CPU backend cannot run cross-process collectives (the same environmental
  limit that skips the real-gang drills): rank 0 is the training host — a
  deterministic single-device run with checkpoint/resume and telemetry —
  and every other rank is a stand-in *host agent* that heartbeats, honors
  ``DLS_FAULT=die_host@N`` (dies when the step-N checkpoint lands; stays
  dead on relaunches), and exits cleanly when training completes. The
  supervisor cannot tell the difference: gang launch, death detection,
  shrink-to-survive, and restore-from-checkpoint all run the real code.
"""

import argparse
import os
import signal
import sys

# Launched as a bare script (sys.path[0] = tests/workers), so the package
# under test must be made importable regardless of cwd/PYTHONPATH.
sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..", "..")))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def build_session():
    from distributeddeeplearningspark_tpu import Session

    # DLS_COORDINATOR/DLS_NUM_PROCESSES/DLS_PROCESS_ID come from the
    # supervisor; Session auto-runs jax.distributed.initialize from them.
    return Session.builder.master("auto").appName("worker").getOrCreate()


def mode_train(args) -> int:
    import optax

    from distributeddeeplearningspark_tpu import Checkpointer, PartitionedDataset, Trainer
    from distributeddeeplearningspark_tpu import faults
    from distributeddeeplearningspark_tpu.models import LeNet5
    from distributeddeeplearningspark_tpu.train import losses

    faults.die_if_dead_host_on_relaunch()  # pre-rendezvous, so the gang
    # fails by fast exit detection, not by blocking in jax.distributed
    spark = build_session()
    rng = np.random.default_rng(0)
    examples = [
        {"image": rng.normal(0, 1, (28, 28, 1)).astype(np.float32),
         "label": np.int32(i % 10)}
        for i in range(256)
    ]
    ds = PartitionedDataset.parallelize(examples, spark.default_parallelism).repeat()

    ckpt = Checkpointer(args.ckpt_dir)
    trainer = Trainer(spark, LeNet5(), losses.softmax_xent,
                      optax.sgd(0.05, momentum=0.9), checkpointer=ckpt, seed=5)

    data_state = None
    if ckpt.latest_step() is not None:
        trainer.init(trainer._sample_batch(ds, args.batch_size))
        try:
            _, data_state = trainer.restore()
        except Exception:
            # the supervisor contract: dying AT restore is a different
            # failure class than dying mid-training — relaunching against
            # the same checkpoint would crash identically, so say so
            from distributeddeeplearningspark_tpu.supervisor import (
                RESTORE_FAILED_EXIT)

            import traceback

            traceback.print_exc()
            return RESTORE_FAILED_EXIT

    attempt = int(os.environ.get("DLS_RESTART", "0"))
    fault_cbs = []
    if args.fault_step and attempt == 0 and jax.process_index() == 1:
        def die(step, _metrics):
            if step >= args.fault_step:
                os.kill(os.getpid(), signal.SIGKILL)
        fault_cbs.append(die)

    state, _ = trainer.fit(
        ds, batch_size=args.batch_size, steps=args.steps, log_every=5,
        checkpoint_every=args.checkpoint_every, data_state=data_state,
        sanitize_every=5, callbacks=fault_cbs,
        on_nonfinite=args.on_nonfinite,
    )
    ckpt.wait()
    final_step = int(jax.device_get(state.step))
    if jax.process_index() == 0:
        with open(os.path.join(args.ckpt_dir, "DONE"), "w") as f:
            f.write(f"{final_step} {attempt}\n")
    return 0 if final_step >= args.steps else 4


def _latest_step(directory: str) -> int | None:
    """checkpoint.latest_step_in without the jax import — the host agent
    must stay a sub-second process (its whole job is dying on time)."""
    try:
        steps = [int(d) for d in os.listdir(directory)
                 if d.isdigit() and os.path.isdir(os.path.join(directory, d))]
    except OSError:
        return None
    return max(steps) if steps else None


def host_agent(args) -> int:
    """A stand-in surviving/dying pod host (ranks > 0 of ``elastic`` mode).

    No jax: it stamps the supervisor's heartbeat file, applies the
    ``die_host`` discipline (die at the step-N checkpoint boundary on
    attempt 0; die at startup on every later attempt — a dead machine
    stays dead), exits 0 when the trainer's DRAIN evidence appears (a
    graceful ``sigterm`` preemption ends the WHOLE gang cleanly — the
    doomed host's "death" is this clean exit), and exits 0 once rank 0's
    DONE marker appears."""
    import time

    from distributeddeeplearningspark_tpu import faults

    faults.die_if_dead_host_on_relaunch()
    fault = faults.get()  # already host-gated for die_host
    hb = os.environ.get("DLS_HEARTBEAT_FILE")
    deadline = time.monotonic() + 600.0
    while time.monotonic() < deadline:
        if hb:
            try:
                with open(hb, "w") as f:
                    f.write(str(os.getpid()))
            except OSError:
                pass
        if fault is not None and fault.kind == "die_host":
            latest = _latest_step(args.ckpt_dir)
            if latest is not None and latest >= fault.step:
                faults.crash()
        if os.path.exists(os.path.join(args.ckpt_dir, "DRAIN")):
            return 0  # graceful preemption: whole gang exits clean
        if os.path.exists(os.path.join(args.ckpt_dir, "DONE")):
            return 0
        time.sleep(0.1)
    return 5  # training host never finished nor died — drill misconfigured


def mode_elastic(args) -> int:
    """Rank 0: deterministic single-device training with checkpoint/resume
    (a fixed 2-partition stream, so the batch sequence is identical at any
    gang width); ranks > 0: :func:`host_agent`."""
    if int(os.environ.get("DLS_PROCESS_ID", "0") or 0) != 0:
        return host_agent(args)
    gang_width = os.environ.get("DLS_NUM_PROCESSES", "1")
    # solo trainer: do NOT auto-join the pod (Session would rendezvous with
    # stand-in agents that never initialize jax.distributed)
    os.environ.pop("DLS_COORDINATOR", None)
    import optax

    from distributeddeeplearningspark_tpu import (
        Checkpointer,
        PartitionedDataset,
        Session,
        Trainer,
    )
    from distributeddeeplearningspark_tpu.models import LeNet5
    from distributeddeeplearningspark_tpu.train import losses

    spark = Session.builder.master("local[1]").appName("elastic").getOrCreate()
    rng = np.random.default_rng(0)
    examples = [
        {"image": rng.normal(0, 1, (28, 28, 1)).astype(np.float32),
         "label": np.int32(i % 10)}
        for i in range(256)
    ]
    ds = PartitionedDataset.parallelize(examples, 2).repeat()
    ckpt = Checkpointer(args.ckpt_dir)
    trainer = Trainer(spark, LeNet5(), losses.softmax_xent,
                      optax.sgd(0.05, momentum=0.9), checkpointer=ckpt, seed=5)
    data_state = None
    restored = False
    from distributeddeeplearningspark_tpu.parallel import live_reshard

    if live_reshard.has_handoff(args.ckpt_dir):
        # graceful-preemption resume: ingest the drained gang's live
        # handoff and continue from the CURRENT step — no walk-back
        trainer.init(trainer._sample_batch(ds, args.batch_size))
        try:
            _, data_state = trainer.restore_live_handoff()
            restored = True
        except live_reshard.HandoffError:
            import traceback

            traceback.print_exc()
            # torn/mismatched handoff: consume it and walk back through
            # the checkpoint like any hard failure
            live_reshard.clear_handoff(args.ckpt_dir)
    if not restored and ckpt.latest_step() is not None:
        if trainer.state is None:
            trainer.init(trainer._sample_batch(ds, args.batch_size))
        try:
            _, data_state = trainer.restore()
        except Exception:
            from distributeddeeplearningspark_tpu.supervisor import (
                RESTORE_FAILED_EXIT)

            import traceback

            traceback.print_exc()
            return RESTORE_FAILED_EXIT
    attempt = int(os.environ.get("DLS_RESTART", "0") or 0)
    state, _ = trainer.fit(
        ds, batch_size=args.batch_size, steps=args.steps, log_every=2,
        checkpoint_every=args.checkpoint_every, data_state=data_state,
    )
    if trainer.preempted_at is not None:
        # drained gracefully: the live handoff + DRAIN evidence are the
        # exit artifacts — no DONE, no final checkpoint; the supervisor
        # shrinks and relaunches from the current step
        return 0
    ckpt.wait()
    final_step = int(jax.device_get(state.step))
    with open(os.path.join(args.ckpt_dir, "DONE"), "w") as f:
        f.write(f"{final_step} {attempt} {gang_width}\n")
    return 0 if final_step >= args.steps else 4


def mode_desync(args) -> int:
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributeddeeplearningspark_tpu.utils.sanitize import (
        DesyncError,
        assert_replicas_in_sync,
    )

    spark = build_session()
    mesh = spark.mesh
    rep = NamedSharding(mesh, P())
    ones = np.ones((16,), np.float32)

    synced = jax.make_array_from_process_local_data(rep, ones)
    assert_replicas_in_sync({"w": synced})  # must pass

    skewed = jax.make_array_from_process_local_data(
        rep, ones * (1.0 + 0.25 * jax.process_index())
    )
    try:
        assert_replicas_in_sync({"w": skewed})
    except DesyncError:
        return 0
    return 3  # sanitizer missed the desync


def fingerprint_reference(steps: int, batch_size: int, mesh) -> dict:
    """The deterministic DP training recipe shared by the gang worker and
    the in-test single-process reference — ONE definition, so the
    fingerprint can only diverge through the process boundary, never
    through drifting test code. Returns the post-step params as numpy.
    """
    import optax

    from distributeddeeplearningspark_tpu.data.feed import put_global
    from distributeddeeplearningspark_tpu.models import LeNet5
    from distributeddeeplearningspark_tpu.parallel.mesh import num_data_shards
    from distributeddeeplearningspark_tpu.parallel.sharding import REPLICATED
    from distributeddeeplearningspark_tpu.train import losses
    from distributeddeeplearningspark_tpu.train import step as step_lib

    def global_batch(step: int) -> dict:
        rng = np.random.default_rng(1000 + step)
        return {
            "image": rng.normal(0, 1, (batch_size, 28, 28, 1)).astype(np.float32),
            "label": rng.integers(0, 10, (batch_size,)).astype(np.int32),
        }

    def local_rows(gb: dict) -> dict:
        # put_global (multi-process) wants each process's OWN rows; the
        # shard-range math (process-major device order) lives in feed.py —
        # derive from it rather than duplicating the invariant here
        from distributeddeeplearningspark_tpu.data.feed import (
            process_shard_range)

        nshards = num_data_shards(mesh)
        rng_ = process_shard_range(nshards)
        if rng_ is None:
            return gb
        rows_per_shard = batch_size // nshards
        lo, hi = rng_[0] * rows_per_shard, rng_[1] * rows_per_shard
        return {k: v[lo:hi] for k, v in gb.items()}

    assert batch_size % num_data_shards(mesh) == 0
    model = LeNet5()
    tx = optax.sgd(0.05, momentum=0.9)
    state, shardings = step_lib.init_state(
        model, tx, local_rows(global_batch(0)), mesh, REPLICATED, seed=5)
    train = step_lib.jit_train_step(
        step_lib.make_train_step(model.apply, tx, losses.softmax_xent),
        mesh, shardings)
    for k in range(steps):
        state, _ = train(state, put_global(local_rows(global_batch(k)), mesh))
    return {
        "/".join(str(getattr(p, "key", p)) for p in path): np.asarray(
            jax.device_get(x))
        for path, x in jax.tree_util.tree_flatten_with_path(state.params)[0]
    }


def mode_fingerprint(args) -> int:
    spark = build_session()
    params = fingerprint_reference(args.steps, args.batch_size, spark.mesh)
    if jax.process_index() == 0:
        np.savez(args.out, **params)
    return 0


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("mode", choices=["train", "desync", "fingerprint",
                                    "elastic"])
    p.add_argument("--ckpt-dir", default="/tmp/worker_ck")
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--checkpoint-every", type=int, default=10)
    p.add_argument("--fault-step", type=int, default=0)
    p.add_argument("--on-nonfinite", default="raise",
                   choices=["raise", "skip", "rollback"])
    p.add_argument("--out", default="/tmp/fingerprint.npz")
    args = p.parse_args()
    if args.mode == "fingerprint":
        return mode_fingerprint(args)
    if args.mode == "elastic":
        return mode_elastic(args)
    return mode_train(args) if args.mode == "train" else mode_desync(args)


if __name__ == "__main__":
    sys.exit(main())
