"""Chaos drills — deterministic fault injection through the full recovery
chain (faults.py → checkpoint manifests → supervisor classification →
trainer divergence policies).

Each drill is the end-to-end shape of one production failure mode:

- **kill-mid-checkpoint-finalize** (``DLS_FAULT=truncate_ckpt@N``): the
  latest step is torn after its manifest committed; the relaunch must walk
  back to the newest *verified* step, quarantine the torn one, and finish.
- **restore-poisoned checkpoint**: a step that verifies byte-for-byte but
  crashes restore (sentinel exit 13); the supervisor must quarantine it and
  fall back instead of burning every restart on it.
- **hang** (``DLS_FAULT=hang@N``): progress stops without an exit; the
  watchdog must kill, classify, and relaunch to completion.
- **NaN spike** (``DLS_FAULT=nan@N``): ``fit(on_nonfinite=...)`` must
  contain the divergence (skip) or rewind past it (rollback).

Run via ``bash tools/ci.sh chaos`` (appends its own SUITE_LOG.md line).
"""

import os
import re
import sys

import numpy as np
import pytest

from distributeddeeplearningspark_tpu import faults, status, telemetry
from distributeddeeplearningspark_tpu.supervisor import (
    RESTORE_FAILED_EXIT,
    Supervisor,
)

WORKER = os.path.join(os.path.dirname(__file__), "workers", "worker.py")

# Workers are single-device gang members; they must not inherit the test
# process's 8-fake-device XLA_FLAGS (same contract as test_supervisor.py).
_CLEAN_ENV = {"XLA_FLAGS": "", "JAX_PLATFORMS": "cpu"}


def _corrupt_dirs(path):
    return [d for d in os.listdir(path) if re.match(r"\d+\.corrupt-\d+$", d)]


def _attempt_ends(workdir):
    """{ordinal: classification} from the run's attempt telemetry — every
    drill asserts its fault left the matching audit record behind."""
    return {e["ordinal"]: e["classification"]
            for e in telemetry.read_events(workdir)
            if e["kind"] == "attempt" and e.get("edge") == "end"}


def _recovery_events(workdir):
    return [e for e in telemetry.read_events(workdir)
            if e["kind"] == "recovery"]


# -- fault spec parsing (fast tier: no gangs) --------------------------------


def test_fault_parse():
    f = faults.parse("truncate_ckpt@20")
    assert (f.kind, f.step) == ("truncate_ckpt", 20)
    for bad in ("nan", "nan@", "nan@x", "frobnicate@3", "crash@0"):
        with pytest.raises(ValueError):
            faults.parse(bad)


def test_fault_gating(monkeypatch):
    monkeypatch.setenv("DLS_FAULT", "nan@3")
    monkeypatch.delenv("DLS_RESTART", raising=False)
    assert faults.get() == faults.Fault("nan", 3)
    monkeypatch.setenv("DLS_RESTART", "1")  # relaunch attempts run clean
    assert faults.get() is None
    monkeypatch.setenv("DLS_FAULT_ALL_ATTEMPTS", "1")
    assert faults.get() == faults.Fault("nan", 3)
    monkeypatch.delenv("DLS_FAULT")
    assert faults.get() is None


def test_die_host_fault_gating(monkeypatch):
    """die_host targets by stable host identity, persists across attempts
    by default (a dead machine stays dead), and validates its env knobs
    with the same loud ladder as the spec itself."""
    monkeypatch.setenv("DLS_FAULT", "die_host@7")
    monkeypatch.setenv("DLS_PROCESS_ID", "1")
    monkeypatch.delenv("DLS_RESTART", raising=False)
    monkeypatch.delenv("DLS_HOST_ID", raising=False)
    monkeypatch.delenv("DLS_FAULT_HOST", raising=False)
    assert faults.get() == faults.Fault("die_host", 7)
    # persists across attempts (unlike crash's first-attempt-only rule) …
    monkeypatch.setenv("DLS_RESTART", "2")
    assert faults.get() == faults.Fault("die_host", 7)
    # … unless the drill opts back into one-shot
    monkeypatch.setenv("DLS_FAULT_ONCE", "1")
    assert faults.get() is None
    monkeypatch.delenv("DLS_FAULT_ONCE")
    # DLS_HOST_ID (stable across elastic renumbering) wins over the rank
    monkeypatch.setenv("DLS_PROCESS_ID", "0")
    monkeypatch.setenv("DLS_HOST_ID", "1")
    assert faults.get() == faults.Fault("die_host", 7)
    # surviving hosts run clean
    monkeypatch.setenv("DLS_HOST_ID", "0")
    assert faults.get() is None
    # validation ladder: bad host env and 0-step specs fail loudly
    monkeypatch.setenv("DLS_HOST_ID", "1")
    monkeypatch.setenv("DLS_FAULT_HOST", "frobnicate")
    with pytest.raises(ValueError, match="DLS_FAULT_HOST"):
        faults.get()
    monkeypatch.setenv("DLS_FAULT_HOST", "-1")
    with pytest.raises(ValueError, match=">= 0"):
        faults.get()
    for bad in ("die_host@0", "die_host@", "die_host@x"):
        with pytest.raises(ValueError):
            faults.parse(bad)


def test_sigterm_fault_scoping(monkeypatch):
    """sigterm is a preemption NOTICE, not a crash: faults.get() never
    returns it (non-trainer callers must not mistake it for a kill), the
    trainer's scoped accessor does — on attempt 0 only, with the doomed
    host's env knob validated eagerly."""
    monkeypatch.setenv("DLS_FAULT", "sigterm@9")
    monkeypatch.delenv("DLS_RESTART", raising=False)
    monkeypatch.delenv("DLS_FAULT_HOST", raising=False)
    monkeypatch.delenv("DLS_FAULT_ALL_ATTEMPTS", raising=False)
    assert faults.get() is None
    assert faults.sigterm_fault() == faults.Fault("sigterm", 9)
    # the shrunk relaunch must run clean …
    monkeypatch.setenv("DLS_RESTART", "1")
    assert faults.sigterm_fault() is None
    # … unless the drill opts into give-up testing
    monkeypatch.setenv("DLS_FAULT_ALL_ATTEMPTS", "1")
    assert faults.sigterm_fault() == faults.Fault("sigterm", 9)
    monkeypatch.delenv("DLS_FAULT_ALL_ATTEMPTS")
    monkeypatch.delenv("DLS_RESTART")
    # other kinds don't leak through the scoped accessor
    monkeypatch.setenv("DLS_FAULT", "crash@3")
    assert faults.sigterm_fault() is None
    # a typo'd doomed-host knob fails loudly at consult time
    monkeypatch.setenv("DLS_FAULT", "sigterm@9")
    monkeypatch.setenv("DLS_FAULT_HOST", "frobnicate")
    with pytest.raises(ValueError, match="DLS_FAULT_HOST"):
        faults.sigterm_fault()
    for bad in ("sigterm@0", "sigterm@", "sigterm@x"):
        with pytest.raises(ValueError):
            faults.parse(bad)


def test_drain_evidence_roundtrip_and_classification(tmp_path):
    """The DRAIN evidence file protocol: written atomically, read back as
    (host, step), consumed to a forensic rename — and it overrides BOTH the
    all-zero "clean" read and the non-zero "training-crash" read in the
    supervisor's classifier (a drain is a handoff, not a completion)."""
    import sys as _sys

    from distributeddeeplearningspark_tpu import supervisor as sup_lib

    assert sup_lib.read_drain_evidence(tmp_path) is None
    sup_lib.write_drain_evidence(tmp_path, host=1, step=9)
    assert sup_lib.read_drain_evidence(tmp_path) == (1, 9)

    sup = Supervisor([_sys.executable, "-c", "pass"], num_processes=2,
                     ckpt_dir=str(tmp_path))
    # all-zero exits would otherwise read "clean" and END the run
    assert sup._classify([0, 0], ordinal=0, hang=False,
                         made_progress=True) == "graceful-shutdown"
    # a drain raced by the kill path must not burn a backoff slot either
    assert sup._classify([0, -15], ordinal=0, hang=False,
                         made_progress=True) == "graceful-shutdown"
    attempt = sup_lib.Attempt(ordinal=0, returncodes=[0, 0], duration_s=1.0,
                              classification="graceful-shutdown")
    assert not attempt.ok  # a handoff is not a completion

    sup_lib.consume_drain_evidence(tmp_path, ordinal=0)
    assert sup_lib.read_drain_evidence(tmp_path) is None
    assert os.path.exists(tmp_path / "DRAIN.consumed-0")
    assert sup._classify([0, 0], ordinal=0, hang=False,
                         made_progress=True) == "clean"


# -- drill 1: SIGKILL mid-checkpoint-finalize --------------------------------


@pytest.mark.slow
def test_kill_mid_finalize_recovers_from_verified_step(tmp_path):
    """THE acceptance drill: a worker dies mid-checkpoint-finalize leaving a
    partial latest step (torn bytes, manifest already committed); the
    supervised relaunch restores from the newest VERIFIED earlier step —
    quarantining the torn one — and completes within max_restarts."""
    sup = Supervisor(
        [sys.executable, WORKER, "train", "--ckpt-dir", str(tmp_path),
         "--steps", "30", "--checkpoint-every", "10"],
        num_processes=1, max_restarts=2, restart_backoff_s=0.05,
        env={**_CLEAN_ENV, "DLS_FAULT": "truncate_ckpt@20"},
        progress_path=str(tmp_path),
    )
    result = sup.run()
    assert result.ok, f"attempts: {[(a.ordinal, a.returncodes, a.classification) for a in result.attempts]}"
    assert result.restarts == 1
    # attempt 0 died by SIGKILL right after tearing step 20
    assert -9 in result.attempts[0].returncodes
    step, attempt = open(tmp_path / "DONE").read().split()
    assert int(step) == 30 and int(attempt) == 1
    # the torn step 20 was quarantined, not retried and not GC-counted
    quarantined = _corrupt_dirs(tmp_path)
    assert any(d.startswith("20.corrupt-") for d in quarantined), (
        quarantined, sorted(os.listdir(tmp_path)))
    # training continued past the tear on the relaunch: step 30 committed
    assert os.path.isdir(tmp_path / "30")
    # the audit trail survived the SIGKILL: attempt lifecycle + the restart
    # decision + the relaunch's quarantine of the torn step are all on disk
    ends = _attempt_ends(tmp_path)
    assert ends[0] == "training-crash" and ends[1] == "clean", ends
    recov = _recovery_events(tmp_path)
    assert any(e["event"] == "restart"
               and e["classification"] == "training-crash" for e in recov)
    assert any(e["event"] == "quarantine" and e["step"] == 20
               for e in recov), recov


# -- drill 2: verified-but-poisoned restore → supervisor fallback ------------


def test_restore_failure_falls_back_to_previous_step(tmp_path):
    """A checkpoint whose BYTES verify but whose restore crashes (sentinel
    exit 13) must not burn max_restarts: the supervisor quarantines the
    latest step and the relaunch succeeds on the previous one. Workers are
    plain python (no jax) so this drill stays in the fast tier."""
    (tmp_path / "10").mkdir()
    (tmp_path / "10" / "ok").write_text("good step")
    (tmp_path / "20").mkdir()
    (tmp_path / "20" / "ok").write_text("poisoned step")
    script = (
        "import os, sys\n"
        "root = sys.argv[1]\n"
        "steps = sorted(int(d) for d in os.listdir(root) if d.isdigit())\n"
        f"if steps[-1] == 20: sys.exit({RESTORE_FAILED_EXIT})\n"
        "open(os.path.join(root, 'DONE'), 'w').write(str(steps[-1]))\n"
    )
    sup = Supervisor(
        [sys.executable, "-c", script, str(tmp_path)],
        num_processes=1, max_restarts=2, restart_backoff_s=0.01,
        backoff_jitter=0.0, ckpt_dir=str(tmp_path),
    )
    result = sup.run()
    assert result.ok, [(a.returncodes, a.classification) for a in result.attempts]
    assert result.restarts == 1
    assert result.attempts[0].classification == "restore-failure"
    assert result.attempts[1].classification == "clean"
    assert _corrupt_dirs(tmp_path) == ["20.corrupt-0"]
    assert open(tmp_path / "DONE").read() == "10"
    # supervisor telemetry: the classification and the destructive fallback
    # are auditable from the run dir alone
    ends = _attempt_ends(tmp_path)
    assert ends == {0: "restore-failure", 1: "clean"}, ends
    assert any(e["event"] == "restore-fallback" and e["step"] == 20
               for e in _recovery_events(tmp_path))


def test_restore_failure_without_fallback_burns_restarts(tmp_path):
    """Control for the drill above: fallback disabled → every attempt dies
    on the same poisoned step (the pre-PR behavior the ISSUE describes)."""
    (tmp_path / "20").mkdir()
    script = f"import sys; sys.exit({RESTORE_FAILED_EXIT})\n"
    sup = Supervisor(
        [sys.executable, "-c", script],
        num_processes=1, max_restarts=2, restart_backoff_s=0.01,
        backoff_jitter=0.0, ckpt_dir=str(tmp_path),
        fallback_on_restore_failure=False,
    )
    result = sup.run()
    assert not result.ok
    assert [a.classification for a in result.attempts] == ["restore-failure"] * 3
    assert _corrupt_dirs(tmp_path) == []
    assert _attempt_ends(tmp_path) == {i: "restore-failure" for i in range(3)}


# -- drill 3: hang -----------------------------------------------------------


@pytest.mark.slow
def test_hang_is_killed_classified_and_relaunched(tmp_path):
    """DLS_FAULT=hang@8: attempt 0 stops progressing mid-run; the watchdog
    kills it, the attempt is classified 'hang', and the relaunch (fault
    disarmed by DLS_RESTART=1) resumes from the step-5 checkpoint."""
    sup = Supervisor(
        [sys.executable, WORKER, "train", "--ckpt-dir", str(tmp_path),
         "--steps", "15", "--checkpoint-every", "5"],
        num_processes=1, max_restarts=2, restart_backoff_s=0.05,
        env={**_CLEAN_ENV, "DLS_FAULT": "hang@8"},
        hang_timeout_s=8.0, startup_grace_s=240.0,
        progress_path=str(tmp_path),
    )
    result = sup.run()
    assert result.ok, f"attempts: {[(a.ordinal, a.returncodes, a.classification) for a in result.attempts]}"
    assert result.restarts == 1
    assert result.attempts[0].classification == "hang"
    step, attempt = open(tmp_path / "DONE").read().split()
    assert int(step) == 15 and int(attempt) == 1
    # the hang classification is in the durable attempt timeline
    ends = _attempt_ends(tmp_path)
    assert ends[0] == "hang" and ends[1] == "clean", ends


# -- drill 3b: crash + dlstatus — the run is explainable from its dir alone --


@pytest.mark.slow
def test_crash_drill_dlstatus_reports_attempts_and_goodput(tmp_path):
    """ISSUE 2 acceptance: after a supervised DLS_FAULT=crash run,
    ``dlstatus <workdir>`` reports the attempt timeline, the recovery
    event, and a goodput breakdown whose components sum to wall-clock
    within 5% — and exits 0."""
    sup = Supervisor(
        [sys.executable, WORKER, "train", "--ckpt-dir", str(tmp_path),
         "--steps", "20", "--checkpoint-every", "5"],
        num_processes=1, max_restarts=2, restart_backoff_s=0.05,
        env={**_CLEAN_ENV, "DLS_FAULT": "crash@12"},
        progress_path=str(tmp_path),
    )
    result = sup.run()
    assert result.ok, f"attempts: {[(a.ordinal, a.returncodes, a.classification) for a in result.attempts]}"
    assert result.restarts == 1

    rep = status.report(str(tmp_path))
    # attempt timeline: the crash and the clean relaunch, with durations
    assert [a["ordinal"] for a in rep["attempts"]] == [0, 1]
    assert rep["attempts"][0]["classification"] == "training-crash"
    assert -9 in rep["attempts"][0]["returncodes"]
    assert rep["attempts"][1]["classification"] == "clean"
    assert all(a["duration_s"] > 0 for a in rep["attempts"])
    # the recovery event tying the fault to the restart decision
    assert any(e["event"] == "restart"
               and e["classification"] == "training-crash"
               for e in rep["recovery_events"]), rep["recovery_events"]
    # both attempts' trainer streams merged: laps from before AND after
    steps_seen = [e["step"] for e in telemetry.read_events(str(tmp_path))
                  if e["kind"] == "step_metrics"]
    assert any(s <= 10 for s in steps_seen) and 20 in steps_seen, steps_seen
    # goodput breakdown: components sum to wall-clock within 5%
    g = rep["goodput"]
    assert g["wall_s"] > 0 and g["goodput_frac"] > 0
    assert g["compile_s"] > 0          # both attempts jit-compiled
    assert g["restart_overhead_s"] > 0  # the backoff + teardown gap
    total = sum(g[k] for k in telemetry.GOODPUT_COMPONENTS)
    assert total == pytest.approx(g["wall_s"], rel=0.05), (total, g)
    # the CLI renders the same report and exits 0
    assert status.main([str(tmp_path)]) == 0


# -- drill 5: kill-a-host — elastic shrink-to-survive ------------------------


def _geometry_changes(workdir):
    return [e for e in _recovery_events(workdir)
            if e["event"] == "geometry_change"]


def _losses_by_step(workdir, *, after_ts=None):
    out = {}
    for e in telemetry.read_events(workdir):
        if e.get("kind") != "step_metrics":
            continue
        if after_ts is not None and float(e["ts"]) <= after_ts:
            continue
        loss = (e.get("metrics") or {}).get("loss")
        if loss is not None:
            out[int(e["step"])] = float(loss)
    return out


@pytest.mark.slow
def test_die_host_shrinks_gang_and_training_continues(tmp_path):
    """THE elastic acceptance drill: DLS_FAULT=die_host@12 kills host 1 of a
    2-host gang mid-run and keeps it dead across attempts. After 2
    consecutive failures blaming the same host, the supervisor re-plans the
    gang onto the surviving host (shrink-to-survive), relaunches from the
    last verified checkpoint, and training runs to completion on 1 host —
    with a loss trajectory matching a clean 1-host run restored from the
    same step, and the shrink recorded as a first-class geometry_change
    event that ``dlstatus`` renders.

    (On builds whose CPU backend cannot run cross-process collectives the
    gang uses the worker's ``elastic`` mode — rank 0 trains, rank 1 is a
    stand-in host agent; the supervisor machinery under test is identical.
    The real-gang variant below additionally proves the resharded restore
    when multiprocess collectives exist.)"""
    import shutil

    wd = tmp_path / "run"
    wd.mkdir()
    sup = Supervisor(
        [sys.executable, WORKER, "elastic", "--ckpt-dir", str(wd),
         "--steps", "24", "--checkpoint-every", "6"],
        num_processes=2, max_restarts=4, restart_backoff_s=0.05,
        backoff_jitter=0.0, shrink_after=2,
        env={**_CLEAN_ENV, "DLS_FAULT": "die_host@12"},
        progress_path=str(wd),
    )
    result = sup.run()
    assert result.ok, (
        f"attempts: {[(a.ordinal, a.returncodes, a.classification) for a in result.attempts]}")
    # attempt 0: host 1 died at the step-12 checkpoint; attempt 1: host 1
    # died at startup (a dead host stays dead); attempt 2: 1-host gang
    assert result.restarts == 2
    assert [a.num_processes for a in result.attempts] == [2, 2, 1]
    assert result.attempts[0].dead_host == 1
    assert result.attempts[1].dead_host == 1
    step, attempt, nprocs = open(wd / "DONE").read().split()
    assert (int(step), int(attempt), int(nprocs)) == (24, 2, 1)

    # the shrink is a first-class durable event naming evidence and action
    geo = _geometry_changes(wd)
    assert len(geo) == 1, geo
    assert geo[0]["dead_host"] == 1
    assert geo[0]["from_processes"] == 2 and geo[0]["to_processes"] == 1
    assert geo[0]["hosts"] == [0]
    assert geo[0]["batch_policy"] == "preserve_global"
    assert geo[0]["evidence_attempts"] == 2

    # dlstatus explains the whole incident from the run dir alone
    rep = status.report(str(wd))
    assert any(e["event"] == "geometry_change"
               for e in rep["recovery_events"])
    nps = [a.get("num_processes") for a in rep["attempts"]]
    assert nps == [2, 2, 1], nps
    rendered = status.render(rep)
    assert "geometry" in rendered and "np=1" in rendered, rendered

    # loss trajectory: the post-shrink attempt must match a CLEAN 1-host run
    # restored from the same checkpoint step, batch for batch
    events = telemetry.read_events(wd)
    restores = [e for e in events
                if e.get("kind") == "phase" and e.get("name") == "restore"
                and e.get("edge") == "end"]
    assert restores, "the shrunk relaunch never restored a checkpoint"
    resume_step = int(restores[-1]["step"])
    geo_ts = float(next(e["ts"] for e in events
                        if e.get("kind") == "recovery"
                        and e.get("event") == "geometry_change"))
    drill_losses = _losses_by_step(wd, after_ts=geo_ts)
    assert max(drill_losses) == 24 and min(drill_losses) > resume_step

    clean = tmp_path / "clean"
    clean.mkdir()
    for d in os.listdir(wd):
        if d.isdigit() and int(d) <= resume_step:
            shutil.copytree(wd / d, clean / d)
    sup2 = Supervisor(
        [sys.executable, WORKER, "elastic", "--ckpt-dir", str(clean),
         "--steps", "24", "--checkpoint-every", "6"],
        num_processes=1, max_restarts=0, env=_CLEAN_ENV,
        progress_path=str(clean),
    )
    assert sup2.run().ok
    clean_losses = _losses_by_step(clean)
    common = sorted(set(drill_losses) & set(clean_losses))
    assert common and common[-1] == 24, (drill_losses, clean_losses)
    for s in common:
        assert drill_losses[s] == pytest.approx(clean_losses[s], rel=1e-6), (
            s, drill_losses[s], clean_losses[s])


@pytest.mark.slow
def test_sigterm_drains_and_continues_from_current_step(tmp_path):
    """THE graceful-preemption drill (ISSUE 16): DLS_FAULT=sigterm@9 is a
    preemption NOTICE for host 1 of a 2-host gang. The doomed rank drains
    its in-flight step, the state is re-gathered live and handed off, the
    gang exits clean — and the supervisor classifies it graceful-shutdown
    (not training-crash), shrinks IMMEDIATELY (no repeat-evidence wait, no
    backoff slot), and the relaunch continues from the CURRENT step via
    the handoff: checkpoint-free, no walk-back, loss trajectory matching
    an unfaulted run. (die_host keeps its checkpoint walk-back — the drill
    above.)"""
    wd = tmp_path / "run"
    wd.mkdir()
    sup = Supervisor(
        [sys.executable, WORKER, "elastic", "--ckpt-dir", str(wd),
         "--steps", "18", "--checkpoint-every", "6"],
        num_processes=2, max_restarts=4, restart_backoff_s=0.05,
        backoff_jitter=0.0, shrink_after=2,
        env={**_CLEAN_ENV, "DLS_FAULT": "sigterm@9"},
        progress_path=str(wd),
    )
    result = sup.run()
    assert result.ok, (
        f"attempts: {[(a.ordinal, a.returncodes, a.classification) for a in result.attempts]}")
    # ONE drain, ONE relaunch — no dead-host repeat evidence needed
    assert result.restarts == 1
    assert [a.num_processes for a in result.attempts] == [2, 1]
    assert result.attempts[0].classification == "graceful-shutdown"
    assert result.attempts[0].returncodes == [0, 0]
    step, attempt, nprocs = open(wd / "DONE").read().split()
    assert (int(step), int(attempt), int(nprocs)) == (18, 1, 1)
    # the evidence file was consumed to its forensic rename
    assert not os.path.exists(wd / "DRAIN")
    assert os.path.exists(wd / "DRAIN.consumed-0")

    events = telemetry.read_events(wd)
    # first-class graceful_shutdown event at the drained step
    gs = [e for e in events if e.get("kind") == "recovery"
          and e.get("event") == "graceful_shutdown"]
    assert len(gs) == 1
    assert gs[0]["step"] == 9 and gs[0]["dead_host"] == 1
    assert gs[0]["drained"] is True
    # the shrink resumed from the DRAIN step via the live handoff —
    # not from a checkpoint walk-back
    geo = _geometry_changes(wd)
    assert len(geo) == 1, geo
    assert geo[0]["resume"] == "live-handoff"
    assert geo[0]["step"] == 9
    assert geo[0]["dead_host"] == 1
    assert geo[0]["from_processes"] == 2 and geo[0]["to_processes"] == 1
    # reshard telemetry: the drain's live re-gather + the relaunch's
    # handoff ingest; NOTHING walked back through a checkpoint
    rs = [e for e in events if e.get("kind") == "recovery"
          and e.get("event") == "reshard"]
    assert any(e["transport"] == "collectives"
               and e.get("reason") == "preemption-drain" for e in rs), rs
    assert any(e["transport"] == "handoff"
               and e.get("reason") == "preemption-resume" for e in rs), rs
    assert not any(e.get("walk_back") for e in rs), rs
    # no step ran twice: drain at 9, resume at 10 — checkpoint-free
    seen = [int(e["step"]) for e in events
            if e.get("kind") == "step_metrics"]
    assert len(seen) == len(set(seen)), sorted(seen)
    # no backoff slot burned on the graceful path
    assert not any(e.get("kind") == "attempt" and e.get("edge") == "backoff"
                   for e in events)

    # dlstatus explains the incident: graceful line, reshard block, np 2->1
    rep = status.report(str(wd))
    assert rep["reshard"]["live_moves"] >= 2
    assert rep["reshard"]["walk_back_moves"] == 0
    rendered = status.render(rep)
    assert "graceful shutdown: host 1" in rendered, rendered
    assert "checkpoint-free (live)" in rendered, rendered

    # loss trajectory: the whole drill run must match an unfaulted 1-host
    # run step for step (the drain/handoff must not perturb training)
    clean = tmp_path / "clean"
    clean.mkdir()
    sup2 = Supervisor(
        [sys.executable, WORKER, "elastic", "--ckpt-dir", str(clean),
         "--steps", "18", "--checkpoint-every", "6"],
        num_processes=1, max_restarts=0, env=_CLEAN_ENV,
        progress_path=str(clean),
    )
    assert sup2.run().ok
    drill_losses = _losses_by_step(wd)
    clean_losses = _losses_by_step(clean)
    common = sorted(set(drill_losses) & set(clean_losses))
    assert common and common[-1] == 18, (drill_losses, clean_losses)
    assert any(s > 9 for s in common)  # post-drain steps are compared
    for s in common:
        assert drill_losses[s] == pytest.approx(clean_losses[s], rel=1e-6), (
            s, drill_losses[s], clean_losses[s])


@pytest.mark.slow
def test_die_host_real_gang_reshards_onto_survivor(tmp_path):
    """The same drill over a REAL jax.distributed gang (2 processes sharing
    one DP mesh): host 1's rank dies at step 12 and stays dead; the shrunk
    relaunch restores the 2-host checkpoint onto the 1-host mesh through
    the reshard-on-restore path and finishes. Skips (with evidence) on
    builds whose CPU backend cannot run multiprocess collectives."""
    from tests.test_supervisor import _gang_skip_reason

    reason = _gang_skip_reason()
    if reason:
        pytest.skip(reason)
    sup = Supervisor(
        [sys.executable, WORKER, "train", "--ckpt-dir", str(tmp_path),
         "--steps", "24", "--checkpoint-every", "6"],
        num_processes=2, max_restarts=4, restart_backoff_s=0.05,
        backoff_jitter=0.0, shrink_after=2,
        env={**_CLEAN_ENV, "DLS_FAULT": "die_host@12"},
        progress_path=str(tmp_path), hang_timeout_s=60.0,
        startup_grace_s=240.0,
    )
    result = sup.run()
    assert result.ok, (
        f"attempts: {[(a.ordinal, a.returncodes, a.classification) for a in result.attempts]}")
    assert result.attempts[-1].num_processes == 1
    step, _attempt = open(tmp_path / "DONE").read().split()
    assert int(step) == 24
    assert _geometry_changes(tmp_path), "no geometry_change event recorded"


# -- drill 4: NaN spike vs the divergence policies ---------------------------


def _mnist_trainer(checkpointer=None, seed=1):
    import optax

    from distributeddeeplearningspark_tpu import (
        PartitionedDataset,
        Session,
        Trainer,
    )
    from distributeddeeplearningspark_tpu.models import LeNet5
    from distributeddeeplearningspark_tpu.train import losses

    rng = np.random.default_rng(0)
    examples = [
        {"image": rng.normal(0, 1, (28, 28, 1)).astype(np.float32),
         "label": np.int32(i % 10)}
        for i in range(128)
    ]
    sess = Session.builder.master("local[2]").getOrCreate()
    ds = PartitionedDataset.parallelize(examples, 2).repeat()
    t = Trainer(sess, LeNet5(), losses.softmax_xent,
                optax.sgd(0.05, momentum=0.9), checkpointer=checkpointer,
                seed=seed)
    return t, ds


@pytest.mark.slow
def test_nan_spike_skip_policy_finishes_finite(tmp_path, monkeypatch):
    """Acceptance: fit(on_nonfinite='skip') + DLS_FAULT=nan@N finishes with
    finite final metrics and reports the skipped-step count in its summary;
    params never absorb the poisoned update."""
    import jax

    monkeypatch.setenv("DLS_FAULT", "nan@5")
    monkeypatch.setenv(telemetry.WORKDIR_ENV, str(tmp_path))
    monkeypatch.delenv("DLS_RESTART", raising=False)
    t, ds = _mnist_trainer()
    state, summary = t.fit(ds, batch_size=16, steps=10, log_every=2,
                           on_nonfinite="skip")
    assert summary["skipped_steps"] == 1.0
    assert np.isfinite(summary["loss"]) and np.isfinite(summary["grad_norm"])
    assert int(jax.device_get(state.step)) == 10
    for leaf in jax.tree.leaves(state.params):
        assert np.all(np.isfinite(np.asarray(jax.device_get(leaf))))
    # the divergence skip left its durable audit record
    assert any(e["event"] == "skip" and e.get("skipped_steps") == 1
               for e in _recovery_events(tmp_path)), \
        _recovery_events(tmp_path)


@pytest.mark.slow
def test_nan_every_step_exhausts_skip_budget(monkeypatch):
    """Persistent divergence must not masquerade as progress: a loss that is
    non-finite from init (lr=inf blows up step 1 and never recovers) has to
    fail once the skip budget is exhausted."""
    import optax

    from distributeddeeplearningspark_tpu import PartitionedDataset, Session, Trainer
    from distributeddeeplearningspark_tpu.models import LeNet5
    from distributeddeeplearningspark_tpu.train import losses

    monkeypatch.delenv("DLS_FAULT", raising=False)
    rng = np.random.default_rng(0)
    examples = [
        {"image": rng.normal(0, 1, (28, 28, 1)).astype(np.float32),
         "label": np.int32(i % 10)}
        for i in range(64)
    ]
    sess = Session.builder.master("local[2]").getOrCreate()
    ds = PartitionedDataset.parallelize(examples, 2).repeat()
    t = Trainer(sess, LeNet5(), losses.softmax_xent,
                optax.sgd(float("inf")), seed=1)
    with pytest.raises(FloatingPointError, match="nonfinite_budget"):
        t.fit(ds, batch_size=16, steps=50, log_every=2,
              on_nonfinite="skip", nonfinite_budget=3)


@pytest.mark.slow
def test_nan_spike_rollback_policy(tmp_path, monkeypatch):
    """fit(on_nonfinite='rollback'): the model rewinds to the last verified
    checkpoint while the data stream keeps moving, so the poisoned window is
    fast-forwarded past and training completes with finite metrics."""
    import jax

    from distributeddeeplearningspark_tpu import Checkpointer

    monkeypatch.setenv("DLS_FAULT", "nan@6")
    monkeypatch.delenv("DLS_RESTART", raising=False)
    with Checkpointer(tmp_path / "ck") as ck:
        t, ds = _mnist_trainer(checkpointer=ck)
        state, summary = t.fit(ds, batch_size=16, steps=12, log_every=2,
                               checkpoint_every=4, on_nonfinite="rollback")
        assert summary["rollbacks"] == 1.0
        assert np.isfinite(summary["loss"])
        assert int(jax.device_get(state.step)) == 12
        for leaf in jax.tree.leaves(state.params):
            assert np.all(np.isfinite(np.asarray(jax.device_get(leaf))))
        # the final checkpoint's data_state must record the TRUE stream
        # position: 12 steps of state + the 2-batch rolled-back window the
        # feed consumed (model rewound 6→4, stream did not)
        _, data_state = ck.restore(state)
        assert data_state["examples_seen"] == (12 + 2) * 16, data_state
    # telemetry (bound to the checkpointer dir): the rollback recovery
    # record names the step the model rewound to
    recov = _recovery_events(tmp_path / "ck")
    assert any(e["event"] == "rollback" and e.get("to_step") == 4
               for e in recov), recov


@pytest.mark.slow
def test_rollback_walks_past_nan_checkpoints(tmp_path, monkeypatch):
    """Checkpoint cadence finer than the detection window: the newest
    byte-verified checkpoints hold NaN params (divergence was saved before a
    log boundary saw it). Rollback must detect the poisoned restore, \
quarantine those steps, and walk back to the last numerically clean one."""
    import jax

    from distributeddeeplearningspark_tpu import Checkpointer

    monkeypatch.setenv("DLS_FAULT", "nan@2")
    monkeypatch.delenv("DLS_RESTART", raising=False)
    with Checkpointer(tmp_path / "ck", max_to_keep=20) as ck:
        t, ds = _mnist_trainer(checkpointer=ck)
        state, summary = t.fit(ds, batch_size=16, steps=10, log_every=5,
                               checkpoint_every=1, on_nonfinite="rollback")
        assert summary["rollbacks"] == 1.0
        assert np.isfinite(summary["loss"])
        assert int(jax.device_get(state.step)) == 10
        for leaf in jax.tree.leaves(state.params):
            assert np.all(np.isfinite(np.asarray(jax.device_get(leaf))))
    # the NaN-holding steps (2..4 — step 5's save is pre-empted by the
    # rollback itself) were quarantined; clean step 1 survived and was the
    # restore target
    quarantined = {d.split(".")[0] for d in _corrupt_dirs(tmp_path / "ck")}
    assert quarantined >= {"2", "3", "4"}, sorted(os.listdir(tmp_path / "ck"))
    assert os.path.isdir(tmp_path / "ck" / "1")


def test_rollback_without_checkpointer_raises(monkeypatch):
    monkeypatch.delenv("DLS_FAULT", raising=False)
    import optax

    from distributeddeeplearningspark_tpu import PartitionedDataset, Session, Trainer
    from distributeddeeplearningspark_tpu.models import LeNet5
    from distributeddeeplearningspark_tpu.train import losses

    rng = np.random.default_rng(0)
    examples = [
        {"image": rng.normal(0, 1, (28, 28, 1)).astype(np.float32),
         "label": np.int32(i % 10)}
        for i in range(64)
    ]
    sess = Session.builder.master("local[2]").getOrCreate()
    ds = PartitionedDataset.parallelize(examples, 2).repeat()
    t = Trainer(sess, LeNet5(), losses.softmax_xent, optax.sgd(float("inf")),
                seed=1)
    with pytest.raises(FloatingPointError, match="checkpointer"):
        t.fit(ds, batch_size=16, steps=10, log_every=2,
              on_nonfinite="rollback")


def test_on_nonfinite_validation():
    t, ds = _mnist_trainer()
    with pytest.raises(ValueError, match="on_nonfinite"):
        t.fit(ds, batch_size=16, steps=2, on_nonfinite="retry")
