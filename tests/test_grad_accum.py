"""Gradient accumulation: N micro-steps of B/N ≡ one step of B (VERDICT r1 #6).

The equivalence holds exactly (fp tol) because each micro-loss is a mean over
an equal-size micro-batch, so the average of micro-gradients equals the
gradient of the full-batch mean loss.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from flax import linen as nn

from distributeddeeplearningspark_tpu.parallel.mesh import MeshSpec
from distributeddeeplearningspark_tpu.parallel.sharding import REPLICATED
from distributeddeeplearningspark_tpu.train import losses, step as step_lib


class _MLP(nn.Module):
    """Deterministic model (no dropout/BN) so accum parity is exact."""

    @nn.compact
    def __call__(self, batch, *, train=False):
        x = batch["image"].reshape((batch["image"].shape[0], -1))
        x = nn.relu(nn.Dense(32)(x))
        return nn.Dense(10)(x)


def _batch(n=32, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "image": jnp.asarray(rng.normal(0, 1, (n, 8, 8, 1)).astype(np.float32)),
        "label": jnp.asarray(rng.integers(0, 10, (n,)).astype(np.int32)),
    }


@pytest.mark.parametrize("accum", [2, 4])
def test_accum_equals_full_batch_step(eight_devices, accum):
    model = _MLP()
    batch = _batch(32)
    mesh = MeshSpec(data=2).build(eight_devices[:2])
    tx = optax.adamw(1e-2)

    results = {}
    for a in (1, accum):
        state, shardings = step_lib.init_state(model, tx, batch, mesh, REPLICATED, seed=5)
        step = step_lib.jit_train_step(
            step_lib.make_train_step(model.apply, tx, losses.softmax_xent,
                                     accum_steps=a),
            mesh, shardings,
        )
        from distributeddeeplearningspark_tpu.data.feed import put_global

        new_state, metrics = step(state, put_global(batch, mesh))
        results[a] = (jax.device_get(new_state.params), jax.device_get(metrics))

    p1, m1 = results[1]
    pa, ma = results[accum]
    jax.tree.map(
        lambda x, y: np.testing.assert_allclose(x, y, rtol=1e-5, atol=1e-6), p1, pa
    )
    np.testing.assert_allclose(m1["loss"], ma["loss"], rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(m1["grad_norm"], ma["grad_norm"], rtol=1e-4, atol=1e-6)


def test_accum_multiple_steps_trains(eight_devices):
    """3 accumulated steps behave like 3 full-batch steps (trajectory parity)."""
    model = _MLP()
    mesh = MeshSpec(data=4).build(eight_devices[:4])
    tx = optax.sgd(0.1)
    from distributeddeeplearningspark_tpu.data.feed import put_global

    hist = {}
    for a in (1, 4):
        state, shardings = step_lib.init_state(model, tx, _batch(64), mesh, REPLICATED, seed=2)
        step = step_lib.jit_train_step(
            step_lib.make_train_step(model.apply, tx, losses.softmax_xent,
                                     accum_steps=a),
            mesh, shardings,
        )
        losses_seen = []
        for i in range(3):
            state, m = step(state, put_global(_batch(64, seed=i), mesh))
            losses_seen.append(float(jax.device_get(m["loss"])))
        hist[a] = losses_seen
    np.testing.assert_allclose(hist[1], hist[4], rtol=1e-5, atol=1e-6)


def test_accum_indivisible_batch_rejected(eight_devices):
    model = _MLP()
    mesh = MeshSpec(data=1).build(eight_devices[:1])
    tx = optax.sgd(0.1)
    batch = _batch(30)  # not divisible by 4
    state, shardings = step_lib.init_state(model, tx, batch, mesh, REPLICATED)
    step = step_lib.jit_train_step(
        step_lib.make_train_step(model.apply, tx, losses.softmax_xent, accum_steps=4),
        mesh, shardings,
    )
    from distributeddeeplearningspark_tpu.data.feed import put_global

    with pytest.raises(ValueError, match="divide"):
        step(state, put_global(batch, mesh))


def test_trainer_fit_accum_wiring(eight_devices):
    """Trainer.fit(accum_steps=...) trains and reports finite metrics."""
    import optax

    from distributeddeeplearningspark_tpu import Session, Trainer
    from distributeddeeplearningspark_tpu.data.sources import synthetic_mnist
    from distributeddeeplearningspark_tpu.models import LeNet5

    spark = Session.builder.master("local[2]").getOrCreate()
    ds = synthetic_mnist(num_examples=256, num_partitions=2, seed=4)
    trainer = Trainer(spark, LeNet5(), losses.softmax_xent, optax.sgd(0.05))
    state, summary = trainer.fit(
        ds.repeat(), batch_size=32, steps=4, accum_steps=2, log_every=2
    )
    assert int(jax.device_get(state.step)) == 4
    assert np.isfinite(summary["loss"])


def test_lamb_optimizer_steps():
    """LAMB (large-batch BERT optimizer): params move, lr schedule works."""
    import jax
    import jax.numpy as jnp

    from distributeddeeplearningspark_tpu.train import optim

    tx = optim.lamb(optim.warmup_linear(1e-2, 2, 10))
    params = {"w": jnp.ones((4, 4)), "b": jnp.zeros((4,))}
    state = tx.init(params)
    grads = jax.tree.map(jnp.ones_like, params)
    for _ in range(3):
        updates, state = tx.update(grads, state, params)
        params = __import__("optax").apply_updates(params, updates)
    assert float(jnp.abs(params["w"] - 1.0).max()) > 0


def test_lars_optimizer_layerwise_trust():
    """LARS (large-batch CNN optimizer, config 2 at pod batch): params
    move, and the update magnitude is layerwise-NORMALIZED — two layers
    whose gradients differ by 100× get updates scaled by their own
    param/grad norm ratio (the trust ratio), which is the property that
    keeps batch-8k SGD stable and what distinguishes LARS from plain
    momentum (where update size tracks raw gradient size)."""
    import jax
    import jax.numpy as jnp

    from distributeddeeplearningspark_tpu.train import optim

    tx = optim.lars(1e-1, weight_decay=0.0)
    params = {"small_grad": jnp.ones((8, 8)), "big_grad": jnp.ones((8, 8))}
    grads = {"small_grad": jnp.full((8, 8), 1e-3),
             "big_grad": jnp.full((8, 8), 1e-1)}
    state = tx.init(params)
    updates, state = tx.update(grads, state, params)
    small = float(jnp.abs(updates["small_grad"]).max())
    big = float(jnp.abs(updates["big_grad"]).max())
    assert small > 0 and big > 0
    # trust ratio ||w||/||g|| cancels the 100x gradient-scale difference:
    # both layers' updates come out the same size (plain SGD would differ
    # by exactly 100x)
    assert 0.5 < small / big < 2.0, (small, big)


def test_adafactor_factors_second_moments():
    """Adafactor (the TPU memory-frugal optimizer): params move AND the
    second-moment state for a factorable matrix is O(rows+cols), not
    O(rows*cols) — the property it exists for."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributeddeeplearningspark_tpu.train import optim

    tx = optim.adafactor(1e-2, min_dim_size_to_factor=8)
    params = {"w": jnp.ones((128, 256)), "b": jnp.zeros((4,))}
    state = tx.init(params)
    # no state leaf may be as large as the factored matrix itself
    big = [int(np.size(l)) for l in jax.tree_util.tree_leaves(state)
           if int(np.size(l)) >= 128 * 256]
    assert not big, f"unfactored second moments found: {big}"
    grads = jax.tree.map(jnp.ones_like, params)
    for _ in range(3):
        updates, state = tx.update(grads, state, params)
        params = __import__("optax").apply_updates(params, updates)
    assert float(jnp.abs(params["w"] - 1.0).max()) > 0
