"""DLRM / Wide&Deep + sharded embedding tests (config 4, SURVEY.md §4).

The key assertion: row-sharding the fused table over the `expert` mesh axis
computes the SAME numbers as the replicated layout — the sharded-gather
collective path is semantics-preserving.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from distributeddeeplearningspark_tpu.data.feed import host_batches, put_global, stack_examples
from distributeddeeplearningspark_tpu.data.sources import synthetic_criteo
from distributeddeeplearningspark_tpu.models.dlrm import (
    DLRM,
    FusedEmbedding,
    WideAndDeep,
    dlrm_rules,
    dot_interaction,
)
from distributeddeeplearningspark_tpu.parallel.mesh import MeshSpec
from distributeddeeplearningspark_tpu.parallel.sharding import REPLICATED
from distributeddeeplearningspark_tpu.train import losses, step as step_lib

VOCABS = (50, 30, 20, 40)


def tiny_batch(n=8, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "dense": rng.exponential(1.0, (n, 13)).astype(np.float32),
        "sparse": np.stack(
            [rng.integers(0, v, n) for v in VOCABS], axis=1
        ).astype(np.int32),
        "label": rng.integers(0, 2, (n,)).astype(np.int32),
    }


def make_model(**kw):
    kw.setdefault("vocab_sizes", VOCABS)
    kw.setdefault("embed_dim", 16)
    kw.setdefault("bottom_mlp", (32, 16))
    kw.setdefault("top_mlp", (32, 1))
    kw.setdefault("dtype", jnp.float32)
    return DLRM(**kw)


def test_fused_embedding_offsets():
    """Feature i / local id j must hit row offset_i + j of the fused table."""
    emb = FusedEmbedding(vocab_sizes=(3, 2), embed_dim=4)
    vars_ = emb.init(jax.random.PRNGKey(0), np.zeros((1, 2), np.int32))
    table = vars_["params"]["embedding_table"]
    assert table.shape == (5, 4)
    out = emb.apply(vars_, np.array([[2, 1]], np.int32))
    np.testing.assert_allclose(out[0, 0], table[2], rtol=1e-6)
    np.testing.assert_allclose(out[0, 1], table[3 + 1], rtol=1e-6)


def test_dot_interaction_shape_and_values():
    b, n, d = 2, 3, 4
    bottom = jnp.ones((b, d))
    emb = jnp.ones((b, n, d)) * 2
    out = dot_interaction(bottom, emb)
    # d + C(n+1, 2) pairwise terms
    assert out.shape == (b, d + (n + 1) * n // 2)
    # pair (emb_i, emb_j) dot = 2*2*d = 16; (bottom, emb_i) = 2*d = 8
    assert float(out[0, d]) == 8.0  # first pair involves bottom


def test_dlrm_forward_shape():
    model = make_model()
    batch = tiny_batch()
    vars_ = model.init(jax.random.PRNGKey(0), batch, train=False)
    out = model.apply(vars_, batch, train=False)
    assert out.shape == (8,)
    assert out.dtype == jnp.float32


def test_wide_and_deep_forward_shape():
    model = WideAndDeep(vocab_sizes=VOCABS, embed_dim=8, deep_mlp=(16, 1),
                        dtype=jnp.float32)
    batch = tiny_batch()
    vars_ = model.init(jax.random.PRNGKey(0), batch, train=False)
    out = model.apply(vars_, batch, train=False)
    assert out.shape == (8,)


def test_sharded_embedding_matches_replicated(eight_devices):
    """expert-sharded table ≡ replicated table, bit-for-bit-ish."""
    batch = tiny_batch(n=16)
    model = make_model()
    tx = optax.sgd(0.1)

    mesh_rep = MeshSpec(data=8).build(eight_devices)
    state_r, sh_r = step_lib.init_state(model, tx, batch, mesh_rep, REPLICATED)
    step_r = step_lib.jit_train_step(
        step_lib.make_train_step(model.apply, tx, losses.binary_xent),
        mesh_rep, sh_r)
    state_r2, m_rep = step_r(state_r, put_global(batch, mesh_rep))

    mesh_sh = MeshSpec(data=2, expert=4).build(eight_devices)
    # NOTE: same seed → same init values regardless of sharding
    state_s, sh_s = step_lib.init_state(model, tx, batch, mesh_sh, dlrm_rules())
    spec = sh_s.params["embedding"]["embedding_table"].spec
    assert spec[0] == "expert", spec  # vocab dim actually sharded
    step_s = step_lib.jit_train_step(
        step_lib.make_train_step(model.apply, tx, losses.binary_xent),
        mesh_sh, sh_s)
    state_s2, m_sh = step_s(state_s, put_global(batch, mesh_sh))

    assert np.isclose(float(m_rep["loss"]), float(m_sh["loss"]), rtol=1e-5)
    assert np.isclose(float(m_rep["accuracy"]), float(m_sh["accuracy"]), rtol=1e-6)
    # backward parity: grad norm covers the scatter-add through the sharded
    # gather, and a second step covers the applied update
    assert np.isclose(float(m_rep["grad_norm"]), float(m_sh["grad_norm"]), rtol=1e-4)
    _, m_rep2 = step_r(state_r2, put_global(batch, mesh_rep))
    _, m_sh2 = step_s(state_s2, put_global(batch, mesh_sh))
    assert np.isclose(float(m_rep2["loss"]), float(m_sh2["loss"]), rtol=1e-4)


def test_dlrm_learns(eight_devices):
    mesh = MeshSpec(data=2, expert=4).build(eight_devices)
    ds = synthetic_criteo(1024, vocab_sizes=VOCABS, num_partitions=4)
    feed = host_batches(ds.repeat(), 64, num_shards=2)
    model = make_model()
    tx = optax.adam(5e-3)
    batch = next(feed)
    state, shardings = step_lib.init_state(model, tx, batch, mesh, dlrm_rules())
    train_step = step_lib.jit_train_step(
        step_lib.make_train_step(model.apply, tx, losses.binary_xent),
        mesh, shardings)
    first = None
    accs = []
    for i, hb in enumerate(feed):
        if i >= 50:
            break
        state, m = train_step(state, put_global(hb, mesh))
        if first is None:
            first = float(m["loss"])
        accs.append(float(m["accuracy"]))
    assert np.mean(accs[-10:]) > 0.62  # decisively above chance on synthetic CTR
    assert float(m["loss"]) < first


def test_dlrm_matches_torch_reference():
    """Numerical parity vs an independent torch implementation of the same
    DLRM math (SURVEY §4: torch parity replaces 'compare against the
    reference' for the absent repo; ResNet/BERT/Llama have theirs — this
    closes config 4). Weights copied flax→torch; f32 both sides so the
    comparison is about the MATH (fused-table offsets, log1p dense
    transform, lower-triangle dot interaction, MLP activations), not bf16
    rounding."""
    import torch

    from distributeddeeplearningspark_tpu.models.dlrm import fused_flat_ids

    vocabs = (11, 7, 19)
    model = DLRM(vocab_sizes=vocabs, embed_dim=8, bottom_mlp=(16, 8),
                 top_mlp=(16, 1), dtype=np.float32)
    rng = np.random.default_rng(3)
    batch = {
        "dense": rng.normal(0, 2, (4, 13)).astype(np.float32),
        "sparse": np.stack([rng.integers(0, v, 4) for v in vocabs],
                           axis=1).astype(np.int32),
    }
    params = model.init(jax.random.PRNGKey(1), batch, train=False)["params"]
    ours = np.asarray(model.apply({"params": params}, batch, train=False))

    def lin(dense_params):
        """flax Dense {kernel [in,out], bias [out]} → torch Linear."""
        w = torch.tensor(np.asarray(dense_params["kernel"]).T)
        b = torch.tensor(np.asarray(dense_params["bias"]))
        m = torch.nn.Linear(w.shape[1], w.shape[0])
        with torch.no_grad():
            m.weight.copy_(w)
            m.bias.copy_(b)
        return m

    bot = [lin(params["bottom_mlp"][f"dense_{i}"]) for i in range(2)]
    top = [lin(params["top_mlp"][f"dense_{i}"]) for i in range(2)]
    table = torch.tensor(
        np.asarray(params["embedding"]["embedding_table"]))

    with torch.no_grad():
        dense = torch.log1p(
            torch.clamp(torch.tensor(batch["dense"]), min=0.0))
        x = dense
        for m in bot:  # final_activation=True: relu after every layer
            x = torch.relu(m(x))
        flat = np.asarray(fused_flat_ids(vocabs, batch["sparse"]))
        emb = table[torch.tensor(flat)]                      # [B, N, D]
        z = torch.cat([x[:, None, :], emb], dim=1)           # [B, N+1, D]
        gram = torch.einsum("bnd,bmd->bnm", z, z)
        li, lj = np.tril_indices(z.shape[1], k=-1)           # row-major,
        # same enumeration as the flax side's jnp.tril_indices
        feats = torch.cat([x, gram[:, li, lj]], dim=1)
        y = feats
        for i, m in enumerate(top):  # final_activation=False
            y = m(y)
            if i < len(top) - 1:
                y = torch.relu(y)
        theirs = y[:, 0].numpy()

    np.testing.assert_allclose(ours, theirs, rtol=1e-5, atol=1e-5)
