"""Device-side performance observatory (ISSUE 10): compile ledger,
step anatomy, MFU arithmetic, memory watermarks, `dlstatus --anatomy`,
and the tools/perf_guard.py regression sentinel."""

import importlib.util
import json
import math
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributeddeeplearningspark_tpu import status, telemetry
from distributeddeeplearningspark_tpu.telemetry import anatomy


def _load_perf_guard():
    path = os.path.join(os.path.dirname(__file__), "..", "tools",
                        "perf_guard.py")
    spec = importlib.util.spec_from_file_location("perf_guard", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture
def workdir(tmp_path):
    """Bind the process-global telemetry writer to a temp workdir and
    always unbind after (the ledger emits through the global writer)."""
    telemetry.configure(tmp_path)
    yield str(tmp_path)
    telemetry.reset()


# -- compile ledger -----------------------------------------------------------


def test_compile_event_schema_and_phase_span(workdir):
    fn = anatomy.instrument(jax.jit(lambda x: x * 2 + 1), name="double")
    out = fn(jnp.ones((4, 4), jnp.float32))
    assert np.allclose(np.asarray(out), 3.0)
    events = telemetry.read_events(workdir)
    comp = [e for e in events if e["kind"] == "compile"]
    assert len(comp) == 1
    e = comp[0]
    assert e["fn"] == "double"
    assert "f32[4,4]" in e["sig"]
    assert isinstance(e["sig_hash"], str) and len(e["sig_hash"]) == 16
    assert e["compile_s"] > 0
    assert e["flops"] and e["flops"] > 0          # cost analysis rode along
    assert e["bytes_accessed"] and e["bytes_accessed"] > 0
    assert e["recompile"] is False and e["aot"] is True
    assert e["sig_compiles"] == 1 and e["distinct_signatures"] == 1
    # the compile is ALSO a phase span, so goodput accounts the stall
    phases = [p for p in events
              if p["kind"] == "phase" and p.get("name") == "compile"]
    assert any(p.get("edge") == "begin" for p in phases)
    assert any(p.get("edge") == "end" for p in phases)
    assert telemetry.goodput(events)["compile_s"] >= 0.0
    # same signature again: dict hit, no new compile, same result
    fn(jnp.ones((4, 4), jnp.float32))
    comp2 = [e for e in telemetry.read_events(workdir)
             if e["kind"] == "compile"]
    assert len(comp2) == 1
    assert fn._cache_size() == 1


def test_second_shape_flags_exactly_one_recompile(workdir):
    """A shape-stable step (expected_signatures=1) forced through a second
    shape flags EXACTLY one recompile — the acceptance drill."""
    fn = anatomy.instrument(jax.jit(lambda x: x + 1), name="step")
    fn(jnp.ones((8,)))
    fn(jnp.ones((16,)))          # the forced second shape
    fn(jnp.ones((16,)))          # reuse: no further compile
    comp = [e for e in telemetry.read_events(workdir)
            if e["kind"] == "compile"]
    assert len(comp) == 2
    assert [e["recompile"] for e in comp] == [False, True]
    assert fn.compile_summary()["flagged_recompiles"] == 1
    rep = anatomy.anatomy_report(telemetry.read_events(workdir))
    assert rep["compile_ledger"]["flagged_recompiles"] == 1
    assert rep["verdicts"]["recompile"].startswith("RECOMPILES")


def test_expected_signatures_pins_a_bucket_ladder(workdir):
    """The serve-engine discipline: a pinned ladder of N shapes is clean;
    shape N+1 flags."""
    fn = anatomy.instrument(jax.jit(lambda x: x.sum()), name="fwd",
                            expected_signatures=2)
    fn(jnp.ones((2,)))
    fn(jnp.ones((4,)))
    comp = [e for e in telemetry.read_events(workdir)
            if e["kind"] == "compile"]
    assert [e["recompile"] for e in comp] == [False, False]
    fn(jnp.ones((8,)))           # beyond the pinned ladder
    comp = [e for e in telemetry.read_events(workdir)
            if e["kind"] == "compile"]
    assert [e["recompile"] for e in comp] == [False, False, True]


def test_dtype_change_is_a_new_signature(workdir):
    fn = anatomy.instrument(jax.jit(lambda x: x * 1), name="cast")
    fn(jnp.ones((4,), jnp.float32))
    fn(jnp.ones((4,), jnp.int32))
    comp = [e for e in telemetry.read_events(workdir)
            if e["kind"] == "compile"]
    assert len(comp) == 2
    assert comp[0]["sig_hash"] != comp[1]["sig_hash"]


def test_prepare_compiles_once_and_reports_flops(workdir):
    fn = anatomy.instrument(jax.jit(lambda a, b: a @ b), name="mm")
    a = jnp.ones((8, 8))
    rec = fn.prepare(a, a)
    assert rec["flops"] == pytest.approx(2 * 8 * 8 * 8, rel=0.5)
    assert fn.flops_per_step == rec["flops"]
    fn(a, a)  # dispatches on the prepared executable — no second compile
    comp = [e for e in telemetry.read_events(workdir)
            if e["kind"] == "compile"]
    assert len(comp) == 1


def test_instrument_is_idempotent_and_exposes_lower():
    fn = anatomy.instrument(jax.jit(lambda x: x), name="id")
    assert anatomy.instrument(fn, name="other") is fn
    lowered = fn.lower(jnp.ones((2,)))
    assert lowered.compile() is not None


def test_donated_state_dispatch(workdir):
    """The trainer shape: donated arg 0, repeated dispatch on the same
    executable (the donation chain must survive AOT dispatch)."""
    step = anatomy.instrument(
        jax.jit(lambda s, x: (s + x, (s * x).sum()), donate_argnums=(0,)),
        name="train_step")
    s = jnp.zeros((16,))
    x = jnp.ones((16,))
    for i in range(3):
        s, m = step(s, x)
    assert float(s[0]) == 3.0
    comp = [e for e in telemetry.read_events(workdir)
            if e["kind"] == "compile"]
    assert len(comp) == 1 and comp[0]["recompile"] is False


# -- step anatomy / MFU arithmetic -------------------------------------------


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_step_anatomy_split_and_mfu_arithmetic(monkeypatch):
    """Hand-computed case: 10 steps of 2e9 FLOPs over a 4-chip mesh in a
    10s lap with a 1e9 FLOPs/s/chip peak → MFU = 2e9*10/10/4/1e9 = 0.5.
    The split must tile the lap: device 6s (4 dispatch + 2 drain), compile
    1s, input 0.5s, host = the 2.5s residual."""
    monkeypatch.setenv(anatomy.PEAK_FLOPS_ENV, "1e9")
    clock = FakeClock()
    anat = anatomy.StepAnatomy(clock=clock)
    anat.reset()
    anat.note_compile(1.0)
    anat.note_dispatch(4.0)
    clock.t = 8.0
    with anat.drain():
        clock.t = 10.0
    rec = anat.lap(steps=10, input_wait_s=0.5, flops_per_step=2e9,
                   num_chips=4)
    assert rec["anatomy_wall_s"] == 10.0
    assert rec["device_s"] == 6.0
    assert rec["device_dispatch_s"] == 4.0
    assert rec["device_drain_s"] == 2.0
    assert rec["compile_in_lap_s"] == 1.0
    assert rec["host_s"] == pytest.approx(2.5)
    assert rec["mfu"] == pytest.approx(0.5)
    assert rec["mfu_device"] == pytest.approx(2e9 * 10 / 6.0 / 4 / 1e9)
    assert rec["peak_flops_per_chip"] == 1e9
    assert rec["peak_source"] == anatomy.PEAK_FLOPS_ENV
    # lap() reset: a second, empty lap is all host
    clock.t = 12.0
    rec2 = anat.lap(steps=0)
    assert rec2["anatomy_wall_s"] == 2.0
    assert rec2["device_s"] == 0.0 and rec2["host_s"] == 2.0
    assert "mfu" not in rec2


def test_resolve_peak_flops_order(monkeypatch):
    monkeypatch.setenv(anatomy.PEAK_FLOPS_ENV, "123.5")
    peak, source = anatomy.resolve_peak_flops()
    assert peak == 123.5 and source == anatomy.PEAK_FLOPS_ENV
    monkeypatch.delenv(anatomy.PEAK_FLOPS_ENV)
    peak, source = anatomy.resolve_peak_flops()
    # the suite runs on the CPU backend: the labeled nominal fallback
    assert peak and peak > 0 and source.startswith("nominal-cpu")
    monkeypatch.setenv(anatomy.PEAK_FLOPS_ENV, "not-a-number")
    peak2, _ = anatomy.resolve_peak_flops()
    assert peak2 == peak  # malformed override ignored, not fatal


# -- memory watermarks --------------------------------------------------------


def test_memory_watermarks_cpu_fallback():
    """This backend exposes no allocator stats → the live-buffer path."""
    keep = jnp.ones((1024,), jnp.float32)  # noqa: F841 — held live
    rec = anatomy.memory_watermarks()
    assert rec["source"] == "live-buffers"
    assert rec["devices"] >= 1
    assert rec["live_bytes"] >= keep.nbytes


def test_memory_fold_prefers_stats_and_computes_headroom():
    events = [
        {"ts": 1.0, "kind": "memory", "process": "p0",
         "source": "memory_stats", "bytes_in_use_max": 100,
         "peak_bytes_in_use_max": 150, "bytes_limit_min": 1000,
         "headroom_bytes": 850},
        {"ts": 2.0, "kind": "memory", "process": "p1",
         "source": "memory_stats", "bytes_in_use_max": 200,
         "peak_bytes_in_use_max": 300, "bytes_limit_min": 900,
         "headroom_bytes": 600},
        {"ts": 3.0, "kind": "memory", "process": "bench",
         "source": "live-buffers", "live_bytes": 7},
    ]
    rep = anatomy.anatomy_report(events)
    mem = rep["memory"]
    assert mem["source"] == "memory_stats"
    assert mem["bytes_in_use_max"] == 200
    assert mem["peak_bytes_in_use_max"] == 300
    assert mem["bytes_limit_min"] == 900
    assert mem["headroom_bytes"] == 600
    # live-buffer-only stream falls back
    rep2 = anatomy.anatomy_report([events[-1]])
    assert rep2["memory"] == {"source": "live-buffers", "live_bytes": 7}


# -- reader fold / dlstatus ---------------------------------------------------


def _lap_event(proc, ts, *, steps=10, wall=10.0, device=6.0, dispatch=4.0,
               drain=2.0, host=2.5, compile_s=1.0, input_wait=0.5,
               flops=2e9, peak=1e9, chips=4, mfu=0.5):
    return {"ts": ts, "kind": "step_metrics", "process": proc, "step": steps,
            "steps": steps, "lap_s": wall, "input_wait_s": input_wait,
            "anatomy_wall_s": wall, "device_s": device,
            "device_dispatch_s": dispatch, "device_drain_s": drain,
            "host_s": host, "compile_in_lap_s": compile_s,
            "num_chips": chips, "peak_flops_per_chip": peak,
            "peak_source": "DLS_PEAK_FLOPS", "flops_per_step": flops,
            "mfu": mfu}


def test_anatomy_report_fold_totals_and_verdicts():
    events = [
        {"ts": 0.0, "kind": "compile", "process": "p0", "fn": "train_step",
         "sig": "f32[8]", "sig_hash": "aa", "compile_s": 2.0, "flops": 2e9,
         "bytes_accessed": 1e6, "recompile": False, "aot": True},
        _lap_event("p0", 10.0),
        _lap_event("p0", 20.0),
    ]
    rep = anatomy.anatomy_report(events)
    st = rep["steps"]
    assert st["laps"] == 2 and st["steps"] == 20
    assert st["wall_s"] == 20.0 and st["device_s"] == 12.0
    assert st["coverage"] == pytest.approx(1.0)
    assert st["fractions"]["device"] == pytest.approx(0.6)
    # aggregate MFU: 2e9*20 flops over 20s on 4 chips at 1e9 peak = 0.5
    assert rep["mfu"]["mfu"] == pytest.approx(0.5)
    assert rep["mfu"]["num_chips"] == 4
    assert rep["verdicts"]["recompile"].startswith("OK")
    assert rep["verdicts"]["bound"].startswith("device-bound")
    assert rep["per_process"]["p0"]["laps"] == 2
    # an empty stream has no report at all
    assert anatomy.anatomy_report([{"ts": 0, "kind": "heartbeat"}]) is None


def test_anatomy_report_cross_process_duplicates_are_not_flagged():
    """A restart re-pays the compile of the SAME signature: reported as a
    duplicate (restarts re-pay jit), not flagged as a recompile storm."""
    ev = {"kind": "compile", "fn": "train_step", "sig": "f32[8]",
          "sig_hash": "aa", "compile_s": 1.0, "recompile": False,
          "aot": True}
    events = [{"ts": 0.0, "process": "p0", **ev},
              {"ts": 10.0, "process": "p0", **ev}]
    rep = anatomy.anatomy_report(events)
    assert rep["compile_ledger"]["flagged_recompiles"] == 0
    assert rep["compile_ledger"]["duplicate_signatures"] == 1
    assert "re-paid" in rep["verdicts"]["recompile"]


def test_dlstatus_anatomy_json_schema(tmp_path, capsys):
    w = telemetry.EventWriter(tmp_path, process="p0", clock=FakeClock(),
                              host=0)
    w.emit("compile", fn="train_step", sig="f32[8]", sig_hash="ab",
           compile_s=2.0, flops=2e9, bytes_accessed=1e6, recompile=False,
           aot=True)
    w.emit("step_metrics", **{k: v for k, v in
                              _lap_event("p0", 0.0).items()
                              if k not in ("ts", "kind", "process")})
    w.emit("memory", source="live-buffers", devices=8, live_bytes=4096)
    w.close()
    rc = status.main([str(tmp_path), "--anatomy", "--json"])
    assert rc == 0
    rep = json.loads(capsys.readouterr().out)
    an = rep["anatomy"]
    for key in ("compile_ledger", "steps", "mfu", "memory", "per_process",
                "verdicts"):
        assert key in an, key
    cl = an["compile_ledger"]
    for key in ("compiles", "distinct_signatures", "flagged_recompiles",
                "duplicate_signatures", "total_compile_s", "by_fn",
                "events"):
        assert key in cl, key
    for key in ("laps", "steps", "wall_s", "device_s", "device_dispatch_s",
                "device_drain_s", "host_s", "compile_s", "input_wait_s",
                "coverage", "fractions"):
        assert key in an["steps"], key
    for key in ("mfu", "mfu_last_lap", "flops_per_step",
                "peak_flops_per_chip", "peak_source", "num_chips"):
        assert key in an["mfu"], key
    assert an["memory"]["live_bytes"] == 4096
    # the human rendering carries the section too
    rc = status.main([str(tmp_path), "--anatomy"])
    out = capsys.readouterr().out
    assert rc == 0 and "device anatomy:" in out and "compile ledger:" in out


def test_dlstatus_watch_mode(tmp_path, capsys):
    """--watch re-reads and re-renders; bounded by --watch-count for tests,
    and an empty workdir waits instead of exiting 1."""
    rc = status.main([str(tmp_path), "--watch", "--watch-count", "2",
                      "--interval", "0.11"])
    assert rc == 0
    out = capsys.readouterr().out
    assert out.count("no telemetry events yet") == 2
    w = telemetry.EventWriter(tmp_path, process="p0", clock=FakeClock())
    w.heartbeat(step=3)
    w.close()
    rc = status.main([str(tmp_path), "--watch", "--watch-count", "1",
                      "--interval", "0.11", "--json"])
    assert rc == 0
    rep = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rep["last_step"] == 3


def test_chrome_trace_memory_counter_track(tmp_path):
    from distributeddeeplearningspark_tpu.telemetry import trace as trace_lib

    events = [
        {"ts": 1.0, "kind": "phase", "process": "p0", "name": "compile",
         "edge": "begin"},
        {"ts": 3.0, "kind": "phase", "process": "p0", "name": "compile",
         "edge": "end", "dur_s": 2.0},
        {"ts": 2.0, "kind": "memory", "process": "p0",
         "source": "live-buffers", "live_bytes": 1234},
    ]
    data = trace_lib.chrome_trace(events)
    counters = [e for e in data["traceEvents"] if e.get("ph") == "C"]
    assert len(counters) == 1
    assert counters[0]["args"] == {"live_bytes": 1234}
    spans = [e for e in data["traceEvents"]
             if e.get("ph") == "X" and e["name"] == "compile"]
    assert len(spans) == 1  # the compile phase lowered into the export
    # memory events alone still produce a loadable trace
    data2 = trace_lib.chrome_trace([events[-1]])
    assert any(e.get("ph") == "C" for e in data2["traceEvents"])


# -- serve-side compile visibility (satellite) --------------------------------


def test_engine_warmup_emits_compile_phases(tmp_path):
    """engine.warmup()'s bucket-ladder compiles must land as `compile`
    phases + ledger events — warmup seconds were silently misattributed
    before (ISSUE 10 satellite)."""
    from distributeddeeplearningspark_tpu.serve.engine import InferenceEngine

    def forward(params, batch):
        return {"y": batch["x"] * params["w"]}

    eng = InferenceEngine(forward, {"w": jnp.float32(2.0)}, max_batch=4,
                          workdir=str(tmp_path), name="anat")
    try:
        n = eng.warmup({"x": np.float32(1.0)})
        assert n == len(eng.batch_sizes)
        events = telemetry.read_events(tmp_path)
        comp = [e for e in events if e["kind"] == "compile"]
        assert len(comp) == len(eng.batch_sizes)
        assert all(e["fn"] == "serve-anat" for e in comp)
        assert not any(e["recompile"] for e in comp)
        phases = [e for e in events if e["kind"] == "phase"
                  and e.get("name") == "compile" and e.get("edge") == "end"]
        assert len(phases) == len(eng.batch_sizes)
        # goodput now accounts the warmup stall as compile time
        assert telemetry.goodput(events)["compile_s"] > 0
        # the pinned-compile-set stat still reads through the wrapper
        assert eng.stats()["compiled_batch_shapes"] == len(eng.batch_sizes)
        # traffic through a warmed bucket adds NO compile
        with eng:
            eng.infer({"x": np.float32(3.0)})
        comp2 = [e for e in telemetry.read_events(tmp_path)
                 if e["kind"] == "compile"]
        assert len(comp2) == len(comp)
    finally:
        eng.stop()
        telemetry.reset()


# -- perf_guard ---------------------------------------------------------------


def _bench_record(value, *, metric="resnet50_images_per_sec_per_chip",
                  backend="tpu", step_time_ms=None, mfu=None,
                  compile_s=None, recompile_count=None, spread_pct=None):
    arm = {}
    for k, v in (("images_per_sec_per_chip", value),
                 ("step_time_ms", step_time_ms), ("mfu", mfu),
                 ("compile_s", compile_s),
                 ("recompile_count", recompile_count),
                 ("spread_pct", spread_pct)):
        if v is not None:
            arm[k] = v
    return {"metric": metric, "value": value, "unit": "images/sec/chip",
            "extra": {"backend": backend, "resnet50": arm}}


def test_perf_guard_ok_regressed_insufficient():
    pg = _load_perf_guard()
    hist = [_bench_record(100.0, step_time_ms=10.0, mfu=0.4),
            _bench_record(104.0, step_time_ms=9.6, mfu=0.41),
            _bench_record(98.0, step_time_ms=10.2, mfu=0.39)]

    ok = pg.guard(_bench_record(101.0, step_time_ms=9.9, mfu=0.4), hist)
    assert ok["verdict"] == "OK" and not ok["regressed"]

    slow = pg.guard(_bench_record(80.0, step_time_ms=12.5, mfu=0.32), hist)
    assert slow["verdict"] == "REGRESSED"
    assert "resnet50.images_per_sec_per_chip" in slow["regressed"]
    assert "resnet50.step_time_ms" in slow["regressed"]
    assert "value:resnet50_images_per_sec_per_chip" in slow["regressed"]

    # one prior record: every check lacks history -> explicit refusal
    short = pg.guard(_bench_record(80.0), hist[:1])
    assert short["verdict"] == "INSUFFICIENT_HISTORY"
    assert all(c["status"] == "insufficient-history"
               for c in short["checks"])


def test_perf_guard_backend_and_metric_scoping():
    """A host-degraded round must not be judged against chip history."""
    pg = _load_perf_guard()
    tpu_hist = [_bench_record(100.0), _bench_record(101.0)]
    host = pg.guard(_bench_record(5.0, backend="host"), tpu_hist)
    assert host["verdict"] == "INSUFFICIENT_HISTORY"
    assert host["comparable_history"] == 0


def test_perf_guard_recompile_and_compile_band():
    pg = _load_perf_guard()
    hist = [_bench_record(100.0, compile_s=10.0, recompile_count=0),
            _bench_record(100.0, compile_s=14.0, recompile_count=0)]
    # compile_s gets a widened (3x) band: +40% over baseline stays ok
    ok = pg.guard(_bench_record(100.0, compile_s=16.0, recompile_count=0),
                  hist)
    assert ok["verdict"] == "OK"
    # +60% trips even the widened band
    slow = pg.guard(_bench_record(100.0, compile_s=20.0), hist)
    assert "resnet50.compile_s" in slow["regressed"]
    # ANY recompile over a clean baseline is a regression, band-free
    storm = pg.guard(_bench_record(100.0, recompile_count=1), hist)
    assert "resnet50.recompile_count" in storm["regressed"]


def test_perf_guard_spread_widens_step_time_band():
    pg = _load_perf_guard()
    hist = [_bench_record(100.0, step_time_ms=10.0),
            _bench_record(100.0, step_time_ms=10.0)]
    # +18% step time with a self-reported 25% spread: inside the widened band
    noisy = pg.guard(_bench_record(100.0, step_time_ms=11.8,
                                   spread_pct=25.0), hist)
    assert "resnet50.step_time_ms" not in noisy["regressed"]
    tight = pg.guard(_bench_record(100.0, step_time_ms=11.8,
                                   spread_pct=2.0), hist)
    assert "resnet50.step_time_ms" in tight["regressed"]


def test_perf_guard_cli_on_wrapper_records(tmp_path):
    """The CLI reads the driver wrapper shape ({'rc', 'parsed'}) and skips
    failed rounds when picking current/history."""
    pg = _load_perf_guard()
    recs = [(1, 0, _bench_record(100.0)), (2, 0, _bench_record(102.0)),
            (3, 1, _bench_record(999.0)),  # failed round: ignored
            (4, 0, _bench_record(101.0))]
    for n, rc, parsed in recs:
        (tmp_path / f"BENCH_r{n:02d}.json").write_text(
            json.dumps({"n": n, "rc": rc, "parsed": parsed}))
    assert pg.main(["--dir", str(tmp_path)]) == 0
    (tmp_path / "BENCH_r05.json").write_text(
        json.dumps({"n": 5, "rc": 0, "parsed": _bench_record(70.0)}))
    assert pg.main(["--dir", str(tmp_path)]) == 1
