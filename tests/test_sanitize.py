"""Single-process sanitizer units (multi-process coverage: test_supervisor)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributeddeeplearningspark_tpu.utils.sanitize import (
    assert_all_finite,
    assert_replicas_in_sync,
    params_checksum,
    tree_fingerprint,
)


def test_fingerprint_is_deterministic_and_value_sensitive():
    tree = {"a": jnp.arange(12.0).reshape(3, 4), "b": {"w": jnp.ones((5,))}}
    fp1 = tree_fingerprint(tree)
    fp2 = tree_fingerprint(jax.tree.map(lambda x: x + 0, tree))
    np.testing.assert_array_equal(fp1, fp2)
    assert fp1.shape == (2, 4)
    fp3 = tree_fingerprint({"a": jnp.arange(12.0).reshape(3, 4) + 1e-6,
                            "b": {"w": jnp.ones((5,))}})
    assert np.abs(fp1 - fp3).max() > 0


def test_single_process_sync_is_trivial():
    assert_replicas_in_sync({"w": jnp.ones((4,))})  # no-op, must not raise


def test_assert_all_finite():
    assert_all_finite({"loss": 0.5, "acc": 1.0, "step": 3})
    with pytest.raises(FloatingPointError, match="loss"):
        assert_all_finite({"loss": float("nan")}, step=7)
    with pytest.raises(FloatingPointError):
        assert_all_finite({"grad_norm": float("inf")})


def test_params_checksum_scalar():
    c = params_checksum({"a": jnp.ones((3,)), "b": -2.0 * jnp.ones((2,))})
    assert c == pytest.approx(7.0)
