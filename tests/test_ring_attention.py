"""Ring attention (context parallelism) vs dense attention, on a real seq mesh.

Runs on 8 fake CPU devices with nontrivial (data × seq × tensor) meshes so the
ppermute ring and the batch/head shardings are genuinely exercised.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributeddeeplearningspark_tpu.models import LlamaConfig, LlamaForCausalLM
from distributeddeeplearningspark_tpu.ops.attention import _xla_attention
from distributeddeeplearningspark_tpu.ops.ring_attention import ring_attention
from distributeddeeplearningspark_tpu.parallel.mesh import MeshSpec
from distributeddeeplearningspark_tpu.parallel.sharding import ShardingRules
from distributeddeeplearningspark_tpu.train import losses, step as step_lib


def _qkv(b=4, s=32, h=4, d=16, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(0, 1, (b, s, h, d)).astype(np.float32))
    return mk(), mk(), mk()


@pytest.mark.parametrize("spec", [
    MeshSpec(data=2, seq=4),
    MeshSpec(data=1, seq=8),
    MeshSpec(data=2, seq=2, tensor=2),
])
def test_ring_matches_dense_causal(spec, eight_devices):
    mesh = spec.build()
    q, k, v = _qkv()
    want = _xla_attention(q, k, v, bias=None, mask=None, causal=True, scale=None)
    got = jax.jit(lambda a, b_, c: ring_attention(a, b_, c, mesh=mesh, causal=True))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


def test_ring_matches_dense_non_causal(eight_devices):
    mesh = MeshSpec(data=2, seq=4).build()
    q, k, v = _qkv(seed=3)
    want = _xla_attention(q, k, v, bias=None, mask=None, causal=False, scale=None)
    got = jax.jit(lambda a, b_, c: ring_attention(a, b_, c, mesh=mesh, causal=False))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


def test_ring_gradients_match_dense(eight_devices):
    mesh = MeshSpec(data=2, seq=4).build()
    q, k, v = _qkv(b=2, s=16, h=2, d=8, seed=7)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh=mesh, causal=True) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(_xla_attention(q, k, v, bias=None, mask=None,
                                      causal=True, scale=None) ** 2)

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_dense = jax.jit(jax.grad(loss_dense, argnums=(0, 1, 2)))(q, k, v)
    for gr, gd in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gd), atol=1e-4, rtol=1e-4)


def test_ring_rejects_bias_qmask_and_uneven_shapes(eight_devices):
    mesh = MeshSpec(data=2, seq=4).build()
    q, k, v = _qkv()
    # a mask that varies over queries is not expressible key-blockwise
    with pytest.raises(NotImplementedError):
        ring_attention(q, k, v, mesh=mesh, mask=jnp.ones((4, 1, 32, 32), bool))
    with pytest.raises(NotImplementedError):
        ring_attention(q, k, v, mesh=mesh, bias=jnp.zeros((4, 1, 32, 32)))
    with pytest.raises(ValueError, match="k/v shapes must match"):
        ring_attention(q, k[:, :, :2], v, mesh=mesh)
    # GQA with a non-dividing head count is rejected
    with pytest.raises(ValueError, match="multiple"):
        ring_attention(q, k[:, :, :3], v[:, :, :3], mesh=mesh)


def test_llama_context_parallel_train_step(eight_devices):
    """Full CP train step: Llama with ring attention over data=2 x seq=4."""
    mesh = MeshSpec(data=2, seq=4).build()
    import dataclasses

    cfg = dataclasses.replace(LlamaConfig.tiny(), attention_impl="ring",
                              scan_layers=False, remat=False)
    from distributeddeeplearningspark_tpu.ops import ring_attention as ring_mod

    ring_mod.set_default_mesh(mesh)
    model = LlamaForCausalLM(cfg)
    batch = {
        "input_ids": np.tile(np.arange(32, dtype=np.int32)[None], (8, 1)) % cfg.vocab_size,
        "loss_mask": np.ones((8, 32), np.float32),
    }
    tx = optax.adamw(1e-3)
    state, shardings = step_lib.init_state(model, tx, batch, mesh, ShardingRules())
    train = step_lib.make_train_step(model.apply, tx, losses.causal_lm)
    jitted = step_lib.jit_train_step(train, mesh, shardings, seq_sharded=True)
    from distributeddeeplearningspark_tpu.data.feed import put_global

    gbatch = put_global(batch, mesh, seq_sharded=True)
    assert "seq" in str(gbatch["input_ids"].sharding.spec)
    state2, metrics = jitted(state, gbatch)
    assert np.isfinite(float(jax.device_get(metrics["loss"])))

    # CP loss equals the pure-DP loss on the same batch/params
    mesh_dp = MeshSpec(data=8).build()
    cfg_dp = dataclasses.replace(cfg, attention_impl="xla")
    model_dp = LlamaForCausalLM(cfg_dp)
    state_dp, sh_dp = step_lib.init_state(model_dp, tx, batch, mesh_dp, ShardingRules())
    train_dp = step_lib.make_train_step(model_dp.apply, tx, losses.causal_lm)
    jitted_dp = step_lib.jit_train_step(train_dp, mesh_dp, sh_dp)
    gbatch_dp = put_global(batch, mesh_dp)
    _, metrics_dp = jitted_dp(state_dp, gbatch_dp)
    np.testing.assert_allclose(
        float(jax.device_get(metrics["loss"])),
        float(jax.device_get(metrics_dp["loss"])),
        rtol=1e-4,
    )


# -- blockwise backward memory proxy (VERDICT r1 missing-#6) ----------------

def _subjaxprs(val):
    for v in (val if isinstance(val, (list, tuple)) else [val]):
        if hasattr(v, "eqns"):
            yield v
        elif hasattr(v, "jaxpr") and hasattr(v.jaxpr, "eqns"):
            yield v.jaxpr


def _collect_sizes(jaxpr, inside, sizes):
    for eqn in jaxpr.eqns:
        now_inside = inside or eqn.primitive.name == "shard_map"
        if inside:
            for var in eqn.outvars:
                aval = getattr(var, "aval", None)
                if aval is not None and getattr(aval, "size", 0):
                    sizes.append(int(aval.size))
        for val in eqn.params.values():
            for sub in _subjaxprs(val):
                _collect_sizes(sub, now_inside, sizes)


def test_ring_backward_does_not_stack_per_hop_probabilities(eight_devices):
    """The custom-VJP backward recomputes probabilities per hop; no residual
    inside the shard_map body may be larger than ~one probability block.
    Autodiff-through-scan (the r1 implementation) stacks (ring-1) blocks of
    [B,H,Sq,Sk] residuals and trips this bound."""
    ring = 4
    mesh = MeshSpec(data=2, seq=ring).build()
    b, s, h, d = 2, 32, 2, 8
    q, k, v = _qkv(b=b, s=s, h=h, d=d, seed=9)

    def loss(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh=mesh, causal=True) ** 2)

    jaxpr = jax.make_jaxpr(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
    sizes: list[int] = []
    _collect_sizes(jaxpr.jaxpr, False, sizes)
    assert sizes, "jaxpr walk found nothing inside shard_map — test is broken"
    # local probability block: [B, H, Sq/ring... wait batch is also sharded
    # (data=2): local q block is [B/2, S/ring, H, D]
    block_elems = (b // 2) * h * (s // ring) * (s // ring)
    limit = 2 * block_elems
    offenders = [sz for sz in sizes if sz > limit]
    assert not offenders, (
        f"backward materializes arrays of sizes {sorted(set(offenders))} "
        f"(> {limit} elems ≈ 2 probability blocks) inside shard_map — "
        f"per-hop residuals are being stacked again")


def test_ring_gqa_matches_xla_repeat(eight_devices):
    """GQA-native ring (grouped KV on the ring, no repeat) == XLA attention
    with explicitly repeated KV — values and grads."""
    import jax.numpy as jnp

    from distributeddeeplearningspark_tpu.ops.attention import dot_product_attention
    from distributeddeeplearningspark_tpu.ops.ring_attention import ring_attention
    from distributeddeeplearningspark_tpu.parallel.mesh import MeshSpec

    mesh = MeshSpec(data=2, seq=4).build(eight_devices)
    b, s, h, hkv, d = 2, 32, 4, 2, 16
    key = jax.random.PRNGKey(3)
    ks = jax.random.split(key, 3)
    q = jax.random.normal(ks[0], (b, s, h, d), jnp.float32)
    k = jax.random.normal(ks[1], (b, s, hkv, d), jnp.float32)
    v = jax.random.normal(ks[2], (b, s, hkv, d), jnp.float32)

    def ring_loss(q, k, v):
        o = ring_attention(q, k, v, mesh=mesh, causal=True)
        return jnp.sum(o ** 2), o

    def xla_loss(q, k, v):
        kk = jnp.repeat(k, h // hkv, axis=2)
        vv = jnp.repeat(v, h // hkv, axis=2)
        o = dot_product_attention(q, kk, vv, causal=True, impl="xla")
        return jnp.sum(o ** 2), o

    with jax.sharding.use_mesh(mesh) if hasattr(jax.sharding, "use_mesh") else __import__("contextlib").nullcontext():
        (lv, o1), g1 = jax.jit(jax.value_and_grad(ring_loss, argnums=(0, 1, 2),
                                                  has_aux=True))(q, k, v)
    (lv2, o2), g2 = jax.jit(jax.value_and_grad(xla_loss, argnums=(0, 1, 2),
                                               has_aux=True))(q, k, v)
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o2), rtol=2e-5, atol=2e-5)
    for a, b2 in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b2),
                                   rtol=5e-4, atol=5e-5)


def test_ring_gqa_rejects_undividable_tensor_degree(eight_devices):
    """kv heads must divide the tensor degree — clear error, not a cryptic
    shard_map failure."""
    from distributeddeeplearningspark_tpu.ops.ring_attention import ring_attention
    from distributeddeeplearningspark_tpu.parallel.mesh import MeshSpec

    mesh = MeshSpec(data=2, seq=2, tensor=2).build()
    q = jnp.zeros((2, 16, 4, 8))
    kv = jnp.zeros((2, 16, 1, 8))  # 1 kv head, tensor=2
    with pytest.raises(ValueError, match="tensor degree"):
        ring_attention(q, kv, kv, mesh=mesh, causal=True)


class TestFlashHops:
    """Flash-kernel-per-hop ring (use_flash=True → interpret kernels on CPU)
    must match the einsum ring and dense attention exactly — forward and
    gradients, causal and not, GQA included."""

    @pytest.mark.parametrize("causal", [True, False])
    def test_forward_matches_dense(self, causal, eight_devices):
        mesh = MeshSpec(data=2, seq=4).build()
        q, k, v = _qkv(b=2, s=32, h=4, d=16, seed=11)
        want = _xla_attention(q, k, v, bias=None, mask=None, causal=causal,
                              scale=None)
        got = jax.jit(lambda a, b_, c: ring_attention(
            a, b_, c, mesh=mesh, causal=causal, use_flash=True))(q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-5, rtol=2e-5)

    def test_gradients_match_einsum_ring_and_dense(self, eight_devices):
        mesh = MeshSpec(data=2, seq=4).build()
        q, k, v = _qkv(b=2, s=16, h=2, d=8, seed=13)

        def loss(fn):
            return jax.jit(jax.grad(
                lambda a, b_, c: jnp.sum(fn(a, b_, c) ** 2), argnums=(0, 1, 2)))

        g_flash = loss(lambda a, b_, c: ring_attention(
            a, b_, c, mesh=mesh, causal=True, use_flash=True))(q, k, v)
        g_einsum = loss(lambda a, b_, c: ring_attention(
            a, b_, c, mesh=mesh, causal=True, use_flash=False))(q, k, v)
        g_dense = loss(lambda a, b_, c: _xla_attention(
            a, b_, c, bias=None, mask=None, causal=True, scale=None))(q, k, v)
        for gf, ge, gd in zip(g_flash, g_einsum, g_dense):
            np.testing.assert_allclose(np.asarray(gf), np.asarray(ge),
                                       atol=2e-5, rtol=2e-5)
            np.testing.assert_allclose(np.asarray(gf), np.asarray(gd),
                                       atol=2e-5, rtol=2e-5)

    def test_gqa_forward_and_grads(self, eight_devices):
        mesh = MeshSpec(data=1, seq=4, tensor=2).build()
        rng = np.random.default_rng(17)
        b, s, h, hkv, d = 2, 32, 8, 4, 16
        q = jnp.asarray(rng.normal(0, 1, (b, s, h, d)).astype(np.float32))
        k = jnp.asarray(rng.normal(0, 1, (b, s, hkv, d)).astype(np.float32))
        v = jnp.asarray(rng.normal(0, 1, (b, s, hkv, d)).astype(np.float32))
        kr = jnp.repeat(k, h // hkv, axis=2)
        vr = jnp.repeat(v, h // hkv, axis=2)
        want = _xla_attention(q, kr, vr, bias=None, mask=None, causal=True,
                              scale=None)
        got = jax.jit(lambda a, b_, c: ring_attention(
            a, b_, c, mesh=mesh, causal=True, use_flash=True))(q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-5, rtol=2e-5)

        g = jax.jit(jax.grad(lambda a, b_, c: jnp.sum(ring_attention(
            a, b_, c, mesh=mesh, causal=True, use_flash=True) ** 2),
            argnums=(0, 1, 2)))(q, k, v)
        g_ref = jax.jit(jax.grad(lambda a, b_, c: jnp.sum(ring_attention(
            a, b_, c, mesh=mesh, causal=True, use_flash=False) ** 2),
            argnums=(0, 1, 2)))(q, k, v)
        for gf, ge in zip(g, g_ref):
            np.testing.assert_allclose(np.asarray(gf), np.asarray(ge),
                                       atol=2e-5, rtol=2e-5)

    def test_odd_small_local_blocks(self, eight_devices):
        """s_local=6 (block == whole local seq) still runs and matches —
        the whole-block case of the kernel tiling rules."""
        mesh = MeshSpec(data=2, seq=4).build()
        q, k, v = _qkv(b=2, s=24, h=2, d=8, seed=19)  # s_local = 6
        want = _xla_attention(q, k, v, bias=None, mask=None, causal=True,
                              scale=None)
        got = jax.jit(lambda a, b_, c: ring_attention(
            a, b_, c, mesh=mesh, causal=True, use_flash=True))(q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-5, rtol=2e-5)

    def test_large_future_logit_does_not_nan_gradients(self, eight_devices):
        """Inactive (fully-masked future) hops run the kernel unmasked; a
        large future logit overflows exp(s − lse) to inf there, and the gate
        must SELECT the contribution away (inf × 0 would be NaN). Regression
        for the confirmed repro: q[0,0] = k[0, future] = 10·1⃗ → all-NaN
        grads under the multiply gate."""
        mesh = MeshSpec(data=4, seq=2).build()
        rng = np.random.default_rng(23)
        b, s, h, d = 4, 16, 2, 8
        q = rng.normal(0, 1, (b, s, h, d)).astype(np.float32)
        k = rng.normal(0, 1, (b, s, h, d)).astype(np.float32)
        v = rng.normal(0, 1, (b, s, h, d)).astype(np.float32)
        q[0, 0] = 10.0   # early query...
        k[0, 14] = 10.0  # ...against a huge key in the FUTURE block
        q, k, v = jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)
        grads = jax.jit(jax.grad(lambda a, b_, c: jnp.sum(ring_attention(
            a, b_, c, mesh=mesh, causal=True, use_flash=True) ** 2),
            argnums=(0, 1, 2)))(q, k, v)
        ref = jax.jit(jax.grad(lambda a, b_, c: jnp.sum(ring_attention(
            a, b_, c, mesh=mesh, causal=True, use_flash=False) ** 2),
            argnums=(0, 1, 2)))(q, k, v)
        for g, r in zip(grads, ref):
            assert np.isfinite(np.asarray(g)).all()
            np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                       atol=2e-4, rtol=2e-4)

    def test_explicit_use_flash_with_bad_shapes_raises(self, eight_devices):
        mesh = MeshSpec(data=2, seq=4).build()
        q, k, v = _qkv(b=1, s=30, h=2, d=8)  # 30 % 4 != 0
        with pytest.raises(ValueError, match="use_flash"):
            ring_attention(q, k, v, mesh=mesh, causal=True, use_flash=True)

    def test_qualification_gate(self):
        from distributeddeeplearningspark_tpu.ops.ring_attention import (
            _flash_hop_qualifies,
        )

        # whole-block local sequences always tile; >512 must tile by 512
        assert _flash_hop_qualifies(6, 8, on_tpu=True)
        assert _flash_hop_qualifies(512, 64, on_tpu=True)
        assert _flash_hop_qualifies(1024, 128, on_tpu=True)
        assert not _flash_hop_qualifies(768, 128, on_tpu=True)  # 768 % 512
        assert not _flash_hop_qualifies(512, 12, on_tpu=True)   # d % 8
        assert _flash_hop_qualifies(512, 12, on_tpu=False)      # interpret: ok
        assert not _flash_hop_qualifies(0, 8, on_tpu=False)


def _padded_mask(b, s, valid_lens, seed=None):
    """[B, S] int32 key-padding mask: first valid_lens[i] positions valid."""
    m = np.zeros((b, s), np.int32)
    for i, n in enumerate(valid_lens):
        m[i, :n] = 1
    return jnp.asarray(m)


class TestKeyPaddingMask:
    """CP for padded batches (VERDICT r2 #6): a key-only mask sharded over
    the seq axis rides the ring with its K/V block. Parity vs the XLA path
    with the same mask, forward AND gradients, on 4+ seq shards — both hop
    implementations (einsum and interpret-mode flash kernels)."""

    @pytest.mark.parametrize("use_flash", [False, True])
    def test_forward_matches_dense_masked(self, use_flash, eight_devices):
        mesh = MeshSpec(data=2, seq=4).build()
        b, s = 4, 32
        q, k, v = _qkv(b=b, s=s)
        # ragged valid lengths; 20 leaves shard 3 (positions 24..31) fully
        # padded and shard 2 partially padded — both block regimes on the ring
        mask = _padded_mask(b, s, [32, 20, 8, 27])
        want = _xla_attention(q, k, v, bias=None,
                              mask=(mask != 0)[:, None, None, :],
                              causal=False, scale=None)
        got = jax.jit(lambda a, b_, c: ring_attention(
            a, b_, c, mesh=mesh, causal=False, mask=mask,
            use_flash=use_flash))(q, k, v)
        # padded QUERY rows disagree by convention (xla: uniform attention,
        # ring/flash: zeros) — compare valid query rows only, like the loss
        w = np.asarray(want)
        g = np.asarray(got)
        mb = np.asarray(mask)
        for i in range(b):
            n = mb[i].sum()
            np.testing.assert_allclose(g[i, :n], w[i, :n],
                                       atol=2e-5, rtol=2e-5)
            # padded query rows must be exactly finite (zero output)
            assert np.isfinite(g[i, n:]).all()

    @pytest.mark.parametrize("use_flash", [False, True])
    def test_gradients_match_dense_masked(self, use_flash, eight_devices):
        mesh = MeshSpec(data=2, seq=4).build()
        b, s = 2, 16
        q, k, v = _qkv(b=b, s=s, h=2, d=8, seed=29)
        mask = _padded_mask(b, s, [16, 9])
        # weight the loss by the query-validity mask so the conventions for
        # padded query rows (uniform vs zero) never enter the gradients —
        # exactly how a padded-batch model consumes attention output
        qw = (mask != 0)[:, :, None, None].astype(jnp.float32)

        def loss_ring(a, b_, c):
            o = ring_attention(a, b_, c, mesh=mesh, causal=False, mask=mask,
                               use_flash=use_flash)
            return jnp.sum((o * qw) ** 2)

        def loss_dense(a, b_, c):
            o = _xla_attention(a, b_, c, bias=None,
                               mask=(mask != 0)[:, None, None, :],
                               causal=False, scale=None)
            return jnp.sum((o * qw) ** 2)

        g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
        g_dense = jax.jit(jax.grad(loss_dense, argnums=(0, 1, 2)))(q, k, v)
        for gr, gd in zip(g_ring, g_dense):
            assert np.isfinite(np.asarray(gr)).all()
            np.testing.assert_allclose(np.asarray(gr), np.asarray(gd),
                                       atol=1e-4, rtol=1e-4)

    @pytest.mark.parametrize("use_flash", [False, True])
    def test_masked_and_causal_compose(self, use_flash, eight_devices):
        """Causal × padding-mask is the trickiest interaction: einsum's
        sentinel-LSE rows and the flash path's _hop_active gating must both
        compose with a mask riding the ring — so check fwd AND grads on
        both hop implementations."""
        mesh = MeshSpec(data=1, seq=8).build()
        b, s = 2, 32
        q, k, v = _qkv(b=b, s=s, h=2, d=8, seed=31)
        mask = _padded_mask(b, s, [32, 21])
        want = _xla_attention(q, k, v, bias=None,
                              mask=(mask != 0)[:, None, None, :],
                              causal=True, scale=None)
        got = jax.jit(lambda a, b_, c: ring_attention(
            a, b_, c, mesh=mesh, causal=True, mask=mask,
            use_flash=use_flash))(q, k, v)
        w, g, mb = np.asarray(want), np.asarray(got), np.asarray(mask)
        for i in range(b):
            n = mb[i].sum()
            np.testing.assert_allclose(g[i, :n], w[i, :n],
                                       atol=2e-5, rtol=2e-5)

        qw = (mask != 0)[:, :, None, None].astype(jnp.float32)

        def loss_ring(a, b_, c):
            o = ring_attention(a, b_, c, mesh=mesh, causal=True, mask=mask,
                               use_flash=use_flash)
            return jnp.sum((o * qw) ** 2)

        def loss_dense(a, b_, c):
            o = _xla_attention(a, b_, c, bias=None,
                               mask=(mask != 0)[:, None, None, :],
                               causal=True, scale=None)
            return jnp.sum((o * qw) ** 2)

        g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
        g_dense = jax.jit(jax.grad(loss_dense, argnums=(0, 1, 2)))(q, k, v)
        for gr, gd in zip(g_ring, g_dense):
            assert np.isfinite(np.asarray(gr)).all()
            np.testing.assert_allclose(np.asarray(gr), np.asarray(gd),
                                       atol=1e-4, rtol=1e-4)

    def test_bert_style_broadcast_mask_accepted(self, eight_devices):
        """[B, 1, 1, S] (the form padding_mask() emits) reduces key-only."""
        mesh = MeshSpec(data=2, seq=4).build()
        q, k, v = _qkv()
        mask4 = _padded_mask(4, 32, [32, 20, 8, 27])[:, None, None, :] != 0
        got = jax.jit(lambda a, b_, c: ring_attention(
            a, b_, c, mesh=mesh, causal=False, mask=mask4))(q, k, v)
        assert np.isfinite(np.asarray(got)).all()

    def test_gqa_masked_ring(self, eight_devices):
        mesh = MeshSpec(data=1, seq=4, tensor=2).build()
        rng = np.random.default_rng(37)
        b, s, h, hkv, d = 2, 32, 8, 4, 16
        q = jnp.asarray(rng.normal(0, 1, (b, s, h, d)).astype(np.float32))
        k = jnp.asarray(rng.normal(0, 1, (b, s, hkv, d)).astype(np.float32))
        v = jnp.asarray(rng.normal(0, 1, (b, s, hkv, d)).astype(np.float32))
        mask = _padded_mask(b, s, [26, 15])
        want = _xla_attention(q, jnp.repeat(k, 2, axis=2),
                              jnp.repeat(v, 2, axis=2), bias=None,
                              mask=(mask != 0)[:, None, None, :],
                              causal=False, scale=None)
        got = jax.jit(lambda a, b_, c: ring_attention(
            a, b_, c, mesh=mesh, causal=False, mask=mask))(q, k, v)
        w, g, mb = np.asarray(want), np.asarray(got), np.asarray(mask)
        for i in range(b):
            n = mb[i].sum()
            np.testing.assert_allclose(g[i, :n], w[i, :n],
                                       atol=2e-5, rtol=2e-5)


class TestSegmentIdsRing:
    """Packed sequences under CP (VERDICT r2 #4 x #6): segment ids shard
    over seq, q side reads locally, kv side rides the ring. Parity vs the
    dense XLA path with the equivalent segment mask, fwd AND grads, both
    hop implementations, 4+ seq shards."""

    @staticmethod
    def _segs(b, s, bounds):
        out = np.zeros((b, s), np.int32)
        for i, starts in enumerate(bounds):
            for d_, st in enumerate(starts):
                out[i, st:] = d_
        return jnp.asarray(out)

    @staticmethod
    def _seg_mask(segs):
        return segs[:, None, :, None] == segs[:, None, None, :]

    @pytest.mark.parametrize("use_flash", [False, True])
    def test_forward_matches_dense(self, use_flash, eight_devices):
        mesh = MeshSpec(data=2, seq=4).build()
        b, s = 4, 32
        q, k, v = _qkv(b=b, s=s)
        segs = self._segs(b, s, [[0, 10, 20], [0, 16], [0], [0, 5, 11, 27]])
        want = _xla_attention(q, k, v, bias=None, mask=self._seg_mask(segs),
                              causal=False, scale=None)
        got = jax.jit(lambda a, b_, c: ring_attention(
            a, b_, c, mesh=mesh, causal=False, segment_ids=segs,
            use_flash=use_flash))(q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-5, rtol=2e-5)

    @pytest.mark.parametrize("use_flash", [False, True])
    def test_gradients_match_dense_causal(self, use_flash, eight_devices):
        """Causal x segments is the hard composition (flash _hop_active
        gating + riding seg blocks) — grads on both impls."""
        mesh = MeshSpec(data=2, seq=4).build()
        b, s = 2, 16
        q, k, v = _qkv(b=b, s=s, h=2, d=8, seed=41)
        segs = self._segs(b, s, [[0, 7], [0, 3, 12]])

        def loss_ring(a, b_, c):
            o = ring_attention(a, b_, c, mesh=mesh, causal=True,
                               segment_ids=segs, use_flash=use_flash)
            return jnp.sum(o ** 2)

        def loss_dense(a, b_, c):
            o = _xla_attention(a, b_, c, bias=None,
                               mask=self._seg_mask(segs), causal=True,
                               scale=None)
            return jnp.sum(o ** 2)

        g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
        g_dense = jax.jit(jax.grad(loss_dense, argnums=(0, 1, 2)))(q, k, v)
        for gr, gd in zip(g_ring, g_dense):
            assert np.isfinite(np.asarray(gr)).all()
            np.testing.assert_allclose(np.asarray(gr), np.asarray(gd),
                                       atol=1e-4, rtol=1e-4)

    def test_composes_with_padding_mask(self, eight_devices):
        """Packed tail window: padding mask + segment ids (-1 on pads)
        together on the ring."""
        mesh = MeshSpec(data=2, seq=4).build()
        b, s = 2, 32
        q, k, v = _qkv(b=b, s=s, h=2, d=8, seed=43)
        pad_mask = _padded_mask(b, s, [32, 24])
        segs = np.array(self._segs(b, s, [[0, 13], [0, 9, 17]]))
        segs[1, 24:] = -1
        segs = jnp.asarray(segs)
        want = _xla_attention(
            q, k, v, bias=None,
            mask=self._seg_mask(segs)
            & (pad_mask != 0)[:, None, None, :],
            causal=False, scale=None)
        got = jax.jit(lambda a, b_, c: ring_attention(
            a, b_, c, mesh=mesh, causal=False, mask=pad_mask,
            segment_ids=segs))(q, k, v)
        w, g, mb = np.asarray(want), np.asarray(got), np.asarray(pad_mask)
        for i in range(b):
            n = mb[i].sum()
            np.testing.assert_allclose(g[i, :n], w[i, :n],
                                       atol=2e-5, rtol=2e-5)
            assert np.isfinite(g[i]).all()

    def test_gqa_with_segments(self, eight_devices):
        mesh = MeshSpec(data=1, seq=4, tensor=2).build()
        rng = np.random.default_rng(47)
        b, s, h, hkv, d = 2, 32, 8, 4, 16
        q = jnp.asarray(rng.normal(0, 1, (b, s, h, d)).astype(np.float32))
        k = jnp.asarray(rng.normal(0, 1, (b, s, hkv, d)).astype(np.float32))
        v = jnp.asarray(rng.normal(0, 1, (b, s, hkv, d)).astype(np.float32))
        segs = self._segs(b, s, [[0, 21], [0, 6]])
        want = _xla_attention(q, jnp.repeat(k, 2, axis=2),
                              jnp.repeat(v, 2, axis=2), bias=None,
                              mask=self._seg_mask(segs), causal=True,
                              scale=None)
        got = jax.jit(lambda a, b_, c: ring_attention(
            a, b_, c, mesh=mesh, causal=True, segment_ids=segs))(q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-5, rtol=2e-5)

    def test_bad_shape_rejected(self, eight_devices):
        mesh = MeshSpec(data=2, seq=4).build()
        q, k, v = _qkv()
        with pytest.raises(ValueError, match="segment_ids"):
            ring_attention(q, k, v, mesh=mesh,
                           segment_ids=jnp.zeros((4, 16), jnp.int32))
