"""Run telemetry: event bus, goodput accounting, starvation probe, dlstatus.

Everything here runs on fake clocks — no sleeps, no real-time dependence —
because the goodput accountant is a pure fold over timestamped records and
the probe takes an injectable clock. The gang-level acceptance drill
(supervised crash → dlstatus report) lives in test_chaos.py with the other
recovery drills.
"""

import json
import logging
import os

import numpy as np
import pytest

from distributeddeeplearningspark_tpu import status, telemetry
from distributeddeeplearningspark_tpu.data.prefetch import (
    StarvationProbe,
    prefetch_to_device,
)
from distributeddeeplearningspark_tpu.metrics import Meter, MetricLogger, _log_value


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def tick(self, dt):
        self.t += dt


def _writer(tmp_path, process="p0", t0=0.0):
    clock = FakeClock(t0)
    return telemetry.EventWriter(tmp_path, process=process, clock=clock), clock


# -- event bus ---------------------------------------------------------------


def test_writer_appends_typed_records(tmp_path):
    w, clock = _writer(tmp_path)
    w.step_metrics(10, steps=10, lap_s=2.5, metrics={"loss": 1.0})
    clock.tick(1.0)
    w.recovery(10, "skip", skipped_steps=1)
    w.heartbeat(step=10)
    w.close()
    events = telemetry.read_events(tmp_path)
    assert [e["kind"] for e in events] == ["step_metrics", "recovery",
                                           "heartbeat"]
    assert events[0]["metrics"] == {"loss": 1.0}
    assert events[0]["process"] == "p0"
    assert events[1]["event"] == "skip" and events[1]["ts"] == 1.0
    # the file lands where the contract says
    assert os.path.exists(tmp_path / "telemetry" / "events-p0.jsonl")


def test_multi_process_merge_is_ts_ordered_and_stable(tmp_path):
    """Files from different processes interleave by timestamp; equal
    timestamps keep per-file (append) order — the merge contract dlstatus
    timelines rely on."""
    a, ca = _writer(tmp_path, "p0")
    b, cb = _writer(tmp_path, "supervisor")
    ca.t = 1.0
    a.emit("heartbeat", seq="a1")
    cb.t = 0.5
    b.emit("attempt", edge="begin", ordinal=0, seq="b1")
    ca.t = 2.0
    a.emit("heartbeat", seq="a2")
    cb.t = 2.0  # ties: file order (events-p0 sorts before events-supervisor)
    b.emit("attempt", edge="end", ordinal=0, seq="b2")
    a.close(), b.close()
    seqs = [e["seq"] for e in telemetry.read_events(tmp_path)]
    assert seqs == ["b1", "a1", "a2", "b2"]


def test_torn_tail_and_garbage_lines_are_skipped(tmp_path):
    """A SIGKILL'd writer can leave a half-written last line; a crashed-run
    stream must still parse (minus only the torn record)."""
    w, clock = _writer(tmp_path)
    w.emit("heartbeat", step=1)
    clock.tick(1.0)
    w.emit("heartbeat", step=2)
    w.close()
    path = tmp_path / "telemetry" / "events-p0.jsonl"
    with open(path, "a") as f:
        f.write("not json at all\n")
        f.write('{"ts": 3.0, "kind": "heartbeat", "step": 3')  # torn, no \n
    events = telemetry.read_events(tmp_path)
    assert [e["step"] for e in events] == [1, 2]


def test_reader_accepts_workdir_or_telemetry_dir(tmp_path):
    w, _ = _writer(tmp_path)
    w.emit("heartbeat", step=1)
    w.close()
    assert len(telemetry.read_events(tmp_path)) == 1
    assert len(telemetry.read_events(tmp_path / "telemetry")) == 1


def test_singleton_configure_reset(tmp_path):
    assert telemetry.get() is None
    telemetry.emit("heartbeat")  # unconfigured: silent no-op
    w = telemetry.configure(tmp_path)
    assert telemetry.configure(tmp_path) is w  # idempotent per workdir
    telemetry.emit("heartbeat", step=7)
    with telemetry.phase("checkpoint"):
        pass
    telemetry.reset()
    assert telemetry.get() is None
    kinds = [e["kind"] for e in telemetry.read_events(tmp_path)]
    assert kinds == ["heartbeat", "phase", "phase"]


# -- goodput accounting ------------------------------------------------------


def _ev(ts, kind, **f):
    return {"ts": ts, "kind": kind, "process": f.pop("process", "p0"), **f}


def test_goodput_components_sum_to_wall():
    events = [
        _ev(0.0, "phase", name="run", edge="begin"),
        _ev(0.0, "phase", name="compile", edge="begin"),
        _ev(10.0, "phase", name="compile", edge="end", dur_s=10.0),
        _ev(20.0, "step_metrics", step=10, steps=10, lap_s=10.0,
            input_wait_s=2.0),
        _ev(20.0, "phase", name="checkpoint", edge="begin"),
        _ev(25.0, "phase", name="checkpoint", edge="end", dur_s=5.0),
        _ev(30.0, "phase", name="eval", edge="begin"),
        _ev(34.0, "phase", name="eval", edge="end", dur_s=4.0),
        _ev(100.0, "phase", name="run", edge="end"),
    ]
    g = telemetry.goodput(events)
    assert g["wall_s"] == 100.0
    assert g["compile_s"] == 10.0
    assert g["checkpoint_s"] == 5.0
    assert g["eval_s"] == 4.0
    assert g["input_starved_s"] == 2.0
    assert g["productive_s"] == 100.0 - 10.0 - 5.0 - 4.0 - 2.0
    assert g["goodput_frac"] == pytest.approx(0.79)
    total = sum(g[k] for k in telemetry.GOODPUT_COMPONENTS)
    assert total == pytest.approx(g["wall_s"])


def test_goodput_overlapping_spans_count_once():
    """Within a category overlaps merge by union; across categories the
    productive residual subtracts the union of ALL spans, so a span nested
    inside another is never deducted twice."""
    events = [
        _ev(0.0, "heartbeat"),
        # two overlapping compile spans: [0,10] + [5,15] -> 15s, not 20
        _ev(0.0, "phase", name="compile", edge="begin"),
        _ev(10.0, "phase", name="compile", edge="end", dur_s=10.0),
        _ev(5.0, "phase", name="compile", edge="begin", process="p1"),
        _ev(15.0, "phase", name="compile", edge="end", dur_s=10.0,
            process="p1"),
        # restore nested inside the compile window
        _ev(8.0, "phase", name="restore", edge="begin"),
        _ev(12.0, "phase", name="restore", edge="end", dur_s=4.0),
        _ev(30.0, "heartbeat"),
    ]
    g = telemetry.goodput(events)
    assert g["compile_s"] == 15.0
    assert g["restore_s"] == 4.0
    # union of everything is [0,15] -> productive = 30 - 15
    assert g["productive_s"] == 15.0
    assert g["goodput_frac"] == pytest.approx(0.5)


def test_goodput_crashed_run_partial_stream():
    """A phase whose end never arrived (the process died inside it) is
    accounted up to the last event seen — a crashed stream under-reports
    nothing silently."""
    events = [
        _ev(0.0, "heartbeat"),
        _ev(10.0, "phase", name="compile", edge="begin"),
        _ev(30.0, "heartbeat"),  # last sign of life
    ]
    g = telemetry.goodput(events)
    assert g["wall_s"] == 30.0
    assert g["compile_s"] == 20.0
    assert g["productive_s"] == 10.0


def test_goodput_orphaned_phase_capped_at_attempt_end():
    """A phase left open by a SIGKILL mid-checkpoint must be accounted up
    to the supervisor reaping that attempt — NOT to the end of the merged
    stream, which would swallow the relaunch's hour of productive time."""
    events = [
        _ev(0.0, "attempt", edge="begin", ordinal=0, process="supervisor"),
        _ev(60.0, "phase", name="checkpoint", edge="begin"),
        # SIGKILL here: no end ever arrives for p0's checkpoint span
        _ev(65.0, "attempt", edge="end", ordinal=0, process="supervisor"),
        _ev(70.0, "attempt", edge="begin", ordinal=1, process="supervisor"),
        _ev(3600.0, "attempt", edge="end", ordinal=1, process="supervisor"),
    ]
    g = telemetry.goodput(events)
    assert g["checkpoint_s"] == 5.0  # 60 -> 65, not 60 -> 3600
    assert g["restart_overhead_s"] == 5.0
    assert g["productive_s"] == 3600.0 - 10.0


def test_goodput_orphaned_phase_unsupervised_caps_at_process_silence():
    """Without a supervisor, the orphan is bounded by the opening process's
    own last event — the moment it went silent."""
    events = [
        _ev(0.0, "heartbeat", process="p0"),
        _ev(10.0, "phase", name="compile", edge="begin", process="p0"),
        _ev(30.0, "heartbeat", process="p0"),
        _ev(100.0, "heartbeat", process="p1"),  # another process lives on
    ]
    g = telemetry.goodput(events)
    assert g["compile_s"] == 20.0  # 10 -> 30 (p0's silence), not 10 -> 100


def test_goodput_restart_gap_between_attempts():
    events = [
        _ev(0.0, "attempt", edge="begin", ordinal=0, process="supervisor"),
        _ev(50.0, "attempt", edge="end", ordinal=0, process="supervisor"),
        _ev(60.0, "attempt", edge="begin", ordinal=1, process="supervisor"),
        _ev(100.0, "attempt", edge="end", ordinal=1, process="supervisor"),
    ]
    g = telemetry.goodput(events)
    assert g["restart_overhead_s"] == 10.0
    assert g["productive_s"] == 90.0


def test_goodput_idle_between_sessions_not_productive():
    """Stop today, resume tomorrow into the same workdir: the gap between
    run spans is idle_s, not a 99%-goodput lie."""
    events = [
        _ev(0.0, "phase", name="run", edge="begin"),
        _ev(100.0, "phase", name="run", edge="end"),
        _ev(1100.0, "phase", name="run", edge="begin"),  # resumed much later
        _ev(1200.0, "phase", name="run", edge="end"),
    ]
    g = telemetry.goodput(events)
    assert g["idle_s"] == 1000.0
    assert g["productive_s"] == 200.0
    assert g["goodput_frac"] == pytest.approx(200.0 / 1200.0)
    assert sum(g[k] for k in telemetry.GOODPUT_COMPONENTS) == \
        pytest.approx(g["wall_s"])


def test_goodput_crashed_then_resumed_gap_is_idle():
    """A SIGKILL'd run never closes its run span; when the workdir is
    resumed later, the dead gap must land in idle_s, not inflate the
    productive residual toward goodput_frac ~1.0."""
    events = [
        _ev(0.0, "phase", name="run", edge="begin"),
        _ev(95.0, "heartbeat", step=10),  # last sign of life, then SIGKILL
        _ev(1000.0, "phase", name="run", edge="begin"),  # resumed next day
        _ev(1100.0, "phase", name="run", edge="end"),
    ]
    g = telemetry.goodput(events)
    assert g["idle_s"] == 1000.0 - 95.0
    assert g["productive_s"] == pytest.approx(95.0 + 100.0)
    assert sum(g[k] for k in telemetry.GOODPUT_COMPONENTS) == \
        pytest.approx(g["wall_s"])


def test_goodput_supervised_relaunch_gap_is_restart_not_idle():
    """A clean-exit worker relaunched by the supervisor closes its run span
    before the restart gap; the supervisor's gap stays restart_overhead_s
    and only the teardown/startup tails outside it count as idle."""
    events = [
        _ev(0.0, "attempt", edge="begin", ordinal=0, process="supervisor"),
        _ev(1.0, "phase", name="run", edge="begin"),
        _ev(49.0, "phase", name="run", edge="end"),
        _ev(50.0, "attempt", edge="end", ordinal=0, process="supervisor"),
        _ev(60.0, "attempt", edge="begin", ordinal=1, process="supervisor"),
        _ev(61.0, "phase", name="run", edge="begin"),
        _ev(99.0, "phase", name="run", edge="end"),
        _ev(100.0, "attempt", edge="end", ordinal=1, process="supervisor"),
    ]
    g = telemetry.goodput(events)
    assert g["restart_overhead_s"] == 10.0
    # run-end 49 -> run-begin 61 minus the restart interval [50, 60]:
    # 1s worker teardown + 1s relaunch startup, not double-counted
    assert g["idle_s"] == pytest.approx(2.0)
    assert sum(g[k] for k in telemetry.GOODPUT_COMPONENTS) == \
        pytest.approx(g["wall_s"])


def test_goodput_hang_dwell_not_productive():
    """A hang: the worker goes silent at t=100, the watchdog reaps it at
    t=400, relaunch runs on. The 300s dwell plus the startup tail must not
    land in the productive residual (only trimmed of the restart gap)."""
    events = [
        _ev(0.0, "attempt", edge="begin", ordinal=0, process="supervisor"),
        _ev(1.0, "phase", name="run", edge="begin"),
        _ev(100.0, "heartbeat", step=10),  # last sign of life; hang
        _ev(400.0, "attempt", edge="end", ordinal=0, process="supervisor"),
        _ev(401.0, "attempt", edge="begin", ordinal=1, process="supervisor"),
        _ev(405.0, "phase", name="run", edge="begin"),
        _ev(500.0, "phase", name="run", edge="end"),
        _ev(501.0, "attempt", edge="end", ordinal=1, process="supervisor"),
    ]
    g = telemetry.goodput(events)
    assert g["restart_overhead_s"] == 1.0
    assert g["idle_s"] == pytest.approx(304.0)  # (100,400) + (401,405)
    assert g["productive_s"] == pytest.approx(501.0 - 1.0 - 304.0)


def test_goodput_two_supervisor_sessions_gap_is_idle_not_restart():
    """dlsupervise run today, again tomorrow: the overnight gap between
    sessions (ordinal restarts at 0) is idle, not an 86000s 'restart'."""
    events = [
        _ev(0.0, "attempt", edge="begin", ordinal=0, process="supervisor"),
        _ev(1.0, "phase", name="run", edge="begin"),
        _ev(100.0, "phase", name="run", edge="end"),
        _ev(101.0, "attempt", edge="end", ordinal=0, process="supervisor"),
        _ev(86400.0, "attempt", edge="begin", ordinal=0, process="supervisor"),
        _ev(86401.0, "phase", name="run", edge="begin"),
        _ev(86500.0, "phase", name="run", edge="end"),
        _ev(86501.0, "attempt", edge="end", ordinal=0, process="supervisor"),
    ]
    g = telemetry.goodput(events)
    assert g["restart_overhead_s"] == 0.0
    assert g["idle_s"] == pytest.approx(86401.0 - 100.0)
    assert g["goodput_frac"] < 0.01


def test_goodput_multi_process_starvation_is_max_not_sum():
    """Lockstep SPMD: the slowest host's input wait gates the gang, so
    gang-level starvation is the max across processes — summing 4 hosts'
    waits would over-count 4x and could exceed wall-clock."""
    events = [_ev(0.0, "heartbeat")]
    for proc, wait in (("p0", 30.0), ("p1", 28.0), ("p2", 31.0),
                       ("p3", 29.0)):
        events.append(_ev(50.0, "step_metrics", step=10, steps=10,
                          lap_s=50.0, input_wait_s=wait, process=proc))
    events.append(_ev(100.0, "heartbeat"))
    g = telemetry.goodput(events)
    assert g["input_starved_s"] == 31.0
    assert g["productive_s"] == 69.0


def test_goodput_empty_and_single_event():
    assert telemetry.goodput([])["goodput_frac"] == 0.0
    g = telemetry.goodput([_ev(5.0, "heartbeat")])
    assert g["wall_s"] == 0.0 and g["goodput_frac"] == 0.0


# -- starvation probe --------------------------------------------------------


def test_probe_timed_counts_waits_with_fake_clock():
    clock = FakeClock()
    probe = StarvationProbe(clock=clock)

    def slow_source():
        for i in range(4):
            clock.tick(0.5 if i % 2 else 2.0)  # alternating slow/fast
            yield {"x": i}

    out = list(probe.timed(slow_source()))
    assert [b["x"] for b in out] == [0, 1, 2, 3]
    snap = probe.snapshot()
    assert snap["input_waits"] == 4
    assert snap["input_wait_s"] == pytest.approx(5.0)
    assert snap["input_wait_max_s"] == pytest.approx(2.0)
    # snapshot(reset=True) cleared the counters
    assert probe.snapshot()["input_waits"] == 0


def test_probe_timed_accepts_plain_iterables():
    probe = StarvationProbe()
    assert [b for b in probe.timed([{"x": 1}, {"x": 2}])] == \
        [{"x": 1}, {"x": 2}]
    assert probe.snapshot()["input_waits"] == 2


def test_probe_through_prefetch_no_background():
    """prefetch_to_device(probe=...) attributes the synchronous host-side
    assembly to consumer wait — the no-thread path every test can rely on
    deterministically."""
    clock = FakeClock()
    probe = StarvationProbe(clock=clock)

    def source():
        for i in range(3):
            clock.tick(1.0)
            yield {"x": np.full((2,), i)}

    batches = list(prefetch_to_device(
        source(), mesh=None, put=lambda b, m: b, background=False,
        probe=probe, buffer_size=2))
    assert len(batches) == 3
    snap = probe.snapshot()
    assert snap["input_waits"] == 3
    assert snap["input_wait_s"] == pytest.approx(3.0)


def test_probe_through_device_batches():
    """The unbuffered feed path (device_batches) times every host-batch
    assembly as consumer wait — same probe, no prefetch ring."""
    from distributeddeeplearningspark_tpu import PartitionedDataset, Session
    from distributeddeeplearningspark_tpu.data.feed import device_batches

    sess = Session.builder.master("local[1]").getOrCreate()
    examples = [{"x": np.float32(i)} for i in range(8)]
    ds = PartitionedDataset.parallelize(examples, 2)
    probe = StarvationProbe()
    batches = list(device_batches(ds, sess.mesh, 4, probe=probe))
    assert len(batches) == 2
    snap = probe.snapshot()
    assert snap["input_waits"] == 2  # one per yielded batch
    assert snap["input_wait_s"] >= 0.0


def test_probe_background_records_depth_and_assembly():
    """The background path samples queue depth per consumer get and times
    producer-side assembly separately from consumer-side waits."""
    probe = StarvationProbe()
    src = ({"x": i} for i in range(5))
    batches = list(prefetch_to_device(
        src, mesh=None, put=lambda b, m: b, background=True, probe=probe))
    assert len(batches) == 5
    snap = probe.snapshot()
    assert snap["input_waits"] == 5
    assert snap["input_assembly_s"] >= 0.0
    assert "prefetch_depth_mean" in snap and "prefetch_depth_min" in snap


# -- Meter / MetricLogger satellites ----------------------------------------


def test_meter_lap_coerces_and_quarantines_nonfinite():
    m = Meter(examples_per_step=8, warmup_laps=0)
    m.start()
    rec1 = m.lap(2, {"loss": np.float32(1.5), "acc": np.array(0.5)})
    assert rec1 == {"loss": 1.5, "acc": 0.5}
    # a NaN lap: returned record keeps the NaN (divergence detection needs
    # it) but the history feeding summary() takes only the finite subset
    rec2 = m.lap(2, {"loss": float("nan"), "acc": 0.75,
                     "junk": "not-a-number",
                     "per_class": np.array([1.0, float("nan")]),
                     "finite_vec": np.array([1.0, 2.0])})
    assert np.isnan(rec2["loss"]) and rec2["acc"] == 0.75
    assert "junk" not in rec2
    # a NaN hiding in a non-scalar metric must stay LOUD in the returned
    # record (divergence detection reads it); an all-finite vector just
    # stays out of the scalar stream
    assert np.isnan(rec2["per_class"])
    assert "finite_vec" not in rec2
    s = m.summary()
    assert s["acc"] == 0.75       # last finite value won
    assert "loss" not in s or np.isfinite(s["loss"])
    assert np.isfinite(s["step_time_ms"])
    assert m.last_lap is not None and m.last_lap[1] == 2


def test_meter_all_nan_lap_keeps_last_finite_summary():
    m = Meter(warmup_laps=0)
    m.start()
    m.lap(1, {"loss": 2.0})
    m.lap(1, {"loss": float("inf")})
    assert m.summary()["loss"] == 2.0


def test_log_value_counters_not_mangled():
    # large counters arrive as floats; round(v, 6) keeps them floats and
    # json renders 1e+16-style — ints must print exactly
    assert _log_value(1.2e16) == 12000000000000000
    assert json.dumps(_log_value(float(10**15 + 1))) == str(10**15 + 1)
    assert _log_value(0.1234567891) == 0.123457
    assert _log_value(float("nan")) != _log_value(1.0)  # NaN passes through
    assert _log_value("label") == "label"


def test_metric_logger_log_formats_counters(caplog):
    mlog = MetricLogger()
    with caplog.at_level(logging.INFO,
                         logger="distributeddeeplearningspark_tpu.metrics"):
        mlog.log(3, {"step": 3.0, "tokens": 1.2e16, "loss": 0.5})
    line = caplog.records[-1].getMessage()
    assert "12000000000000000" in line and "e+16" not in line


def test_metric_logger_event_mirrors_to_telemetry(tmp_path):
    w, _ = _writer(tmp_path)
    mlog = MetricLogger(telemetry=w)
    mlog.event(42, "rollback", to_step=40, window=2)
    w.close()
    events = telemetry.read_events(tmp_path)
    assert len(events) == 1
    e = events[0]
    assert (e["kind"], e["event"], e["step"]) == ("recovery", "rollback", 42)
    assert e["to_step"] == 40 and e["window"] == 2


# -- dlstatus ----------------------------------------------------------------


def _synth_run(tmp_path):
    w, clock = _writer(tmp_path, "p0")
    sup, sclock = _writer(tmp_path, "supervisor")
    sup.attempt("begin", 0)
    clock.t = 1.0
    w.emit("phase", name="run", edge="begin", step=0)
    with w.phase("compile"):
        clock.t = 9.0
    clock.t = 20.0
    w.step_metrics(10, steps=10, lap_s=11.0, metrics={"loss": 0.9},
                   input_wait_s=1.5)
    w.heartbeat(step=10)
    w.recovery(10, "skip", skipped_steps=1)
    sclock.t = 30.0
    sup.attempt("end", 0, returncodes=[-9], duration_s=30.0,
                classification="training-crash", made_progress=True)
    sup.recovery(None, "restart", ordinal=0, classification="training-crash")
    sclock.t = 35.0
    sup.attempt("begin", 1)
    sclock.t = 60.0
    sup.attempt("end", 1, returncodes=[0], duration_s=25.0,
                classification="clean", made_progress=True)
    w.close(), sup.close()


def test_status_report_fields(tmp_path):
    _synth_run(tmp_path)
    rep = status.report(str(tmp_path), now=70.0)
    assert rep["num_events"] == 11
    assert rep["last_step"] == 10
    assert rep["last_heartbeat_age_s"] == pytest.approx(50.0)
    assert [a["ordinal"] for a in rep["attempts"]] == [0, 1]
    assert rep["attempts"][0]["classification"] == "training-crash"
    assert rep["attempts"][1]["classification"] == "clean"
    assert {e["event"] for e in rep["recovery_events"]} == {"skip", "restart"}
    g = rep["goodput"]
    assert g["wall_s"] == 60.0
    assert g["compile_s"] == 8.0
    assert g["restart_overhead_s"] == 5.0
    total = sum(g[k] for k in telemetry.GOODPUT_COMPONENTS)
    assert total == pytest.approx(g["wall_s"], rel=0.05)


def test_status_cli_renders_and_exits_zero(tmp_path, capsys):
    _synth_run(tmp_path)
    assert status.main([str(tmp_path)]) == 0
    out = capsys.readouterr().out
    for needle in ("goodput breakdown", "attempts", "training-crash",
                   "recovery events", "restart", "last heartbeat"):
        assert needle in out, out
    assert status.main([str(tmp_path), "--json"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["goodput"]["goodput_frac"] > 0


def test_status_json_is_strict_json_with_nan_incidents(tmp_path, capsys):
    """Divergence incidents put real NaNs in the stream; --json must still
    emit STRICT JSON (no bare NaN literals jq would choke on)."""
    w, clock = _writer(tmp_path)
    w.step_metrics(4, steps=2, lap_s=1.0, metrics={"loss": float("nan")})
    clock.tick(1.0)
    w.recovery(4, "skip", skipped_steps=1,
               nonfinite={"loss": float("nan"), "grad_norm": float("inf")})
    w.close()
    assert status.main([str(tmp_path), "--json"]) == 0
    out = capsys.readouterr().out
    assert "NaN" not in out and "Infinity" not in out
    rep = json.loads(out)
    assert rep["recovery_events"][0]["nonfinite"]["loss"] is None


def test_status_last_step_is_most_recent_not_max(tmp_path):
    """After a rollback the step counter legitimately rewinds; 'last step'
    must be the most recent position, not the pre-rollback max."""
    w, clock = _writer(tmp_path)
    w.heartbeat(step=20)
    clock.tick(5.0)
    w.step_metrics(12, steps=2, lap_s=1.0, metrics={})  # post-rollback lap
    w.close()
    assert status.report(str(tmp_path))["last_step"] == 12


def test_status_attempts_from_two_supervisor_sessions(tmp_path):
    """A second dlsupervise invocation on the same workdir restarts
    ordinals at 0; the first session's rows must survive in the timeline,
    not be overwritten."""
    sup, clock = _writer(tmp_path, "supervisor")
    for session_cls in ("restore-failure", "clean"):
        sup.attempt("begin", 0)
        clock.tick(10.0)
        sup.attempt("end", 0, returncodes=[1 if session_cls != "clean" else 0],
                    duration_s=10.0, classification=session_cls)
        clock.tick(100.0)
    sup.close()
    rows = status.attempts_from(telemetry.read_events(tmp_path))
    assert [(r["session"], r["ordinal"], r["classification"])
            for r in rows] == [(0, 0, "restore-failure"), (1, 0, "clean")]


def test_status_backoff_only_attempt_says_never_launched(tmp_path, capsys):
    """Supervisor killed during the backoff sleep: the next attempt has a
    backoff record but never began — the report must say so instead of the
    'in-flight' label that sends operators hunting a nonexistent gang."""
    sup, clock = _writer(tmp_path, "supervisor")
    sup.attempt("begin", 0)
    clock.tick(10.0)
    sup.attempt("end", 0, returncodes=[1], duration_s=10.0,
                classification="training-crash")
    sup.attempt("backoff", 1, delay_s=30.0)
    sup.close()  # SIGTERM'd during the sleep; attempt 1 never launched
    assert status.main([str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "never launched" in out
    assert "in-flight" not in out


def test_status_cli_no_telemetry_exits_nonzero(tmp_path, capsys):
    assert status.main([str(tmp_path)]) == 1
    assert "no telemetry" in capsys.readouterr().err


# -- end-to-end: Trainer.fit emits a readable run ---------------------------


@pytest.mark.slow
def test_fit_emits_telemetry_and_dlstatus_reads_it(tmp_path, monkeypatch):
    """The integration contract: a plain fit() with DLS_TELEMETRY_DIR set
    leaves a stream from which dlstatus reports compile/productive time,
    step metrics, heartbeats, and a goodput_frac > 0."""
    import optax

    from distributeddeeplearningspark_tpu import (
        PartitionedDataset,
        Session,
        Trainer,
    )
    from distributeddeeplearningspark_tpu.models import LeNet5
    from distributeddeeplearningspark_tpu.train import losses

    monkeypatch.setenv(telemetry.WORKDIR_ENV, str(tmp_path))
    monkeypatch.delenv("DLS_FAULT", raising=False)
    rng = np.random.default_rng(0)
    examples = [
        {"image": rng.normal(0, 1, (28, 28, 1)).astype(np.float32),
         "label": np.int32(i % 10)}
        for i in range(64)
    ]
    sess = Session.builder.master("local[2]").getOrCreate()
    ds = PartitionedDataset.parallelize(examples, 2).repeat()
    t = Trainer(sess, LeNet5(), losses.softmax_xent, optax.sgd(0.05), seed=0)
    t.fit(ds, batch_size=16, steps=6, log_every=2)

    events = telemetry.read_events(str(tmp_path))
    kinds = {e["kind"] for e in events}
    assert {"phase", "step_metrics", "heartbeat"} <= kinds
    names = {e.get("name") for e in events if e["kind"] == "phase"}
    assert {"run", "compile"} <= names
    laps = [e for e in events if e["kind"] == "step_metrics"]
    assert [e["step"] for e in laps] == [2, 4, 6]
    assert all("input_wait_s" in e for e in laps)
    rep = status.report(str(tmp_path))
    assert rep["goodput"]["goodput_frac"] > 0
    assert rep["goodput"]["compile_s"] > 0
    assert status.main([str(tmp_path)]) == 0
