"""Checkpoint-free live resharding (parallel/live_reshard.py, ISSUE 16).

The acceptance invariants pinned here:

- a live fsdp → tensor move is BITWISE-equal to the checkpoint round trip
  (save on mesh A, restore re-projected onto mesh B) for params AND
  optimizer state, without touching disk and faster than the walk-back;
- peak in-flight transfer bytes stay within ``DLS_RESHARD_MEM_MB`` — the
  engine rounds large leaves instead of materializing them whole;
- a corrupted move raises :class:`ReshardVerifyError` naming the recovery
  action instead of silently training on garbage;
- the drained-host handoff (save → load) round-trips bitwise and refuses
  torn/corrupt manifests with :class:`HandoffError`;
- ``Trainer.apply_plan`` switches plans between steps with a trajectory
  thereafter bitwise-equal to a run restarted under the new plan.
"""

import os

import jax
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from distributeddeeplearningspark_tpu import Checkpointer, telemetry
from distributeddeeplearningspark_tpu.checkpoint import abstract_like
from distributeddeeplearningspark_tpu.models import LeNet5
from distributeddeeplearningspark_tpu.parallel import live_reshard
from distributeddeeplearningspark_tpu.parallel.mesh import MeshSpec
from distributeddeeplearningspark_tpu.parallel.sharding import (
    FSDP,
    ShardingRules,
    state_shardings,
)
from distributeddeeplearningspark_tpu.train import losses, step as step_lib

#: Shards the big LeNet dense kernel's output dim (400x120) over the tensor
#: axis; everything else stays replicated — enough real movement for the
#: layout-cross tests without inventing a model (the later kernels' dims
#: don't divide by 8).
TENSOR_RULES = ShardingRules(rules=((r"Dense_0/kernel", P(None, "tensor")),))


def _host_tree(tree):
    return jax.tree.map(lambda x: np.asarray(jax.device_get(x)), tree)


def _assert_trees_bitwise(a, b):
    fa, fb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(fa) == len(fb)
    for x, y in zip(fa, fb):
        assert np.asarray(x).tobytes() == np.asarray(y).tobytes()


@pytest.fixture()
def meshes(eight_devices):
    return {
        "fsdp": MeshSpec(data=2, fsdp=4).build(),
        "tensor": MeshSpec(data=1, tensor=8).build(),
    }


def _lenet_state(mesh, rules=FSDP, seed=0):
    rng = np.random.default_rng(0)
    batch = {
        "image": rng.normal(0, 1, (8, 28, 28, 1)).astype(np.float32),
        "label": rng.integers(0, 10, (8,)).astype(np.int32),
    }
    return step_lib.init_state(LeNet5(), optax.adamw(1e-3), batch, mesh,
                               rules, seed=seed)


# -- the engine ---------------------------------------------------------------


def test_live_reshard_bitwise_matches_checkpoint_roundtrip(tmp_path, meshes):
    """fsdp → tensor over collectives == save + cross-topology restore,
    byte for byte, params AND optimizer moments — at a fraction of the
    wall and with zero disk traffic."""
    import time

    state, _ = _lenet_state(meshes["fsdp"])
    targets = state_shardings(abstract_like(state), meshes["tensor"],
                              TENSOR_RULES)

    t0 = time.perf_counter()
    with Checkpointer(tmp_path / "ckpt", async_save=False) as ckpt:
        ckpt.save(0, state)
        ckpt.wait()
        via_disk, _ = ckpt.restore(abstract_like(state), shardings=targets)
    ckpt_wall = time.perf_counter() - t0

    live, stats = live_reshard.redistribute(state, targets)

    _assert_trees_bitwise(_host_tree(via_disk), _host_tree(live))
    _assert_trees_bitwise(_host_tree(state), _host_tree(live))
    for arr, sh in zip(jax.tree.leaves(live),
                       jax.tree.leaves(targets,
                                       is_leaf=lambda s: hasattr(s, "spec"))):
        assert arr.sharding.is_equivalent_to(sh, arr.ndim)
    # the dense kernels really crossed layouts (not an all-noop pass)
    assert stats.leaves_moved >= 2 and stats.bytes_moved > 0
    assert stats.verified
    assert stats.peak_inflight_bytes <= stats.mem_budget_bytes
    # checkpoint-free must beat the disk round trip it replaces (the ci.sh
    # smoke pins the "small fraction" ratio; here just strictly faster)
    assert stats.wall_s < ckpt_wall, (stats.wall_s, ckpt_wall)


def test_rounds_bound_peak_inflight_bytes(meshes):
    """A leaf far over budget moves in multiple rounds, never holding more
    than the budget in flight — the 2112.01075 bounded-memory contract."""
    x_host = np.arange(512 * 64, dtype=np.float32).reshape(512, 64)
    x = jax.device_put(x_host,
                       NamedSharding(meshes["fsdp"], P("fsdp", None)))
    target = NamedSharding(meshes["tensor"], P(None, "tensor"))
    out, stats = live_reshard.redistribute(
        {"w": x}, {"w": target}, mem_mb=0.01)  # 10 KB budget vs 128 KB leaf
    assert np.asarray(out["w"]).tobytes() == x_host.tobytes()
    assert out["w"].sharding.is_equivalent_to(target, 2)
    assert stats.rounds > 1
    assert 0 < stats.peak_inflight_bytes <= stats.mem_budget_bytes


def test_memory_budget_env_var(monkeypatch):
    monkeypatch.setenv(live_reshard.RESHARD_MEM_ENV, "3")
    assert live_reshard.memory_budget_bytes() == 3 * 1024 * 1024
    monkeypatch.delenv(live_reshard.RESHARD_MEM_ENV)
    assert (live_reshard.memory_budget_bytes()
            == int(live_reshard.DEFAULT_MEM_MB * 1024 * 1024))
    # explicit argument beats the env
    monkeypatch.setenv(live_reshard.RESHARD_MEM_ENV, "3")
    assert live_reshard.memory_budget_bytes(1.0) == 1024 * 1024
    with pytest.raises(ValueError):
        live_reshard.memory_budget_bytes(-1.0)


def test_equivalent_layout_is_noop(meshes):
    x = jax.device_put(np.ones((64, 16), np.float32),
                       NamedSharding(meshes["fsdp"], P("fsdp", None)))
    out, stats = live_reshard.redistribute(
        {"w": x}, {"w": NamedSharding(meshes["fsdp"], P("fsdp", None))})
    assert out["w"] is x
    assert stats.leaves_moved == 0 and stats.bytes_moved == 0
    assert stats.bytes_total == x.nbytes  # accounted, just not moved


def test_verify_catches_corrupted_move(meshes, monkeypatch):
    """A digest mismatch across the move is a typed refusal naming the
    recovery action — never a silent continue."""
    real = live_reshard._move_leaf

    def corrupt(x, target, chunks, ledger):
        out, _ = real(x, target, chunks, ledger)
        return out, "0" * 32  # claim a digest the re-read cannot match

    monkeypatch.setattr(live_reshard, "_move_leaf", corrupt)
    x = jax.device_put(np.ones((64, 16), np.float32),
                       NamedSharding(meshes["fsdp"], P("fsdp", None)))
    with pytest.raises(live_reshard.ReshardVerifyError,
                       match="last verified checkpoint"):
        live_reshard.redistribute(
            {"w": x}, {"w": NamedSharding(meshes["tensor"],
                                          P(None, "tensor"))})


def test_none_target_leaves_leaf_alone(meshes):
    """None in the shardings tree means 'do not touch' — including python
    scalars a TrainState may carry."""
    x = jax.device_put(np.ones((8, 8), np.float32),
                       NamedSharding(meshes["fsdp"], P()))
    tree = {"w": x, "count": 5}
    out, stats = live_reshard.redistribute(
        tree, {"w": NamedSharding(meshes["tensor"], P()), "count": None})
    assert out["count"] == 5
    assert stats.leaves == 2


def test_chunk_rows_shapes():
    # 0-d: one degenerate chunk; zero rows: none; otherwise row ranges
    assert live_reshard.chunk_rows((), 4, 1024) == ((0, 1),)
    assert live_reshard.chunk_rows((0, 8), 4, 1024) == ()
    chunks = live_reshard.chunk_rows((10, 100), 4, 1200)  # 3 rows/chunk
    assert chunks[0] == (0, 3) and chunks[-1][1] == 10
    assert all(hi > lo for lo, hi in chunks)
    # a single over-budget row still moves (honest peak, not a deadlock)
    assert live_reshard.chunk_rows((4, 1000), 4, 100) == (
        (0, 1), (1, 2), (2, 3), (3, 4))


# -- the handoff --------------------------------------------------------------


def test_handoff_round_trip_bitwise(tmp_path, meshes):
    state, shardings = _lenet_state(meshes["fsdp"])
    assert not live_reshard.has_handoff(tmp_path)
    live_reshard.save_handoff(tmp_path, 7, state,
                              data_state={"examples_seen": 112,
                                          "batch_size": 16})
    assert live_reshard.has_handoff(tmp_path)
    peek = live_reshard.peek_handoff(tmp_path)
    assert peek["step"] == 7 and peek["data_state"]["examples_seen"] == 112

    targets = state_shardings(abstract_like(state), meshes["tensor"],
                              TENSOR_RULES)
    loaded, manifest = live_reshard.load_handoff(tmp_path, state, targets)
    _assert_trees_bitwise(_host_tree(state), _host_tree(loaded))
    assert manifest["step"] == 7
    live_reshard.clear_handoff(tmp_path)
    assert not live_reshard.has_handoff(tmp_path)


def test_handoff_rejects_corrupt_leaf(tmp_path, meshes):
    state, shardings = _lenet_state(meshes["fsdp"])
    live_reshard.save_handoff(tmp_path, 3, state)
    d = live_reshard.handoff_dir(tmp_path)
    victim = sorted(f for f in os.listdir(d) if f.endswith(".npy"))[0]
    arr = np.load(os.path.join(d, victim))
    np.save(os.path.join(d, victim), arr + 1.0)
    with pytest.raises(live_reshard.HandoffError, match="checkpoint"):
        live_reshard.load_handoff(tmp_path, state, shardings)


def test_handoff_rejects_missing_and_extra_leaves(tmp_path, meshes):
    import json

    state, shardings = _lenet_state(meshes["fsdp"])
    live_reshard.save_handoff(tmp_path, 3, state)
    d = live_reshard.handoff_dir(tmp_path)
    with open(os.path.join(d, live_reshard.HANDOFF_MANIFEST)) as f:
        manifest = json.load(f)
    manifest["leaves"] = manifest["leaves"][:-1]
    with open(os.path.join(d, live_reshard.HANDOFF_MANIFEST), "w") as f:
        json.dump(manifest, f)
    with pytest.raises(live_reshard.HandoffError, match="checkpoint"):
        live_reshard.load_handoff(tmp_path, state, shardings)


def test_tree_digest_orders_and_discriminates():
    a = {"w": np.ones((4, 4), np.float32), "b": np.zeros(3, np.float32)}
    b = {"w": np.ones((4, 4), np.float32), "b": np.zeros(3, np.float32)}
    assert live_reshard.tree_digest(a) == live_reshard.tree_digest(b)
    b["w"] = b["w"] + 1
    assert live_reshard.tree_digest(a) != live_reshard.tree_digest(b)


# -- telemetry ----------------------------------------------------------------


def test_emit_reshard_event_fields(tmp_path, meshes):
    telemetry.configure(tmp_path)
    try:
        x = jax.device_put(np.ones((64, 16), np.float32),
                           NamedSharding(meshes["fsdp"], P("fsdp", None)))
        _, stats = live_reshard.redistribute(
            {"w": x}, {"w": NamedSharding(meshes["tensor"],
                                          P(None, "tensor"))})
        live_reshard.emit_reshard_event(stats, step=12, reason="apply-plan")
        telemetry.get().close()
        events = [e for e in telemetry.read_events(tmp_path)
                  if e.get("kind") == "recovery"
                  and e.get("event") == "reshard"]
        assert len(events) == 1
        e = events[0]
        assert e["transport"] == "collectives" and e["walk_back"] is False
        assert e["step"] == 12 and e["reason"] == "apply-plan"
        assert e["bytes_moved"] == stats.bytes_moved
        assert e["rounds"] == stats.rounds
        assert e["peak_inflight_bytes"] == stats.peak_inflight_bytes
        assert e["mem_budget_mb"] == pytest.approx(
            stats.mem_budget_bytes / (1024 * 1024))
        assert e["leaves_moved"] == stats.leaves_moved and e["verified"]
    finally:
        telemetry.reset()


def test_dlstatus_renders_reshard_and_graceful_shutdown(tmp_path, meshes):
    """The status satellite: reshard events get a dedicated block (live vs
    walk-back split), graceful shutdowns a dedicated attempt line, and
    --json a structured reshard summary."""
    from distributeddeeplearningspark_tpu import status

    telemetry.configure(tmp_path)
    try:
        x = jax.device_put(np.ones((64, 16), np.float32),
                           NamedSharding(meshes["fsdp"], P("fsdp", None)))
        _, stats = live_reshard.redistribute(
            {"w": x}, {"w": NamedSharding(meshes["tensor"],
                                          P(None, "tensor"))})
        live_reshard.emit_reshard_event(stats, step=9,
                                        reason="preemption-drain")
        tele = telemetry.get()
        tele.recovery(9, "graceful_shutdown", ordinal=0, dead_host=1,
                      drained=True)
        tele.emit("attempt", edge="begin", ordinal=0, num_processes=2)
        tele.emit("attempt", edge="end", ordinal=0, returncodes=[0, 0],
                  classification="graceful-shutdown", duration_s=1.0)
        tele.close()

        rep = status.report(str(tmp_path))
        rs = rep["reshard"]
        assert rs["moves"] == 1 and rs["live_moves"] == 1
        assert rs["walk_back_moves"] == 0
        assert rs["by_transport"]["collectives"] == 1
        assert rs["last"]["transport"] == "collectives"
        assert rs["last"]["step"] == 9
        assert rs["bytes_moved"] == stats.bytes_moved

        rendered = status.render(rep)
        assert "resharding" in rendered
        assert "checkpoint-free (live)" in rendered
        assert "graceful shutdown: host 1" in rendered
    finally:
        telemetry.reset()


# -- Trainer.apply_plan -------------------------------------------------------


def test_trainer_apply_plan_trajectory_bitwise(tmp_path):
    """Switching plans LIVE between steps must land exactly where a run
    restarted under the new plan from the same checkpoint lands — the plan
    sweep's winner can be applied without a restart."""
    import dataclasses

    from distributeddeeplearningspark_tpu import (
        PartitionedDataset,
        Session,
        Trainer,
    )
    from distributeddeeplearningspark_tpu.parallel.plan import (
        DP,
        Plan,
        zero_plan,
    )

    rng = np.random.default_rng(5)
    examples = [
        {"image": rng.normal(0, 1, (28, 28, 1)).astype(np.float32),
         "label": np.int32(i % 10)}
        for i in range(128)
    ]
    batch_size = 16
    plan_a = Plan(name="dp", donate_state=False)
    plan_b = dataclasses.replace(
        zero_plan(DP, axes=("data",), name="dp+zero"),
        zero_min_size=64, donate_state=False)

    def make_trainer(plan, ckpt):
        sess = Session.builder.master("local[2]").getOrCreate()
        ds = PartitionedDataset.parallelize(examples, 2).repeat()
        t = Trainer(sess, LeNet5(), losses.softmax_xent,
                    optax.sgd(0.1, momentum=0.9), plan=plan,
                    checkpointer=ckpt, seed=11)
        return t, ds

    with Checkpointer(tmp_path / "ck", async_save=False) as ck:
        # live run: 3 steps under plan A, switch in place, 3 more under B
        t1, ds = make_trainer(plan_a, ck)
        t1.fit(ds, batch_size=batch_size, steps=3, checkpoint_every=3,
               log_every=100)
        stats = t1.apply_plan(plan_b)
        assert stats.verified
        assert t1.plan.name == "dp+zero"
        assert t1._train_step.plan_name == "dp+zero"
        state_live, _ = t1.fit(ds, batch_size=batch_size, steps=6,
                               log_every=100,
                               data_state={"examples_seen": 3 * batch_size,
                                           "batch_size": batch_size})
        Session._active and Session._active.stop()

        # pinned run: fresh process under plan B from the same checkpoint
        t2, ds = make_trainer(plan_b, ck)
        t2.init(t2._sample_batch(ds, batch_size))
        _, data_state = t2.restore()
        assert int(jax.device_get(t2.state.step)) == 3
        state_pin, _ = t2.fit(ds, batch_size=batch_size, steps=6,
                              log_every=100, data_state=data_state)

    _assert_trees_bitwise(_host_tree(state_live.params),
                          _host_tree(state_pin.params))
    _assert_trees_bitwise(_host_tree(state_live.opt_state),
                          _host_tree(state_pin.opt_state))


def test_apply_plan_requires_init():
    from distributeddeeplearningspark_tpu import Session, Trainer
    from distributeddeeplearningspark_tpu.parallel.plan import Plan

    sess = Session.builder.master("local[2]").getOrCreate()
    t = Trainer(sess, LeNet5(), losses.softmax_xent, optax.sgd(0.1))
    with pytest.raises(RuntimeError, match="init"):
        t.apply_plan(Plan(name="dp"))


def test_apply_plan_rejects_shard_map_style():
    from distributeddeeplearningspark_tpu import Session, Trainer
    from distributeddeeplearningspark_tpu.parallel.plan import (
        Plan,
        PlanValidationError,
    )

    sess = Session.builder.master("local[2]").getOrCreate()
    t = Trainer(sess, LeNet5(), losses.softmax_xent, optax.sgd(0.1))
    t.state = object()  # get past the init guard to the style guard
    with pytest.raises(PlanValidationError, match="style='jit'"):
        t.apply_plan(Plan(name="mapstyle", style="shard_map"))
