"""Profiler integration: trace capture window, annotations, XLA dump flag."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributeddeeplearningspark_tpu import PartitionedDataset, Session, Trainer
from distributeddeeplearningspark_tpu.models import LeNet5
from distributeddeeplearningspark_tpu.train import losses
from distributeddeeplearningspark_tpu.utils import profiling


def test_trace_context_manager_writes_xplane(tmp_path):
    d = str(tmp_path / "prof")
    with profiling.trace(d):
        with profiling.annotate("compute"):
            jax.block_until_ready(jnp.dot(jnp.ones((64, 64)), jnp.ones((64, 64))))
    assert profiling.trace_files(d), "no .xplane.pb produced by trace capture"


def test_step_profiler_window(tmp_path):
    d = str(tmp_path / "prof")
    prof = profiling.StepProfiler(profiling.ProfileSpec(d, start_step=2, num_steps=2))
    for step in range(6):
        prof.observe(step)
        with profiling.step_annotation(step):
            jax.block_until_ready(jnp.ones((8,)) * step)
    prof.stop()
    assert profiling.trace_files(d)
    # idempotent: stop again is a no-op, disabled profiler observes freely
    prof.stop()
    profiling.StepProfiler(None).observe(0)


def test_fit_with_profile_and_flops(tmp_path):
    rng = np.random.default_rng(0)
    examples = [
        {"image": rng.normal(0, 1, (28, 28, 1)).astype(np.float32),
         "label": np.int32(i % 10)}
        for i in range(64)
    ]
    spark = Session.builder.master("local[2]").getOrCreate()
    ds = PartitionedDataset.parallelize(examples, 2).repeat()
    trainer = Trainer(spark, LeNet5(), losses.softmax_xent, optax.sgd(0.01))
    prof_dir = str(tmp_path / "prof")
    state, summary = trainer.fit(
        ds, batch_size=16, steps=8, log_every=4,
        profile=profiling.ProfileSpec(prof_dir, start_step=4, num_steps=2),
        measure_flops=True,
    )
    assert profiling.trace_files(prof_dir)
    # CPU backend supports cost analysis, so MFU pieces must be present
    assert "step_time_ms" in summary


def test_enable_xla_dump_appends_flag(tmp_path, monkeypatch):
    monkeypatch.setenv("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    profiling.enable_xla_dump(str(tmp_path / "dump"))
    flags = os.environ["XLA_FLAGS"]
    assert "--xla_dump_to=" in flags and "device_count=8" in flags


def test_step_profiler_offset_is_resume_relative(tmp_path):
    d = str(tmp_path / "prof")
    prof = profiling.StepProfiler(
        profiling.ProfileSpec(d, start_step=2, num_steps=1), start_offset=1000
    )
    for step in range(1000, 1002):  # before window: 1000+2
        prof.observe(step)
        assert not prof._active
    prof.observe(1002)
    assert prof._active
    prof.stop()
    assert profiling.trace_files(d)


def test_fit_crash_mid_window_still_flushes_trace(tmp_path):
    rng = np.random.default_rng(0)
    examples = [
        {"image": rng.normal(0, 1, (28, 28, 1)).astype(np.float32),
         "label": np.int32(i % 10)}
        for i in range(64)
    ]
    spark = Session.builder.master("local[2]").getOrCreate()
    ds = PartitionedDataset.parallelize(examples, 2).repeat()
    trainer = Trainer(spark, LeNet5(), losses.softmax_xent, optax.sgd(0.01))

    def boom(step, _):
        if step >= 3:
            raise RuntimeError("injected")

    prof_dir = str(tmp_path / "prof")
    with pytest.raises(RuntimeError, match="injected"):
        trainer.fit(ds, batch_size=16, steps=10, log_every=100,
                    profile=profiling.ProfileSpec(prof_dir, start_step=1, num_steps=8),
                    callbacks=[boom])
    assert profiling.trace_files(prof_dir), "crashed run must still flush its trace"
    # profiler fully stopped: a later fit with profiling must not collide
    state, _ = trainer.fit(ds, batch_size=16, steps=6, log_every=100,
                           profile=profiling.ProfileSpec(str(tmp_path / "p2"),
                                                         start_step=1, num_steps=2))
    assert profiling.trace_files(str(tmp_path / "p2"))


def test_op_breakdown_parses_cpu_trace(tmp_path):
    """op_breakdown must read a real capture without TensorBoard's converter:
    aggregate per-op times from the busiest line and report a sane budget
    (CPU traces carry host/TFRT lines rather than a TPU 'XLA Ops' line —
    the fallback path; the device path was exercised on the real chip, see
    BASELINE.md r2 roofline entry)."""
    d = str(tmp_path / "prof")
    with profiling.trace(d):
        x = jnp.ones((128, 128))
        for _ in range(3):
            x = jnp.dot(x, x)
        jax.block_until_ready(x)
    rec = profiling.op_breakdown(d, top=10)
    assert "error" not in rec, rec
    assert rec["event_count"] > 0
    assert rec["ops"] and len(rec["ops"]) <= 10
    total_pct = sum(o["pct"] for o in rec["ops"])
    assert 0 < total_pct <= 100.5, rec["ops"]
    assert rec["ops"] == sorted(rec["ops"], key=lambda o: -o["ms"])


def test_op_breakdown_missing_dir(tmp_path):
    rec = profiling.op_breakdown(str(tmp_path / "nothing_here"))
    assert "error" in rec


def test_profile_cli_prints_budget(tmp_path, capsys):
    d = str(tmp_path / "prof")
    with profiling.trace(d):
        jax.block_until_ready(jnp.dot(jnp.ones((64, 64)), jnp.ones((64, 64))))
    assert profiling.profile_cli([d, "--top", "3"]) == 0
    out = capsys.readouterr().out
    assert "total" in out and "%" in out
    assert profiling.profile_cli([str(tmp_path / "missing"), "--json"]) == 1
