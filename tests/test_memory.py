"""Analytic HBM budget (VERDICT r2 next-#3): the formula must agree with
the real model's parameter tree exactly, and the 7B report must carry the
v4-32 fit evidence the config-5 contract names."""

import jax
import numpy as np

from distributeddeeplearningspark_tpu.models import LlamaConfig, LlamaForCausalLM
from distributeddeeplearningspark_tpu.utils.memory import (
    GiB,
    llama_memory_report,
    llama_param_count,
)


def _real_param_count(cfg):
    model = LlamaForCausalLM(cfg)
    batch = {"input_ids": np.zeros((1, 16), np.int32)}
    variables = model.init(jax.random.PRNGKey(0), batch, train=False)
    total = lora = 0
    for path, leaf in jax.tree_util.tree_leaves_with_path(variables["params"]):
        n = int(np.size(leaf))
        total += n
        if "lora" in jax.tree_util.keystr(path):
            lora += n
    return {"base": total - lora, "lora": lora}


def test_param_count_matches_model_exactly():
    for cfg in (LlamaConfig.tiny(), LlamaConfig.tiny(lora_rank=4),
                LlamaConfig.tiny(num_kv_heads=1, lora_rank=2,
                                 lora_targets=("wq", "wk", "wv", "wo")),
                # MoE: router + E-wide expert bank replace the dense FFN —
                # the r4 review caught the budget omitting the bank (the
                # dominant HBM term for the on-chip MoE queue items)
                LlamaConfig.tiny(moe_experts=4, intermediate_size=64),
                LlamaConfig.tiny(moe_experts=2, moe_top_k=1, lora_rank=2,
                                 intermediate_size=64)):
        want = _real_param_count(cfg)
        got = llama_param_count(cfg)
        assert got == want, (got, want, cfg)


def test_7b_count_is_llama2_7b():
    counts = llama_param_count(LlamaConfig.llama2_7b())
    # Llama-2 7B: 6.74B params (±: exact value 6738415616 + tied head extra —
    # our head is untied, so ~+131M)
    assert 6.6e9 < counts["base"] < 7.0e9, counts


def test_7b_v4_32_fsdp_layout_fits():
    """The contract layout: 7B LoRA on v4-32 (16 chips, 32 GiB HBM each),
    FSDP=8 x data=2, b=8 global (b=4/data shard... report uses global)."""
    cfg = LlamaConfig.llama2_7b(lora_rank=16, fused_head_loss=True,
                                remat_policy=None)
    rep = llama_memory_report(
        cfg, batch=8, seq=4096, mesh_shape={"data": 2, "fsdp": 8},
        hbm_per_chip_gib=32)
    d = rep.to_dict()
    assert rep.fits(32 * GiB), d
    # sanity: base params dominate and shard 8x
    assert 1.5 < d["per_chip_gib"]["base_params_bf16"] < 2.0, d


def test_7b_single_chip_borderline_documented():
    """Single dev chip (v5e, 16 GiB): bf16 base alone is ~12.6 GiB — the
    report must show b=1 s=1024 with remat None + fused CE as borderline,
    NOT comfortably fitting (that's why the real attempt is evidence either
    way). Window tightened from the r3 ±22% to the r4 chip-window
    measurement (VERDICT r3 next-#7): the compiler's memory_analysis()
    reported 14.68 GiB live for this exact shape and the analytic total
    landed −5.7% under it (13.84; the 0.9b shape validated at +2.1%), so
    the model must stay within ±10% of that measured anchor."""
    cfg = LlamaConfig.llama2_7b(lora_rank=16, fused_head_loss=True,
                                remat_policy=None)
    rep = llama_memory_report(cfg, batch=1, seq=1024, mesh_shape={},
                              hbm_per_chip_gib=16)
    total = rep.total_bytes / GiB
    measured_compiled_live = 14.678   # CHIP_QUEUE_r04.jsonl memval, 07-31
    assert abs(total - measured_compiled_live) / measured_compiled_live < 0.10, \
        rep.to_dict()


def test_report_scales_with_knobs():
    cfg = LlamaConfig.llama2_7b(lora_rank=16)
    base = llama_memory_report(cfg, batch=4, seq=2048, mesh_shape={})
    fsdp = llama_memory_report(cfg, batch=4, seq=2048,
                               mesh_shape={"fsdp": 8})
    assert (fsdp.components["base_params_bf16"]
            == base.components["base_params_bf16"] / 8)
    dots = llama_memory_report(
        LlamaConfig.llama2_7b(lora_rank=16, remat_policy="dots"),
        batch=4, seq=2048, mesh_shape={})
    assert dots.components["activations_bf16"] > base.components["activations_bf16"]
    unfused = llama_memory_report(cfg, batch=4, seq=2048, mesh_shape={})
    fused = llama_memory_report(
        LlamaConfig.llama2_7b(lora_rank=16, fused_head_loss=True),
        batch=4, seq=2048, mesh_shape={})
    assert fused.components["loss_head"] < unfused.components["loss_head"] / 4


def test_13b_count_and_v4_32_fsdp_layout_fits():
    """Llama-2 13B (the config-5 pod-scale step-up, MHA geometry): exact
    param count matches the published 13.0B (+131M untied head), and the
    LoRA fine-tune budget sits comfortably inside a v4-32 fsdp=8 layout —
    measured 10.4 GiB/chip of 32 (same analytic model the r4 chip window
    validated within +2.1%/-5.7% of compiled.memory_analysis())."""
    counts = llama_param_count(LlamaConfig.llama2_13b())
    assert 12.9e9 < counts["base"] < 13.2e9, counts
    cfg = LlamaConfig.llama2_13b(lora_rank=16, fused_head_loss=True,
                                 remat_policy=None)
    # bf16 base storage must kick in exactly as in llama2_7b
    import jax.numpy as jnp
    assert cfg.param_dtype == jnp.bfloat16
    rep = llama_memory_report(
        cfg, batch=8, seq=4096, mesh_shape={"data": 2, "fsdp": 8},
        hbm_per_chip_gib=32)
    d = rep.to_dict()
    assert rep.fits(32 * GiB), d
    # base params shard 8x: 13.0B * 2B / 8 = ~3.0 GiB/chip
    assert 2.8 < d["per_chip_gib"]["base_params_bf16"] < 3.3, d


def test_7b_fsdp_layout_lowers_abstractly(eight_devices):
    """The REAL 7B geometry traces + SPMD-partitions on a data=1 x fsdp=8
    mesh without materializing a single weight (jax.eval_shape init +
    jit.lower on ShapeDtypeStructs) — the AOT half of VERDICT r2 next-#3's
    evidence: the program exists at scale; the byte budget says it fits."""
    import jax.numpy as jnp
    import optax

    from distributeddeeplearningspark_tpu.models import (
        llama_rules, lora_trainable)
    from distributeddeeplearningspark_tpu.parallel.mesh import MeshSpec
    from distributeddeeplearningspark_tpu.train import (
        losses, optim, step as step_lib)

    cfg = LlamaConfig.llama2_7b(lora_rank=16, dtype=jnp.bfloat16,
                                max_position=1024, remat_policy=None,
                                fused_head_loss=True)
    model = LlamaForCausalLM(cfg)
    mesh = MeshSpec(data=1, fsdp=8).build(eight_devices)
    rules = llama_rules(cfg)
    tx = optim.masked(optax.adamw(1e-4), lora_trainable)
    batch = {"input_ids": jax.ShapeDtypeStruct((8, 1024), jnp.int32),
             "loss_mask": jax.ShapeDtypeStruct((8, 1024), jnp.float32)}

    def init_fn(rng):
        model_rng, state_rng = jax.random.split(rng)
        variables = dict(model.init(
            {"params": model_rng, "dropout": model_rng},
            {"input_ids": jnp.zeros((8, 1024), jnp.int32)}, train=False))
        params = variables.pop("params")
        return step_lib.TrainState.create(
            params=params, opt_state=tx.init(params), mutable=variables,
            rng=state_rng, embed_state={})

    abstract = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
    shardings = step_lib.state_shardings(abstract, mesh, rules)
    # base kernels must actually shard over fsdp at this size
    wq_sh = shardings.params["layers"]["attention"]["wq"]["base"]["kernel"]
    assert "fsdp" in str(wq_sh.spec), wq_sh
    jitted = step_lib.jit_train_step(
        step_lib.make_train_step(model.apply, tx,
                                 losses.causal_lm_fused,
                                 trainable=lora_trainable),
        mesh, shardings)
    lowered = jitted.lower(abstract, batch)
    text = lowered.as_text()
    assert "stablehlo" in text.split("\n", 2)[0] or len(text) > 1000
