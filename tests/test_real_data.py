"""Real-dataset ingestion (VERDICT r1 missing-#3): ImageNet folder with the
native JPEG decoder, Criteo TSV, Wikipedia dumps.

Fixtures are generated with independent encoders (PIL JPEG, hand-written XML)
so the parity is against a second implementation, not our own round-trip.
"""

import io
import os

import numpy as np
import pytest

from distributeddeeplearningspark_tpu.data import vision
from distributeddeeplearningspark_tpu.data.sources import (
    CRITEO_DENSE,
    CRITEO_SPARSE,
    criteo_tsv,
    imagenet_folder,
)
from distributeddeeplearningspark_tpu.data.text import clean_wikitext, wikipedia_dump
from distributeddeeplearningspark_tpu.utils import native

PIL = pytest.importorskip("PIL.Image")


def _jpeg_bytes(arr: np.ndarray, *, subsampling=0, quality=90, **kw) -> bytes:
    img = PIL.fromarray(arr if arr.ndim == 3 else arr, "RGB" if arr.ndim == 3 else "L")
    buf = io.BytesIO()
    img.save(buf, format="JPEG", quality=quality, subsampling=subsampling, **kw)
    return buf.getvalue()


def _smooth(h, w, c=3, seed=0):
    """Genuinely smooth content (gaussian-filtered noise): chroma-upsampling
    differences between decoders vanish away from hard edges."""
    from scipy.ndimage import gaussian_filter

    rng = np.random.default_rng(seed)
    base = rng.normal(128, 60, (h, w, c))
    sm = gaussian_filter(base, sigma=(3, 3, 0))
    return np.clip(sm, 0, 255).astype(np.uint8)


def _pil_decode(data: bytes) -> np.ndarray:
    arr = np.asarray(PIL.open(io.BytesIO(data)).convert("RGB"))
    return arr


# -- native JPEG decoder -----------------------------------------------------

def test_native_jpeg_444_matches_pil_closely():
    data = _jpeg_bytes(_smooth(96, 128), subsampling=0)
    got = native.jpeg_decode(data)
    assert got is not None, "native library failed to build"
    want = _pil_decode(data)
    diff = np.abs(got.astype(int) - want.astype(int))
    assert got.shape == want.shape
    assert diff.max() <= 4, f"max diff {diff.max()}"  # IDCT rounding only


@pytest.mark.parametrize("subsampling,hw", [(2, (120, 200)), (1, (64, 96)),
                                            (2, (251, 133))])
def test_native_jpeg_subsampled_close_to_pil(subsampling, hw):
    data = _jpeg_bytes(_smooth(*hw, seed=subsampling), subsampling=subsampling)
    got = native.jpeg_decode(data)
    want = _pil_decode(data)
    diff = np.abs(got.astype(int) - want.astype(int))
    assert got.shape == want.shape
    # box vs triangle chroma upsampling differs at edges; content is smooth
    assert diff.mean() < 1.5 and diff.max() <= 48, (diff.mean(), diff.max())


def test_native_jpeg_grayscale():
    arr = _smooth(80, 60, c=1, seed=7)[..., 0]
    data = _jpeg_bytes(arr)
    got = native.jpeg_decode(data)
    assert got.shape == (80, 60, 1)
    want = np.asarray(PIL.open(io.BytesIO(data)).convert("L"))[..., None]
    assert np.abs(got.astype(int) - want.astype(int)).max() <= 2


def test_native_jpeg_progressive_rejected_and_vision_falls_back():
    arr = _smooth(48, 48, seed=3)
    data = _jpeg_bytes(arr, progressive=True)
    with pytest.raises(native.JpegUnsupported):
        native.jpeg_decode(data)
    # the public decode path falls back to PIL transparently
    out = vision.decode_jpeg(data)
    np.testing.assert_array_equal(out, _pil_decode(data))


def test_native_jpeg_malformed_raises():
    with pytest.raises(ValueError):
        native.jpeg_decode(b"\xff\xd8\xff\xe0not a real jpeg at all")


def test_native_jpeg_batch_matches_single():
    datas = [_jpeg_bytes(_smooth(64 + 8 * i, 80, seed=i)) for i in range(5)]
    batch = native.jpeg_decode_batch(datas)
    assert batch is not None
    for d, got in zip(datas, batch):
        np.testing.assert_array_equal(got, native.jpeg_decode(d))


# -- ImageNet folder ---------------------------------------------------------

def _make_imagenet(tmp_path, n_per_class=3):
    for ci, cname in enumerate(["n01440764", "n01443537"]):
        d = tmp_path / cname
        d.mkdir()
        for j in range(n_per_class):
            arr = _smooth(72 + 8 * j, 96, seed=ci * 10 + j)
            (d / f"{cname}_{j}.JPEG").write_bytes(_jpeg_bytes(arr))
    return tmp_path


def test_imagenet_folder_loads_and_labels(tmp_path):
    root = _make_imagenet(tmp_path)
    ds = imagenet_folder(str(root), num_partitions=2)
    examples = ds.collect()
    assert len(examples) == 6
    labels = sorted(int(e["label"]) for e in examples)
    assert labels == [0, 0, 0, 1, 1, 1]  # sorted-dir-order convention
    for e in examples:
        assert e["image"].dtype == np.uint8 and e["image"].shape[-1] == 3


def test_imagenet_folder_trains_through_pipeline(tmp_path):
    from distributeddeeplearningspark_tpu.data.feed import host_batches

    root = _make_imagenet(tmp_path)
    ds = vision.imagenet_train(imagenet_folder(str(root), num_partitions=2),
                               size=32, seed=0)
    batches = list(host_batches(ds, 4, num_shards=2))
    assert batches and batches[0]["image"].shape == (4, 32, 32, 3)
    assert batches[0]["image"].dtype == np.float32


def test_imagenet_folder_raw_bytes_mode(tmp_path):
    root = _make_imagenet(tmp_path)
    ds = imagenet_folder(str(root), num_partitions=1, decode=False)
    e = ds.take(1)[0]
    assert isinstance(e["jpeg"], bytes) and e["jpeg"][:2] == b"\xff\xd8"


def test_imagenet_folder_missing_dir_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        imagenet_folder(str(tmp_path / "nope"))
    (tmp_path / "empty").mkdir()
    with pytest.raises(FileNotFoundError):
        imagenet_folder(str(tmp_path / "empty"))


# -- Criteo TSV --------------------------------------------------------------

def _criteo_line(rng, missing=False):
    label = rng.integers(0, 2)
    dense = ["" if (missing and i == 3) else str(rng.integers(0, 1000))
             for i in range(CRITEO_DENSE)]
    cats = ["" if (missing and i == 5) else format(rng.integers(0, 1 << 32), "08x")
            for i in range(CRITEO_SPARSE)]
    return "\t".join([str(label), *dense, *cats])


def test_criteo_tsv_parses_schema(tmp_path):
    rng = np.random.default_rng(0)
    lines = [_criteo_line(rng, missing=(i % 3 == 0)) for i in range(50)]
    f = tmp_path / "day_0.txt"
    f.write_text("\n".join(lines) + "\n")
    ds = criteo_tsv(str(f), vocab_sizes=(1000,) * CRITEO_SPARSE)
    examples = ds.collect()
    assert len(examples) == 50
    e = examples[0]
    assert e["dense"].shape == (CRITEO_DENSE,) and e["dense"].dtype == np.float32
    assert e["sparse"].shape == (CRITEO_SPARSE,) and e["sparse"].dtype == np.int32
    assert all(0 <= s < 1000 for s in e["sparse"])
    assert int(e["label"]) in (0, 1)
    # missing dense → 0.0; missing categorical → bucket 0
    miss = examples[0]
    assert miss["dense"][3] == 0.0 and miss["sparse"][5] == 0


def test_criteo_tsv_byte_splits_cover_every_line_once(tmp_path):
    """A >1MB file splits by byte ranges; the union of partitions must be
    exactly the file's lines (the Spark TextInputFormat contract)."""
    rng = np.random.default_rng(1)
    n = 12000
    f = tmp_path / "big.txt"
    f.write_text("\n".join(_criteo_line(rng) for _ in range(n)) + "\n")
    assert f.stat().st_size > (1 << 20)
    ds = criteo_tsv(str(f), num_partitions=4, vocab_sizes=(1 << 16,) * CRITEO_SPARSE)
    assert ds.num_partitions >= 4
    total = sum(len(list(ds.iter_partition(i))) for i in range(ds.num_partitions))
    assert total == n


def test_criteo_tsv_trains_dlrm_batch(tmp_path, eight_devices):
    import jax
    import optax

    from distributeddeeplearningspark_tpu.data.feed import put_global, stack_examples
    from distributeddeeplearningspark_tpu.models import DLRM
    from distributeddeeplearningspark_tpu.parallel.mesh import MeshSpec
    from distributeddeeplearningspark_tpu.parallel.sharding import REPLICATED
    from distributeddeeplearningspark_tpu.train import losses, step as step_lib

    rng = np.random.default_rng(2)
    f = tmp_path / "c.txt"
    f.write_text("\n".join(_criteo_line(rng) for _ in range(16)) + "\n")
    vocab = (64,) * CRITEO_SPARSE
    ds = criteo_tsv(str(f), vocab_sizes=vocab)
    batch = stack_examples(ds.take(8))
    mesh = MeshSpec(data=2).build(eight_devices[:2])
    model = DLRM(vocab_sizes=vocab, embed_dim=8, bottom_mlp=(16, 8), top_mlp=(8, 1))
    state, sh = step_lib.init_state(model, optax.sgd(0.1), batch, mesh, REPLICATED)
    step = step_lib.jit_train_step(
        step_lib.make_train_step(model.apply, optax.sgd(0.1), losses.binary_xent),
        mesh, sh)
    _, metrics = step(state, put_global(batch, mesh))
    assert np.isfinite(float(jax.device_get(metrics["loss"])))


# -- Wikipedia dumps ---------------------------------------------------------

_XML_DUMP = """<mediawiki xmlns="http://www.mediawiki.org/xml/export-0.10/">
  <page>
    <title>Alpha</title>
    <revision><text>'''Alpha''' is the [[first letter|first]] letter of the
[[Greek alphabet]].{{Infobox|foo=bar}} It has been used since the
[[8th century BC]] in ancient texts.&lt;ref&gt;cite&lt;/ref&gt; More prose
follows here so the document clears the minimum length filter easily.</text></revision>
  </page>
  <page>
    <title>Redirect me</title>
    <redirect title="Alpha"/>
    <revision><text>#REDIRECT [[Alpha]]</text></revision>
  </page>
  <page>
    <title>Beta</title>
    <revision><text>Beta is the second letter. {{stub}} It follows
[[Alpha|alpha]] and precedes gamma in the traditional ordering of the
alphabet, and this sentence pads the document past the length filter.</text></revision>
  </page>
</mediawiki>
"""


def test_wikipedia_xml_dump(tmp_path):
    f = tmp_path / "enwiki-test.xml"
    f.write_text(_XML_DUMP)
    docs = wikipedia_dump(str(f), num_partitions=2).collect()
    assert len(docs) == 2  # redirect skipped
    joined = " ".join(docs)
    assert "Greek alphabet" in joined and "first letter" not in joined.replace(
        "first letter of", "KEEP")  # [[a|b]] unwrapped to b
    assert "{{" not in joined and "[[" not in joined and "'''" not in joined


def test_wikipedia_xml_bz2(tmp_path):
    import bz2

    f = tmp_path / "enwiki-test.xml.bz2"
    f.write_bytes(bz2.compress(_XML_DUMP.encode()))
    docs = wikipedia_dump(str(f)).collect()
    assert len(docs) == 2


def test_wikipedia_wikiextractor_tree(tmp_path):
    d = tmp_path / "AA"
    d.mkdir()
    (d / "wiki_00").write_text(
        '<doc id="1" title="A">\nAlpha doc body, long enough to pass the '
        "minimum character filter for documents.\n</doc>\n"
        '<doc id="2" title="B">\nBeta doc body, also made long enough to '
        "pass the minimum character filter here.\n</doc>\n")
    docs = wikipedia_dump(str(tmp_path)).collect()
    assert len(docs) == 2
    assert all("<doc" not in doc for doc in docs)


def test_wikipedia_plain_text(tmp_path):
    f = tmp_path / "corpus.txt"
    f.write_text(
        "A single long line that is definitely over the minimum character "
        "limit for a document to be yielded.\n"
        "short line one\nshort line two\nshort line three which together "
        "with its siblings forms one long merged paragraph\n"
        "\n")
    docs = wikipedia_dump(str(f)).collect()
    assert len(docs) == 2  # long line + merged paragraph


def test_wikipedia_feeds_mlm_pipeline(tmp_path):
    from distributeddeeplearningspark_tpu.data.text import (
        WordPieceTokenizer,
        mlm_dataset,
    )

    f = tmp_path / "enwiki-test.xml"
    f.write_text(_XML_DUMP)
    docs = wikipedia_dump(str(f), num_partitions=2)
    tok = WordPieceTokenizer.train(docs.collect(), vocab_size=256)
    ds = mlm_dataset(docs, tok, seq_len=32)
    e = ds.take(1)[0]
    assert e["input_ids"].shape == (32,)
    assert set(e) >= {"input_ids", "attention_mask", "mlm_labels", "mlm_weights"}


def test_clean_wikitext_handles_nested_templates():
    s = "Keep {{outer {{inner}} more}} this and {{a|b}} that."
    out = clean_wikitext(s)
    assert "{{" not in out and "Keep" in out and "this and" in out


def test_eval_transform_resize_scales_with_crop_size():
    """The shorter-side resize must track the crop (ratio 0.875, the 256→224
    recipe generalized). A fixed 256 would zoom a 64-crop onto the central
    24×24 of the source — measured as a 1.0-train/0.28-eval accuracy split
    on a memorized set before the fix."""
    import numpy as np

    from distributeddeeplearningspark_tpu.data.vision import eval_transform

    # image with a bright left half: a correct 64/73 resize+center-crop keeps
    # roughly half the crop bright; a 256 resize would see only the center
    img = np.zeros((96, 96, 3), np.uint8)
    img[:, :48] = 255
    out = eval_transform(size=64)({"image": img, "label": 0})["image"]
    assert out.shape == (64, 64, 3)
    bright = (out[:, :, 0] > 0.0).mean()  # normalized: bright ≫ dark
    assert 0.35 < bright < 0.65, bright  # ~half, not all-or-nothing
