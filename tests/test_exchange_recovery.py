"""Shuffle task-level fault tolerance (data/exchange.py — ISSUE 14).

The contracts under test:

- lineage retry: a mapper that raises or is SIGKILLed mid-exchange has
  its slices re-executed (respawned or surviving worker) and the output
  is BYTE-IDENTICAL to a fault-free run — reducers dedupe replayed
  frames by their deterministic (part, slot, seq) identity; same for a
  dead reducer rebuilt from retained spill-dir frames, including with
  spilled runs already on disk;
- speculation: a slice lagging the median re-executes on an idle worker,
  first finish wins, dedup keeps the bytes identical;
- policy: per-worker strikes blacklist a slot after K failures (work
  redistributes), and the DLS_SHUFFLE_MAX_RETRIES budget bounds total
  recovery — exhaustion (or budget 0) escalates to the same typed
  WorkerCrashed as the fail-fast days, with full teardown;
- telemetry: every retry/speculation/blacklist decision is a ``shuffle``
  event, rendered by the dlstatus shuffle block's recovery line;
- no orphans: recovered exchanges — including respawned children —
  leak no process, shm segment, or spill file, even on interpreter exit
  mid-recovery (the weakref.finalize lists are LIVE, so
  dynamically-added children are reaped too).
"""

import multiprocessing as mp
import os
import signal
import subprocess
import sys
import time

import pytest

from distributeddeeplearningspark_tpu import telemetry
from distributeddeeplearningspark_tpu.data import exchange
from distributeddeeplearningspark_tpu.data.workers import (
    WorkerCrashed, fork_available)
from distributeddeeplearningspark_tpu.rdd import PartitionedDataset

pytestmark = pytest.mark.skipif(
    not fork_available(), reason="exchange needs the fork start method")


@pytest.fixture(autouse=True)
def _spill_here(tmp_path, monkeypatch):
    spill_root = tmp_path / "spill"
    spill_root.mkdir()
    monkeypatch.setenv(exchange.SPILL_DIR_ENV, str(spill_root))
    monkeypatch.delenv("DLS_DATA_WORKERS", raising=False)
    monkeypatch.delenv(exchange.MEM_MB_ENV, raising=False)
    for var in ("DLS_FAULT", "DLS_FAULT_SHUFFLE_ROLE", "DLS_FAULT_SHUFFLE_ID",
                "DLS_FAULT_ALL_ATTEMPTS", exchange.MAX_RETRIES_ENV,
                exchange.BLACKLIST_ENV, exchange.SPECULATE_ENV):
        monkeypatch.delenv(var, raising=False)
    yield spill_root


def _assert_no_leaks(spill_root):
    deadline = time.time() + 5.0
    while time.time() < deadline:
        if not [p for p in mp.active_children()
                if p.name.startswith("dlsx-")]:
            break
        time.sleep(0.05)
    assert not [p for p in mp.active_children()
                if p.name.startswith("dlsx-")]
    if os.path.isdir("/dev/shm"):
        mine = [f for f in os.listdir("/dev/shm")
                if f.startswith(f"dlsx-{os.getpid()}-")]
        assert not mine, mine
    import gc

    gc.collect()
    left = [str(p) for d in spill_root.iterdir() for p in d.iterdir()]
    assert not left, left


def _pairs_ds(n=20_000, kmod=997, nparts=4):
    data = [((i * 2654435761) % kmod, i % 13) for i in range(n)]
    chunks = [data[i::nparts] for i in range(nparts)]
    return PartitionedDataset.from_generators(
        [(lambda c=c: iter(c)) for c in chunks])


def _collect(ds):
    return [list(ds.iter_partition(i)) for i in range(ds.num_partitions)]


def _events_spy(monkeypatch):
    events = []
    orig = telemetry.emit
    monkeypatch.setattr(
        telemetry, "emit",
        lambda kind, **f: (events.append({"kind": kind, **f}),
                           orig(kind, **f))[1])
    return events


def _shuffle_edges(events, edge):
    return [e for e in events if e["kind"] == "shuffle"
            and e.get("edge") == edge]


# ---------------------------------------------------------------------------
# mapper failure: SIGKILL and raise, tuple and columnar, 1/4 workers
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("transport", ["tuple", "columnar"])
@pytest.mark.parametrize("nw", [1, 4])
def test_mapper_sigkill_recovers_byte_identical(_spill_here, monkeypatch,
                                                transport, nw):
    """A mapper killed mid-exchange respawns; its slices replay from
    lineage and the output matches the serial reference byte for byte on
    BOTH transports at any worker count."""
    ref = _collect(_pairs_ds().reduce_by_key(
        lambda a, b: a + b, num_workers=0))
    events = _events_spy(monkeypatch)
    monkeypatch.setenv("DLS_FAULT", "die_shuffle_worker@2000")
    monkeypatch.setenv("DLS_FAULT_SHUFFLE_ROLE", "mapper")
    monkeypatch.setenv("DLS_FAULT_SHUFFLE_ID", "0")
    got = _collect(_pairs_ds().reduce_by_key(
        lambda a, b: a + b, num_workers=nw,
        combine="sum" if transport == "columnar" else None,
        transport=transport))
    assert got == ref, f"{transport}@{nw} diverged after mapper kill"
    retries = _shuffle_edges(events, "retry")
    assert retries and retries[0]["role"] == "mapper"
    assert retries[0]["reason"] == "died"
    assert retries[0]["exitcode"] == -signal.SIGKILL
    done = _shuffle_edges(events, "done")[-1]
    assert done["mapper_retries"] >= 1
    # winning-slice accounting is deterministic despite the replay
    assert done["pairs_in"] == 20_000
    _assert_no_leaks(_spill_here)


def test_mapper_transient_raise_retried_then_succeeds(_spill_here, tmp_path,
                                                      monkeypatch):
    """A slice whose combine raises once (transient: bad NFS read, a
    flaky record) is re-executed and the exchange completes — identical
    bytes, one mapper retry recorded, reason 'raised'."""
    marker = tmp_path / "raised-once"
    events = _events_spy(monkeypatch)

    def flaky(a, b):
        if a + b > 20 and not marker.exists():
            marker.write_text("x")
            raise ValueError("transient poison")
        return a + b

    ref = _collect(_pairs_ds(n=2000, kmod=97).reduce_by_key(
        lambda a, b: a + b, num_workers=0))
    got = _collect(_pairs_ds(n=2000, kmod=97).reduce_by_key(
        flaky, num_workers=2))
    assert got == ref
    retries = _shuffle_edges(events, "retry")
    assert retries and retries[0]["role"] == "mapper"
    assert retries[0]["reason"] == "raised"
    _assert_no_leaks(_spill_here)


def test_mapper_deterministic_raise_escalates_with_traceback(_spill_here):
    """A raise that repeats on every attempt burns the budget and
    escalates as the typed WorkerCrashed carrying the user traceback."""
    def boom(a, b):
        if a + b > 50:
            raise ValueError("poisoned combine")
        return a + b

    out = _pairs_ds(n=2000, kmod=97).reduce_by_key(boom, num_workers=2)
    t0 = time.monotonic()
    with pytest.raises(WorkerCrashed) as ei:
        _collect(out)
    assert time.monotonic() - t0 < 60.0
    assert "poisoned combine" in str(ei.value)
    _assert_no_leaks(_spill_here)


# ---------------------------------------------------------------------------
# reducer failure (with spilled runs on disk)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("transport", ["tuple", "columnar"])
def test_reducer_sigkill_with_spills_recovers(_spill_here, monkeypatch,
                                              transport):
    """A reducer killed after it has already SPILLED sorted runs to disk
    restarts, discards the dead attempt's runs, rebuilds its buckets from
    the retained mapper frames, and finalizes byte-identically."""
    kw = dict(combine="sum" if transport == "columnar" else None,
              transport=transport)
    # 200k distinct keys: even the compact columnar planes overflow the
    # 4MB floor budget's per-reducer share, so runs really hit disk
    # before the kill
    ref = _collect(_pairs_ds(n=200_000, kmod=199_999).reduce_by_key(
        lambda a, b: a + b, num_workers=0, **kw))
    events = _events_spy(monkeypatch)
    monkeypatch.setenv(exchange.MEM_MB_ENV, "4")  # floor budget → spills
    monkeypatch.setenv("DLS_FAULT", "die_shuffle_worker@3")
    monkeypatch.setenv("DLS_FAULT_SHUFFLE_ROLE", "reducer")
    monkeypatch.setenv("DLS_FAULT_SHUFFLE_ID", "0")
    got = _collect(_pairs_ds(n=200_000, kmod=199_999).reduce_by_key(
        lambda a, b: a + b, num_workers=2, **kw))
    assert got == ref, f"{transport} diverged after reducer kill"
    retries = _shuffle_edges(events, "retry")
    assert any(r["role"] == "reducer" and r["reason"] == "died"
               for r in retries)
    assert _shuffle_edges(events, "spill"), "budget floor never spilled"
    _assert_no_leaks(_spill_here)


def test_mapper_and_reducer_killed_same_run(_spill_here, monkeypatch):
    """The shuffle-chaos shape: one mapper AND one reducer die in the
    same exchange; both recover; bytes identical; one retry each."""
    ref = _collect(_pairs_ds().reduce_by_key(
        lambda a, b: a + b, num_workers=0))
    events = _events_spy(monkeypatch)
    monkeypatch.setenv("DLS_FAULT", "die_shuffle_worker@6")
    monkeypatch.setenv("DLS_FAULT_SHUFFLE_ROLE", "both")
    monkeypatch.setenv("DLS_FAULT_SHUFFLE_ID", "0")
    got = _collect(_pairs_ds().reduce_by_key(
        lambda a, b: a + b, num_workers=2))
    assert got == ref
    done = _shuffle_edges(events, "done")[-1]
    assert done["mapper_retries"] >= 1 and done["reducer_retries"] >= 1
    _assert_no_leaks(_spill_here)


# ---------------------------------------------------------------------------
# speculation
# ---------------------------------------------------------------------------

def test_speculation_first_finish_wins_dedup(_spill_here, monkeypatch):
    """One partition is pathologically slow; its slice gets cloned to an
    idle worker once it lags the median past the (patched-down) floor.
    Both attempts ship byte-identical frames, dedup keeps exactly one
    copy, output matches the serial reference."""
    def make_ds():
        def chunk(i):
            def gen():
                for j in range(40):
                    if i == 0:
                        time.sleep(0.05)  # the straggler partition
                    yield ((i * 40 + j) % 13, 1)
            return gen
        return PartitionedDataset.from_generators(
            [chunk(i) for i in range(4)])

    ref = _collect(make_ds().reduce_by_key(lambda a, b: a + b,
                                           num_workers=0))
    events = _events_spy(monkeypatch)
    monkeypatch.setattr(exchange, "_SPECULATE_FLOOR_S", 0.3)
    monkeypatch.setenv(exchange.SPECULATE_ENV, "2.0")
    got = _collect(make_ds().reduce_by_key(lambda a, b: a + b,
                                           num_workers=2))
    assert got == ref
    spec = _shuffle_edges(events, "speculate")
    assert spec, "no speculation despite a 2s straggler"
    assert spec[0]["part"] == 0
    done = _shuffle_edges(events, "done")[-1]
    assert done["speculations"] >= 1
    # dedup: winning-slice accounting counts every pair exactly once
    assert done["pairs_in"] == 160
    _assert_no_leaks(_spill_here)


# ---------------------------------------------------------------------------
# blacklisting + budget
# ---------------------------------------------------------------------------

def test_blacklist_after_k_strikes_redistributes(_spill_here, monkeypatch):
    """With the fault firing on EVERY attempt of mapper slot 0 and the
    strike threshold at 1, the slot is blacklisted after its first death
    and the surviving mapper absorbs its work — completion, identical
    bytes, a blacklist event, no further slot-0 respawn."""
    ref = _collect(_pairs_ds().reduce_by_key(
        lambda a, b: a + b, num_workers=0))
    events = _events_spy(monkeypatch)
    monkeypatch.setenv("DLS_FAULT", "die_shuffle_worker@500")
    monkeypatch.setenv("DLS_FAULT_SHUFFLE_ROLE", "mapper")
    monkeypatch.setenv("DLS_FAULT_SHUFFLE_ID", "0")
    monkeypatch.setenv("DLS_FAULT_ALL_ATTEMPTS", "1")
    monkeypatch.setenv(exchange.BLACKLIST_ENV, "1")
    got = _collect(_pairs_ds().reduce_by_key(
        lambda a, b: a + b, num_workers=2))
    assert got == ref
    bl = _shuffle_edges(events, "blacklist")
    assert len(bl) == 1 and bl[0]["role"] == "mapper" and bl[0]["worker"] == 0
    done = _shuffle_edges(events, "done")[-1]
    assert done["blacklists"] == 1
    _assert_no_leaks(_spill_here)


def test_retry_budget_exhaustion_escalates_typed(_spill_here, monkeypatch):
    """A single-mapper exchange whose worker dies on every attempt burns
    DLS_SHUFFLE_MAX_RETRIES respawns, then escalates to the typed
    WorkerCrashed with the budget named — and tears everything down."""
    monkeypatch.setenv("DLS_FAULT", "die_shuffle_worker@500")
    monkeypatch.setenv("DLS_FAULT_SHUFFLE_ROLE", "mapper")
    monkeypatch.setenv("DLS_FAULT_SHUFFLE_ID", "0")
    monkeypatch.setenv("DLS_FAULT_ALL_ATTEMPTS", "1")
    monkeypatch.setenv(exchange.MAX_RETRIES_ENV, "2")
    monkeypatch.setenv(exchange.BLACKLIST_ENV, "99")
    t0 = time.monotonic()
    with pytest.raises(WorkerCrashed) as ei:
        _collect(_pairs_ds().reduce_by_key(
            lambda a, b: a + b, num_workers=1))
    assert time.monotonic() - t0 < 60.0
    assert "exhausted" in str(ei.value)
    assert ei.value.exitcode == -signal.SIGKILL
    _assert_no_leaks(_spill_here)


def test_zero_retries_is_fail_fast(_spill_here, monkeypatch):
    """DLS_SHUFFLE_MAX_RETRIES=0: the first death raises today's typed
    WorkerCrashed within a bounded wait, full teardown — the acceptance
    gate for the legacy behavior (and the retention-free perf baseline)."""
    monkeypatch.setenv(exchange.MAX_RETRIES_ENV, "0")
    monkeypatch.setenv("DLS_FAULT", "die_shuffle_worker@2000")
    monkeypatch.setenv("DLS_FAULT_SHUFFLE_ROLE", "mapper")
    monkeypatch.setenv("DLS_FAULT_SHUFFLE_ID", "0")
    t0 = time.monotonic()
    with pytest.raises(WorkerCrashed) as ei:
        _collect(_pairs_ds().reduce_by_key(
            lambda a, b: a + b, num_workers=2))
    assert time.monotonic() - t0 < 30.0
    assert "died" in str(ei.value)
    assert ei.value.exitcode == -signal.SIGKILL
    _assert_no_leaks(_spill_here)


# ---------------------------------------------------------------------------
# group_by_key / sort_by replay identity (tagged values, sort frames)
# ---------------------------------------------------------------------------

def test_group_and_sort_recover_byte_identical(_spill_here, monkeypatch):
    monkeypatch.setenv("DLS_FAULT", "die_shuffle_worker@1500")
    monkeypatch.setenv("DLS_FAULT_SHUFFLE_ROLE", "mapper")
    monkeypatch.setenv("DLS_FAULT_SHUFFLE_ID", "0")
    ref_g = _collect(_pairs_ds(n=8000).group_by_key(num_workers=0))
    got_g = _collect(_pairs_ds(n=8000).group_by_key(num_workers=2))
    assert got_g == ref_g
    ref_s = list(_pairs_ds(n=8000).sort_by(
        lambda kv: kv[0], num_workers=0).collect())
    got_s = list(_pairs_ds(n=8000).sort_by(
        lambda kv: kv[0], num_workers=2).collect())
    assert got_s == ref_s
    _assert_no_leaks(_spill_here)


# ---------------------------------------------------------------------------
# dlstatus recovery rollup
# ---------------------------------------------------------------------------

def test_dlstatus_renders_recovery_line(tmp_path, monkeypatch, _spill_here):
    from distributeddeeplearningspark_tpu import status

    wd = tmp_path / "tele"
    monkeypatch.setenv("DLS_FAULT", "die_shuffle_worker@6")
    monkeypatch.setenv("DLS_FAULT_SHUFFLE_ROLE", "both")
    monkeypatch.setenv("DLS_FAULT_SHUFFLE_ID", "0")
    telemetry.configure(wd)
    try:
        _collect(_pairs_ds().reduce_by_key(lambda a, b: a + b,
                                           num_workers=2))
    finally:
        telemetry.reset()
    rep = status.report(str(wd))
    sh = rep["shuffle"]
    rec = sh["recovery"]
    assert rec["retries"] >= 2
    assert rec["mapper_retries"] >= 1 and rec["reducer_retries"] >= 1
    rendered = status.render(rep)
    assert "recovery:" in rendered and "self-healed" in rendered
    _assert_no_leaks(_spill_here)


# ---------------------------------------------------------------------------
# no orphans on interpreter exit mid-recovery (live finalizer lists)
# ---------------------------------------------------------------------------

def test_interpreter_exit_mid_recovery_leaks_nothing(tmp_path):
    # slow-marked centrally in conftest._SLOW_PATTERNS
    """Abandon an exchange WHILE a respawned mapper (epoch 1) is
    running, then exit. The weakref.finalize registration holds the LIVE
    proc list, so the dynamically-added child is reaped too — no process
    survives, no shm leaks, and the resource tracker has nothing to
    complain about."""
    script = r"""
import os, sys, threading, time
os.environ["DLS_SHUFFLE_SPILL_DIR"] = sys.argv[1]
os.environ["DLS_FAULT"] = "die_shuffle_worker@30"
os.environ["DLS_FAULT_SHUFFLE_ROLE"] = "mapper"
os.environ["DLS_FAULT_SHUFFLE_ID"] = "0"
from distributeddeeplearningspark_tpu.rdd import PartitionedDataset

def chunk(i):
    def gen():
        for j in range(400):
            time.sleep(0.02)   # keep the exchange mid-flight at exit
            yield ((i * 400 + j) % 97, 1)
    return gen

ds = PartitionedDataset.from_generators([chunk(i) for i in range(4)])
out = ds.reduce_by_key(lambda a, b: a + b, num_workers=2)
th = threading.Thread(
    target=lambda: list(out.iter_partition(0)), daemon=True)
th.start()
time.sleep(4.0)  # the fault fired (~0.6s in) and epoch 1 is running
print("pid", os.getpid())
sys.exit(0)      # finalize must reap the epoch-1 child too
"""
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    out = subprocess.run(
        [sys.executable, "-c", script, str(tmp_path)], env=env,
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "leaked shared_memory" not in out.stderr, out.stderr[-2000:]
    pid = int(out.stdout.split()[-1])
    if os.path.isdir("/dev/shm"):
        left = [f for f in os.listdir("/dev/shm")
                if f.startswith(f"dlsx-{pid}-")]
        assert not left, left
