"""Distributed shuffle exchange (data/exchange.py — ISSUE 8).

The contracts under test:

- determinism: `reduce_by_key`/`group_by_key`/`sort_by`/`groupBy().agg`
  output is byte-identical at num_workers 0/1/4 (canonical key_bytes
  bucketing + ordering on both paths), and identical again when the
  reducers are forced through the spill-to-disk path by a tiny
  ``DLS_SHUFFLE_MEM_MB``;
- failure: a mapper that raises forwards its traceback, a SIGKILLed one
  surfaces a typed WorkerCrashed within a bounded wait, and either way no
  child process, shm segment, or spill file survives;
- serial ceilings: without workers every wide op refuses loudly past
  ``max_groups``, naming ``DLS_DATA_WORKERS`` (the exchange) as the first
  remediation;
- telemetry: a shuffle leaves ``shuffle-map``/``shuffle-merge`` phase
  spans plus ``shuffle`` spill/done gauges, and ``dlstatus`` renders them
  as the shuffle block.
"""

import multiprocessing as mp
import os
import signal
import time

import numpy as np
import pytest

from distributeddeeplearningspark_tpu.data import exchange
from distributeddeeplearningspark_tpu.data.dataframe import DataFrame
from distributeddeeplearningspark_tpu.data.workers import WorkerCrashed, fork_available
from distributeddeeplearningspark_tpu.rdd import PartitionedDataset

pytestmark = pytest.mark.skipif(
    not fork_available(), reason="exchange needs the fork start method")


@pytest.fixture(autouse=True)
def _spill_here(tmp_path, monkeypatch):
    """Pin spill dirs under tmp_path so leak assertions see everything."""
    spill_root = tmp_path / "spill"
    spill_root.mkdir()
    monkeypatch.setenv(exchange.SPILL_DIR_ENV, str(spill_root))
    monkeypatch.delenv("DLS_DATA_WORKERS", raising=False)
    monkeypatch.delenv(exchange.MEM_MB_ENV, raising=False)
    yield spill_root


def _assert_no_leaks(spill_root):
    """No dlsx child, shm segment, or spill directory survives."""
    deadline = time.time() + 5.0
    while time.time() < deadline:
        if not [p for p in mp.active_children()
                if p.name.startswith("dlsx-")]:
            break
        time.sleep(0.05)
    assert not [p for p in mp.active_children()
                if p.name.startswith("dlsx-")]
    if os.path.isdir("/dev/shm"):
        mine = [f for f in os.listdir("/dev/shm")
                if f.startswith(f"dlsx-{os.getpid()}-")]
        assert not mine, mine
    import gc

    gc.collect()  # ShuffleResult finalizers remove kept spill dirs
    left = [str(p) for d in spill_root.iterdir() for p in d.iterdir()]
    assert not left, left


def _pairs_ds(n=2000, kmod=97, nparts=4):
    data = [((i * 2654435761) % kmod, i % 13) for i in range(n)]
    chunks = [data[i::nparts] for i in range(nparts)]
    return PartitionedDataset.from_generators(
        [(lambda c=c: iter(c)) for c in chunks])


def _collect_parts(ds):
    return [list(ds.iter_partition(i)) for i in range(ds.num_partitions)]


# ---------------------------------------------------------------------------
# canonical key identity
# ---------------------------------------------------------------------------

def test_key_bytes_is_canonical_and_sortable():
    kbs = [exchange.key_bytes(k) for k in range(100)]
    assert len(set(kbs)) == 100
    assert exchange.key_bytes(7) == exchange.key_bytes(7)
    # tuple/str/int all hash; buckets stay in range
    for k in (1, "a", (2, "b"), 3.5):
        assert 0 <= exchange.bucket_of(exchange.key_bytes(k), 7) < 7


def test_resolve_shuffle_workers_env(monkeypatch):
    assert exchange.resolve_shuffle_workers(3) == 3
    assert exchange.resolve_shuffle_workers(0) == 0
    monkeypatch.setenv("DLS_DATA_WORKERS", "2")
    assert exchange.resolve_shuffle_workers(None) == 2


def test_mem_budget_env(monkeypatch):
    monkeypatch.setenv(exchange.MEM_MB_ENV, "8")
    assert exchange.mem_budget_bytes() == 8 << 20
    assert exchange.mem_budget_bytes(16) == 16 << 20
    # floor: never less than 4MB even for absurd settings
    assert exchange.mem_budget_bytes(0.001) == 4 << 20


# ---------------------------------------------------------------------------
# determinism: 0/1/4 workers byte-identical
# ---------------------------------------------------------------------------

def test_reduce_by_key_identical_across_worker_counts(_spill_here):
    ref = _collect_parts(
        _pairs_ds().reduce_by_key(lambda a, b: a + b, num_workers=0))
    assert sum(len(p) for p in ref) == 97
    for nw in (1, 4):
        got = _collect_parts(
            _pairs_ds().reduce_by_key(lambda a, b: a + b, num_workers=nw))
        assert got == ref, f"num_workers={nw} diverged"
    _assert_no_leaks(_spill_here)


def test_group_by_key_value_order_identical(_spill_here):
    ref = _collect_parts(_pairs_ds().group_by_key(num_workers=0))
    for nw in (1, 4):
        got = _collect_parts(_pairs_ds().group_by_key(num_workers=nw))
        assert got == ref
    _assert_no_leaks(_spill_here)


def test_sort_by_identical_both_directions(_spill_here):
    for ascending in (True, False):
        ref = list(_pairs_ds().sort_by(
            lambda kv: kv[0], ascending=ascending, num_workers=0).collect())
        for nw in (1, 4):
            got = list(_pairs_ds().sort_by(
                lambda kv: kv[0], ascending=ascending,
                num_workers=nw).collect())
            assert got == ref, (ascending, nw)
    _assert_no_leaks(_spill_here)


def test_sort_by_exchange_is_range_partitioned(_spill_here):
    out = _pairs_ds(n=4000).sort_by(lambda kv: kv[0], num_workers=2)
    parts = _collect_parts(out)
    last = None
    for p in parts:
        keys = [k for k, _ in p]
        assert keys == sorted(keys)
        if p and last is not None:
            assert last <= p[0][0]
        if p:
            last = p[-1][0]


def test_distinct_exchange_dedups(_spill_here):
    ds = _pairs_ds(n=3000).map(lambda kv: kv[0])
    serial = set(ds.distinct(num_workers=0).collect())
    for nw in (1, 4):
        got = list(_pairs_ds(n=3000).map(lambda kv: kv[0])
                   .distinct(num_workers=nw).collect())
        assert len(got) == len(set(got)) == len(serial)
        assert set(got) == serial
    # exchange path is itself deterministic run-to-run
    a = list(_pairs_ds(n=3000).map(lambda kv: kv[0])
             .distinct(num_workers=2).collect())
    b = list(_pairs_ds(n=3000).map(lambda kv: kv[0])
             .distinct(num_workers=2).collect())
    assert a == b
    _assert_no_leaks(_spill_here)


def _agg_df(n=6000, kmod=151, nparts=3):
    # integer-valued float64 values: their sums are EXACT below 2^53, so
    # they commute/associate bitwise and byte-identity across worker
    # counts is the honest claim (rdd.py docstring: float sums of
    # arbitrary reals reorder under the exchange like they do in Spark)
    rng = np.random.default_rng(7)
    k = (np.arange(n) * 2654435761) % kmod
    v = rng.integers(-1000, 1000, size=n).astype(np.float64)
    chunks = []
    for i in range(nparts):
        sl = slice(i * n // nparts, (i + 1) * n // nparts)
        chunks.append({"k": k[sl].copy(), "v": v[sl].copy()})
    ds = PartitionedDataset.from_generators(
        [(lambda c=c: iter([c])) for c in chunks])
    return DataFrame(ds, ["k", "v"])


def _agg_bytes(df) -> bytes:
    """Whole-result bytes, column-major over the CONCATENATED row stream
    (chunk boundaries are layout, not content: the serial path emits one
    chunk, the exchange re-chunks per bucket at DEFAULT_CHUNK_ROWS)."""
    chunks = [ch for p in range(df._chunks.num_partitions)
              for ch in df._chunks.iter_partition(p)]
    assert chunks, "empty result"
    return b"".join(
        np.ascontiguousarray(
            np.concatenate([np.atleast_1d(ch[c]) for ch in chunks])).tobytes()
        for c in sorted(chunks[0]))


def test_groupby_agg_identical_across_worker_counts(_spill_here):
    spec = {"v": "sum", "k": "count"}
    ref = _agg_bytes(_agg_df().groupBy("k").agg(spec, num_workers=0))
    for nw in (1, 4):
        got = _agg_bytes(_agg_df().groupBy("k").agg(spec, num_workers=nw))
        assert got == ref, f"num_workers={nw} diverged"
    _assert_no_leaks(_spill_here)


def test_groupby_agg_min_max_mean_parity(_spill_here):
    spec = {"v": "min", "k": "count"}
    assert _agg_bytes(_agg_df().groupBy("k").agg(spec, num_workers=2)) == \
        _agg_bytes(_agg_df().groupBy("k").agg(spec, num_workers=0))
    spec = {"v": "max"}
    assert _agg_bytes(_agg_df().groupBy("k").agg(spec, num_workers=2)) == \
        _agg_bytes(_agg_df().groupBy("k").agg(spec, num_workers=0))
    # mean = sum/count from identical partials → bit-identical too
    spec = {"v": "mean"}
    assert _agg_bytes(_agg_df().groupBy("k").agg(spec, num_workers=2)) == \
        _agg_bytes(_agg_df().groupBy("k").agg(spec, num_workers=0))


# ---------------------------------------------------------------------------
# spill path
# ---------------------------------------------------------------------------

def test_spill_path_equals_in_memory(_spill_here, monkeypatch):
    """A tiny DLS_SHUFFLE_MEM_MB forces reducer spills; the merged output
    must equal the all-in-memory result byte for byte."""
    big = _collect_parts(
        _pairs_ds(n=60_000, kmod=59999).reduce_by_key(
            lambda a, b: a + b, num_workers=2))
    monkeypatch.setenv(exchange.MEM_MB_ENV, "4")  # floor budget → spills
    stats = {}
    orig = exchange.run_exchange

    def spy(*a, **kw):
        r = orig(*a, **kw)
        stats.update(r.stats)
        return r

    monkeypatch.setattr(exchange, "run_exchange", spy)
    small = _collect_parts(
        _pairs_ds(n=60_000, kmod=59999).reduce_by_key(
            lambda a, b: a + b, num_workers=2))
    assert stats["spills"] >= 1, stats
    assert small == big
    _assert_no_leaks(_spill_here)


# ---------------------------------------------------------------------------
# failure propagation + cleanup
# ---------------------------------------------------------------------------

def test_mapper_exception_is_typed_with_traceback(_spill_here):
    def boom(a, b):
        if a + b > 50:
            raise ValueError("poisoned combine")
        return a + b

    out = _pairs_ds().reduce_by_key(boom, num_workers=2)
    t0 = time.monotonic()
    with pytest.raises(WorkerCrashed) as ei:
        _collect_parts(out)
    assert time.monotonic() - t0 < 30.0
    assert "poisoned combine" in str(ei.value)
    _assert_no_leaks(_spill_here)


def test_mapper_sigkill_surfaces_worker_crashed(_spill_here):
    """A mapper killed mid-exchange (OOM stand-in) is detected by the
    liveness poll within a bounded wait — a CRASH, not a hang — and the
    failed exchange tears down every child, shm segment, and spill file."""
    def die_at(kv):
        k, v = kv
        if k == 5:
            os.kill(os.getpid(), signal.SIGKILL)
        time.sleep(0.0005)
        return ((k, v),)

    ds = _pairs_ds(n=2000).map(lambda kv: kv)
    out = exchange._lazy_exchange_dataset(
        ds._parts, num_workers=2, n_out=4,
        spec=exchange._Spec(pre=die_at, combine=lambda a, b: a + b),
        label="sigkill-drill")
    t0 = time.monotonic()
    with pytest.raises(WorkerCrashed) as ei:
        _collect_parts(out)
    assert time.monotonic() - t0 < 30.0
    assert "died" in str(ei.value)
    assert ei.value.exitcode == -signal.SIGKILL
    _assert_no_leaks(_spill_here)


# ---------------------------------------------------------------------------
# serial ceilings
# ---------------------------------------------------------------------------

def test_serial_refusals_name_the_exchange(monkeypatch):
    monkeypatch.setenv(exchange.MAX_GROUPS_ENV, "10")
    ds = _pairs_ds(n=500, kmod=97)
    for make in (
        lambda: ds.reduce_by_key(lambda a, b: a + b, num_workers=0),
        lambda: ds.group_by_key(num_workers=0),
        lambda: ds.map(lambda kv: kv[0]).distinct(num_workers=0),
        lambda: ds.sort_by(lambda kv: kv[0], num_workers=0),
    ):
        with pytest.raises(ValueError, match="DLS_DATA_WORKERS"):
            make().collect()


def test_agg_serial_refusal_names_workers_first(monkeypatch):
    monkeypatch.delenv("DLS_DATA_WORKERS", raising=False)
    df = _agg_df(n=2000, kmod=500)
    with pytest.raises(ValueError) as ei:
        _agg_bytes(df.groupBy("k").agg({"v": "sum"}, max_groups=10))
    msg = str(ei.value)
    assert "DLS_DATA_WORKERS" in msg and "hash_bucket" in msg
    assert msg.index("DLS_DATA_WORKERS") < msg.index("hash_bucket")


def test_exchange_has_no_ceiling(monkeypatch, _spill_here):
    """The exact workload the serial path refuses completes through the
    exchange under the same (tiny) ceiling — the ceiling is serial-only."""
    monkeypatch.setenv(exchange.MAX_GROUPS_ENV, "10")
    out = _collect_parts(
        _pairs_ds(n=500, kmod=97).reduce_by_key(
            lambda a, b: a + b, num_workers=2))
    assert sum(len(p) for p in out) == 97


# ---------------------------------------------------------------------------
# telemetry + dlstatus
# ---------------------------------------------------------------------------

def test_shuffle_telemetry_and_dlstatus_block(tmp_path, monkeypatch,
                                              _spill_here):
    from distributeddeeplearningspark_tpu import status, telemetry

    wd = tmp_path / "tele"
    monkeypatch.setenv(exchange.MEM_MB_ENV, "4")
    telemetry.configure(wd)
    try:
        _collect_parts(
            _pairs_ds(n=60_000, kmod=59999).reduce_by_key(
                lambda a, b: a + b, num_workers=2))
    finally:
        telemetry.reset()
    events = telemetry.read_events(wd)
    phases = [(e["name"], e.get("edge")) for e in events
              if e.get("kind") == "phase"]
    assert ("shuffle-map", "begin") in phases
    assert ("shuffle-map", "end") in phases
    assert ("shuffle-merge", "end") in phases
    done = [e for e in events
            if e.get("kind") == "shuffle" and e.get("edge") == "done"]
    assert len(done) == 1
    d = done[0]
    assert d["op"] == "reduce_by_key" and d["workers"] == 2
    assert d["pairs_in"] == 60_000 and d["rows_out"] > 30_000
    assert d["spills"] >= 1 and len(d["bucket_rows"]) == d["buckets"]
    spill_evts = [e for e in events
                  if e.get("kind") == "shuffle" and e.get("edge") == "spill"]
    assert len(spill_evts) >= 1
    assert all("bucket" in e and "bytes" in e for e in spill_evts)

    rep = status.report(str(wd))
    sh = rep["shuffle"]
    assert sh and sh["ops"] == 1 and sh["spills"] >= 1
    assert sh["last"]["op"] == "reduce_by_key"
    assert sh["last"]["verdict"].startswith("balanced")
    rendered = status.render(rep)
    assert "shuffle: 1 op(s)" in rendered
    assert "reduce_by_key" in rendered


def test_shuffle_skew_verdict_names_hot_bucket():
    from distributeddeeplearningspark_tpu import status

    events = [{"kind": "shuffle", "edge": "done", "op": "reduce_by_key",
               "workers": 2, "buckets": 4, "pairs_in": 100, "rows_out": 40,
               "bytes_moved": 1000, "spills": 0, "overflow": 0,
               "map_s": 0.1, "merge_s": 0.1, "mem_budget_mb": 64,
               "bucket_rows": [37, 1, 1, 1]}]
    sh = status.shuffle_from(events)
    assert sh["last"]["skew"] > 2
    assert sh["last"]["verdict"].startswith("SKEWED")
    assert "bucket 0" in sh["last"]["verdict"]


def test_lazy_exchange_runs_once(_spill_here):
    """The exchange is lazy (nothing runs at call time) and memoized
    (N output partitions trigger ONE shuffle)."""
    calls = []
    orig = exchange.run_exchange

    def spy(*a, **kw):
        calls.append(1)
        return orig(*a, **kw)

    exchange.run_exchange = spy
    try:
        out = _pairs_ds().reduce_by_key(lambda a, b: a + b, num_workers=2)
        assert calls == []  # lazy
        _collect_parts(out)
        _collect_parts(out)
        assert len(calls) == 1  # memoized across partitions AND re-reads
    finally:
        exchange.run_exchange = orig
