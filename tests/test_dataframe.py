"""DataFrame plane (SURVEY.md §2 'Data: tabular pipeline'; VERDICT r1: the
reference's Spark-SQL feature surface had no counterpart)."""

import numpy as np
import pytest

from distributeddeeplearningspark_tpu.data import dataframe as df_mod
from distributeddeeplearningspark_tpu.data.dataframe import (
    DataFrame,
    DataFrameReader,
    col,
    from_dataset,
    from_rows,
    hash_bucket,
    lit,
    log1p,
    read_csv,
    read_parquet,
    when,
)


def toy_df(n=100, parts=4):
    rows = [{"x": float(i), "y": float(i % 7), "name": f"u{i % 5}"}
            for i in range(n)]
    return from_rows(rows, num_partitions=parts, chunk_rows=16)


def test_select_and_exprs():
    df = toy_df()
    out = df.select("x", (col("x") * 2 + 1).alias("x2"),
                    log1p(col("y")).alias("ly"))
    assert out.columns == ["x", "x2", "ly"]
    rows = out.take(3)
    assert rows[1]["x2"] == 3.0
    assert np.isclose(rows[2]["ly"], np.log1p(2.0))


def test_with_column_filter_count():
    df = toy_df(100)
    df2 = df.withColumn("even", col("x") % 2 == 0).filter(col("even"))
    assert df2.count() == 50
    assert df2.columns == ["x", "y", "name", "even"]


def test_fillna_float_and_string():
    rows = [{"a": np.nan, "s": ""}, {"a": 3.0, "s": "hi"}]
    df = from_rows(rows, num_partitions=1)
    out = df.fillna(0.0, subset=["a"]).fillna("?", subset=["s"]).collect()
    assert out[0]["a"] == 0.0 and out[0]["s"] == "?"
    assert out[1]["a"] == 3.0 and out[1]["s"] == "hi"


def test_when_otherwise():
    df = toy_df(10, parts=1)
    out = df.select(when(col("x") < 3, -1).when(col("x") < 6, 0)
                    .otherwise(col("x")).alias("b"))
    vals = [r["b"] for r in out.collect()]
    assert vals[:3] == [-1, -1, -1] and vals[3:6] == [0, 0, 0]
    assert vals[6:] == [6.0, 7.0, 8.0, 9.0]


def test_hash_bucket_deterministic_and_bounded():
    df = toy_df(50, parts=2)
    h1 = [r["h"] for r in df.select(
        hash_bucket(col("name"), 13).alias("h")).collect()]
    h2 = [r["h"] for r in df.select(
        hash_bucket(col("name"), 13).alias("h")).collect()]
    assert h1 == h2
    assert all(0 <= v < 13 for v in h1)
    # int path: deterministic across evaluations, equal inputs collide
    int_df = df.withColumn("k", col("x").cast(np.int64) % 3)
    a = [r["h"] for r in int_df.select(hash_bucket(col("k"), 13).alias("h")).collect()]
    b = [r["h"] for r in int_df.select(hash_bucket(col("k"), 13).alias("h")).collect()]
    ks = [r["k"] for r in int_df.select("k").collect()]
    assert a == b
    assert all(a[i] == a[j] for i in range(len(a)) for j in range(len(a))
               if ks[i] == ks[j])
    with pytest.raises(ValueError):
        hash_bucket(col("x"), 0)


def test_random_split_partitions_all_rows():
    df = toy_df(200, parts=4)
    a, b = df.randomSplit([0.8, 0.2], seed=7)
    na, nb = a.count(), b.count()
    assert na + nb == 200
    assert 120 < na < 195  # loose: hash-split around 80%
    # deterministic
    assert a.count() == na


def test_to_dataset_vector_packing():
    rows = [{"I1": float(i), "I2": float(2 * i), "label": i % 2}
            for i in range(10)]
    df = from_rows(rows, num_partitions=2)
    ds = df.to_dataset(vector_columns={"dense": ["I1", "I2"]})
    ex = ds.take(3)[2]
    assert set(ex) == {"dense", "label"}
    assert ex["dense"].shape == (2,)
    assert ex["dense"][1] == 4.0


def test_with_columns_simultaneous_semantics():
    """pyspark semantics: all exprs see the INPUT row — a/b swap works."""
    df = from_rows([{"a": 1.0, "b": 2.0}], num_partitions=1)
    out = df.withColumns({"a": col("b"), "b": col("a")}).collect()[0]
    assert out["a"] == 2.0 and out["b"] == 1.0


def test_repartition_up_and_down():
    df = toy_df(96, parts=2)
    up = df.repartition(6)
    assert up.num_partitions == 6
    assert up.count() == 96
    assert sorted(r["x"] for r in up.collect()) == sorted(
        r["x"] for r in df.collect())
    down = up.repartition(2)
    assert down.num_partitions == 2 and down.count() == 96


def test_read_csv_clamps_partitions_to_file_count(tmp_path):
    for i in range(2):
        (tmp_path / f"day_{i}").write_text(f"{i},a\n{i},b\n")
    df = read_csv(str(tmp_path / "day_*"), names=["v", "s"],
                  dtypes={"s": np.str_}, num_partitions=8)
    assert df.num_partitions == 2
    assert df.count() == 4
    assert df.repartition(4).count() == 4


def test_rdd_round_trip():
    df = toy_df(20, parts=2)
    ds = df.rdd
    df2 = from_dataset(ds, df.columns, chunk_rows=8)
    assert df2.count() == 20
    assert df2.take(1)[0]["x"] == 0.0


def test_read_csv_missing_fields_and_types(tmp_path):
    p = tmp_path / "t.tsv"
    p.write_text("1.5\ta\t3\n\tb\t\n2.0\t\t7\n")
    df = read_csv(str(p), names=["f", "s", "k"], sep="\t",
                  dtypes={"s": np.str_, "k": np.int32}, num_partitions=2)
    rows = df.collect()
    assert np.isnan(rows[1]["f"]) and rows[1]["k"] == 0
    assert rows[2]["s"] == ""
    filled = df.fillna(0.0, subset=["f"]).collect()
    assert filled[1]["f"] == 0.0


def test_read_csv_multi_file_glob(tmp_path):
    for i in range(3):
        (tmp_path / f"part-{i}.csv").write_text(f"{i},x{i}\n")
    df = read_csv(str(tmp_path / "part-*.csv"), names=["v", "s"],
                  dtypes={"s": np.str_}, num_partitions=3)
    assert df.num_partitions == 3
    assert sorted(r["v"] for r in df.collect()) == [0.0, 1.0, 2.0]
    with pytest.raises(FileNotFoundError):
        read_csv(str(tmp_path / "nope-*.csv"), names=["v"])


def test_reader_surface(tmp_path):
    p = tmp_path / "d.csv"
    p.write_text("1,2\n3,4\n")
    df = (DataFrameReader(default_parallelism=2)
          .option("sep", ",").schema(["a", "b"]).csv(str(p)))
    assert df.count() == 2
    with pytest.raises(ValueError):
        DataFrameReader().csv(str(p))


def test_criteo_shaped_pipeline_end_to_end(tmp_path):
    """Raw Criteo-style TSV -> DataFrame features -> feed -> one DLRM step."""
    import optax

    from distributeddeeplearningspark_tpu.data.feed import put_global, stack_examples
    from distributeddeeplearningspark_tpu.models import DLRM, dlrm_rules
    from distributeddeeplearningspark_tpu.parallel.mesh import MeshSpec
    from distributeddeeplearningspark_tpu.train import losses, step as step_lib

    # label, 2 dense, 2 hex-categorical (tab-separated, some missing)
    lines = []
    rng = np.random.default_rng(0)
    for i in range(64):
        dense = [str(rng.integers(0, 50)) if i % 5 else "", str(i)]
        cats = [f"{rng.integers(0, 1 << 16):08x}" if i % 7 else "", "cafe0001"]
        lines.append("\t".join([str(i % 2)] + dense + cats))
    p = tmp_path / "day_0.tsv"
    p.write_text("\n".join(lines) + "\n")

    names = ["label", "I1", "I2", "C1", "C2"]
    vocab = [32, 16]
    df = read_csv(str(p), names=names, sep="\t",
                  dtypes={"label": np.int32, "C1": np.str_, "C2": np.str_},
                  num_partitions=2)
    feats = df.withColumns({
        "I1": log1p(col("I1").fillna(0.0)),
        "I2": log1p(col("I2").fillna(0.0)),
        "C1": hash_bucket(col("C1"), vocab[0]),
        "C2": hash_bucket(col("C2"), vocab[1]),
    })
    ds = feats.to_dataset(vector_columns={"dense": ["I1", "I2"],
                                          "sparse": ["C1", "C2"]})
    examples = ds.take(16)
    assert examples[0]["dense"].shape == (2,) and examples[0]["sparse"].shape == (2,)

    batch = stack_examples(examples)
    batch["label"] = batch.pop("label").astype(np.int32)
    batch["dense"] = np.pad(batch["dense"].astype(np.float32),
                            ((0, 0), (0, 11)))  # DLRM wants 13 dense
    mesh = MeshSpec(data=-1).build()
    model = DLRM(vocab_sizes=vocab, embed_dim=8, bottom_mlp=(16, 8),
                 top_mlp=(16, 1))
    state, shardings = step_lib.init_state(
        model, optax.adagrad(1e-2), batch, mesh, dlrm_rules())
    train_step = step_lib.jit_train_step(
        step_lib.make_train_step(model.apply, optax.adagrad(1e-2),
                                 losses.binary_xent),
        mesh, shardings)
    state, metrics = train_step(state, put_global(batch, mesh))
    assert np.isfinite(float(np.asarray(metrics["loss"])))


def test_column_repr_names():
    c = (col("a") + 1).alias("b")
    assert c.name == "b"
    assert (col("x") * col("y")).name == "(x * y)"
    assert df_mod.clip(col("x"), 0, 1).name == "clip(x)"


def test_read_parquet_single_file_row_groups(tmp_path):
    pa = pytest.importorskip("pyarrow")
    pq = pytest.importorskip("pyarrow.parquet")

    t = pa.table({"x": np.arange(100, dtype=np.float32),
                  "s": [f"u{i % 5}" for i in range(100)]})
    p = tmp_path / "t.parquet"
    pq.write_table(t, p, row_group_size=25)  # 4 row groups
    df = read_parquet(str(p), num_partitions=2)
    assert df.columns == ["x", "s"]
    assert df.num_partitions == 2
    assert df.count() == 100
    out = df.withColumn("x2", col("x") * 2).take(3)
    assert out[2]["x2"] == 4.0
    # column projection
    dfx = read_parquet(str(p), columns=["x"], num_partitions=2)
    assert dfx.columns == ["x"]


def test_read_parquet_multi_file_and_reader_surface(tmp_path):
    pa = pytest.importorskip("pyarrow")
    pq = pytest.importorskip("pyarrow.parquet")

    for i in range(3):
        pq.write_table(pa.table({"v": np.full(4, i, np.int64)}),
                       tmp_path / f"part-{i}.parquet")
    df = (DataFrameReader(default_parallelism=8)
          .parquet(str(tmp_path / "part-*.parquet")))
    assert df.num_partitions == 3  # clamped to file count
    assert sorted(np.unique([r["v"] for r in df.collect()]).tolist()) == [0, 1, 2]
    with pytest.raises(FileNotFoundError):
        read_parquet(str(tmp_path / "nope-*.parquet"))


def test_reader_parquet_applies_schema_dtypes(tmp_path):
    pa = pytest.importorskip("pyarrow")
    pq = pytest.importorskip("pyarrow.parquet")

    pq.write_table(pa.table({"x": np.array([1.7, 2.2])}), tmp_path / "d.parquet")
    df = (DataFrameReader(default_parallelism=1)
          .schema(["x"], {"x": np.int32}).parquet(str(tmp_path / "d.parquet")))
    vals = [r["x"] for r in df.collect()]
    assert all(isinstance(v, np.int32) for v in vals)
    assert vals == [1, 2]


def test_expand_paths_literal_with_glob_chars(tmp_path):
    p = tmp_path / "data[1].csv"
    p.write_text("5\n")
    df = read_csv(str(p), names=["v"], num_partitions=1)
    assert df.collect()[0]["v"] == 5.0


def test_read_parquet_directory(tmp_path):
    pa = pytest.importorskip("pyarrow")
    pq = pytest.importorskip("pyarrow.parquet")

    d = tmp_path / "out"
    d.mkdir()
    for i in range(2):
        pq.write_table(pa.table({"v": np.full(3, i, np.int64)}),
                       d / f"part-{i}.parquet")
    df = read_parquet(str(d), num_partitions=2)
    assert df.count() == 6


def test_groupby_agg_across_partitions():
    """groupBy().agg(): per-chunk vectorized partials must merge exactly —
    groups spanning partitions get one output row with the global
    count/sum/mean/min/max."""
    rows = [{"cat": i % 3, "x": float(i)} for i in range(12)]
    df = df_mod.from_rows(rows, num_partitions=3, chunk_rows=2)
    out = df.groupBy("cat").agg({"x": "mean"}).collect()
    got = {r["cat"]: r["mean(x)"] for r in out}
    want = {c: np.mean([r["x"] for r in rows if r["cat"] == c])
            for c in (0, 1, 2)}
    assert got == want
    # every agg fn, one pass each
    for fn, expect in [("sum", 18.0), ("min", 0.0), ("max", 9.0),
                       ("count", 4)]:
        r0 = {r["cat"]: r[f"{fn}(x)"]
              for r in df.groupBy("cat").agg({"x": fn}).collect()}
        assert r0[0] == expect, (fn, r0)


def test_groupby_count_and_multikey():
    rows = [{"a": 1, "b": 10, "x": 1.0}, {"a": 1, "b": 10, "x": 2.0},
            {"a": 1, "b": 20, "x": 3.0}, {"a": 2, "b": 10, "x": 4.0}]
    df = df_mod.from_rows(rows, num_partitions=2, chunk_rows=1)
    counts = {(r["a"], r["b"]): r["count"]
              for r in df.groupBy("a", "b").count().collect()}
    assert counts == {(1, 10): 2, (1, 20): 1, (2, 10): 1}


def test_groupby_rejects_bad_keys_and_spec():
    df = df_mod.from_rows([{"a": 1, "x": 2.0}])
    with pytest.raises(ValueError, match="groupBy keys"):
        df.groupBy("nope")
    with pytest.raises(ValueError, match="agg spec"):
        df.groupBy("a").agg({"x": "median"})
    with pytest.raises(ValueError, match="agg spec"):
        df.groupBy("a").agg({})


def test_groupby_count_on_string_keys_and_null_guard():
    """count() must not coerce the key column to float (string categories
    are the primary count-feature case), and None-bearing object keys must
    fail with a message naming the column, not a numpy internals error."""
    rows = [{"cat": "a", "x": 1.0}, {"cat": "b", "x": 2.0},
            {"cat": "a", "x": 3.0}]
    df = df_mod.from_rows(rows, num_partitions=2, chunk_rows=1)
    got = {r["cat"]: r["count"] for r in df.groupBy("cat").count().collect()}
    assert got == {"a": 2, "b": 1}
    bad = df_mod.from_rows([{"cat": "a", "x": 1.0},
                            {"cat": None, "x": 2.0}], num_partitions=1)
    with pytest.raises(ValueError, match="groupBy key 'cat'"):
        # agg is lazy (module contract) — the guard fires on first scan
        bad.groupBy("cat").agg({"x": "sum"}).collect()


def test_groupby_nan_key_guard_and_lazy():
    """NaN keys must fail loudly (NaN != NaN would split groups per chunk),
    a key literally named 'count' must not be silently destroyed by
    .count(), and agg() must stay lazy like every other verb."""
    bad = df_mod.from_rows([{"k": np.nan, "x": 1.0},
                            {"k": np.nan, "x": 2.0}], num_partitions=1)
    with pytest.raises(ValueError, match="contains NaN"):
        bad.groupBy("k").agg({"x": "sum"}).collect()
    named = df_mod.from_rows([{"count": 1, "x": 2.0}])
    with pytest.raises(ValueError, match="named 'count'"):
        named.groupBy("count").count()
    # laziness: constructing the agg must not scan the source
    scans = [0]

    def gen():
        scans[0] += 1
        yield {"k": np.asarray([1, 1]), "x": np.asarray([1.0, 2.0])}

    from distributeddeeplearningspark_tpu.rdd import PartitionedDataset
    lazy_df = df_mod.DataFrame(
        PartitionedDataset.from_generators([gen]), ["k", "x"])
    out = lazy_df.groupBy("k").agg({"x": "sum"})
    assert scans[0] == 0  # construction scanned nothing
    assert out.collect() == [{"k": 1, "sum(x)": 3.0}]
    assert scans[0] == 1
    out.collect()
    assert scans[0] == 1  # memoized, cache() semantics


def test_groupby_agg_cardinality_guard(monkeypatch):
    """VERDICT r5 weak-#7: a high-cardinality (user-id-like) key must refuse
    loudly at the configurable ceiling — with the hash_bucket remediation
    named — instead of silently growing an unbounded driver-side dict."""
    rows = [{"user_id": i, "x": float(i)} for i in range(100)]
    df = df_mod.from_rows(rows, num_partitions=2, chunk_rows=8)
    with pytest.raises(ValueError, match="hash_bucket"):
        df.groupBy("user_id").agg({"x": "sum"}, max_groups=10).collect()
    with pytest.raises(ValueError, match="max_groups=10"):
        df.groupBy("user_id").agg({"x": "sum"}, max_groups=10).collect()
    # env ceiling is the default; explicit kwarg still wins
    monkeypatch.setenv("DLS_AGG_MAX_GROUPS", "10")
    with pytest.raises(ValueError, match="hash_bucket"):
        df.groupBy("user_id").agg({"x": "sum"}).collect()
    out = df.groupBy("user_id").agg({"x": "sum"}, max_groups=100).collect()
    assert len(out) == 100
    monkeypatch.delenv("DLS_AGG_MAX_GROUPS")
    # vocab-sized keys stay well under the default ceiling: unchanged
    small = df_mod.from_rows(
        [{"cat": i % 3, "x": 1.0} for i in range(30)], num_partitions=2)
    assert len(small.groupBy("cat").agg({"x": "sum"}).collect()) == 3
    with pytest.raises(ValueError, match="max_groups must be"):
        df.groupBy("user_id").agg({"x": "sum"}, max_groups=0)
