"""Elastic supervisor + fault injection + desync sanitizer (SURVEY.md §4/§5).

These run REAL multi-process jax.distributed gangs (gloo collectives over
localhost) — the rebuild's analogue of the reference's `local[2]` two-executor
Spark testbed, including the kill-one-process recovery drill.
"""

import functools
import os
import subprocess
import sys

import numpy as np
import pytest

from distributeddeeplearningspark_tpu.supervisor import (
    Supervisor,
    SupervisorResult,
    free_port,
)

WORKER = os.path.join(os.path.dirname(__file__), "workers", "worker.py")

# Worker processes must NOT inherit the 8-fake-device flag the test process
# uses — each gang member is one "executor" with its own single CPU device.
_CLEAN_ENV = {"XLA_FLAGS": "", "JAX_PLATFORMS": "cpu"}

# Minimal 2-process rendezvous + one cross-process collective — exactly the
# machinery every gang drill below depends on, nothing else.
_GANG_PROBE = """\
import os
import jax
jax.distributed.initialize(coordinator_address=os.environ["DLS_COORDINATOR"],
                           num_processes=2,
                           process_id=int(os.environ["DLS_PROCESS_ID"]))
from jax.experimental import multihost_utils
multihost_utils.sync_global_devices("gang-probe")
"""


@functools.lru_cache(maxsize=1)
def _gang_skip_reason() -> str | None:
    """Capability probe, run once per session: can this jax build actually
    execute CPU multiprocess collectives? Some builds rendezvous fine and
    then die at the first cross-process psum with "Multiprocess
    computations aren't implemented on the CPU backend" — an environmental
    limit, not a supervisor bug, so the real-gang drills SKIP with the
    probe's evidence instead of failing every full-suite run on such
    builds."""
    port = free_port()
    base_env = {**os.environ, **_CLEAN_ENV,
                "DLS_COORDINATOR": f"localhost:{port}"}
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _GANG_PROBE],
            env={**base_env, "DLS_PROCESS_ID": str(pid)},
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        for pid in range(2)
    ]
    tails = []
    for p in procs:
        try:
            _, err = p.communicate(timeout=120)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
                q.wait()
            return "2-process CPU gang probe hung at rendezvous/collective"
        if p.returncode != 0 and err.strip():
            tails.append(err.strip().splitlines()[-1])
    if all(p.returncode == 0 for p in procs):
        return None
    return ("this jax build cannot run CPU multiprocess collectives: "
            + (tails[0][:160] if tails else "probe worker died"))


@pytest.fixture()
def gang():
    """Skip (with the probe's evidence) when real multi-process gangs
    cannot run here; the probe result is cached for the session."""
    reason = _gang_skip_reason()
    if reason:
        pytest.skip(reason)


@pytest.mark.slow
def test_gang_completes_without_faults(tmp_path, gang):
    sup = Supervisor(
        [sys.executable, WORKER, "train", "--ckpt-dir", str(tmp_path),
         "--steps", "10", "--checkpoint-every", "5"],
        num_processes=2, max_restarts=0, env=_CLEAN_ENV,
    )
    result = sup.run()
    assert result.ok and result.restarts == 0
    step, attempt = open(tmp_path / "DONE").read().split()
    assert int(step) == 10 and int(attempt) == 0


@pytest.mark.slow
def test_kill_one_worker_recovers_from_checkpoint(tmp_path, gang):
    """Process 1 SIGKILLs itself at step 15 of 30 on attempt 0; the supervisor
    tears down the gang and relaunches; workers resume from the step-10
    checkpoint and finish."""
    sup = Supervisor(
        [sys.executable, WORKER, "train", "--ckpt-dir", str(tmp_path),
         "--steps", "30", "--checkpoint-every", "10", "--fault-step", "15"],
        num_processes=2, max_restarts=2, env=_CLEAN_ENV,
        hang_timeout_s=120.0, progress_path=str(tmp_path),
    )
    result = sup.run()
    assert result.ok, f"attempts: {[(a.ordinal, a.returncodes) for a in result.attempts]}"
    assert result.restarts == 1
    # SIGKILL shows up as -9 on the faulted attempt
    assert -9 in result.attempts[0].returncodes
    step, attempt = open(tmp_path / "DONE").read().split()
    assert int(step) == 30 and int(attempt) == 1


@pytest.mark.slow
def test_two_process_gang_matches_single_process_numerics(tmp_path, eight_devices, gang):
    """VERDICT r4 next-#8: the DCN control-plane analog of the dryrun's
    single-process fingerprint. A 2-process × 4-device jax.distributed
    gang runs 5 deterministic DP steps; post-step params must equal a
    single-process run over the same 8-device topology numerically — the
    supervisor drills prove processes LIVE across the boundary, this
    proves the numbers CROSS it unchanged (same recipe function on both
    sides, so only the process boundary can differ)."""
    import importlib.util

    out = tmp_path / "gang.npz"
    sup = Supervisor(
        [sys.executable, WORKER, "fingerprint", "--steps", "5",
         "--batch-size", "32", "--out", str(out)],
        num_processes=2, max_restarts=0,
        env={"XLA_FLAGS": "--xla_force_host_platform_device_count=4",
             "JAX_PLATFORMS": "cpu"},
    )
    result = sup.run()
    assert result.ok, f"returncodes: {result.attempts[-1].returncodes}"
    gang = dict(np.load(out))

    from distributeddeeplearningspark_tpu.parallel.mesh import MeshSpec

    spec = importlib.util.spec_from_file_location("fp_worker", WORKER)
    wmod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(wmod)
    ref = wmod.fingerprint_reference(
        5, 32, MeshSpec(data=-1).build(eight_devices))
    assert gang.keys() == ref.keys()
    for k in ref:
        np.testing.assert_allclose(gang[k], ref[k], rtol=1e-5, atol=1e-6,
                                   err_msg=k)


@pytest.mark.slow
def test_desync_sanitizer_catches_split_brain(tmp_path, gang):
    sup = Supervisor(
        [sys.executable, WORKER, "desync"],
        num_processes=2, max_restarts=0, env=_CLEAN_ENV,
    )
    result = sup.run()
    assert result.ok, f"returncodes: {result.attempts[-1].returncodes}"


def test_supervisor_gives_up_after_max_restarts():
    sup = Supervisor(
        [sys.executable, "-c", "import sys; sys.exit(7)"],
        num_processes=1, max_restarts=2, restart_backoff_s=0.01,
    )
    result = sup.run()
    assert not result.ok
    assert len(result.attempts) == 3
    assert all(a.returncodes == [7] for a in result.attempts)
    # no progress tracking configured and no checkpoint dir → a plain crash,
    # never misclassified as a restore failure
    assert all(a.classification == "training-crash" for a in result.attempts)


def test_restart_backoff_grows_exponentially_with_cap():
    """Satellite: the relaunch delay doubles per attempt from the base and
    saturates at restart_backoff_max_s (jitter disabled for determinism)."""
    s = Supervisor(["true"], restart_backoff_s=0.5, restart_backoff_max_s=3.0,
                   backoff_jitter=0.0)
    assert [s._backoff_delay(i) for i in range(5)] == [0.5, 1.0, 2.0, 3.0, 3.0]
    j = Supervisor(["true"], restart_backoff_s=1.0, restart_backoff_max_s=8.0,
                   backoff_jitter=0.25)
    for i in range(4):
        base = min(1.0 * 2 ** i, 8.0)
        d = j._backoff_delay(i)
        assert 0.75 * base <= d <= 1.25 * base, (i, d)


def test_restart_backoff_timing_observed(monkeypatch):
    """The run loop actually waits the exponential delays between attempts
    (sleep calls recorded; poll-interval sleeps are distinguishable)."""
    from distributeddeeplearningspark_tpu import supervisor as sup_mod

    sleeps: list[float] = []
    real_sleep = sup_mod.time.sleep
    monkeypatch.setattr(
        sup_mod.time, "sleep",
        lambda s: (sleeps.append(s), real_sleep(min(s, 0.01)))[1])
    result = Supervisor(
        [sys.executable, "-c", "import sys; sys.exit(7)"],
        max_restarts=2, restart_backoff_s=0.15, backoff_jitter=0.0,
        poll_interval=0.01,
    ).run()
    assert len(result.attempts) == 3
    backoffs = [s for s in sleeps if s > 0.01]
    assert backoffs == [0.15, 0.3], backoffs


def test_backoff_resets_after_observed_progress(tmp_path, monkeypatch):
    """Satellite: an attempt with OBSERVED progress evidence resets the
    backoff ladder — a run that trains for a while and then crashes backs
    off from the base delay, not from its early flaky attempts' doubled
    ceiling. (Without progress tracking the ladder still doubles — see
    test_restart_backoff_timing_observed — because made_progress is then
    only assumed.)"""
    from distributeddeeplearningspark_tpu import supervisor as sup_mod

    script = tmp_path / "worker.py"
    script.write_text(
        "import os, sys\n"
        "open(os.path.join(os.environ['PROG'], 'touch'), 'w').write('x')\n"
        "sys.exit(7)\n"
    )
    prog = tmp_path / "prog"
    prog.mkdir()
    sleeps: list[float] = []
    real_sleep = sup_mod.time.sleep
    monkeypatch.setattr(
        sup_mod.time, "sleep",
        lambda s: (sleeps.append(s), real_sleep(min(s, 0.01)))[1])
    result = Supervisor(
        [sys.executable, str(script)],
        max_restarts=2, restart_backoff_s=0.15, backoff_jitter=0.0,
        poll_interval=0.01, progress_path=str(prog),
        env={"PROG": str(prog)},
    ).run()
    assert len(result.attempts) == 3
    assert all(a.made_progress for a in result.attempts)
    backoffs = [s for s in sleeps if s > 0.01]
    # every attempt made real progress → every delay is the base, no doubling
    assert backoffs == [0.15, 0.15], backoffs


def test_shrink_to_survive_drops_dead_host(tmp_path):
    """Fast-tier shrink drill (plain-python workers): host 1 dies on every
    attempt; after shrink_after=2 consecutive same-host failures the gang
    re-plans to the surviving host — which then finishes — and the
    geometry_change recovery record ties evidence to action. Host identity
    (DLS_HOST_ID) stays stable across the rank renumbering."""
    from distributeddeeplearningspark_tpu import telemetry

    (tmp_path / "10").mkdir()  # the "last checkpoint" the relaunch resumes
    script = tmp_path / "worker.py"
    script.write_text(
        "import os, sys, time\n"
        "host = os.environ.get('DLS_HOST_ID', os.environ['DLS_PROCESS_ID'])\n"
        "if host == '1':\n"
        "    sys.exit(1)\n"
        "if os.environ['DLS_NUM_PROCESSES'] == '1':\n"
        "    with open(os.path.join(sys.argv[1], 'DONE'), 'w') as f:\n"
        "        f.write(os.environ['DLS_RESTART'] + ' '\n"
        "                + os.environ['DLS_HOST_ID'])\n"
        "    sys.exit(0)\n"
        "time.sleep(30)\n"  # healthy peer: killed when host 1 dies
    )
    sup = Supervisor(
        [sys.executable, str(script), str(tmp_path)],
        num_processes=2, max_restarts=3, restart_backoff_s=0.01,
        backoff_jitter=0.0, ckpt_dir=str(tmp_path), shrink_after=2,
    )
    result = sup.run()
    assert result.ok, [(a.returncodes, a.classification) for a in result.attempts]
    assert [a.num_processes for a in result.attempts] == [2, 2, 1]
    assert [a.dead_host for a in result.attempts] == [1, 1, None]
    attempt, host = open(tmp_path / "DONE").read().split()
    assert (attempt, host) == ("2", "0")  # survivor kept its host identity
    geo = [e for e in telemetry.read_events(tmp_path)
           if e.get("kind") == "recovery" and e["event"] == "geometry_change"]
    assert len(geo) == 1
    assert geo[0]["dead_host"] == 1 and geo[0]["hosts"] == [0]
    assert geo[0]["from_processes"] == 2 and geo[0]["to_processes"] == 1
    assert geo[0]["step"] == 10  # the checkpoint the survivors resume from
    assert geo[0]["batch_policy"] == "preserve_global"


def test_shrink_respects_min_processes(tmp_path):
    """The gang never shrinks below min_processes — a persistent dead host
    in a gang already at the floor burns restarts instead of amputating to
    nothing."""
    script = "import sys; sys.exit(1)\n"
    sup = Supervisor(
        [sys.executable, "-c", script],
        num_processes=2, max_restarts=3, restart_backoff_s=0.01,
        backoff_jitter=0.0, shrink_after=2, min_processes=2,
    )
    result = sup.run()
    assert not result.ok
    assert all(a.num_processes == 2 for a in result.attempts)


def test_result_shapes():
    r = SupervisorResult(attempts=[])
    assert not r.ok and r.restarts == 0


def test_startup_grace_defaults_to_5x_hang_timeout():
    """ADVICE r1: first-checkpoint latency (compile + warmup) must not be
    judged by the steady-state hang timeout."""
    s = Supervisor(["true"], hang_timeout_s=2.0)
    assert s.startup_grace_s == 10.0
    s2 = Supervisor(["true"], hang_timeout_s=2.0, startup_grace_s=30.0)
    assert s2.startup_grace_s == 30.0
    assert Supervisor(["true"]).startup_grace_s is None


def test_heartbeat_file_counts_as_progress(tmp_path):
    """ADVICE r1: a worker stamping DLS_HEARTBEAT_FILE between checkpoints
    must not be judged hung by the watchdog."""
    # worker: stamps the heartbeat every 0.2s for 2.5s, never checkpoints
    script = tmp_path / "worker.py"
    script.write_text(
        "import os, time\n"
        "hb = os.environ['DLS_HEARTBEAT_FILE']\n"
        "for _ in range(12):\n"
        "    open(hb, 'w').write('x')\n"
        "    time.sleep(0.2)\n"
    )
    # hang_timeout (steady state) is far shorter than the worker's runtime,
    # so only the heartbeats keep it alive; startup grace stays generous —
    # python startup in this sandbox alone takes >1s (site hooks)
    s = Supervisor([sys.executable, str(script)], num_processes=1,
                   max_restarts=0, hang_timeout_s=1.0, startup_grace_s=30.0,
                   progress_path=str(tmp_path / "ckpt-does-not-exist"))
    result = s.run()
    assert result.ok, f"healthy heartbeating worker was killed: {result}"
    assert result.attempts[0].returncodes == [0]
