"""Loss-function unit tests — the eval_mask row-weighting contract.

VERDICT r3 missing-#5: `Trainer.evaluate` pads sub-shard tails with
``eval_mask == 0`` rows (data/feed.py `_pad_to_shards`); every contract loss
must (a) exclude those rows from every mean exactly and (b) report the real
weight so the weighted-mean aggregation stays exact. These tests prove (a)/(b)
directly against hand-computed references, independent of the Trainer plumbing
(tests/test_train_mnist.py covers the end-to-end path).
"""

import jax.numpy as jnp
import numpy as np

from distributeddeeplearningspark_tpu.train import losses


def test_softmax_xent_eval_mask_excludes_pad_rows():
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(0, 1, (6, 10)).astype(np.float32))
    labels = jnp.asarray(rng.integers(0, 10, (6,)).astype(np.int32))
    mask = jnp.asarray([1, 1, 1, 1, 0, 0], jnp.float32)

    full, m_full = losses.softmax_xent(
        logits[:4], {"label": labels[:4]})
    masked, m_masked = losses.softmax_xent(
        logits, {"label": labels, "eval_mask": mask})
    np.testing.assert_allclose(float(masked), float(full), rtol=1e-6)
    np.testing.assert_allclose(float(m_masked["accuracy"]),
                               float(m_full["accuracy"]), rtol=1e-6)
    np.testing.assert_allclose(float(m_masked["top5_accuracy"]),
                               float(m_full["top5_accuracy"]), rtol=1e-6)
    assert float(m_masked["weight"]) == 4.0
    assert "weight" not in m_full  # unpadded batches keep the legacy shape


def test_binary_xent_eval_mask_excludes_pad_rows():
    rng = np.random.default_rng(1)
    logits = jnp.asarray(rng.normal(0, 1, (5, 1)).astype(np.float32))
    labels = jnp.asarray(rng.integers(0, 2, (5,)).astype(np.int32))
    mask = jnp.asarray([1, 1, 1, 0, 0], jnp.float32)

    full, m_full = losses.binary_xent(logits[:3], {"label": labels[:3]})
    masked, m_masked = losses.binary_xent(
        logits, {"label": labels, "eval_mask": mask})
    np.testing.assert_allclose(float(masked), float(full), rtol=1e-6)
    np.testing.assert_allclose(float(m_masked["accuracy"]),
                               float(m_full["accuracy"]), rtol=1e-6)
    assert float(m_masked["weight"]) == 3.0


def test_masked_lm_eval_mask_zeroes_pad_row_tokens():
    rng = np.random.default_rng(2)
    b, s, v = 4, 8, 32
    logits = jnp.asarray(rng.normal(0, 1, (b, s, v)).astype(np.float32))
    ids = jnp.asarray(rng.integers(0, v, (b, s)).astype(np.int32))
    w = jnp.asarray(rng.random((b, s)) < 0.5, jnp.float32)
    batch = {"mlm_labels": ids, "mlm_weights": w}

    full, m_full = losses.masked_lm(
        logits[:2], {k: val[:2] for k, val in batch.items()})
    masked, m_masked = losses.masked_lm(
        logits, {**batch, "eval_mask": jnp.asarray([1, 1, 0, 0], jnp.float32)})
    np.testing.assert_allclose(float(masked), float(full), rtol=1e-5)
    # weight = surviving mask count, NOT the padded batch's
    assert float(m_masked["weight"]) == float(w[:2].sum())


def test_causal_lm_eval_mask_with_and_without_loss_mask():
    rng = np.random.default_rng(3)
    b, s, v = 4, 8, 32
    logits = jnp.asarray(rng.normal(0, 1, (b, s, v)).astype(np.float32))
    ids = jnp.asarray(rng.integers(0, v, (b, s)).astype(np.int32))
    em = jnp.asarray([1, 1, 1, 0], jnp.float32)

    # with an explicit loss_mask
    lm = jnp.asarray(rng.random((b, s)) < 0.7, jnp.float32)
    full, _ = losses.causal_lm(
        logits[:3], {"input_ids": ids[:3], "loss_mask": lm[:3]})
    masked, m = losses.causal_lm(
        logits, {"input_ids": ids, "loss_mask": lm, "eval_mask": em})
    np.testing.assert_allclose(float(masked), float(full), rtol=1e-5)
    assert float(m["weight"]) == float(lm[:3, 1:].sum())

    # without one (eval_mask alone synthesizes the token mask)
    full2, _ = losses.causal_lm(logits[:3], {"input_ids": ids[:3]})
    masked2, m2 = losses.causal_lm(
        logits, {"input_ids": ids, "eval_mask": em})
    np.testing.assert_allclose(float(masked2), float(full2), rtol=1e-5)
    assert float(m2["weight"]) == 3 * (s - 1)
