"""Portable cross-topology redistribution (parallel/reshard.py) — the data
and spec layers behind elastic reshard-on-restore (ISSUE 11, PAPERS.md
2112.01075's all-gather/dynamic-slice framing).

The acceptance invariant pinned here: moving state between layouts is
BITWISE — fsdp-saved → tensor-restored → replicated round-trips change
where bytes live, never what they are.
"""

import jax
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from distributeddeeplearningspark_tpu.parallel import reshard
from distributeddeeplearningspark_tpu.parallel.mesh import MeshSpec


def _host(x):
    return np.asarray(jax.device_get(x))


@pytest.fixture()
def meshes(eight_devices):
    return {
        "fsdp": MeshSpec(data=2, fsdp=4).build(),
        "tensor": MeshSpec(data=1, tensor=8).build(),
        "dp": MeshSpec(data=8).build(),
        "half": MeshSpec(data=1, fsdp=4).build(jax.devices()[:4]),
    }


# -- spec re-projection -------------------------------------------------------


def test_spec_record_round_trip():
    for spec in (P(), P(None, "fsdp"), P(("data", "fsdp"), None),
                 P("tensor")):
        rec = reshard.spec_to_record(spec)
        assert reshard.spec_from_record(rec) == spec
        # records are JSON-clean (lists/strings/None only)
        import json

        json.dumps(rec)


def test_project_spec_keeps_divisible_axes(meshes):
    # fsdp=4 on the source survives onto the 4-wide fsdp of the half mesh
    assert (reshard.project_spec(P("fsdp", None), (64, 16), meshes["half"])
            == P("fsdp", None))
    # ...degrades to replicated where the target axis is width 1
    assert (reshard.project_spec(P("fsdp", None), (64, 16), meshes["tensor"])
            == P(None, None))
    # ...and where the dim no longer divides (65 % 4 != 0)
    assert (reshard.project_spec(P("fsdp", None), (65, 16), meshes["half"])
            == P(None, None))


def test_project_spec_tuple_entries(meshes):
    # ("data","fsdp") batch-style entries keep the members that still fit
    out = reshard.project_spec(P(("data", "fsdp"), None), (64, 16),
                               meshes["half"])
    assert out == P("fsdp", None)


def test_shardings_from_record_unknown_leaf_replicates(meshes):
    record = {"specs": {"w": ["fsdp", None]}}
    abstract = {"w": jax.ShapeDtypeStruct((64, 16), np.float32),
                "new_leaf": jax.ShapeDtypeStruct((8,), np.float32)}
    sh = reshard.shardings_from_record(record, abstract, meshes["half"])
    assert sh["w"].spec == P("fsdp", None)
    assert sh["new_leaf"].spec == P()


def test_shardings_from_record_uneven_leaf_degrades(meshes):
    """A recorded axis whose dim no longer divides the target mesh width
    must degrade that leaf to replicated — never crash the restore with a
    divisibility error deep in XLA."""
    record = {"specs": {"even": ["fsdp", None], "odd": ["fsdp", None]}}
    abstract = {"even": jax.ShapeDtypeStruct((64, 16), np.float32),
                "odd": jax.ShapeDtypeStruct((65, 16), np.float32)}
    sh = reshard.shardings_from_record(record, abstract, meshes["half"])
    assert sh["even"].spec == P("fsdp", None)
    assert sh["odd"].spec == P(None, None)


def test_shardings_from_record_zero_d_scalar(meshes):
    """0-d leaves (step counters, schedule counts) always come back
    replicated — even when the record carries junk for them."""
    record = {"specs": {"step": ["fsdp"], "count": []}}
    abstract = {"step": jax.ShapeDtypeStruct((), np.int32),
                "count": jax.ShapeDtypeStruct((), np.float32)}
    sh = reshard.shardings_from_record(record, abstract, meshes["half"])
    assert sh["step"].spec == P()
    assert sh["count"].spec == P()
    # and a 0-d leaf moves bitwise between meshes
    from jax.sharding import NamedSharding as NS

    s = jax.device_put(np.float32(3.5), NS(meshes["fsdp"], P()))
    out = reshard.redistribute({"s": s}, {"s": NS(meshes["dp"], P())})
    assert float(out["s"]) == 3.5 and out["s"].ndim == 0


def test_shardings_from_record_opt_state_without_specs(meshes):
    """Optimizer-state leaves a pre-live checkpoint never recorded specs
    for replicate cleanly instead of guessing — the spec keys cover params
    only, the abstract tree carries the full TrainState paths."""
    record = {"specs": {"params/w": ["fsdp", None]}}
    abstract = {
        "params": {"w": jax.ShapeDtypeStruct((64, 16), np.float32)},
        "opt_state": {"mu": {"w": jax.ShapeDtypeStruct((64, 16),
                                                       np.float32)},
                      "count": jax.ShapeDtypeStruct((), np.int32)},
    }
    sh = reshard.shardings_from_record(record, abstract, meshes["half"])
    assert sh["params"]["w"].spec == P("fsdp", None)
    assert sh["opt_state"]["mu"]["w"].spec == P()
    assert sh["opt_state"]["count"].spec == P()


def test_reshard_error_names_escape_hatch(tmp_path, meshes):
    """The typed refusal when a checkpoint's recorded topology cannot be
    rebuilt here must name BOTH escape hatches (shardings / mesh=) — the
    operator fixes this from the message alone (POD_PLAYBOOK)."""
    from distributeddeeplearningspark_tpu.checkpoint import (
        Checkpointer,
        ReshardError,
    )

    with Checkpointer(tmp_path / "ck", async_save=False) as ck:
        with pytest.raises(ReshardError, match="shardings") as ei:
            ck._reshard_check(5, {"num_devices": 4096, "num_processes": 512,
                                  "mesh": {"data": 4096}})
    assert "mesh=" in str(ei.value)
    assert "4096" in str(ei.value)


# -- data movement ------------------------------------------------------------


def test_redistribute_round_trip_bitwise(meshes):
    """fsdp → tensor → replicated → fsdp: every hop preserves bytes."""
    x_host = np.arange(64 * 16, dtype=np.float32).reshape(64, 16)
    x = jax.device_put(x_host, NamedSharding(meshes["fsdp"], P("fsdp", None)))
    hops = [NamedSharding(meshes["tensor"], P(None, "tensor")),
            NamedSharding(meshes["dp"], P()),
            NamedSharding(meshes["fsdp"], P("fsdp", None))]
    tree = {"w": x}
    for target in hops:
        tree = reshard.redistribute(tree, {"w": target})
        assert tree["w"].sharding.is_equivalent_to(target, 2)
        assert _host(tree["w"]).tobytes() == x_host.tobytes()


def test_redistribute_noop_on_equivalent_layout(meshes):
    x = jax.device_put(np.ones((8, 8), np.float32),
                       NamedSharding(meshes["dp"], P()))
    out = reshard.redistribute({"w": x}, {"w": NamedSharding(meshes["dp"], P())})
    assert out["w"] is x  # no copy when already placed right


def test_assembly_fallback_matches_device_put(meshes, monkeypatch):
    """The explicit shard-assembly path (what runs when device_put refuses a
    mesh pair) produces the same bytes and layout as the fast path."""
    x_host = np.arange(32 * 24, dtype=np.float32).reshape(32, 24)
    x = jax.device_put(x_host, NamedSharding(meshes["fsdp"], P(None, "fsdp")))
    target = NamedSharding(meshes["tensor"], P("tensor", None))

    real_put = jax.device_put

    def refuse_sharded(v, s=None, **kw):
        if hasattr(s, "spec"):
            raise ValueError("forced fallback")
        return real_put(v, s, **kw)

    monkeypatch.setattr(jax, "device_put", refuse_sharded)
    out = reshard._reshard_leaf(x, target)
    assert _host(out).tobytes() == x_host.tobytes()
    assert out.sharding.is_equivalent_to(target, 2)


def test_assembly_reports_missing_span():
    """A target span no local shard covers raises the typed error naming
    the recovery action (restore from the shared checkpoint)."""
    shape = (16, 4)
    span = [(0, 16), (0, 4)]
    # only rows 0..8 available
    sources = [([(0, 8), (0, 4)], np.zeros((8, 4), np.float32))]
    with pytest.raises(reshard.SpanUnavailableError, match="checkpoint"):
        reshard._assemble_block(shape, span, sources)


def test_geometry_of_records_mesh_and_specs(meshes):
    x = jax.device_put(np.zeros((64, 16), np.float32),
                       NamedSharding(meshes["fsdp"], P("fsdp", None)))
    g = reshard.geometry_of({"a": {"w": x}, "scalar": 3})
    assert g["num_devices"] == 8
    assert g["mesh"]["fsdp"] == 4 and g["mesh"]["data"] == 2
    assert g["specs"]["a/w"] == ["fsdp", None]
    assert g["num_processes"] == 1
    assert reshard.geometry_of({"host_only": np.zeros(3)}) is None
