"""Health engine: rules, flap damping, alert edges, health.json, cluster.

Everything runs on fake clocks — no sleeps, no real-time dependence — the
engine takes an injectable clock and every rule is a pure fold over
timestamped records. The live end-to-end drill (faulted serving fleet →
CRIT naming the replica → clean rerun → clear edge) is ``tools/ci.sh
health``; this file pins the contracts it relies on.
"""

import json
import os

import pytest

from distributeddeeplearningspark_tpu import status, telemetry
from distributeddeeplearningspark_tpu.telemetry import fleet as fleet_lib
from distributeddeeplearningspark_tpu.telemetry import health
from distributeddeeplearningspark_tpu.telemetry import trace as trace_lib


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def tick(self, dt):
        self.t += dt


def _writer(tmp_path, process="p0", t0=0.0, **kw):
    clock = FakeClock(t0)
    return telemetry.EventWriter(tmp_path, process=process, clock=clock,
                                 **kw), clock


def _alert_events(workdir):
    return [e for e in telemetry.read_events(workdir)
            if e.get("kind") == "alert"]


# -- incremental reads: EventCursor ------------------------------------------


def test_cursor_second_poll_parses_only_appended_lines(tmp_path):
    w, clock = _writer(tmp_path)
    for step in (1, 2, 3):
        w.heartbeat(step=step)
        clock.tick(1.0)
    cur = telemetry.EventCursor(tmp_path)
    first = cur.poll()
    assert [e["step"] for e in first] == [1, 2, 3]
    # append two more; the second poll must surface exactly those two
    w.heartbeat(step=4)
    clock.tick(1.0)
    w.heartbeat(step=5)
    w.close()
    second = cur.poll()
    assert [e["step"] for e in second] == [4, 5]
    assert [e["step"] for e in cur.events] == [1, 2, 3, 4, 5]
    # nothing new -> empty, state unchanged
    assert cur.poll() == []
    assert len(cur.events) == 5


def test_cursor_holds_back_torn_tail_until_completed(tmp_path):
    tdir = tmp_path / telemetry.TELEMETRY_DIRNAME
    tdir.mkdir()
    path = tdir / "events-p0.jsonl"
    whole = json.dumps({"ts": 1.0, "kind": "heartbeat", "step": 1})
    torn = json.dumps({"ts": 2.0, "kind": "heartbeat", "step": 2})
    with open(path, "w") as f:
        f.write(whole + "\n" + torn[:10])  # mid-record crash: no newline
    cur = telemetry.EventCursor(tmp_path)
    assert [e["step"] for e in cur.poll()] == [1]
    # the torn fragment was NOT consumed: completing the line surfaces it
    with open(path, "a") as f:
        f.write(torn[10:] + "\n")
    assert [e["step"] for e in cur.poll()] == [2]
    assert cur.skipped_lines == 0


def test_cursor_tolerates_truncation_and_garbage(tmp_path):
    tdir = tmp_path / telemetry.TELEMETRY_DIRNAME
    tdir.mkdir()
    path = tdir / "events-p0.jsonl"
    with open(path, "w") as f:
        f.write(json.dumps({"ts": 1.0, "kind": "heartbeat"}) + "\n")
        f.write("this is not json\n")
    cur = telemetry.EventCursor(tmp_path)
    assert len(cur.poll()) == 1
    assert cur.skipped_lines == 1
    # file replaced with a shorter one (rotation/copy-truncate): the
    # cursor resets its offset instead of seeking past EOF forever
    with open(path, "w") as f:
        f.write(json.dumps({"ts": 5.0, "kind": "heartbeat", "step": 9})
                + "\n")
    assert [e["step"] for e in cur.poll()] == [9]


def test_cursor_picks_up_new_files(tmp_path):
    w0, _ = _writer(tmp_path, process="p0")
    w0.heartbeat(step=1)
    w0.close()
    cur = telemetry.EventCursor(tmp_path)
    assert len(cur.poll()) == 1
    w1, _ = _writer(tmp_path, process="p1", t0=0.5)
    w1.heartbeat(step=2)
    w1.close()
    assert [e["process"] for e in cur.poll()] == ["p1"]
    # accumulated view stays ts-sorted across files
    assert [e["ts"] for e in cur.events] == [0.0, 0.5]


# -- tenant stamping ----------------------------------------------------------


def test_tenant_env_stamps_every_record(tmp_path, monkeypatch):
    monkeypatch.setenv(telemetry.TENANT_ENV, "teamA")
    w, _ = _writer(tmp_path)
    w.heartbeat(step=1)
    w.emit("request", outcome="ok", latency_s=0.1, tenant="explicit")
    w.close()
    events = telemetry.read_events(tmp_path)
    assert events[0]["tenant"] == "teamA"
    # a record-level tenant (the router's attribution) wins over the env
    assert events[1]["tenant"] == "explicit"


# -- flap damping and alert edges ---------------------------------------------


def _gauge_engine(tmp_path, damping):
    w, clock = _writer(tmp_path)
    eng = health.HealthEngine(tmp_path, damping=damping, clock=clock)
    return w, clock, eng


def test_oscillating_rule_emits_zero_edges(tmp_path):
    """A rule flipping OK<->CRIT every evaluation never confirms, so the
    bus sees nothing — the whole point of flap damping."""
    w, clock, eng = _gauge_engine(tmp_path, damping=3)
    for i in range(8):
        w.emit("serve", queue_depth=100 if i % 2 == 0 else 0)
        clock.tick(1.0)
        eng.evaluate()
    eng.close()
    w.close()
    assert _alert_events(tmp_path) == []
    assert eng._state == {}


def test_damping_holds_n_evaluations_then_raises_once(tmp_path):
    w, clock, eng = _gauge_engine(tmp_path, damping=3)
    w.emit("serve", queue_depth=100)
    w.close()
    for _ in range(2):
        clock.tick(1.0)
        rep = eng.evaluate()
        assert _alert_events(tmp_path) == []       # still pending
        assert rep["worst_severity"] == "OK"
    clock.tick(1.0)
    rep = eng.evaluate()
    alerts = _alert_events(tmp_path)
    assert [(a["edge"], a["key"], a["severity"], a["held"])
            for a in alerts] == [("raise", "queue:p0", "CRIT", 3)]
    assert rep["worst_severity"] == "CRIT"
    assert [a["key"] for a in rep["alerts_active"]] == ["queue:p0"]
    # identical re-raises dedup: further evaluations emit nothing new
    for _ in range(3):
        clock.tick(1.0)
        eng.evaluate()
    assert len(_alert_events(tmp_path)) == 1
    eng.close()


def test_clear_edge_pairs_with_raise(tmp_path):
    w, clock, eng = _gauge_engine(tmp_path, damping=2)
    w.emit("serve", queue_depth=100)
    for _ in range(2):
        clock.tick(1.0)
        eng.evaluate()
    assert [a["edge"] for a in _alert_events(tmp_path)] == ["raise"]
    # condition recovers; the clear must also hold `damping` evaluations
    w.emit("serve", queue_depth=0)
    w.close()
    clock.tick(1.0)
    eng.evaluate()
    assert [a["edge"] for a in _alert_events(tmp_path)] == ["raise"]
    clock.tick(1.0)
    rep = eng.evaluate()
    eng.close()
    alerts = _alert_events(tmp_path)
    assert [(a["edge"], a["key"]) for a in alerts] == [
        ("raise", "queue:p0"), ("clear", "queue:p0")]
    clear = alerts[-1]
    assert clear["severity"] == "OK" and clear["cleared_from"] == "CRIT"
    assert rep["worst_severity"] == "OK" and rep["alerts_active"] == []


def test_escalation_carries_prev_severity(tmp_path):
    w, clock, eng = _gauge_engine(tmp_path, damping=1)
    w.emit("serve", queue_depth=10)      # warn >= 8
    clock.tick(1.0)
    eng.evaluate()
    w.emit("serve", queue_depth=50)      # crit >= 32
    w.close()
    clock.tick(1.0)
    eng.evaluate()
    eng.close()
    alerts = _alert_events(tmp_path)
    assert [a["severity"] for a in alerts] == ["WARN", "CRIT"]
    assert alerts[1]["prev"] == "WARN"


# -- health.json contract -----------------------------------------------------

HEALTH_KEYS = {
    "schema", "generated_ts", "workdir", "worst_severity", "rules",
    "goodput", "slo", "queue_depth", "tenants", "last_step",
    "last_heartbeat_age_s", "stream", "evaluations", "alerts_active",
    "engine",
}


def test_health_json_schema_and_no_internal_keys(tmp_path):
    w, clock, eng = _gauge_engine(tmp_path, damping=1)
    w.heartbeat(step=7)
    w.close()
    clock.tick(1.0)
    eng.evaluate()
    eng.close()
    path = os.path.join(str(tmp_path), health.HEALTH_FILENAME)
    with open(path) as f:
        doc = json.load(f)
    assert set(doc) == HEALTH_KEYS
    assert doc["schema"] == health.HEALTH_SCHEMA
    assert doc["worst_severity"] in health.SEVERITIES
    assert doc["last_step"] == 7
    assert set(doc["rules"]) == {name for name, _ in health.RULES}
    # the atomic rewrite leaves no temp droppings behind
    assert [p for p in os.listdir(tmp_path) if ".tmp." in p] == []


def test_worst_severity_ladder():
    assert health.worst_severity([]) == "OK"
    assert health.worst_severity(["OK", "WARN"]) == "WARN"
    assert health.worst_severity(["WARN", "CRIT", "OK"]) == "CRIT"


# -- rules --------------------------------------------------------------------


def test_slo_rule_names_worst_replica(tmp_path):
    wa, ca = _writer(tmp_path, process="p0")
    wb, cb = _writer(tmp_path, process="p1")
    for i in range(20):
        wa.emit("request", outcome="ok", latency_s=2.0, tenant="t0")
        wb.emit("request", outcome="ok", latency_s=0.01, tenant="t0")
        ca.tick(0.5)
        cb.tick(0.5)
    wa.close()
    wb.close()
    rep = health.evaluate_health(telemetry.read_events(tmp_path),
                                 slo_target_s=0.5)
    slo = [v for v in rep["_verdicts"] if v["rule"] == "slo"]
    assert len(slo) == 1
    v = slo[0]
    assert v["key"] == "slo:t0" and v["severity"] == "CRIT"
    assert v["evidence"]["worst_replica"] == "p0"
    assert "worst replica p0" in v["summary"]
    assert rep["slo"]["tenants"]["t0"]["verdict"] == "EXHAUSTED"


def test_windowed_rules_clear_on_clean_rerun(tmp_path):
    w, clock = _writer(tmp_path)
    for _ in range(20):
        w.emit("request", outcome="ok", latency_s=2.0)
        clock.tick(0.5)
    # a clean rerun appended much later: the trailing window holds only it
    clock.t = 1000.0
    for _ in range(20):
        w.emit("request", outcome="ok", latency_s=0.01)
        clock.tick(0.1)
    w.close()
    events = telemetry.read_events(tmp_path)
    burning = health.evaluate_health(events, slo_target_s=0.5, now=10.0,
                                     window_s=50.0)
    assert any(v["rule"] == "slo" for v in burning["_verdicts"])
    healed = health.evaluate_health(events, slo_target_s=0.5, now=1002.0,
                                    window_s=50.0)
    assert [v for v in healed["_verdicts"] if v["rule"] == "slo"] == []
    assert healed["worst_severity"] == "OK"


def test_restart_storm_rule(tmp_path):
    w, clock = _writer(tmp_path)
    for i in range(4):
        w.recovery(i * 10, "restart", classification="training-crash")
        clock.tick(5.0)
    w.close()
    rep = health.evaluate_health(telemetry.read_events(tmp_path))
    storm = [v for v in rep["_verdicts"] if v["rule"] == "restarts"]
    assert len(storm) == 1 and storm[0]["severity"] == "CRIT"
    assert storm[0]["evidence"]["classifications"] == ["training-crash"]


def test_degraded_stream_rule_and_engine(tmp_path):
    """Satellite: a workdir whose only file is a crashed run's torn
    partial segment is parseable-but-degraded, never a crash."""
    tdir = tmp_path / telemetry.TELEMETRY_DIRNAME
    tdir.mkdir()
    with open(tdir / "events-p0.jsonl", "w") as f:
        f.write('{"ts": 1.0, "kind": "step_m')  # torn mid-record, no \n
    eng = health.HealthEngine(tmp_path, damping=1, clock=FakeClock(5.0),
                              write_alerts=False)
    rep = eng.evaluate()
    eng.close()
    assert rep["worst_severity"] == "WARN"
    assert rep["stream"]["degraded"] is True
    assert [a["key"] for a in rep["alerts_active"]] == ["stream:degraded"]


def test_engine_ignores_its_own_alerts_for_degradation(tmp_path):
    """The engine's alert stream must not count as workdir liveness,
    or a degraded workdir would raise->self-clear forever."""
    tdir = tmp_path / telemetry.TELEMETRY_DIRNAME
    tdir.mkdir()
    with open(tdir / "events-p0.jsonl", "w") as f:
        f.write("garbage, not json\n")
    clock = FakeClock(5.0)
    eng = health.HealthEngine(tmp_path, damping=1, clock=clock)
    for _ in range(4):
        clock.tick(1.0)
        eng.evaluate()
    eng.close()
    alerts = _alert_events(tmp_path)
    # one raise, held forever: its own edges never read as recovery
    assert [(a["edge"], a["key"]) for a in alerts] == [
        ("raise", "stream:degraded")]


# -- schema stability: serving / SLO row contracts ----------------------------


def test_serving_fleet_row_key_stability(tmp_path):
    w, clock = _writer(tmp_path)
    w.emit("request", outcome="ok", latency_s=0.1, engine="e0")
    clock.tick(1.0)
    w.emit("request", outcome="shed")
    gauge = {k: 1 for k in fleet_lib.SERVE_GAUGE_KEYS}
    w.emit("serve", **gauge)
    w.close()
    sf = fleet_lib.serving_fleet(telemetry.read_events(tmp_path))
    row = sf["replicas"][0]
    assert set(row) == (set(fleet_lib.SERVE_ROW_BASE_KEYS)
                        | set(fleet_lib.SERVE_GAUGE_KEYS) | {"process"})
    assert "queue_depth" in fleet_lib.SERVE_GAUGE_KEYS


def test_slo_row_key_stability(tmp_path):
    w, clock = _writer(tmp_path)
    for i in range(10):
        w.emit("request", outcome="ok", latency_s=0.01 if i else 2.0,
               tenant="t0")
        clock.tick(0.5)
    w.close()
    slo = fleet_lib.slo_report(telemetry.read_events(tmp_path),
                               target_p99_s=0.5)
    for row in slo["tenants"].values():
        assert set(row) == set(fleet_lib.SLO_ROW_KEYS)
    assert "burn_rate" in fleet_lib.SLO_ROW_KEYS


# -- incident timeline --------------------------------------------------------


def test_incident_timeline_orders_and_attributes(tmp_path):
    events = [
        {"ts": 1.0, "kind": "alert", "edge": "raise", "rule": "slo",
         "key": "slo:t0", "severity": "CRIT", "summary": "burning",
         "evidence": {"worst_replica": "p0"}},
        {"ts": 2.0, "kind": "recovery", "event": "replica-restart",
         "replica": "r0", "process": "router"},
        {"ts": 3.0, "kind": "alert", "edge": "clear", "rule": "slo",
         "key": "slo:t0", "severity": "OK", "cleared_from": "CRIT",
         "summary": "cleared: burning"},
        {"ts": 0.5, "kind": "attempt", "edge": "end", "ordinal": 0,
         "classification": "training-crash", "returncodes": [1]},
        {"ts": 0.6, "kind": "attempt", "edge": "end", "ordinal": 1,
         "classification": "clean", "returncodes": [0]},
        {"ts": 0.7, "kind": "step_metrics", "step": 1},  # not an incident
    ]
    rows = health.incident_timeline(events)
    assert [r["type"] for r in rows] == [
        "attempt-end", "alert-raise", "recovery", "alert-clear"]
    assert rows[1]["who"] == "replica p0"
    assert rows[2]["who"] == "replica r0"
    assert rows[3]["cleared_from"] == "CRIT"
    assert "training-crash" in rows[0]["summary"]


# -- cluster view -------------------------------------------------------------


def _train_workdir(root, name, tenant):
    wd = os.path.join(root, name)
    clock = FakeClock(0.0)
    w = telemetry.EventWriter(wd, process="p0", clock=clock, tenant=tenant)
    for step in range(1, 4):
        w.step_metrics(step, steps=1, lap_s=1.0, metrics={})
        clock.tick(1.0)
    w.heartbeat(step=3)
    w.close()
    return wd


def _serve_workdir(root, name, tenant):
    wd = os.path.join(root, name)
    clock = FakeClock(0.0)
    w = telemetry.EventWriter(wd, process="p0", clock=clock)
    for _ in range(10):
        w.emit("request", outcome="ok", latency_s=0.01, tenant=tenant)
        clock.tick(0.2)
    w.emit("serve", kv_page_occupancy=0.5, queue_depth=1)
    w.close()
    return wd


def test_cluster_report_folds_workdirs_and_tenants(tmp_path):
    root = str(tmp_path)
    wd_a = _train_workdir(root, "jobs/mnist", "teamA")
    wd_b = _serve_workdir(root, "serve/llm", "teamB")
    rep = health.cluster_report(root)
    assert [r["workdir"] for r in rep["workdirs"]] == sorted([wd_a, wd_b])
    by_wd = {r["workdir"]: r for r in rep["workdirs"]}
    assert by_wd[wd_a]["kind"] == "train"
    assert by_wd[wd_a]["tenants"] == ["teamA"]
    assert by_wd[wd_a]["last_step"] == 3
    assert by_wd[wd_b]["kind"] == "serve"
    assert by_wd[wd_b]["tenants"] == ["teamB"]
    assert by_wd[wd_b]["occupancy"] == 0.5
    assert set(rep["tenants"]) == {"teamA", "teamB"}
    assert rep["tenants"]["teamB"]["requests"] == 10
    assert rep["tenants"]["teamB"]["serve_workdirs"] == 1
    assert rep["tenants"]["teamA"]["train_workdirs"] == 1
    assert rep["worst_severity"] in health.SEVERITIES


def test_discover_workdirs_strips_telemetry_dir(tmp_path):
    root = str(tmp_path)
    wd = _train_workdir(root, "a/b/c", "t")
    assert health.discover_workdirs(root) == [wd]
    assert health.discover_workdirs(os.path.join(root, "empty-miss")) == []


# -- dlstatus surfaces --------------------------------------------------------


def test_dlstatus_health_and_incidents_json(tmp_path, capsys):
    w, clock = _writer(tmp_path)
    for _ in range(10):
        w.emit("request", outcome="ok", latency_s=2.0)
        clock.tick(0.5)
    w.close()
    rc = status.main([str(tmp_path), "--health", "--incidents", "--json",
                      "--slo", "0.5"])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["health"]["worst_severity"] == "CRIT"
    assert "_verdicts" not in doc["health"]
    assert set(doc["health"]) == HEALTH_KEYS
    assert doc["incidents"] == []  # no edges were ever written


def test_dlstatus_degraded_workdir_is_rc0(tmp_path, capsys):
    """Satellite: a crashed run's partial segment must render a degraded
    notice, not die. rc 1 is reserved for 'no telemetry files at all'."""
    tdir = tmp_path / telemetry.TELEMETRY_DIRNAME
    tdir.mkdir()
    with open(tdir / "events-p0.jsonl", "w") as f:
        f.write('{"ts": 1.0, "kind": "step_m')  # torn, nothing parses
    assert status.main([str(tmp_path), "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["num_events"] == 0
    empty = tmp_path / "no-telemetry-here"
    empty.mkdir()
    assert status.main([str(empty)]) == 1


def test_dlstatus_cluster_json(tmp_path, capsys):
    root = str(tmp_path)
    _train_workdir(root, "job0", "teamA")
    rc = status.main(["--cluster", root, "--json"])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    assert len(doc["workdirs"]) == 1
    assert doc["workdirs"][0]["tenants"] == ["teamA"]
    # an empty root is an error: nothing to report on
    empty = os.path.join(root, "job0", "nope")
    os.makedirs(empty)
    assert status.main(["--cluster", empty]) == 1


# -- trace export -------------------------------------------------------------


def test_chrome_trace_renders_alert_instants(tmp_path):
    w, clock, eng = _gauge_engine(tmp_path, damping=1)
    w.emit("serve", queue_depth=100)
    w.close()
    clock.tick(1.0)
    eng.evaluate()
    eng.close()
    doc = trace_lib.chrome_trace(telemetry.read_events(tmp_path))
    marks = [e for e in doc["traceEvents"] if e.get("cat") == "alert"]
    assert len(marks) == 1
    assert marks[0]["ph"] == "i"
    assert marks[0]["name"] == "raise queue:p0"
    assert marks[0]["args"]["severity"] == "CRIT"
