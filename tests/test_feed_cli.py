"""Regression tests for feed/CLI/collectives bugs found in review."""

import numpy as np

from distributeddeeplearningspark_tpu.cli import build_parser
from distributeddeeplearningspark_tpu.data.feed import host_batches
from distributeddeeplearningspark_tpu.parallel import collectives
from distributeddeeplearningspark_tpu.rdd import PartitionedDataset


def _examples(n):
    return [{"x": np.float32(i)} for i in range(n)]


def test_host_batches_aligned_keeps_remainder():
    # 2 partitions × 50 examples, batch 32, 2 shards → aligned path;
    # drop_remainder=False must keep the final partial (even-sized) batch.
    ds = PartitionedDataset.parallelize(_examples(100), 2)
    kept = list(host_batches(ds, 32, num_shards=2, drop_remainder=False))
    total = sum(b["x"].shape[0] for b in kept)
    assert total == 100
    dropped = list(host_batches(ds, 32, num_shards=2, drop_remainder=True))
    assert sum(b["x"].shape[0] for b in dropped) == 96


def test_host_batches_chained_remainder():
    ds = PartitionedDataset.parallelize(_examples(10), 3)  # 3 parts, 1 shard
    kept = list(host_batches(ds, 4, num_shards=1, drop_remainder=False))
    assert [b["x"].shape[0] for b in kept] == [4, 4, 2]


def test_tree_aggregate_distinct_seq_comb_ops():
    # seq_op squares-and-sums within a partition; comb_op plain-sums across.
    parts = [[1.0, 2.0], [3.0, 4.0]]
    got = collectives.tree_aggregate(
        parts, 0.0, lambda acc, x: acc + x * x, lambda a, b: a + b
    )
    assert got == (1 + 4) + (9 + 16)  # comb_op must NOT square again


def test_tree_aggregate_empty():
    assert collectives.tree_aggregate([], 5.0, lambda a, x: a + x, lambda a, b: a + b) == 5.0


def test_rdd_getnumpartitions_is_callable():
    ds = PartitionedDataset.parallelize(range(4), 2)
    assert ds.getNumPartitions() == 2  # pyspark spells it as a method


def test_cli_parser_conf_mapping():
    args = build_parser().parse_args(
        ["--master", "local[2]", "--name", "app", "--conf", "mesh.fsdp=2",
         "--num-executors", "4", "script.py", "--steps", "5"]
    )
    assert args.master == "local[2]"
    assert args.conf == ["mesh.fsdp=2"]
    assert args.num_executors == 4
    assert args.script == "script.py"
    assert args.script_args == ["--steps", "5"]


def test_shard_range_termination_agrees_on_uneven_shards():
    """Hosts with uneven shard sizes must stop after the SAME batch count, or
    the longer host hangs in the next collective (multi-process contract)."""
    from distributeddeeplearningspark_tpu.data.feed import host_batches

    # partitions of 50 and 46 rows → shard 0 longer than shard 1
    examples = [{"x": np.float32(i)} for i in range(96)]
    ds = PartitionedDataset.from_generators([
        lambda: examples[:50], lambda: examples[50:],
    ])
    counts = {}
    for lo, hi in [(0, 1), (1, 2)]:
        batches = list(host_batches(ds, 32, num_shards=2, shard_range=(lo, hi)))
        counts[(lo, hi)] = len(batches)
        assert all(b["x"].shape == (16,) for b in batches)  # local rows only
    assert counts[(0, 1)] == counts[(1, 2)] == 2  # min(50,46)//16


def test_shard_range_rows_are_disjoint_and_ordered():
    from distributeddeeplearningspark_tpu.data.feed import host_batches

    examples = [{"x": np.float32(i)} for i in range(64)]
    ds = PartitionedDataset.parallelize(examples, 4)
    full = list(host_batches(ds, 16, num_shards=2))
    left = list(host_batches(ds, 16, num_shards=2, shard_range=(0, 1)))
    right = list(host_batches(ds, 16, num_shards=2, shard_range=(1, 2)))
    assert len(full) == len(left) == len(right)
    for f, l, r in zip(full, left, right):
        np.testing.assert_array_equal(f["x"], np.concatenate([l["x"], r["x"]]))


def test_infinite_feed_never_opens_non_local_partitions():
    """.repeat() multi-host feed: host IO must be shard-local (pod-scale
    bandwidth contract) — non-local partitions are never even opened."""
    from distributeddeeplearningspark_tpu.data.feed import host_batches

    opened: list[int] = []

    def make(i):
        def gen():
            opened.append(i)
            k = 0
            while True:
                yield {"x": np.float32(i * 1000 + k)}
                k += 1
        return gen

    ds = PartitionedDataset.from_generators([make(i) for i in range(4)])
    ds = ds.map(lambda e: e).repeat()
    assert ds.is_infinite
    it = host_batches(ds, 16, num_shards=2, shard_range=(1, 2))
    batches = [next(it) for _ in range(3)]
    # shard 1 owns partitions 1 and 3; partitions 0/2 must stay closed
    assert sorted(set(opened)) == [1, 3]
    assert all(b["x"].shape == (8,) for b in batches)
    vals = np.concatenate([b["x"] for b in batches])
    assert set(np.unique(vals // 1000).astype(int)) == {1, 3}


def test_infinite_flag_propagation_and_guards():
    import pytest

    ds = PartitionedDataset.parallelize(list(range(8)), 2)
    assert not ds.is_infinite
    assert ds.repeat().is_infinite
    assert ds.repeat(2).is_infinite is False
    assert ds.repeat().map(lambda x: x).is_infinite
    assert ds.shuffle().repeat().is_infinite  # documented order: shuffle first
    # degenerate compositions fail loudly instead of hanging / dropping data
    for op in ("shuffle", "coalesce", "collect", "count", "zip_with_index"):
        with pytest.raises(ValueError, match="BEFORE .repeat"):
            getattr(ds.repeat(), op)(*((1,) if op == "coalesce" else ()))


def test_pad_remainder_aligned_subshard_tail():
    """VERDICT r3 missing-#5: a tail smaller than the shard count is padded
    with eval_mask=0 rows instead of dropped — every real row survives."""
    # 4 partitions × uneven sizes (13 rows), batch 8, 4 shards → aligned
    # path; final leftover is 5 rows (< batch), pads to 8
    ds = PartitionedDataset.parallelize(_examples(13), 4)
    got = list(host_batches(ds, 8, num_shards=4, drop_remainder=False,
                            pad_remainder=True))
    real = np.concatenate([
        b["x"][b["eval_mask"] > 0] if "eval_mask" in b else b["x"]
        for b in got])
    assert sorted(real.tolist()) == [float(i) for i in range(13)]
    tail = got[-1]
    assert tail["x"].shape[0] % 4 == 0
    assert tail["eval_mask"].sum() + (len(got) - 1) * 8 == 13


def test_pad_remainder_multiprocess_slices_reassemble():
    """Multi-process tails were previously dropped whole; with padding, each
    host's slice of the padded final batch must reassemble to the global
    batch — same shapes on every host (collective safety) and no lost rows."""
    ds = PartitionedDataset.parallelize(_examples(13), 4)
    hosts = [list(host_batches(ds, 8, num_shards=4, drop_remainder=False,
                               shard_range=rng, pad_remainder=True))
             for rng in ((0, 2), (2, 4))]
    assert len(hosts[0]) == len(hosts[1])
    seen = []
    for b0, b1 in zip(hosts[0], hosts[1]):
        assert b0["x"].shape == b1["x"].shape
        if "eval_mask" in b0:
            glob_x = np.concatenate([b0["x"], b1["x"]])
            glob_m = np.concatenate([b0["eval_mask"], b1["eval_mask"]])
            seen.extend(glob_x[glob_m > 0].tolist())
        else:
            seen.extend(np.concatenate([b0["x"], b1["x"]]).tolist())
    assert sorted(seen) == [float(i) for i in range(13)]


def test_pad_remainder_chained_path():
    # 3 partitions don't align with 2 shards → chained fallback
    ds = PartitionedDataset.parallelize(_examples(11), 3)
    got = list(host_batches(ds, 4, num_shards=2, drop_remainder=False,
                            pad_remainder=True))
    real = np.concatenate([
        b["x"][b["eval_mask"] > 0] if "eval_mask" in b else b["x"]
        for b in got])
    assert sorted(real.tolist()) == [float(i) for i in range(11)]
    assert all(b["x"].shape[0] % 2 == 0 for b in got)


def test_pad_remainder_rejects_reserved_key():
    import pytest

    ds = PartitionedDataset.parallelize(
        [{"x": np.float32(i), "eval_mask": np.float32(1)} for i in range(3)], 1)
    with pytest.raises(ValueError, match="eval_mask"):
        list(host_batches(ds, 2, num_shards=2, drop_remainder=False,
                          pad_remainder=True))
