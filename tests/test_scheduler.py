"""Multi-tenant cluster scheduler: ledger fold, gang-aware packing,
quotas/priorities, preemption planning, crash recovery, accounting.

Everything here is jax-free and process-free: the ledger is a pure fold
and ``plan`` is a pure function, so the whole decision surface pins down
on fake clocks with no sleeps. The live drill (oversubscribed tenants →
graceful shrink preemption → resume on fewer hosts → accounting tie-out)
is ``tools/ci.sh sched``; this file is the contract it relies on.
"""

import json
import os

import pytest

from distributeddeeplearningspark_tpu.scheduler import core, ledger
from distributeddeeplearningspark_tpu.telemetry import health


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        self.t += 1.0  # every stamp advances: submission order is total
        return self.t


def _cluster(tmp_path, hosts=4, quotas=None):
    root = str(tmp_path / "pool")
    ledger.init_cluster(root, hosts=hosts, quotas=quotas or {})
    return root


def _sched(root):
    return core.Scheduler(root, clock=FakeClock())


# -- inventory + ledger durability --------------------------------------------


def test_init_cluster_counts_and_names(tmp_path):
    root = _cluster(tmp_path, hosts=3, quotas={"a": 2})
    cfg = ledger.load_config(root)
    assert cfg["hosts"] == ["h0", "h1", "h2"]
    assert cfg["quotas"] == {"a": 2}
    # explicit slot names + dup rejection
    ledger.init_cluster(root, hosts=["tpu-a", "tpu-b"])
    assert ledger.load_config(root)["hosts"] == ["tpu-a", "tpu-b"]
    with pytest.raises(ValueError):
        ledger.init_cluster(root, hosts=["x", "x"])
    with pytest.raises(ValueError):
        ledger.init_cluster(root, hosts=0)


def test_load_config_rejects_wrong_schema(tmp_path):
    root = _cluster(tmp_path)
    with open(ledger.config_path(root), "w") as f:
        json.dump({"schema": 99, "hosts": ["h0"]}, f)
    with pytest.raises(ValueError, match="schema"):
        ledger.load_config(root)


def test_read_ledger_skips_torn_tail(tmp_path):
    root = _cluster(tmp_path)
    ledger.append(root, "submit", "j000", ts=1.0, spec={"tenant": "a"})
    ledger.append(root, "place", "j000", ts=2.0,
                  assignment=[[0, "h0"]])
    with open(ledger.ledger_path(root), "a") as f:
        f.write('{"ts": 3.0, "edge": "laun')  # SIGKILL mid-append
    recs = ledger.read_ledger(root)
    assert [r["edge"] for r in recs] == ["submit", "place"]
    # the fold works on the torn ledger as-is
    st = ledger.load_state(root)
    assert st.jobs["j000"].status == "PLACED"


def test_append_rejects_unknown_edge(tmp_path):
    root = _cluster(tmp_path)
    with pytest.raises(ValueError, match="bad ledger edge"):
        ledger.append(root, "explode", "j000")


def test_next_job_id_counts_submits_only(tmp_path):
    root = _cluster(tmp_path)
    assert ledger.next_job_id(root) == "j000"
    ledger.append(root, "submit", "j000", spec={})
    ledger.append(root, "cancel", "j000")
    # terminal jobs keep their ids: the ledger is history
    assert ledger.next_job_id(root) == "j001"


# -- the lifecycle fold -------------------------------------------------------


def _submit_rec(root, jid, *, ts, tenant="a", priority=0, gangs=(2,),
                min_hosts=None):
    return ledger.append(root, "submit", jid, ts=ts, spec={
        "name": jid, "tenant": tenant, "priority": priority,
        "gangs": list(gangs),
        "min_hosts": sum(gangs) if min_hosts is None else min_hosts,
        "cmd": ["true"], "env": {}})


def test_fold_full_lifecycle(tmp_path):
    root = _cluster(tmp_path)
    _submit_rec(root, "j000", ts=1.0, tenant="research")
    st = ledger.load_state(root)
    assert st.jobs["j000"].status == "PENDING"
    assert st.free_hosts() == ["h0", "h1", "h2", "h3"]
    ledger.append(root, "place", "j000", ts=2.0,
                  assignment=[[0, "h0"], [1, "h1"]])
    ledger.append(root, "launch", "j000", ts=3.0, pid=4242)
    st = ledger.load_state(root)
    j = st.jobs["j000"]
    assert j.status == "RUNNING" and j.pid == 4242
    assert j.held_hosts == ["h0", "h1"]
    assert st.used_by_tenant() == {"research": 2}
    assert st.free_hosts() == ["h2", "h3"]
    ledger.append(root, "complete", "j000", ts=9.0, rc=0)
    st = ledger.load_state(root)
    assert st.jobs["j000"].status == "COMPLETED"
    assert st.jobs["j000"].rc == 0
    assert st.used_by_tenant() == {}       # terminal jobs hold nothing
    assert len(st.free_hosts()) == 4


def test_fold_shrink_frees_one_ordinal(tmp_path):
    root = _cluster(tmp_path)
    _submit_rec(root, "j000", ts=1.0, min_hosts=1)
    ledger.append(root, "place", "j000", ts=2.0,
                  assignment=[[0, "h0"], [1, "h1"]])
    ledger.append(root, "launch", "j000", ts=3.0, pid=1)
    ledger.append(root, "preempt", "j000", ts=4.0, mode="shrink", ordinal=1)
    st = ledger.load_state(root)
    assert st.jobs["j000"].draining == 1
    assert st.jobs["j000"].draining_since == 4.0
    ledger.append(root, "shrink", "j000", ts=5.0, ordinal=1, host="h1")
    st = ledger.load_state(root)
    j = st.jobs["j000"]
    assert j.status == "RUNNING" and j.draining is None
    assert j.held_hosts == ["h0"]
    assert "h1" in st.free_hosts()


def test_fold_requeue_after_terminal_is_noop(tmp_path):
    """The race guard: the runner's own verdict landed between the
    scheduler's state fold and its liveness check — the verdict wins."""
    root = _cluster(tmp_path)
    _submit_rec(root, "j000", ts=1.0)
    ledger.append(root, "place", "j000", ts=2.0, assignment=[[0, "h0"]])
    ledger.append(root, "launch", "j000", ts=3.0, pid=1)
    ledger.append(root, "complete", "j000", ts=4.0, rc=0)
    ledger.append(root, "requeue", "j000", ts=5.0, reason="runner-died")
    st = ledger.load_state(root)
    assert st.jobs["j000"].status == "COMPLETED"
    assert st.jobs["j000"].requeues == 0


def test_fold_requeue_resets_assignment(tmp_path):
    root = _cluster(tmp_path)
    _submit_rec(root, "j000", ts=1.0)
    ledger.append(root, "place", "j000", ts=2.0,
                  assignment=[[0, "h0"], [1, "h1"]])
    ledger.append(root, "launch", "j000", ts=3.0, pid=1)
    ledger.append(root, "requeue", "j000", ts=4.0, reason="wedged")
    st = ledger.load_state(root)
    j = st.jobs["j000"]
    assert j.status == "PENDING" and j.assignment == {} and j.pid is None
    assert j.requeues == 1 and j.reason == "wedged"
    assert len(st.free_hosts()) == 4


# -- queue order + submission validation --------------------------------------


def test_pending_orders_priority_desc_then_fifo(tmp_path):
    root = _cluster(tmp_path)
    _submit_rec(root, "j000", ts=1.0, priority=0)
    _submit_rec(root, "j001", ts=2.0, priority=5)
    _submit_rec(root, "j002", ts=3.0, priority=5)
    st = ledger.load_state(root)
    assert [j.job_id for j in st.pending()] == ["j001", "j002", "j000"]


def test_submit_validates_gang_shapes(tmp_path):
    root = _cluster(tmp_path)
    s = _sched(root)
    try:
        with pytest.raises(ValueError, match="gang"):
            s.submit(["true"], tenant="a", gangs=[])
        with pytest.raises(ValueError, match="gang"):
            s.submit(["true"], tenant="a", gangs=[2, 0])
        with pytest.raises(ValueError, match="outside"):
            s.submit(["true"], tenant="a", gangs=2, min_hosts=3)
        # multi-gang jobs are rigid: partial placement would break a gang
        with pytest.raises(ValueError, match="rigid"):
            s.submit(["true"], tenant="a", gangs=[2, 2], min_hosts=2)
        jid = s.submit(["true"], tenant="a", gangs=[2, 2])
        assert ledger.load_state(root).jobs[jid].min_hosts == 4
    finally:
        s.close()


# -- gang-aware packing (plan is pure) ----------------------------------------


def test_plan_places_whole_gangs_or_nothing(tmp_path):
    root = _cluster(tmp_path, hosts=3)
    _submit_rec(root, "j000", ts=1.0, gangs=(2, 2))  # needs 4, rigid
    actions = core.plan(ledger.load_state(root))
    assert actions["place"] == []
    assert actions["blocked"][0]["reason"] == "capacity"
    _submit_rec(root, "j001", ts=2.0, gangs=(2,))
    actions = core.plan(ledger.load_state(root))
    placed = {p.job_id: p.assignment for p in actions["place"]}
    assert placed == {"j001": {0: "h0", 1: "h1"}}  # j000 still whole-or-not


def test_plan_elastic_partial_placement(tmp_path):
    """A single-gang job with min_hosts < total starts on what's free —
    the requeued-preemptee path (reshard-on-restore makes it safe)."""
    root = _cluster(tmp_path, hosts=2)
    _submit_rec(root, "j000", ts=1.0, gangs=(1,))
    ledger.append(root, "place", "j000", ts=2.0, assignment=[[0, "h0"]])
    _submit_rec(root, "j001", ts=3.0, gangs=(4,), min_hosts=1)
    actions = core.plan(ledger.load_state(root))
    placed = {p.job_id: p.assignment for p in actions["place"]}
    assert placed == {"j001": {0: "h1"}}


def test_plan_quota_gates_placement(tmp_path):
    root = _cluster(tmp_path, hosts=4, quotas={"smalltenant": 1})
    _submit_rec(root, "j000", ts=1.0, tenant="smalltenant", gangs=(2,))
    actions = core.plan(ledger.load_state(root))
    assert actions["place"] == []
    assert actions["blocked"][0]["reason"] == "quota"
    # the queue view explains the wait without re-running the planner
    rep = ledger.load_state(root).to_report()
    assert rep["jobs"][0]["reason"] == "quota"
    # an elastic job under quota takes only its quota headroom
    _submit_rec(root, "j001", ts=2.0, tenant="smalltenant", gangs=(2,),
                min_hosts=1)
    actions = core.plan(ledger.load_state(root))
    placed = {p.job_id: len(p.assignment) for p in actions["place"]}
    assert placed == {"j001": 1}


def test_plan_priority_order_drains_free_pool(tmp_path):
    root = _cluster(tmp_path, hosts=2)
    _submit_rec(root, "j000", ts=1.0, priority=0, gangs=(2,))
    _submit_rec(root, "j001", ts=2.0, priority=9, gangs=(2,))
    actions = core.plan(ledger.load_state(root))
    # the high-priority job packs first and takes the whole pool
    assert [p.job_id for p in actions["place"]] == ["j001"]
    assert actions["blocked"][0]["job"] == "j000"


# -- preemption planning ------------------------------------------------------


def _running(root, jid, *, ts, tenant="a", priority=0, gangs=(2,),
             min_hosts=None, hosts=("h0", "h1"), pid=1):
    _submit_rec(root, jid, ts=ts, tenant=tenant, priority=priority,
                gangs=gangs, min_hosts=min_hosts)
    ledger.append(root, "place", jid, ts=ts + 0.1,
                  assignment=[[o, h] for o, h in enumerate(hosts)])
    ledger.append(root, "launch", jid, ts=ts + 0.2, pid=pid)


def test_plan_prefers_graceful_shrink_of_elastic_victim(tmp_path):
    root = _cluster(tmp_path, hosts=2)
    _running(root, "j000", ts=1.0, priority=0, min_hosts=1)
    _submit_rec(root, "j001", ts=2.0, priority=5, gangs=(1,))
    actions = core.plan(ledger.load_state(root))
    assert actions["place"] == []
    [p] = actions["preempt"]
    assert (p.victim, p.mode, p.ordinal, p.for_job) == \
        ("j000", "shrink", 1, "j001")
    # the preempting tick does NOT place the beneficiary: hosts freed by
    # a drain only exist once the ledger says so
    assert actions["blocked"][0]["reason"] == "awaiting-preemption"


def test_plan_evicts_rigid_victim(tmp_path):
    root = _cluster(tmp_path, hosts=2)
    _running(root, "j000", ts=1.0, priority=0)  # rigid: min_hosts = 2
    _submit_rec(root, "j001", ts=2.0, priority=5, gangs=(2,))
    [p] = core.plan(ledger.load_state(root))["preempt"]
    assert (p.victim, p.mode) == ("j000", "evict")


def test_plan_never_preempts_equal_or_higher_priority(tmp_path):
    root = _cluster(tmp_path, hosts=2)
    _running(root, "j000", ts=1.0, priority=5, min_hosts=1)
    _submit_rec(root, "j001", ts=2.0, priority=5, gangs=(1,))
    actions = core.plan(ledger.load_state(root))
    assert actions["preempt"] == []
    assert actions["blocked"][0]["reason"] == "capacity"


def test_plan_skips_victims_already_draining(tmp_path):
    """A victim whose drain is in flight is off the table — no pile-on
    while the graceful machinery re-gathers its shards."""
    root = _cluster(tmp_path, hosts=2)
    _running(root, "j000", ts=1.0, priority=0, min_hosts=1)
    ledger.append(root, "preempt", "j000", ts=3.0, mode="shrink", ordinal=1)
    _submit_rec(root, "j001", ts=4.0, priority=5, gangs=(1,))
    actions = core.plan(ledger.load_state(root))
    assert actions["preempt"] == []
    assert actions["blocked"][0]["reason"] == "capacity"


def test_plan_preempts_only_to_the_floor(tmp_path):
    """The preemption goal is the beneficiary's min_hosts, not its full
    size: minimal disruption now, elastic growth later."""
    root = _cluster(tmp_path, hosts=2)
    _running(root, "j000", ts=1.0, priority=0, min_hosts=1)
    _submit_rec(root, "j001", ts=2.0, priority=5, gangs=(4,), min_hosts=1)
    preempts = core.plan(ledger.load_state(root))["preempt"]
    assert [(p.victim, p.mode) for p in preempts] == [("j000", "shrink")]


# -- the scheduler control loop (no processes) --------------------------------


def test_tick_places_and_is_crash_recoverable(tmp_path):
    root = _cluster(tmp_path, hosts=4, quotas={"research": 2})
    s = _sched(root)
    try:
        s.submit(["true"], tenant="research", priority=0, gangs=2,
                 min_hosts=1, name="train-lo")
        s.submit(["true"], tenant="prod", priority=10, gangs=1,
                 name="serve-hi")
        out = s.tick(launch=False)
    finally:
        s.close()
    assert sorted(out["placed"]) == ["j000", "j001"]
    # a fresh Scheduler on the same root folds back the identical view
    rep_a = ledger.load_state(root).to_report()
    s2 = _sched(root)
    try:
        out2 = s2.tick(launch=False)
    finally:
        s2.close()
    assert out2["placed"] == [] and out2["preempted"] == []
    rep_b = ledger.load_state(root).to_report()
    assert rep_a == rep_b
    assert rep_a["tenants"]["research"] == {"used": 2, "quota": 2}


def test_tick_shrink_preemption_delivers_notice(tmp_path):
    root = _cluster(tmp_path, hosts=2)
    s = _sched(root)
    try:
        lo = s.submit(["true"], tenant="a", priority=0, gangs=2,
                      min_hosts=1, name="lo")
        s.tick(launch=False)
        ledger.append(root, "launch", lo, pid=os.getpid())  # "running"
        s.submit(["true"], tenant="b", priority=5, gangs=1, name="hi")
        out = s.tick(launch=False)
    finally:
        s.close()
    assert out["preempted"] == [(lo, "shrink")]
    st = ledger.load_state(root)
    assert st.jobs[lo].draining == 1
    # the runtime channel: an atomic notice file under the victim's workdir
    from distributeddeeplearningspark_tpu import faults

    notice = faults.read_preempt_notice(
        core.notice_path(st.jobs[lo].workdir))
    assert notice is not None and notice.host == 1
    assert notice.step >= 2  # last step (none yet) + margin


def test_tick_observed_drain_frees_host_for_the_beneficiary(tmp_path):
    from distributeddeeplearningspark_tpu import telemetry

    root = _cluster(tmp_path, hosts=2)
    s = _sched(root)
    try:
        lo = s.submit(["true"], tenant="a", priority=0, gangs=2,
                      min_hosts=1, name="lo")
        s.tick(launch=False)
        ledger.append(root, "launch", lo, pid=os.getpid())
        hi = s.submit(["true"], tenant="b", priority=5, gangs=1, name="hi")
        s.tick(launch=False)  # delivers the shrink notice
        st = ledger.load_state(root)
        # the victim's gang drains and its supervisor logs the shrink —
        # write the geometry_change the reconcile loop watches for
        w = telemetry.EventWriter(st.jobs[lo].workdir, process="supervisor",
                                  host=None,
                                  clock=FakeClock(st.jobs[lo].draining_since))
        w.emit("recovery", event="geometry_change", dead_host=1,
               resume="live-handoff", num_processes=1)
        w.close()
        # reconcile runs before plan: the freed host is placeable in the
        # SAME tick that observes the drain
        out = s.tick(launch=False)
        assert out["shrunk"] == [lo]
        assert out["placed"] == [hi]
    finally:
        s.close()
    st = ledger.load_state(root)
    assert st.jobs[lo].held_hosts == ["h0"]
    assert st.jobs[hi].held_hosts == ["h1"]


def test_reconcile_ignores_stale_geometry_events(tmp_path):
    """A requeued job's earlier life may have drained the same ordinal —
    its old events must not free hosts this time around."""
    from distributeddeeplearningspark_tpu import telemetry

    root = _cluster(tmp_path, hosts=2)
    _running(root, "j000", ts=100.0, min_hosts=1)
    wd = ledger.load_state(root).jobs["j000"].workdir
    w = telemetry.EventWriter(wd, process="supervisor", host=None,
                              clock=FakeClock(50.0))  # BEFORE the preempt
    w.emit("recovery", event="geometry_change", dead_host=1,
           resume="live-handoff", num_processes=1)
    w.close()
    ledger.append(root, "preempt", "j000", ts=200.0, mode="shrink",
                  ordinal=1)
    s = core.Scheduler(root, clock=FakeClock(300.0))
    try:
        state = ledger.load_state(root)
        state.jobs["j000"].pid = os.getpid()  # keep the liveness check green
        out = s._reconcile(state)
    finally:
        s.close()
    assert out["shrunk"] == []


def test_reconcile_requeues_dead_runner_then_fails_at_limit(tmp_path,
                                                            monkeypatch):
    import subprocess

    monkeypatch.setenv(core.MAX_REQUEUES_ENV, "1")
    root = _cluster(tmp_path, hosts=1)
    # a real, already-reaped pid: os.kill(pid, 0) raises -> runner is dead
    proc = subprocess.Popen(["true"])
    proc.wait()
    dead_pid = proc.pid
    _running(root, "j000", ts=1.0, gangs=(1,), hosts=("h0",), pid=dead_pid)
    s = _sched(root)
    try:
        out = s.tick(launch=False)
        assert out["requeued"] == ["j000"]
        st = ledger.load_state(root)
        # same tick: requeued by reconcile, then re-placed by the planner
        assert st.jobs["j000"].status == "PLACED"
        assert st.jobs["j000"].requeues == 1
        # requeued -> placed again (launch=False leaves it PLACED, not
        # RUNNING) -> simulate another launch+death: the budget is spent
        ledger.append(root, "launch", "j000", ts=50.0, pid=dead_pid)
        s.tick(launch=False)
    finally:
        s.close()
    st = ledger.load_state(root)
    assert st.jobs["j000"].status == "FAILED"
    recs = [r for r in ledger.read_ledger(root) if r["edge"] == "fail"]
    assert recs[-1]["classification"] == "requeue-limit:runner-died"


# -- accounting tie-out (satellite: ledger vs cluster_report) -----------------


def test_quota_accounting_ties_out_with_cluster_report(tmp_path):
    """The per-tenant used/quota in the ledger fold must tie out exactly
    against the ``dlstatus --cluster`` rollup on the same state dir."""
    root = _cluster(tmp_path, hosts=4,
                    quotas={"research": 2, "prod": 4})
    s = _sched(root)
    try:
        s.submit(["true"], tenant="research", priority=0, gangs=2,
                 min_hosts=1, name="train-lo")
        s.submit(["true"], tenant="prod", priority=10, gangs=2,
                 name="serve-hi")
        s.tick(launch=False)
        s.submit(["true"], tenant="research", priority=1, gangs=1,
                 name="overquota")
        s.tick(launch=False)
    finally:
        s.close()
    state = ledger.load_state(root)
    rep = health.cluster_report(root)
    # 1) the sched block IS the ledger fold, verbatim
    assert rep["sched"] == state.to_report()
    # 2) used/quota per tenant tie out against the fold's own accounting
    used = state.used_by_tenant()
    for t, row in rep["sched"]["tenants"].items():
        assert row["used"] == used.get(t, 0)
        assert row["quota"] == state.quotas.get(t)
    assert rep["sched"]["tenants"]["research"] == {"used": 2, "quota": 2}
    assert rep["sched"]["tenants"]["prod"] == {"used": 2, "quota": 4}
    # 3) the oversubscribed submission is pending with the quota reason
    by_id = {j["job"]: j for j in rep["sched"]["jobs"]}
    assert by_id["j002"]["status"] == "PENDING"
    assert by_id["j002"]["reason"] == "quota"
    # 4) hosts held + free partition the inventory
    assert rep["sched"]["hosts"] == {"total": 4, "free": 0}
    # 5) the scheduler's own stream is a discovered workdir: the mirror
    # edges give every tenant a presence in the telemetry rollup too
    assert set(rep["sched"]["tenants"]) <= (set(rep["tenants"]) | {"-"})


def test_cluster_report_without_ledger_has_no_sched_block(tmp_path):
    from distributeddeeplearningspark_tpu import telemetry

    wd = tmp_path / "solo"
    w = telemetry.EventWriter(wd, process="p0", clock=FakeClock())
    w.heartbeat(step=1)
    w.close()
    rep = health.cluster_report(tmp_path)
    assert rep["sched"] is None
