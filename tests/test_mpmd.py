"""MPMD multi-gang pipeline: transport framing, scheduling, parity, drills.

Three layers of coverage:

- **Transport** (jax-free, fast tier): frame pack/read round-trip, torn and
  corrupted frames as typed :class:`FrameError`, peer death as a typed
  :class:`PeerDiedError` within a bounded wait, the bounded-backpressure
  contract, authkey rejection, and the chain resume-step consensus wave.
- **Folds** (jax-free, fast tier): the bubble-fraction accounting behind
  ``dlstatus --traces``'s pipeline block, on hand-built span streams.
- **Pipelines** (slow tier — whole-model jits): 2-stage bitwise parity with
  the single-program ``llama_pp`` baseline, heterogeneous per-stage meshes
  (fsdp stage + tensor stage), per-stage geometry-changing restore, and the
  process-level stage-kill drill (only the dead stage restarts; the loss
  trajectory matches an unfaulted run bitwise).
"""

import json
import os
import socket
import threading
import time

import numpy as np
import pytest

from distributeddeeplearningspark_tpu import telemetry
from distributeddeeplearningspark_tpu.parallel import mpmd
from distributeddeeplearningspark_tpu.telemetry import fleet as fleet_lib
from distributeddeeplearningspark_tpu.telemetry import trace as trace_lib


# -- framing ------------------------------------------------------------------


def test_frame_roundtrip_over_socketpair():
    a, b = socket.socketpair()
    payload = mpmd.encode_payload(
        {"act": np.arange(12, dtype=np.float32).reshape(3, 4), "step": 7})
    a.sendall(mpmd.pack_frame(mpmd.ACT, 1, 3, payload))
    kind, stage, mb, raw = mpmd.read_frame(b)
    assert (kind, stage, mb) == (mpmd.ACT, 1, 3)
    obj = mpmd.decode_payload(raw)
    assert obj["step"] == 7
    np.testing.assert_array_equal(obj["act"],
                                  np.arange(12, dtype=np.float32).reshape(3, 4))
    a.close()
    assert mpmd.read_frame(b) is None  # clean EOF at a frame boundary
    b.close()


def test_torn_frame_is_typed():
    a, b = socket.socketpair()
    frame = mpmd.pack_frame(mpmd.GRAD, 0, 1, mpmd.encode_payload({"x": 1}))
    a.sendall(frame[: len(frame) - 3])  # die mid-payload
    a.close()
    with pytest.raises(mpmd.FrameError, match="torn"):
        mpmd.read_frame(b)
    b.close()


def test_bad_magic_is_typed():
    a, b = socket.socketpair()
    a.sendall(b"GARBAGEGARBAGEGARBAGEGARBAGE")
    with pytest.raises(mpmd.FrameError, match="magic"):
        mpmd.read_frame(b)
    a.close()
    b.close()


def test_corrupted_payload_checksum_is_typed():
    a, b = socket.socketpair()
    frame = bytearray(mpmd.pack_frame(mpmd.ACT, 0, 0,
                                      mpmd.encode_payload({"x": 123})))
    frame[-1] ^= 0xFF  # flip one payload byte; header CRC now disagrees
    a.sendall(bytes(frame))
    with pytest.raises(mpmd.FrameError, match="checksum"):
        mpmd.read_frame(b)
    a.close()
    b.close()


# -- StageLink ----------------------------------------------------------------


def _link_pair(depth=2):
    a, b = socket.socketpair()
    out = {}

    def make(sock, stage, peer):
        out[stage] = mpmd.StageLink(sock, stage=stage, peer_stage=peer,
                                    depth=depth, hello={"step": stage * 10})

    t0 = threading.Thread(target=make, args=(a, 0, 1))
    t1 = threading.Thread(target=make, args=(b, 1, 0))
    t0.start(); t1.start(); t0.join(5); t1.join(5)
    return out[0], out[1]


def test_link_hello_and_data_roundtrip():
    l0, l1 = _link_pair()
    assert l0.peer_hello["step"] == 10 and l1.peer_hello["step"] == 0
    l0.send(mpmd.ACT, {"v": np.ones(4)}, mb=2)
    mb, obj = l1.recv(mpmd.ACT, timeout=5.0)
    assert mb == 2 and obj["v"].shape == (4,)
    l1.send(mpmd.GRAD, {"g": 1}, mb=2)
    assert l0.recv(mpmd.GRAD, timeout=5.0) == (2, {"g": 1})
    l0.close(); l1.close()


def test_peer_death_typed_within_bounded_wait():
    l0, l1 = _link_pair()
    # receiver blocked, peer process "dies" (socket torn without DONE)
    got: dict = {}

    def wait():
        t0 = time.monotonic()
        try:
            l0.recv(mpmd.GRAD, timeout=30.0)
        except mpmd.TransportError as e:
            got["err"] = e
            got["waited"] = time.monotonic() - t0

    th = threading.Thread(target=wait)
    th.start()
    time.sleep(0.1)
    # SIGKILL shape: the kernel tears the socket (shutdown, not a python
    # close — CPython defers close while a thread is blocked reading)
    l1.sock.shutdown(socket.SHUT_RDWR)
    th.join(10.0)
    assert isinstance(got.get("err"), mpmd.PeerDiedError)
    assert got["waited"] < 5.0  # bounded: death is detected, not timed out
    with pytest.raises(mpmd.PeerDiedError):
        l0.send(mpmd.ACT, {}, mb=0)  # subsequent calls fail typed too
    l0.close(send_done=False)


def test_buffered_frames_survive_peer_death():
    l0, l1 = _link_pair()
    l1.send(mpmd.GRAD, {"g": 7}, mb=0)
    time.sleep(0.3)  # let it land in l0's inbox
    l1.sock.shutdown(socket.SHUT_RDWR)
    assert l0.recv(mpmd.GRAD, timeout=5.0) == (0, {"g": 7})  # intact frame
    with pytest.raises(mpmd.PeerDiedError):
        l0.recv(mpmd.GRAD, timeout=5.0)  # then the death surfaces
    l0.close(send_done=False)


def test_send_backpressure_is_bounded():
    l0, l1 = _link_pair(depth=1)
    # the peer never drains: depth-1 send queue + depth-1 remote inbox +
    # the TCP buffers absorb a few frames, then send must BLOCK (and time
    # out typed), never buffer unboundedly
    big = {"x": np.zeros(1 << 20, np.uint8)}  # 1 MiB >> socket buffers
    with pytest.raises(mpmd.TransportTimeout):
        for _ in range(8):
            l0.send(mpmd.ACT, big, mb=0, timeout=0.3)
    assert len(l0._send_q) <= 1  # the bound held
    l0.close(send_done=False); l1.close(send_done=False)


def test_done_makes_teardown_clean():
    l0, l1 = _link_pair()
    l0.close(send_done=True)   # sends DONE then tears the socket
    time.sleep(0.3)
    assert not l1.dead          # EOF after DONE is an expected teardown
    l1.close(send_done=False)


# -- chain topology + resync --------------------------------------------------


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_transport_chain_sync_step_consensus():
    ports = [_free_port(), _free_port()]
    key = os.urandom(16)
    steps = {0: 12, 1: 8, 2: 12}
    agreed: dict = {}
    errs: dict = {}

    def run(stage):
        try:
            tr = mpmd.PipelineTransport(stage, 3, ports, key,
                                        connect_timeout=20)
            tr.connect(hello={"step": steps[stage]})
            agreed[stage] = tr.sync_step(steps[stage], timeout=20)
            tr.close()
        except Exception as e:  # noqa: BLE001 — surfaced via assert below
            errs[stage] = e

    ths = [threading.Thread(target=run, args=(s,)) for s in range(3)]
    for t in ths:
        t.start()
    for t in ths:
        t.join(30)
    assert not errs, errs
    assert agreed == {0: 8, 1: 8, 2: 8}  # min over committed steps


def test_transport_rejects_wrong_authkey():
    ports = [_free_port()]
    server = mpmd.PipelineTransport(0, 2, ports, b"right-key",
                                    connect_timeout=5)
    result: dict = {}

    def accept():
        try:
            server.connect()
            result["ok"] = True
        except mpmd.TransportError as e:
            result["err"] = e

    th = threading.Thread(target=accept)
    th.start()
    with pytest.raises(mpmd.TransportError):
        bad = mpmd.PipelineTransport(1, 2, ports, b"wrong-key",
                                     connect_timeout=3)
        bad.connect()
    th.join(10)
    server.close()
    assert "ok" not in result  # the unauthenticated dial never linked


# -- bubble accounting fold ---------------------------------------------------


def _pipe_span(name, t0, t1, *, stage, step, trace="t0", extra=None):
    rec = trace_lib.span(trace, trace_lib.new_span_id(), name, t0, t1,
                         stage=stage, step=step, **(extra or {}))
    return {"ts": t0, "kind": "span", "process": f"p{stage}", **rec}


def _step_cell(stage, step, t0, *, busy, wall, m=4, p=2):
    """One stage-step: a pipe-step span of ``wall`` with a pipe-fwd span
    of ``busy`` inside it."""
    return [
        _pipe_span("pipe-step", t0, t0 + wall, stage=stage, step=step,
                   extra={"m": m, "p": p, "schedule": "gpipe"}),
        _pipe_span("pipe-fwd", t0, t0 + busy, stage=stage, step=step,
                   extra={"mb": 0}),
    ]


def test_pipeline_anatomy_hand_computed_bubble():
    events = []
    # step 0 = warmup (huge wall, would dominate): must be skipped
    events += _step_cell(0, 0, 0.0, busy=1.0, wall=30.0)
    events += _step_cell(1, 0, 0.0, busy=1.0, wall=30.0)
    # steps 1..2: stage 0 busy 0.8/1.0 (bubble .2), stage 1 busy 0.6/1.0
    for s in (1, 2):
        events += _step_cell(0, s, 100.0 + s, busy=0.8, wall=1.0)
        events += _step_cell(1, s, 100.0 + s, busy=0.6, wall=1.0)
    rep = fleet_lib.pipeline_anatomy(events)
    assert rep is not None
    assert rep["m"] == 4 and rep["p"] == 2 and rep["schedule"] == "gpipe"
    assert rep["theoretical_bubble_frac"] == pytest.approx(1 / 5)
    # mean of (0.2, 0.4) over both stages and both judged steps
    assert rep["measured_bubble_frac"] == pytest.approx(0.3, abs=1e-6)
    assert rep["steps_judged"] == 2
    assert rep["cells_skipped_warmup_or_outlier"] == 2
    assert rep["stages"]["0"]["bubble_frac"] == pytest.approx(0.2, abs=1e-4)
    assert rep["stages"]["1"]["bubble_frac"] == pytest.approx(0.4, abs=1e-4)


def test_pipeline_anatomy_skips_midrun_recompile_outlier():
    events = []
    events += _step_cell(0, 0, 0.0, busy=0.5, wall=10.0)      # warmup
    for s in range(1, 6):
        events += _step_cell(0, s, 100.0 + s, busy=0.9, wall=1.0)
    # a restarted stage's first step back recompiles: 20x the median wall
    events += _step_cell(0, 6, 200.0, busy=1.0, wall=20.0)
    rep = fleet_lib.pipeline_anatomy(events)
    assert rep["measured_bubble_frac"] == pytest.approx(0.1, abs=1e-6)
    assert rep["cells_skipped_warmup_or_outlier"] == 2  # warmup + outlier


def test_pipeline_anatomy_none_without_pipe_spans():
    events = [{"ts": 1.0, "kind": "step_metrics", "process": "p0",
               "step": 1, "steps": 1, "lap_s": 0.1}]
    assert fleet_lib.pipeline_anatomy(events) is None


def test_dlstatus_pipeline_block_rendered_and_json(tmp_path):
    from distributeddeeplearningspark_tpu import status

    wd = tmp_path / "run"
    w = telemetry.EventWriter(wd, process="p0", host=0)
    recs = []
    for ev in (_step_cell(0, 0, 0.0, busy=1.0, wall=5.0)
               + _step_cell(0, 1, 10.0, busy=0.75, wall=1.0)
               + _step_cell(0, 2, 11.0, busy=0.85, wall=1.0)):
        recs.append({k: v for k, v in ev.items()
                     if k not in ("ts", "kind", "process")})
    w.emit_many("span", recs)
    w.step_metrics(2, steps=1, lap_s=1.0, metrics={"loss": 3.0})
    w.close()
    rep = status.report(str(wd), traces=True)
    pl = rep["pipeline"]
    for key in ("m", "p", "schedule", "steps", "steps_judged",
                "measured_bubble_frac", "theoretical_bubble_frac", "stages"):
        assert key in pl, key
    assert pl["measured_bubble_frac"] == pytest.approx(0.2, abs=1e-4)
    text = status.render(rep)
    assert "pipeline: 2 stage(s) x 4 microbatch(es)" in text
    assert "bubble fraction: measured 0.200" in text
    assert "(P-1)/(M+P-1) = 0.200" in text
    # strict-JSON round trip (the --json contract)
    json.loads(json.dumps(status._json_safe(rep), default=str))


def test_theoretical_bubble():
    from distributeddeeplearningspark_tpu.train.pipeline_trainer import (
        theoretical_bubble,
    )

    assert theoretical_bubble(4, 2) == pytest.approx(1 / 5)
    assert theoretical_bubble(8, 4) == pytest.approx(3 / 11)


# -- supervisor env contract --------------------------------------------------


def test_pipeline_supervisor_stage_env_contract(tmp_path):
    from distributeddeeplearningspark_tpu.supervisor import (
        PipelineSupervisor,
        StagePlan,
    )

    sup = PipelineSupervisor(
        [StagePlan(env={"XLA_FLAGS": "a"}), StagePlan(env={"XLA_FLAGS": "b"})],
        env={mpmd.ENV_SPEC: json.dumps({"steps": 1})},
        telemetry_dir=str(tmp_path))
    env0 = sup._stage_env(0)
    env1 = sup._stage_env(1)
    assert env0[mpmd.ENV_STAGE] == "0" and env1[mpmd.ENV_STAGE] == "1"
    assert env0[mpmd.ENV_NUM_STAGES] == "2"
    ports = json.loads(env0[mpmd.ENV_PORTS])
    assert len(ports) == 1 and ports == json.loads(env1[mpmd.ENV_PORTS])
    assert env0[mpmd.ENV_AUTHKEY] == env1[mpmd.ENV_AUTHKEY]
    # stage-targetable identity: DLS_FAULT=die_host@N + DLS_FAULT_HOST=k
    # kills exactly stage k's gang
    assert env0["DLS_HOST_ID"] == "0" and env1["DLS_HOST_ID"] == "1"
    assert env0["DLS_PROCESS_ID"] == "0" and env1["DLS_PROCESS_ID"] == "1"
    assert env0["XLA_FLAGS"] == "a" and env1["XLA_FLAGS"] == "b"
    assert env0[telemetry.WORKDIR_ENV] == str(tmp_path)
    assert StagePlan().command()[-1].endswith("pipeline_trainer")


def test_pipeline_supervisor_needs_two_stages():
    from distributeddeeplearningspark_tpu.supervisor import (
        PipelineSupervisor,
        StagePlan,
    )

    with pytest.raises(ValueError, match=">= 2 stages"):
        PipelineSupervisor([StagePlan()])


def test_pipeline_supervisor_hang_watchdog_plumbing(tmp_path):
    from distributeddeeplearningspark_tpu.supervisor import (
        PipelineSupervisor,
        StagePlan,
    )

    sup = PipelineSupervisor(
        [StagePlan(argv=["true"]), StagePlan(argv=["true"])],
        telemetry_dir=str(tmp_path), hang_timeout_s=5.0)
    env0 = sup._stage_env(0)
    assert env0["DLS_HEARTBEAT_FILE"] == sup._hb_path(0)
    now = time.time()
    sup._launch_wall[0] = now
    assert not sup._hb_stale(0, now)           # just launched: in grace
    assert sup._hb_stale(0, now - 60.0)        # silent past the timeout
    with open(sup._hb_path(0), "w") as f:      # a heartbeat resets it
        f.write("1")
    assert not sup._hb_stale(0, now - 60.0)
    import shutil

    shutil.rmtree(sup._hb_dir, ignore_errors=True)


def test_pipeline_supervisor_requires_spec_for_builtin_worker(monkeypatch):
    from distributeddeeplearningspark_tpu.supervisor import (
        PipelineSupervisor,
        StagePlan,
    )

    monkeypatch.delenv(mpmd.ENV_SPEC, raising=False)
    # built-in worker without its run spec: fail at construction with the
    # var named, not after max_restarts KeyError crash-loops per stage
    with pytest.raises(ValueError, match="DLS_PIPE_SPEC"):
        PipelineSupervisor([StagePlan(), StagePlan()])
    # a custom argv does not need the spec; a per-stage env satisfies it
    PipelineSupervisor([StagePlan(argv=["true"]), StagePlan(argv=["true"])])
    PipelineSupervisor([StagePlan(env={mpmd.ENV_SPEC: "{}"}),
                        StagePlan(env={mpmd.ENV_SPEC: "{}"})])


# -- stage program validation -------------------------------------------------


def test_stage_program_validation(eight_devices):
    import optax

    from distributeddeeplearningspark_tpu.models import LlamaConfig
    from distributeddeeplearningspark_tpu.parallel.mesh import MeshSpec
    from distributeddeeplearningspark_tpu.train.pipeline_trainer import (
        LlamaStageProgram,
    )

    cfg = LlamaConfig.tiny()
    mesh_t = MeshSpec(data=1, tensor=2).build(eight_devices[:2])
    with pytest.raises(ValueError, match="sharded"):
        LlamaStageProgram(cfg, 0, 2, mesh_t, optax.sgd(0.1), mode="exact")
    mesh_d = MeshSpec(data=2).build(eight_devices[:2])
    with pytest.raises(ValueError, match="full_batch"):
        LlamaStageProgram(cfg, 0, 2, mesh_d, optax.sgd(0.1), mode="exact",
                          loss_mode="per_microbatch")
    with pytest.raises(ValueError, match="mode"):
        LlamaStageProgram(cfg, 0, 2, mesh_d, optax.sgd(0.1), mode="magic")
    with pytest.raises(ValueError, match="divide"):
        LlamaStageProgram(cfg, 0, 3, mesh_d, optax.sgd(0.1))


# -- end-to-end pipelines (slow tier: whole-model jits) -----------------------


def _llama_batch_fn(cfg, b, t):
    def batch_fn(step):
        rng = np.random.default_rng(100 + step)
        # distinct tokens per batch: the embedding-grad scatter-add order
        # is then immaterial, one fewer confound in the bitwise pin
        ids = rng.permutation(cfg.vocab_size)[: b * t].reshape(b, t)
        return {"input_ids": ids.astype(np.int32),
                "loss_mask": np.ones((b, t), np.float32)}

    return batch_fn


def _run_pipeline_threads(make_stage, num_stages, *, steps, batch_size,
                          microbatches, batch_fn, seed=7, ckpt_dirs=None,
                          timeout=900):
    """Drive ``num_stages`` stage runners on threads over real sockets."""
    from distributeddeeplearningspark_tpu.train.pipeline_trainer import (
        PipelineStageRunner,
        StageRunConfig,
    )

    ports = [_free_port() for _ in range(num_stages - 1)]
    key = os.urandom(16)
    results: dict = {}
    errors: dict = {}

    def run(stage):
        try:
            program, ckpt = make_stage(stage)
            tr = mpmd.PipelineTransport(stage, num_stages, ports, key,
                                        connect_timeout=120)
            run_cfg = StageRunConfig(steps=steps, batch_size=batch_size,
                                     microbatches=microbatches, seed=seed,
                                     checkpoint_every=(
                                         None if ckpt is None else
                                         ckpt_dirs["every"]))
            r = PipelineStageRunner(
                program, tr, run_cfg,
                batch_fn=batch_fn if stage == 0 else None, checkpointer=ckpt)
            results[stage] = r.run()
        except BaseException as e:  # noqa: BLE001 — reported via assert
            import traceback

            traceback.print_exc()
            errors[stage] = e

    ths = [threading.Thread(target=run, args=(s,)) for s in range(num_stages)]
    for t in ths:
        t.start()
    for t in ths:
        t.join(timeout)
    assert not errors, errors
    assert set(results) == set(range(num_stages))
    return results


def test_mpmd_bitwise_parity_vs_single_program_llama_pp(eight_devices):
    """The flagship pin: a 2-stage × 2-device-per-stage MPMD pipeline
    (separate meshes, socket transport, per-stage optimizers) produces the
    SAME per-step losses and the SAME updated params, bit for bit, as the
    single-program ``llama_pp`` GPipe baseline on a pipe=2 × data=2 mesh."""
    import jax
    import optax

    from distributeddeeplearningspark_tpu.data.feed import put_global
    from distributeddeeplearningspark_tpu.models import (
        LlamaConfig,
        LlamaForCausalLM,
        llama_rules,
    )
    from distributeddeeplearningspark_tpu.models.llama_pp import make_pp_apply
    from distributeddeeplearningspark_tpu.parallel.mesh import MeshSpec
    from distributeddeeplearningspark_tpu.train import losses, step as step_lib
    from distributeddeeplearningspark_tpu.train.pipeline_trainer import (
        LlamaStageProgram,
    )

    cfg = LlamaConfig.tiny()
    steps, b, t, m, seed = 3, 8, 32, 4, 7
    batch_fn = _llama_batch_fn(cfg, b, t)
    tx = optax.adamw(1e-3)

    mesh_pp = MeshSpec(data=2, pipe=2).build(eight_devices[:4])
    model = LlamaForCausalLM(cfg)
    state, shardings = step_lib.init_state(
        model, tx, batch_fn(0), mesh_pp,
        llama_rules(cfg, fsdp=False, pipeline=True), seed=seed)
    ts = step_lib.jit_train_step(
        step_lib.make_train_step(make_pp_apply(cfg, mesh_pp, m), tx,
                                 losses.causal_lm), mesh_pp, shardings)
    base_losses = []
    for s in range(steps):
        state, met = ts(state, put_global(batch_fn(s), mesh_pp))
        base_losses.append(float(jax.device_get(met["loss"])))
    base = jax.device_get(state.params)

    def make_stage(stage):
        mesh = MeshSpec(data=2).build(
            eight_devices[2 * stage:2 * stage + 2])
        return LlamaStageProgram(cfg, stage, 2, mesh, optax.adamw(1e-3),
                                 mode="exact"), None

    results = _run_pipeline_threads(make_stage, 2, steps=steps,
                                    batch_size=b, microbatches=m,
                                    batch_fn=batch_fn, seed=seed)
    mp_losses = results[0]["losses"]
    assert [np.float32(x).tobytes() for x in base_losses] == \
        [np.float32(x).tobytes() for x in mp_losses], (base_losses, mp_losses)

    s0 = jax.device_get(results[0]["state"].params)
    s1 = jax.device_get(results[1]["state"].params)

    def flat(tree):
        return {"/".join(str(getattr(p, "key", p)) for p in path): np.asarray(v)
                for path, v in jax.tree_util.tree_flatten_with_path(tree)[0]}

    fb, f0, f1 = flat(base), flat(s0), flat(s1)
    for k, v in fb.items():
        if k.startswith("layers/"):
            got = np.concatenate([f0[k], f1[k]], axis=0)
        elif k.startswith("token_embed/"):
            got = f0[k]
        else:
            got = f1[k]
        assert v.tobytes() == got.tobytes(), f"params diverged at {k}"


def test_mpmd_heterogeneous_stage_meshes(eight_devices):
    """The MPMD headline: stage 0 on a wide-fsdp mesh (embedding-heavy),
    stage 1 on a tensor-parallel mesh (MLP/head-heavy), per-microbatch
    1F1B loss — different layouts per stage, loss still matching a pure-DP
    reference to fp tolerance."""
    import jax
    import optax

    from distributeddeeplearningspark_tpu.data.feed import put_global
    from distributeddeeplearningspark_tpu.models import (
        LlamaConfig,
        LlamaForCausalLM,
        llama_rules,
    )
    from distributeddeeplearningspark_tpu.parallel.mesh import MeshSpec
    from distributeddeeplearningspark_tpu.parallel.sharding import (
        ShardingRules,
    )
    from distributeddeeplearningspark_tpu.train import losses, step as step_lib
    from distributeddeeplearningspark_tpu.train.pipeline_trainer import (
        LlamaStageProgram,
    )

    cfg = LlamaConfig.tiny()
    steps, b, t, m, seed = 2, 8, 32, 4, 7
    batch_fn = _llama_batch_fn(cfg, b, t)
    tx = optax.adamw(1e-3)
    model = LlamaForCausalLM(cfg)
    mesh_dp = MeshSpec(data=4).build(eight_devices[:4])
    state, sh = step_lib.init_state(model, tx, batch_fn(0), mesh_dp,
                                    ShardingRules(), seed=seed)
    ts = step_lib.jit_train_step(
        step_lib.make_train_step(model.apply, tx, losses.causal_lm),
        mesh_dp, sh)
    ref = []
    for s in range(steps):
        state, met = ts(state, put_global(batch_fn(s), mesh_dp))
        ref.append(float(jax.device_get(met["loss"])))

    def make_stage(stage):
        if stage == 0:
            mesh = MeshSpec(data=1, fsdp=2).build(eight_devices[0:2])
            rules = ShardingRules(fsdp=True, fsdp_min_size=1 << 10)
        else:
            mesh = MeshSpec(data=1, tensor=2).build(eight_devices[2:4])
            rules = llama_rules(cfg, fsdp=False)
        return LlamaStageProgram(cfg, stage, 2, mesh, optax.adamw(1e-3),
                                 mode="sharded",
                                 loss_mode="per_microbatch",
                                 rules=rules), None

    results = _run_pipeline_threads(make_stage, 2, steps=steps,
                                    batch_size=b, microbatches=m,
                                    batch_fn=batch_fn, seed=seed)
    np.testing.assert_allclose(ref, results[0]["losses"], rtol=1e-5,
                               atol=1e-6)
    # the layouts really were heterogeneous
    specs0 = {str(l.sharding.spec) for l in
              jax.tree_util.tree_leaves(results[0]["state"].params)}
    specs1 = {str(l.sharding.spec) for l in
              jax.tree_util.tree_leaves(results[1]["state"].params)}
    assert any("fsdp" in s for s in specs0), specs0
    assert any("tensor" in s for s in specs1), specs1


def test_mpmd_stage_geometry_change_on_restore(eight_devices, tmp_path):
    """A stage can come back on a DIFFERENT mesh: train 2 steps on
    (data=2, data=2) checkpointing, then restart with stage 1 on a
    tensor=2 mesh restoring through the reshard path — training continues
    and the remaining losses match the uninterrupted run."""
    import optax

    from distributeddeeplearningspark_tpu.checkpoint import Checkpointer
    from distributeddeeplearningspark_tpu.models import (
        LlamaConfig,
        llama_rules,
    )
    from distributeddeeplearningspark_tpu.parallel.mesh import MeshSpec
    from distributeddeeplearningspark_tpu.train.pipeline_trainer import (
        LlamaStageProgram,
    )

    cfg = LlamaConfig.tiny()
    b, t, m, seed = 8, 32, 4, 7
    batch_fn = _llama_batch_fn(cfg, b, t)

    def exact_stage(stage):
        mesh = MeshSpec(data=2).build(eight_devices[2 * stage:2 * stage + 2])
        return LlamaStageProgram(cfg, stage, 2, mesh, optax.adamw(1e-3),
                                 mode="exact")

    # uninterrupted 4-step reference
    ref = _run_pipeline_threads(lambda s: (exact_stage(s), None), 2,
                                steps=4, batch_size=b, microbatches=m,
                                batch_fn=batch_fn, seed=seed)
    # session 1: 2 steps, checkpointed per stage
    dirs = {s: str(tmp_path / f"stage{s}") for s in range(2)}

    def with_ckpt(builder):
        def make(stage):
            return builder(stage), Checkpointer(dirs[stage],
                                                async_save=False)
        return make

    _run_pipeline_threads(with_ckpt(exact_stage), 2, steps=2, batch_size=b,
                          microbatches=m, batch_fn=batch_fn, seed=seed,
                          ckpt_dirs={"every": 2})

    # session 2: stage 1 restarts on a DIFFERENT mesh (sharded/tensor) and
    # restores the exact-mode checkpoint through reshard-on-restore
    def changed_stage(stage):
        if stage == 0:
            return exact_stage(stage)
        mesh = MeshSpec(data=1, tensor=2).build(eight_devices[2:4])
        return LlamaStageProgram(cfg, 1, 2, mesh, optax.adamw(1e-3),
                                 mode="sharded",
                                 loss_mode="full_batch",
                                 rules=llama_rules(cfg, fsdp=False))

    res = _run_pipeline_threads(with_ckpt(changed_stage), 2, steps=4,
                                batch_size=b, microbatches=m,
                                batch_fn=batch_fn, seed=seed,
                                ckpt_dirs={"every": 2})
    # the restored run reports the WHOLE trajectory (steps 1-2 ride the
    # checkpoint's data_state); steps 3-4 ran with a tensor-parallel
    # stage 1 — same training to fp tolerance
    assert len(res[0]["losses"]) == 4
    np.testing.assert_allclose(ref[0]["losses"], res[0]["losses"],
                               rtol=1e-5, atol=1e-6)
    specs1 = {str(l.sharding.spec) for l in
              __import__("jax").tree_util.tree_leaves(
                  res[1]["state"].params)}
    assert any("tensor" in s for s in specs1), specs1


def test_pipeline_supervisor_stage_kill_drill(tmp_path):
    """Process-level chaos: DLS_FAULT=die_host@5 targeted at stage 1's
    gang kills it mid-run; ONLY stage 1 restarts (stage 0 resyncs over the
    transport without restarting), the run completes, and the end-to-end
    loss trajectory matches an unfaulted run bitwise."""
    from distributeddeeplearningspark_tpu.supervisor import (
        PipelineSupervisor,
        StagePlan,
    )

    spec = {"steps": 6, "batch_size": 8, "microbatches": 4, "seq": 32,
            "checkpoint_every": 2, "seed": 0, "mode": "exact",
            "mesh": {"data": 2}}
    repo = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
    base_env = {
        "DLS_PIPE_SPEC": json.dumps(spec),
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        "PYTHONPATH": repo + os.pathsep + os.environ.get("PYTHONPATH", ""),
    }

    def run(tag, fault):
        wd = str(tmp_path / tag)
        env = dict(base_env)
        if fault:
            env.update({"DLS_FAULT": "die_host@5", "DLS_FAULT_HOST": "1",
                        "DLS_FAULT_ONCE": "1"})
        sup = PipelineSupervisor([StagePlan(), StagePlan()], env=env,
                                 telemetry_dir=wd, wall_timeout_s=900,
                                 restart_backoff_s=0.1)
        res = sup.run()
        assert res.ok, {k: [a.returncodes for a in v]
                        for k, v in res.attempts.items()}
        with open(os.path.join(wd, "DONE")) as f:
            done = json.load(f)
        return res, done, wd

    _, clean, _ = run("clean", fault=False)
    res, faulted, wd = run("fault", fault=True)
    assert res.restarts_of(1) == 1 and res.restarts_of(0) == 0, \
        {k: len(v) for k, v in res.attempts.items()}
    assert faulted["step"] == 6
    assert [np.float32(x).tobytes() for x in clean["losses"]] == \
        [np.float32(x).tobytes() for x in faulted["losses"]]
    events = telemetry.read_events(wd)
    rec = [(e.get("event"), e.get("stage")) for e in events
           if e.get("kind") == "recovery"]
    assert ("stage-restart", 1) in rec, rec
    assert ("pipeline-resync", 0) in rec, rec  # the survivor resync'd
    ends = [(e.get("stage"), e.get("classification")) for e in events
            if e.get("kind") == "attempt" and e.get("edge") == "end"]
    assert (1, "stage-crash") in ends and (0, "clean") in ends, ends
    # the pipeline block is populated from the same workdir
    from distributeddeeplearningspark_tpu import status

    pl = status.report(wd, traces=True)["pipeline"]
    assert pl and pl["p"] == 2 and pl["measured_bubble_frac"] is not None
