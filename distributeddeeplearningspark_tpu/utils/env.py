"""Environment/platform plumbing.

Some deployments (including this sandbox) register an accelerator PJRT plugin
from ``sitecustomize`` *before* user code runs, which defeats the documented
``JAX_PLATFORMS=cpu`` / ``XLA_FLAGS=--xla_force_host_platform_device_count=N``
env workflow: by the time a driver script runs, the env vars have already been
read (or pre-empted). ``jax.config.update`` wins regardless of import order as
long as no backend client has been created yet, so Session creation funnels
through here first.
"""

from __future__ import annotations

import os
import re


def process_identity() -> tuple[int, int]:
    """This host's (process index, process count) from the ``DLS_*`` env
    contract — the same variables the supervisor exports and ``Session``
    consumes (``DLS_PROCESS_ID`` / ``DLS_NUM_PROCESSES``).

    Deliberately env-only, never ``jax.process_index()``: the telemetry
    writer stamps every event with this identity and must work in processes
    that never initialize jax (the supervisor, ``tpu_watch``, a crashed
    worker's last gasp) and on boxes without jax at all (``dlstatus`` on a
    copied-out run directory). A malformed value degrades to the
    single-process identity rather than poisoning the event stream.
    """
    try:
        index = int(os.environ.get("DLS_PROCESS_ID", "0"))
    except ValueError:
        index = 0
    try:
        count = int(os.environ.get("DLS_NUM_PROCESSES", "1"))
    except ValueError:
        count = 1
    # a contract violation (id >= count) still yields a usable identity
    return max(0, index), max(1, count, index + 1)


def apply_env_platform_config(min_cpu_devices: int | None = None) -> None:
    """Honor JAX_PLATFORMS / XLA_FLAGS env intent via jax.config (best effort).

    No-op once backends are initialized (config.update then raises; we keep
    the original error surface by swallowing only that case).
    """
    import jax

    plats = os.environ.get("JAX_PLATFORMS", "")
    primary = plats.split(",")[0] if plats else ""
    try:
        if plats:
            jax.config.update("jax_platforms", plats)
        if primary == "cpu":
            m = re.search(
                r"xla_force_host_platform_device_count=(\d+)",
                os.environ.get("XLA_FLAGS", ""),
            )
            n = int(m.group(1)) if m else (min_cpu_devices or 0)
            if n > 1:
                jax.config.update("jax_num_cpu_devices", n)
    except RuntimeError:
        pass  # backend already live; the caller's device checks will report
    except AttributeError:
        pass  # jax < 0.5: no jax_num_cpu_devices; XLA_FLAGS env already took
