"""Tracing/profiling — the rebuild of the reference's Spark-UI/torch-profiler.

The reference's observability is Spark stage timelines plus (optionally) the
torch profiler inside the mapPartitions closure (SURVEY.md §5
'Tracing/profiling'). TPU-first, the device timeline lives in XLA/PJRT, so the
native story is:

- ``jax.profiler`` traces (host Python + device HLO timeline) written in
  TensorBoard 'profile' plugin format — ``ProfileSpec`` captures a window of
  steps mid-training from the Trainer without stopping the job;
- ``annotate(name)`` TraceAnnotations to label host phases (input pipeline,
  checkpoint, eval) so they're attributable in the trace viewer;
- XLA HLO dumps (``enable_xla_dump``) for compiler-level inspection of what
  GSPMD did to the step function — set BEFORE the first compile.
"""

from __future__ import annotations

import contextlib
import dataclasses
import glob
import logging
import os

import jax

from distributeddeeplearningspark_tpu import telemetry

logger = logging.getLogger("distributeddeeplearningspark_tpu.profiling")


@dataclasses.dataclass(frozen=True)
class ProfileSpec:
    """Capture ``num_steps`` steps starting at ``start_step`` into ``dir``.

    ``start_step`` defaults past warmup so the window sees steady-state steps,
    not the first compile.
    """

    dir: str
    start_step: int = 10
    num_steps: int = 5


class StepProfiler:
    """Drives a jax.profiler trace window across a training loop.

    Call ``observe(step)`` once per loop iteration; the profiler starts and
    stops itself around the configured window. Trace capture is process-local;
    on a pod every host writes its own trace (process 0's is the one usually
    inspected).
    """

    def __init__(self, spec: ProfileSpec | None, *, start_offset: int = 0,
                 sync=None):
        """``start_offset`` shifts the window to be relative to the loop's
        first step (a job resumed at step 1000 with start_step=10 traces
        steps 1010+, not the post-restore recompile). ``sync`` is a zero-arg
        callable that blocks until the dispatched steps' device work is done —
        REQUIRED for a faithful trace under async dispatch; the Trainer passes
        one that blocks on the live train state."""
        self.spec = spec
        self.start_offset = start_offset
        self._sync = sync
        self._active = False
        self._done = spec is None
        self._breakdown_thread = None

    def observe(self, step: int) -> None:
        if self._done:
            return
        assert self.spec is not None
        if not self._active and step >= self.spec.start_step + self.start_offset:
            os.makedirs(self.spec.dir, exist_ok=True)
            jax.profiler.start_trace(self.spec.dir)
            self._active = True
            self._stop_at = step + self.spec.num_steps
            # mark the window in the run's event stream (informational —
            # "profile-trace" is not a goodput overhead category) so a
            # dlstatus reader knows which steps carry tracing overhead
            telemetry.emit("phase", name="profile-trace", edge="begin",
                           step=step, dir=self.spec.dir)
            logger.info("profiler: tracing steps %d..%d → %s",
                        step, self._stop_at, self.spec.dir)
        elif self._active and step >= self._stop_at:
            self.stop()

    def stop(self) -> None:
        if self._active:
            # block on the real step outputs so the trace includes the
            # windowed steps' device work (async dispatch runs ahead)
            if self._sync is not None:
                self._sync()
            jax.profiler.stop_trace()
            self._active = False
            telemetry.emit("phase", name="profile-trace", edge="end",
                           dir=self.spec.dir)
            logger.info("profiler: trace written to %s", self.spec.dir)
            # Spark-UI moment: surface where the captured steps' device time
            # went without requiring TensorBoard (whose profile converter is
            # broken in mismatched installs — see op_breakdown/xplane.py).
            # In a DAEMON THREAD: the parse is a subprocess that can take
            # seconds, and stop() fires mid-training-loop — a synchronous
            # parse would stall the loop and corrupt the enclosing metrics
            # lap's step timing.
            import threading

            def _log_budget(d: str) -> None:
                rec = op_breakdown(d, top=5)
                if rec.get("ops"):
                    budget = ", ".join(
                        f"{o['name']} {o['pct']:.1f}%" for o in rec["ops"])
                    logger.info("profiler: device-time budget (%s, %.1f ms): %s",
                                rec.get("line"), rec.get("total_ms", 0.0), budget)
                else:
                    logger.info("profiler: no device-time budget: %s",
                                rec.get("error", "trace had no op events"))

            self._breakdown_thread = threading.Thread(
                target=_log_budget, args=(self.spec.dir,), daemon=True,
                name="op-breakdown",
            )
            self._breakdown_thread.start()
        self._done = True

    def join_breakdown(self, timeout_s: float = 150.0) -> None:
        """Wait for the async device-time-budget log (call AFTER the training
        loop — e.g. Trainer does, once timing laps are closed — so short jobs
        still surface the budget without the parse ever stalling a step).

        Default exceeds op_breakdown's 120 s subprocess timeout so the wait
        can't silently abandon a parse that was about to finish; if the
        thread is somehow still alive afterwards, say so instead of letting
        the promised budget line vanish without a trace."""
        if self._breakdown_thread is not None:
            self._breakdown_thread.join(timeout_s)
            if self._breakdown_thread.is_alive():
                logger.warning(
                    "profiler: device-time budget parse still running after "
                    "%.0fs — abandoning (trace remains at %s)",
                    timeout_s, self.spec.dir)


def annotate(name: str):
    """Label a host-side phase in the trace (input prep, checkpoint, eval)."""
    return jax.profiler.TraceAnnotation(name)


def step_annotation(step: int):
    """Mark one train step so the profile tool computes per-step stats."""
    return jax.profiler.StepTraceAnnotation("train", step_num=step)


def enable_xla_dump(dump_dir: str) -> None:
    """Route XLA HLO dumps (post-GSPMD, post-fusion) to ``dump_dir``.

    Must run before the first jit compilation; appends to XLA_FLAGS so it
    composes with the fake-device flag used in tests.
    """
    os.makedirs(dump_dir, exist_ok=True)
    flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_dump_to" not in flags:
        os.environ["XLA_FLAGS"] = f"{flags} --xla_dump_to={dump_dir}".strip()


def trace_files(profile_dir: str) -> list[str]:
    """The .xplane.pb trace files a capture produced (for tooling/tests)."""
    return sorted(
        glob.glob(os.path.join(profile_dir, "**", "*.xplane.pb"), recursive=True)
    )


def op_breakdown(profile_dir_or_file: str, *, top: int = 25,
                 timeout_s: float = 120.0) -> dict:
    """Per-op device-time budget from a captured trace — "where did the step
    go?" without TensorBoard (whose profile-plugin converter is broken by a
    protobuf mismatch in common installs; see utils/xplane.py).

    Accepts a profile directory (uses the newest ``.xplane.pb`` capture) or a
    single xplane file. Returns ``{"plane", "line", "total_ms",
    "event_count", "ops": [{"name", "ms", "pct", "count", "top_instance"}]}``
    with ops aggregated by HLO op class and sorted by total time, or
    ``{"error": ...}``.

    Runs the parse in a subprocess under the pure-python protobuf runtime —
    the env's stale generated protos cannot load under the C++ runtime, and
    the runtime choice is frozen at first protobuf import, so it must happen
    in a fresh interpreter.
    """
    import json
    import subprocess
    import sys

    path = profile_dir_or_file
    if not os.path.exists(path):
        return {"error": f"no such file or directory: {path}"}
    if os.path.isdir(path):
        files = trace_files(path)
        if not files:
            return {"error": f"no .xplane.pb under {path}"}
        path = max(files, key=os.path.getmtime)
    env = dict(os.environ, PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION="python")
    try:
        out = subprocess.run(
            [sys.executable, "-m",
             "distributeddeeplearningspark_tpu.utils.xplane", path, str(top)],
            capture_output=True, text=True, timeout=timeout_s, env=env,
        )
    except subprocess.TimeoutExpired:
        return {"error": f"xplane parse exceeded {timeout_s:.0f}s"}
    try:
        rec = json.loads(out.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        return {"error": f"xplane parser produced no JSON: "
                         f"{(out.stderr or out.stdout)[-300:]}"}
    return rec


def profile_cli(argv=None) -> int:
    """``dlprofile <trace-dir-or-xplane.pb>`` — print the device-time budget.

    The terminal counterpart of the Spark UI stage table: point it at any
    ``--profile-dir`` capture (or a bare ``.xplane.pb``) and read where the
    step went, without TensorBoard. Its sibling ``dlstatus`` answers the
    wall-clock question (goodput, attempts, recovery) from the run's
    telemetry stream — see docs/OBSERVABILITY.md.
    """
    import argparse
    import json

    ap = argparse.ArgumentParser(prog="dlprofile", description=profile_cli.__doc__)
    ap.add_argument("path", help="profile dir (newest capture used) or .xplane.pb")
    ap.add_argument("--top", type=int, default=15)
    ap.add_argument("--json", action="store_true", help="machine-readable output")
    args = ap.parse_args(argv)
    rec = op_breakdown(args.path, top=args.top)
    if args.json:
        print(json.dumps(rec))
        return 0 if rec.get("ops") else 1
    if not rec.get("ops"):
        print(f"error: {rec.get('error', 'trace contains no op events')}")
        return 1
    print(f"{rec['plane']}  [{rec['line']}]  total {rec['total_ms']:.1f} ms "
          f"over {rec['event_count']} events")
    for o in rec["ops"]:
        print(f"{o['pct']:6.2f}%  {o['ms']:9.2f} ms  x{o['count']:<6d} {o['name']}")
        if o.get("top_instance"):
            print(f"         └─ {o['top_instance'][:100]}")
    return 0


@contextlib.contextmanager
def trace(profile_dir: str):
    """Context-manager capture: everything inside the block is traced."""
    os.makedirs(profile_dir, exist_ok=True)
    jax.profiler.start_trace(profile_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
