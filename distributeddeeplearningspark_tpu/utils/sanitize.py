"""Training sanitizers: cross-replica desync detection and NaN guards.

The reference has no sanitizer layer — NCCL races/desyncs surface as hangs or
silently wrong gradients (SURVEY.md §5 'Race detection'). SPMD under a single
jit makes on-device races structurally absent, so the remaining failure modes
are:

- **replica desync** (multi-controller only): each process holds its own copy
  of every *replicated* array; a nondeterministic host-side op, mismatched
  RNG, or a corrupted restore can make process 3's "replicated" params differ
  from process 0's. GSPMD assumes they are identical — it will happily keep
  training with each process applying different updates.
- **numerical blowup**: NaN/Inf loss or gradients.

Both get cheap, explicit checks here rather than a debugger-shaped subsystem:
a fingerprint (per-leaf float64 sums) compared across processes, and a
finite-metrics assertion the Trainer can run at log boundaries.
"""

from __future__ import annotations

import logging
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

logger = logging.getLogger("distributeddeeplearningspark_tpu.sanitize")


class DesyncError(RuntimeError):
    """Replicated state differs across processes."""


def tree_fingerprint(tree: Any) -> np.ndarray:
    """Order-stable per-leaf [sum, l2, min, max] fingerprint, float64 on host.

    Only *fully addressable or replicated* data contributes deterministically
    per process: for sharded leaves each process folds in just its local
    shards (still a valid desync probe — identical programs must produce
    identical local shards for the same process id).
    """
    rows = []
    for leaf in jax.tree.leaves(tree):
        shards = getattr(leaf, "addressable_shards", None)
        if shards is not None:
            datas = [np.asarray(s.data, dtype=np.float64) for s in shards]
        else:
            datas = [np.asarray(leaf, dtype=np.float64)]
        flat = np.concatenate([d.reshape(-1) for d in datas]) if datas else np.zeros(1)
        rows.append(
            [flat.sum(), float(np.sqrt((flat * flat).sum())), flat.min(), flat.max()]
        )
    return np.asarray(rows, dtype=np.float64)


def _scalar_fingerprint(tree: Any) -> jax.Array:
    """Cheap order-independent scalar fingerprint of a pytree (jit-able)."""
    acc = jnp.float32(0)
    for leaf in jax.tree.leaves(tree):
        x = leaf.astype(jnp.float32)
        acc = acc + jnp.sum(x * jnp.float32(1e-3)) + jnp.sum(jnp.abs(x)) * jnp.float32(1e-6)
    return acc


def assert_replicas_in_sync(
    tree: Any, mesh=None, *, atol: float = 0.0, what: str = "params"
) -> None:
    """Raise :class:`DesyncError` if replicated copies of ``tree`` diverge —
    across the local devices of this process AND across processes.

    THE desync sanitizer (the two r1 variants merged; VERDICT r1 weak-#4):

    - **local devices**: a scalar fingerprint is computed *on every device*
      under jit; replicated inputs make each device fold its own physical
      copy, so diverged copies (donation bugs, stray per-device ``device_put``)
      yield different shard values of the replicated output.
    - **processes**: the replicated leaves' host-side fingerprints are
      all-gathered and compared — the rebuild of the 'checksum the broadcast
      weights' check a Spark driver could do, without gathering the weights.

    ``mesh`` is accepted (and ignored) for callers that historically passed
    it — the arrays' own shardings carry the layout.
    """
    del mesh
    leaves = jax.tree.leaves(tree)
    if not leaves:
        return
    # (a) across this process's devices, when leaves live on >1 device
    if any(getattr(leaf, "sharding", None) is not None for leaf in leaves):
        fp = jax.jit(_scalar_fingerprint)(tree)
        shards = getattr(fp, "addressable_shards", None) or []
        vals = [float(np.asarray(s.data)) for s in shards]
        for i, v in enumerate(vals[1:], start=1):
            if abs(v - vals[0]) > atol:
                raise DesyncError(
                    f"{what} desynced across local devices: device shard {i} "
                    f"fingerprint {v!r} != shard 0 {vals[0]!r} (atol={atol})"
                )
    # (b) across processes
    if jax.process_count() == 1:
        return
    replicated = [
        leaf for leaf in leaves
        if getattr(getattr(leaf, "sharding", None), "is_fully_replicated", True)
    ]
    fp = tree_fingerprint(replicated)
    from jax.experimental import multihost_utils

    all_fps = np.asarray(multihost_utils.process_allgather(fp))  # [P, L, 4]
    ref = all_fps[0]
    worst = np.max(np.abs(all_fps - ref[None]), axis=(1, 2)) if ref.size else np.zeros(1)
    bad = [i for i, w in enumerate(worst) if w > atol]
    if bad:
        raise DesyncError(
            f"{what} desynced across processes {bad} "
            f"(max fingerprint deviation {float(worst.max()):.3e} > atol={atol}); "
            f"replicated arrays must be bit-identical on every process"
        )


def nonfinite_metrics(metrics: dict[str, Any]) -> dict[str, float]:
    """The NaN/Inf entries of a metrics dict (empty when healthy).

    The non-raising primitive under :func:`assert_all_finite` — the Trainer's
    divergence-recovery policies (``on_nonfinite="skip"|"rollback"``) need to
    *observe* a blowup and keep going, not die on it.
    """
    return {k: float(v) for k, v in metrics.items()
            if np.issubdtype(np.asarray(v).dtype, np.floating)
            and not np.all(np.isfinite(np.asarray(v)))}


def assert_all_finite(metrics: dict[str, Any], *, step: int | None = None) -> None:
    """Raise FloatingPointError on NaN/Inf metric values (loss blowup guard)."""
    bad = nonfinite_metrics(metrics)
    if bad:
        at = f" at step {step}" if step is not None else ""
        raise FloatingPointError(f"non-finite metrics{at}: {bad}")


def tree_all_finite(tree: Any) -> bool:
    """True iff every float leaf of ``tree`` is entirely finite — the guard
    a rollback runs on a restored state before trusting it (a checkpoint's
    integrity manifest certifies bytes, not numerics: a NaN state checkpoints
    and restores byte-perfectly). One device-side reduction, one host sync.
    """
    acc = None
    for leaf in jax.tree.leaves(tree):
        if not jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.floating):
            continue
        ok = jnp.all(jnp.isfinite(leaf))
        acc = ok if acc is None else jnp.logical_and(acc, ok)
    return True if acc is None else bool(jax.device_get(acc))


def enable_nan_checks(enable: bool = True) -> None:
    """Turn on jax's per-op NaN debugging (slow; development only)."""
    jax.config.update("jax_debug_nans", enable)


def params_checksum(params: Any) -> float:
    """One scalar over the GLOBAL logical state (collective-backed for sharded
    arrays): identical on every process by construction, useful as a cheap
    step-to-step corruption log line."""
    leaves = [jnp.sum(jnp.abs(x.astype(jnp.float32))) for x in jax.tree.leaves(params)]
    return float(jax.device_get(sum(leaves)))
