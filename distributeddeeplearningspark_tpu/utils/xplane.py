"""XPlane (.xplane.pb) trace parsing → per-op device-time breakdown.

``jax.profiler`` writes traces in the TensorBoard 'profile' plugin's XPlane
format. The plugin's own converter is the intended reader, but it depends on
a matched TensorFlow build — in mismatched environments (as shipped here:
``tensorboard_plugin_profile`` generated against an older protobuf than the
installed runtime) it dies with descriptor errors, leaving no way to see
where device time went. This module is a self-contained fallback reader for
the one question a training engineer always asks first: *which ops are
eating the step?* — the TPU-native equivalent of reading Spark UI stage
timings (SURVEY.md §5 'Tracing/profiling').

Run as a subprocess (``python -m distributeddeeplearningspark_tpu.utils.xplane
<trace.xplane.pb>``) — the stale generated protos only import under the
pure-python protobuf runtime, which must be selected by env var *before* any
protobuf import, so the parse is isolated from the caller's process. Use
:func:`distributeddeeplearningspark_tpu.utils.profiling.op_breakdown` as the
in-process API; it manages the subprocess.

Output: one JSON object on stdout —
``{"plane", "line", "total_ms", "event_count", "ops": [{"name", "ms",
"pct", "count"}, ...]}``; ops are aggregated over occurrences and sorted by
total time. HLO instruction names are reduced to ``opcode`` (text before
``=``'s left operand dot suffixes), keeping fusion identity (``fusion.108``
and ``fusion.109`` fold into ``fusion``) so the table reads as an op-class
budget, with the full top instruction preserved per class in ``top_instance``.
"""

from __future__ import annotations

import json
import re
import sys


def _import_xplane_pb2():
    """Locate XPlane protos among known install locations."""
    errors = []
    for mod in (
        "tensorflow.tsl.profiler.protobuf.xplane_pb2",
        "tsl.profiler.protobuf.xplane_pb2",
        "tensorflow.core.profiler.protobuf.xplane_pb2",
    ):
        try:
            import importlib

            return importlib.import_module(mod)
        except Exception as e:  # noqa: BLE001 — try every known location
            errors.append(f"{mod}: {type(e).__name__}: {e}")
    raise ImportError("no xplane_pb2 available:\n" + "\n".join(errors))


_INSTR = re.compile(r"^%?(?P<name>[A-Za-z0-9_.\-]+)")


def _op_class(instruction: str) -> str:
    """'%fusion.108 = bf16[...] fusion(...)' → 'fusion' (class identity)."""
    m = _INSTR.match(instruction)
    name = m.group("name") if m else instruction
    return name.split(".")[0]


def parse(path: str, *, top: int = 25) -> dict:
    xplane_pb2 = _import_xplane_pb2()
    xs = xplane_pb2.XSpace()
    with open(path, "rb") as f:
        xs.ParseFromString(f.read())

    # Prefer a device plane's "XLA Ops" line (real per-op device intervals);
    # fall back to the busiest line anywhere (e.g. host python on CPU runs).
    best = None  # (priority, event_count, plane, line)
    for plane in xs.planes:
        for line in plane.lines:
            if not line.events:
                continue
            prio = 1 if line.name == "XLA Ops" else 0
            cand = (prio, len(line.events), plane, line)
            if best is None or cand[:2] > best[:2]:
                best = cand
    if best is None:
        return {"plane": None, "line": None, "total_ms": 0.0,
                "event_count": 0, "ops": []}
    _, _, plane, line = best

    meta = plane.event_metadata
    agg: dict[str, dict] = {}
    total_ps = 0
    for e in line.events:
        full = meta[e.metadata_id].name
        cls = _op_class(full)
        rec = agg.setdefault(cls, {"ps": 0, "count": 0, "top_ps": 0, "top": ""})
        rec["ps"] += e.duration_ps
        rec["count"] += 1
        if e.duration_ps > rec["top_ps"]:
            rec["top_ps"], rec["top"] = e.duration_ps, full
        total_ps += e.duration_ps
    ops = sorted(agg.items(), key=lambda kv: -kv[1]["ps"])[:top]
    return {
        "plane": plane.name,
        "line": line.name,
        "total_ms": round(total_ps / 1e9, 3),
        "event_count": len(line.events),
        "ops": [
            {
                "name": cls,
                "ms": round(rec["ps"] / 1e9, 3),
                "pct": round(100.0 * rec["ps"] / total_ps, 2) if total_ps else 0.0,
                "count": rec["count"],
                "top_instance": rec["top"][:160],
            }
            for cls, rec in ops
        ],
    }


def main(argv: list[str]) -> int:
    if len(argv) not in (2, 3):
        print(json.dumps({"error": "usage: python -m ...utils.xplane "
                                   "<trace.xplane.pb> [top_n]"}))
        return 2
    try:
        top = int(argv[2]) if len(argv) == 3 else 25
        print(json.dumps(parse(argv[1], top=top)))
        return 0
    except Exception as e:  # noqa: BLE001 — caller wants JSON, not a traceback
        print(json.dumps({"error": f"{type(e).__name__}: {e}"}))
        return 1


if __name__ == "__main__":
    sys.exit(main(sys.argv))
