"""ctypes loader for the native (C++) host data-plane kernels.

The reference's native layer is CUDA/NCCL linked through torch/Horovod; the
rebuild's device-side native layer is XLA:TPU itself (SURVEY.md §1 L2). This
module owns the *host-side* native layer: csrc/dls_native.cc, compiled to a
shared library and called through ctypes (pybind11 is not in the image; ctypes
releases the GIL around every call, so these kernels parallelize for real
under the prefetch thread).

Loading strategy: use a prebuilt ``_dls_native*.so`` next to this package if
present, else build one on first import with the system ``g++`` (cached under
``~/.cache/dls_tpu``). Every entry point has a numpy fallback with identical
semantics — :func:`available` says which path is live, and the test suite
pins native == numpy bit-for-bit where exactness is defined.
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
import tempfile

import numpy as np

logger = logging.getLogger("distributeddeeplearningspark_tpu.native")

_CSRC_DIR = os.path.join(os.path.dirname(os.path.dirname(__file__)), "csrc")
_SRC = [
    os.path.join(_CSRC_DIR, "dls_native.cc"),
    os.path.join(_CSRC_DIR, "dls_jpeg.cc"),
]
_LIB: ctypes.CDLL | None = None
_TRIED = False

#: dls_jpeg.cc return codes
_JPEG_OK = 0
_JPEG_UNSUPPORTED = -2

_f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
_u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
_i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")


def _build(srcs: list[str]) -> str | None:
    """Compile csrc → cached .so keyed by source hashes; None if no compiler."""
    h = hashlib.sha256()
    for src in srcs:
        with open(src, "rb") as f:
            h.update(f.read())
    digest = h.hexdigest()[:16]
    cache_dir = os.path.join(
        os.environ.get("XDG_CACHE_HOME", os.path.expanduser("~/.cache")), "dls_tpu"
    )
    out = os.path.join(cache_dir, f"_dls_native_{digest}.so")
    if os.path.exists(out):
        return out
    os.makedirs(cache_dir, exist_ok=True)
    # unique per-builder temp name (mkstemp), atomic rename into the cache:
    # concurrent builders each link their own file and the last rename wins
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=cache_dir)
    os.close(fd)
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread", "-o", tmp, *srcs]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, out)
    except (OSError, subprocess.SubprocessError) as e:
        logger.warning("native build failed (%s); using numpy fallbacks", e)
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None
    return out


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    lib.dls_version.restype = ctypes.c_int
    lib.dls_num_threads.restype = ctypes.c_int
    lib.dls_crop_flip_normalize_batch.argtypes = [
        _u8p, ctypes.c_int64, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        _i32p, _i32p, _u8p, ctypes.c_int, ctypes.c_int, _f32p, _f32p, _f32p,
    ]
    lib.dls_normalize_u8_batch.argtypes = [
        _u8p, ctypes.c_int64, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        _f32p, _f32p, _f32p,
    ]
    lib.dls_resize_bilinear.argtypes = [
        _f32p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, _f32p,
    ]
    lib.dls_rrc_flip_normalize.argtypes = [
        _u8p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, _f32p, _f32p, _f32p,
    ]
    lib.dls_rrc_flip_normalize_varbatch.argtypes = [
        ctypes.POINTER(ctypes.c_void_p), _i32p, _i32p, ctypes.c_int,
        _i32p, _i32p, _i32p, _i32p, _u8p, ctypes.c_int64,
        ctypes.c_int, ctypes.c_int, _f32p, _f32p, _f32p,
    ]
    lib.dls_sum_into_f32.argtypes = [_f32p, _f32p, ctypes.c_int64]
    lib.dls_jpeg_info.restype = ctypes.c_int
    lib.dls_jpeg_info.argtypes = [
        _u8p, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_int),
    ]
    lib.dls_jpeg_decode.restype = ctypes.c_int
    lib.dls_jpeg_decode.argtypes = [_u8p, ctypes.c_int64, _u8p, ctypes.c_int64]
    lib.dls_jpeg_decode_batch.restype = None
    lib.dls_jpeg_decode_batch.argtypes = [
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int, ctypes.POINTER(ctypes.c_int),
    ]
    return lib


def _prebuilt() -> str | None:
    """A _dls_native*.so shipped next to the package (no-compiler deploys)."""
    import glob

    pkg_dir = os.path.dirname(os.path.dirname(__file__))
    hits = sorted(glob.glob(os.path.join(pkg_dir, "_dls_native*.so")))
    return hits[-1] if hits else None


def _load() -> ctypes.CDLL | None:
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    if os.environ.get("DLS_DISABLE_NATIVE"):
        return None
    try:
        path = _prebuilt() or _build(_SRC)
        if path is not None:
            _LIB = _bind(ctypes.CDLL(path))
            logger.info("native kernels loaded (%d threads): %s",
                        _LIB.dls_num_threads(), path)
    except Exception as e:  # any load failure → clean numpy fallback
        logger.warning("native kernels unavailable (%s); using numpy", e)
        _LIB = None
    return _LIB


def available() -> bool:
    return _load() is not None


# ---------------------------------------------------------------------------
# Kernels (native with numpy fallback, identical semantics)
# ---------------------------------------------------------------------------

def crop_flip_normalize_batch(
    images: np.ndarray,          # [N, H, W, C] uint8
    ys: np.ndarray,              # [N] int32 crop origin rows
    xs: np.ndarray,              # [N] int32 crop origin cols
    flips: np.ndarray,           # [N] bool/uint8 horizontal flip
    crop: tuple[int, int],
    mean: np.ndarray,
    std: np.ndarray,
) -> np.ndarray:
    """Fused random-crop + flip + (x/255 - mean)/std over a batch → float32."""
    n, h, w, c = images.shape
    ch, cw = crop
    images = np.ascontiguousarray(images, np.uint8)
    ys = np.ascontiguousarray(ys, np.int32)
    xs = np.ascontiguousarray(xs, np.int32)
    # Bounds-check BEFORE dispatch: the C++ kernel reads raw offsets, so an
    # invalid origin would be an out-of-bounds heap read there, while the
    # numpy path would merely produce a short slice — fail loudly on both.
    if len(ys) != n or len(xs) != n:
        raise ValueError(f"ys/xs must have length {n}: got {len(ys)}/{len(xs)}")
    if ch > h or cw > w:
        raise ValueError(f"crop {crop} exceeds image size {(h, w)}")
    bad = (ys < 0) | (ys > h - ch) | (xs < 0) | (xs > w - cw)
    if bad.any():
        i = int(np.argmax(bad))
        raise ValueError(
            f"crop origin out of bounds at index {i}: y={ys[i]} x={xs[i]} "
            f"for image {(h, w)} crop {crop}")
    flips = np.ascontiguousarray(flips, np.uint8)
    mean = np.ascontiguousarray(mean, np.float32)
    std = np.ascontiguousarray(std, np.float32)
    lib = _load()
    if lib is not None:
        out = np.empty((n, ch, cw, c), np.float32)
        lib.dls_crop_flip_normalize_batch(
            images, n, h, w, c, ys, xs, flips, ch, cw, mean, std, out
        )
        return out
    out = np.empty((n, ch, cw, c), np.float32)
    for i in range(n):
        img = images[i, ys[i]:ys[i] + ch, xs[i]:xs[i] + cw]
        if flips[i]:
            img = img[:, ::-1]
        out[i] = (img.astype(np.float32) / 255.0 - mean) / std
    return out


def normalize_u8_batch(images: np.ndarray, mean: np.ndarray, std: np.ndarray) -> np.ndarray:
    """[N,H,W,C] uint8 → standardized float32 (no crop/flip)."""
    n, h, w, c = images.shape
    images = np.ascontiguousarray(images, np.uint8)
    mean = np.ascontiguousarray(mean, np.float32)
    std = np.ascontiguousarray(std, np.float32)
    lib = _load()
    if lib is not None:
        out = np.empty((n, h, w, c), np.float32)
        lib.dls_normalize_u8_batch(images, n, h, w, c, mean, std, out)
        return out
    return (images.astype(np.float32) / 255.0 - mean) / std


def rrc_flip_normalize(
    image: np.ndarray,                # [H, W, C] uint8
    region: tuple[int, int, int, int],  # (y0, x0, ch, cw) crop in source px
    flip: bool,
    size: tuple[int, int],
    mean: np.ndarray,
    std: np.ndarray,
) -> np.ndarray | None:
    """Fused crop→bilinear-resize→flip→(x/255-mean)/std, uint8 in, f32 out.

    The whole per-epoch augmentation tail of the record input path in ONE
    GIL-free pass with no float intermediate image (the numpy chain converts
    the full frame to f32 before cropping — ~4× the bytes touched). Returns
    None when the native library is unavailable; callers fall back to the
    equivalent numpy chain (vision.train_transform does).
    """
    lib = _load()
    if lib is None:
        return None
    h, w, c = image.shape
    y0, x0, ch, cw = region
    if not (0 <= y0 and 0 <= x0 and ch > 0 and cw > 0
            and y0 + ch <= h and x0 + cw <= w):
        raise ValueError(f"crop region {region} out of bounds for {(h, w)}")
    image = np.ascontiguousarray(image, np.uint8)
    mean = np.ascontiguousarray(mean, np.float32)
    std = np.ascontiguousarray(std, np.float32)
    oh, ow = size
    out = np.empty((oh, ow, c), np.float32)
    lib.dls_rrc_flip_normalize(image, h, w, c, y0, x0, ch, cw, int(flip),
                               oh, ow, mean, std, out)
    return out


def rrc_flip_normalize_varbatch(
    images: list[np.ndarray],          # N × [Hi, Wi, C] uint8 (varying size)
    regions: np.ndarray,               # [N, 4] int32 (y0, x0, ch, cw)
    flips: np.ndarray,                 # [N] uint8
    size: tuple[int, int],
    mean: np.ndarray,
    std: np.ndarray,
    out: np.ndarray | None = None,     # [N, OH, OW, C] f32 (written in place)
) -> np.ndarray | None:
    """Whole-batch fused augmentation over variable-size images in ONE
    native call (parallel over images × row groups) writing directly into
    the batch buffer — no per-image ctypes overhead, no np.stack pass.
    Returns None when the native library is unavailable (callers fall back
    to the per-example path)."""
    lib = _load()
    if lib is None:
        return None
    n = len(images)
    c = images[0].shape[2]
    oh, ow = size
    regions = np.ascontiguousarray(regions, np.int32)
    if regions.shape != (n, 4):
        raise ValueError(f"regions must be [{n}, 4], got {regions.shape}")
    hs = np.empty(n, np.int32)
    ws = np.empty(n, np.int32)
    ptrs = (ctypes.c_void_p * n)()
    contig = []  # keep alive for the duration of the call
    for i, img in enumerate(images):
        img = np.ascontiguousarray(img, np.uint8)
        if img.ndim != 3 or img.shape[2] != c:
            raise ValueError(f"image {i}: want [H, W, {c}] u8, got {img.shape}")
        h, w = img.shape[:2]
        y0, x0, ch, cw = regions[i]
        if not (0 <= y0 and 0 <= x0 and ch > 0 and cw > 0
                and y0 + ch <= h and x0 + cw <= w):
            raise ValueError(
                f"image {i}: crop region {tuple(regions[i])} out of bounds "
                f"for {(h, w)}")
        hs[i], ws[i] = h, w
        contig.append(img)
        ptrs[i] = img.ctypes.data_as(ctypes.c_void_p)
    # fail loudly BEFORE dispatch — the C++ kernel reads raw offsets, so a
    # short flips/mean/std array would be an out-of-bounds heap read there
    flips = np.ascontiguousarray(flips, np.uint8)
    if len(flips) != n:
        raise ValueError(f"flips must have length {n}, got {len(flips)}")
    mean = np.ascontiguousarray(mean, np.float32)
    std = np.ascontiguousarray(std, np.float32)
    if len(mean) != c or len(std) != c:
        raise ValueError(
            f"mean/std must have length {c}, got {len(mean)}/{len(std)}")
    if out is None:
        out = np.empty((n, oh, ow, c), np.float32)
    elif out.shape != (n, oh, ow, c) or out.dtype != np.float32 \
            or not out.flags.c_contiguous:
        raise ValueError(f"out must be C-contiguous [{n}, {oh}, {ow}, {c}] f32")
    lib.dls_rrc_flip_normalize_varbatch(
        ptrs, hs, ws, c,
        np.ascontiguousarray(regions[:, 0]), np.ascontiguousarray(regions[:, 1]),
        np.ascontiguousarray(regions[:, 2]), np.ascontiguousarray(regions[:, 3]),
        flips, n, oh, ow, mean, std, out)
    del contig
    return out


def resize_bilinear(image: np.ndarray, size: tuple[int, int]) -> np.ndarray:
    """[H,W,C] (or [H,W]) float32 → resized, half-pixel centers (vision.py math)."""
    if image.ndim == 2:  # grayscale: process as single-channel
        return resize_bilinear(image[..., None], size)[..., 0]
    h, w, c = image.shape
    oh, ow = size
    if (h, w) == (oh, ow):
        return np.asarray(image, np.float32)
    image = np.ascontiguousarray(image, np.float32)
    lib = _load()
    if lib is not None:
        out = np.empty((oh, ow, c), np.float32)
        lib.dls_resize_bilinear(image, h, w, c, oh, ow, out)
        return out
    from distributeddeeplearningspark_tpu.data import vision

    return vision.resize_bilinear(image, size)


class JpegUnsupported(ValueError):
    """Valid JPEG but a coding mode outside baseline (progressive, 12-bit,
    arithmetic, CMYK) — callers fall back to PIL."""


def jpeg_decode(data: bytes) -> np.ndarray | None:
    """Baseline JPEG bytes → uint8 HWC (csrc/dls_jpeg.cc).

    Returns None when the native library is unavailable; raises
    :class:`JpegUnsupported` for non-baseline streams and ValueError for
    malformed data. The decode releases the GIL (ctypes), so prefetch
    threads decode in parallel with the main thread.
    """
    lib = _load()
    if lib is None:
        return None
    buf = np.frombuffer(data, np.uint8)
    h = ctypes.c_int()
    w = ctypes.c_int()
    c = ctypes.c_int()
    rc = lib.dls_jpeg_info(buf, buf.size, ctypes.byref(h), ctypes.byref(w),
                           ctypes.byref(c))
    if rc == _JPEG_UNSUPPORTED:
        raise JpegUnsupported("non-baseline JPEG (progressive/12-bit/arith)")
    if rc != _JPEG_OK:
        raise ValueError(f"malformed JPEG (dls_jpeg_info rc={rc})")
    out = np.empty((h.value, w.value, c.value), np.uint8)
    rc = lib.dls_jpeg_decode(buf, buf.size, out.reshape(-1), out.size)
    if rc == _JPEG_UNSUPPORTED:
        raise JpegUnsupported("non-baseline JPEG (progressive/12-bit/arith)")
    if rc != _JPEG_OK:
        raise ValueError(f"malformed JPEG (dls_jpeg_decode rc={rc})")
    return out


def jpeg_decode_batch(datas: list[bytes]) -> list[np.ndarray] | None:
    """Decode many baseline JPEGs in parallel (one C++ thread per image).

    Returns None when the native library is unavailable. Per-image failures
    raise (JpegUnsupported if any stream is non-baseline, ValueError
    otherwise) — callers wanting soft failure decode singly.
    """
    lib = _load()
    if lib is None:
        return None
    n = len(datas)
    if n == 0:
        return []
    bufs = [np.frombuffer(d, np.uint8) for d in datas]
    outs: list[np.ndarray] = []
    h = ctypes.c_int()
    w = ctypes.c_int()
    c = ctypes.c_int()
    for buf in bufs:
        rc = lib.dls_jpeg_info(buf, buf.size, ctypes.byref(h), ctypes.byref(w),
                               ctypes.byref(c))
        if rc == _JPEG_UNSUPPORTED:
            raise JpegUnsupported("non-baseline JPEG in batch")
        if rc != _JPEG_OK:
            raise ValueError(f"malformed JPEG in batch (rc={rc})")
        outs.append(np.empty((h.value, w.value, c.value), np.uint8))
    data_ptrs = (ctypes.c_void_p * n)(*[b.ctypes.data for b in bufs])
    lens = (ctypes.c_int64 * n)(*[b.size for b in bufs])
    out_ptrs = (ctypes.c_void_p * n)(*[o.ctypes.data for o in outs])
    out_lens = (ctypes.c_int64 * n)(*[o.size for o in outs])
    rcs = (ctypes.c_int * n)()
    lib.dls_jpeg_decode_batch(data_ptrs, lens, out_ptrs, out_lens, n, rcs)
    for i in range(n):
        if rcs[i] == _JPEG_UNSUPPORTED:
            raise JpegUnsupported(f"non-baseline JPEG at batch index {i}")
        if rcs[i] != _JPEG_OK:
            raise ValueError(f"malformed JPEG at batch index {i} (rc={rcs[i]})")
    return outs


def sum_into(dst: np.ndarray, src: np.ndarray) -> np.ndarray:
    """dst += src (float32, flattened view) — host gradient aggregation."""
    if dst.dtype != np.float32 or not dst.flags.c_contiguous:
        # reshape(-1) on a non-contiguous dst would COPY, and the kernel
        # would accumulate into the discarded copy — hard error instead
        raise ValueError("sum_into needs a C-contiguous float32 dst")
    if src.size != dst.size:
        # the kernel reads dst.size floats from src — a short src would be
        # a heap over-read, not the broadcast error numpy would raise
        raise ValueError(f"sum_into size mismatch: dst {dst.size} vs src {src.size}")
    src = np.ascontiguousarray(src, np.float32)
    lib = _load()
    if lib is not None:
        lib.dls_sum_into_f32(dst.reshape(-1), src.reshape(-1), dst.size)
        return dst
    dst += src
    return dst
