"""Small shared utilities."""
