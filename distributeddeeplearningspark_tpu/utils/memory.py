"""Analytic per-chip HBM budget for Llama training layouts.

VERDICT r2 missing-#3 / next-#3: config 5 names "Llama-2 7B LoRA … on
v4-32", but no 7B geometry had ever been compiled or budgeted. This module
is the checked-in memory analysis: a component-by-component byte budget for
a (batch, seq, mesh, remat, LoRA) layout, validated against the live
backend's compiled memory analysis where one is available (the test suite
cross-checks the formula's activation model against jit-lowered cost
analysis on small shapes; `bench.py --model llama --variant 7b` prints the
report and attempts the real step when a chip is up).

The budget model (bf16 params/activations, f32 LoRA optimizer state):

- **base params**: every dense kernel + embeddings, bf16, sharded over
  mesh's fsdp×tensor product (GSPMD shards both; data/seq axes replicate).
- **LoRA params + AdamW state**: rank·(in+out) per adapted projection; the
  masked optimizer allocates m/v for trainable leaves only. f32 ×3 (param
  + m + v) + a bf16 compute copy.
- **gradients**: trainable-only (frozen base excluded from autodiff —
  train/step.py `trainable`); transient f32 at adapter size.
- **activations** (the term remat policy controls), per layer per token:
  - policy None: only the scan-carry residual stream survives the forward
    (hidden bf16), everything else recomputes in backward;
  - policy "dots": matmul outputs are kept — q/k/v/attn-out, gate/up/down:
    (3 + 2·kv/h)·H + 3·I bf16 per token per layer, plus the carry.
  Activations shard over data×seq (batch and sequence parallel axes);
  tensor shards the head/ffn dims of the saved dots.
- **head/loss**: fused CE keeps [B,S,H] hidden + chunked logits (≤
  chunk·V); unfused keeps [B,S,V] f32 logits + cotangent (the 2.1 GB the
  fused path exists to kill).
- **workspace**: one transient ~max-layer-tensor ×2 allowance for XLA
  temp buffers (measured fudge, stated explicitly in the report).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

GiB = 1024 ** 3


@dataclass
class MemoryReport:
    components: dict[str, float]  # bytes per chip
    mesh: dict[str, int]
    notes: list[str]

    @property
    def total_bytes(self) -> float:
        return sum(self.components.values())

    def fits(self, hbm_bytes: float) -> bool:
        return self.total_bytes <= hbm_bytes

    def to_dict(self) -> dict:
        return {
            "per_chip_gib": {k: round(v / GiB, 3)
                             for k, v in self.components.items()},
            "total_gib_per_chip": round(self.total_bytes / GiB, 3),
            "mesh": dict(self.mesh),
            "notes": list(self.notes),
        }


def llama_param_count(cfg) -> dict[str, int]:
    """Exact parameter counts by group (validated vs model.init in tests)."""
    h, i, v = cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size
    kvh = cfg.num_kv_heads * cfg.head_dim
    # MoE (moe_experts > 0) replaces the dense FFN with a router + an
    # E-wide expert bank — the DOMINANT param term (bf16 E=8 at the 0.9b
    # shape is 8.9 GiB of kernels alone); counted exactly like model.init
    e = getattr(cfg, "moe_experts", 0)
    ffn = (h * e + e * 3 * h * i) if e else 3 * h * i
    per_layer = (
        h * h            # wq
        + 2 * h * kvh    # wk, wv
        + h * h          # wo
        + ffn            # dense SwiGLU, or router + stacked expert bank
        + 2 * h          # two RMSNorm scales
    )
    base = cfg.num_layers * per_layer + v * h + h + v * h  # + final norm + head
    if getattr(cfg, "base_quant", None) == "int8":
        # per-output-channel scale leaves ride next to every int8 kernel
        base += cfg.num_layers * (3 * h + 2 * kvh + 2 * i)
    lora = 0
    if cfg.lora_rank:
        r = cfg.lora_rank
        sizes = {"wq": (h, h), "wk": (h, kvh), "wv": (h, kvh), "wo": (h, h),
                 "gate": (h, i), "up": (h, i), "down": (i, h)}
        for t in cfg.lora_targets:
            if t in sizes:
                fin, fout = sizes[t]
                lora += cfg.num_layers * r * (fin + fout)
    return {"base": base, "lora": lora}


def llama_memory_report(
    cfg,
    *,
    batch: int,
    seq: int,
    mesh_shape: dict[str, int] | None = None,
    optimizer: str = "adamw",
    trainable: str = "lora",
    hbm_per_chip_gib: float | None = None,
) -> MemoryReport:
    """Per-chip HBM budget for one train step of ``cfg`` at (batch, seq).

    ``mesh_shape``: axis→size (missing axes = 1); params shard over
    fsdp×tensor, activations over data×seq. ``trainable='lora'`` assumes
    the frozen-base autodiff exclusion (no base grads/opt state).
    """
    mesh_shape = dict(mesh_shape or {})
    dp = mesh_shape.get("data", 1)
    fsdp = mesh_shape.get("fsdp", 1)
    tp = mesh_shape.get("tensor", 1)
    sp = mesh_shape.get("seq", 1)
    param_shard = fsdp * tp
    act_shard = dp * sp

    counts = llama_param_count(cfg)
    notes: list[str] = []
    comp: dict[str, float] = {}
    # STORAGE dtype of the base weights (LlamaConfig.param_dtype): the r4
    # memval run caught this model assuming bf16 while the weights were
    # stored f32 (compiled argument size 25.2 vs analytic 12.6 GiB on the
    # 7B) — the byte count must come from the config, not an assumption
    pdt = str(getattr(cfg, "param_dtype", "float32"))
    pbytes = 2 if ("bfloat16" in pdt or "float16" in pdt) else 4
    if getattr(cfg, "base_quant", None) == "int8":
        # int8 projection/FFN kernels + f32 per-out-channel scales; the
        # embedding and LM head stay at param_dtype (QLoRA convention,
        # see LlamaConfig.base_quant). Scales are per output channel —
        # ≤ (heads·hd + i + h) per layer, O(1e-3) of the kernel bytes.
        emb_head = 2 * cfg.vocab_size * cfg.hidden_size
        norms = cfg.num_layers * 2 * cfg.hidden_size + cfg.hidden_size
        scales = cfg.num_layers * (
            2 * cfg.hidden_size                       # wq out + wo out
            + 2 * cfg.num_kv_heads * cfg.head_dim     # wk, wv out
            + 2 * cfg.intermediate_size               # gate, up out
            + cfg.hidden_size)                        # down out
        # counts["base"] already includes the scale leaves (param-count
        # parity with model.init) — subtract them so they aren't charged
        # once at 1 B here and again at 4 B below
        kernels = counts["base"] - emb_head - norms - scales
        comp["base_params_int8"] = (
            kernels * 1 + (scales + norms) * 4 + emb_head * pbytes
        ) / param_shard
        notes.append("base_quant=int8: kernels 1 B + f32 scales; "
                     "embed/head at param_dtype")
    else:
        comp[f"base_params_{'bf16' if pbytes == 2 else 'f32'}"] = (
            counts["base"] * pbytes / param_shard)

    n_lora = counts["lora"]
    if trainable == "lora" and cfg.lora_rank:
        # f32 master + AdamW m/v (masked optimizer: trainable leaves only)
        opt_mult = 3 if optimizer == "adamw" else 1
        comp["lora_params_opt_f32"] = n_lora * 4 * opt_mult / param_shard
        comp["trainable_grads_f32"] = n_lora * 4 / param_shard
    else:
        opt_mult = 3 if optimizer == "adamw" else 1
        comp["params_opt_f32"] = counts["base"] * 4 * opt_mult / param_shard
        comp["grads_f32"] = counts["base"] * 4 / param_shard
        notes.append("full-parameter training: base grads + opt state counted")

    tokens = batch * seq
    h, i = cfg.hidden_size, cfg.intermediate_size
    kv_frac = cfg.num_kv_heads / cfg.num_heads
    carry = tokens * h * 2  # residual stream checkpointed per scan step
    if cfg.remat and cfg.remat_policy is None:
        per_layer_saved = carry
        notes.append("remat_policy=None: only the scan carry survives fwd")
    elif cfg.remat:  # "dots"-family
        dots = tokens * ((3 + 2 * kv_frac) * h + 3 * i) * 2
        per_layer_saved = carry + dots
        notes.append("remat_policy=dots: matmul outputs kept per layer")
    else:
        # no remat: everything live — dots + norms + softmax probs (approx)
        dots = tokens * ((3 + 2 * kv_frac) * h + 3 * i) * 2
        per_layer_saved = carry + dots + tokens * h * 4
        notes.append("remat off: full activation liveness (approximate)")
    # tensor parallel shards the dot outputs' feature dims; the carry
    # (residual stream) is replicated across tensor — data/seq shard it
    comp["activations_bf16"] = cfg.num_layers * (
        carry / act_shard + (per_layer_saved - carry) / act_shard / tp)

    v = cfg.vocab_size
    if cfg.fused_head_loss:
        chunk = min(tokens, 2048)
        comp["loss_head"] = (tokens * h * 2 + chunk * v * 4) / act_shard
        notes.append("fused CE: chunked logits, no [B,S,V] materialization")
    else:
        comp["loss_head"] = tokens * v * 4 * 2 / act_shard  # logits + cotangent
        notes.append("unfused head: [B,S,V] f32 logits + cotangent live")

    # transient workspace: ~2× the largest single tensor in flight
    biggest = max(tokens * max(h, i) * 2 / act_shard,
                  counts["base"] * 2 / param_shard / max(cfg.num_layers, 1))
    comp["xla_workspace_allowance"] = 2 * biggest
    notes.append("workspace = 2x largest in-flight tensor (stated fudge)")

    if hbm_per_chip_gib is not None:
        notes.append(
            f"fits {hbm_per_chip_gib} GiB/chip: "
            f"{sum(comp.values()) <= hbm_per_chip_gib * GiB}")
    return MemoryReport(components=comp, mesh=mesh_shape, notes=notes)
