"""Gang-aware placement, quotas/priorities, and the reconcile loop.

:func:`plan` is the pure packing decision (state in, actions out — unit
testable with no processes); :class:`Scheduler` is the control loop that
folds the ledger, reconciles it against reality (runner liveness, each
workdir's ``health.json``, observed drains), executes the plan, and
launches placed jobs through :mod:`.runner`.

Preemption ladder (highest-priority pending job first):

1. **Free hosts** — place on them when all gangs fit; no victim needed.
2. **Graceful shrink** — an elastic, single-gang, lower-priority victim
   above its ``min_hosts`` floor gives back its highest gang ordinal: a
   preemption notice file (:func:`~..faults.deliver_preempt_notice`) makes
   the trainer drain in-flight work, commit the live handoff, and exit
   clean; the victim's own supervisor shrinks and resumes WITHOUT
   walk-back, and the freed host joins the pool next tick (when the
   drain's ``geometry_change`` lands in the victim's stream).
3. **Eviction** — when shrinking can't cover the deficit, the whole
   lowest-priority victim is stopped (SIGTERM its process group, escalate
   to SIGKILL) and requeued; it resumes later from its checkpoint on
   whatever is free, through reshard-on-restore.
4. **Blocked** — equal-or-higher-priority holders are never preempted;
   the job waits in the queue with its reason recorded.

A preempting tick does NOT place the beneficiary — hosts freed by a drain
or eviction only exist once the ledger says so, and the next tick places
against the real inventory (no optimistic double-booking).
"""

from __future__ import annotations

import dataclasses
import logging
import os
import signal
import subprocess
import sys
import time

from distributeddeeplearningspark_tpu import faults
from distributeddeeplearningspark_tpu import telemetry as telemetry_lib
from distributeddeeplearningspark_tpu.scheduler import ledger as ledger_lib

logger = logging.getLogger("distributeddeeplearningspark_tpu.scheduler")

#: the runtime preemption notice file, under the victim's workdir
PREEMPT_NOTICE_NAME = "PREEMPT"
#: checkpoint subdir convention for scheduler-launched jobs ({ckpt} in
#: a submitted command expands to it; the DRAIN evidence lands there)
CKPT_DIRNAME = "ckpt"

#: steps of margin between a victim's last observed step and the notice's
#: drain-step floor — the window in which every rank must observe the
#: notice file so the gang drains at ONE agreed step
DRAIN_MARGIN_ENV = "DLS_SCHED_DRAIN_MARGIN_STEPS"
#: heartbeat age (seconds) past which a CRIT job is declared wedged and
#: requeued (its runner killed first)
WEDGE_ENV = "DLS_SCHED_WEDGE_S"
#: requeues after which a job is declared failed instead of relaunched —
#: a job whose runner dies every attempt must not spin the cluster forever
MAX_REQUEUES_ENV = "DLS_SCHED_MAX_REQUEUES"


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, ""))
    except ValueError:
        return default


def notice_path(workdir: str) -> str:
    return os.path.join(workdir, PREEMPT_NOTICE_NAME)


@dataclasses.dataclass(frozen=True)
class Placement:
    """Place ``job_id`` on ``assignment`` (gang ordinal -> host slot)."""

    job_id: str
    assignment: dict[int, str]


@dataclasses.dataclass(frozen=True)
class Preemption:
    """Reclaim hosts from ``victim``: ``mode`` "shrink" drains gang
    ordinal ``ordinal`` (one host back, job keeps running); "evict"
    stops and requeues the whole job."""

    victim: str
    mode: str  # "shrink" | "evict"
    for_job: str
    ordinal: int | None = None


def plan(state: ledger_lib.ClusterState) -> dict:
    """The packing decision: placements for pending jobs that fit (whole
    gangs, within quota), preemptions where a higher-priority job is
    short, and the blocked remainder with reasons. Pure — no clocks, no
    filesystem, deterministic given the state."""
    placements: list[Placement] = []
    preemptions: list[Preemption] = []
    blocked: list[dict] = []
    free = list(state.free_hosts())
    used = state.used_by_tenant()
    # victims a preemption was already planned against this tick (or whose
    # drain is still in flight from an earlier tick) are off the table
    claimed_victims = {j.job_id for j in state.jobs.values()
                       if j.draining is not None}
    for job in state.pending():
        quota = state.quota_of(job.tenant)
        if quota is not None and used.get(job.tenant, 0) + job.min_hosts > quota:
            blocked.append({"job": job.job_id, "reason": "quota",
                            "detail": f"used {used.get(job.tenant, 0)} + "
                                      f"min {job.min_hosts} > quota {quota}"})
            continue
        want = job.total_hosts
        if quota is not None:
            want = min(want, quota - used.get(job.tenant, 0))
        if want >= job.total_hosts and len(free) >= job.total_hosts:
            take = job.total_hosts
        elif (len(job.gangs) == 1 and job.min_hosts < job.total_hosts
              and min(want, len(free)) >= job.min_hosts):
            # elastic partial placement: run now on what's free (a
            # requeued preemptee resuming on fewer hosts lands here —
            # reshard-on-restore makes the geometry change safe)
            take = min(want, len(free))
        else:
            take = 0
        if take:
            assignment = {o: free[o] for o in range(take)}
            placements.append(Placement(job.job_id, assignment))
            free = free[take:]
            used[job.tenant] = used.get(job.tenant, 0) + take
            continue
        # can't place: try to free hosts from strictly-lower-priority
        # holders (never peers — priority ties don't churn each other).
        # The preemption goal is the job's FLOOR, not its full size:
        # minimal disruption now, elastic growth later when hosts free up
        deficit = job.min_hosts - len(free)
        victims = sorted(
            (v for v in state.jobs.values()
             if v.status in ledger_lib.ACTIVE_STATUSES
             and v.priority < job.priority
             and v.job_id not in claimed_victims),
            key=lambda v: (v.priority, -(v.started_ts or 0.0)))
        planned: list[Preemption] = []
        for v in victims:
            if deficit <= 0:
                break
            shrinkable = (len(v.assignment) - v.min_hosts
                          if len(v.gangs) == 1 else 0)
            if v.status == "RUNNING" and shrinkable >= 1:
                # one drained host per victim per tick: the graceful
                # machinery re-gathers ONE doomed host's shards at a time
                ordinal = max(v.assignment)
                planned.append(Preemption(v.job_id, "shrink", job.job_id,
                                          ordinal=ordinal))
                deficit -= 1
            else:
                planned.append(Preemption(v.job_id, "evict", job.job_id))
                deficit -= len(v.assignment)
        if deficit <= 0 and planned:
            preemptions.extend(planned)
            claimed_victims.update(p.victim for p in planned)
            blocked.append({"job": job.job_id,
                            "reason": "awaiting-preemption",
                            "detail": f"{len(planned)} victim(s) preempted"})
        else:
            blocked.append({"job": job.job_id, "reason": "capacity",
                            "detail": f"needs {job.min_hosts}+, "
                                      f"{len(free)} free, no lower-priority "
                                      f"victim covers the deficit"})
    return {"place": placements, "preempt": preemptions, "blocked": blocked}


class Scheduler:
    """The cluster control loop over one state dir.

    Crash-recoverable by construction: every decision is a ledger append
    before it is an action, and a fresh Scheduler on the same root folds
    itself back to the identical view. ``clock`` is injectable so the
    accounting tests run on a fake clock."""

    def __init__(self, root: str | os.PathLike, *, clock=time.time):
        self.root = os.path.abspath(os.fspath(root))
        self._clock = clock
        self._tele: telemetry_lib.EventWriter | None = None
        #: Popen handles for runners THIS process launched (liveness via
        #: poll(); a recovered scheduler falls back to kill(pid, 0))
        self._procs: dict[str, subprocess.Popen] = {}
        self._engines: dict[str, object] = {}

    # -- telemetry ------------------------------------------------------------

    def _telemetry(self) -> telemetry_lib.EventWriter:
        if self._tele is None:
            self._tele = telemetry_lib.EventWriter(
                ledger_lib.sched_dir(self.root), process="sched", host=None,
                clock=self._clock)
        return self._tele

    def _emit(self, edge: str, job: ledger_lib.Job, *, mirror: bool = False,
              **fields) -> None:
        """One ``sched`` event into the scheduler's own stream, mirrored
        into the job's workdir stream for the edges that concern it (so
        the job's incident timeline shows its own preemption)."""
        rec = {"edge": edge, "job": job.job_id, "tenant": job.tenant,
               "priority": job.priority, **fields}
        self._telemetry().emit("sched", **rec)
        if mirror and job.workdir:
            w = telemetry_lib.EventWriter(
                job.workdir, process="sched", host=None, clock=self._clock,
                tenant=job.tenant, priority=job.priority)
            try:
                w.emit("sched", **rec)
            finally:
                w.close()

    def close(self) -> None:
        if self._tele is not None:
            self._tele.close()
            self._tele = None
        for eng in self._engines.values():
            try:
                eng.close()
            except Exception:  # noqa: BLE001 — teardown must not raise
                pass
        self._engines.clear()

    # -- submission -----------------------------------------------------------

    def submit(self, cmd: list[str], *, tenant: str, priority: int = 0,
               gangs: list[int] | int = 1, min_hosts: int | None = None,
               name: str | None = None, kind: str = "train",
               env: dict[str, str] | None = None) -> str:
        """Append a job to the queue; returns its ledger id. ``cmd`` may
        reference ``{workdir}`` / ``{ckpt}``, expanded at launch to the
        job's run directory / checkpoint root."""
        gangs = [gangs] if isinstance(gangs, int) else list(gangs)
        if not gangs or any(g < 1 for g in gangs):
            raise ValueError(f"bad gang shape {gangs}: every gang needs "
                             f">= 1 host")
        total = sum(gangs)
        min_hosts = total if min_hosts is None else int(min_hosts)
        if not 1 <= min_hosts <= total:
            raise ValueError(
                f"min_hosts {min_hosts} outside [1, {total}]")
        if len(gangs) > 1 and min_hosts != total:
            raise ValueError(
                "multi-gang jobs are rigid: every gang places whole-or-"
                "not-at-all, so min_hosts must equal the total "
                f"({total}); only single-gang jobs shrink elastically")
        ledger_lib.load_config(self.root)  # init_cluster must have run
        job_id = ledger_lib.next_job_id(self.root)
        spec = {"name": name or job_id, "tenant": tenant,
                "priority": int(priority), "gangs": gangs,
                "min_hosts": min_hosts, "cmd": list(cmd), "kind": kind,
                "env": dict(env or {}),
                "workdir": ledger_lib.job_workdir(self.root, job_id)}
        rec = ledger_lib.append(self.root, "submit", job_id,
                                ts=self._clock(), spec=spec)
        state = ledger_lib.ClusterState(self.root, [], {})
        state.apply(rec)
        self._emit("submit", state.jobs[job_id], gangs=gangs,
                   min_hosts=min_hosts)
        logger.info("submitted %s: tenant=%s priority=%d gangs=%s",
                    job_id, tenant, priority, gangs)
        return job_id

    def cancel(self, job_id: str) -> None:
        state = ledger_lib.load_state(self.root)
        job = state.jobs[job_id]
        if job.status == "RUNNING":
            self._stop_runner(job)
        if job.status not in ledger_lib.TERMINAL_STATUSES:
            ledger_lib.append(self.root, "cancel", job_id, ts=self._clock())
            self._emit("cancel", job)

    # -- reconciliation -------------------------------------------------------

    def _runner_alive(self, job: ledger_lib.Job) -> bool:
        if job.pid is None:
            return False
        proc = self._procs.get(job.job_id)
        if proc is not None and proc.pid == job.pid:
            return proc.poll() is None
        try:
            os.kill(job.pid, 0)
            return True
        except OSError:
            return False

    def _stop_runner(self, job: ledger_lib.Job,
                     *, grace_s: float = 5.0) -> None:
        """SIGTERM the runner's whole process group (runner + supervisor
        + gang — the runner is a session leader), escalate to SIGKILL.
        Zero orphans is the contract the CI drill asserts."""
        if job.pid is None:
            return
        for sig in (signal.SIGTERM, signal.SIGKILL):
            try:
                os.killpg(job.pid, sig)
            except OSError:
                break  # group already gone
            deadline = time.time() + grace_s
            while time.time() < deadline:
                if not self._runner_alive(job):
                    break
                time.sleep(0.05)
            if not self._runner_alive(job):
                break
        proc = self._procs.pop(job.job_id, None)
        if proc is not None:
            try:
                proc.wait(timeout=grace_s)
            except Exception:  # noqa: BLE001 — reaping is best-effort
                pass

    def _health_of(self, job: ledger_lib.Job) -> dict | None:
        """Evaluate (and rewrite) the job workdir's ``health.json`` —
        the scheduler doubles as the fleet's health daemon, and its
        requeue decisions read the same machine contract operators do."""
        if not job.workdir or not os.path.isdir(
                telemetry_lib.telemetry_dir(job.workdir)):
            return None
        from distributeddeeplearningspark_tpu.telemetry import health

        eng = self._engines.get(job.workdir)
        if eng is None:
            # write_alerts=False: the scheduler inspects the job's
            # stream, it must not append alert edges to it
            eng = self._engines[job.workdir] = health.HealthEngine(
                job.workdir, damping=1, write_alerts=False)
        try:
            return eng.evaluate()
        except Exception:  # noqa: BLE001 — health is advisory
            logger.debug("health evaluation failed for %s", job.workdir,
                         exc_info=True)
            return None

    def _observed_drain(self, job: ledger_lib.Job) -> str | None:
        """The host slot a delivered shrink notice has finished freeing
        (the victim's own stream carries the ``geometry_change``), or
        None while the drain is still in flight."""
        if job.draining is None or not job.workdir:
            return None
        since = job.draining_since or 0.0
        for e in telemetry_lib.read_events(job.workdir):
            if (e.get("kind") == "recovery"
                    and e.get("event") == "geometry_change"
                    and e.get("dead_host") == job.draining
                    and e.get("resume") == "live-handoff"
                    and float(e.get("ts", 0.0)) >= since):
                return job.assignment.get(job.draining)
        return None

    def _reconcile(self, state: ledger_lib.ClusterState) -> dict:
        """Absorb reality into the ledger: completed drains free their
        hosts, dead runners and wedged jobs requeue."""
        out = {"shrunk": [], "requeued": []}
        wedge_s = _env_int(WEDGE_ENV, 300)
        for job in list(state.running()):
            freed = self._observed_drain(job)
            if freed is not None:
                rec = ledger_lib.append(
                    self.root, "shrink", job.job_id, ts=self._clock(),
                    ordinal=job.draining, host=freed)
                self._emit("shrink", job, mirror=True,
                           ordinal=job.draining, host=freed)
                state.apply(rec)
                out["shrunk"].append(job.job_id)
            if not self._runner_alive(job):
                # the runner appends complete/fail itself; a RUNNING job
                # with a dead runner died without a verdict — requeue it
                # (its checkpoint survives; placement is elastic)
                self._requeue_or_fail(state, out, job, "runner-died")
                continue
            rep = self._health_of(job)
            hb_age = rep.get("last_heartbeat_age_s") if rep else None
            if (rep is not None and rep.get("worst_severity") == "CRIT"
                    and hb_age is not None and hb_age > wedge_s):
                self._stop_runner(job)
                self._requeue_or_fail(state, out, job, "wedged",
                                      heartbeat_age_s=round(float(hb_age), 1))
        return out

    def _requeue_or_fail(self, state: ledger_lib.ClusterState, out: dict,
                         job: ledger_lib.Job, reason: str, **fields) -> None:
        """Requeue the job for replacement, or — past the requeue budget —
        declare it FAILED so a crash-looping runner cannot hold the queue
        hostage."""
        if job.requeues >= _env_int(MAX_REQUEUES_ENV, 5):
            rec = ledger_lib.append(
                self.root, "fail", job.job_id, ts=self._clock(), rc=None,
                classification=f"requeue-limit:{reason}")
            self._emit("fail", job, mirror=True,
                       classification=f"requeue-limit:{reason}", **fields)
        else:
            rec = ledger_lib.append(self.root, "requeue", job.job_id,
                                    ts=self._clock(), reason=reason, **fields)
            self._emit("requeue", job, mirror=True, reason=reason, **fields)
            out["requeued"].append(job.job_id)
        state.apply(rec)

    # -- acting on the plan ---------------------------------------------------

    def _last_step(self, job: ledger_lib.Job) -> int:
        last = 0
        if job.workdir:
            for e in telemetry_lib.read_events(job.workdir):
                s = e.get("step")
                if (e.get("kind") in ("step_metrics", "heartbeat")
                        and isinstance(s, (int, float))):
                    last = max(last, int(s))
        return last

    def _deliver_shrink(self, state: ledger_lib.ClusterState,
                        p: Preemption) -> None:
        victim = state.jobs[p.victim]
        floor = self._last_step(victim) + _env_int(DRAIN_MARGIN_ENV, 2)
        faults.deliver_preempt_notice(
            notice_path(victim.workdir), host=p.ordinal, step=floor)
        rec = ledger_lib.append(
            self.root, "preempt", p.victim, ts=self._clock(), mode="shrink",
            ordinal=p.ordinal, victim_of=p.for_job, step=floor)
        self._emit("preempt", victim, mirror=True, mode="shrink",
                   ordinal=p.ordinal, victim_of=p.for_job, step=floor)
        state.apply(rec)
        logger.warning("preempting %s (shrink ordinal %d) for %s",
                       p.victim, p.ordinal, p.for_job)

    def _evict(self, state: ledger_lib.ClusterState, p: Preemption) -> None:
        victim = state.jobs[p.victim]
        self._stop_runner(victim)
        for edge, fields in (("preempt", {"mode": "evict",
                                          "victim_of": p.for_job}),
                             ("requeue", {"reason":
                                          f"evicted-for-{p.for_job}"})):
            rec = ledger_lib.append(self.root, edge, p.victim,
                                    ts=self._clock(), **fields)
            self._emit(edge, victim, mirror=True, **fields)
            state.apply(rec)
        logger.warning("preempting %s (evict) for %s", p.victim, p.for_job)

    def _launch(self, state: ledger_lib.ClusterState,
                pl: Placement) -> None:
        job = state.jobs[pl.job_id]
        os.makedirs(job.workdir, exist_ok=True)
        log_path = os.path.join(job.workdir, "runner.log")
        # the detached runner must resolve this package regardless of the
        # scheduler's cwd / install mode
        pkg_parent = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env = dict(os.environ)
        env["PYTHONPATH"] = (pkg_parent + os.pathsep + env["PYTHONPATH"]
                             if env.get("PYTHONPATH") else pkg_parent)
        with open(log_path, "ab") as log:
            proc = subprocess.Popen(
                [sys.executable, "-m",
                 "distributeddeeplearningspark_tpu.scheduler.runner",
                 "--root", self.root, "--job", job.job_id],
                stdout=log, stderr=subprocess.STDOUT,
                env=env, start_new_session=True)
        self._procs[job.job_id] = proc
        rec = ledger_lib.append(self.root, "launch", job.job_id,
                                ts=self._clock(), pid=proc.pid,
                                workdir=job.workdir)
        self._emit("launch", job, pid=proc.pid)
        state.apply(rec)
        logger.info("launched %s (pid %d) on %s", job.job_id, proc.pid,
                    job.held_hosts)

    def tick(self, *, launch: bool = True) -> dict:
        """One reconcile + plan + act pass. ``launch=False`` records
        placements in the ledger without spawning runners (planning /
        test mode). Returns a summary of everything this tick did."""
        state = ledger_lib.load_state(self.root)
        summary = self._reconcile(state)
        actions = plan(state)
        for p in actions["preempt"]:
            if p.mode == "shrink":
                self._deliver_shrink(state, p)
            else:
                self._evict(state, p)
        placed, launched = [], []
        for pl in actions["place"]:
            job = state.jobs[pl.job_id]
            rec = ledger_lib.append(
                self.root, "place", pl.job_id, ts=self._clock(),
                assignment=sorted([o, h] for o, h in pl.assignment.items()))
            state.apply(rec)
            self._emit("place", state.jobs[pl.job_id], mirror=True,
                       assignment=sorted(
                           [o, h] for o, h in pl.assignment.items()))
            placed.append(pl.job_id)
            if launch:
                self._launch(state, pl)
                launched.append(pl.job_id)
        summary.update({
            "placed": placed, "launched": launched,
            "preempted": [(p.victim, p.mode) for p in actions["preempt"]],
            "blocked": actions["blocked"],
            "free_hosts": state.free_hosts(),
        })
        return summary

    def run(self, *, interval: float = 2.0, max_ticks: int | None = None,
            until_idle: bool = False) -> int:
        """The daemon loop: tick forever (or ``max_ticks``), or with
        ``until_idle`` until every submitted job is terminal. Returns the
        number of ticks run."""
        ticks = 0
        while True:
            self.tick()
            ticks += 1
            if max_ticks is not None and ticks >= max_ticks:
                return ticks
            if until_idle:
                state = ledger_lib.load_state(self.root)
                if state.jobs and all(
                        j.status in ledger_lib.TERMINAL_STATUSES
                        for j in state.jobs.values()):
                    return ticks
            time.sleep(max(0.05, interval))
