"""Multi-tenant cluster scheduler — the resource manager above the job.

The reference's Spark heritage implies a Borg/YARN-shaped layer the rebuild
never had: many tenants submitting training gangs, serving fleets, MPMD
stage pipelines, and shuffle pools against ONE shared host fleet. This
package is that control plane, built on the planes that already exist
instead of beside them:

- **Durable state** (:mod:`.ledger`): an append-only JSONL ledger + an
  atomic ``cluster.json`` host/quota inventory under ``<root>/sched`` —
  crash-recoverable by the same fold-the-stream discipline as the
  telemetry and checkpoint planes. Current cluster state is a pure fold
  over the ledger; a restarted scheduler resumes from the fold.
- **Gang-aware placement** (:mod:`.core`): a job declares its gangs (a
  mesh, each MPMD stage, a shuffle pool) and every gang places
  whole-or-not-at-all — the 2412.14374 model where a gang is the
  indivisible scheduling unit. Per-tenant host quotas bound admission;
  integer priorities order the queue.
- **Checkpoint-preemption on the elastic machinery**: a high-priority job
  short of hosts preempts the lowest-priority victim — preferring a
  *graceful shrink* (the PR 16 drain: a runtime preemption notice file,
  :func:`~..faults.deliver_preempt_notice`, makes the victim checkpoint/
  hand off live state and give one host back NOW, resuming the rest
  without walk-back) and falling back to *eviction* (stop + requeue; the
  victim later resumes from its checkpoint on whatever frees up, through
  reshard-on-restore).
- **Reconciliation** (:meth:`.core.Scheduler.tick`): each tick consumes
  every running job's workdir — its ``health.json`` (worst severity,
  heartbeat age) and telemetry stream (geometry changes, runner
  liveness) — to absorb completed shrinks, free hosts, and requeue dead
  or wedged jobs.

Everything here is jax-free: the scheduler is an operator-side control
loop, cheap enough for a CLI. Jobs are launched through the existing
supervisor machinery with the ``DLS_*`` env contract (see :mod:`.runner`),
and every lifecycle edge is also emitted as a ``sched`` telemetry event,
so ``dlstatus --cluster`` / ``--incidents`` / ``--export-trace`` see the
scheduler's decisions in the same streams as everything else.
"""

from distributeddeeplearningspark_tpu.scheduler.core import (  # noqa: F401
    Placement,
    Preemption,
    Scheduler,
    plan,
)
from distributeddeeplearningspark_tpu.scheduler.ledger import (  # noqa: F401
    ACTIVE_STATUSES,
    EDGES,
    ClusterState,
    Job,
    append,
    init_cluster,
    job_workdir,
    ledger_path,
    load_config,
    load_state,
    read_ledger,
    sched_dir,
)
