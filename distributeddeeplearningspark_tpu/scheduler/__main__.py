"""``python -m distributeddeeplearningspark_tpu.scheduler`` — operate a
cluster state dir: init the inventory, run the control loop, inspect the
queue. Submission goes through ``dlsubmit --cluster`` (cli.py); this is
the operator side.

    python -m distributeddeeplearningspark_tpu.scheduler init ROOT --hosts 4 \\
        --quota research=2 --quota prod=4
    python -m distributeddeeplearningspark_tpu.scheduler tick ROOT
    python -m distributeddeeplearningspark_tpu.scheduler run ROOT --interval 2
    python -m distributeddeeplearningspark_tpu.scheduler status ROOT
"""

from __future__ import annotations

import argparse
import json
import sys

from distributeddeeplearningspark_tpu.scheduler import core, ledger


def _parse_quota(entries: list[str]) -> dict[str, int]:
    quotas: dict[str, int] = {}
    for e in entries:
        tenant, sep, n = e.partition("=")
        if not sep:
            raise SystemExit(f"--quota expects TENANT=HOSTS, got {e!r}")
        quotas[tenant] = int(n)
    return quotas


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m distributeddeeplearningspark_tpu.scheduler",
        description="Operate a multi-tenant cluster scheduler state dir.")
    sub = ap.add_subparsers(dest="op", required=True)
    p_init = sub.add_parser("init", help="create the host/quota inventory")
    p_init.add_argument("root")
    p_init.add_argument("--hosts", type=int, required=True)
    p_init.add_argument("--quota", action="append", default=[],
                        metavar="TENANT=HOSTS")
    p_tick = sub.add_parser("tick", help="one reconcile+plan+act pass")
    p_tick.add_argument("root")
    p_tick.add_argument("--no-launch", action="store_true",
                        help="record placements without spawning runners")
    p_run = sub.add_parser("run", help="the control loop")
    p_run.add_argument("root")
    p_run.add_argument("--interval", type=float, default=2.0)
    p_run.add_argument("--max-ticks", type=int, default=None)
    p_run.add_argument("--until-idle", action="store_true",
                       help="exit once every submitted job is terminal")
    p_status = sub.add_parser("status", help="queue + accounting (JSON)")
    p_status.add_argument("root")
    args = ap.parse_args(argv)

    if args.op == "init":
        cfg = ledger.init_cluster(args.root, hosts=args.hosts,
                                  quotas=_parse_quota(args.quota))
        print(json.dumps(cfg))
        return 0
    if args.op == "status":
        print(json.dumps(ledger.load_state(args.root).to_report()))
        return 0
    sched = core.Scheduler(args.root)
    try:
        if args.op == "tick":
            print(json.dumps(sched.tick(launch=not args.no_launch)))
            return 0
        sched.run(interval=args.interval, max_ticks=args.max_ticks,
                  until_idle=args.until_idle)
        return 0
    finally:
        sched.close()


if __name__ == "__main__":
    sys.exit(main())
