"""Job runner: one placed ledger job, launched through the supervisor.

The scheduler spawns ``python -m ...scheduler.runner --root R --job J``
as a detached session leader; the runner builds the job's env from the
``DLS_*`` contract (tenant, priority, telemetry workdir, the preemption-
notice path) and runs one :class:`~..supervisor.Supervisor` per gang —
so a scheduler-launched job gets the WHOLE elastic machinery for free:
restart classification, backoff, shrink-to-survive, graceful-drain
handling, and the merged telemetry stream ``dlstatus`` reads.

The runner's last act is the job's verdict: a ``complete`` or ``fail``
ledger edge. A runner that dies without one (SIGKILL, node loss) is what
the scheduler's reconcile loop detects and requeues.

Command/env templating: ``{workdir}``, ``{ckpt}`` and ``{root}`` in a
submitted command or env value expand at launch — a submitter does not
know the job's run directory (it is derived from the ledger id), so the
template is how a training script finds its own checkpoint root.
"""

from __future__ import annotations

import argparse
import os
import sys
import threading

from distributeddeeplearningspark_tpu import telemetry as telemetry_lib
from distributeddeeplearningspark_tpu.scheduler import core as core_lib
from distributeddeeplearningspark_tpu.scheduler import ledger as ledger_lib


def _expand(value: str, job: "ledger_lib.Job", root: str) -> str:
    return (value
            .replace("{workdir}", job.workdir)
            .replace("{ckpt}", os.path.join(job.workdir,
                                            core_lib.CKPT_DIRNAME))
            .replace("{root}", root))


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, ""))
    except ValueError:
        return default


def run_job(root: str, job_id: str) -> int:
    from distributeddeeplearningspark_tpu.supervisor import Supervisor

    state = ledger_lib.load_state(root)
    job = state.jobs.get(job_id)
    if job is None:
        print(f"runner: no such job {job_id} in {root}", file=sys.stderr)
        return 2
    if not job.assignment:
        print(f"runner: {job_id} holds no hosts (status {job.status})",
              file=sys.stderr)
        return 2
    os.makedirs(job.workdir, exist_ok=True)
    ckpt_dir = os.path.join(job.workdir, core_lib.CKPT_DIRNAME)
    os.makedirs(ckpt_dir, exist_ok=True)
    cmd = [_expand(c, job, root) for c in job.cmd]
    env = {
        telemetry_lib.TENANT_ENV: job.tenant,
        telemetry_lib.PRIORITY_ENV: str(job.priority),
        # the runtime preemption channel: the trainer polls this path at
        # step boundaries, the scheduler writes it, the supervisor
        # retires it once the drain is acted on
        "DLS_PREEMPT_NOTICE": core_lib.notice_path(job.workdir),
        **{k: _expand(v, job, root) for k, v in job.env.items()},
    }
    ordinals = sorted(job.assignment)
    width = len(ordinals)

    def build(num: int, min_procs: int) -> Supervisor:
        return Supervisor(
            cmd, num_processes=num,
            max_restarts=int(_env_float("DLS_SCHED_MAX_RESTARTS", 4)),
            restart_backoff_s=_env_float("DLS_SCHED_BACKOFF_S", 0.25),
            backoff_jitter=0.0,
            shrink_after=2, min_processes=min_procs,
            env=env, progress_path=ckpt_dir, ckpt_dir=ckpt_dir,
            telemetry_dir=job.workdir)

    if len(job.gangs) == 1:
        # elastic single gang: width is whatever placement granted (a
        # requeued job resuming on fewer hosts restores through
        # reshard-on-restore), floor is the job's declared minimum
        results = [build(width, max(1, min(job.min_hosts, width))).run()]
    else:
        # MPMD-shaped: one supervisor per gang, run concurrently; gangs
        # are rigid (placement guaranteed all-or-nothing)
        sups = [build(g, g) for g in job.gangs]
        results = [None] * len(sups)

        def drive(i: int) -> None:
            results[i] = sups[i].run()

        threads = [threading.Thread(target=drive, args=(i,), daemon=True)
                   for i in range(len(sups))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    ok = all(r is not None and r.ok for r in results)
    if ok:
        ledger_lib.append(root, "complete", job_id, rc=0)
        _emit_verdict(root, job, "complete", rc=0)
        return 0
    classification = None
    for r in results:
        if r is not None and r.attempts and not r.ok:
            classification = r.attempts[-1].classification
            break
    ledger_lib.append(root, "fail", job_id, rc=1,
                      classification=classification)
    _emit_verdict(root, job, "fail", rc=1, classification=classification)
    return 1


def _emit_verdict(root: str, job: "ledger_lib.Job", edge: str,
                  **fields) -> None:
    """The job's terminal ``sched`` event, in both the scheduler's stream
    and the job's own (so each timeline is complete on its own)."""
    for wd, process in ((ledger_lib.sched_dir(root), f"run-{job.job_id}"),
                        (job.workdir, "runner")):
        w = telemetry_lib.EventWriter(wd, process=process, host=None,
                                      tenant=job.tenant,
                                      priority=job.priority)
        try:
            w.emit("sched", edge=edge, job=job.job_id, tenant=job.tenant,
                   priority=job.priority, **fields)
        finally:
            w.close()


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m distributeddeeplearningspark_tpu.scheduler.runner",
        description="Run one placed scheduler job under supervision.")
    ap.add_argument("--root", required=True, help="cluster state dir")
    ap.add_argument("--job", required=True, help="ledger job id")
    args = ap.parse_args(argv)
    return run_job(os.path.abspath(args.root), args.job)


if __name__ == "__main__":
    sys.exit(main())
