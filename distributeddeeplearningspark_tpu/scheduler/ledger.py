"""Durable scheduler state: the JSONL job ledger + host/quota inventory.

The cluster state dir (``<root>/sched``) holds exactly two artifacts:

- ``cluster.json`` — the host inventory and per-tenant quotas, rewritten
  atomically (tmp + rename, the ``health.json`` discipline). Hosts are
  named slots (``h0``..``hN-1`` when initialized from a count); one slot
  is one gang member, the 1-process-per-host model everywhere else in
  the codebase.
- ``ledger.jsonl`` — the append-only job lifecycle ledger, one JSON
  object per line (``ts`` + ``edge`` + ``job`` always present). Current
  cluster state is a PURE FOLD over the ledger (:func:`load_state`): a
  scheduler that crashes mid-tick loses nothing, and a torn final line
  (SIGKILL mid-append) is skipped by the reader like any telemetry
  stream's.

Ledger edges::

    submit   {job, spec: {name, tenant, priority, gangs, min_hosts,
              cmd, env, kind}}           -> PENDING
    place    {job, assignment: [[ordinal, host], ...]}  -> PLACED
    launch   {job, pid, workdir}                        -> RUNNING
    preempt  {job, mode: shrink|evict, ordinal?, victim_of} (shrink: the
              notice is delivered; the job keeps RUNNING with
              ``draining`` set until the drain is observed)
    shrink   {job, ordinal, host}        -> host freed, drain retired
    requeue  {job, reason}               -> PENDING again (hosts freed)
    complete {job, rc}                   -> COMPLETED
    fail     {job, rc, classification?}  -> FAILED
    cancel   {job}                       -> CANCELLED

Per-tenant accounting (``used`` hosts vs quota) is derived from the same
fold — the tie-out target the cluster view asserts against.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time

SCHEMA = 1

#: subdir of the cluster root holding the scheduler's own state
SCHED_DIRNAME = "sched"
LEDGER_NAME = "ledger.jsonl"
CONFIG_NAME = "cluster.json"
#: subdir of the cluster root the scheduler creates job workdirs under
JOBS_DIRNAME = "jobs"

EDGES = ("submit", "place", "launch", "preempt", "shrink", "requeue",
         "complete", "fail", "cancel")

#: statuses that hold hosts
ACTIVE_STATUSES = ("PLACED", "RUNNING")
TERMINAL_STATUSES = ("COMPLETED", "FAILED", "CANCELLED")


def sched_dir(root: str | os.PathLike) -> str:
    return os.path.join(os.fspath(root), SCHED_DIRNAME)


def ledger_path(root: str | os.PathLike) -> str:
    return os.path.join(sched_dir(root), LEDGER_NAME)


def config_path(root: str | os.PathLike) -> str:
    return os.path.join(sched_dir(root), CONFIG_NAME)


def job_workdir(root: str | os.PathLike, job_id: str) -> str:
    """Where a job's run lives: telemetry, checkpoints, health.json — the
    workdir ``dlstatus --cluster <root>`` discovers."""
    return os.path.join(os.fspath(root), JOBS_DIRNAME, job_id)


def init_cluster(root: str | os.PathLike, *,
                 hosts: int | list[str],
                 quotas: dict[str, int] | None = None) -> dict:
    """Create (or rewrite) the cluster inventory. ``hosts`` is a count
    (named ``h0..hN-1``) or an explicit slot-name list; ``quotas`` maps
    tenant -> max concurrently-held hosts (absent tenant = unlimited)."""
    if isinstance(hosts, int):
        if hosts < 1:
            raise ValueError(f"a cluster needs >= 1 host, got {hosts}")
        hosts = [f"h{i}" for i in range(hosts)]
    if len(set(hosts)) != len(hosts):
        raise ValueError(f"duplicate host names in {hosts}")
    cfg = {"schema": SCHEMA, "hosts": list(hosts),
           "quotas": {str(t): int(q) for t, q in (quotas or {}).items()}}
    os.makedirs(sched_dir(root), exist_ok=True)
    path = config_path(root)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(cfg, f, indent=1)
    os.replace(tmp, path)
    return cfg


def load_config(root: str | os.PathLike) -> dict:
    with open(config_path(root)) as f:
        cfg = json.load(f)
    if cfg.get("schema") != SCHEMA:
        raise ValueError(
            f"cluster.json schema {cfg.get('schema')!r} != {SCHEMA} "
            f"(re-run init_cluster on {os.fspath(root)})")
    return cfg


def append(root: str | os.PathLike, edge: str, job: str,
           *, ts: float | None = None, **fields) -> dict:
    """Append one ledger record (atomic at line granularity: one write of
    one newline-terminated line on an O_APPEND fd — readers see whole
    records or nothing)."""
    if edge not in EDGES:
        raise ValueError(f"bad ledger edge {edge!r}: expected one of {EDGES}")
    rec = {"ts": float(ts) if ts is not None else time.time(),
           "edge": edge, "job": job, **fields}
    os.makedirs(sched_dir(root), exist_ok=True)
    line = json.dumps(rec, sort_keys=True) + "\n"
    fd = os.open(ledger_path(root), os.O_WRONLY | os.O_CREAT | os.O_APPEND,
                 0o644)
    try:
        os.write(fd, line.encode("utf-8"))
    finally:
        os.close(fd)
    return rec


def read_ledger(root: str | os.PathLike) -> list[dict]:
    """Every parseable ledger record, in append order. A torn final line
    (writer SIGKILLed mid-append) is skipped, same as the telemetry
    readers — the fold works on a crashed scheduler's ledger as-is."""
    path = ledger_path(root)
    records: list[dict] = []
    try:
        with open(path, encoding="utf-8") as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict) and "ts" in rec and "edge" in rec:
                    records.append(rec)
    except OSError:
        pass
    return records


@dataclasses.dataclass
class Job:
    """One job's folded state."""

    job_id: str
    name: str
    tenant: str
    priority: int
    #: host count per gang — every gang places whole-or-not-at-all and a
    #: job only places when ALL its gangs do
    gangs: tuple[int, ...]
    #: elastic floor: preemption may shrink the job down to this many
    #: hosts (total); at the floor only eviction can reclaim its hosts
    min_hosts: int
    cmd: tuple[str, ...]
    env: dict[str, str]
    kind: str = "train"
    submitted_ts: float = 0.0
    status: str = "PENDING"
    #: gang ordinal -> host slot, for every host the job currently holds
    assignment: dict[int, str] = dataclasses.field(default_factory=dict)
    workdir: str | None = None
    pid: int | None = None
    started_ts: float | None = None
    finished_ts: float | None = None
    rc: int | None = None
    #: ordinal a delivered shrink notice is draining (None = not draining)
    draining: int | None = None
    #: ledger ts of the delivered notice — the reconcile loop only trusts
    #: geometry changes AT OR AFTER it (a requeued job's earlier life may
    #: have drained the same ordinal; its old events must not free hosts)
    draining_since: float | None = None
    requeues: int = 0
    reason: str | None = None

    @property
    def total_hosts(self) -> int:
        return sum(self.gangs)

    @property
    def held_hosts(self) -> list[str]:
        return [self.assignment[o] for o in sorted(self.assignment)]


@dataclasses.dataclass
class ClusterState:
    """The fold of ``cluster.json`` + the ledger: what the planner packs
    against and what the cluster view renders."""

    root: str
    hosts: list[str]
    quotas: dict[str, int]
    jobs: dict[str, Job] = dataclasses.field(default_factory=dict)

    def free_hosts(self) -> list[str]:
        held = {h for j in self.jobs.values() for h in j.assignment.values()}
        return [h for h in self.hosts if h not in held]

    def used_by_tenant(self) -> dict[str, int]:
        """Hosts currently held, per tenant — the ledger-side accounting
        the cluster_report rollup must tie out against."""
        used: dict[str, int] = {}
        for j in self.jobs.values():
            if j.assignment:
                used[j.tenant] = used.get(j.tenant, 0) + len(j.assignment)
        return used

    def quota_of(self, tenant: str) -> int | None:
        return self.quotas.get(tenant)

    def pending(self) -> list[Job]:
        """The queue, scheduling order: priority desc, then FIFO."""
        return sorted(
            (j for j in self.jobs.values() if j.status == "PENDING"),
            key=lambda j: (-j.priority, j.submitted_ts, j.job_id))

    def running(self) -> list[Job]:
        return [j for j in self.jobs.values() if j.status == "RUNNING"]

    def apply(self, rec: dict) -> None:
        """Fold ONE ledger record into the state (load_state = apply over
        the whole ledger; the live scheduler applies each record it
        appends so its in-memory view never diverges from disk)."""
        edge, jid = rec.get("edge"), rec.get("job")
        if edge == "submit":
            spec = rec.get("spec") or {}
            self.jobs[jid] = Job(
                job_id=jid,
                name=str(spec.get("name") or jid),
                tenant=str(spec.get("tenant") or "default"),
                priority=int(spec.get("priority") or 0),
                gangs=tuple(int(g) for g in (spec.get("gangs") or (1,))),
                min_hosts=int(spec.get("min_hosts")
                              or sum(spec.get("gangs") or (1,))),
                cmd=tuple(spec.get("cmd") or ()),
                env={str(k): str(v)
                     for k, v in (spec.get("env") or {}).items()},
                kind=str(spec.get("kind") or "train"),
                submitted_ts=float(rec.get("ts", 0.0)),
                workdir=spec.get("workdir") or job_workdir(self.root, jid),
            )
            return
        job = self.jobs.get(jid)
        if job is None:
            return  # an edge for a job whose submit line was torn away
        if edge == "place":
            job.assignment = {int(o): str(h)
                              for o, h in (rec.get("assignment") or [])}
            job.status = "PLACED"
            job.reason = None
        elif edge == "launch":
            job.status = "RUNNING"
            job.pid = rec.get("pid")
            job.started_ts = float(rec.get("ts", 0.0))
            if rec.get("workdir"):
                job.workdir = rec["workdir"]
        elif edge == "preempt":
            if rec.get("mode") == "shrink":
                job.draining = int(rec["ordinal"])
                job.draining_since = float(rec.get("ts", 0.0))
            # evict is always followed by its own requeue edge
        elif edge == "shrink":
            job.assignment.pop(int(rec["ordinal"]), None)
            job.draining = None
            job.draining_since = None
        elif edge == "requeue":
            if job.status in TERMINAL_STATUSES:
                # lost race: the runner's own complete/fail landed between
                # the scheduler's state fold and its liveness check — the
                # verdict wins, the spurious requeue is a no-op
                return
            job.status = "PENDING"
            job.assignment = {}
            job.pid = None
            job.draining = None
            job.draining_since = None
            job.requeues += 1
            job.reason = rec.get("reason")
        elif edge in ("complete", "fail", "cancel"):
            job.status = {"complete": "COMPLETED", "fail": "FAILED",
                          "cancel": "CANCELLED"}[edge]
            job.assignment = {}
            job.pid = None
            job.draining = None
            job.draining_since = None
            job.finished_ts = float(rec.get("ts", 0.0))
            job.rc = rec.get("rc")

    def _pending_reason(self, j: "Job", used: dict[str, int]) -> str | None:
        """Annotate a PENDING row with the quota gate when it applies —
        the same pure check ``plan`` runs, so the queue view explains why
        a job is waiting without re-running the planner."""
        if j.status == "PENDING":
            quota = self.quotas.get(j.tenant)
            if quota is not None and used.get(j.tenant, 0) + j.min_hosts > quota:
                return "quota"
        return j.reason

    def to_report(self) -> dict:
        """The JSON-safe block ``cluster_report`` embeds as ``sched`` —
        queue + accounting, pinned shape for ``dlstatus --cluster
        --json`` consumers."""
        used = self.used_by_tenant()
        tenants = sorted(set(self.quotas) | set(used)
                         | {j.tenant for j in self.jobs.values()})
        return {
            "root": self.root,
            "hosts": {"total": len(self.hosts),
                      "free": len(self.free_hosts())},
            "tenants": {t: {"used": used.get(t, 0),
                            "quota": self.quotas.get(t)} for t in tenants},
            "jobs": [{
                "job": j.job_id, "name": j.name, "tenant": j.tenant,
                "priority": j.priority, "kind": j.kind,
                "status": j.status, "gangs": list(j.gangs),
                "hosts": j.held_hosts, "min_hosts": j.min_hosts,
                "draining": j.draining, "requeues": j.requeues,
                "reason": self._pending_reason(j, used),
                "workdir": j.workdir, "rc": j.rc,
            } for j in sorted(self.jobs.values(),
                              key=lambda j: (j.submitted_ts, j.job_id))],
        }


def has_ledger(root: str | os.PathLike) -> bool:
    """Is ``root`` a cluster state dir? True from ``init_cluster`` on —
    an initialized-but-empty cluster still renders its inventory."""
    return (os.path.exists(config_path(root))
            or os.path.exists(ledger_path(root)))


def load_state(root: str | os.PathLike) -> ClusterState:
    """cluster.json + the full ledger fold. Raises if the cluster was
    never initialized (a scheduler must not invent an inventory)."""
    cfg = load_config(root)
    state = ClusterState(root=os.path.abspath(os.fspath(root)),
                         hosts=list(cfg["hosts"]),
                         quotas={str(t): int(q)
                                 for t, q in (cfg.get("quotas") or {}).items()})
    for rec in read_ledger(root):
        state.apply(rec)
    return state


def next_job_id(root: str | os.PathLike) -> str:
    """Deterministic from the ledger: one id per submit edge ever
    appended (terminal jobs keep their ids — the ledger is history)."""
    n = sum(r.get("edge") == "submit" for r in read_ledger(root))
    return f"j{n:03d}"
