"""DLRM & Wide-and-Deep recommenders — BASELINE.json config 4.

The reference trains a Wide&Deep / DLRM CTR model on Criteo with Spark
DataFrame features and distributed embedding tables (SURVEY.md §2
'Models: Wide&Deep / DLRM'; embedding-table sharding is the one non-DP
parallelism the reference certainly has).

TPU-first decisions:

- **One fused table**: the 26 per-feature tables are concatenated row-wise
  into a single ``[sum(vocab_sizes), embed_dim]`` array and each feature's
  local index is shifted by a static offset. One big gather per step instead
  of 26 small ones — fewer HLO ops, one collective, and a single target for
  sharding/prefetch. (The reference keeps separate ``nn.Embedding`` modules
  per feature, the torch idiom.)
- **Row-sharded over the ``expert`` mesh axis**: vocab rows are distributed
  (EP-adjacent, matching the reference's table distribution); GSPMD lowers
  the sharded gather to an index all-gather + local take + result exchange —
  the all-to-all lookup pattern of SURVEY.md §2, compiler-scheduled.
- Embeddings gather in f32 (tables stay f32: tiny compute, precision-
  sensitive), MLPs run bf16 on the MXU.

Batch dict: ``dense`` [B, D_dense] f32, ``sparse`` [B, N_feat] i32 (per-
feature local ids), ``label`` [B] {0,1}. Returns CTR logit [B] f32.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn
from jax.sharding import PartitionSpec as P

from distributeddeeplearningspark_tpu.parallel.mesh import AXIS_EXPERT
from distributeddeeplearningspark_tpu.parallel.sharding import ShardingRules

#: Criteo Kaggle/Terabyte schema: 13 dense + 26 categorical.
CRITEO_DENSE = 13
CRITEO_SPARSE = 26


def fused_flat_ids(vocab_sizes: Sequence[int], sparse_ids: jax.Array) -> jax.Array:
    """Per-feature local ids [B, N] → fused-table row ids (static offsets)."""
    offsets = np.concatenate([[0], np.cumsum(vocab_sizes)[:-1]]).astype(np.int32)
    return sparse_ids + jnp.asarray(offsets)[None, :]


class FusedEmbedding(nn.Module):
    """N categorical features → one row-sharded table + static offsets.

    ``vocab_sizes[i]`` rows are reserved for feature i; lookup index is
    ``local_id + offset[i]``. The table param path matches
    :data:`EMBEDDING_RULE` so the vocab dim shards over the ``expert`` axis.

    ``override``: pre-gathered vectors [B, N, D] from the row-sparse training
    path (train/embed.py) — the param is still created (trees/checkpoints
    unchanged) but the lookup is skipped, so no dense table gradient exists.
    """

    vocab_sizes: Sequence[int]
    embed_dim: int

    @nn.compact
    def __call__(self, sparse_ids: jax.Array,
                 override: jax.Array | None = None) -> jax.Array:
        total = int(sum(self.vocab_sizes))
        table = self.param(
            "embedding_table",
            nn.initializers.normal(stddev=1.0 / np.sqrt(self.embed_dim)),
            (total, self.embed_dim),
            jnp.float32,
        )
        if override is not None:
            return override
        return jnp.take(table, fused_flat_ids(self.vocab_sizes, sparse_ids), axis=0)


class MLP(nn.Module):
    features: Sequence[int]
    dtype: Any = jnp.bfloat16
    final_activation: bool = True

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        for i, f in enumerate(self.features):
            x = nn.Dense(f, dtype=self.dtype, name=f"dense_{i}")(x)
            if i < len(self.features) - 1 or self.final_activation:
                x = nn.relu(x)
        return x


def dot_interaction(bottom: jax.Array, emb: jax.Array) -> jax.Array:
    """DLRM pairwise-dot feature interaction.

    ``bottom`` [B, D], ``emb`` [B, N, D] → lower-triangle of the Gram matrix
    of the N+1 feature vectors, concatenated with ``bottom``.
    One [B, N+1, D] × [B, D, N+1] batched matmul — MXU work, not gathers.
    """
    z = jnp.concatenate([bottom[:, None, :], emb], axis=1)  # [B, N+1, D]
    gram = jnp.einsum("bnd,bmd->bnm", z, z)  # [B, N+1, N+1]
    n = z.shape[1]
    li, lj = jnp.tril_indices(n, k=-1)
    return jnp.concatenate([bottom, gram[:, li, lj]], axis=1)


class DLRM(nn.Module):
    """Deep Learning Recommendation Model (Naumov et al.) for Criteo CTR."""

    vocab_sizes: Sequence[int]
    embed_dim: int = 64
    bottom_mlp: Sequence[int] = (512, 256, 64)
    top_mlp: Sequence[int] = (512, 256, 1)
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, batch: dict[str, jax.Array], *, train: bool = False,
                 overrides: dict[str, jax.Array] | None = None) -> jax.Array:
        if self.bottom_mlp[-1] != self.embed_dim:
            raise ValueError(
                f"bottom_mlp output {self.bottom_mlp[-1]} must equal embed_dim "
                f"{self.embed_dim} for dot interaction"
            )
        overrides = overrides or {}
        # log-transform dense counters in f32 (Criteo counts reach 1e7 —
        # bf16 before the log would quantize them), then cast for the MXU
        dense = jnp.log1p(jnp.maximum(batch["dense"].astype(jnp.float32), 0.0))
        bottom = MLP(self.bottom_mlp, self.dtype, name="bottom_mlp")(dense.astype(self.dtype))
        emb = FusedEmbedding(self.vocab_sizes, self.embed_dim, name="embedding")(
            batch["sparse"], override=overrides.get("embedding")
        )
        feats = dot_interaction(bottom.astype(jnp.float32), emb)
        logit = MLP(self.top_mlp, self.dtype, final_activation=False, name="top_mlp")(
            feats.astype(self.dtype)
        )
        return logit[:, 0].astype(jnp.float32)


class WideAndDeep(nn.Module):
    """Wide (linear over categorical ids) + Deep (embeddings → MLP) CTR model."""

    vocab_sizes: Sequence[int]
    embed_dim: int = 32
    deep_mlp: Sequence[int] = (256, 128, 1)
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, batch: dict[str, jax.Array], *, train: bool = False,
                 overrides: dict[str, jax.Array] | None = None) -> jax.Array:
        overrides = overrides or {}
        dense = jnp.log1p(jnp.maximum(batch["dense"].astype(jnp.float32), 0.0))
        # wide: per-category scalar weights == embed_dim-1 fused table
        wide = FusedEmbedding(self.vocab_sizes, 1, name="wide_table")(
            batch["sparse"], override=overrides.get("wide_table"))
        wide_logit = wide[..., 0].sum(-1) + nn.Dense(1, dtype=jnp.float32, name="wide_dense")(
            dense
        )[:, 0]
        # deep: embeddings + dense → MLP
        emb = FusedEmbedding(self.vocab_sizes, self.embed_dim, name="embedding")(
            batch["sparse"], override=overrides.get("embedding")
        )
        deep_in = jnp.concatenate(
            [emb.reshape(emb.shape[0], -1), dense], axis=1
        ).astype(self.dtype)
        deep_logit = MLP(self.deep_mlp, self.dtype, final_activation=False,
                         name="deep_mlp")(deep_in)[:, 0]
        return (wide_logit + deep_logit.astype(jnp.float32))


#: Shard fused-table vocab rows over the `expert` axis; FSDP may still shard
#: other large params when enabled.
EMBEDDING_RULE = ("embedding_table", P(AXIS_EXPERT, None))

#: Row-accumulator of the sparse optimizer (train/embed.py): [vocab_rows]
#: rank-1, sharded like the table's rows. Lives at
#: ``embed_state/<spec_name>/row_accum`` in the TrainState, so the leaf-name
#: match cannot collide with the rank-2 table rule.
ROW_ACCUM_RULE = ("row_accum$", P(AXIS_EXPERT))


def dlrm_rules(*, fsdp: bool = False) -> ShardingRules:
    """Canned sharding for config 4: row-sharded tables (+ optional FSDP)."""
    return ShardingRules(rules=(ROW_ACCUM_RULE, EMBEDDING_RULE), fsdp=fsdp)


def sparse_embed_specs(model, *, lr: float = 1e-2) -> tuple:
    """Row-sparse training specs (train/embed.py) for DLRM / WideAndDeep.

    The returned specs carry each fused table's param path, its batch→row-ids
    function, and the row-wise AdaGrad hyperparameters; hand them to
    ``Trainer(sparse_embed=...)`` (or ``make_sparse_embed_train_step``).
    """
    from distributeddeeplearningspark_tpu.train.embed import SparseEmbedSpec

    vocab = tuple(model.vocab_sizes)

    def ids_fn(batch):
        return fused_flat_ids(vocab, batch["sparse"])

    specs = [SparseEmbedSpec(
        name="embedding", param_path="embedding/embedding_table",
        ids_fn=ids_fn, lr=lr)]
    if isinstance(model, WideAndDeep):
        specs.append(SparseEmbedSpec(
            name="wide_table", param_path="wide_table/embedding_table",
            ids_fn=ids_fn, lr=lr))
    return tuple(specs)
