"""Mixture-of-Experts FFN with expert parallelism — the EP mesh axis's
model-parallel workload.

SURVEY.md §2 lists EP as "not built unless reference shows it"; the
reference stayed unreadable, so this is a beyond-contract addition giving
the reserved ``expert`` mesh axis a real MoE consumer (the DLRM embedding
tables were its only user). TPU-first choices:

- **Dense one-hot dispatch** (GShard, arXiv:2006.16668): routing becomes
  einsums against a [G, S, E, C] dispatch tensor — static shapes, MXU
  matmuls, no gather/scatter. Under GSPMD the stacked expert parameters
  shard over ``expert`` (dim 0 of every [E, ...] kernel) and the dispatch
  einsum's contraction lowers to the all-to-all the reference would have
  hand-written.
- **Per-sequence routing groups** (G = batch) by default: capacity is
  bounded per group, so the dispatch tensor is O(S · E · C) per sequence,
  not O(T²). With C = capacity_factor·g·k/E the dispatch/combine einsums
  still cost ~capacity_factor·k·g·H FLOPs *per token* — linear in the
  group size g, which defaults to the whole sequence. ``group_size``
  shrinks g below S (the GShard/GLaM grouping knob): r4 CPU table showed
  even E=1 top-1 paying 1.33× dense step time at g=S=256, which is
  exactly this term; smaller groups trade a little routing freedom
  (capacity is enforced per group, so load imbalance *within* a group
  drops tokens a global router would have kept) for dispatch cost.
  The "tighter constraint" reading holds when ``cf·g·k/E ≥ 1`` — below
  that, the ≥1 capacity floor (needed so tiny shapes route at all) gives
  every group a full slot per expert and tiny groups can aggregate MORE
  capacity than one per-sequence group; per-group ``int()`` truncation
  also shifts aggregate capacity slightly vs g=S (ADVICE r4). Real
  configs sit far above the boundary (g=256, E=8, k=2, cf=1.25 →
  cf·g·k/E = 80), so the floor is a test-shape affordance, not a
  production regime.
- **Top-k routing with capacity dropping** (Switch/GShard): tokens beyond
  an expert's capacity fall through (the residual connection carries
  them); an auxiliary load-balance loss (Switch Transformer eq. 4 —
  E · Σ_e f_e · p̄_e) keeps the router from collapsing onto one expert.
- Router math in f32 regardless of activation dtype (standard for
  stability); expert FFNs are SwiGLU, matching the dense LlamaMLP.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn


class MoEMLP(nn.Module):
    """Drop-in for a SwiGLU FFN:
    ``[B, S, H] → ([B, S, H], (aux_loss, dropped_frac))``."""

    hidden_size: int
    intermediate_size: int
    num_experts: int
    top_k: int = 2
    capacity_factor: float = 1.25
    group_size: int = 0  # 0 = one group per sequence (g = S)
    dtype: Any = jnp.bfloat16
    # STORAGE dtype of the expert kernels. f32 default (experts normally
    # TRAIN and want f32 masters); bf16 halves resident expert bytes when
    # the bank is frozen or bf16-trained — at the 0.9b bench shape E=8
    # f32 kernels alone are 17.7 GiB (> one chip), bf16 8.9 (fits).
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> tuple[jax.Array, jax.Array]:
        h, i, e = self.hidden_size, self.intermediate_size, self.num_experts
        if not 1 <= self.top_k <= e:
            raise ValueError(f"top_k {self.top_k} must be in [1, {e}]")
        bb, ss, _ = x.shape
        if self.group_size:
            # Regroup [B, S] tokens into [B·S/g, g]: dim 0 stays B-major so
            # a data/fsdp-sharded batch dim regroups without resharding (as
            # long as g divides the per-shard token count — a group that
            # spans shard boundaries forces an all-gather).
            if (bb * ss) % self.group_size:
                raise ValueError(
                    f"group_size {self.group_size} must divide B*S "
                    f"({bb}*{ss}); pick a divisor of the per-step token "
                    "count or 0 for per-sequence groups")
            x = x.reshape(bb * ss // self.group_size, self.group_size, h)
        b, s, _ = x.shape
        # per-group (= per-sequence) expert capacity, ≥1 so tiny test
        # shapes still route. The floor means the module-docstring
        # "small groups only drop more" trade only holds for
        # cf·g·k/E ≥ 1 (see header); an exact ceil-split of the
        # sequence-level cap would restore universality but change
        # routing vs the measured r4 group-size A/B series, so the
        # claim is qualified instead.
        cap = max(1, int(self.capacity_factor * s * self.top_k / e))

        router = self.param("router", nn.initializers.lecun_normal(),
                            (h, e), jnp.float32)  # router math stays f32
        w_gate = self.param("w_gate", nn.initializers.lecun_normal(),
                            (e, h, i), self.param_dtype)
        w_up = self.param("w_up", nn.initializers.lecun_normal(),
                          (e, h, i), self.param_dtype)
        w_down = self.param("w_down", nn.initializers.lecun_normal(),
                            (e, i, h), self.param_dtype)

        logits = jnp.einsum("bsh,he->bse", x.astype(jnp.float32), router)
        probs = jax.nn.softmax(logits, axis=-1)               # [B, S, E] f32

        # Iterative top-k assignment with per-expert cumulative positions
        # (the GShard scheme): slot k masks out previously chosen experts,
        # takes the argmax, and claims the next capacity positions.
        remaining = probs
        claimed = jnp.zeros((b, e), jnp.int32)                # tokens so far
        dispatch = jnp.zeros((b, s, e, cap), self.dtype)
        combine = jnp.zeros((b, s, e, cap), jnp.float32)
        gate_sum = jnp.zeros((b, s), jnp.float32)
        dropped = jnp.float32(0.0)  # routed-but-over-capacity assignments
        first_mask = None
        for _ in range(self.top_k):
            idx = jnp.argmax(remaining, axis=-1)              # [B, S]
            onehot = jax.nn.one_hot(idx, e, dtype=jnp.int32)  # [B, S, E]
            if first_mask is None:
                first_mask = onehot
            # position of each token within its chosen expert's capacity
            pos = (jnp.cumsum(onehot, axis=1) - 1) + claimed[:, None, :]
            keep = (onehot > 0) & (pos < cap)                 # [B, S, E]
            pos_oh = jax.nn.one_hot(pos, cap, dtype=jnp.float32)  # [B,S,E,C]
            slot = jnp.where(keep[..., None], pos_oh, 0.0)
            dropped = dropped + jnp.sum(
                ((onehot > 0) & ~keep).astype(jnp.float32))
            gate = jnp.sum(probs * onehot, axis=-1)           # [B, S]
            kept_gate = gate * keep.any(axis=-1)
            dispatch = dispatch + slot.astype(self.dtype)
            combine = combine + slot * kept_gate[:, :, None, None]
            gate_sum = gate_sum + kept_gate
            # NOTE (ADVICE r3): `claimed` counts every routed token,
            # INCLUDING ones just dropped for exceeding capacity — so later
            # top-k slots compute positions past those holes and effective
            # capacity is slightly understated at tight capacity_factor.
            # This is deliberate GShard parity (their cumsum also runs over
            # the pre-drop assignment); reclaiming dropped slots would
            # change routing vs the paper. The dropped-token fraction is
            # measured honestly instead (`moe_dropped_frac` in the metrics).
            claimed = claimed + jnp.sum(onehot, axis=1)
            remaining = remaining * (1 - onehot)
        # normalize kept gates so the output is a convex combination
        combine = combine / jnp.maximum(gate_sum, 1e-9)[:, :, None, None]

        xe = jnp.einsum("bsec,bsh->bech", dispatch, x.astype(self.dtype))
        g1 = jnp.einsum("bech,ehi->beci", xe, w_gate.astype(self.dtype))
        g2 = jnp.einsum("bech,ehi->beci", xe, w_up.astype(self.dtype))
        ye = jnp.einsum("beci,eih->bech", nn.silu(g1) * g2,
                        w_down.astype(self.dtype))
        y = jnp.einsum("bsec,bech->bsh", combine.astype(self.dtype), ye)

        # Switch load-balance loss: E · Σ_e (fraction routed to e, top-1) ·
        # (mean router prob of e) — minimized at uniform routing (= 1.0)
        frac = jnp.mean(first_mask.astype(jnp.float32), axis=(0, 1))  # [E]
        mean_p = jnp.mean(probs, axis=(0, 1))                         # [E]
        aux = e * jnp.sum(frac * mean_p)
        # dropped-token fraction of all B·S·top_k routing assignments —
        # the capacity-tuning honesty metric (VERDICT r3 weak-#4): reported
        # next to moe_aux so a tight capacity_factor can't silently starve
        # tokens of their experts
        dropped_frac = dropped / jnp.float32(b * s * self.top_k)
        if self.group_size:
            y = y.reshape(bb, ss, h)
        return y.astype(x.dtype), (aux, dropped_frac)


# Sharding rules for the MoE params live in models/llama.py:llama_rules
# (one source for the whole tree): stacked expert kernels shard dim-0 over
# ``expert`` (+ the FFN dims over ``tensor``); the router replicates.
