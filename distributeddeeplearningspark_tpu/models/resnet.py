"""ResNet family for ImageNet — BASELINE.json config 2.

The reference trains ResNet-50/ImageNet-1k through a Spark RDD image pipeline
on CUDA (SURVEY.md §2 'Models: ResNet-50'); its headline metric is
images/sec/chip and the north star is ≥50% MFU on a v4-32 pod.

TPU-first design decisions (vs. a torch translation):

- **NHWC layout** end to end — channels-last is what XLA:TPU tiles onto the
  MXU without relayout transposes (torch is NCHW).
- **bfloat16 compute, float32 state**: conv/matmul inputs and activations in
  bf16 feed the MXU at full rate; params, BN statistics and the final logits
  stay f32 for stable training. This is the standard TPU mixed-precision
  recipe — no loss-scaling machinery needed (unlike fp16 on GPU).
- **BatchNorm compute follows the activation dtype** (``norm_dtype=None`` →
  ``self.dtype``): flax upcasts the mean/var *statistics* to f32 internally
  and keeps scale/bias params f32 regardless, so only the normalize/affine
  elementwise math runs in bf16 — measured on the dev v5e this alone is
  134→101 ms/step on ResNet-50 b=256 (23.2%→30.7% MFU), because an f32 BN
  sandwiched between bf16 convs pays convert+double-bandwidth on every
  activation tensor (A/B on a scratch harness; the committed ``bench.py``
  run of the same change landed at 103.0 ms / 30.16% — see BASELINE.md).
  Set ``norm_dtype=jnp.float32`` to reproduce torch-default numerics; the
  weight-import parity tests get this implicitly by running the whole model
  at ``dtype=float32``, which the norm dtype follows.
- **v1.5 stride placement** (stride on the 3×3, not the 1×1) — the variant
  every published ResNet-50 benchmark uses.
- **Distributed BN for free**: under GSPMD the batch axis is sharded over the
  (data, fsdp) mesh axes, so BatchNorm's batch-mean lowers to a per-chip
  partial sum + an XLA all-reduce — the cross-replica sync-BN the reference
  would need explicit hooks for is just how the compiler partitions the mean.
"""

from __future__ import annotations

import functools
from typing import Any, Sequence

import jax
import jax.numpy as jnp
from flax import linen as nn


def _norm_dtype(norm_dtype, dtype):
    """BN compute dtype: explicit override, else follow the activation dtype."""
    return norm_dtype if norm_dtype is not None else dtype


class BottleneckBlock(nn.Module):
    """1×1 → 3×3 → 1×1 bottleneck with projection shortcut when needed.

    ``fused_conv_bn=True`` routes the two stride-1 1×1 conv→BN pairs through
    the Pallas matmul-with-stats-epilogue kernel (``ops/conv_bn.py`` —
    VERDICT r2 next-#2's byte-diet lever: the separate whole-activation
    BN-statistics read disappears for the block's fattest tensors).
    """

    filters: int  # bottleneck width; output channels = 4 * filters
    strides: int = 1
    dtype: Any = jnp.bfloat16
    norm_dtype: Any = None  # None → follow self.dtype (see module docstring)
    fused_conv_bn: bool = False

    @nn.compact
    def __call__(self, x: jax.Array, *, train: bool) -> jax.Array:
        conv = functools.partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = functools.partial(
            nn.BatchNorm, use_running_average=not train, momentum=0.9,
            epsilon=1e-5, dtype=_norm_dtype(self.norm_dtype, self.dtype),
        )

        def conv1x1_bn(features, name, zero_gamma=False):
            from distributeddeeplearningspark_tpu.ops.conv_bn import Conv1x1BN

            return Conv1x1BN(
                features, dtype=self.dtype, norm_dtype=self.norm_dtype,
                scale_init=(nn.initializers.zeros if zero_gamma
                            else nn.initializers.ones),
                name=name)

        residual = x
        if self.fused_conv_bn:
            y = conv1x1_bn(self.filters, "conv_bn_1")(x, train=train)
            y = nn.relu(y)
        else:
            y = conv(self.filters, (1, 1))(x)
            y = nn.relu(norm()(y))
        # explicit (1,1) padding = torch semantics; flax SAME pads (0,1) on
        # stride-2, which would break pretrained-weight parity (resnet_io)
        y = conv(self.filters, (3, 3), strides=(self.strides, self.strides),
                 padding=[(1, 1), (1, 1)])(y)
        y = nn.relu(norm()(y))
        # zero-init gamma on the last BN: each block starts as identity,
        # the standard large-batch trick (Goyal et al.) — free accuracy.
        if self.fused_conv_bn:
            y = conv1x1_bn(4 * self.filters, "conv_bn_3",
                           zero_gamma=True)(y, train=train)
        else:
            y = conv(4 * self.filters, (1, 1))(y)
            y = norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = conv(4 * self.filters, (1, 1), strides=(self.strides, self.strides),
                            name="shortcut_conv")(residual)
            residual = norm(name="shortcut_bn")(residual)
        return nn.relu(residual + y.astype(residual.dtype))


class BasicBlock(nn.Module):
    """3×3 → 3×3 block (ResNet-18/34)."""

    filters: int
    strides: int = 1
    dtype: Any = jnp.bfloat16
    norm_dtype: Any = None  # None → follow self.dtype (see module docstring)

    @nn.compact
    def __call__(self, x: jax.Array, *, train: bool) -> jax.Array:
        conv = functools.partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = functools.partial(
            nn.BatchNorm, use_running_average=not train, momentum=0.9,
            epsilon=1e-5, dtype=_norm_dtype(self.norm_dtype, self.dtype),
        )
        residual = x
        # explicit (1,1) padding = torch semantics (see BottleneckBlock)
        y = conv(self.filters, (3, 3), strides=(self.strides, self.strides),
                 padding=[(1, 1), (1, 1)])(x)
        y = nn.relu(norm()(y))
        y = conv(self.filters, (3, 3), padding=[(1, 1), (1, 1)])(y)
        y = norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = conv(self.filters, (1, 1), strides=(self.strides, self.strides),
                            name="shortcut_conv")(residual)
            residual = norm(name="shortcut_bn")(residual)
        return nn.relu(residual + y.astype(residual.dtype))


class ResNet(nn.Module):
    """Input: batch dict with ``image`` [B,H,W,3] float; returns logits f32.

    ``stage_sizes`` counts blocks per stage; stage widths are the classic
    64/128/256/512.
    """

    stage_sizes: Sequence[int]
    block_cls: type = BottleneckBlock
    num_classes: int = 1000
    width: int = 64
    dtype: Any = jnp.bfloat16
    norm_dtype: Any = None  # None → follow self.dtype (see module docstring)
    fused_conv_bn: bool = False  # Pallas conv+BN-stats epilogue (bottlenecks)

    @nn.compact
    def __call__(self, batch: dict[str, jax.Array], *, train: bool = False) -> jax.Array:
        ndtype = _norm_dtype(self.norm_dtype, self.dtype)
        x = batch["image"].astype(self.dtype)
        x = nn.Conv(self.width, (7, 7), strides=(2, 2), padding=[(3, 3), (3, 3)],
                    use_bias=False, dtype=self.dtype, name="stem_conv")(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9, epsilon=1e-5,
                         dtype=ndtype, name="stem_bn")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=[(1, 1), (1, 1)])
        is_bottleneck = (isinstance(self.block_cls, type)
                         and issubclass(self.block_cls, BottleneckBlock))
        if self.fused_conv_bn and not is_bottleneck:
            raise ValueError(
                "fused_conv_bn=True requires a BottleneckBlock block_cls "
                f"(got {self.block_cls!r}) — BasicBlock has no 1×1 convs "
                "to fuse")
        kw = {"fused_conv_bn": self.fused_conv_bn} if is_bottleneck else {}
        for stage, n_blocks in enumerate(self.stage_sizes):
            for block in range(n_blocks):
                x = self.block_cls(
                    filters=self.width * 2**stage,
                    strides=2 if stage > 0 and block == 0 else 1,
                    dtype=self.dtype,
                    norm_dtype=self.norm_dtype,
                    **kw,
                )(x, train=train)
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)


def ResNet18(**kw) -> ResNet:
    return ResNet(stage_sizes=(2, 2, 2, 2), block_cls=BasicBlock, **kw)


def ResNet34(**kw) -> ResNet:
    return ResNet(stage_sizes=(3, 4, 6, 3), block_cls=BasicBlock, **kw)


def ResNet50(**kw) -> ResNet:
    return ResNet(stage_sizes=(3, 4, 6, 3), block_cls=BottleneckBlock, **kw)


def ResNet101(**kw) -> ResNet:
    return ResNet(stage_sizes=(3, 4, 23, 3), block_cls=BottleneckBlock, **kw)


def ResNet152(**kw) -> ResNet:
    return ResNet(stage_sizes=(3, 8, 36, 3), block_cls=BottleneckBlock, **kw)
