"""LeNet-5 for MNIST — BASELINE.json config 1, the reference's PR1 workload.

The reference trains this with 2 local Spark executors in pure-CPU data
parallelism (SURVEY.md §3.1); it is the minimum end-to-end slice and the
acceptance test for DP parity (SPMD psum ≡ driver treeAggregate averaging).

Classic topology (LeCun et al. 1998, as commonly modernized): two 5×5 conv +
max-pool stages, then 120/84/10 dense. Inputs NHWC ``[B, 28, 28, 1]`` —
channels-last is the TPU-native layout (the reference's torch modules are
NCHW; translating that layout would cost a transpose on every step).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn


class LeNet5(nn.Module):
    """Input: batch dict with ``image`` [B,28,28,1] float; returns logits [B,10]."""

    num_classes: int = 10
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, batch: dict[str, jax.Array], *, train: bool = False) -> jax.Array:
        x = batch["image"].astype(self.dtype)
        x = nn.Conv(6, (5, 5), padding="SAME", dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(16, (5, 5), padding="VALID", dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(120, dtype=self.dtype)(x))
        x = nn.relu(nn.Dense(84, dtype=self.dtype)(x))
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)
