"""BERT ↔ Hugging Face weight interchange.

The reference world pretrains/loads stock BERT checkpoints; config 3 users
expect to start MLM pretraining from (or export to) the standard
``bert-base-uncased`` layout (SURVEY.md §2 'Models: BERT-base MLM' —
"vendored or HF"). This maps the HF BERT parameter tree (the flax layout of
``FlaxBertForMaskedLM``; the torch ``state_dict`` transposes linear weights)
onto :class:`~.bert.BertForMLM`'s tree and back.

Shape conventions bridged:

- HF stores attention projections as flat ``[H, H]`` Dense kernels; ours are
  ``DenseGeneral`` kernels ``[H, heads, head_dim]`` (and the output
  projection ``[heads, head_dim, H]``) so TP rules can shard the head axis.
- HF keeps a separate ``cls.predictions.decoder`` tied to the word
  embeddings; ours ties structurally (``Embed.attend``), so only the bias
  transfers.

All staging is host-side numpy — call ``Trainer.load_pretrained`` with the
result to place slices per the active sharding.
"""

from __future__ import annotations

from typing import Any, Mapping

import numpy as np

from distributeddeeplearningspark_tpu.models.bert import BertConfig


def _g(tree: Mapping, *path):
    node: Any = tree
    for p in path:
        node = node[p]
    return np.asarray(node)


def import_hf_bert(hf_params: Mapping, cfg: BertConfig) -> dict:
    """HF ``FlaxBertForMaskedLM`` param tree → :class:`BertForMLM` tree.

    ``hf_params`` is the dict under the HF model's ``params`` (with top-level
    keys ``bert`` and ``cls``). Returns a nested numpy tree matching
    ``BertForMLM.init(...)['params']``.
    """
    h, heads = cfg.hidden_size, cfg.num_heads
    hd = h // heads
    emb = hf_params["bert"]["embeddings"]

    def qkv(kernel, bias):
        return {"kernel": np.asarray(kernel).reshape(h, heads, hd),
                "bias": np.asarray(bias).reshape(heads, hd)}

    encoder: dict[str, Any] = {
        "position_embeddings": {"embedding": _g(emb, "position_embeddings", "embedding")},
        "type_embeddings": {"embedding": _g(emb, "token_type_embeddings", "embedding")},
        "embeddings_ln": {"scale": _g(emb, "LayerNorm", "scale"),
                          "bias": _g(emb, "LayerNorm", "bias")},
    }
    for i in range(cfg.num_layers):
        hf_layer = hf_params["bert"]["encoder"]["layer"][str(i)]
        att, out = hf_layer["attention"], hf_layer["output"]
        encoder[f"layer_{i}"] = {
            "attention": {
                "query": qkv(att["self"]["query"]["kernel"], att["self"]["query"]["bias"]),
                "key": qkv(att["self"]["key"]["kernel"], att["self"]["key"]["bias"]),
                "value": qkv(att["self"]["value"]["kernel"], att["self"]["value"]["bias"]),
                "out": {
                    "kernel": _g(att, "output", "dense", "kernel").reshape(heads, hd, h),
                    "bias": _g(att, "output", "dense", "bias"),
                },
            },
            "attention_ln": {"scale": _g(att, "output", "LayerNorm", "scale"),
                             "bias": _g(att, "output", "LayerNorm", "bias")},
            "mlp_in": {"kernel": _g(hf_layer, "intermediate", "dense", "kernel"),
                       "bias": _g(hf_layer, "intermediate", "dense", "bias")},
            "mlp_out": {"kernel": _g(out, "dense", "kernel"),
                        "bias": _g(out, "dense", "bias")},
            "mlp_ln": {"scale": _g(out, "LayerNorm", "scale"),
                       "bias": _g(out, "LayerNorm", "bias")},
        }
    transform = hf_params["cls"]["predictions"]["transform"]
    return {
        "token_embeddings": {"embedding": _g(emb, "word_embeddings", "embedding")},
        "encoder": encoder,
        "mlm_dense": {"kernel": _g(transform, "dense", "kernel"),
                      "bias": _g(transform, "dense", "bias")},
        "mlm_ln": {"scale": _g(transform, "LayerNorm", "scale"),
                   "bias": _g(transform, "LayerNorm", "bias")},
        # cls/predictions/bias is the array itself in the HF flax layout
        "mlm_bias": _g(hf_params["cls"]["predictions"], "bias"),
    }


def export_hf_bert(params: Mapping, cfg: BertConfig) -> dict:
    """:class:`BertForMLM` tree → HF ``FlaxBertForMaskedLM`` layout (numpy).

    Inverse of :func:`import_hf_bert`. Only the decoder BIAS is emitted
    (``cls/predictions/bias``): HF's flax model ties the decoder kernel to
    the word embeddings at apply time, same as ours — loading into an
    UNTIED model requires materializing ``cls.predictions.decoder`` from
    ``bert/embeddings/word_embeddings`` yourself.
    """
    h, heads = cfg.hidden_size, cfg.num_heads
    hd = h // heads
    enc = params["encoder"]

    def flat(k, b):
        return {"kernel": np.asarray(k).reshape(h, h),
                "bias": np.asarray(b).reshape(h)}

    layers: dict[str, Any] = {}
    for i in range(cfg.num_layers):
        ly = enc[f"layer_{i}"]
        att = ly["attention"]
        layers[str(i)] = {
            "attention": {
                "self": {
                    "query": flat(att["query"]["kernel"], att["query"]["bias"]),
                    "key": flat(att["key"]["kernel"], att["key"]["bias"]),
                    "value": flat(att["value"]["kernel"], att["value"]["bias"]),
                },
                "output": {
                    "dense": {"kernel": np.asarray(att["out"]["kernel"]).reshape(h, h),
                              "bias": np.asarray(att["out"]["bias"])},
                    "LayerNorm": {"scale": np.asarray(ly["attention_ln"]["scale"]),
                                  "bias": np.asarray(ly["attention_ln"]["bias"])},
                },
            },
            "intermediate": {"dense": {
                "kernel": np.asarray(ly["mlp_in"]["kernel"]),
                "bias": np.asarray(ly["mlp_in"]["bias"])}},
            "output": {
                "dense": {"kernel": np.asarray(ly["mlp_out"]["kernel"]),
                          "bias": np.asarray(ly["mlp_out"]["bias"])},
                "LayerNorm": {"scale": np.asarray(ly["mlp_ln"]["scale"]),
                              "bias": np.asarray(ly["mlp_ln"]["bias"])},
            },
        }
    word = np.asarray(params["token_embeddings"]["embedding"])
    return {
        "bert": {
            "embeddings": {
                "word_embeddings": {"embedding": word},
                "position_embeddings": {
                    "embedding": np.asarray(enc["position_embeddings"]["embedding"])},
                "token_type_embeddings": {
                    "embedding": np.asarray(enc["type_embeddings"]["embedding"])},
                "LayerNorm": {"scale": np.asarray(enc["embeddings_ln"]["scale"]),
                              "bias": np.asarray(enc["embeddings_ln"]["bias"])},
            },
            "encoder": {"layer": layers},
        },
        "cls": {"predictions": {
            "transform": {
                "dense": {"kernel": np.asarray(params["mlm_dense"]["kernel"]),
                          "bias": np.asarray(params["mlm_dense"]["bias"])},
                "LayerNorm": {"scale": np.asarray(params["mlm_ln"]["scale"]),
                              "bias": np.asarray(params["mlm_ln"]["bias"])},
            },
            "bias": np.asarray(params["mlm_bias"]),
        }},
    }


def import_hf_bert_torch(state_dict: Mapping, cfg: BertConfig) -> dict:
    """Torch ``BertForMaskedLM.state_dict()`` → :class:`BertForMLM` tree.

    Torch linear weights are ``[out, in]`` — transposed to flax's
    ``[in, out]`` before the flax-layout mapping above is applied.
    """
    flax_tree: dict[str, Any] = {}

    def put(path: list[str], value: np.ndarray) -> None:
        node = flax_tree
        for p in path[:-1]:
            node = node.setdefault(p, {})
        node[path[-1]] = value

    for name, tensor in state_dict.items():
        v = np.asarray(tensor)
        parts = name.split(".")
        if parts[-1] == "weight":
            if "embeddings" in parts and "LayerNorm" not in parts:
                parts[-1] = "embedding"
            elif "LayerNorm" in parts:
                parts[-1] = "scale"
            else:
                parts[-1] = "kernel"
                v = v.T
        if parts[:2] == ["cls", "predictions"] and parts[2] == "decoder":
            continue  # tied to word embeddings structurally
        put(parts, v)
    return import_hf_bert(flax_tree, cfg)
