"""Pipeline-parallel forward for Llama — wires models/llama.py into
parallel/pipeline.py (VERDICT r1 next-#5: PP as a capability, not a demo).

The reference has no pipeline parallelism (SURVEY.md §2: PP "unknown — no
evidence"), so this is capability beyond the contract, built the TPU way:
the ``nn.scan``-stacked decoder weights [L, ...] regroup into [P, L/P, ...]
stages (a pure reshape — no model rewrite), the embed/head run replicated
over the ``pipe`` axis (they are a few % of FLOPs; dedicating stages to them
would only deepen the bubble), and the GPipe ring of
:func:`..parallel.pipeline.pipeline` carries the decoder trunk.

No flax refactor: the embedding/norm/head submodules are re-instantiated
standalone with the SAME constructor arguments the full model uses and
applied to the corresponding parameter subtrees, so the math — dtype
promotion included — is the model's own code, and the parameter tree remains
byte-compatible with non-PP checkpoints (PP is a runtime layout choice, not
a model variant).

Limitations (asserted): ``scan_layers=True``, ``num_layers % pipe == 0``,
no ``attention_mask`` (causal-LM packing handles padding via ``loss_mask``,
as the config-5 fine-tune does).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.sharding import Mesh

from distributeddeeplearningspark_tpu.models.llama import (
    DecoderLayer,
    LlamaConfig,
    RMSNorm,
)
from distributeddeeplearningspark_tpu.parallel.mesh import AXIS_PIPE
from distributeddeeplearningspark_tpu.parallel.pipeline import pipeline, stack_stages


def check_pp_config(cfg: LlamaConfig, p: int) -> None:
    """The shared pipeline-compatibility ladder (single-program GPipe and
    the MPMD multi-gang trainer enforce the same contract)."""
    if not cfg.scan_layers:
        raise ValueError("pipeline parallelism requires scan_layers=True "
                         "(stacked [L, ...] params are what stages reshape)")
    if cfg.moe_experts:
        raise NotImplementedError(
            "MoE is not wired through pipeline parallelism: the stage "
            "forward discards each layer's load-balance aux loss, so the "
            "router would silently collapse (no balancing gradient) — use "
            "the data×expert(+fsdp/tensor) layout for MoE models")
    if cfg.fused_head_loss:
        raise ValueError(
            "fused_head_loss is not supported with pipeline parallelism: "
            "the GPipe forward emits real logits — pair PP with "
            "losses.causal_lm (or drop the config flag)")
    if cfg.num_layers % p:
        raise ValueError(f"num_layers {cfg.num_layers} must divide by pipe {p}")


def build_stage_modules(cfg: LlamaConfig, stage_len: int):
    """(stage_mod, embed_mod, norm_mod, head_mod) — the EXACT module stack
    both pipeline implementations run, factored so the MPMD per-gang stage
    program (train/pipeline_trainer.py) computes bit-for-bit the same math
    as this module's single-program GPipe ring."""
    from distributeddeeplearningspark_tpu.models.llama import (
        _barrier_differentiable,
    )

    layer_cls = DecoderLayer
    if cfg.scan_param_barrier and _barrier_differentiable():
        # same whole-stack relayout hazard as the non-PP scan (see
        # LlamaConfig.scan_param_barrier): each stage's [L/P, ...] stacked
        # weights would otherwise grow hoisted fwd+bwd layout copies.
        # Ordering as in llama.py: inside the remat region, or the barrier
        # outputs become per-layer saved residuals — and like llama.py's
        # own scan, the wrap must auto-disable on jax builds whose
        # optimization_barrier has no autodiff rule, or every backward
        # through a pipeline stage dies (llama.py got this guard in the
        # jax-skew fix round; this path had been left behind).
        layer_cls = nn.map_variables(
            layer_cls, "params",
            trans_in_fn=lambda tree: jax.tree.map(
                jax.lax.optimization_barrier, tree))
    if cfg.remat:
        layer_cls = nn.remat(layer_cls, prevent_cse=False)
    stage_mod = nn.scan(
        layer_cls,
        variable_axes={"params": 0},
        split_rngs={"params": True},
        in_axes=nn.broadcast,
        length=stage_len,
    )(cfg)
    embed_mod = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype)
    norm_mod = RMSNorm(cfg.rms_eps, cfg.dtype)
    head_mod = nn.Dense(cfg.vocab_size, use_bias=False, dtype=cfg.dtype)
    return stage_mod, embed_mod, norm_mod, head_mod


def make_pp_apply(cfg: LlamaConfig, mesh: Mesh, num_microbatches: int | None = None):
    """Build an ``apply_fn(variables, batch, train=..., rngs=...)`` running
    the decoder trunk through P pipeline stages.

    Drop-in for ``model.apply`` in :func:`..train.step.make_train_step`; the
    parameter tree is the ordinary :class:`LlamaForCausalLM` one.
    """
    p = int(mesh.shape[AXIS_PIPE])
    if p < 2:
        raise ValueError(f"pipeline apply needs a pipe axis > 1 (mesh {dict(mesh.shape)})")
    check_pp_config(cfg, p)
    m = num_microbatches or p
    stage_len = cfg.num_layers // p
    stage_mod, embed_mod, norm_mod, head_mod = build_stage_modules(cfg, stage_len)

    def stage_fn(stage_params: Any, act):
        out, _ = stage_mod.apply({"params": stage_params}, act, None, None)
        return out

    def apply_fn(variables, batch, *, train: bool = False, rngs=None, mutable=None):
        del train, rngs, mutable  # no dropout/BN in Llama-2
        params = variables["params"]
        if batch.get("attention_mask") is not None:
            raise NotImplementedError(
                "pipeline-parallel Llama supports causal packing only; "
                "handle padding via loss_mask (as config 5 does)")
        if batch.get("segment_ids") is not None:
            raise NotImplementedError(
                "pipeline-parallel Llama does not thread segment_ids to the "
                "stage forwards — packed batches would silently attend "
                "across documents; drop segment_ids (GPT-style packing) or "
                "use a non-PP layout")
        ids = batch["input_ids"]
        if ids.shape[1] > cfg.max_position:
            raise ValueError(
                f"sequence length {ids.shape[1]} exceeds max_position "
                f"{cfg.max_position}")
        x = embed_mod.apply({"params": params["token_embed"]}, ids)
        stage_params = stack_stages(params["layers"], p)
        x = pipeline(stage_fn, stage_params, x, mesh=mesh, num_microbatches=m)
        x = norm_mod.apply({"params": params["final_norm"]}, x)
        logits = head_mod.apply({"params": params["lm_head"]}, x)
        return logits.astype(jnp.float32)

    return apply_fn
