"""ResNet ← torchvision-convention weight import.

Config 2's operating mode in the reference world starts from the canonical
ImageNet-pretrained ResNet-50 (`torchvision.models.resnet50().state_dict()`
naming — the layout virtually every published ResNet checkpoint uses;
SURVEY.md §2 'Models: ResNet-50' — "vendored or torchvision"). torchvision
itself is not installed here, so this maps the *key convention* onto our
flax tree; the numerical contract is proven in tests against the
`transformers` torch ResNet (same v1.5 architecture, renamed keys).

Layout bridged:

- torch convs are OIHW → flax HWIO (transpose ``(2, 3, 1, 0)``).
- torch ``fc.weight`` is [out, in] → flax ``head.kernel`` [in, out].
- BatchNorm splits: ``weight``/``bias`` → params ``scale``/``bias``;
  ``running_mean``/``running_var`` → **batch_stats** ``mean``/``var``
  (returned separately — pass both to ``model.apply``).
- ``layer{s}.{b}`` → the flat auto-named block index
  ``{Bottleneck,Basic}Block_{sum(depths[:s-1]) + b}``; ``conv{i}``/``bn{i}``
  → ``Conv_{i-1}``/``BatchNorm_{i-1}``; ``downsample.0/.1`` →
  ``shortcut_conv``/``shortcut_bn``.

The model's 3×3 convs use explicit (1, 1) padding (torch semantics) so the
import is numerically exact — flax ``SAME`` would pad (0, 1) on stride-2.
"""

from __future__ import annotations

from typing import Mapping

import numpy as np


def import_torchvision_resnet(
    state_dict: Mapping, *, stage_sizes, bottleneck: bool = True
) -> tuple[dict, dict]:
    """torchvision-convention ``state_dict`` → (params, batch_stats) trees.

    ``stage_sizes``: blocks per stage, e.g. ``(3, 4, 6, 3)`` for ResNet-50
    (must match the target model). ``bottleneck``: True for 50/101/152,
    False for 18/34 (two convs per block instead of three).
    """
    sd = {k: np.asarray(v) for k, v in state_dict.items()
          if not k.endswith("num_batches_tracked")}
    block_name = "BottleneckBlock" if bottleneck else "BasicBlock"
    n_convs = 3 if bottleneck else 2
    params: dict = {}
    stats: dict = {}

    def conv(key):
        return {"kernel": sd[key].transpose(2, 3, 1, 0)}

    def bn(prefix):
        return (
            {"scale": sd[f"{prefix}.weight"], "bias": sd[f"{prefix}.bias"]},
            {"mean": sd[f"{prefix}.running_mean"],
             "var": sd[f"{prefix}.running_var"]},
        )

    params["stem_conv"] = conv("conv1.weight")
    params["stem_bn"], stats["stem_bn"] = bn("bn1")

    idx = 0
    for s, depth in enumerate(stage_sizes, start=1):
        for b in range(depth):
            name = f"{block_name}_{idx}"
            idx += 1
            p_blk: dict = {}
            s_blk: dict = {}
            for c in range(n_convs):
                p_blk[f"Conv_{c}"] = conv(f"layer{s}.{b}.conv{c + 1}.weight")
                p_blk[f"BatchNorm_{c}"], s_blk[f"BatchNorm_{c}"] = bn(
                    f"layer{s}.{b}.bn{c + 1}")
            if f"layer{s}.{b}.downsample.0.weight" in sd:
                p_blk["shortcut_conv"] = conv(f"layer{s}.{b}.downsample.0.weight")
                p_blk["shortcut_bn"], s_blk["shortcut_bn"] = bn(
                    f"layer{s}.{b}.downsample.1")
            params[name] = p_blk
            stats[name] = s_blk

    params["head"] = {"kernel": sd["fc.weight"].T, "bias": sd["fc.bias"]}
    return params, stats


def hf_resnet_to_torchvision_keys(state_dict: Mapping) -> dict:
    """``transformers`` torch ResNet ``state_dict`` → torchvision naming.

    The HF graph is the same v1.5 ResNet with renamed modules
    (``resnet.embedder...`` → ``conv1``/``bn1``, ``resnet.encoder.stages.S
    .layers.B.layer.C`` → ``layerS+1.B.convC+1``, ``shortcut`` →
    ``downsample``, ``classifier.1`` → ``fc``); used by the parity tests and
    by anyone holding an HF-format ResNet checkpoint.
    """
    out = {}
    skipped = []
    for k, v in state_dict.items():
        if k.endswith("num_batches_tracked"):
            continue
        parts = k.split(".")
        if k.startswith("resnet.embedder"):
            leaf = parts[-1]
            kind = "conv1" if parts[-2] == "convolution" else "bn1"
            if kind == "conv1":
                out["conv1.weight"] = v
            else:
                out[f"bn1.{leaf}"] = v
        elif k.startswith("resnet.encoder.stages."):
            s, b = int(parts[3]), int(parts[5])
            if parts[6] == "shortcut":
                which = "0" if parts[7] == "convolution" else "1"
                out[f"layer{s + 1}.{b}.downsample.{which}.{parts[-1]}"] = v
            else:  # layer.C.{convolution|normalization}
                c = int(parts[7])
                if parts[8] == "convolution":
                    out[f"layer{s + 1}.{b}.conv{c + 1}.weight"] = v
                else:
                    out[f"layer{s + 1}.{b}.bn{c + 1}.{parts[-1]}"] = v
        elif k.startswith("classifier."):
            out[f"fc.{parts[-1]}"] = v
        else:
            skipped.append(k)
    if not out or len(skipped) > len(out):
        raise ValueError(
            f"state_dict does not look like a transformers "
            f"ResNetForImageClassification checkpoint: matched {len(out)} "
            f"keys, unrecognized {len(skipped)} (e.g. {skipped[:3]}) — a "
            f"bare ResNetModel lacks the 'resnet.' prefix; wrap it or "
            f"rename keys first")
    return out
