"""Llama-2 checkpoint interchange: HF safetensors ↔ flax param tree.

The reference loads Llama-2 7B base weights from a Hugging Face checkpoint
before attaching LoRA adapters (SURVEY.md §2 'Models: Llama-2 7B + LoRA';
BASELINE.json config 5). Equivalent here: read HF ``*.safetensors`` shards
into this package's :class:`~.llama.LlamaForCausalLM` param tree.

Layout translation (HF torch stores Linear weights [out, in]; flax Dense
kernels are [in, out]; attention projections additionally reshape to
[in, heads, head_dim]):

==============================================  =====================================
HF tensor                                       flax path (per layer i)
==============================================  =====================================
model.embed_tokens.weight [V,H]                 token_embed/embedding [V,H]
model.layers.i.self_attn.{q,k,v}_proj.weight    layers_i/attention/w{q,k,v}/base/kernel
model.layers.i.self_attn.o_proj.weight [H,NH*D] layers_i/attention/wo/base/kernel [NH,D,H]
model.layers.i.mlp.{gate,up}_proj.weight [I,H]  layers_i/mlp/{gate,up}/base/kernel [H,I]
model.layers.i.mlp.down_proj.weight [H,I]       layers_i/mlp/down/base/kernel [I,H]
model.layers.i.input_layernorm.weight           layers_i/attention_norm/scale
model.layers.i.post_attention_layernorm.weight  layers_i/mlp_norm/scale
model.norm.weight                               final_norm/scale
lm_head.weight [V,H]                            lm_head/kernel [H,V]
==============================================  =====================================

With ``cfg.scan_layers`` the per-layer trees are stacked on a new leading axis
(``layers/...`` [L, ...]) to match the ``nn.scan`` parameter layout. Loading
streams one HF tensor at a time (numpy memory-map) so a 7B import never holds
two full copies in host RAM; the caller then ``device_put``s with FSDP
shardings so each chip receives only its slice.

RoPE uses the same rotate-half convention as HF's modeling_llama, so imported
weights reproduce HF logits bit-for-tolerance (see tests/test_llama.py parity
test against ``transformers``).
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Callable

import numpy as np

from distributeddeeplearningspark_tpu.models.llama import LlamaConfig

# LoRA adapters are deliberately absent: import provides the *base* model;
# adapters are fresh (B=0) or restored from our own orbax checkpoints.


def _layer_maps(cfg: LlamaConfig) -> list[tuple[str, str, Callable[[np.ndarray], np.ndarray]]]:
    """(hf_suffix, flax_subpath, transform) for one decoder layer."""
    h, nh, nkv, hd = cfg.hidden_size, cfg.num_heads, cfg.num_kv_heads, cfg.head_dim

    def qkv(heads):
        # [heads*hd, H] torch → [H, heads, hd] flax
        return lambda w: np.ascontiguousarray(w.T).reshape(h, heads, hd)

    def o_proj(w):
        # [H, nh*hd] torch → [nh, hd, H] flax
        return np.ascontiguousarray(w.T).reshape(nh, hd, h)

    t = lambda w: np.ascontiguousarray(w.T)  # noqa: E731
    ident = lambda w: w  # noqa: E731
    return [
        ("self_attn.q_proj.weight", "attention/wq/base/kernel", qkv(nh)),
        ("self_attn.k_proj.weight", "attention/wk/base/kernel", qkv(nkv)),
        ("self_attn.v_proj.weight", "attention/wv/base/kernel", qkv(nkv)),
        ("self_attn.o_proj.weight", "attention/wo/base/kernel", o_proj),
        ("mlp.gate_proj.weight", "mlp/gate/base/kernel", t),
        ("mlp.up_proj.weight", "mlp/up/base/kernel", t),
        ("mlp.down_proj.weight", "mlp/down/base/kernel", t),
        ("input_layernorm.weight", "attention_norm/scale", ident),
        ("post_attention_layernorm.weight", "mlp_norm/scale", ident),
    ]


def _set_path(tree: dict, path: str, value) -> None:
    keys = path.split("/")
    for k in keys[:-1]:
        tree = tree.setdefault(k, {})
    tree[keys[-1]] = value


def _open_shards(path: str):
    """Yield a name→numpy loader over a file or HF shard directory."""
    from safetensors import safe_open

    if os.path.isdir(path):
        index = os.path.join(path, "model.safetensors.index.json")
        if os.path.exists(index):
            with open(index) as f:
                weight_map = json.load(f)["weight_map"]
            files = sorted(set(weight_map.values()))
        else:
            files = sorted(f for f in os.listdir(path) if f.endswith(".safetensors"))
        files = [os.path.join(path, f) for f in files]
    else:
        files = [path]

    handles = [safe_open(f, framework="numpy") for f in files]
    name_to_handle = {}
    for hshard in handles:
        for name in hshard.keys():
            name_to_handle[name] = hshard

    def load(name: str) -> np.ndarray:
        if name not in name_to_handle:
            raise KeyError(f"tensor {name!r} not found in {path}")
        return name_to_handle[name].get_tensor(name)

    return load, set(name_to_handle)


def load_llama_safetensors(path: str, cfg: LlamaConfig,
                           param_dtype: Any = np.float32) -> dict:
    """HF Llama-2 safetensors (file or shard dir) → flax params dict."""
    load, names = _open_shards(path)
    cast = lambda w: np.asarray(w, dtype=param_dtype)  # noqa: E731

    params: dict = {}
    _set_path(params, "token_embed/embedding", cast(load("model.embed_tokens.weight")))
    _set_path(params, "final_norm/scale", np.asarray(load("model.norm.weight"), np.float32))
    if "lm_head.weight" in names:
        head = load("lm_head.weight")
    else:  # tied-embedding exports omit it
        head = load("model.embed_tokens.weight")
    _set_path(params, "lm_head/kernel", cast(np.ascontiguousarray(head.T)))

    maps = _layer_maps(cfg)
    if cfg.scan_layers:
        for suffix, sub, tf in maps:
            dtype = np.float32 if sub.endswith("scale") else param_dtype
            stacked = np.stack([
                np.asarray(tf(load(f"model.layers.{i}.{suffix}")), dtype=dtype)
                for i in range(cfg.num_layers)
            ])
            _set_path(params, f"layers/{sub}", stacked)
    else:
        for i in range(cfg.num_layers):
            for suffix, sub, tf in maps:
                dtype = np.float32 if sub.endswith("scale") else param_dtype
                w = np.asarray(tf(load(f"model.layers.{i}.{suffix}")), dtype=dtype)
                _set_path(params, f"layers_{i}/{sub}", w)
    return params


def export_llama_safetensors(params: dict, cfg: LlamaConfig, path: str) -> None:
    """flax params → one HF-layout safetensors file (inverse of the loader).

    Used for interchange back to torch tooling and as the round-trip oracle in
    tests. LoRA adapters, if present, are NOT merged or exported — fold them
    into base kernels first if a merged export is needed (:func:`merge_lora`).
    """
    from safetensors.numpy import save_file

    flat = _flatten(params)
    if any(k.endswith("base_q8") for k in flat):
        raise NotImplementedError(
            "export of an int8-quantized tree: HF interchange has no "
            "per-channel-scale layout for it — export the DENSE tree you "
            "quantized from (quantization is lossy; there is no faithful "
            "int8 → HF bf16 inverse)")
    h = cfg.hidden_size
    out: dict[str, np.ndarray] = {}
    out["model.embed_tokens.weight"] = np.asarray(flat["token_embed/embedding"])
    out["model.norm.weight"] = np.asarray(flat["final_norm/scale"])
    out["lm_head.weight"] = np.ascontiguousarray(np.asarray(flat["lm_head/kernel"]).T)

    def un_qkv(w):  # [H, heads, hd] → [heads*hd, H]
        return np.ascontiguousarray(w.reshape(h, -1).T)

    inverse = {
        "attention/wq/base/kernel": ("self_attn.q_proj.weight", un_qkv),
        "attention/wk/base/kernel": ("self_attn.k_proj.weight", un_qkv),
        "attention/wv/base/kernel": ("self_attn.v_proj.weight", un_qkv),
        "attention/wo/base/kernel": (
            "self_attn.o_proj.weight",
            lambda w: np.ascontiguousarray(w.reshape(-1, h).T),
        ),
        "mlp/gate/base/kernel": ("mlp.gate_proj.weight", lambda w: np.ascontiguousarray(w.T)),
        "mlp/up/base/kernel": ("mlp.up_proj.weight", lambda w: np.ascontiguousarray(w.T)),
        "mlp/down/base/kernel": ("mlp.down_proj.weight", lambda w: np.ascontiguousarray(w.T)),
        "attention_norm/scale": ("input_layernorm.weight", lambda w: w),
        "mlp_norm/scale": ("post_attention_layernorm.weight", lambda w: w),
    }
    for key, value in flat.items():
        m = re.match(r"layers(?:_(\d+))?/(.+)", key)
        if not m:
            continue
        idx, sub = m.group(1), m.group(2)
        if "lora_" in sub:
            continue
        hf_suffix, tf = inverse[sub]
        value = np.asarray(value)
        if idx is None:  # scanned: [L, ...] stacked
            for i in range(cfg.num_layers):
                out[f"model.layers.{i}.{hf_suffix}"] = tf(value[i])
        else:
            out[f"model.layers.{idx}.{hf_suffix}"] = tf(value)
    save_file(out, path)


def merge_lora(params: dict, cfg: LlamaConfig) -> dict:
    """Fold trained LoRA adapters into base kernels: W ← W + (alpha/r)·A·B.

    Returns a new tree with adapters removed — the deploy-time merge that makes
    LoRA inference free (Hu et al. 2021 §4).
    """

    def merge_node(node):
        if not isinstance(node, dict):
            return node
        if "base_q8" in node:
            raise NotImplementedError(
                "merge_lora on an int8-quantized tree would bake absmax "
                "re-quantization error into the merged weights; merge on "
                "the dense tree FIRST, then quantize_base_int8 the result "
                "(or keep adapters separate — int8 decode serves them "
                "unmerged)")
        if "lora_a" in node and "base" in node:
            a, b = np.asarray(node["lora_a"]), np.asarray(node["lora_b"])
            kernel = np.asarray(node["base"]["kernel"])
            scale = cfg.lora_alpha / cfg.lora_rank
            if a.ndim == 3:  # scanned: [L, in, r] @ [L, r, out]
                delta = np.einsum("lir,lro->lio", a, b) * scale
            else:
                delta = (a @ b) * scale
            merged = kernel + delta.reshape(kernel.shape).astype(kernel.dtype)
            return {"base": {"kernel": merged}}
        return {k: merge_node(v) for k, v in node.items()}

    return merge_node(params)


def quantize_base_int8(params: dict) -> dict:
    """Quantize every frozen base kernel to int8 + per-output-channel f32
    absmax scales — the tree transform that turns a dense (f32/bf16) Llama
    param tree into the shapes a ``base_quant='int8'`` model expects.

    Each ``.../<proj>/base/kernel`` node becomes ``<proj>/base_q8`` (int8,
    input axes folded to one leading dim, matching LoRADenseGeneral's int8
    layout) + ``<proj>/base_scale`` (f32, the kernel's output dims).
    Scanned stacks keep their leading [L] on both. Per-channel absmax:
    q = round(W/s), s = max|W_channel|/127 — max quantization error is
    s/2 per weight (≤0.4% of the channel's absmax). Embeddings, LM head,
    norms, and LoRA adapters pass through untouched (QLoRA convention).

    Use after :func:`load_llama_safetensors` (or on any trained tree) and
    feed the result to ``Trainer.load_pretrained`` on an int8-config model.
    """

    def walk(tree, scanned=False):
        out = {}
        for k, v in tree.items():
            if k == "layers":
                out[k] = walk(v, scanned=True)
                continue
            if isinstance(v, dict) and "base" in v and \
                    isinstance(v["base"], dict) and "kernel" in v["base"]:
                w = np.asarray(v["base"]["kernel"], np.float32)
                lead = 1 if scanned else 0
                # output dims: (heads, hd) for wq/wk/wv; 1 dim otherwise.
                # wo's kernel is [.., nh, hd, h]: TWO input dims to fold.
                if k in ("wq", "wk", "wv"):
                    n_in, out_dims = 1, 2
                elif k == "wo":
                    n_in, out_dims = 2, 1
                else:  # gate/up/down
                    n_in, out_dims = 1, 1
                assert w.ndim == lead + n_in + out_dims, (k, w.shape)
                l_shape = w.shape[:lead]
                in_dim = int(np.prod(w.shape[lead:lead + n_in]))
                feats = w.shape[lead + n_in:]
                w2 = w.reshape(l_shape + (in_dim,) + feats)
                # per-(L, out-channel) absmax over the folded input axis
                s = np.max(np.abs(w2), axis=lead) / 127.0        # [L?, *feats]
                s = np.maximum(s, 1e-12)
                q = np.clip(np.round(w2 / np.expand_dims(s, lead)),
                            -127, 127).astype(np.int8)
                rest = {kk: vv for kk, vv in v.items() if kk != "base"}
                out[k] = {"base_q8": q, "base_scale": s.astype(np.float32),
                          **walk(rest, scanned)}
            elif isinstance(v, dict):
                out[k] = walk(v, scanned)
            else:
                out[k] = v
        return out

    return walk(params)


def _flatten(tree: dict, prefix: str = "") -> dict[str, Any]:
    out = {}
    for k, v in tree.items():
        key = f"{prefix}/{k}" if prefix else k
        if isinstance(v, dict):
            out.update(_flatten(v, key))
        else:
            out[key] = v
    return out
